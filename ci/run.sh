#!/usr/bin/env bash
# One-command reproducible CI gate (reference analog: `ci/mpi-ctest` +
# the RANK_N-labeled ctest tiers of `cmake/DLAF_AddTest.cmake:60-193`).
#
#   ci/run.sh smoke   — the `quick` marker tier (< ~2 min; per-push gate)
#   ci/run.sh main    — full suite minus the slow tier + both driver
#                       entry checks (the default; what a PR must pass)
#   ci/run.sh full    — everything: main + the slow deep-distributed tier
#
# Every tier finishes with the multi-chip sharding dry run: an 8-virtual-
# device CPU mesh jit of the full distributed training-step analog
# (`__graft_entry__.dryrun_multichip`), which is the in-repo stand-in for
# the reference's RANK_6 MPI jobs. All tiers are hermetic: CPU platform,
# no tunnel, no network.
set -euo pipefail
cd "$(dirname "$0")/.."

TIER=${1:-main}

# never probe a (possibly wedged) accelerator tunnel from CI: the plugin
# force-registers at interpreter start unless its discovery env is unset
export PALLAS_AXON_POOL_IPS=
export JAX_PLATFORMS=cpu

case "$TIER" in
  smoke)
    # post-mortem evidence (ISSUE 14 satellite): every leg registers its
    # scratch dirs here; on ANY smoke failure the trap copies them into
    # one repo-local smoke_artifacts/ dir (gitignored) instead of
    # leaving the devtrace/flight/merged-JSONL evidence scattered in
    # per-leg mktemp dirs under /tmp
    SMOKE_KEEP=()
    archive_smoke_artifacts() {
      rc=$?
      if [ "$rc" -ne 0 ] && [ "${#SMOKE_KEEP[@]}" -gt 0 ]; then
        dest="smoke_artifacts"
        rm -rf "$dest"; mkdir -p "$dest"
        for p in "${SMOKE_KEEP[@]}"; do
          if [ -e "$p" ]; then cp -r "$p" "$dest/" || true; fi
        done
        echo "smoke FAILED (rc=$rc): evidence archived in $dest/" >&2
        ls "$dest" >&2
      fi
      exit "$rc"
    }
    trap archive_smoke_artifacts EXIT
    python -m pytest tests/ -q -m quick
    echo "== smoke: miniapp_cholesky observability artifact =="
    # distributed run on a 2x2 virtual-CPU grid so the artifact carries
    # real collective byte counters; the validator fails the tier on any
    # missing or non-finite field (NaN GFlop/s must not scrape as data)
    # comm look-ahead pinned ON (the CPU auto would resolve it off): the
    # artifact must additionally carry the dlaf_comm_overlapped_total
    # trace-time counters and finite per-axis collective byte counts —
    # the audit trail that the hoisted-collective programs were built
    # (docs/comm_overlap.md)
    # per-rank artifact convention (%r -> jax.process_index()) + program
    # telemetry (ISSUE 7): compile walls, retrace counters, and HBM
    # gauges must land in the artifact; obs.aggregate merges the
    # per-rank files into one timeline and exports a Chrome trace
    # accuracy telemetry rides the same run (DLAF_ACCURACY=1,
    # docs/accuracy.md): every timed run probes its factor in-graph and
    # the merged artifact must carry the accuracy records
    # (--require-accuracy) that scripts/accuracy_gate.py gates below
    # device-timeline attribution rides the same run (ISSUE 14): the
    # trace dir arms the jax.profiler Chrome trace that obs.devtrace
    # attributes below — per-phase device walls, measured overlap,
    # coverage — gated by --require-devtrace
    OBS_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$OBS_DIR")
    OBS_ART="$OBS_DIR/miniapp_cholesky.r%r.jsonl"
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4" \
      DLAF_METRICS_PATH="$OBS_ART" DLAF_PROGRAM_TELEMETRY=1 \
      DLAF_ACCURACY=1 DLAF_TRACE_DIR="$OBS_DIR/trace" \
      DLAF_CHOLESKY_LOOKAHEAD=1 DLAF_COMM_LOOKAHEAD=1 \
      python -m dlaf_tpu.miniapp.miniapp_cholesky -m 256 -b 64 \
        --grid-rows 2 --grid-cols 2 --nruns 2
    python -m dlaf_tpu.obs.aggregate "$OBS_DIR"/miniapp_cholesky.r*.jsonl \
      -o "$OBS_DIR/merged.jsonl" --chrome "$OBS_DIR/trace.json"
    python -m dlaf_tpu.obs.validate "$OBS_DIR/merged.jsonl" \
      --require-spans --require-gflops --require-collectives \
      --require-comm-overlap --require-telemetry --require-accuracy
    # the Chrome export must be valid trace-event JSON with spans from
    # EVERY rank that produced an artifact
    python - "$OBS_DIR" <<'EOF'
import glob, json, sys
d = sys.argv[1]
doc = json.load(open(f"{d}/trace.json"))
evs = doc["traceEvents"]
span_pids = {e["pid"] for e in evs if e.get("ph") == "X" and e.get("tid") == 0}
# the rank-from-filename convention has ONE owner (obs.aggregate);
# unresolved-rank placeholder files map >= UNRESOLVED_RANK_BASE
from dlaf_tpu.obs.aggregate import UNRESOLVED_RANK_BASE, infer_rank
ranks = set()
for i, p in enumerate(sorted(glob.glob(f"{d}/miniapp_cholesky.r*.jsonl"))):
    rk = infer_rank(p, i)
    if rk < UNRESOLVED_RANK_BASE:
        ranks.add(rk)
assert ranks and span_pids >= ranks, (ranks, span_pids)
print(f"chrome trace ok: {len(evs)} events, span ranks {sorted(span_pids)}")
EOF
    echo "== smoke: device-timeline attribution (obs.devtrace, ISSUE 14) =="
    # the traced 2x2 run's profiler artifact, attributed end-to-end: the
    # enriched artifact must carry >= 1 finite measured_overlap record
    # with positive collective time AND coverage >= the documented floor
    # (sinks.DEVTRACE_COVERAGE_FLOOR) — --require-devtrace gates both
    python -m dlaf_tpu.obs.devtrace "$OBS_DIR/trace" \
      "$OBS_DIR/merged.jsonl" -o "$OBS_DIR/devtrace.jsonl" \
      | tee "$OBS_DIR/devtrace_report.txt"
    grep -q "MXU-overlapped" "$OBS_DIR/devtrace_report.txt"
    python -m dlaf_tpu.obs.validate "$OBS_DIR/devtrace.jsonl" \
      --require-devtrace
    # profile_summary's trace mode shares the parser (single owner) and
    # must print the per-phase attribution section for the same join
    python scripts/profile_summary.py "$OBS_DIR/trace" 10 \
      --jsonl "$OBS_DIR/merged.jsonl" > "$OBS_DIR/profile_summary.txt"
    grep -q "device-time attribution" "$OBS_DIR/profile_summary.txt"
    grep -q "coverage" "$OBS_DIR/profile_summary.txt"
    echo "== smoke: perf_diff must-trip drill (regression explainer) =="
    # identity diff must pass; an injected slowdown on the cholesky
    # phase must exit SPECIFICALLY 1 with the phase NAMED in a
    # REGRESSION line — the gate-to-diagnosis contract bench_gate's
    # verdict points at
    python scripts/perf_diff.py "$OBS_DIR/devtrace.jsonl" \
      "$OBS_DIR/devtrace.jsonl"
    drill_rc=0
    python scripts/perf_diff.py "$OBS_DIR/devtrace.jsonl" \
      "$OBS_DIR/devtrace.jsonl" --inject-slowdown cholesky=0.5 \
      > "$OBS_DIR/perf_diff_drill.log" 2>&1 || drill_rc=$?
    if [ "$drill_rc" -ne 1 ] \
        || ! grep -q "REGRESSION.*cholesky" "$OBS_DIR/perf_diff_drill.log"; then
      echo "perf_diff drill did not name the injected phase" \
           "(rc=$drill_rc, wanted rc=1 + REGRESSION naming cholesky)" >&2
      cat "$OBS_DIR/perf_diff_drill.log" >&2; exit 1
    fi
    echo "perf_diff correctly named the injected regressing phase"
    # zero-attribution rejection drill: a trace stripped of its
    # collectives attributes NO collective time — the devtrace artifact
    # it produces must be REJECTED by --require-devtrace
    python - "$OBS_DIR" <<'EOF'
import json, sys
from dlaf_tpu.obs import devtrace
from dlaf_tpu.obs.aggregate import merge_artifacts
d = sys.argv[1]
events = [e for e in devtrace.load_trace(f"{d}/trace")
          if devtrace.classify_op(e.get("name", ""))[0] != "collective"]
records = merge_artifacts([f"{d}/merged.jsonl"])
report = devtrace.attribute(events, records)
assert not report["overlap"], "stripped trace still attributed collectives"
with open(f"{d}/devtrace_nocoll.jsonl", "w") as f:
    for r in devtrace.records_from_report(report, "stripped.json.gz"):
        f.write(json.dumps(r) + "\n")
print("zero-collective artifact written")
EOF
    if python -m dlaf_tpu.obs.validate "$OBS_DIR/devtrace_nocoll.jsonl" \
        --require-devtrace > /dev/null 2>&1; then
      echo "--require-devtrace FAILED to reject the zero-attribution" \
           "artifact" >&2; exit 1
    fi
    echo "--require-devtrace correctly rejected the zero-attribution artifact"
    echo "== smoke: measured-MFU replay (mfu_table --measured fixture) =="
    # the committed devtrace fixture must replay hermetically into the
    # measured(dev) column (CPU-labeled, BASELINE.md acceptance)
    python scripts/mfu_table.py --no-ici --measured \
      > "$OBS_DIR/mfu_measured.txt"
    grep -q "measured(dev) GF/s" "$OBS_DIR/mfu_measured.txt"
    grep -Eq "cpu [0-9]+/[0-9]+" "$OBS_DIR/mfu_measured.txt"
    # the measured bound column must also fill from the critpath fixture
    grep -q "measured bound" "$OBS_DIR/mfu_measured.txt"
    echo "== smoke: critical-path attribution (obs.critpath, ISSUE 16) =="
    # the telemetry-armed traced run above carries schedule records:
    # reconstruct the live per-step timeline and gate the artifact with
    # --require-critpath (>= 1 multi-step critpath record at or above
    # the coverage floor + >= 1 whatif projection)
    python -m dlaf_tpu.obs.critpath "$OBS_DIR/trace" \
      "$OBS_DIR/merged.jsonl" -o "$OBS_DIR/critpath.jsonl" \
      | tee "$OBS_DIR/critpath_report.txt"
    grep -q "critical path" "$OBS_DIR/critpath_report.txt"
    grep -q "what-if" "$OBS_DIR/critpath_report.txt"
    python -m dlaf_tpu.obs.validate "$OBS_DIR/critpath.jsonl" \
      --require-critpath
    # hermetic fixture replay: the committed tests/fixtures/critpath/
    # fixture must reproduce per-step bound classification AND a NONZERO
    # measured step-boundary gap (the fixture's documented 2 ms
    # synthetic injection — scripts/refresh_devtrace_fixture.py)
    python -m dlaf_tpu.obs.critpath tests/fixtures/critpath/trace.json.gz \
      tests/fixtures/critpath/merged.jsonl \
      -o "$OBS_DIR/critpath_fixture.jsonl" > /dev/null
    python -m dlaf_tpu.obs.validate "$OBS_DIR/critpath_fixture.jsonl" \
      --require-critpath
    python - "$OBS_DIR" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(f"{sys.argv[1]}/critpath_fixture.jsonl")]
cps = [r for r in recs if r["type"] == "critpath" and r["algo"] == "cholesky"]
assert cps, "fixture replay produced no cholesky critpath record"
steps = [s for r in cps for s in r["steps"] if not s.get("empty")]
bounds = {s["bound"] for s in steps}
gaps = [s.get("gap_after_s", 0.0) for s in steps]
assert bounds, "no per-step bound classification"
assert max(gaps) > 0.0, f"fixture carries no step-boundary gap: {gaps}"
print(f"fixture replay ok: bounds {sorted(bounds)}, "
      f"max step-boundary gap {max(gaps) * 1e3:.3f} ms")
EOF
    echo "== smoke: gap-injection must-trip drill (critpath explainer) =="
    # inject a 5 ms stall before cholesky.step003 at the TRACE level and
    # diff against the clean fixture replay: perf_diff must exit
    # SPECIFICALLY 1 with a REGRESSION line naming the injected step's
    # gap — the step-level gate-to-diagnosis contract
    python -m dlaf_tpu.obs.critpath tests/fixtures/critpath/trace.json.gz \
      tests/fixtures/critpath/merged.jsonl \
      --inject-gap cholesky.step003=5.0 \
      -o "$OBS_DIR/critpath_injected.jsonl" > /dev/null
    drill_rc=0
    python scripts/perf_diff.py "$OBS_DIR/critpath_fixture.jsonl" \
      "$OBS_DIR/critpath_injected.jsonl" \
      > "$OBS_DIR/critpath_drill.log" 2>&1 || drill_rc=$?
    if [ "$drill_rc" -ne 1 ] \
        || ! grep -q "REGRESSION.*cholesky\.step003 gap" \
             "$OBS_DIR/critpath_drill.log"; then
      echo "gap-injection drill did not name the injected step" \
           "(rc=$drill_rc, wanted rc=1 + REGRESSION naming" \
           "cholesky.step003 gap)" >&2
      cat "$OBS_DIR/critpath_drill.log" >&2; exit 1
    fi
    echo "perf_diff correctly named the injected step-boundary gap"
    echo "== smoke: bench-regression gate (replay + injection drill) =="
    # clean replay of the committed history must pass; a 20% synthetic
    # slowdown must trip the gate (exit nonzero) — proving the gate
    # would catch a real regression of that size
    python scripts/bench_gate.py --replay
    if python scripts/bench_gate.py --replay --inject-slowdown 0.2 \
        > /dev/null 2>&1; then
      echo "bench_gate FAILED to flag a 20% injected slowdown" >&2; exit 1
    fi
    echo "bench_gate correctly flagged the injected slowdown"
    echo "== smoke: accuracy gate (fresh artifact + corruption drill) =="
    # the fresh accuracy records of the run above must pass BOTH gate
    # legs (analytic c*n*eps budget + drift vs the committed
    # .accuracy_history.jsonl), the history must validate standalone,
    # and the corrupt-collective drill — a REAL injected fault through
    # health.inject, not a synthetic number — must trip the gate
    python -m dlaf_tpu.obs.validate --accuracy-history .accuracy_history.jsonl
    python scripts/accuracy_gate.py --replay
    python scripts/accuracy_gate.py --fresh "$OBS_DIR/merged.jsonl"
    # require SPECIFICALLY exit 1 + a REGRESSION verdict: a crash in the
    # inject path (any other nonzero exit) must not masquerade as the
    # corruption-detection proof
    drill_rc=0
    python scripts/accuracy_gate.py --inject corrupt_collective \
      > "$OBS_DIR/accuracy_drill.log" 2>&1 || drill_rc=$?
    if [ "$drill_rc" -ne 1 ] \
        || ! grep -q "regressed key(s)" "$OBS_DIR/accuracy_drill.log"; then
      echo "accuracy_gate injection drill did not trip cleanly" \
           "(rc=$drill_rc)" >&2
      cat "$OBS_DIR/accuracy_drill.log" >&2; exit 1
    fi
    echo "accuracy_gate correctly flagged the injected corruption"
    echo "== smoke: fault-injection / graceful-degradation artifact =="
    # drive the robustness layer end-to-end (docs/robustness.md): a tiny
    # non-SPD robust_cholesky must recover through shift-retry (leaving
    # robust_cholesky.attempt spans), and an injected native-load failure
    # must degrade to numpy (leaving a dlaf_fallback_total counter); the
    # validator fails the tier unless the artifact records BOTH
    HEALTH_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$HEALTH_DIR")
    HEALTH_ART="$HEALTH_DIR/health_metrics.jsonl"
    DLAF_METRICS_PATH="$HEALTH_ART" python - <<'EOF'
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.eigensolver.band_to_tridiag import band_to_tridiag
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((64, 64))
indef = x @ x.T + 64 * np.eye(64) - 100 * np.eye(64)   # non-SPD
mat = Matrix.from_global(indef, TileElementSize(16, 16))
res = health.robust_cholesky("L", mat)
assert res.attempts > 1 and res.infos[-1] == 0, res
print(f"robust_cholesky recovered: attempts={res.attempts} "
      f"shifts={list(res.shifts)}")
band = np.zeros((3, 16))
band[0] = np.arange(1, 17); band[1, :-1] = 0.5; band[2, :-2] = 0.1
with inject.force_native_failure():
    band_to_tridiag(band, 2)
c = obs.registry().counter("dlaf_fallback_total", site="band_to_tridiag",
                           reason="native_unavailable").snapshot()
assert c["value"] >= 1, c
print("native-load injection degraded to numpy:", c)
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$HEALTH_ART" \
      --require-spans --require-retries --require-fallbacks
    echo "== smoke: fused Pallas panel route (panel_impl=fused) =="
    # tiny local + 2x2-distributed f32 cholesky on the FUSED panel route
    # (off-TPU the kernels run in interpret mode, docs/pallas_panel.md);
    # the artifact must carry the trace-time
    # dlaf_panel_kernel_total{impl="fused"} counters AND a finite
    # accuracy record next to them
    PANEL_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$PANEL_DIR")
    PANEL_ART="$PANEL_DIR/panel_metrics.jsonl"
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4" \
      DLAF_METRICS_PATH="$PANEL_ART" DLAF_PANEL_IMPL=fused DLAF_ACCURACY=1 \
      python - <<'EOF'
import numpy as np
import scipy.linalg as sla
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.obs import accuracy

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((64, 64)).astype(np.float32)
a = x @ x.T + 64 * np.eye(64, dtype=np.float32)
ref = sla.cholesky(a, lower=True)
for grid_shape in (None, (2, 2)):
    grid = Grid(*grid_shape) if grid_shape else None
    mat = Matrix.from_global(a, TileElementSize(16, 16), grid=grid)
    fac = cholesky("L", mat)
    rel = abs(np.tril(fac.to_numpy()) - ref).max() / abs(ref).max()
    assert rel < 1e-5, rel
    accuracy.emit("ci_panel", "cholesky_residual",
                  accuracy.cholesky_residual(
                      "L", Matrix.from_global(a, TileElementSize(16, 16),
                                              grid=grid), fac),
                  n=64, nb=16, c=60.0, dtype=np.float32, of=fac.storage)
fused = obs.registry().counter("dlaf_panel_kernel_total", impl="fused",
                               op="potrf").snapshot()
assert fused["value"] >= 8, fused   # 4 steps x (local + dist)
print("fused panel smoke ok:", fused)
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$PANEL_ART" --require-accuracy
    python - "$PANEL_ART" <<'EOF'
import json, sys
recs = [json.loads(line) for line in open(sys.argv[1])]
mets = [m for r in recs if r.get("type") == "metrics"
        for m in r["metrics"]]
fused = [m for m in mets if m["name"] == "dlaf_panel_kernel_total"
         and m["labels"].get("impl") == "fused"]
assert fused and all(m["value"] > 0 for m in fused), fused
print(f"panel artifact ok: {len(fused)} fused kernel counter series")
EOF
    echo "== smoke: disable_pallas must-trip drill (panel route) =="
    # non-strict leg: the injected pallas-off must COUNT the degradation
    # at site=panel and once-announce it; strict leg: the same injection
    # must exit SPECIFICALLY 1 with DegradationError named (any other
    # exit = a crash masquerading as detection — PR 8/9 drill contract)
    PANEL_DRILL_LOG=$(mktemp)
    drill0_rc=0
    # metrics must be armed or the fallback counter is a no-op singleton
    DLAF_PANEL_IMPL=fused DLAF_METRICS_PATH=$(mktemp -d)/panel_drill.jsonl \
      python - > "$PANEL_DRILL_LOG" 2>&1 <<'EOF' || drill0_rc=$?
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((32, 32)).astype(np.float32)
a = x @ x.T + 32 * np.eye(32, dtype=np.float32)
with inject.disable_pallas():
    cholesky("L", Matrix.from_global(a, TileElementSize(8, 8)))
c = obs.registry().counter("dlaf_fallback_total", site="panel",
                           reason="injected_off").snapshot()
assert c["value"] >= 1, c
print("panel fallback counted:", c)
EOF
    if [ "$drill0_rc" -ne 0 ] \
        || ! grep -q "panel fallback counted" "$PANEL_DRILL_LOG"; then
      echo "panel fallback counter leg failed (rc=$drill0_rc)" >&2
      cat "$PANEL_DRILL_LOG" >&2; exit 1
    fi
    grep -q "degraded path at 'panel'" "$PANEL_DRILL_LOG" || {
      echo "panel degradation was not once-announced" >&2
      cat "$PANEL_DRILL_LOG" >&2; exit 1; }
    drill_rc=0
    DLAF_PANEL_IMPL=fused DLAF_STRICT=1 python - > "$PANEL_DRILL_LOG" 2>&1 \
      <<'EOF' || drill_rc=$?
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((32, 32)).astype(np.float32)
a = x @ x.T + 32 * np.eye(32, dtype=np.float32)
with inject.disable_pallas():
    cholesky("L", Matrix.from_global(a, TileElementSize(8, 8)))
raise SystemExit(3)   # reaching here = the strict raise never fired
EOF
    if [ "$drill_rc" -ne 1 ] \
        || ! grep -q "DegradationError" "$PANEL_DRILL_LOG"; then
      echo "disable_pallas panel drill did not trip cleanly" \
           "(rc=$drill_rc, wanted rc=1 + DegradationError)" >&2
      cat "$PANEL_DRILL_LOG" >&2; exit 1
    fi
    echo "disable_pallas panel drill tripped as required (DegradationError)"
    echo "== smoke: fused step kernel route (step_impl=fused, ISSUE 19) =="
    # tiny local + 2x2-distributed f32 cholesky on the FUSED STEP route
    # (one pallas_call per strip-bearing blocked step: panel potrf +
    # strip solve + adjacent trailing slab, docs/pallas_panel.md "Fused
    # step kernel"; off-TPU the kernel runs in interpret mode); the
    # artifact must carry the trace-time
    # dlaf_step_kernel_total{impl="fused"} counters AND a finite
    # accuracy record next to them
    STEP_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$STEP_DIR")
    STEP_ART="$STEP_DIR/step_metrics.jsonl"
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4" \
      DLAF_METRICS_PATH="$STEP_ART" DLAF_STEP_IMPL=fused DLAF_ACCURACY=1 \
      python - <<'EOF'
import numpy as np
import scipy.linalg as sla
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.obs import accuracy

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((64, 64)).astype(np.float32)
a = x @ x.T + 64 * np.eye(64, dtype=np.float32)
ref = sla.cholesky(a, lower=True)
for grid_shape in (None, (2, 2)):
    grid = Grid(*grid_shape) if grid_shape else None
    mat = Matrix.from_global(a, TileElementSize(16, 16), grid=grid)
    fac = cholesky("L", mat)
    rel = abs(np.tril(fac.to_numpy()) - ref).max() / abs(ref).max()
    assert rel < 1e-5, rel
    accuracy.emit("ci_step", "cholesky_residual",
                  accuracy.cholesky_residual(
                      "L", Matrix.from_global(a, TileElementSize(16, 16),
                                              grid=grid), fac),
                  n=64, nb=16, c=60.0, dtype=np.float32, of=fac.storage)
fused = obs.registry().counter("dlaf_step_kernel_total",
                               impl="fused").snapshot()
assert fused["value"] >= 6, fused   # 3 strip-bearing steps x (local + dist)
print("fused step smoke ok:", fused)
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$STEP_ART" --require-accuracy
    python - "$STEP_ART" <<'EOF'
import json, sys
recs = [json.loads(line) for line in open(sys.argv[1])]
mets = [m for r in recs if r.get("type") == "metrics"
        for m in r["metrics"]]
fused = [m for m in mets if m["name"] == "dlaf_step_kernel_total"
         and m["labels"].get("impl") == "fused"]
assert fused and all(m["value"] > 0 for m in fused), fused
print(f"step artifact ok: {len(fused)} fused step counter series")
EOF
    echo "== smoke: fused step degrade must-trip drill (VMEM budget) =="
    # the ladder's automatic-degrade contract, drilled end to end: a
    # starved DLAF_STEP_VMEM_LIMIT must land the explicitly-requested
    # fused step route on the composed per-op chain, COUNTING the
    # fallback at site=step reason=vmem_budget and once-announcing it;
    # the injected route-off must count reason=injected_off the same
    # way; and the same starvation under DLAF_STRICT=1 must exit
    # SPECIFICALLY 1 naming DegradationError (any other exit = a crash
    # masquerading as detection — PR 8/9 drill contract)
    STEP_DRILL_LOG=$(mktemp)
    sdrill0_rc=0
    DLAF_STEP_IMPL=fused DLAF_STEP_VMEM_LIMIT=1024 \
      DLAF_METRICS_PATH=$(mktemp -d)/step_drill.jsonl \
      python - > "$STEP_DRILL_LOG" 2>&1 <<'EOF' || sdrill0_rc=$?
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((32, 32)).astype(np.float32)
a = x @ x.T + 32 * np.eye(32, dtype=np.float32)
cholesky("L", Matrix.from_global(a, TileElementSize(8, 8)))
c = obs.registry().counter("dlaf_fallback_total", site="step",
                           reason="vmem_budget").snapshot()
assert c["value"] >= 1, c
print("step vmem fallback counted:", c)
EOF
    if [ "$sdrill0_rc" -ne 0 ] \
        || ! grep -q "step vmem fallback counted" "$STEP_DRILL_LOG"; then
      echo "step vmem fallback counter leg failed (rc=$sdrill0_rc)" >&2
      cat "$STEP_DRILL_LOG" >&2; exit 1
    fi
    grep -q "degraded path at 'step'" "$STEP_DRILL_LOG" || {
      echo "step degradation was not once-announced" >&2
      cat "$STEP_DRILL_LOG" >&2; exit 1; }
    sdrill1_rc=0
    DLAF_STEP_IMPL=fused DLAF_METRICS_PATH=$(mktemp -d)/step_drill2.jsonl \
      python - > "$STEP_DRILL_LOG" 2>&1 <<'EOF' || sdrill1_rc=$?
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((32, 32)).astype(np.float32)
a = x @ x.T + 32 * np.eye(32, dtype=np.float32)
with inject.disable_route("pallas"):
    cholesky("L", Matrix.from_global(a, TileElementSize(8, 8)))
c = obs.registry().counter("dlaf_fallback_total", site="step",
                           reason="injected_off").snapshot()
assert c["value"] >= 1, c
print("step injected_off fallback counted:", c)
EOF
    if [ "$sdrill1_rc" -ne 0 ] \
        || ! grep -q "step injected_off fallback counted" "$STEP_DRILL_LOG"
    then
      echo "step disable_route counter leg failed (rc=$sdrill1_rc)" >&2
      cat "$STEP_DRILL_LOG" >&2; exit 1
    fi
    sdrill_rc=0
    DLAF_STEP_IMPL=fused DLAF_STEP_VMEM_LIMIT=1024 DLAF_STRICT=1 \
      python - > "$STEP_DRILL_LOG" 2>&1 <<'EOF' || sdrill_rc=$?
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
x = rng.standard_normal((32, 32)).astype(np.float32)
a = x @ x.T + 32 * np.eye(32, dtype=np.float32)
cholesky("L", Matrix.from_global(a, TileElementSize(8, 8)))
raise SystemExit(3)   # reaching here = the strict raise never fired
EOF
    if [ "$sdrill_rc" -ne 1 ] \
        || ! grep -q "DegradationError" "$STEP_DRILL_LOG"; then
      echo "step vmem-budget drill did not trip cleanly" \
           "(rc=$sdrill_rc, wanted rc=1 + DegradationError)" >&2
      cat "$STEP_DRILL_LOG" >&2; exit 1
    fi
    echo "fused step degrade drill tripped as required (DegradationError)"
    echo "== smoke: fstep bench A/B pair + completeness gate (ISSUE 19) =="
    # the fused-step A/B bench arms (plain fstep pins step_impl=xla,
    # fstep+fs1 pins fused) must land paired records in one artifact
    # that clears bench_gate --fresh; a HALF-pair artifact must trip
    # the gate's history-free completeness leg — the pair IS the claim
    FSTEP_BENCH_ART="$STEP_DIR/fstep_bench.jsonl"
    for v in fstep fstep+fs1; do
      DLAF_BENCH_VARIANT="$v" DLAF_METRICS_PATH="$FSTEP_BENCH_ART" \
        DLAF_BENCH_HISTORY_PATH="$STEP_DIR/bench_history.jsonl" \
        DLAF_BENCH_FSTEP_N=64 DLAF_ACCURACY=1 python bench.py > /dev/null
    done
    python scripts/bench_gate.py --fresh "$FSTEP_BENCH_ART"
    FSTEP_HALF_ART="$STEP_DIR/fstep_half.jsonl"
    DLAF_BENCH_VARIANT=fstep+fs1 DLAF_METRICS_PATH="$FSTEP_HALF_ART" \
      DLAF_BENCH_HISTORY_PATH="$STEP_DIR/bench_history.jsonl" \
      DLAF_BENCH_FSTEP_N=64 python bench.py > /dev/null
    if python scripts/bench_gate.py --fresh "$FSTEP_HALF_ART" \
        > /dev/null 2>&1; then
      echo "bench_gate FAILED to flag a half fstep A/B pair" >&2
      exit 1
    fi
    echo "bench_gate fstep completeness leg trips as required"
    echo "== smoke: batched serving layer (warm queue stream, ISSUE 11) =="
    # drive serve.Queue end-to-end (docs/serving.md): warmup a bucket
    # set, then a seeded mixed-shape cholesky/solve/eigh request stream
    # — the artifact must carry >= 1 batched dispatch, all-hit cache
    # (post-warmup contract), finite per-request latency, per-request
    # accuracy records, and zero post-warmup retraces (--require-serve)
    # ISSUE 13 additions to the same run: the live exporter is scraped
    # MID-STREAM (/metrics parses, counters monotone across two scrapes,
    # exemplar trace IDs live; /healthz parses and must agree with the
    # artifact's dispatch records), the flight recorder is ARMED and the
    # clean stream must produce NO flight artifact, and one request's
    # trace ID is saved for the aggregate --trace waterfall check below
    SERVE_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$SERVE_DIR")
    SERVE_ART="$SERVE_DIR/serve_metrics.jsonl"
    SERVE_PORT=${DLAF_CI_METRICS_PORT:-$((18000 + RANDOM % 2000))}
    DLAF_METRICS_PATH="$SERVE_ART" DLAF_PROGRAM_TELEMETRY=1 \
      DLAF_ACCURACY=1 DLAF_SERVE_BUCKETS=32,64 DLAF_SERVE_BATCH=4 \
      DLAF_METRICS_PORT="$SERVE_PORT" DLAF_FLIGHT_RECORDER=64 \
      SERVE_TRACE_OUT="$SERVE_DIR/trace_id.txt" \
      SERVE_HEALTHZ_OUT="$SERVE_DIR/healthz.json" \
      python - <<'EOF'
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.serve import Queue, Request, get_service

C.initialize()
rng = np.random.default_rng(0)


def hpd(n):
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


reqs = [Request(op="cholesky", a=hpd(int(rng.integers(17, 33))))
        for _ in range(8)]
for _ in range(4):
    n = int(rng.integers(17, 33))
    reqs.append(Request(op="solve",
                        a=np.tril(rng.standard_normal((n, n)))
                        + 3 * np.eye(n),
                        b=rng.standard_normal((n, 5))))
for _ in range(4):
    x = rng.standard_normal((int(rng.integers(17, 33)),) * 2)
    reqs.append(Request(op="eigh", a=(x + x.T) / 2))
q = Queue()
q.warmup(reqs)
import json as _json
import os
import urllib.request

port = int(os.environ["DLAF_METRICS_PORT"])


def scrape(route, accept=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{route}")
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.read().decode()


def counters(text):
    out = {}
    for ln in text.splitlines():
        name, _, val = ln.rpartition(" ")
        if name and ("_total" in name or "_count" in name) \
                and not name.startswith("#"):
            out[name] = float(val)
    return out


tickets = [q.submit(r) for r in reqs[:8]]
m1 = scrape("/metrics")            # MID-stream scrape (live process)
tickets += [q.submit(r) for r in reqs[8:]]
q.flush()
assert all(t.done for t in tickets)
for t in tickets:
    a = np.asarray(t.request.a)
    assert t.info == 0, (t.request.op, t.info)
    if t.request.op == "cholesky":
        fac = np.tril(t.result())
        ref = np.tril(a) + np.tril(a, -1).T
        assert np.allclose(fac @ fac.T, ref, atol=1e-8)
    elif t.request.op == "solve":
        x = t.result()
        assert np.allclose(np.tril(a) @ x, np.asarray(t.request.b),
                           atol=1e-8)
    else:
        w, v = t.result()
        assert np.allclose(a @ v, v * w[None, :], atol=1e-8)
st = get_service().stats()
assert st["misses"] == 0 and st["hit_rate"] == 1.0, st
print(f"serve smoke ok: {q.requests} requests over {q.dispatches} "
      f"dispatches, {st['warmups']} warmed programs, hit rate "
      f"{st['hit_rate']:.2f}")
# live scrape checks (ISSUE 13): both scrapes parse, counters monotone,
# the classic rendering stays exemplar-free (the 0.0.4 grammar has no
# exemplar clause), the OpenMetrics rendering carries exemplar trace
# IDs + the # EOF terminator, healthz saved for the artifact-agreement
# check in the driver
m2 = scrape("/metrics")
c1, c2 = counters(m1), counters(m2)
assert c1 and set(c1) <= set(c2), "second scrape lost counter series"
assert all(c2[k] >= v for k, v in c1.items()), \
    "counters not monotone across scrapes"
assert " # {" not in m2, "classic /metrics leaked an exemplar clause"
om = scrape("/metrics", accept="application/openmetrics-text;"
            "version=1.0.0,text/plain;version=0.0.4")
assert " # {trace_id=" in om, "no exemplar trace IDs on OpenMetrics scrape"
assert om.endswith("# EOF\n"), "OpenMetrics scrape lacks the terminator"
hz = _json.loads(scrape("/healthz"))
assert hz["status"] == "ok" and hz["queues"], hz
with open(os.environ["SERVE_HEALTHZ_OUT"], "w") as f:
    f.write(_json.dumps(hz))
obs.flush()
# end-to-end trace join (ISSUE 13 acceptance): ONE trace_id on the
# request's serve record, its dispatch (membership), its span records,
# and its accuracy record
from dlaf_tpu.obs.context import trace_matches

recs = obs.read_records(os.environ["DLAF_METRICS_PATH"])
tid = tickets[0].trace_id
mine = [r for r in recs if trace_matches(r, tid)]
types = {r["type"] for r in mine}
assert {"serve", "span", "accuracy"} <= types, types
events = {r.get("event") for r in mine if r["type"] == "serve"}
assert events == {"request", "dispatch"}, events
with open(os.environ["SERVE_TRACE_OUT"], "w") as f:
    f.write(tid)
print("live scrape ok: counters monotone, exemplars live, trace "
      f"{tid} joins {len(mine)} records")
EOF
    python -m dlaf_tpu.obs.validate "$SERVE_ART" --require-serve
    # must-NOT-trip leg: a clean stream with the recorder armed writes
    # no incident artifact — its existence IS the incident signal
    if [ -e "$SERVE_ART.flight.jsonl" ]; then
      echo "clean serve run produced a flight artifact" >&2; exit 1
    fi
    echo "clean serve run produced no flight artifact (must-not-trip ok)"
    echo "== smoke: trace waterfall (obs.aggregate --trace) =="
    SERVE_TRACE_ID=$(cat "$SERVE_DIR/trace_id.txt")
    python -m dlaf_tpu.obs.aggregate "$SERVE_ART" \
        --trace "$SERVE_TRACE_ID" > "$SERVE_DIR/trace_report.txt"
    for stage in "queue wait" compose program fetch unpad; do
      if ! grep -q "$stage" "$SERVE_DIR/trace_report.txt"; then
        echo "aggregate --trace waterfall missing stage '$stage'" >&2
        cat "$SERVE_DIR/trace_report.txt" >&2; exit 1
      fi
    done
    python -m dlaf_tpu.obs.aggregate "$SERVE_ART" --top-slow 3 \
        > "$SERVE_DIR/top_slow.txt"
    grep -q "slowest requests" "$SERVE_DIR/top_slow.txt"
    echo "aggregate --trace waterfall + --top-slow ok"
    # the mid-stream /healthz must agree with the artifact: queue
    # drained, dispatch count == the artifact's dispatch records,
    # breaker states are the documented names
    python - "$SERVE_ART" "$SERVE_DIR/healthz.json" <<'EOF'
import json
import sys

art, hz_path = sys.argv[1], sys.argv[2]
hz = json.load(open(hz_path))
recs = [json.loads(ln) for ln in open(art)]
disp = [r for r in recs if r.get("type") == "serve"
        and r.get("event") == "dispatch"]
q = hz["queues"][0]
assert q["pending"] == 0, q
assert q["dispatches"] == len(disp), (q["dispatches"], len(disp))
assert q["buckets"], "healthz carries no per-bucket table"
for site, b in q["buckets"].items():
    assert b["breaker"] in (None, "closed", "half_open", "open"), (site, b)
print(f"healthz/artifact agreement ok: {q['dispatches']} dispatches == "
      f"{len(disp)} artifact dispatch records, depth 0")
EOF
    echo "== smoke: flight-recorder must-trip drill (ISSUE 13) =="
    # leg A: a TRANSIENT fault retries and recovers — the retry record
    # must carry the members' trace IDs (the resilience leg of the
    # trace-join acceptance) and must NOT trip the recorder. leg B:
    # SUSTAINED fail_dispatch opens the bucket breaker — the flight
    # artifact must exist, hold the pre-trigger dispatch records, and
    # pass --require-flight
    FLIGHT_ART="$SERVE_DIR/flight_drill.jsonl"
    DLAF_METRICS_PATH="$FLIGHT_ART" DLAF_FLIGHT_RECORDER=64 \
      DLAF_CIRCUIT_THRESHOLD=2 DLAF_SERVE_RETRY_ATTEMPTS=2 \
      DLAF_SERVE_RETRY_BACKOFF_MS=0 python - <<'EOF'
import os

import numpy as np

import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.health import inject
from dlaf_tpu.obs.context import trace_matches
from dlaf_tpu.serve import Queue, Request

C.initialize()
rng = np.random.default_rng(3)


def hpd(n):
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


q = Queue(buckets=(32,), batch=2, deadline_s=1e9)
q.warmup([Request(op="cholesky", a=hpd(24))])
with inject.fail_dispatch(count=1):
    tickets = [q.submit(Request(op="cholesky", a=hpd(24)))
               for _ in range(2)]
for t in tickets:
    t.result()                     # the retry recovered the batch
obs.flush()
recs = obs.read_records(os.environ["DLAF_METRICS_PATH"])
tid = tickets[0].trace_id
mine = [r for r in recs if trace_matches(r, tid)]
assert any(r.get("type") == "resilience" and r.get("event") == "retry"
           for r in mine), "retry record missing the batch trace stamp"
flight_path = os.environ["DLAF_METRICS_PATH"] + ".flight.jsonl"
assert not os.path.exists(flight_path), \
    "a recovered transient fault must not trip the flight recorder"
with inject.fail_dispatch(count=100):
    for i in range(3):
        try:
            q.submit(Request(op="cholesky", a=hpd(24)))
        except Exception:
            pass
assert os.path.exists(flight_path), \
    "breaker open did not trip the flight recorder"
print("flight drill ok: retry carries the trace, breaker-open dump "
      "landed")
obs.flush()
EOF
    if ! grep -q '"reason": "breaker_open"' "$FLIGHT_ART.flight.jsonl"; then
      echo "flight dump header does not name breaker_open" >&2; exit 1
    fi
    if ! grep -q '"type": "serve"' "$FLIGHT_ART.flight.jsonl"; then
      echo "flight dump holds no pre-trigger dispatch records" >&2; exit 1
    fi
    python -m dlaf_tpu.obs.validate "$FLIGHT_ART.flight.jsonl" \
        --require-flight
    echo "flight must-trip drill passed (--require-flight)"
    echo "== smoke: serve evict/miss must-trip drill =="
    # an evicted bucket hit by the next in-bucket request, and an
    # out-of-bucket shape, must BOTH recompile and bump the miss
    # counter (rc 0 + marker = the metrics recorded it); then the
    # drill's own artifact must FAIL --require-serve (miss dispatches +
    # a retraced serve site) — proving the validator leg has teeth
    SERVE_DRILL_ART="$SERVE_DIR/serve_drill.jsonl"
    SERVE_DRILL_LOG=$(mktemp)
    drill_rc=0
    DLAF_METRICS_PATH="$SERVE_DRILL_ART" DLAF_PROGRAM_TELEMETRY=1 \
      DLAF_SERVE_BUCKETS=32 DLAF_SERVE_BATCH=2 \
      python - > "$SERVE_DRILL_LOG" 2>&1 <<'EOF' || drill_rc=$?
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.serve import Queue, Request, get_service

C.initialize()
rng = np.random.default_rng(1)


def hpd(n):
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


q = Queue()
sample = [Request(op="cholesky", a=hpd(24))]
q.warmup(sample)
(spec,) = q.warmup_specs(sample)
svc = get_service()
assert svc.evict(spec), "warm bucket was not resident"
base = svc.stats()
# leg 1: the evicted bucket's next in-bucket request must recompile
q.submit(Request(op="cholesky", a=hpd(24)))
q.submit(Request(op="cholesky", a=hpd(20)))
st = svc.stats()
assert st["misses"] == base["misses"] + 1, (base, st)
assert st["compiles"] == base["compiles"] + 1, (base, st)
retrace = obs.registry().counter("dlaf_retrace_total",
                                 site=spec.site).snapshot()
assert retrace["value"] >= 2, retrace
# leg 2: an out-of-bucket shape (above every configured ceiling) lands
# in a cold power-of-two bucket — another miss + compile
q.submit(Request(op="cholesky", a=hpd(40)))
q.submit(Request(op="cholesky", a=hpd(40)))
st2 = svc.stats()
assert st2["misses"] == st["misses"] + 1, (st, st2)
assert st2["compiles"] == st["compiles"] + 1, (st, st2)
print(f"serve evict drill ok: misses {base['misses']}->{st2['misses']}, "
      f"recompiles {base['compiles']}->{st2['compiles']}, "
      f"retrace[{spec.site}]={retrace['value']}")
obs.flush()
EOF
    if [ "$drill_rc" -ne 0 ] \
        || ! grep -q "serve evict drill ok" "$SERVE_DRILL_LOG"; then
      echo "serve evict/miss drill failed (rc=$drill_rc)" >&2
      cat "$SERVE_DRILL_LOG" >&2; exit 1
    fi
    grep "serve evict drill ok" "$SERVE_DRILL_LOG"
    if python -m dlaf_tpu.obs.validate "$SERVE_DRILL_ART" --require-serve \
        > /dev/null 2>&1; then
      echo "--require-serve FAILED to flag the evict-drill artifact" \
           "(miss dispatches + retraced serve site)" >&2; exit 1
    fi
    echo "--require-serve correctly rejected the evict-drill artifact"
    echo "== smoke: serve bench arm + speedup gate =="
    # the serving workload arm (bench.py, workload=serve) must clear the
    # ISSUE-11 floor — batched entry >= 3x a loop of singleton cholesky
    # calls — enforced by bench_gate's history-free speedup leg; an
    # absurd floor must trip it (the leg's own must-trip)
    SERVE_BENCH_ART="$SERVE_DIR/serve_bench.jsonl"
    # history redirected: a CI container's numbers must never enter the
    # git-tracked drift baselines (the gate reads the obs artifact)
    DLAF_BENCH_VARIANT=serve DLAF_METRICS_PATH="$SERVE_BENCH_ART" \
      DLAF_BENCH_HISTORY_PATH="$SERVE_DIR/bench_history.jsonl" \
      DLAF_ACCURACY=1 python bench.py > /dev/null
    python scripts/bench_gate.py --fresh "$SERVE_BENCH_ART"
    if python scripts/bench_gate.py --fresh "$SERVE_BENCH_ART" \
        --min-serve-speedup 1000 > /dev/null 2>&1; then
      echo "bench_gate FAILED to flag a sub-floor serve speedup" >&2
      exit 1
    fi
    echo "bench_gate serve-speedup leg trips as required"
    echo "== smoke: autotune closed loop (ISSUE 15, docs/autotune.md) =="
    # leg 0 — clean warm start: a run steered by the COMMITTED route
    # table must hold its routes (ZERO escalate/relax records) and never
    # retrace a program (dlaf_retrace_total stays <= 1 per site). The
    # committed table is copied aside first: a CI run must never mutate
    # the git-tracked warm-start file (the .bench_history.jsonl rule).
    AT_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$AT_DIR")
    cp .autotune_table.json "$AT_DIR/table.json"
    AT_CLEAN_ART="$AT_DIR/clean.jsonl"
    DLAF_AUTOTUNE=1 DLAF_AUTOTUNE_TABLE="$AT_DIR/table.json" \
      DLAF_PROGRAM_TELEMETRY=1 DLAF_METRICS_PATH="$AT_CLEAN_ART" \
      python - <<'EOF'
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(5)
n, nb = 48, 16
x = rng.standard_normal((n, n))
mat = Matrix.from_global(x @ x.T + n * np.eye(n), TileElementSize(nb, nb))
for _ in range(4):
    cholesky("L", mat)
obs.flush()
EOF
    python - "$AT_CLEAN_ART" <<'EOF'
import json
import sys

recs = [json.loads(line) for line in open(sys.argv[1])]
decisions = [r for r in recs if r.get("type") == "autotune"]
assert decisions, "clean warm-started run emitted no autotune decisions"
moves = [r for r in decisions if r["reason"] in ("escalate", "relax")]
assert not moves, f"clean warm-started run CHANGED routes: {moves}"
hot = [m for r in recs if r.get("type") == "metrics"
       for m in r["metrics"]
       if m.get("name") == "dlaf_retrace_total" and m.get("value", 0) >= 2]
assert not hot, f"clean warm-started run retraced: {hot}"
print(f"clean warm start held the committed route ({len(decisions)} hold "
      "decision(s), zero route changes, zero retraces)")
EOF
    # drill A — injected accuracy breach: a nan_tile-poisoned input's
    # probe is non-finite, the autotuner must escalate within the ladder
    # budget, and the artifact must PASS --require-autotune (decision
    # records + gauge transitions)
    AT_BREACH_ART="$AT_DIR/breach.jsonl"
    DLAF_AUTOTUNE=1 DLAF_METRICS_PATH="$AT_BREACH_ART" python - <<'EOF'
import numpy as np
import dlaf_tpu.config as C
import dlaf_tpu.autotune as autotune
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(6)
n, nb = 48, 16
x = rng.standard_normal((n, n))
mat = Matrix.from_global(x @ x.T + n * np.eye(n), TileElementSize(nb, nb))
start = autotune.LADDER_F64.start
cholesky("L", inject.nan_tile(mat, tile=(1, 0), element=(2, 3)))
key = autotune.site_key("cholesky", n=n, nb=nb, dtype=np.float64,
                        platform="cpu")
rung = autotune.get_table().rung_of(key)
assert rung == start + 1, f"breach did not escalate: rung {rung}"
gauge = obs.registry().gauge("dlaf_autotune_route", op="cholesky",
                             knob="rung").snapshot()
assert gauge["value"] == start + 1, gauge
cholesky("L", mat)          # a clean follow-up holds the escalated route
assert autotune.get_table().rung_of(key) == start + 1
print(f"injected breach escalated rung {start} -> {start + 1} "
      "(gauge transition verified); clean follow-up held")
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$AT_BREACH_ART" --require-autotune
    # drill B — escalation exhaustion at the ladder top: under
    # DLAF_STRICT the run must die with AutotuneExhaustedError, the
    # flight recorder must dump with the autotune_exhausted trigger, and
    # the open-state artifact must be REJECTED by --require-autotune
    # (the teeth leg)
    AT_EXH_ART="$AT_DIR/exhaust.jsonl"
    exh_rc=0
    DLAF_AUTOTUNE=1 DLAF_STRICT=1 DLAF_FLIGHT_RECORDER=64 \
      DLAF_METRICS_PATH="$AT_EXH_ART" \
      python - > "$AT_DIR/exhaust.log" 2>&1 <<'EOF' || exh_rc=$?
import numpy as np
import dlaf_tpu.config as C
import dlaf_tpu.autotune as autotune
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(7)
n, nb = 48, 16
x = rng.standard_normal((n, n))
mat = Matrix.from_global(x @ x.T + n * np.eye(n), TileElementSize(nb, nb))
bad = inject.nan_tile(mat, tile=(0, 0), element=(1, 1))
ladder = autotune.LADDER_F64
for _ in range(len(ladder.rungs)):     # breach past the top: must raise
    cholesky("L", bad)
raise SystemExit(3)                    # reaching here = never exhausted
EOF
    if [ "$exh_rc" -eq 0 ] || [ "$exh_rc" -eq 3 ] \
        || ! grep -q "AutotuneExhaustedError" "$AT_DIR/exhaust.log"; then
      echo "autotune exhaustion drill did not raise under DLAF_STRICT" \
           "(rc=$exh_rc)" >&2
      cat "$AT_DIR/exhaust.log" >&2; exit 1
    fi
    if [ ! -f "$AT_EXH_ART.flight.jsonl" ] \
        || ! head -1 "$AT_EXH_ART.flight.jsonl" \
             | grep -q '"reason": "autotune_exhausted"'; then
      echo "exhaustion drill left no autotune_exhausted flight dump" >&2
      exit 1
    fi
    python -m dlaf_tpu.obs.validate "$AT_EXH_ART.flight.jsonl" \
      --require-flight
    if python -m dlaf_tpu.obs.validate "$AT_EXH_ART" --require-autotune \
        > /dev/null 2>&1; then
      echo "--require-autotune FAILED to reject the exhausted-ladder" \
           "artifact" >&2; exit 1
    fi
    echo "exhaustion drill: strict raise + flight dump + open state" \
         "rejected by --require-autotune"
    echo "== smoke: autotune bench arm + speedup gate =="
    # the autotune workload arm (bench.py, workload=autotune): learned
    # table vs pinned worst-case route, gated by bench_gate's
    # history-free --min-autotune-speedup leg — and an absurd floor must
    # trip it (the leg's own must-trip)
    AT_BENCH_ART="$AT_DIR/autotune_bench.jsonl"
    DLAF_BENCH_VARIANT=autotune DLAF_METRICS_PATH="$AT_BENCH_ART" \
      DLAF_BENCH_HISTORY_PATH="$AT_DIR/bench_history.jsonl" \
      python bench.py > /dev/null
    python scripts/bench_gate.py --fresh "$AT_BENCH_ART"
    if python scripts/bench_gate.py --fresh "$AT_BENCH_ART" \
        --min-autotune-speedup 1000 > /dev/null 2>&1; then
      echo "bench_gate FAILED to flag a sub-floor autotune speedup" >&2
      exit 1
    fi
    echo "bench_gate autotune-speedup leg trips as required"
    echo "== smoke: chaos drill 1 — preempt at b2t -> resume -> identical =="
    # the kill-and-resume proof (docs/robustness.md §5), CROSS-PROCESS:
    # (a) an uninterrupted reference run records its eigenpairs; (b) a
    # checkpointing run is killed by inject.preempt at the b2t stage
    # boundary (must die with PreemptionError, nonzero exit); (c) a fresh
    # process resumes from the on-disk checkpoints and must reproduce the
    # reference BITWISE; the shared artifact must then validate under
    # --require-resilience (resume records present, no breaker open)
    RESUME_TMP=$(mktemp -d)
    SMOKE_KEEP+=("$RESUME_TMP")
    RESIL_ART="$RESUME_TMP/resilience.jsonl"
    python - "$RESUME_TMP" <<'EOF'
import sys
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.eigensolver.eigensolver import eigensolver
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(12)
n, nb = 48, 8
x = rng.standard_normal((n, n))
a = (x + x.T) / 2
res = eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)))
np.savez(f"{sys.argv[1]}/ref.npz", w=np.asarray(res.eigenvalues),
         v=res.eigenvectors.to_numpy())
print("reference eigenpairs recorded")
EOF
    preempt_rc=0
    DLAF_RESUME_DIR="$RESUME_TMP/ck" DLAF_METRICS_PATH="$RESIL_ART" \
      python - > "$RESUME_TMP/preempt.log" 2>&1 <<'EOF' || preempt_rc=$?
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.eigensolver.eigensolver import eigensolver
from dlaf_tpu.health import inject
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(12)
n, nb = 48, 8
x = rng.standard_normal((n, n))
a = (x + x.T) / 2
with inject.preempt("b2t"):
    eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)))
raise SystemExit(3)   # reaching here = the preemption never fired
EOF
    if [ "$preempt_rc" -eq 0 ] || [ "$preempt_rc" -eq 3 ] \
        || ! grep -q "PreemptionError" "$RESUME_TMP/preempt.log"; then
      echo "preemption drill did not kill the pipeline (rc=$preempt_rc)" >&2
      cat "$RESUME_TMP/preempt.log" >&2; exit 1
    fi
    DLAF_RESUME_DIR="$RESUME_TMP/ck" DLAF_METRICS_PATH="$RESIL_ART" \
      python - "$RESUME_TMP" <<'EOF'
import sys
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.eigensolver.eigensolver import eigensolver
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(12)
n, nb = 48, 8
x = rng.standard_normal((n, n))
a = (x + x.T) / 2
res = eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb)),
                  resume=True)
ref = np.load(f"{sys.argv[1]}/ref.npz")
np.testing.assert_array_equal(np.asarray(res.eigenvalues), ref["w"])
np.testing.assert_array_equal(res.eigenvectors.to_numpy(), ref["v"])
print("kill -> resume -> eigenpairs BITWISE identical to the "
      "uninterrupted run")
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$RESIL_ART" --require-resilience
    echo "== smoke: chaos drill 2 — dispatch retry + breaker teeth =="
    # leg A: fail_dispatch twice -> the policy engine retries and the
    # stream succeeds; the artifact's retry records satisfy
    # --require-resilience. leg B (separate process/artifact): a
    # sustained fault exhausts the retries, the bucket breaker OPENS, and
    # the process dies mid-storm (os._exit models the real crash) — that
    # artifact must be REJECTED by --require-resilience (breaker left
    # open), proving the gate has teeth
    RETRY_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$RETRY_DIR")
    DLAF_METRICS_PATH="$RETRY_DIR/retry.jsonl" python - <<'EOF'
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.health import inject
from dlaf_tpu.serve import ProgramService, Queue, Request

C.initialize()
rng = np.random.default_rng(3)
x = rng.standard_normal((24, 24))
a = x @ x.T + 24 * np.eye(24)
q = Queue(ProgramService(), batch=2, deadline_s=1e9, buckets=(32,),
          retry_attempts=3)
with inject.fail_dispatch(nth=0, count=2):
    t1 = q.submit(Request(op="cholesky", a=a))
    t2 = q.submit(Request(op="cholesky", a=a + np.eye(24)))
assert t1.done and t2.done, "retry did not recover the dispatch"
retries = [m for m in obs.registry().snapshot()
           if m["name"] == "dlaf_retry_total"
           and m["labels"].get("site", "").startswith("serve.")]
assert retries and sum(m["value"] for m in retries) >= 2, retries
print(f"fail_dispatch x2 recovered by retry "
      f"({int(sum(m['value'] for m in retries))} retries counted)")
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$RETRY_DIR/retry.jsonl" \
      --require-resilience
    DLAF_METRICS_PATH="$RETRY_DIR/breaker.jsonl" python - <<'EOF'
import os
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.health import circuit, inject
from dlaf_tpu.health.errors import CircuitOpenError
from dlaf_tpu.serve import ProgramService, Queue, Request

C.initialize()
rng = np.random.default_rng(4)
x = rng.standard_normal((24, 24))
a = x @ x.T + 24 * np.eye(24)
q = Queue(ProgramService(), batch=1, deadline_s=1e9, buckets=(32,),
          retry_attempts=3)
with inject.fail_dispatch(nth=0, count=100):
    try:
        q.submit(Request(op="cholesky", a=a))
        raise SystemExit(3)   # the sustained fault must fail the dispatch
    except RuntimeError:
        pass
    (bucket,) = q.stats()["buckets"].values()
    assert bucket["breaker"] == "open", bucket
    try:
        q.submit(Request(op="cholesky", a=a))
        raise SystemExit(3)   # the open breaker must fail fast
    except CircuitOpenError:
        pass
    print("thrice-consecutive failure opened the breaker; fails fast")
    obs.flush()
    # model the real incident: the process dies while the breaker is
    # open (skip atexit/injection cleanup — the artifact must end in
    # the tripped state the validator exists to reject)
    os._exit(0)
EOF
    if python -m dlaf_tpu.obs.validate "$RETRY_DIR/breaker.jsonl" \
        --require-resilience > /dev/null 2>&1; then
      echo "--require-resilience FAILED to reject the open-breaker" \
           "artifact" >&2; exit 1
    fi
    echo "--require-resilience correctly rejected the open-breaker artifact"
    echo "== smoke: chaos drill 3 — overload shed, bounded depth =="
    # a burst at 2x DLAF_SERVE_MAX_DEPTH: the overflow must shed fast
    # with OverloadError (counted per bucket), pending depth must NEVER
    # exceed the bound, and every accepted ticket must complete — zero
    # stranded (docs/serving.md overload protection)
    DLAF_SERVE_MAX_DEPTH=8 DLAF_METRICS_PATH="$RETRY_DIR/overload.jsonl" \
      python - <<'EOF'
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.health.errors import OverloadError
from dlaf_tpu.serve import ProgramService, Queue, Request

C.initialize()
rng = np.random.default_rng(5)
q = Queue(ProgramService(), batch=16, deadline_s=1e9, buckets=(16,))
assert q.max_depth == 8, q.max_depth     # the env knob reached the queue
tickets, shed, max_seen = [], 0, 0
for i in range(16):                      # 2x the admission bound
    x = rng.standard_normal((12, 12))
    try:
        tickets.append(q.submit(Request(op="cholesky",
                                        a=x @ x.T + 12 * np.eye(12))))
    except OverloadError:
        shed += 1
    max_seen = max(max_seen, q.pending())
assert shed == 8 and len(tickets) == 8, (shed, len(tickets))
assert max_seen <= 8, f"depth {max_seen} exceeded the bound"
q.flush()
stranded = [t for t in tickets if not t.done and t.error is None]
assert not stranded, f"{len(stranded)} stranded tickets"
assert q.stats()["shed"] == 8, q.stats()
snap = [m for m in obs.registry().snapshot()
        if m["name"] == "dlaf_serve_shed_total"]
assert snap and sum(m["value"] for m in snap) == 8, snap
print(f"overload drill ok: shed={shed}, max depth {max_seen}/8, "
      f"0 stranded of {len(tickets)} accepted")
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$RETRY_DIR/overload.jsonl"
    echo "== smoke: chaos drill 4 — fleet replica kill, zero loss =="
    # 3 REAL subprocess workers behind one fleet Router (docs/fleet.md):
    # a mixed cholesky/solve stream is mid-flight when the replica
    # holding unacked tickets dies by SIGKILL — every ticket must still
    # resolve with a CORRECT answer, zero tickets lost, >= 1 observed
    # redispatch, and the merged per-process artifact must PASS
    # --require-fleet (trace-stamped route records, zero-loss contract).
    # One driver script, three modes (FLEET_MODE): the kill drill, its
    # graceful SIGTERM twin, and the failover-off must-trip leg
    FLEET_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$FLEET_DIR")
    cat > "$FLEET_DIR/drill.py" <<'EOF'
"""Fleet chaos-drill driver (ci/run.sh smoke; mode from FLEET_MODE)."""
import os
import signal
import subprocess
import sys
import time

import numpy as np

import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.fleet import Router
from dlaf_tpu.serve import Request, cholesky_spec

mode = os.environ["FLEET_MODE"]
C.initialize()
router = Router(port=0)
env = dict(os.environ, DLAF_METRICS_PATH=os.environ["FLEET_WORKER_ART"])
procs = [subprocess.Popen(
    [sys.executable, "-m", "dlaf_tpu.fleet.worker",
     "--connect", f"127.0.0.1:{router.port}", "--worker", str(k)],
    env=env) for k in range(3)]
deadline = time.monotonic() + 120
while True:
    states = router.stats()["workers"]
    if sum(1 for m in states.values() if m["state"] == "up") == 3:
        break
    assert time.monotonic() < deadline, f"workers never joined: {states}"
    router.poll()
    time.sleep(0.05)
router.warmup([cholesky_spec(batch=4, n=16, nb=16, dtype="float64")])

rng = np.random.default_rng(0)


def hpd(n):
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


reqs = [Request(op="cholesky", a=hpd(int(rng.integers(10, 17))))
        for _ in range(8)]
for _ in range(4):
    reqs.append(Request(op="solve",
                        a=np.tril(rng.standard_normal((12, 12)))
                        + 3 * np.eye(12),
                        b=rng.standard_normal((12, 3))))
tickets = [router.submit(r) for r in reqs[:6]]

# the victim: whichever replica holds an unresolved ticket's unacked
# dispatch — batch=4/huge-deadline guarantees a partial batch is still
# queued there, so the kill strands real work, not an idle socket
router.poll()
pending = [t for t in tickets if not t.resolved()]
assert pending, "no unacked tickets to strand (batch/deadline config?)"
victim = pending[0].attempts[-1]
vpid = router.stats()["workers"][victim]["pid"]
os.kill(vpid, signal.SIGTERM if mode == "sigterm" else signal.SIGKILL)
procs[victim].wait(timeout=60)

tickets += [router.submit(r) for r in reqs[6:]]  # routed around the hole
router.flush()
ok = router.join(tickets, timeout_s=180.0)
st = router.stats()
if mode == "nofailover":
    assert st["lost"] >= 1, st
    lost = [t for t in tickets if t.error is not None]
    assert lost, "failover off but no ticket was poisoned"
    for t in lost:
        try:
            t.result()
            raise SystemExit(3)  # a lost ticket must NOT answer
        except RuntimeError:
            pass
    print(f"failover OFF: {st['lost']} ticket(s) stranded as designed")
else:
    assert ok, f"stream did not complete: {st}"
    for t in tickets:
        a = np.asarray(t.request.a)
        if t.request.op == "cholesky":
            fac = np.tril(t.result())
            ref = np.tril(a) + np.tril(a, -1).T
            assert np.allclose(fac @ fac.T, ref, atol=1e-8)
        else:
            x = t.result()
            assert np.allclose(np.tril(a) @ x, np.asarray(t.request.b),
                               atol=1e-8)
    assert st["lost"] == 0, st
    assert st["workers"][victim]["state"] == "dead", st
    if mode == "sigkill":
        assert st["redispatches"] >= 1, st
        assert procs[victim].returncode != 0, "SIGKILL exited cleanly?"
    else:                       # sigterm: drained handbacks, NO failover
        assert st["redispatches"] == 0, st
        assert st["handbacks"] >= 1, st
        assert procs[victim].returncode == 0, procs[victim].returncode
    print(f"fleet {mode} drill ok: {len(tickets)} tickets resolved, "
          f"lost={st['lost']}, redispatches={st['redispatches']}, "
          f"handbacks={st['handbacks']}")
router.drain_fleet()
obs.flush()
for p in procs:
    if p.poll() is None:
        p.terminate()
        p.wait(timeout=30)
EOF
    DLAF_METRICS_PATH="$FLEET_DIR/kill_router.jsonl" \
      FLEET_WORKER_ART="$FLEET_DIR/kill_worker.r%r.jsonl" \
      FLEET_MODE=sigkill DLAF_SERVE_BATCH=4 DLAF_SERVE_BUCKETS=16 \
      DLAF_SERVE_DEADLINE_MS=60000 PYTHONPATH="$PWD" \
      python "$FLEET_DIR/drill.py"
    python -m dlaf_tpu.obs.aggregate "$FLEET_DIR"/kill_*.jsonl \
      -o "$FLEET_DIR/kill_merged.jsonl"
    python -m dlaf_tpu.obs.validate "$FLEET_DIR/kill_merged.jsonl" \
      --require-fleet
    # graceful twin: SIGTERM the same victim profile — the worker drains
    # (absorbs + hands back its undispatched tickets, exit 0) and the
    # router re-routes the handbacks with ZERO failover redispatches;
    # the artifact still passes --require-fleet (worker_dead carries
    # reason=drained, so no redispatch obligation applies)
    DLAF_METRICS_PATH="$FLEET_DIR/drain_router.jsonl" \
      FLEET_WORKER_ART="$FLEET_DIR/drain_worker.r%r.jsonl" \
      FLEET_MODE=sigterm DLAF_SERVE_BATCH=4 DLAF_SERVE_BUCKETS=16 \
      DLAF_SERVE_DEADLINE_MS=60000 PYTHONPATH="$PWD" \
      python "$FLEET_DIR/drill.py"
    python -m dlaf_tpu.obs.aggregate "$FLEET_DIR"/drain_*.jsonl \
      -o "$FLEET_DIR/drain_merged.jsonl"
    python -m dlaf_tpu.obs.validate "$FLEET_DIR/drain_merged.jsonl" \
      --require-fleet
    # must-trip: with failover OFF the same kill strands tickets — the
    # artifact carries ticket_lost records and --require-fleet must
    # REJECT it, proving the zero-loss contract has teeth
    DLAF_METRICS_PATH="$FLEET_DIR/off_router.jsonl" \
      FLEET_WORKER_ART="$FLEET_DIR/off_worker.r%r.jsonl" \
      FLEET_MODE=nofailover DLAF_FLEET_FAILOVER=0 DLAF_SERVE_BATCH=4 \
      DLAF_SERVE_BUCKETS=16 DLAF_SERVE_DEADLINE_MS=60000 \
      PYTHONPATH="$PWD" python "$FLEET_DIR/drill.py"
    python -m dlaf_tpu.obs.aggregate "$FLEET_DIR"/off_*.jsonl \
      -o "$FLEET_DIR/off_merged.jsonl"
    off_out=$(python -m dlaf_tpu.obs.validate \
      "$FLEET_DIR/off_merged.jsonl" --require-fleet 2>&1) && {
      echo "--require-fleet FAILED to reject the lost-ticket artifact" >&2
      exit 1
    }
    echo "$off_out" | grep -q "ticket_lost" || {
      echo "lost-ticket rejection did not name ticket_lost:" >&2
      echo "$off_out" >&2; exit 1
    }
    echo "--require-fleet correctly rejected the failover-off artifact"
    echo "== smoke: fleet bench arm + scaling gate =="
    # the fleet workload arm (bench.py, workload=fleet): requests/s over
    # N real subprocess replicas vs one through the same router, plus
    # the mid-stream SIGKILL recovery_s leg — gated by bench_gate's
    # history-free --min-fleet-scaling floor, whose must-trip is an
    # absurd floor the measured ratio cannot clear
    FLEET_BENCH_ART="$FLEET_DIR/fleet_bench.jsonl"
    DLAF_BENCH_VARIANT=fleet DLAF_METRICS_PATH="$FLEET_BENCH_ART" \
      DLAF_BENCH_HISTORY_PATH="$FLEET_DIR/bench_history.jsonl" \
      python bench.py > /dev/null
    python scripts/bench_gate.py --fresh "$FLEET_BENCH_ART"
    if python scripts/bench_gate.py --fresh "$FLEET_BENCH_ART" \
        --min-fleet-scaling 1000 > /dev/null 2>&1; then
      echo "bench_gate FAILED to flag a sub-floor fleet scaling" >&2
      exit 1
    fi
    echo "bench_gate fleet-scaling leg trips as required"
    echo "== smoke: eigensolver pipeline (batched D&C + pipelined bt) =="
    # distributed eigensolver on a 2x2 virtual-CPU grid with the two
    # ISSUE-6 knobs pinned ON (the CPU auto would resolve both off): the
    # artifact must carry the level-batched merge counters
    # (dlaf_dc_merges_total{mode=batched}) AND the hoisted bt-collective
    # counters (dlaf_comm_overlapped_total{algo=bt_*}) — the audit trail
    # that the batched/pipelined programs were actually built
    # (docs/eigensolver_perf.md)
    EIG_DIR=$(mktemp -d)
    SMOKE_KEEP+=("$EIG_DIR")
    EIG_ART="$EIG_DIR/eigensolver_metrics.jsonl"
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4" \
      DLAF_METRICS_PATH="$EIG_ART" \
      DLAF_DC_LEVEL_BATCH=1 DLAF_BT_LOOKAHEAD=1 DLAF_DIST_STEP_MODE=unrolled \
      python - <<'EOF'
import numpy as np
import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.eigensolver.eigensolver import eigensolver
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
n, nb = 64, 8
x = rng.standard_normal((n, n))
a = (x + x.T) / 2
res = eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb),
                                          grid=Grid(2, 2)))
q = res.eigenvectors.to_numpy()
resid = np.linalg.norm(a @ q - q * res.eigenvalues[None, :])
assert resid < 1e-10 * n, resid
print(f"eigensolver smoke ok: n={n} residual={resid:.2e}")
obs.flush()
EOF
    python -m dlaf_tpu.obs.validate "$EIG_ART" \
      --require-spans --require-dc-batch --require-bt-overlap
    echo "== smoke: sanitizers (debug_nans + transfer guard happy path) =="
    # dynamic counterpart of the static no-host-callback audit below: a
    # tiny local AND 2x2-distributed cholesky must neither produce NaNs
    # on the happy path (jax_debug_nans re-executes op-by-op on any NaN)
    # nor fetch device values mid-factorization (device->host transfer
    # guard; result fetch happens AFTER the guard, the caller's explicit
    # decision — the same contract test_health pins for with_info)
    XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4" \
      python - <<'EOF'
import numpy as np
import jax
import dlaf_tpu.config as C
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix

C.initialize()
rng = np.random.default_rng(0)
for grid_shape in (None, (2, 2)):
    x = rng.standard_normal((32, 32))
    a = x @ x.T + 32 * np.eye(32)
    grid = Grid(*grid_shape) if grid_shape else None
    label = "2x2" if grid_shape else "local"
    # phase 1: NaN sanitizer armed, full run + fetch
    jax.config.update("jax_debug_nans", True)
    try:
        fac = cholesky("L", Matrix.from_global(a, TileElementSize(8, 8),
                                               grid=grid))
        l = np.tril(fac.to_numpy())
    finally:
        jax.config.update("jax_debug_nans", False)
    assert np.isfinite(l).all()
    assert np.allclose(l @ l.T, a, atol=1e-8), abs(l @ l.T - a).max()
    # phase 2: transfer guard armed — the hot path must not host-sync
    mat = Matrix.from_global(a, TileElementSize(8, 8), grid=grid)
    with jax.transfer_guard_device_to_host("disallow"):
        fac = cholesky("L", mat)
        jax.block_until_ready(fac.storage)
    print(f"sanitizer smoke ok: {label} (debug_nans + transfer guard)")
EOF
    ;;
  main)
    python -m pytest tests/ -q -m "not slow" ;;
  full)
    python -m pytest tests/ -q
    echo "== armed probe scripts: tiny-N CPU smoke =="
    # the hardware-session probes must stay runnable between tunnel
    # windows: trace+compile both step forms at a toy size (no exec) and
    # run the precision probe end-to-end at tiny shapes. Failures here
    # mean a probe would die on the next healthy window.
    PROBE_TMP=$(mktemp -d)
    DLAF_FRONTIER_N=512 \
      python scripts/tpu_compile_frontier.py "$PROBE_TMP/frontier.json" \
        --skip-exec
    python - "$PROBE_TMP/frontier.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
bad = [p for p in doc["points"] if "error" in p or "compile_s" not in p]
assert not bad, f"frontier smoke: {bad}"
print(f"frontier smoke ok: {len(doc['points'])} points compiled")
EOF
    DLAF_PREC_M=256 DLAF_PREC_K=32 \
      python scripts/tpu_prec_probe.py "$PROBE_TMP/prec.json"
    python - "$PROBE_TMP/prec.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
prims = [p for p in doc if p["probe"].startswith("prim_")]
rels = [p for p in doc if "rel_err" in p]
assert prims and rels, f"prec smoke incomplete: {doc}"
assert all(p.get("ok", True) for p in prims), f"prim findings: {prims}"
assert all(p["rel_err"] < 1e-10 for p in rels), f"prec smoke: {rels}"
print(f"prec smoke ok: {len(doc)} probes")
EOF
    rm -rf "$PROBE_TMP" ;;
  *)
    echo "usage: ci/run.sh [smoke|main|full]" >&2; exit 2 ;;
esac

echo "== static analysis gate (jaxpr auditor + convention linter) =="
# every tier: the graph auditor traces every builder on the 8-virtual-
# device CPU platform (no compile/exec) and the AST linter walks
# dlaf_tpu/; any finding not in the committed .analysis_baseline.json
# fails the tier (docs/static_analysis.md)
python -m dlaf_tpu.analysis

echo "== static analysis must-trip drills =="
# like the bench/accuracy gates, the analysis gate must PROVE it can
# fail: each seeded-bad program must exit SPECIFICALLY 1 with its rule
# named in the log (exit 3 = the checker lost its teeth; any other exit
# = a crash masquerading as detection). Deliberately per-drill fresh
# interpreters — the exit-code contract IS the thing under test; the
# six processes cost ~45 s total, within every tier's budget
ANALYSIS_DRILL_LOG=$(mktemp)
# the drill list comes from the registry itself (--list-drills), so a
# drill added to analysis/drills.py is automatically exercised here; the
# CLI prints "drill <name>: tripped [<rules>] as required" only when
# every expected rule was reported, and exits 3 when a checker lost its
# teeth — so rc=1 + that line IS the proof, with the rules named
ANALYSIS_DRILLS=$(python -m dlaf_tpu.analysis --list-drills)
[ -n "$ANALYSIS_DRILLS" ] || { echo "no analysis drills found" >&2; exit 1; }
for drill in $ANALYSIS_DRILLS; do
  drill_rc=0
  python -m dlaf_tpu.analysis --drill "$drill" \
    > "$ANALYSIS_DRILL_LOG" 2>&1 || drill_rc=$?
  if [ "$drill_rc" -ne 1 ] \
      || ! grep -q "as required" "$ANALYSIS_DRILL_LOG"; then
    echo "analysis drill $drill did not trip cleanly" \
         "(rc=$drill_rc, wanted rc=1 + 'tripped ... as required')" >&2
    cat "$ANALYSIS_DRILL_LOG" >&2; exit 1
  fi
  grep "as required" "$ANALYSIS_DRILL_LOG"
done

echo "== ruff check (style linter; config in pyproject.toml) =="
if command -v ruff >/dev/null 2>&1; then
  ruff check .
else
  # hermetic CI images may lack ruff; the repo-specific conventions are
  # still enforced by the dlaf_tpu.analysis gate above
  echo "ruff not installed in this environment; skipping"
fi

echo "== driver entry: single-device compile check =="
python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn)(*args).block_until_ready()
print("entry() ok")
EOF

echo "== driver entry: 8-device sharding dry run =="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "CI tier '$TIER': PASSED"
