// Native deflation scan for the D&C tridiagonal eigensolver merge.
//
// Counterpart of the reference's vectorized C++ deflation
// (eigensolver/tridiag_solver/merge.h:443-508, LAPACK dlaed2 semantics):
// given the sorted poles d, the normalized coupling weights z, and the
// z-based liveness precomputed by the caller, rotate the z weight of
// near-equal pole pairs onto the earlier live pole (Givens), deflating the
// later one. The scan is inherently sequential (each rotation updates the
// running anchor's z weight, which feeds later rotations), which makes it
// an interpreter bottleneck in Python at n ~ 32k; here it is a single O(n)
// pass (the previous-live index is carried, not re-scanned).
//
// In/out: z (modified), live (uint8, modified). Outputs: up to n Givens
// rotations as (i, j, c, s) quadruples. Returns the rotation count, or -1
// on bad arguments.

#include <cmath>
#include <cstdint>

extern "C" int64_t dlaf_deflate_scan_d(const double* d, double* z,
                                       uint8_t* live, int64_t n, double tol,
                                       int64_t* giv_i, int64_t* giv_j,
                                       double* giv_c, double* giv_s) {
  if (n < 0 || (n > 0 && (!d || !z || !live))) return -1;
  int64_t g = 0;
  int64_t prev = -1;  // latest live index before j (post-deflation)
  for (int64_t j = 0; j < n; ++j) {
    if (!live[j]) continue;
    if (prev >= 0 && d[j] - d[prev] <= tol) {
      double r = std::hypot(z[prev], z[j]);
      if (r == 0.0) {
        prev = j;  // both weights zero: j stays live, becomes the anchor
        continue;
      }
      giv_i[g] = prev;
      giv_j[g] = j;
      giv_c[g] = z[prev] / r;
      giv_s[g] = z[j] / r;
      z[prev] = r;
      z[j] = 0.0;
      live[j] = 0;
      ++g;
    } else {
      prev = j;
    }
  }
  return g;
}
