// Native secular-equation root solver for the D&C tridiagonal eigensolver.
//
// Counterpart of the reference's per-eigenvalue LAPACK laed4 calls
// (reference eigensolver/tridiag_solver/merge.h:590-629 runs laed4 on the
// CPU; this framework cannot link LAPACK, so the solver is implemented
// here): for each i in 0..k-1 find the root lambda_i of
//
//     f(lambda) = 1 + rho * sum_j z_j^2 / (d_j - lambda) = 0
//
// in the open interval (d_i, d_{i+1}) (last interval: (d_{k-1},
// d_{k-1} + rho * sum z^2)), with d ascending, z nonzero, rho > 0.
//
// Representation matches the Python host/device twins: the root is returned
// as (anchor index, offset) with the anchor chosen as the nearest pole by
// the sign of f at the interval midpoint, so downstream pole differences
// d_j - lambda_i never suffer cancellation.
//
// Method: safeguarded Newton on g(mu) = f(d_anchor + mu), which is strictly
// increasing across each interval; the bracket is maintained and any Newton
// step leaving it falls back to bisection — unconditionally convergent,
// typically ~4-6 evaluations vs the vectorized bisection's 90.
//
// Threaded with std::thread across roots (each root is independent).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

struct Problem {
  const double* d;
  const double* zsq;  // z_j^2, precomputed
  double rho;
  std::int64_t k;
};

// g(mu) and g'(mu) about the anchor pole: delta_j = d_j - d_anchor.
inline void eval(const Problem& p, double danchor, double mu, double* g,
                 double* gp) {
  double s = 0.0, sp = 0.0;
  for (std::int64_t j = 0; j < p.k; ++j) {
    const double inv = 1.0 / ((p.d[j] - danchor) - mu);
    const double t = p.zsq[j] * inv;
    s += t;
    sp += t * inv;
  }
  *g = 1.0 + p.rho * s;
  *gp = p.rho * sp;  // > 0: g strictly increasing in mu
}

void solve_range(const Problem& p, double zsum, std::int64_t i0,
                 std::int64_t i1, std::int64_t* anchor, double* mu_out) {
  for (std::int64_t i = i0; i < i1; ++i) {
    const double di = p.d[i];
    const double upper = (i + 1 < p.k) ? p.d[i + 1] : p.d[p.k - 1] + p.rho * zsum;
    const double gap = upper - di;

    // anchor by the sign of f at the midpoint (matches the Python twins)
    double g, gp;
    eval(p, 0.0, di + 0.5 * gap, &g, &gp);
    std::int64_t a = (g >= 0.0 || i + 1 >= p.k) ? i : i + 1;
    if (i == p.k - 1) a = p.k - 1;
    const double da = p.d[a];
    double lo = (a == i) ? 0.0 : di - upper;  // left- vs right-anchored
    double hi = (a == i) ? gap : 0.0;

    // safeguarded Newton on the bracket [lo, hi]; the returned root is the
    // evaluated point with the smallest |g| (Newton converges one-sided, so
    // the bracket midpoint can lag far behind the best iterate)
    // iteration cap: near-deflated z entries put roots ~eps^2 * gap from
    // their pole, and the bisection-dominated phase needs ~log2(gap/mu)
    // halvings to get there (the worst case observed is ~1e-28 offsets, i.e.
    // >90 halvings) — 300 bounds even denormal-scale descents
    double mu = 0.5 * (lo + hi);
    double best_mu = mu, best_ag = HUGE_VAL;
    for (int it = 0; it < 300; ++it) {
      eval(p, da, mu, &g, &gp);
      if (std::isfinite(g) && std::fabs(g) < best_ag) {
        best_ag = std::fabs(g);
        best_mu = mu;
      }
      if (g >= 0.0)
        hi = mu;
      else
        lo = mu;
      double step_mu;
      if (gp > 0.0 && std::isfinite(g)) {
        step_mu = mu - g / gp;
        if (!(step_mu > lo && step_mu < hi)) step_mu = 0.5 * (lo + hi);
      } else {
        step_mu = 0.5 * (lo + hi);
      }
      // downstream eigenvector coefficients need RELATIVE accuracy in the
      // offset mu (the anchor pole difference is exactly -mu), so stop on
      // the bracket being tight relative to |mu|, not to the interval size
      const double width = hi - lo;
      const double scale = std::fmax(std::fabs(best_mu), 1e-300);
      if (width <= 4.0 * 2.220446049250313e-16 * scale || best_ag == 0.0) break;
      if (step_mu == mu) break;  // no representable progress
      mu = step_mu;
    }
    anchor[i] = a;
    mu_out[i] = best_mu;
  }
}

}  // namespace

// nthreads_req <= 0: auto (hardware concurrency, bounded by roots per
// thread); >= 1: forced worker count — results are bitwise identical at
// any count (each root is solved independently from read-only inputs),
// which tests/test_tridiag_solver.py pins with a forced-4 run.
extern "C" int dlaf_secular_roots_d_nt(const double* d, const double* z,
                                       double rho, std::int64_t k,
                                       std::int64_t* anchor, double* mu,
                                       std::int64_t nthreads_req) {
  if (k <= 0) return 0;
  std::vector<double> zsq(static_cast<size_t>(k));
  double zsum = 0.0;
  for (std::int64_t j = 0; j < k; ++j) {
    zsq[static_cast<size_t>(j)] = z[j] * z[j];
    zsum += zsq[static_cast<size_t>(j)];
  }
  Problem p{d, zsq.data(), rho, k};

  std::int64_t nthreads;
  if (nthreads_req >= 1) {
    nthreads = std::min<std::int64_t>(nthreads_req, k);
  } else {
    const unsigned hw = std::thread::hardware_concurrency();
    const std::int64_t min_per_thread = 64;
    nthreads = std::min<std::int64_t>(hw ? hw : 1,
                                      (k + min_per_thread - 1) / min_per_thread);
  }
  if (nthreads <= 1) {
    solve_range(p, zsum, 0, k, anchor, mu);
    return 0;
  }
  std::vector<std::thread> threads;
  const std::int64_t chunk = (k + nthreads - 1) / nthreads;
  for (std::int64_t t = 0; t < nthreads; ++t) {
    const std::int64_t i0 = t * chunk;
    const std::int64_t i1 = std::min(k, i0 + chunk);
    if (i0 >= i1) break;
    threads.emplace_back(
        [&p, zsum, i0, i1, anchor, mu] { solve_range(p, zsum, i0, i1, anchor, mu); });
  }
  for (auto& th : threads) th.join();
  return 0;
}

extern "C" int dlaf_secular_roots_d(const double* d, const double* z,
                                    double rho, std::int64_t k,
                                    std::int64_t* anchor, double* mu) {
  return dlaf_secular_roots_d_nt(d, z, rho, k, anchor, mu, 0);
}
