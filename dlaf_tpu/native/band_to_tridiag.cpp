// Native bulge-chasing kernel: Hermitian band -> tridiagonal.
//
// C++ twin of dlaf_tpu/eigensolver/band_to_tridiag.py (the numpy reference
// implementation); see that module for the algorithm notes and the uniform
// reflector layout contract. This is the performance path for the host stage
// the reference also keeps CPU-only (its pika SweepWorker pipeline,
// eigensolver/band_to_tridiag/mc.h) — here a single tight loop; sweep-level
// pipelining across cores can come later without changing the interface.
//
// Build: g++ -O3 -shared -fPIC band_to_tridiag.cpp -o libdlaf_native.so
// Interface: C ABI consumed via ctypes (dlaf_tpu/native/bindings.py).

#include <cmath>
#include <complex>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

template <typename T>
struct Traits;

template <>
struct Traits<double> {
  static double conj(double x) { return x; }
  static double abs(double x) { return std::fabs(x); }
  static double real(double x) { return x; }
};

template <>
struct Traits<std::complex<double>> {
  static std::complex<double> conj(std::complex<double> x) { return std::conj(x); }
  static double abs(std::complex<double> x) { return std::abs(x); }
  static double real(std::complex<double> x) { return x.real(); }
};

// Householder generator: (I - tau v v^H) x = beta e1, v[0]=1, beta real.
template <typename T>
void larfg(long m, T* x, T* v, T* tau, double* beta_out) {
  T alpha = x[0];
  double xnorm = 0.0;
  for (long i = 1; i < m; ++i) {
    double a = Traits<T>::abs(x[i]);
    xnorm = std::hypot(xnorm, a);
  }
  double alpha_im = Traits<T>::abs(alpha - T(Traits<T>::real(alpha)));
  if (xnorm == 0.0 && alpha_im == 0.0) {
    for (long i = 0; i < m; ++i) v[i] = T(0);
    *tau = T(0);
    *beta_out = Traits<T>::real(alpha);
    return;
  }
  double r = std::hypot(Traits<T>::abs(alpha), xnorm);
  double ar = Traits<T>::real(alpha);
  double beta = (ar != 0.0) ? -std::copysign(r, ar) : -r;
  // our convention: tau = conj((beta - alpha)/beta)
  T t = Traits<T>::conj((T(beta) - alpha) / T(beta));
  T scale = T(1.0) / (alpha - T(beta));
  v[0] = T(1);
  for (long i = 1; i < m; ++i) v[i] = x[i] * scale;
  *tau = t;
  *beta_out = beta;
}

template <typename T>
struct BandChase {
  long n, b, ld;  // ld = 2b+1 rows of working band
  std::vector<T> wb;          // wb[r*n + j] = A[j+r, j]
  std::vector<T> win, blk, u, w, tmp;

  BandChase(const T* band, long n_, long b_) : n(n_), b(b_), ld(2 * b_ + 1) {
    wb.assign(static_cast<size_t>(ld) * n, T(0));
    for (long r = 0; r <= b; ++r)
      std::memcpy(&wb[r * n], &band[r * n], sizeof(T) * n);
    win.resize(b * b);
    blk.resize(b * b);
    u.resize(b);
    w.resize(b);
  }

  T& at(long i, long j) { return wb[(i - j) * n + j]; }  // i >= j, i-j <= 2b

  // S <- H S H^H on the Hermitian window A[j0:j0+m, j0:j0+m]
  void two_sided(long j0, long m, const T* v, T tau) {
    // dense Hermitian window
    for (long c = 0; c < m; ++c)
      for (long r = 0; r < m; ++r)
        win[r * m + c] = (r >= c) ? at(j0 + r, j0 + c)
                                  : Traits<T>::conj(at(j0 + c, j0 + r));
    for (long r = 0; r < m; ++r) win[r * m + r] = T(Traits<T>::real(win[r * m + r]));
    // u = S v ; vhu = v^H u (real)
    for (long r = 0; r < m; ++r) {
      T acc = T(0);
      for (long c = 0; c < m; ++c) acc += win[r * m + c] * v[c];
      u[r] = acc;
    }
    T vhu = T(0);
    for (long r = 0; r < m; ++r) vhu += Traits<T>::conj(v[r]) * u[r];
    double a2 = Traits<T>::abs(tau);
    T half = T(a2 * a2 / 2.0) * vhu;
    for (long r = 0; r < m; ++r) w[r] = Traits<T>::conj(tau) * u[r] - half * v[r];
    // S -= w v^H + v w^H  (write back lower triangle only)
    for (long c = 0; c < m; ++c)
      for (long r = c; r < m; ++r)
        at(j0 + r, j0 + c) = win[r * m + c] - w[r] * Traits<T>::conj(v[c]) -
                             v[r] * Traits<T>::conj(w[c]);
  }

  void run(T* v_out, T* tau_out, long n_steps, double* d_out, T* e_out) {
    // n-2 sweeps like the numpy reference; complex off-diagonal phases are
    // normalized by the caller (python side), not by an extra sweep.
    for (long s = 0; s < n - 2; ++s) {
      long l = std::min(b, n - 1 - s);
      if (l < 1) continue;
      // column s below diag
      std::vector<T> x(l);
      for (long i = 0; i < l; ++i) x[i] = wb[(1 + i) * n + s];
      std::vector<T> v(l);
      T tau;
      double beta;
      larfg<T>(l, x.data(), v.data(), &tau, &beta);
      wb[1 * n + s] = T(beta);
      for (long i = 1; i < l; ++i) wb[(1 + i) * n + s] = T(0);
      T* vrow = &v_out[(s * n_steps + 0) * b];
      for (long i = 0; i < l; ++i) vrow[i] = v[i];
      tau_out[s * n_steps + 0] = tau;

      long j0 = s + 1, t = 0;
      std::vector<T> v2(b), xcol(b);
      while (true) {
        if (Traits<T>::abs(tau) != 0.0) two_sided(j0, l, v.data(), tau);
        long l2 = std::min(b, n - (j0 + l));
        if (l2 == 0) break;
        // B = A[j0+l : j0+l+l2, j0 : j0+l];  B <- B H^H
        // column c of B is at band offsets (j0+l - (j0+c)) .. in col j0+c
        for (long r = 0; r < l2; ++r)
          for (long c = 0; c < l; ++c)
            blk[r * l + c] = at(j0 + l + r, j0 + c);
        if (Traits<T>::abs(tau) != 0.0) {
          for (long r = 0; r < l2; ++r) {
            T acc = T(0);
            for (long c = 0; c < l; ++c) acc += blk[r * l + c] * v[c];
            acc *= Traits<T>::conj(tau);
            for (long c = 0; c < l; ++c)
              blk[r * l + c] -= acc * Traits<T>::conj(v[c]);
          }
        }
        // eliminate first column of B
        for (long r = 0; r < l2; ++r) xcol[r] = blk[r * l + 0];
        T tau2;
        double beta2;
        larfg<T>(l2, xcol.data(), v2.data(), &tau2, &beta2);
        for (long r = 0; r < l2; ++r) blk[r * l + 0] = T(0);
        blk[0] = T(beta2);
        // left-apply H2 to remaining columns
        if (Traits<T>::abs(tau2) != 0.0 && l > 1) {
          for (long c = 1; c < l; ++c) {
            T acc = T(0);
            for (long r = 0; r < l2; ++r)
              acc += Traits<T>::conj(v2[r]) * blk[r * l + c];
            acc *= tau2;
            for (long r = 0; r < l2; ++r) blk[r * l + c] -= v2[r] * acc;
          }
        }
        for (long r = 0; r < l2; ++r)
          for (long c = 0; c < l; ++c)
            at(j0 + l + r, j0 + c) = blk[r * l + c];
        ++t;
        T* vr2 = &v_out[(s * n_steps + t) * b];
        for (long r = 0; r < l2; ++r) vr2[r] = v2[r];
        tau_out[s * n_steps + t] = tau2;
        j0 += l;
        l = l2;
        v.assign(v2.begin(), v2.begin() + l2);
        tau = tau2;
      }
    }
    for (long j = 0; j < n; ++j) d_out[j] = Traits<T>::real(wb[0 * n + j]);
    for (long j = 0; j + 1 < n; ++j) e_out[j] = wb[1 * n + j];
  }
};

}  // namespace

extern "C" {

// band: (b+1) x n row-major; v_out: n_sweeps*n_steps*b; tau_out:
// n_sweeps*n_steps; d_out: n; e_out: n-1 (raw, complex for _z).
int dlaf_band_to_tridiag_d(const double* band, long n, long b, long n_steps,
                           double* v_out, double* tau_out, double* d_out,
                           double* e_out) {
  if (n <= 0 || b <= 0) return 1;
  BandChase<double> chase(band, n, b);
  chase.run(v_out, tau_out, n_steps, d_out, e_out);
  return 0;
}

int dlaf_band_to_tridiag_z(const void* band, long n, long b, long n_steps,
                           void* v_out, void* tau_out, double* d_out,
                           void* e_out) {
  if (n <= 0 || b <= 0) return 1;
  using C = std::complex<double>;
  BandChase<C> chase(reinterpret_cast<const C*>(band), n, b);
  chase.run(reinterpret_cast<C*>(v_out), reinterpret_cast<C*>(tau_out),
            n_steps, d_out, reinterpret_cast<C*>(e_out));
  return 0;
}

}  // extern "C"
