// Native bulge-chasing kernel: Hermitian band -> tridiagonal.
//
// C++ twin of dlaf_tpu/eigensolver/band_to_tridiag.py (the numpy reference
// implementation); see that module for the algorithm notes and the uniform
// reflector layout contract. This is the performance path for the host stage
// the reference also keeps CPU-only (its pika SweepWorker pipeline,
// eigensolver/band_to_tridiag/mc.h) — here a single tight loop; sweep-level
// pipelining across cores can come later without changing the interface.
//
// Build: g++ -O3 -shared -fPIC band_to_tridiag.cpp -o libdlaf_native.so
// Interface: C ABI consumed via ctypes (dlaf_tpu/native/bindings.py).

#include <atomic>
#include <cmath>
#include <complex>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

template <typename T>
struct Traits;

template <>
struct Traits<double> {
  static double conj(double x) { return x; }
  static double abs(double x) { return std::fabs(x); }
  static double real(double x) { return x; }
};

template <>
struct Traits<std::complex<double>> {
  static std::complex<double> conj(std::complex<double> x) { return std::conj(x); }
  static double abs(std::complex<double> x) { return std::abs(x); }
  static double real(std::complex<double> x) { return x.real(); }
};

// Householder generator: (I - tau v v^H) x = beta e1, v[0]=1, beta real.
template <typename T>
void larfg(long m, T* x, T* v, T* tau, double* beta_out) {
  T alpha = x[0];
  double xnorm = 0.0;
  for (long i = 1; i < m; ++i) {
    double a = Traits<T>::abs(x[i]);
    xnorm = std::hypot(xnorm, a);
  }
  double alpha_im = Traits<T>::abs(alpha - T(Traits<T>::real(alpha)));
  if (xnorm == 0.0 && alpha_im == 0.0) {
    for (long i = 0; i < m; ++i) v[i] = T(0);
    *tau = T(0);
    *beta_out = Traits<T>::real(alpha);
    return;
  }
  double r = std::hypot(Traits<T>::abs(alpha), xnorm);
  double ar = Traits<T>::real(alpha);
  double beta = (ar != 0.0) ? -std::copysign(r, ar) : -r;
  // our convention: tau = conj((beta - alpha)/beta)
  T t = Traits<T>::conj((T(beta) - alpha) / T(beta));
  T scale = T(1.0) / (alpha - T(beta));
  v[0] = T(1);
  for (long i = 1; i < m; ++i) v[i] = x[i] * scale;
  *tau = t;
  *beta_out = beta;
}

// Per-worker scratch: every buffer a sweep touches, so concurrent sweeps
// never share temporaries.
template <typename T>
struct Scratch {
  std::vector<T> u, w, x, v, v2, xcol, y, acc;
  explicit Scratch(long b)
      : u(b), w(b), x(b), v(b), v2(b), xcol(b), y(b), acc(b) {}
};

template <typename T>
struct BandChase {
  long n, b, ld;  // ld = 2b+1 rows of working band
  std::vector<T> wb;          // wb[r*n + j] = A[j+r, j]

  BandChase(const T* band, long n_, long b_) : n(n_), b(b_), ld(2 * b_ + 1) {
    wb.assign(static_cast<size_t>(ld) * n, T(0));
    for (long r = 0; r <= b; ++r)
      std::memcpy(&wb[r * n], &band[r * n], sizeof(T) * n);
  }

  T& at(long i, long j) { return wb[(i - j) * n + j]; }  // i >= j, i-j <= 2b

  // S <- H S H^H on the Hermitian window A[j0:j0+m, j0:j0+m].
  //
  // All loops run DIAGONAL-major: for a fixed sub/super-diagonal d the
  // window elements S[c+d, c] are the contiguous run wb[d*n + j0 .. j0+m-d)
  // of the band storage, so both the band-matrix-vector product u = S v and
  // the rank-2 update S -= w v^H + v w^H stream the band rows linearly
  // (the previous dense-window copy strided by n on every element, which
  // was the kernel's bottleneck, not the flops).
  void two_sided(long j0, long m, const T* v, T tau, Scratch<T>& sc) {
    T* u = sc.u.data();
    T* w = sc.w.data();
    // u = S v by diagonals: d = 0 uses the real diagonal; d > 0 adds the
    // lower element to u[c+d] and its conjugate (upper) to u[c]
    for (long r = 0; r < m; ++r) u[r] = T(0);
    {
      const T* row0 = &wb[0 * n + j0];
      for (long c = 0; c < m; ++c) u[c] += T(Traits<T>::real(row0[c])) * v[c];
    }
    for (long d = 1; d < m; ++d) {
      const T* row = &wb[d * n + j0];
      const long len = m - d;
      for (long c = 0; c < len; ++c) {
        u[c + d] += row[c] * v[c];
        u[c] += Traits<T>::conj(row[c]) * v[c + d];
      }
    }
    T vhu = T(0);
    for (long r = 0; r < m; ++r) vhu += Traits<T>::conj(v[r]) * u[r];
    double a2 = Traits<T>::abs(tau);
    T half = T(a2 * a2 / 2.0) * vhu;
    for (long r = 0; r < m; ++r) w[r] = Traits<T>::conj(tau) * u[r] - half * v[r];
    // S -= w v^H + v w^H by diagonals (lower triangle in band storage)
    {
      T* row0 = &wb[0 * n + j0];
      for (long c = 0; c < m; ++c)
        row0[c] = T(Traits<T>::real(row0[c]) -
                    2.0 * Traits<T>::real(w[c] * Traits<T>::conj(v[c])));
    }
    for (long d = 1; d < m; ++d) {
      T* row = &wb[d * n + j0];
      const long len = m - d;
      for (long c = 0; c < len; ++c)
        row[c] -= w[c + d] * Traits<T>::conj(v[c]) +
                  v[c + d] * Traits<T>::conj(w[c]);
    }
  }

  // One full sweep s. ``wait(t)`` blocks until executing chase step t is
  // safe; ``done(t)`` publishes that step t's window writes are complete.
  // Step t of sweep s touches band columns [s+1+t*b, s+1+(t+1)*b) only
  // (plus column s at t=0), so with the pipeline rule "sweep s step t
  // after sweep s-1 completed step t+1" all concurrent windows are
  // disjoint and the result is bitwise identical at any thread count.
  template <typename Wait, typename Done>
  void do_sweep(long s, long n_steps, T* v_out, T* tau_out, Scratch<T>& sc,
                Wait&& wait, Done&& done) {
    long l = std::min(b, n - 1 - s);
    if (l < 1) return;
    wait(0);
    // column s below diag
    T* x = sc.x.data();
    for (long i = 0; i < l; ++i) x[i] = wb[(1 + i) * n + s];
    std::vector<T>& v = sc.v;
    T tau;
    double beta;
    larfg<T>(l, x, v.data(), &tau, &beta);
    wb[1 * n + s] = T(beta);
    for (long i = 1; i < l; ++i) wb[(1 + i) * n + s] = T(0);
    T* vrow = &v_out[(s * n_steps + 0) * b];
    for (long i = 0; i < l; ++i) vrow[i] = v[i];
    tau_out[s * n_steps + 0] = tau;

    long j0 = s + 1, t = 0;
    std::vector<T>& v2 = sc.v2;
    T* xcol = sc.xcol.data();
    T* y = sc.y.data();
    T* acc = sc.acc.data();
    while (true) {
        if (Traits<T>::abs(tau) != 0.0) two_sided(j0, l, v.data(), tau, sc);
        long l2 = std::min(b, n - (j0 + l));
        if (l2 == 0) break;
        // B = A[j0+l : j0+l+l2, j0 : j0+l), worked on IN band storage:
        // B[r, c] lives on band diagonal k2 = l + r - c, whose elements for
        // fixed k2 are the contiguous run wb[k2*n + j0 + c] (c ascending) —
        // all sweeps below stream those rows (no dense block copy)
        const long k2lo = 1, k2hi = l + l2 - 1;
        if (Traits<T>::abs(tau) != 0.0) {
          // B <- B H^H = B - conj(tau) (B v) v^H
          for (long r = 0; r < l2; ++r) y[r] = T(0);
          for (long k2 = k2lo; k2 <= k2hi; ++k2) {
            const T* row = &wb[k2 * n + j0];
            const long clo = std::max<long>(0, l - k2);
            const long chi = std::min<long>(l, l2 + l - k2);
            for (long c = clo; c < chi; ++c) y[k2 - l + c] += row[c] * v[c];
          }
          const T ct = Traits<T>::conj(tau);
          for (long k2 = k2lo; k2 <= k2hi; ++k2) {
            T* row = &wb[k2 * n + j0];
            const long clo = std::max<long>(0, l - k2);
            const long chi = std::min<long>(l, l2 + l - k2);
            for (long c = clo; c < chi; ++c)
              row[c] -= ct * y[k2 - l + c] * Traits<T>::conj(v[c]);
          }
        }
        // eliminate first column of B (strided but only l2 elements)
        for (long r = 0; r < l2; ++r) xcol[r] = wb[(l + r) * n + j0];
        T tau2;
        double beta2;
        larfg<T>(l2, xcol, v2.data(), &tau2, &beta2);
        wb[l * n + j0] = T(beta2);
        for (long r = 1; r < l2; ++r) wb[(l + r) * n + j0] = T(0);
        // left-apply H2 to remaining columns: B -= tau2 v2 (v2^H B)
        if (Traits<T>::abs(tau2) != 0.0 && l > 1) {
          for (long c = 0; c < l; ++c) acc[c] = T(0);
          for (long k2 = k2lo; k2 <= k2hi; ++k2) {
            const T* row = &wb[k2 * n + j0];
            const long clo = std::max<long>(1, l - k2);
            const long chi = std::min<long>(l, l2 + l - k2);
            for (long c = clo; c < chi; ++c)
              acc[c] += Traits<T>::conj(v2[k2 - l + c]) * row[c];
          }
          for (long k2 = k2lo; k2 <= k2hi; ++k2) {
            T* row = &wb[k2 * n + j0];
            const long clo = std::max<long>(1, l - k2);
            const long chi = std::min<long>(l, l2 + l - k2);
            for (long c = clo; c < chi; ++c)
              row[c] -= tau2 * v2[k2 - l + c] * acc[c];
          }
        }
        done(t);
        ++t;
        wait(t);
        T* vr2 = &v_out[(s * n_steps + t) * b];
        for (long r = 0; r < l2; ++r) vr2[r] = v2[r];
        tau_out[s * n_steps + t] = tau2;
        j0 += l;
        l = l2;
        std::memcpy(v.data(), v2.data(), sizeof(T) * l2);
        tau = tau2;
    }
    done(t);
  }

  void extract(double* d_out, T* e_out) {
    for (long j = 0; j < n; ++j) d_out[j] = Traits<T>::real(wb[0 * n + j]);
    for (long j = 0; j + 1 < n; ++j) e_out[j] = wb[1 * n + j];
  }

  void run(T* v_out, T* tau_out, long n_steps, double* d_out, T* e_out,
           long nthreads) {
    // n-2 sweeps like the numpy reference; complex off-diagonal phases are
    // normalized by the caller (python side), not by an extra sweep.
    const long n_sweeps = n - 2;
    const long max_par = std::max<long>(1, (n / std::max<long>(1, b)) / 2);
    long T_ = std::max<long>(1, std::min(nthreads, max_par));
    // pipelined sweeps (the reference's SweepWorker pipeline,
    // band_to_tridiag/mc.h:362-380, as a wavefront over worker threads):
    // progress[s] = completed chase steps of sweep s; sweep s may run step
    // t once sweep s-1 has completed step t+1. Spin-waits are coarse
    // (each step is O(b^2) flops). T_ == 1 runs the SAME worker body
    // inline: a single do_sweep instantiation for every thread count keeps
    // results bitwise identical (separate template instantiations may get
    // different FMA contraction).
    std::vector<std::atomic<long>> progress(std::max<long>(n_sweeps, 1));
    for (auto& p : progress) p.store(0, std::memory_order_relaxed);
    const long FIN = 1L << 60;
    auto worker = [&](long w) {
      Scratch<T> sc(b);
      for (long s = w; s < n_sweeps; s += T_) {
        auto wait = [&](long t) {
          if (s == 0) return;
          while (progress[s - 1].load(std::memory_order_acquire) < t + 2)
            std::this_thread::yield();
        };
        auto done = [&](long t) {
          progress[s].store(t + 1, std::memory_order_release);
        };
        do_sweep(s, n_steps, v_out, tau_out, sc, wait, done);
        progress[s].store(FIN, std::memory_order_release);
      }
    };
    if (T_ <= 1 || n_sweeps <= 1) {
      worker(0);
    } else {
      std::vector<std::thread> pool;
      pool.reserve(T_);
      for (long w = 0; w < T_; ++w) pool.emplace_back(worker, w);
      for (auto& th : pool) th.join();
    }
    extract(d_out, e_out);
  }
};

}  // namespace

extern "C" {

// band: (b+1) x n row-major; v_out: n_sweeps*n_steps*b; tau_out:
// n_sweeps*n_steps; d_out: n; e_out: n-1 (raw, complex for _z).
// nthreads: sweep-pipeline worker count; <= 1 runs the sequential path.
int dlaf_band_to_tridiag_d(const double* band, long n, long b, long n_steps,
                           double* v_out, double* tau_out, double* d_out,
                           double* e_out, long nthreads) {
  if (n <= 0 || b <= 0) return 1;
  BandChase<double> chase(band, n, b);
  chase.run(v_out, tau_out, n_steps, d_out, e_out, nthreads);
  return 0;
}

int dlaf_band_to_tridiag_z(const void* band, long n, long b, long n_steps,
                           void* v_out, void* tau_out, double* d_out,
                           void* e_out, long nthreads) {
  if (n <= 0 || b <= 0) return 1;
  using C = std::complex<double>;
  BandChase<C> chase(reinterpret_cast<const C*>(band), n, b);
  chase.run(reinterpret_cast<C*>(v_out), reinterpret_cast<C*>(tau_out),
            n_steps, d_out, reinterpret_cast<C*>(e_out), nthreads);
  return 0;
}

}  // extern "C"
