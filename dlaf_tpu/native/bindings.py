"""ctypes bindings for the native (C++) host kernels.

The reference's native layer is its entire C++ codebase; here the native
surface is the host-side stages that XLA cannot own: currently the
bulge-chasing band->tridiag kernel (``band_to_tridiag.cpp``). The library is
compiled on first use with g++ (no pybind11 in the image — plain C ABI via
ctypes); failures fall back to the numpy implementation transparently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..types import ceil_div

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_HERE, "band_to_tridiag.cpp"),
         os.path.join(_HERE, "secular.cpp"),
         os.path.join(_HERE, "deflate.cpp")]


def _cpu_tag() -> str:
    """Short tag identifying this host's ISA so a -march=native artifact is
    never loaded on a CPU it wasn't built for (package dirs can live on
    shared filesystems spanning heterogeneous nodes)."""
    import hashlib
    import platform

    ident = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    ident += line
                    break
    except OSError:
        ident += platform.processor()
    return hashlib.sha1(ident.encode()).hexdigest()[:10]


_LIB = os.path.join(_HERE, f"libdlaf_native-{_cpu_tag()}.so")
_lock = threading.Lock()
_lib = None
_load_error: Exception | None = None

#: Test hook (health.inject.force_native_failure): when True, get_lib()
#: fails as if the compiler/loader had — exercising the cached-error
#: re-raise path and every native -> numpy degradation chain without
#: breaking a real toolchain.
_FORCE_BUILD_FAILURE = False


def _reset_for_tests(force_failure: bool = False) -> None:
    """Drop the cached library/error and (un)arm the forced-failure hook,
    so injection contexts neither see a pre-loaded library nor leak the
    injected failure into later callers."""
    global _lib, _load_error, _FORCE_BUILD_FAILURE
    with _lock:
        _lib = None
        _load_error = None
        _FORCE_BUILD_FAILURE = bool(force_failure)


def _build() -> str:
    # -march=native vectorizes the diagonal-major chase streams ~1.5x over
    # baseline -O3 (safe: the .so is built on first use per machine, never
    # committed); retried without the flag for toolchains that reject it
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", *_SRCS,
            "-o", _LIB, "-lpthread"]
    try:
        subprocess.run(base[:1] + ["-march=native"] + base[1:],
                       check=True, capture_output=True)
    except subprocess.CalledProcessError:
        subprocess.run(base, check=True, capture_output=True)
    return _LIB


def get_lib():
    """Load (building if stale) the native library. A failed build/load is
    cached and re-raised immediately so callers with numpy fallbacks don't
    respawn the compiler on every call."""
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            raise _load_error
        try:
            if _FORCE_BUILD_FAILURE:
                raise RuntimeError(
                    "forced build failure (health.inject test hook)")
            if (not os.path.exists(_LIB)
                    or any(os.path.getmtime(_LIB) < os.path.getmtime(s)
                           for s in _SRCS)):
                _build()
            lib = ctypes.CDLL(_LIB)
            for name in ("dlaf_band_to_tridiag_d", "dlaf_band_to_tridiag_z",
                         "dlaf_secular_roots_d", "dlaf_secular_roots_d_nt"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int
            lib.dlaf_deflate_scan_d.restype = ctypes.c_int64
        except Exception as e:
            _load_error = e
            from ..obs import get_logger

            # error level: an order-of-magnitude perf cliff must stay
            # visible even under DLAF_LOG=error deployments
            get_logger("native").error(
                f"build/load failed ({e!r}); numpy fallbacks in effect")
            raise
        _lib = lib
        return lib


def secular_roots(ds: np.ndarray, zs: np.ndarray, rho: float,
                  nthreads: int | None = None):
    """Native counterpart of the host secular solver (safeguarded-Newton
    laed4 analog, ``secular.cpp``): returns ``(anchor, mu)`` with the same
    contract as ``tridiag_solver._secular_roots``.

    ``nthreads``: None or <= 0 = auto (hardware concurrency, bounded by
    roots per worker); >= 1 forces the worker count. Any count yields
    bitwise identical results — each root is independent."""
    ds = np.ascontiguousarray(ds, dtype=np.float64)
    zs = np.ascontiguousarray(zs, dtype=np.float64)
    k = ds.shape[0]
    anchor = np.zeros(k, dtype=np.int64)
    mu = np.zeros(k, dtype=np.float64)
    if k == 0:
        return anchor, mu
    lib = get_lib()
    rc = lib.dlaf_secular_roots_d_nt(
        ds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        zs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_double(float(rho)), ctypes.c_long(k),
        anchor.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        mu.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_long(nthreads if nthreads is not None and nthreads > 0
                      else 0))
    if rc != 0:
        raise RuntimeError(f"native secular_roots failed rc={rc}")
    return anchor, mu


def deflate_scan(ds: np.ndarray, zs: np.ndarray, live: np.ndarray,
                 tol: float):
    """Native near-equal-pole deflation scan (``deflate.cpp``; reference
    ``merge.h:443-508``). Mutates ``zs``/``live`` in place (both must be
    contiguous arrays owned by the caller) and returns the applied Givens
    rotations as arrays ``(i, j, c, s)`` in application order."""
    n = ds.shape[0]
    if n == 0:
        return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                np.zeros(0), np.zeros(0))
    assert zs.flags.c_contiguous and live.flags.c_contiguous
    lib = get_lib()
    gi = np.zeros(n, dtype=np.int64)
    gj = np.zeros(n, dtype=np.int64)
    gc = np.zeros(n, dtype=np.float64)
    gs = np.zeros(n, dtype=np.float64)
    g = lib.dlaf_deflate_scan_d(
        np.ascontiguousarray(ds, dtype=np.float64).ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)),
        zs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        live.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(n), ctypes.c_double(tol),
        gi.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        gj.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        gc.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        gs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if g < 0:
        raise RuntimeError(f"native deflate_scan failed rc={g}")
    return gi[:g], gj[:g], gc[:g], gs[:g]


def _chase_threads() -> int:
    """Worker count for the pipelined sweep chase: the config knob
    ``chase_threads`` (0 = auto = CPU count; 1 = sequential). Results are
    bitwise identical at any count (disjoint pipelined windows)."""
    from ..config import get_configuration

    t = get_configuration().chase_threads
    if t <= 0:
        # affinity-aware (cgroup/taskset-limited) count: oversubscribed
        # spin-yield workers would thrash, not idle
        try:
            t = len(os.sched_getaffinity(0))
        except AttributeError:  # non-Linux
            t = os.cpu_count() or 1
    return t


def band_to_tridiag(band: np.ndarray, b: int, nthreads: int | None = None):
    """Native chase; same result contract as
    :func:`dlaf_tpu.eigensolver.band_to_tridiag.band_to_tridiag_numpy`.

    ``nthreads``: None or <= 0 means the config/auto policy (same as
    ``chase_threads = 0``); 1 sequential; > 1 pipelined workers."""
    from ..eigensolver.band_to_tridiag import TridiagResult

    n = band.shape[1]
    cplx = np.issubdtype(band.dtype, np.complexfloating)
    work_dtype = np.complex128 if cplx else np.float64
    band_w = np.ascontiguousarray(band, dtype=work_dtype)
    n_sweeps = max(n - 2, 0)
    n_steps = ceil_div(max(n - 1, 1), b) if n > 1 else 0
    v = np.zeros((n_sweeps, max(n_steps, 1), b), dtype=work_dtype)
    tau = np.zeros((n_sweeps, max(n_steps, 1)), dtype=work_dtype)
    d = np.zeros(n, dtype=np.float64)
    e_raw = np.zeros(max(n - 1, 0), dtype=work_dtype)
    if n_sweeps > 0 or n > 0:
        lib = get_lib()
        fn = lib.dlaf_band_to_tridiag_z if cplx else lib.dlaf_band_to_tridiag_d
        rc = fn(band_w.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_long(n), ctypes.c_long(b), ctypes.c_long(max(n_steps, 1)),
                v.ctypes.data_as(ctypes.c_void_p),
                tau.ctypes.data_as(ctypes.c_void_p),
                d.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                e_raw.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_long(nthreads if nthreads is not None and nthreads > 0
                              else _chase_threads()))
        if rc != 0:
            raise RuntimeError(f"native band_to_tridiag failed rc={rc}")
    phase = np.ones(n, dtype=work_dtype)
    if cplx:
        e = np.zeros(max(n - 1, 0), dtype=np.float64)
        for j in range(n - 1):
            mag = np.abs(e_raw[j])
            ph = e_raw[j] / mag if mag > 0 else 1.0
            phase[j + 1] = phase[j] * ph
            e[j] = mag
    else:
        e = np.real(e_raw)
    return TridiagResult(d=d, e=e, v=v[:, :n_steps if n_steps else 0],
                         tau=tau[:, :n_steps if n_steps else 0],
                         phase=phase, band=b)
