"""Panel workspaces: tile-row/column exchange over the mesh.

TPU-native counterpart of the reference's ``Panel`` workspace
(``matrix/panel.h:35-485``) and ``broadcast_panel`` (``broadcast_panel.h:
53-193``). The reference materializes per-rank panel workspaces whose tiles
either alias matrix tiles (external link) or are freshly allocated, then
broadcasts them along the orthogonal communicator; transposed panels get a
second broadcast. In the SPMD/shard_map world a panel is just a value: these
helpers produce, inside a traced step, the per-rank slice of a global tile
row/column (aliasing is free — values are immutable), with the broadcast
collapsing to one mask+psum along a mesh axis and the transposed-panel
exchange to an all_gather + static-index select.

All functions are called INSIDE shard_map with the conventions of
:mod:`dlaf_tpu.algorithms` (storage (ltr, ltc, mb, nb) local blocks, trace-
time static ``k``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from . import util_distribution as ud


def uniform_slot_start(k: int, p: int) -> int:
    """Uniform local slot covering every rank's tiles >= global tile ``k``
    on a ``p``-rank axis (equals ``floor(k / p)``; off by at most one slot
    from the per-rank optimum). Single owner of this bound — used by the
    per-``k`` panel ranges AND the telescoped-scan segment slicing."""
    return max(0, -(-(k + 1 - p) // p))


class DistContext:
    """Trace-time constants + traced rank coordinates for one distribution.

    Bundles what every distributed algorithm needs: grid extents, source
    ranks, per-axis cycle positions (traced), and global-index vectors for
    local tile slots.
    """

    def __init__(self, dist):
        self.dist = dist
        self.nt = dist.nr_tiles
        self.mb = dist.block_size.row
        self.nb = dist.block_size.col
        self.P = dist.grid_size.row
        self.Q = dist.grid_size.col
        self.sr = dist.source_rank.row
        self.sc = dist.source_rank.col
        from .tiling import storage_tile_grid

        _, _, self.ltr, self.ltc = storage_tile_grid(dist)
        # traced per-rank values
        self.rank_r = cc.this_rank(ROW_AXIS)
        self.rank_c = cc.this_rank(COL_AXIS)
        self.rr = (self.rank_r - self.sr) % self.P  # cycle position (rows)
        self.rc = (self.rank_c - self.sc) % self.Q

    # trace-time owner/local-index math (static k)
    def owner_r(self, k: int) -> int:
        return ud.rank_global_tile(k, self.P, self.sr)

    def owner_c(self, k: int) -> int:
        return ud.rank_global_tile(k, self.Q, self.sc)

    def kr(self, k: int) -> int:
        return ud.local_tile_from_global_tile(k, self.P)

    def kc(self, k: int) -> int:
        return ud.local_tile_from_global_tile(k, self.Q)

    def row_start(self, k: int) -> int:
        """Uniform local row slot covering every rank's tiles >= k (off by at
        most one slot from the per-rank optimum; see cholesky design note)."""
        return uniform_slot_start(k, self.P)

    def col_start(self, k: int) -> int:
        return uniform_slot_start(k, self.Q)

    def g_rows(self, lu: int, count: int):
        """Traced global tile rows of local slots lu..lu+count-1."""
        return (lu + jnp.arange(count)) * self.P + self.rr

    def g_cols(self, lu: int, count: int):
        return (lu + jnp.arange(count)) * self.Q + self.rc

    def tile_size_r(self, k: int, n_rows: int) -> int:
        return min(self.mb, n_rows - k * self.mb)


def bcast_diag(ctx: DistContext, lt, k: int):
    """The (k,k) tile to every rank: one fused 2D mask+psum
    (:func:`dlaf_tpu.comm.collectives.bcast2d` — one collective on the
    step critical path instead of the previous two hops; reference:
    diag-tile column broadcast, ``cholesky/impl.h:215-219``)."""
    cand = lt[ctx.kr(k), ctx.kc(k)]
    return cc.bcast2d(cand, ctx.owner_r(k), ctx.owner_c(k))


def pad_diag_identity(tile, real_size: int):
    """Replace the zero-padded trailing block of a short edge diagonal tile
    with the identity, keeping factorizations/solves nonsingular. No-op when
    the tile is full (trace-time check)."""
    mb = tile.shape[-1]
    if real_size >= mb:
        return tile
    pad = jnp.arange(mb) >= real_size
    cleared = jnp.where(pad[:, None] | pad[None, :], 0, tile)
    return cleared + jnp.diag(pad.astype(tile.dtype))


def bcast_diag_dyn(ctx: DistContext, lt, k):
    """:func:`bcast_diag` for a TRACED ``k`` (scan-mode steps): the pivot
    slot is a dynamic slice, the owner ranks traced arithmetic."""
    mb, nb = lt.shape[-2], lt.shape[-1]
    cand = jax.lax.dynamic_slice(
        lt, (ctx.kr(k), ctx.kc(k), 0, 0), (1, 1, mb, nb))[0, 0]
    return cc.bcast2d(cand, ctx.owner_r(k), ctx.owner_c(k))


def gather_sub_panel_dyn(ctx: DistContext, lt, *, p, b: int, n: int,
                         row_off: int = 0, col_off: int = 0):
    """:func:`gather_sub_panel` for a TRACED panel index ``p`` (scan-mode
    steps), uniform shapes: the full-height masked panel column is
    gathered in static global order and top-aligned with a traced roll —
    zero rows below a Householder panel do not perturb its reflectors, so
    ``geqrf``/reflector application on the rolled (nt_w*mb, b) column
    equals the shrunken panel's, zero-padded. Returns
    ``(pan, bdy, tc, co, row_val_e, g_rows, raw)`` with ``row_val_e``/
    ``g_rows`` over the window's local row slots and ``raw`` the unmasked
    local slice of the panel column (for write-back).

    ``row_off``/``col_off``: static slot offsets when ``lt`` is a
    telescoped window ``full[row_off:, col_off:]`` — the gather covers
    global tile rows ``[row_off*P, nt)`` and the roll is relative to the
    window's first element row (``bdy - row_off*P*mb``)."""
    nb = ctx.mb
    nt = ctx.nt.row
    base = row_off * ctx.P          # first global tile row of the window
    bdy = (p + 1) * b
    tc = (p * b) // nb
    co = (p * b) % nb
    g_rows = ctx.g_rows(row_off, ctx.ltr - row_off)
    g_erows = g_rows[:, None] * nb + jnp.arange(nb)[None, :]
    row_val_e = (g_erows >= bdy) & (g_erows < n)
    raw = jax.lax.dynamic_slice(
        lt, (0, ctx.kc(tc) - col_off, 0, co),
        (ctx.ltr - row_off, 1, nb, b))[:, 0]
    mine = jnp.where(row_val_e[:, :, None], raw, jnp.zeros_like(raw))
    mine = cc.bcast(mine, COL_AXIS, ctx.owner_c(tc))
    ptiles = gather_col_panel_ordered(ctx, mine, base, row_off)
    pan = jnp.roll(ptiles.reshape((nt - base) * nb, b),
                   -(bdy - base * nb), axis=0)
    return pan, bdy, tc, co, row_val_e, g_rows, raw


def tiles_of_rolled(ctx: DistContext, mat, bdy, base_el: int = 0):
    """Roll a top-aligned sub-panel quantity back to matrix row space and
    cut into (rows/mb, mb, b) tiles (scan-mode counterpart of
    :func:`pad_sub_panel_to_tiles`). ``base_el``: first element row of the
    telescoped window the quantity lives in (0 = whole matrix)."""
    return jnp.roll(mat, bdy - base_el, axis=0).reshape(
        mat.shape[0] // ctx.mb, ctx.mb, mat.shape[1])


def pad_diag_identity_dyn(tile, real_size):
    """:func:`pad_diag_identity` for a TRACED ``real_size`` (no trace-time
    no-op shortcut; full tiles produce an all-False pad mask)."""
    mb = tile.shape[-1]
    pad = jnp.arange(mb) >= real_size
    cleared = jnp.where(pad[:, None] | pad[None, :], 0, tile)
    return cleared + jnp.diag(pad.astype(tile.dtype))


def col_panel_dyn(ctx: DistContext, lt, k, *, col_off: int = 0,
                  lu: int = 0, count: int | None = None):
    """:func:`col_panel` for a TRACED ``k``. Row slots restricted to the
    static window ``[lu, lu+count)`` (telescoped-scan segments slice the
    live trailing region; default = all slots). ``col_off``: slot offset
    of ``lt``'s column axis when the caller passes a column-sliced window
    (the pivot column index becomes ``kc(k) - col_off``)."""
    mb, nb = lt.shape[-2], lt.shape[-1]
    cnt = lt.shape[0] - lu if count is None else count
    mine = jax.lax.dynamic_slice(
        lt, (lu, ctx.kc(k) - col_off, 0, 0), (cnt, 1, mb, nb))[:, 0]
    return cc.bcast(mine, COL_AXIS, ctx.owner_c(k))


def row_panel_dyn(ctx: DistContext, lt, k, *, row_off: int = 0,
                  lu: int = 0, count: int | None = None):
    """:func:`row_panel` for a TRACED ``k``. Col slots restricted to the
    static window ``[lu, lu+count)``; ``row_off``: slot offset of ``lt``'s
    row axis when the caller passes a row-sliced window."""
    mb, nb = lt.shape[-2], lt.shape[-1]
    cnt = lt.shape[1] - lu if count is None else count
    mine = jax.lax.dynamic_slice(
        lt, (ctx.kr(k) - row_off, lu, 0, 0), (1, cnt, mb, nb))[0]
    return cc.bcast(mine, ROW_AXIS, ctx.owner_r(k))


def col_panel(ctx: DistContext, lt, k: int, lu: int):
    """Local-row tiles of global tile column ``k`` (rows from slot ``lu``),
    delivered to every rank of each grid row (reference: panel col->row
    broadcast). Returns (tiles (ltr-lu, mb, nb), valid-row mask source)."""
    mine = lt[lu:, ctx.kc(k)]
    return cc.bcast(mine, COL_AXIS, ctx.owner_c(k))


def row_panel(ctx: DistContext, lt, k: int, lu: int):
    """Local-col tiles of global tile row ``k`` (cols from slot ``lu``),
    delivered to every rank of each grid column."""
    mine = lt[ctx.kr(k), lu:]
    return cc.bcast(mine, ROW_AXIS, ctx.owner_r(k))


def gather_col_panel_ordered(ctx: DistContext, col_tiles, k1: int, lu: int):
    """Every panel tile (global tile rows ``k1..nt_row-1``, in global order)
    on every rank: all_gather the per-rank row slices along the row axis and
    reorder the block-cyclic slots statically.

    ``col_tiles``: my local row tiles of the panel column (already
    :func:`col_panel`-broadcast), slots ``lu..`` covering rows >= ``k1``.
    Shared by the forward reduction_to_band and its back-transform.
    """
    nt = ctx.nt.row
    nrows = col_tiles.shape[0]
    full = cc.all_gather(col_tiles, ROW_AXIS)            # (P, nrows, mb, nb)
    full = full.reshape(ctx.P * nrows, *col_tiles.shape[1:])
    order = []
    for g in range(k1, nt):
        p = (ctx.sr + g) % ctx.P
        order.append(p * nrows + (g // ctx.P - lu))
    return full[jnp.array(order, dtype=jnp.int32)]       # (nt-k1, mb, nb)


def gather_sub_panel(ctx: DistContext, lt, *, pb: int, b: int, n: int):
    """Gather the width-``b`` reflector sub-panel at element columns
    [pb, pb+b) acting below boundary row pb+b, replicated on every rank.

    Shared by the generalized (band <= block size) distributed
    reduction_to_band and bt_reduction_to_band: slices the panel's tile
    column at its static in-tile offset, masks the above-boundary rows
    elementwise, broadcasts along the column axis, gathers tile rows in
    global order, and returns

    ``(vfull, lu, tr0, ro, row_val_e, g_rows)`` where ``vfull`` is the
    (m_full - ro, b) packed panel starting AT the boundary row (R in its
    top b rows after factorization, reflectors below), ``lu``/``tr0``/``ro``
    locate it in tile space, and ``row_val_e``/``g_rows`` are the caller's
    element-level row masks for its local slots.
    """
    from ..common.index2d import GlobalElementIndex
    from .views import SubMatrixView, SubPanelView

    nb = ctx.mb
    nt = ctx.nt.row
    bdy = pb + b
    # static offset bookkeeping via the view types (reference
    # SubPanelView/SubMatrixView, matrix/views.h:85,129): the panel's tile
    # column + in-tile column offset, and the below-boundary sub-matrix's
    # first tile row + in-tile row offset
    pan = SubPanelView(ctx.dist, GlobalElementIndex(pb, pb), width=b)
    body = SubMatrixView(ctx.dist, GlobalElementIndex(bdy, pb))
    tc = pan.begin_tile.col
    co = pan.origin_in_tile.col
    tr0 = body.begin_tile.row
    ro = body.origin_in_tile.row
    lu = ctx.row_start(tr0)
    nrows = ctx.ltr - lu
    if nrows <= 0:
        return None
    g_rows = ctx.g_rows(lu, nrows)
    g_erows = g_rows[:, None] * nb + jnp.arange(nb)[None, :]
    row_val_e = (g_erows >= bdy) & (g_erows < n)
    mine = lt[lu:, ctx.kc(tc), :, co:co + b]
    mine = jnp.where(row_val_e[:, :, None], mine, jnp.zeros_like(mine))
    mine = cc.bcast(mine, COL_AXIS, ctx.owner_c(tc))
    ptiles = gather_col_panel_ordered(ctx, mine, tr0, lu)
    vfull = ptiles.reshape((nt - tr0) * nb, b)[ro:]
    return vfull, lu, tr0, ro, row_val_e, g_rows


def pad_sub_panel_to_tiles(ctx: DistContext, mat, *, tr0: int, ro: int):
    """Align an (m_full - ro, b) sub-panel row space to tile rows: zero-pad
    the ``ro`` above-boundary rows (masked out everywhere by the callers'
    element masks) and cut into (nt - tr0, mb, b) tiles."""
    b = mat.shape[1]
    return jnp.concatenate(
        [jnp.zeros((ro, b), dtype=mat.dtype), mat]).reshape(
            ctx.nt.row - tr0, ctx.mb, b)


def transpose_col_to_rows(ctx: DistContext, col_tiles, lu_r: int, g_cols):
    """Transposed-panel exchange (reference ``panelT`` + transposed
    ``broadcast_panel``, ``broadcast_panel.h:101-193``): given each rank's
    row-slice of a tile *column* (slots >= lu_r, already col_panel-broadcast),
    return for each of my local *column* slots the panel tile of that global
    index — i.e. the panel seen transposed.

    ``g_cols``: traced global tile indices (my local column slots).
    """
    nrows = col_tiles.shape[0]
    full = cc.all_gather(col_tiles, ROW_AXIS)            # (P, nrows, mb, nb)
    full = full.reshape(ctx.P * nrows, *col_tiles.shape[1:])
    pj = (ctx.sr + g_cols) % ctx.P
    lj = g_cols // ctx.P
    flat = pj * nrows + jnp.clip(lj - lu_r, 0, max(nrows - 1, 0))
    return full[flat]


def transpose_row_to_cols(ctx: DistContext, row_tiles, lu_c: int, g_rows):
    """Mirror of :func:`transpose_col_to_rows` for a tile *row* panel."""
    ncols = row_tiles.shape[0]
    full = cc.all_gather(row_tiles, COL_AXIS)            # (Q, ncols, mb, nb)
    full = full.reshape(ctx.Q * ncols, *row_tiles.shape[1:])
    pj = (ctx.sc + g_rows) % ctx.Q
    lj = g_rows // ctx.Q
    flat = pj * ncols + jnp.clip(lj - lu_c, 0, max(ncols - 1, 0))
    return full[flat]
