"""L3 matrix model — public API (reference ``matrix/``: Matrix,
Distribution, LayoutInfo, Panel, views, mirror, copy, print)."""

from .distribution import Distribution
from .matrix import Matrix

__all__ = ["Distribution", "Matrix"]
