"""2D block-cyclic distribution map.

TPU-native counterpart of the reference's ``matrix/distribution.h:25-386`` (and
its design note ``misc/matrix_distribution.md``): given a global matrix size, a
block size, a process-grid size, this process's grid coordinates, and a
*source rank offset*, answer every index question the algorithms ask —
global-tile ↔ local-tile ↔ owning-rank ↔ tile-element conversions, local
extents, and edge-tile sizes. Pure index math; per-axis work is delegated to
:mod:`.util_distribution`.

On TPU the "process grid" is the 2D device mesh (``comm.grid.Grid``); each mesh
coordinate plays the role of an MPI rank in the reference.
"""

from __future__ import annotations

import dataclasses

from ..common.asserts import dlaf_assert
from ..common.index2d import (GlobalElementIndex, GlobalElementSize, GlobalTileIndex,
                              GlobalTileSize, GridSize2D, LocalElementSize, LocalTileIndex,
                              LocalTileSize, RankIndex2D, TileElementIndex, TileElementSize)
from ..types import SizeType, ceil_div
from . import util_distribution as ud


@dataclasses.dataclass(frozen=True)
class Distribution:
    """Block-cyclic 2D distribution (reference ``matrix/distribution.h:25``)."""

    size: GlobalElementSize
    block_size: TileElementSize
    grid_size: GridSize2D = GridSize2D(1, 1)
    rank: RankIndex2D = RankIndex2D(0, 0)
    source_rank: RankIndex2D = RankIndex2D(0, 0)

    def __post_init__(self):
        dlaf_assert(self.size.is_valid(), f"invalid size {self.size}")
        dlaf_assert(self.block_size.row > 0 and self.block_size.col > 0,
                    f"invalid block size {self.block_size}")
        dlaf_assert(self.grid_size.row > 0 and self.grid_size.col > 0,
                    f"invalid grid {self.grid_size}")
        dlaf_assert(self.rank.is_in(self.grid_size), f"rank {self.rank} not in {self.grid_size}")
        dlaf_assert(self.source_rank.is_in(self.grid_size),
                    f"source rank {self.source_rank} not in {self.grid_size}")

    # -- global extents -----------------------------------------------------

    @property
    def nr_tiles(self) -> GlobalTileSize:
        """Global tile-grid extents (reference ``distribution.h:nrTiles``)."""
        return GlobalTileSize(ceil_div(self.size.row, self.block_size.row) if self.size.row else 0,
                              ceil_div(self.size.col, self.block_size.col) if self.size.col else 0)

    # -- local extents ------------------------------------------------------

    @property
    def local_nr_tiles(self) -> LocalTileSize:
        nt = self.nr_tiles
        return LocalTileSize(
            ud.local_nr_tiles(nt.row, self.grid_size.row, self.rank.row, self.source_rank.row),
            ud.local_nr_tiles(nt.col, self.grid_size.col, self.rank.col, self.source_rank.col))

    @property
    def local_size(self) -> LocalElementSize:
        return LocalElementSize(
            ud.local_size(self.size.row, self.block_size.row, self.grid_size.row,
                          self.rank.row, self.source_rank.row),
            ud.local_size(self.size.col, self.block_size.col, self.grid_size.col,
                          self.rank.col, self.source_rank.col))

    # -- ownership ----------------------------------------------------------

    def rank_global_tile(self, index: GlobalTileIndex) -> RankIndex2D:
        """Rank owning a global tile (reference ``distribution.h:rankGlobalTile``)."""
        dlaf_assert(index.is_in(self.nr_tiles), f"{index} not in {self.nr_tiles}")
        return RankIndex2D(
            ud.rank_global_tile(index.row, self.grid_size.row, self.source_rank.row),
            ud.rank_global_tile(index.col, self.grid_size.col, self.source_rank.col))

    def rank_global_element(self, index: GlobalElementIndex) -> RankIndex2D:
        return self.rank_global_tile(self.global_tile_index(index))

    # -- tile index conversions --------------------------------------------

    def local_tile_index(self, index: GlobalTileIndex) -> LocalTileIndex:
        """Local tile index of a tile owned by this rank
        (reference ``distribution.h:localTileIndex``)."""
        dlaf_assert(self.rank_global_tile(index) == self.rank,
                    f"tile {index} not owned by rank {self.rank}")
        return LocalTileIndex(ud.local_tile_from_global_tile(index.row, self.grid_size.row),
                              ud.local_tile_from_global_tile(index.col, self.grid_size.col))

    def global_tile_index(self, index) -> GlobalTileIndex:
        """From a GlobalElementIndex or LocalTileIndex
        (reference ``distribution.h:globalTileIndex`` overloads)."""
        if isinstance(index, GlobalElementIndex):
            return GlobalTileIndex(
                ud.tile_from_element(index.row, self.block_size.row),
                ud.tile_from_element(index.col, self.block_size.col))
        dlaf_assert(isinstance(index, LocalTileIndex), f"bad index type {type(index)}")
        return GlobalTileIndex(
            ud.global_tile_from_local_tile(index.row, self.grid_size.row,
                                           self.rank.row, self.source_rank.row),
            ud.global_tile_from_local_tile(index.col, self.grid_size.col,
                                           self.rank.col, self.source_rank.col))

    def next_local_tile_from_global_tile(self, row: SizeType, col: SizeType) -> LocalTileIndex:
        """Per-axis smallest local tile >= the given global tile indices
        (reference ``distribution.h:nextLocalTileFromGlobalTile``)."""
        return LocalTileIndex(
            ud.next_local_tile_from_global_tile(row, self.grid_size.row,
                                                self.rank.row, self.source_rank.row),
            ud.next_local_tile_from_global_tile(col, self.grid_size.col,
                                                self.rank.col, self.source_rank.col))

    # -- element conversions ------------------------------------------------

    def tile_element_index(self, index: GlobalElementIndex) -> TileElementIndex:
        return TileElementIndex(
            ud.tile_element_from_element(index.row, self.block_size.row),
            ud.tile_element_from_element(index.col, self.block_size.col))

    def global_element_index(self, tile: GlobalTileIndex,
                             el: TileElementIndex) -> GlobalElementIndex:
        return GlobalElementIndex(
            ud.element_from_tile_and_tile_element(tile.row, el.row, self.block_size.row),
            ud.element_from_tile_and_tile_element(tile.col, el.col, self.block_size.col))

    # -- tile sizes ----------------------------------------------------------

    def tile_size_of(self, index: GlobalTileIndex) -> TileElementSize:
        """Actual extents of a global tile; edge tiles may be short
        (reference ``distribution.h:tileSize``)."""
        return TileElementSize(
            ud.tile_size_of(index.row, self.size.row, self.block_size.row),
            ud.tile_size_of(index.col, self.size.col, self.block_size.col))

    def local_tile_linear_index(self, index: LocalTileIndex) -> SizeType:
        """Col-major linearization over local tiles (reference ``MatrixBase``)."""
        lnt = self.local_nr_tiles
        dlaf_assert(index.is_in(lnt), f"{index} not in {lnt}")
        return index.col * lnt.row + index.row

    def single_rank(self) -> bool:
        return self.grid_size == GridSize2D(1, 1)

    def __str__(self) -> str:
        return (f"Distribution(size={self.size}, block={self.block_size}, "
                f"grid={self.grid_size}, rank={self.rank}, src={self.source_rank})")


def assert_slot_aligned(da: "Distribution", db: "Distribution",
                        rows: bool = False, cols: bool = False,
                        what: str = "operands") -> None:
    """Contract check: two distributions' LOCAL TILE SLOTS address the same
    global tiles along the requested axes (same grid extent AND same
    source rank there). The distributed algorithms combine per-slot panels
    of one operand with per-slot tiles of the other (e.g. the solver's
    ``e[slot] @ x`` applied to ``B[slot]``), which is only correct under
    this alignment — a silent mismatch produces numerically wrong results,
    not an error, so callers assert it loudly (round-3 finding: a
    mismatched source rank corrupted a distributed solve with max err
    ~0.26 and no diagnostic)."""
    if rows:
        dlaf_assert(
            da.grid_size.row == db.grid_size.row
            and da.source_rank.row == db.source_rank.row,
            f"{what}: row slots misaligned — grid rows "
            f"{da.grid_size.row}/{db.grid_size.row}, source rows "
            f"{da.source_rank.row}/{db.source_rank.row}; distributed "
            "algorithms require operands aligned on this axis (re-shard "
            "one operand, e.g. Matrix.from_global with the other's "
            "source_rank)")
    if cols:
        dlaf_assert(
            da.grid_size.col == db.grid_size.col
            and da.source_rank.col == db.source_rank.col,
            f"{what}: col slots misaligned — grid cols "
            f"{da.grid_size.col}/{db.grid_size.col}, source cols "
            f"{da.source_rank.col}/{db.source_rank.col}; distributed "
            "algorithms require operands aligned on this axis (re-shard "
            "one operand, e.g. Matrix.from_global with the other's "
            "source_rank)")
