"""Whole-matrix structural ops: transpose, hermitianize, triangle merge, copy.

These cover the reference's ``matrix::copy`` (``matrix/copy.h:29``),
``MatrixMirror`` (``matrix/matrix_mirror.h:31-202``) and the implicit
"other-triangle" handling spread through its algorithms. The TPU-native
expression: run the op on the *global view* inside one jit whose inputs and
outputs carry the block-cyclic tile sharding — GSPMD then inserts the
all-to-all/collective-permute traffic for the storage permutation, instead of
hand-written MPI tile exchanges.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..common.asserts import dlaf_assert
from .matrix import Matrix
from .tiling import (global_to_tiles, tiles_to_global,
                     quiet_donation, donate_argnums_kw)


def _global_op_jit(dist, sharding, fn, donate=False):
    """jit storage->storage running ``fn`` on the global view."""
    def prog(storage):
        g = tiles_to_global(storage, dist)
        return global_to_tiles(fn(g), dist)

    kw = dict(donate_argnums_kw(donate, 0))
    if sharding is not None:
        kw.update(in_shardings=sharding, out_shardings=sharding)
    return jax.jit(prog, **kw)


@functools.lru_cache(maxsize=256)
def _cached_global_op(dist, sharding, name, extra=None, donate=False):
    fns = {
        "transpose": lambda g: jnp.swapaxes(g, 0, 1),
        "conj_transpose": lambda g: jnp.conj(jnp.swapaxes(g, 0, 1)),
        "hermitianize_L": lambda g: _herm(g, "L"),
        "hermitianize_U": lambda g: _herm(g, "U"),
        "tril": lambda g: jnp.tril(g),
        "triu": lambda g: jnp.triu(g),
        "copy": lambda g: g,
    }
    return _global_op_jit(dist, sharding, fns[name], donate)


def _herm(g, uplo):
    tri = jnp.tril(g, -1) if uplo == "L" else jnp.triu(g, 1)
    d = jnp.real(jnp.diagonal(g)) if jnp.iscomplexobj(g) else jnp.diagonal(g)
    return tri + jnp.conj(tri.T) + jnp.diag(d).astype(g.dtype)


def _sharding(mat: Matrix):
    if mat.grid is None or mat.grid.num_devices == 1:
        return None
    return mat.grid.tile_sharding()


def transpose(mat: Matrix, conj: bool = True) -> Matrix:
    """(Conjugate-)transpose; square matrices/blocks keep their distribution."""
    dlaf_assert(mat.size.row == mat.size.col and
                mat.block_size.row == mat.block_size.col,
                "transpose: square matrices only (rectangular lands later)")
    fn = _cached_global_op(mat.dist, _sharding(mat),
                           "conj_transpose" if conj else "transpose")
    return mat.with_storage(fn(mat.storage))


def hermitianize(mat: Matrix, uplo: str, *, donate: bool = False) -> Matrix:
    """Full Hermitian matrix from its stored ``uplo`` triangle
    (the whole-matrix ``hermitian_from``). ``donate=True`` permits
    consuming ``mat``'s storage."""
    fn = _cached_global_op(mat.dist, _sharding(mat), f"hermitianize_{uplo}",
                           donate=donate)
    with quiet_donation():
        return mat.with_storage(fn(mat.storage))


def merge_triangle(new: Matrix, orig: Matrix, uplo: str, *,
                   donate_orig: bool = False) -> Matrix:
    """``uplo`` triangle from ``new``, opposite strict triangle from ``orig``
    (LAPACK in-place update semantics at matrix scope).

    ``new``'s storage is always donated (every caller passes a freshly
    computed intermediate); ``donate_orig=True`` also consumes ``orig``'s
    storage — the final step of an in-place-semantics algorithm entry."""
    fn = _merge_cached(new.dist, _sharding(new), uplo, donate_orig)
    with quiet_donation():
        return new.with_storage(fn(new.storage, orig.storage))


@functools.lru_cache(maxsize=128)
def _merge_cached(dist, sharding, uplo, donate_orig=False):
    def prog(sn, so):
        gn = tiles_to_global(sn, dist)
        go = tiles_to_global(so, dist)
        out = jnp.tril(gn) + jnp.triu(go, 1) if uplo == "L" \
            else jnp.triu(gn) + jnp.tril(go, -1)
        return global_to_tiles(out, dist)

    kw = dict(donate_argnums_kw(True, (0, 1) if donate_orig else (0,)))
    if sharding is not None:
        kw.update(in_shardings=(sharding, sharding), out_shardings=sharding)
    return jax.jit(prog, **kw)


def copy(mat: Matrix) -> Matrix:
    """Fresh storage with identical contents (reference ``matrix::copy``)."""
    return mat.with_storage(mat.storage + 0)


def mirror_to_host(mat: Matrix) -> np.ndarray:
    """Device->host mirror (reference ``MatrixMirror`` D2H side)."""
    return mat.to_numpy()


def mirror_to_device(a: np.ndarray, like: Matrix) -> Matrix:
    """Host->device mirror with ``like``'s layout (MatrixMirror H2D side)."""
    return Matrix.from_global(a, like.block_size, grid=like.grid,
                              source_rank=like.dist.source_rank)
