"""Distributed matrix container.

TPU-native counterpart of the reference's ``Matrix<T, Device>``
(``matrix/matrix.h:56-211``). The reference's Matrix is a pool of per-tile
allocations plus a future-chain dependency engine (``TileFutureManager``,
``misc/synchronization.md``); here a matrix is ONE immutable 4D tile-storage
``jax.Array`` (see :mod:`.tiling`) sharded block-cyclically over the grid's
mesh, plus its :class:`Distribution`. The dependency semantics the reference
implements with RW/RO future chains (``matrix.h:117-197``) map to XLA program
order: algorithms are pure functions ``storage -> storage`` traced per step,
and within a traced program XLA's dataflow *is* the tile DAG — read-after-
write and write-after-read hazards cannot exist on immutable values.

Host-side element access (``set``/``tile``/``to_numpy``) exists for test and
miniapp convenience, mirroring the reference's analytic matrix setters
(``test/include/dlaf_test/matrix/util_matrix.h``).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..comm.grid import Grid
from ..common.asserts import dlaf_assert
from ..common.index2d import (GlobalElementSize, GlobalTileIndex, GridSize2D, RankIndex2D,
                              TileElementSize)
from . import memory
from .distribution import Distribution
from . import tiling


class Matrix:
    """Block-cyclic distributed matrix over a device grid.

    ``storage`` is the 4D cyclic-ordered tile array (possibly sharded over
    ``grid.mesh``); ``dist`` carries the index map. Instances are cheap,
    immutable views — algorithms return new Matrices sharing layout.
    """

    def __init__(self, dist: Distribution, storage, grid: Optional[Grid] = None):
        self.dist = dist
        self.grid = grid
        Sr, Sc, _, _ = tiling.storage_tile_grid(dist)
        expect = (Sr, Sc, dist.block_size.row, dist.block_size.col)
        dlaf_assert(tuple(storage.shape) == expect,
                    f"storage shape {storage.shape} != {expect}")
        self.storage = storage

    # -- constructors -------------------------------------------------------

    @classmethod
    def zeros(cls, size: GlobalElementSize, block_size: TileElementSize,
              grid: Optional[Grid] = None, dtype=np.float64,
              source_rank: RankIndex2D = RankIndex2D(0, 0)) -> "Matrix":
        dist = _make_dist(size, block_size, grid, source_rank)
        Sr, Sc, _, _ = tiling.storage_tile_grid(dist)
        storage = jnp.zeros((Sr, Sc, block_size.row, block_size.col), dtype=dtype)
        return cls(dist, _shard(storage, grid), grid)

    @classmethod
    def from_global(cls, a, block_size: TileElementSize, grid: Optional[Grid] = None,
                    source_rank: RankIndex2D = RankIndex2D(0, 0)) -> "Matrix":
        """Wrap a host/device global array (reference ``Matrix(layout, ptr)``).

        A device-resident (possibly already-sharded) ``jax.Array`` input is
        re-tiled inside ONE compiled program whose output carries the tile
        sharding — the global matrix is never materialized on a single
        device (the handoff path from the mesh-sharded D&C eigenvectors
        into the distributed back-transforms)."""
        a = np.asarray(a) if not isinstance(a, jax.Array) else a
        size = GlobalElementSize(a.shape[0], a.shape[1])
        dist = _make_dist(size, block_size, grid, source_rank)
        if (grid is not None and grid.num_devices > 1
                and isinstance(a, jax.Array)
                # the compiled fast path needs the input on the grid's
                # devices; arrays committed elsewhere (a single device, a
                # different mesh) take the eager re-tile + reshard below
                and set(a.devices()) == set(grid.mesh.devices.flat)):
            return cls(dist, _retile_sharded(dist, grid.tile_sharding())(a),
                       grid)
        storage = tiling.global_to_tiles(a, dist)
        return cls(dist, _shard(storage, grid), grid)

    @classmethod
    def from_element_fn(cls, fn: Callable, size: GlobalElementSize,
                        block_size: TileElementSize, grid: Optional[Grid] = None,
                        dtype=np.float64,
                        source_rank: RankIndex2D = RankIndex2D(0, 0)) -> "Matrix":
        """Build from an analytic element function ``fn(i, j) -> value`` with
        vectorized (broadcasting) ``i``/``j`` — the test-suite setter style of
        the reference (``util_matrix.h:93-212`` ``set``)."""
        i, j = np.meshgrid(np.arange(size.row), np.arange(size.col), indexing="ij")
        a = np.asarray(fn(i, j), dtype=dtype)
        return cls.from_global(a, block_size, grid, source_rank)

    # -- properties ---------------------------------------------------------

    @property
    def size(self) -> GlobalElementSize:
        return self.dist.size

    @property
    def block_size(self) -> TileElementSize:
        return self.dist.block_size

    @property
    def nr_tiles(self):
        return self.dist.nr_tiles

    @property
    def dtype(self):
        return self.storage.dtype

    # -- host access (tests / debugging) ------------------------------------

    def to_numpy(self) -> np.ndarray:
        """Gather the global matrix to host via the blocking ``sync`` comm
        tier (reference test helper ``matrix_local.h`` gather)."""
        from ..comm import sync as comm_sync

        return comm_sync.gather(self)

    def tile(self, index: GlobalTileIndex) -> np.ndarray:
        """Read one global tile (its actual, possibly short, extent)."""
        r, c = tiling.global_tile_to_storage_index(self.dist, index.row, index.col)
        ts = self.dist.tile_size_of(index)
        t = memory.fetch(self.storage[r, c])
        return np.asarray(t[: ts.row, : ts.col])

    def with_storage(self, storage) -> "Matrix":
        """New Matrix sharing this layout (the functional 'write')."""
        return Matrix(self.dist, storage, self.grid)

    def __str__(self) -> str:
        g = f", grid={self.grid}" if self.grid else ""
        return f"Matrix(size={self.size}, block={self.block_size}, dtype={self.dtype}{g})"


def _make_dist(size, block_size, grid: Optional[Grid], source_rank) -> Distribution:
    gs = grid.size if grid is not None else GridSize2D(1, 1)
    return Distribution(size=size, block_size=block_size, grid_size=gs,
                        rank=RankIndex2D(0, 0), source_rank=source_rank)


def _shard(storage, grid: Optional[Grid]):
    from .memory import place

    if grid is None or grid.num_devices == 1:
        return storage
    return place(storage, grid.tile_sharding())


@functools.lru_cache(maxsize=64)
def _retile_sharded(dist: Distribution, sharding):
    """Compiled global->tile-storage re-tile with the block-cyclic output
    ``sharding`` (the grid's ``tile_sharding()``, hashable) baked in; for
    device-array inputs XLA moves shards directly to their owners instead
    of staging the full matrix anywhere."""
    return jax.jit(lambda a: tiling.global_to_tiles(a, dist),
                   out_shardings=sharding)
