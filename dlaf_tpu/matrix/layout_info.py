"""Local memory-layout descriptions.

TPU-native counterpart of the reference's ``matrix/layout_info.h:24-156``:
describes how the *local part* of a distributed matrix maps onto a linear
buffer, used when wrapping user-provided host memory (the reference's
``Matrix(layout, ptr)`` ctors, ``matrix.h:94-109``). Two canonical layouts:

* ``col_major_layout(size, block, ld)`` — ScaLAPACK-style column-major local
  matrix with leading dimension ``ld``;
* ``tile_layout(size, block, ld_tile, tiles_per_col)`` — tiles stored
  contiguously (the packed layout our 4D tile storage generalizes).

Pure index math; the actual HBM residency is PJRT's job.
"""

from __future__ import annotations

import dataclasses

from ..common.asserts import dlaf_assert
from ..common.index2d import LocalElementSize, LocalTileIndex, TileElementSize
from ..types import SizeType, ceil_div


@dataclasses.dataclass(frozen=True)
class LayoutInfo:
    """Placement of each local tile in a linear buffer
    (reference ``LayoutInfo``: size, block, tile offsets, min memory)."""

    size: LocalElementSize
    block_size: TileElementSize
    ld_tile: SizeType          # leading dimension inside a tile
    tile_offset_row: SizeType  # linear offset step between vertical tiles
    tile_offset_col: SizeType  # linear offset step between tile columns

    @property
    def nr_tiles(self):
        return (ceil_div(self.size.row, self.block_size.row) if self.size.row else 0,
                ceil_div(self.size.col, self.block_size.col) if self.size.col else 0)

    def tile_offset(self, index: LocalTileIndex) -> SizeType:
        """Buffer offset of tile ``index`` (reference ``LayoutInfo::tileOffset``)."""
        nt = self.nr_tiles
        dlaf_assert(0 <= index.row < max(nt[0], 1) and 0 <= index.col < max(nt[1], 1),
                    f"tile {index} out of {nt}")
        return index.row * self.tile_offset_row + index.col * self.tile_offset_col

    def tile_size_of(self, index: LocalTileIndex) -> TileElementSize:
        return TileElementSize(
            min(self.block_size.row, self.size.row - index.row * self.block_size.row),
            min(self.block_size.col, self.size.col - index.col * self.block_size.col))

    def min_mem_size(self) -> SizeType:
        """Minimum buffer length (reference ``LayoutInfo::minMemSize``)."""
        if self.size.is_empty():
            return 0
        nt = self.nr_tiles
        last = LocalTileIndex(nt[0] - 1, nt[1] - 1)
        sz = self.tile_size_of(last)
        return self.tile_offset(last) + (sz.col - 1) * self.ld_tile + sz.row


def col_major_layout(size: LocalElementSize, block_size: TileElementSize,
                     ld: SizeType) -> LayoutInfo:
    """Column-major local layout (reference ``colMajorLayout``,
    ``layout_info.h:100-118``)."""
    dlaf_assert(ld >= max(1, size.row), f"ld {ld} < rows {size.row}")
    return LayoutInfo(size=size, block_size=block_size, ld_tile=ld,
                      tile_offset_row=block_size.row,
                      tile_offset_col=block_size.col * ld)


def tile_layout(size: LocalElementSize, block_size: TileElementSize,
                ld_tile: SizeType | None = None,
                tiles_per_col: SizeType | None = None) -> LayoutInfo:
    """Packed tile layout (reference ``tileLayout``, ``layout_info.h:120-156``)."""
    if ld_tile is None:
        ld_tile = max(1, block_size.row)
    nt_row = ceil_div(size.row, block_size.row) if size.row else 0
    if tiles_per_col is None:
        tiles_per_col = nt_row
    dlaf_assert(ld_tile >= min(block_size.row, max(1, size.row)),
                f"ld_tile {ld_tile} too small")
    dlaf_assert(tiles_per_col >= nt_row, f"tiles_per_col {tiles_per_col} < {nt_row}")
    tile_area = ld_tile * block_size.col
    return LayoutInfo(size=size, block_size=block_size, ld_tile=ld_tile,
                      tile_offset_row=tile_area,
                      tile_offset_col=tile_area * tiles_per_col)
