"""Layout transforms between global matrices and block-cyclic tile storage.

This is the TPU-native replacement for the reference's per-tile memory model
(``matrix/layout_info.h``, ``memory/``): instead of a pool of individually
allocated tiles, a distributed matrix lives in ONE 4D "tile storage" array of
shape ``(P*ltr, Q*ltc, mb, nb)`` whose leading two axes enumerate tiles in
*rank-major cyclic-permuted* order:

    storage[p*ltr + l_r, q*ltc + l_c] == global tile (l_r*P + (p - src_r)%P,
                                                      l_c*Q + (q - src_c)%Q)

so a plain ``NamedSharding(mesh, P('row','col'))`` over the leading axes gives
each mesh coordinate exactly its block-cyclic local tiles — XLA's block
sharding composed with this static tile permutation *is* the reference's 2D
block-cyclic distribution (``misc/matrix_distribution.md``). Edge tiles are
zero-padded to full ``(mb, nb)``; ranks owning fewer tiles than the max get
all-zero padding tiles.

All transforms are pure jnp functions (jit-able, run on device). The
permutations are trace-time constants derived from :class:`Distribution`.
"""

from __future__ import annotations

import contextlib
import functools
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from ..types import ceil_div
from .distribution import Distribution
from . import util_distribution as ud


def storage_tile_grid(dist: Distribution) -> tuple[int, int, int, int]:
    """(P*ltr, Q*ltc, ltr, ltc): storage tile-grid extents and the uniform
    per-rank local tile counts (max over ranks, so short ranks are padded)."""
    nt = dist.nr_tiles
    P, Q = dist.grid_size.row, dist.grid_size.col
    ltr = ceil_div(nt.row, P) if nt.row else 0
    ltc = ceil_div(nt.col, Q) if nt.col else 0
    return P * ltr, Q * ltc, ltr, ltc


def _axis_perm(n_tiles: int, grid: int, src: int, lt: int) -> list[int]:
    """storage index -> global tile index (or n_tiles for the zero-pad slot)."""
    perm = []
    for p in range(grid):
        for l in range(lt):
            g = ud.global_tile_from_local_tile(l, grid, p, src)
            perm.append(g if g < n_tiles else n_tiles)
    return perm


def _axis_perm_inv(n_tiles: int, grid: int, src: int, lt: int) -> list[int]:
    """global tile index -> storage index."""
    inv = []
    for g in range(n_tiles):
        p = ud.rank_global_tile(g, grid, src)
        l = ud.local_tile_from_global_tile(g, grid)
        inv.append(p * lt + l)
    return inv


def global_to_tiles(a, dist: Distribution):
    """Global ``(m, n)`` array -> tile storage ``(P*ltr, Q*ltc, mb, nb)``."""
    m, n = dist.size.row, dist.size.col
    mb, nb = dist.block_size.row, dist.block_size.col
    nt = dist.nr_tiles
    Sr, Sc, ltr, ltc = storage_tile_grid(dist)
    if not hasattr(a, "devices"):
        # host input: H2D through memory.place (complex-pair fallback for
        # PJRT paths that reject complex128 transfers)
        from . import memory as _memory

        a = _memory.place(np.asarray(a))
    a = jnp.asarray(a)
    # pad to whole tiles, split into the (ntr, ntc, mb, nb) tile grid
    a = jnp.pad(a, ((0, nt.row * mb - m), (0, nt.col * nb - n)))
    t = a.reshape(nt.row, mb, nt.col, nb).transpose(0, 2, 1, 3)
    # append one zero tile row/col as the target of padding slots, permute
    t = jnp.pad(t, ((0, 1), (0, 1), (0, 0), (0, 0)))
    pr = _axis_perm(nt.row, dist.grid_size.row, dist.source_rank.row, ltr)
    pc = _axis_perm(nt.col, dist.grid_size.col, dist.source_rank.col, ltc)
    t = jnp.take(t, jnp.array(pr, dtype=jnp.int32), axis=0)
    t = jnp.take(t, jnp.array(pc, dtype=jnp.int32), axis=1)
    assert t.shape == (Sr, Sc, mb, nb)
    return t


def tiles_to_global(t, dist: Distribution):
    """Tile storage -> global ``(m, n)`` array (inverse of global_to_tiles)."""
    m, n = dist.size.row, dist.size.col
    mb, nb = dist.block_size.row, dist.block_size.col
    nt = dist.nr_tiles
    _, _, ltr, ltc = storage_tile_grid(dist)
    pr = _axis_perm_inv(nt.row, dist.grid_size.row, dist.source_rank.row, ltr)
    pc = _axis_perm_inv(nt.col, dist.grid_size.col, dist.source_rank.col, ltc)
    t = jnp.asarray(t)
    t = jnp.take(t, jnp.array(pr, dtype=jnp.int32), axis=0)
    t = jnp.take(t, jnp.array(pc, dtype=jnp.int32), axis=1)
    a = t.transpose(0, 2, 1, 3).reshape(nt.row * mb, nt.col * nb)
    return a[:m, :n]


# Donated jit forms of the two layout transforms, shared by the algorithm
# entry points for their internal stage hand-offs (layout -> factorize ->
# layout) and for opt-in input donation (the reference's in-place matrix
# semantics). Donation removes one full-matrix HBM buffer per hand-off —
# at the single-chip ceiling (config #1 N=16384 = 2.1 GB/buffer on a
# 15.75 GB chip) that is the difference between fitting and OOM. No
# config dependence: these never need program-cache invalidation.

@functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
def global_to_tiles_donated(a, dist: Distribution):
    return global_to_tiles(a, dist)


@functools.partial(jax.jit, static_argnums=1, donate_argnums=0)
def tiles_to_global_donated(t, dist: Distribution):
    return tiles_to_global(t, dist)


@contextlib.contextmanager
def quiet_donation():
    """Scope for dispatching donated programs: suppresses jax's
    "Some donated buffers were not usable" warning INSIDE the library's
    own calls only (backends that cannot alias a given buffer — e.g.
    complex128 on XLA:CPU — fall back to a copy, which is exactly the
    pre-donation behavior; per-call noise, not signal). Donation warnings
    from the application's own jax code are left untouched."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


def to_global(storage, dist: Distribution, donate: bool):
    """Entry-point helper: tile storage -> global array, optionally
    consuming ``storage`` (the caller's opt-in input donation). Callers
    dispatch inside their own :func:`quiet_donation` scope."""
    if donate:
        return tiles_to_global_donated(storage, dist)
    return tiles_to_global(storage, dist)


def donate_argnums_kw(donate: bool, argnums) -> dict:
    """``jax.jit`` kwargs for an optionally donated build (shared by the
    per-algorithm program caches, which key on the donate flag)."""
    return {"donate_argnums": argnums} if donate else {}


def global_tile_to_storage_index(dist: Distribution, row: int, col: int) -> tuple[int, int]:
    """Storage coordinates of global tile (row, col) — trace-time helper used
    by the per-k algorithm loops."""
    _, _, ltr, ltc = storage_tile_grid(dist)
    pr = ud.rank_global_tile(row, dist.grid_size.row, dist.source_rank.row)
    pc = ud.rank_global_tile(col, dist.grid_size.col, dist.source_rank.col)
    lr = ud.local_tile_from_global_tile(row, dist.grid_size.row)
    lc = ud.local_tile_from_global_tile(col, dist.grid_size.col)
    return pr * ltr + lr, pc * ltc + lc
