"""Device-memory placement helpers.

TPU-native counterpart of the reference's ``memory/`` layer
(``MemoryChunk``/``MemoryView`` over umpire host/device pools,
``memory/memory_chunk.h:38-165``): PJRT owns allocation, pooling and
pinning, so what remains is placement (host→HBM with a sharding), donation
(the in-place story for functional updates), and wrapping user-provided
buffers without copies where possible.
"""

from __future__ import annotations

import numpy as np

import jax


def place(array, sharding=None):
    """Move a host array into device memory (reference: MemoryChunk alloc +
    H2D); with a NamedSharding this is the distributed placement."""
    if sharding is None:
        return jax.device_put(array)
    return jax.device_put(array, sharding)


def donate_wrapper(fn):
    """jit with first-argument donation: the functional-update analog of the
    reference's in-place tile writes — XLA reuses the input buffer."""
    return jax.jit(fn, donate_argnums=(0,))


def wrap_host(array: np.ndarray) -> np.ndarray:
    """Non-owning host wrap (reference MemoryChunk user-pointer ctor): numpy
    views are already non-owning; returned as-is, documented for parity."""
    return np.asarray(array)


def nbytes(x) -> int:
    return int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
