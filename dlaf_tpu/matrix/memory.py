"""Device-memory placement.

TPU-native counterpart of the reference's ``memory/`` layer
(``MemoryChunk``/``MemoryView`` over umpire host/device pools,
``memory/memory_chunk.h:38-165``): PJRT owns allocation, pooling, pinning,
and non-owning host wraps (numpy views), so the one placement decision left
to the framework is host→HBM transfer with a sharding — :func:`place`, the
H2D path of every :class:`~dlaf_tpu.matrix.matrix.Matrix` construction and
checkpoint restore. In-place reuse (the reference's tile writes into pooled
chunks) is expressed per jit boundary via buffer donation where an
algorithm needs it, not as a pool API.
"""

from __future__ import annotations

import jax


def place(array, sharding=None):
    """Move a host array into device memory (reference: MemoryChunk alloc +
    H2D); with a NamedSharding this is the distributed placement."""
    if sharding is None:
        return jax.device_put(array)
    return jax.device_put(array, sharding)
