"""Device-memory placement.

TPU-native counterpart of the reference's ``memory/`` layer
(``MemoryChunk``/``MemoryView`` over umpire host/device pools,
``memory/memory_chunk.h:38-165``): PJRT owns allocation, pooling, pinning,
and non-owning host wraps (numpy views), so the one placement decision left
to the framework is host→HBM transfer with a sharding — :func:`place`, the
H2D path of every :class:`~dlaf_tpu.matrix.matrix.Matrix` construction and
checkpoint restore. In-place reuse (the reference's tile writes into pooled
chunks) is expressed per jit boundary via buffer donation where an
algorithm needs it, not as a pool API.

complex128 transfer fallback: some PJRT transfer paths reject complex128
buffers even though complex128 *compute* works through the X64 rewrite
(suspected on the v5e tunnel, 2026-07-31: config #3's ``device_put`` of
the c128 input died first thing — concurrent with a tunnel wedge, so the
root cause is still open). :func:`place`/:func:`fetch` try the direct
transfer first and, on failure, retry with the real and imaginary parts as
two f64 transfers combined by ``lax.complex`` on the destination side; the
mode latches process-wide (with a warning) only when the pair retry
actually succeeds, so transient backend failures — which fail both ways —
never flip it.

Scope limits of the fallback (round-2 advisory): only the transfer-error
types in :data:`_TRANSFER_ERRORS` trigger the retry — and RESOURCE_EXHAUSTED
(device OOM) is re-raised without one, since the pair path needs MORE
transient memory, not less. PJRT transfers can also fail ASYNCHRONOUSLY:
``device_put`` may return a future-backed array whose failure only
surfaces at consumption (``block_until_ready``/compute). Such deferred
failures bypass this guard entirely — a wedge observed at
``block_until_ready`` will NOT auto-latch pair mode; set it explicitly by
calling :func:`_latch_pair_mode` or retry at the operator level.
"""

from __future__ import annotations

import warnings

import numpy as np

import jax
import jax.numpy as jnp

#: Tri-state per-process cache: None = direct complex transfers untested,
#: False/None treated as direct-first, True = pair fallback required.
_complex_pair_mode = None

try:  # the PJRT runtime-error type (transfer rejections, backend faults)
    from jax.errors import JaxRuntimeError as _JaxRuntimeError
except ImportError:  # older jaxlib spelling
    from jaxlib.xla_extension import XlaRuntimeError as _JaxRuntimeError

#: Exception types that plausibly mean "this transfer path rejected the
#: buffer" and are worth a pair retry. Bare ``Exception`` used to be
#: caught here; that routed unrelated failures (OOM, interpreter
#: teardown) into a doomed second transfer attempt.
_TRANSFER_ERRORS = (_JaxRuntimeError, ValueError, TypeError)


def _retryable_transfer_error(e: Exception) -> bool:
    """A pair retry is sensible: a recognized transfer-error type that is
    NOT device OOM (RESOURCE_EXHAUSTED needs less memory, and the pair
    path transiently needs more)."""
    return (isinstance(e, _TRANSFER_ERRORS)
            and "RESOURCE_EXHAUSTED" not in str(e))

_combine = jax.jit(jax.lax.complex)


def _is_device_array(x) -> bool:
    return hasattr(x, "devices")


def _place_pair(array, sharding):
    if _is_device_array(array):
        # device-resident complex input (e.g. the distributed reshard in
        # Matrix._shard): split on device — no host round trip, and no
        # direct complex transfer
        re = jax.device_put(jnp.real(array), sharding)
        im = jax.device_put(jnp.imag(array), sharding)
    else:
        a = np.asarray(array)
        re = jax.device_put(np.ascontiguousarray(a.real), sharding)
        im = jax.device_put(np.ascontiguousarray(a.imag), sharding)
    return _combine(re, im)


#: Direct-complex failures whose health probe passed anyway (a
#: sharding/size-specific transfer bug the tiny probe cannot see); after
#: a few of these the pair mode latches regardless.
_probe_passed_failures = 0
_PROBE_PASS_LATCH_AFTER = 3


def _latch_pair_mode(op: str):
    """Latch when a TINY direct complex transfer also fails right now
    (clear-cut backend rejection), or after several CONSECUTIVE direct
    failures whose probe passed (a transfer bug specific to the real
    shapes/shardings that the 1-element probe cannot reproduce; the
    counter resets on any direct success). One-off transient failures
    latch nothing."""
    global _complex_pair_mode, _probe_passed_failures
    if _complex_pair_mode is True:
        return
    reason = f"direct complex128 {op} failed; the 1-element probe failed too"
    try:
        jax.device_get(jax.device_put(np.zeros((1,), np.complex128)))
        _probe_passed_failures += 1
        if _probe_passed_failures < _PROBE_PASS_LATCH_AFTER:
            return   # probably transient; keep trying direct first
        reason = (f"direct complex128 {op} failed "
                  f"{_probe_passed_failures} consecutive times while the "
                  "1-element probe kept passing (shape/sharding-specific "
                  "transfer bug)")
    except Exception:
        pass
    warnings.warn(
        f"{reason}; the real/imag pair transfer succeeded — enabling pair "
        "mode for all further complex transfers in this process "
        "(matrix/memory.py)")
    _complex_pair_mode = True


def place(array, sharding=None):
    """Move a host array into device memory (reference: MemoryChunk alloc +
    H2D); with a NamedSharding this is the distributed placement. Also the
    device-to-device reshard path for device-array inputs."""
    global _probe_passed_failures
    if np.iscomplexobj(array) and _complex_pair_mode:
        return _place_pair(array, sharding)
    try:
        out = jax.device_put(array, sharding)
        if np.iscomplexobj(array):
            _probe_passed_failures = 0   # direct works; reset the streak
        return out
    except Exception as e:
        if not np.iscomplexobj(array) or not _retryable_transfer_error(e):
            raise
        out = _place_pair(array, sharding)   # raises too if truly broken
        _latch_pair_mode("device_put")
        return out


def as_device(x):
    """``jnp.asarray`` for possibly-host inputs, routed through
    :func:`place` so complex host arrays get the pair-transfer fallback;
    device arrays pass through untouched."""
    if _is_device_array(x):
        return x
    return place(np.asarray(x))


def fetch(x) -> np.ndarray:
    """Device array -> host numpy (reference: D2H copy), with the symmetric
    complex-pair fallback: real/imag computed on device, transferred as two
    real arrays, combined on host."""
    global _probe_passed_failures
    if np.iscomplexobj(x) and _complex_pair_mode:
        return _fetch_pair(x)
    try:
        out = np.asarray(jax.device_get(x))
        if np.iscomplexobj(x):
            _probe_passed_failures = 0   # direct works; reset the streak
        return out
    except Exception as e:
        if not np.iscomplexobj(x) or not _retryable_transfer_error(e):
            raise
        out = _fetch_pair(x)
        _latch_pair_mode("device_get")
        return out


def _fetch_pair(x) -> np.ndarray:
    re = np.asarray(jax.device_get(jnp.real(x)))
    im = np.asarray(jax.device_get(jnp.imag(x)))
    return re + 1j * im
