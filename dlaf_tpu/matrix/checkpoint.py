"""Matrix persistence via orbax (application-owned checkpoint hook).

The reference has NO checkpoint subsystem (SURVEY §5): applications own
persistence by wrapping user memory (``matrix/matrix.h:94-109``). This module
keeps the same stance — nothing in the algorithms checkpoints — but makes the
application hook concrete for the JAX ecosystem: a distributed
:class:`~dlaf_tpu.matrix.matrix.Matrix` round-trips through an orbax
checkpoint (sharded tile storage + the Distribution metadata needed to
rebuild it on any grid of the same shape).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..comm.grid import Grid
from ..common.asserts import dlaf_assert
from ..common.index2d import GlobalElementSize, RankIndex2D, TileElementSize
from .matrix import Matrix


def save(path: str, mat: Matrix) -> None:
    """Write ``mat`` (storage + layout metadata) to ``path`` (a directory)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tree = {
        "storage": mat.storage,
        "meta": {
            "size": np.array([mat.size.row, mat.size.col], dtype=np.int64),
            "block_size": np.array([mat.block_size.row, mat.block_size.col],
                                   dtype=np.int64),
            "grid_size": np.array([mat.dist.grid_size.row,
                                   mat.dist.grid_size.col], dtype=np.int64),
            "source_rank": np.array([mat.dist.source_rank.row,
                                     mat.dist.source_rank.col], dtype=np.int64),
        },
    }
    with ocp.PyTreeCheckpointer() as ckpt:
        ckpt.save(path, tree, force=True)


def load(path: str, grid: Optional[Grid] = None) -> Matrix:
    """Rebuild a Matrix from ``path``. ``grid`` must match the saved grid
    shape (or be omitted for a matrix saved without a grid)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckpt:
        tree = ckpt.restore(path)
    meta = tree["meta"]
    gr, gc = (int(x) for x in meta["grid_size"])
    if grid is None:
        dlaf_assert(gr * gc == 1,
                    f"checkpoint was saved on a {gr}x{gc} grid; pass grid=")
    else:
        dlaf_assert((grid.size.row, grid.size.col) == (gr, gc),
                    f"grid {grid.size} != saved {gr}x{gc}")
    size = GlobalElementSize(*(int(x) for x in meta["size"]))
    block = TileElementSize(*(int(x) for x in meta["block_size"]))
    src = RankIndex2D(*(int(x) for x in meta["source_rank"]))
    from .matrix import _make_dist

    dist = _make_dist(size, block, grid, src)
    storage = tree["storage"]
    if grid is not None and grid.num_devices > 1:
        from .memory import place

        storage = place(storage, grid.tile_sharding())
    return Matrix(dist, storage, grid)
