"""Matrix and pipeline-stage persistence (application-owned checkpoints).

The reference has NO checkpoint subsystem (SURVEY §5): applications own
persistence by wrapping user memory (``matrix/matrix.h:94-109``). This module
keeps the same stance — nothing in the algorithms checkpoints implicitly —
but makes the application hook concrete for the JAX ecosystem, in two layers:

* **Whole-matrix round trip** (:func:`save` / :func:`load`): a distributed
  :class:`~dlaf_tpu.matrix.matrix.Matrix` through an orbax checkpoint
  (sharded tile storage + the Distribution metadata needed to rebuild it on
  any grid of the same shape).
* **Stage-level checkpoints** (:func:`save_stage` / :func:`load_stage` /
  :func:`stage_manifest`, PR 12 — docs/robustness.md §5): versioned,
  ATOMIC (write-to-temp + ``os.replace``) ``.npz`` payloads plus JSON
  manifests carrying config/grid/dtype fingerprints, the persistence
  substrate beneath ``DLAF_RESUME_DIR`` preemption-safe pipeline resume
  (:mod:`dlaf_tpu.health.resume`). The manifest is written AFTER the
  payload and its presence IS the completion marker — a process killed
  mid-write leaves either nothing or a complete stage, never a torn one.
  :func:`matrix_arrays` / :func:`matrix_from_arrays` flatten a Matrix
  into such a payload (raw tile storage, NOT the unpadded global view, so
  the round trip is bitwise including edge-tile padding).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ..comm.grid import Grid
from ..common.index2d import GlobalElementSize, RankIndex2D, TileElementSize
from .matrix import Matrix


def save(path: str, mat: Matrix) -> None:
    """Write ``mat`` (storage + layout metadata) to ``path`` (a directory)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tree = {
        "storage": mat.storage,
        "meta": {
            "size": np.array([mat.size.row, mat.size.col], dtype=np.int64),
            "block_size": np.array([mat.block_size.row, mat.block_size.col],
                                   dtype=np.int64),
            "grid_size": np.array([mat.dist.grid_size.row,
                                   mat.dist.grid_size.col], dtype=np.int64),
            "source_rank": np.array([mat.dist.source_rank.row,
                                     mat.dist.source_rank.col], dtype=np.int64),
        },
    }
    with ocp.PyTreeCheckpointer() as ckpt:
        ckpt.save(path, tree, force=True)


_META_FIELDS = ("size", "block_size", "grid_size", "source_rank")


def _meta_pair(meta, name: str, path: str) -> tuple:
    """One validated (row, col) int pair from the restored metadata —
    a missing or malformed field must name ITSELF, not surface later as
    an unrelated shape error."""
    val = meta.get(name) if hasattr(meta, "get") else None
    if val is None:
        raise ValueError(
            f"checkpoint {path!r}: metadata field {name!r} is missing "
            f"(expected one of {_META_FIELDS}) — not a dlaf_tpu matrix "
            "checkpoint, or written by an incompatible version")
    arr = np.asarray(val)
    if arr.shape != (2,):
        raise ValueError(
            f"checkpoint {path!r}: metadata field {name!r} has shape "
            f"{arr.shape}, expected (2,)")
    return int(arr[0]), int(arr[1])


def load(path: str, grid: Optional[Grid] = None) -> Matrix:
    """Rebuild a Matrix from ``path``. ``grid`` must match the saved grid
    shape (or be omitted for a matrix saved without a grid).

    Every metadata field is validated against the restored storage and the
    caller's ``grid`` BEFORE any Matrix is built: a mismatch raises a
    ``ValueError`` naming the offending field (size / block_size /
    grid_size / source_rank / storage shape) instead of a downstream
    shape assertion from the tiling layer."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckpt:
        tree = ckpt.restore(path)
    meta = tree.get("meta") if hasattr(tree, "get") else None
    if meta is None or "storage" not in tree:
        raise ValueError(
            f"checkpoint {path!r}: missing 'meta'/'storage' entries — not "
            "a dlaf_tpu matrix checkpoint")
    gr, gc = _meta_pair(meta, "grid_size", path)
    if grid is None:
        if gr * gc != 1:
            raise ValueError(
                f"checkpoint {path!r}: grid_size mismatch — saved on a "
                f"{gr}x{gc} grid; pass a grid= of that shape")
    elif (grid.size.row, grid.size.col) != (gr, gc):
        raise ValueError(
            f"checkpoint {path!r}: grid_size mismatch — saved {gr}x{gc}, "
            f"loading onto {grid.size.row}x{grid.size.col}")
    size = GlobalElementSize(*_meta_pair(meta, "size", path))
    block = TileElementSize(*_meta_pair(meta, "block_size", path))
    if size.row < 0 or size.col < 0:
        raise ValueError(f"checkpoint {path!r}: size {size} is negative")
    if block.row < 1 or block.col < 1:
        raise ValueError(
            f"checkpoint {path!r}: block_size {block} must be >= 1")
    sr, sc = _meta_pair(meta, "source_rank", path)
    if not (0 <= sr < gr and 0 <= sc < gc):
        raise ValueError(
            f"checkpoint {path!r}: source_rank ({sr}, {sc}) outside the "
            f"saved {gr}x{gc} grid")
    src = RankIndex2D(sr, sc)
    from .matrix import _make_dist
    from .tiling import storage_tile_grid

    dist = _make_dist(size, block, grid, src)
    storage = tree["storage"]
    Sr, Sc, _, _ = storage_tile_grid(dist)
    expect = (Sr, Sc, block.row, block.col)
    if tuple(storage.shape) != expect:
        raise ValueError(
            f"checkpoint {path!r}: storage shape {tuple(storage.shape)} "
            f"inconsistent with metadata (size={size}, block_size={block}, "
            f"grid_size={gr}x{gc} => expected {expect}) — the checkpoint "
            "is corrupt or its metadata was edited")
    if grid is not None and grid.num_devices > 1:
        from .memory import place

        storage = place(storage, grid.tile_sharding())
    return Matrix(dist, storage, grid)


# ---------------------------------------------------------------------------
# Stage-level checkpoints (DLAF_RESUME_DIR; docs/robustness.md §5)
# ---------------------------------------------------------------------------

#: Manifest schema version; a loader seeing a different version must
#: refuse (the resume layer raises ResumeError), never misparse.
STAGE_MANIFEST_VERSION = 1


def matrix_arrays(mat: Matrix, prefix: str = "m") -> dict:
    """Flatten ``mat`` into a stage-payload array dict: the RAW tile
    storage (bitwise — edge-tile padding included, so recomputation from
    a restored matrix sees exactly the bytes the uninterrupted run saw)
    plus the layout metadata needed to rebuild the Distribution."""
    return {
        f"{prefix}.storage": np.asarray(mat.storage),
        f"{prefix}.meta": np.array(
            [mat.size.row, mat.size.col,
             mat.block_size.row, mat.block_size.col,
             mat.dist.grid_size.row, mat.dist.grid_size.col,
             mat.dist.source_rank.row, mat.dist.source_rank.col],
            dtype=np.int64),
    }


def matrix_from_arrays(arrays: dict, prefix: str = "m",
                       grid: Optional[Grid] = None) -> Matrix:
    """Rebuild a Matrix from a :func:`matrix_arrays` payload. ``grid``
    must match the saved grid shape (None only for 1x1 saves) — the same
    contract as :func:`load`, validated before any Matrix is built."""
    meta = np.asarray(arrays[f"{prefix}.meta"]).reshape(-1)
    if meta.shape != (8,):
        raise ValueError(f"stage payload {prefix!r}: meta shape "
                         f"{meta.shape}, expected (8,)")
    size = GlobalElementSize(int(meta[0]), int(meta[1]))
    block = TileElementSize(int(meta[2]), int(meta[3]))
    gr, gc = int(meta[4]), int(meta[5])
    if grid is None:
        if gr * gc != 1:
            raise ValueError(f"stage payload {prefix!r}: saved on a "
                             f"{gr}x{gc} grid; pass a grid= of that shape")
    elif (grid.size.row, grid.size.col) != (gr, gc):
        raise ValueError(f"stage payload {prefix!r}: grid mismatch — "
                         f"saved {gr}x{gc}, loading onto "
                         f"{grid.size.row}x{grid.size.col}")
    from .matrix import _make_dist
    from .tiling import storage_tile_grid

    dist = _make_dist(size, block, grid,
                      RankIndex2D(int(meta[6]), int(meta[7])))
    storage = np.asarray(arrays[f"{prefix}.storage"])
    Sr, Sc, _, _ = storage_tile_grid(dist)
    expect = (Sr, Sc, block.row, block.col)
    if tuple(storage.shape) != expect:
        raise ValueError(f"stage payload {prefix!r}: storage shape "
                         f"{tuple(storage.shape)} inconsistent with its "
                         f"metadata (expected {expect})")
    if grid is not None and grid.num_devices > 1:
        from .memory import place

        storage = place(storage, grid.tile_sharding())
    return Matrix(dist, storage, grid)


def _atomic_replace(path: str, write_fn) -> None:
    """Write via a same-directory temp file + ``os.replace`` (atomic on
    POSIX): readers see the old file or the new one, never a torn one."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _stage_paths(directory: str, stage: str) -> tuple:
    if not stage or any(c in stage for c in "/\\"):
        raise ValueError(f"stage name {stage!r} must be a bare identifier")
    return (os.path.join(directory, f"{stage}.npz"),
            os.path.join(directory, f"{stage}.json"))


def save_stage(directory: str, stage: str, arrays: dict,
               fingerprint: dict, extra: Optional[dict] = None) -> str:
    """Persist one completed stage: the array payload (atomic ``.npz``)
    first, then the manifest (atomic JSON) — manifest presence marks the
    stage complete. Returns the manifest path."""
    os.makedirs(directory, exist_ok=True)
    data_path, man_path = _stage_paths(directory, stage)

    def _write_npz(tmp):
        # write through an open file object: np.savez(path) would append
        # its own .npz suffix and break the rename
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)

    _atomic_replace(data_path, _write_npz)
    manifest = {"version": STAGE_MANIFEST_VERSION, "stage": stage,
                "arrays": os.path.basename(data_path),
                "keys": sorted(arrays),
                "fingerprint": dict(fingerprint), **(extra or {})}

    def _write_json(tmp):
        with open(tmp, "w") as f:
            json.dump(manifest, f, sort_keys=True)

    _atomic_replace(man_path, _write_json)
    return man_path


def stage_manifest(directory: str, stage: str) -> Optional[dict]:
    """The stage's manifest dict, or None when the stage has not
    completed (no manifest). An unparsable manifest raises ValueError —
    corruption must be loud, not "not completed"."""
    _, man_path = _stage_paths(directory, stage)
    if not os.path.exists(man_path):
        return None
    with open(man_path) as f:
        try:
            manifest = json.load(f)
        except ValueError as e:
            raise ValueError(f"stage manifest {man_path!r} is corrupt: {e}")
    if not isinstance(manifest, dict):
        raise ValueError(f"stage manifest {man_path!r}: not an object")
    return manifest


def load_stage(directory: str, stage: str) -> tuple:
    """``(arrays dict, manifest dict)`` for a completed stage; raises
    ValueError when the stage is incomplete or the payload disagrees
    with its manifest key list."""
    manifest = stage_manifest(directory, stage)
    if manifest is None:
        raise ValueError(f"stage {stage!r} has no manifest under "
                         f"{directory!r} — not completed")
    data_path, _ = _stage_paths(directory, stage)
    with np.load(data_path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files}
    if sorted(arrays) != manifest.get("keys", sorted(arrays)):
        raise ValueError(
            f"stage {stage!r}: payload keys {sorted(arrays)} != manifest "
            f"keys {manifest.get('keys')} — checkpoint is torn or edited")
    return arrays, manifest
