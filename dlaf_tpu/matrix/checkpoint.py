"""Matrix persistence via orbax (application-owned checkpoint hook).

The reference has NO checkpoint subsystem (SURVEY §5): applications own
persistence by wrapping user memory (``matrix/matrix.h:94-109``). This module
keeps the same stance — nothing in the algorithms checkpoints — but makes the
application hook concrete for the JAX ecosystem: a distributed
:class:`~dlaf_tpu.matrix.matrix.Matrix` round-trips through an orbax
checkpoint (sharded tile storage + the Distribution metadata needed to
rebuild it on any grid of the same shape).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..comm.grid import Grid
from ..common.index2d import GlobalElementSize, RankIndex2D, TileElementSize
from .matrix import Matrix


def save(path: str, mat: Matrix) -> None:
    """Write ``mat`` (storage + layout metadata) to ``path`` (a directory)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tree = {
        "storage": mat.storage,
        "meta": {
            "size": np.array([mat.size.row, mat.size.col], dtype=np.int64),
            "block_size": np.array([mat.block_size.row, mat.block_size.col],
                                   dtype=np.int64),
            "grid_size": np.array([mat.dist.grid_size.row,
                                   mat.dist.grid_size.col], dtype=np.int64),
            "source_rank": np.array([mat.dist.source_rank.row,
                                     mat.dist.source_rank.col], dtype=np.int64),
        },
    }
    with ocp.PyTreeCheckpointer() as ckpt:
        ckpt.save(path, tree, force=True)


_META_FIELDS = ("size", "block_size", "grid_size", "source_rank")


def _meta_pair(meta, name: str, path: str) -> tuple:
    """One validated (row, col) int pair from the restored metadata —
    a missing or malformed field must name ITSELF, not surface later as
    an unrelated shape error."""
    val = meta.get(name) if hasattr(meta, "get") else None
    if val is None:
        raise ValueError(
            f"checkpoint {path!r}: metadata field {name!r} is missing "
            f"(expected one of {_META_FIELDS}) — not a dlaf_tpu matrix "
            "checkpoint, or written by an incompatible version")
    arr = np.asarray(val)
    if arr.shape != (2,):
        raise ValueError(
            f"checkpoint {path!r}: metadata field {name!r} has shape "
            f"{arr.shape}, expected (2,)")
    return int(arr[0]), int(arr[1])


def load(path: str, grid: Optional[Grid] = None) -> Matrix:
    """Rebuild a Matrix from ``path``. ``grid`` must match the saved grid
    shape (or be omitted for a matrix saved without a grid).

    Every metadata field is validated against the restored storage and the
    caller's ``grid`` BEFORE any Matrix is built: a mismatch raises a
    ``ValueError`` naming the offending field (size / block_size /
    grid_size / source_rank / storage shape) instead of a downstream
    shape assertion from the tiling layer."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckpt:
        tree = ckpt.restore(path)
    meta = tree.get("meta") if hasattr(tree, "get") else None
    if meta is None or "storage" not in tree:
        raise ValueError(
            f"checkpoint {path!r}: missing 'meta'/'storage' entries — not "
            "a dlaf_tpu matrix checkpoint")
    gr, gc = _meta_pair(meta, "grid_size", path)
    if grid is None:
        if gr * gc != 1:
            raise ValueError(
                f"checkpoint {path!r}: grid_size mismatch — saved on a "
                f"{gr}x{gc} grid; pass a grid= of that shape")
    elif (grid.size.row, grid.size.col) != (gr, gc):
        raise ValueError(
            f"checkpoint {path!r}: grid_size mismatch — saved {gr}x{gc}, "
            f"loading onto {grid.size.row}x{grid.size.col}")
    size = GlobalElementSize(*_meta_pair(meta, "size", path))
    block = TileElementSize(*_meta_pair(meta, "block_size", path))
    if size.row < 0 or size.col < 0:
        raise ValueError(f"checkpoint {path!r}: size {size} is negative")
    if block.row < 1 or block.col < 1:
        raise ValueError(
            f"checkpoint {path!r}: block_size {block} must be >= 1")
    sr, sc = _meta_pair(meta, "source_rank", path)
    if not (0 <= sr < gr and 0 <= sc < gc):
        raise ValueError(
            f"checkpoint {path!r}: source_rank ({sr}, {sc}) outside the "
            f"saved {gr}x{gc} grid")
    src = RankIndex2D(sr, sc)
    from .matrix import _make_dist
    from .tiling import storage_tile_grid

    dist = _make_dist(size, block, grid, src)
    storage = tree["storage"]
    Sr, Sc, _, _ = storage_tile_grid(dist)
    expect = (Sr, Sc, block.row, block.col)
    if tuple(storage.shape) != expect:
        raise ValueError(
            f"checkpoint {path!r}: storage shape {tuple(storage.shape)} "
            f"inconsistent with metadata (size={size}, block_size={block}, "
            f"grid_size={gr}x{gc} => expected {expect}) — the checkpoint "
            "is corrupt or its metadata was edited")
    if grid is not None and grid.num_devices > 1:
        from .memory import place

        storage = place(storage, grid.tile_sharding())
    return Matrix(dist, storage, grid)
