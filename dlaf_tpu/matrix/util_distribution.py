"""Pure 1D block-cyclic index conversions.

TPU-native counterpart of the reference's ``matrix/util_distribution.h:28-140``:
stateless per-axis functions mapping between global elements, global tiles,
local tiles, tile-local elements, and owning ranks of a block-cyclic
distribution with a source-rank offset. :class:`..matrix.distribution.Distribution`
composes these per-axis functions into the 2D map.

Conventions (identical to the reference / ScaLAPACK):
* global tile ``i`` is owned by rank ``(src_rank + i) % grid_size``;
* the local tile index of an owned global tile ``i`` is ``i // grid_size``;
* the last global tile may be smaller than ``tile_size``.
"""

from __future__ import annotations

from ..types import SizeType, ceil_div


def tile_from_element(element: SizeType, tile_size: SizeType) -> SizeType:
    """Global tile index containing global element (``util_distribution.h:34``)."""
    return element // tile_size


def tile_element_from_element(element: SizeType, tile_size: SizeType) -> SizeType:
    """Index inside its tile of a global element (``util_distribution.h:41``)."""
    return element % tile_size


def element_from_tile_and_tile_element(tile: SizeType, tile_element: SizeType,
                                       tile_size: SizeType) -> SizeType:
    """Global element from (tile, in-tile) pair (``util_distribution.h:48``)."""
    return tile * tile_size + tile_element


def rank_global_tile(tile: SizeType, grid_size: SizeType, src_rank: SizeType) -> SizeType:
    """Rank owning global tile ``tile`` (``util_distribution.h:56``)."""
    return (src_rank + tile) % grid_size


def local_tile_from_global_tile(tile: SizeType, grid_size: SizeType) -> SizeType:
    """Local tile index of an *owned* global tile (``util_distribution.h:64``).

    Only meaningful on the rank returned by :func:`rank_global_tile`.
    """
    return tile // grid_size


def next_local_tile_from_global_tile(tile: SizeType, grid_size: SizeType,
                                     rank: SizeType, src_rank: SizeType) -> SizeType:
    """Smallest local tile index on ``rank`` whose global tile is >= ``tile``
    (``util_distribution.h:73-88``). Equals ``local_nr_tiles`` when ``rank``
    owns no tile at or past ``tile``.
    """
    r = (rank - src_rank) % grid_size
    # smallest l >= 0 with l*grid_size + r >= tile, i.e. ceil((tile-r)/grid_size)
    return max(0, -(-(tile - r) // grid_size))


def global_tile_from_local_tile(local_tile: SizeType, grid_size: SizeType,
                                rank: SizeType, src_rank: SizeType) -> SizeType:
    """Global tile index of local tile ``local_tile`` on ``rank``
    (``util_distribution.h:95``)."""
    return local_tile * grid_size + (rank - src_rank) % grid_size


def local_nr_tiles(nr_tiles: SizeType, grid_size: SizeType,
                   rank: SizeType, src_rank: SizeType) -> SizeType:
    """Number of local tiles on ``rank`` for ``nr_tiles`` global tiles."""
    return next_local_tile_from_global_tile(nr_tiles, grid_size, rank, src_rank)


def tile_size_of(tile: SizeType, size: SizeType, tile_size: SizeType) -> SizeType:
    """Extent of global tile ``tile`` on an axis of ``size`` elements
    (edge tiles may be short)."""
    return min(tile_size, size - tile * tile_size)


def local_size(size: SizeType, tile_size: SizeType, grid_size: SizeType,
               rank: SizeType, src_rank: SizeType) -> SizeType:
    """Number of local elements on ``rank`` along an axis."""
    nt = ceil_div(size, tile_size) if size > 0 else 0
    ln = local_nr_tiles(nt, grid_size, rank, src_rank)
    if ln == 0:
        return 0
    last_global = global_tile_from_local_tile(ln - 1, grid_size, rank, src_rank)
    return (ln - 1) * tile_size + tile_size_of(last_global, size, tile_size)
