"""Matrix dumps for debugging.

TPU-native counterpart of the reference's ``matrix/print_numpy.h`` (112),
``print_csv.h`` (73), ``print_gpu.h``: ``print(format, matrix)`` emitting a
numpy-expression or CSV rendering of the (gathered) matrix.
"""

from __future__ import annotations

import io
import sys

import numpy as np

from .matrix import Matrix


def print_numpy(mat: Matrix, name: str = "a", file=None) -> str:
    """Emit ``name = np.array([...])`` (reference format::numpy)."""
    a = mat.to_numpy()
    buf = io.StringIO()
    buf.write(f"{name} = np.array(")
    buf.write(np.array2string(a, separator=", ", threshold=np.inf,
                              floatmode="unique"))
    buf.write(f", dtype=np.{a.dtype})\n")
    s = buf.getvalue()
    print(s, file=file or sys.stdout, end="")
    return s


def print_csv(mat: Matrix, file=None) -> str:
    """Comma-separated rows (reference format::csv)."""
    a = mat.to_numpy()
    buf = io.StringIO()
    for row in np.atleast_2d(a):
        buf.write(",".join(repr(x) for x in row.tolist()))
        buf.write("\n")
    s = buf.getvalue()
    print(s, file=file or sys.stdout, end="")
    return s
