"""Sub-matrix / sub-panel views.

TPU-native counterpart of the reference's ``SubMatrixView``/``SubPanelView``
(``matrix/views.h:29-184``) and ``MatrixView`` (``matrix/matrix_view.h``):
offset-limited views handing per-tile ``SubTileSpec``s to algorithms working
on a sub-block (the reference uses them in reduction_to_band). The reference's
MatrixView additionally manages concurrent scheduling epochs with
``done()/doneWrite()`` handoff — with immutable values and jit-step
boundaries there is no epoch state to hand off, so the view here is pure
index bookkeeping.
"""

from __future__ import annotations

import dataclasses

from ..common.asserts import dlaf_assert
from ..common.index2d import GlobalElementIndex, GlobalTileIndex
from ..types import SizeType
from .distribution import Distribution


@dataclasses.dataclass(frozen=True)
class SubTileSpec:
    """Origin + extent inside one tile (reference ``SubTileSpec``)."""

    origin_row: SizeType
    origin_col: SizeType
    rows: SizeType
    cols: SizeType


@dataclasses.dataclass(frozen=True)
class SubMatrixView:
    """View of the sub-matrix starting at a global element offset
    (reference ``matrix/views.h:85``)."""

    dist: Distribution
    offset: GlobalElementIndex

    def __post_init__(self):
        dlaf_assert(self.offset.row >= 0 and self.offset.col >= 0,
                    f"bad offset {self.offset}")

    @property
    def begin_tile(self) -> GlobalTileIndex:
        return self.dist.global_tile_index(self.offset)

    @property
    def origin_in_tile(self):
        """In-tile element offset of the view's origin (the static slice
        offsets the sub-panel algorithms cut tiles at)."""
        return self.dist.tile_element_index(self.offset)

    def tile_spec(self, index: GlobalTileIndex) -> SubTileSpec:
        """Portion of global tile ``index`` inside the view."""
        ts = self.dist.tile_size_of(index)
        first = self.begin_tile
        orow = self.dist.tile_element_index(self.offset).row if index.row == first.row else 0
        ocol = self.dist.tile_element_index(self.offset).col if index.col == first.col else 0
        return SubTileSpec(orow, ocol, ts.row - orow, ts.col - ocol)


@dataclasses.dataclass(frozen=True)
class SubPanelView(SubMatrixView):
    """Single-tile-wide view (reference ``matrix/views.h:129``)."""

    width: SizeType = 0

    def cols(self) -> SizeType:
        return min(self.width or self.dist.block_size.col,
                   self.dist.size.col - self.offset.col)
