"""Scalar/enum foundation types for the TPU-native DLA-Future rebuild.

TPU-native counterpart of the reference's ``include/dlaf/types.h``:

* ``Device`` / ``Backend`` enums (reference ``types.h:30-60``) — here the device
  zoo is {CPU, TPU}: CPU is the host/XLA-CPU backend used for tests and the
  host-resident stages of the eigensolver pipeline (band→tridiag bulge chasing,
  secular-equation solves), TPU is the accelerator backend.
* Default device/backend mappings (reference ``types.h:75-106``).
* Element-type machinery and the *flop-weight model* used for GFLOPS reporting
  (reference ``types.h:120-131,158-161``): a complex multiply counts 6 real ops
  and a complex add counts 2.

Everything here is pure Python with no JAX dependency at import time so that
index math and configuration can be used host-side without touching a device.
"""

from __future__ import annotations

import enum
from typing import Union

import numpy as np

#: Signed size type used for all element/tile indices (reference
#: ``types.h:24-28`` uses ``std::ptrdiff_t``). Python ints are unbounded; the
#: alias documents intent at API boundaries.
SizeType = int


class Device(enum.Enum):
    """Where data lives (reference ``types.h:30-38``), extended with TPU."""

    CPU = "cpu"
    TPU = "tpu"

    def __str__(self) -> str:  # matches reference operator<< spelling
        return self.value


class Backend(enum.Enum):
    """Which execution backend runs kernels (reference ``types.h:40-60``).

    ``MC`` (multicore host, via XLA-CPU) mirrors the reference's ``Backend::MC``;
    ``TPU`` replaces ``Backend::GPU``.
    """

    MC = "mc"
    TPU = "tpu"

    def __str__(self) -> str:
        return self.value


def default_device(backend: Backend) -> Device:
    """``DefaultDevice_v`` mapping (reference ``types.h:75-90``)."""
    return {Backend.MC: Device.CPU, Backend.TPU: Device.TPU}[backend]


def default_backend(device: Device) -> Backend:
    """``DefaultBackend_v`` mapping (reference ``types.h:92-106``)."""
    return {Device.CPU: Backend.MC, Device.TPU: Backend.TPU}[device]


# ---------------------------------------------------------------------------
# Element types
# ---------------------------------------------------------------------------

#: The four scalar types every algorithm is instantiated over, keyed by the
#: single-letter BLAS naming convention used by the miniapps (s/d/c/z).
ELEMENT_TYPES = {
    "s": np.float32,
    "d": np.float64,
    "c": np.complex64,
    "z": np.complex128,
}

_LETTER = {np.dtype(v): k for k, v in ELEMENT_TYPES.items()}


def type_letter(dtype) -> str:
    """BLAS letter (s/d/c/z) for a dtype, used in benchmark output lines."""
    return _LETTER[np.dtype(dtype)]


def is_complex(dtype) -> bool:
    return np.dtype(dtype).kind == "c"


def base_float(dtype):
    """Real scalar type underlying ``dtype`` (``BaseType`` in the reference)."""
    return {np.dtype(np.float32): np.float32,
            np.dtype(np.float64): np.float64,
            np.dtype(np.complex64): np.float32,
            np.dtype(np.complex128): np.float64}[np.dtype(dtype)]


def complex_of(dtype):
    """Complex scalar type with the same base precision."""
    return {np.dtype(np.float32): np.complex64,
            np.dtype(np.float64): np.complex128,
            np.dtype(np.complex64): np.complex64,
            np.dtype(np.complex128): np.complex128}[np.dtype(dtype)]


# ---------------------------------------------------------------------------
# Flop-weight model (reference types.h:120-131 ``TypeInfo::ops_add/ops_mul``
# and types.h:158-161 ``total_ops``)
# ---------------------------------------------------------------------------

def ops_weights(dtype) -> tuple[int, int]:
    """(add_weight, mul_weight) in real flops for one add/mul of ``dtype``."""
    return (2, 6) if is_complex(dtype) else (1, 1)


def total_ops(dtype, add: float, mul: float) -> float:
    """Total real-op count for ``add`` additions and ``mul`` multiplications.

    Mirrors ``dlaf::total_ops`` (reference ``types.h:158-161``): complex
    weighting add=2, mul=6. The miniapps feed this with the canonical flop
    models (e.g. Cholesky: add=mul=N^3/6).
    """
    wa, wm = ops_weights(dtype)
    return wa * add + wm * mul


def ceil_div(num: SizeType, den: SizeType) -> SizeType:
    """Integer ceiling division (reference ``util_math.h::ceilDiv``)."""
    if den <= 0:
        raise ValueError(f"ceil_div: denominator must be positive, got {den}")
    if num < 0:
        raise ValueError(f"ceil_div: numerator must be non-negative, got {num}")
    return -(-num // den)


ScalarLike = Union[int, float, complex]


def telescope_segments(steps: int, min_chunk: int = 8,
                       max_segments: int = 8):
    """Segment lengths for the telescoped ``lax.scan`` formulations:
    EQUAL chunks of ``max(min_chunk, ceil(steps / max_segments))`` steps
    (last chunk ragged). Each segment re-traces the step body on the
    shrinking trailing region, so the uniform masked work tracks the
    live block. Equal chunks dominate geometric halving at the same
    program count (halving spends half the steps at FULL size): work
    ratio vs the exact cubic schedule is ~1 + 3c/(2·steps) — 1.29x at
    64 steps / 1.20x at 128 (vs 1.7x halving, 3.0x for one full-size
    scan) — at <= max_segments + 1 compiled step bodies."""
    if steps <= 0:
        return ()
    c = max(min_chunk, -(-steps // max_segments))
    segs = [c] * (steps // c)
    if steps % c:
        segs.append(steps % c)
    return tuple(segs)


def telescope_windows(steps: int, window_fn):
    """Coalesced ``(window, start, length)`` segments for the telescoped
    scan builders — the single owner of the segment-building loop shared
    by Cholesky, triangular solve/multiply, reduction_to_band and its
    back-transform. ``window_fn(pos, seg_len)`` maps a segment (first
    step index, length) to a hashable window descriptor (slot offsets /
    extents); adjacent segments with equal descriptors merge into one
    scan so no two identically-shaped step programs are compiled."""
    segs = []
    pos = 0
    for seg_len in telescope_segments(steps):
        win = window_fn(pos, seg_len)
        if segs and segs[-1][0] == win:
            segs[-1] = (win, segs[-1][1], segs[-1][2] + seg_len)
        else:
            segs.append((win, pos, seg_len))
        pos += seg_len
    return segs
