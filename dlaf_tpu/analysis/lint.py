"""AST-based repo-convention linter (docs/static_analysis.md).

The conventions this repo runs on — knobs are ``Configuration`` fields
with env/CLI layering, trace-time metric mutation is guarded so programs
stay zero-cost with metrics off, the algorithm layers never touch
``np.*`` on traced values, host syncs live only where a host sync is the
point — were enforced by reviewer memory. This linter makes them
machine-checked:

``lint-unregistered-knob``
    A literal ``DLAF_<NAME>`` environment read inside ``dlaf_tpu/``
    whose ``<name>`` is not a registered ``Configuration`` field: an
    unlayered side-channel knob that ``--dlaf:`` CLI flags, the struct
    API, and ``print_config`` cannot see.

``lint-unguarded-traced-metric``
    Metric mutation (``...counter(...).inc/observe``) in the traced
    layers (``algorithms/``, ``comm/``, ``eigensolver/``,
    ``tile_ops/``) in a function with no ``metrics_active()`` guard.
    The documented trace-time pattern (see ``comm.collectives._record``)
    keeps instrumented call sites zero-allocation no-ops when metrics
    are off.

``lint-np-in-traced``
    ``np.*`` applied to a parameter of a traced function in
    ``algorithms/``/``eigensolver/`` (functions decorated with
    ``jax.jit``, or nested defs inside a ``_build_*`` builder — the
    traced program bodies). Host numpy on traced values either silently
    constant-folds the tracer era value or raises at trace time;
    trace-time numpy on *static* index math (builder-level, outside the
    program body) is fine and not flagged. Dataflow is approximated one
    hop: only direct uses of the traced function's own parameters are
    flagged — precise, no false positives, and exactly the shape a
    refactor accident takes.

``lint-host-sync``
    ``jax.device_get`` / ``.block_until_ready()`` / ``print()`` outside
    the allow-listed host-boundary sites (miniapps, obs, sync modules,
    printing/memory utilities, the tridiag host-control stage, config's
    ``print_config``). Hot-path library code must stay asynchronous.

``lint-suppression-reason``
    A ``# dlaf: disable=RULE`` comment with no parenthesized reason:
    every suppression must say why, or it rots.

Suppression: append ``# dlaf: disable=RULE(reason)`` to the offending
line (any line of a multi-line statement). The reason is mandatory; the
comment suppresses only that rule on that line. Only real comment
tokens count — docstrings and string literals quoting the syntax, like
this one, are ignored.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding

#: Paths (posix, repo-root-relative prefixes) where each rule applies.
TRACED_DIRS = ("dlaf_tpu/algorithms/", "dlaf_tpu/comm/",
               "dlaf_tpu/eigensolver/", "dlaf_tpu/tile_ops/")
NP_TRACED_DIRS = ("dlaf_tpu/algorithms/", "dlaf_tpu/eigensolver/")

#: Sites where a host sync IS the contract (drivers print results, sync
#: modules block by definition, the obs layer is the host boundary, the
#: tridiag D&C control loop is the documented host-sequential stage —
#: docs/eigensolver_perf.md).
HOST_SYNC_ALLOWED = (
    "dlaf_tpu/miniapp/", "dlaf_tpu/obs/", "dlaf_tpu/config.py",
    "dlaf_tpu/common/sync.py", "dlaf_tpu/comm/sync.py",
    "dlaf_tpu/matrix/printing.py", "dlaf_tpu/matrix/memory.py",
    "dlaf_tpu/eigensolver/tridiag_solver.py",
    "dlaf_tpu/native/", "dlaf_tpu/tpu_info.py",
    # the analysis layer itself is a host-side CLI/reporting tool
    "dlaf_tpu/analysis/",
    # the serving front end IS the host boundary: the queue assembles
    # batches on host, evaluates deadlines against a host clock, and
    # fences dispatches for honest per-request latency records
    # (docs/serving.md) — its syncs are the contract, not a leak
    "dlaf_tpu/serve/",
)

#: Literal DLAF_* env names that are deliberately NOT Configuration
#: fields. Keep this list short and justified; prefer an in-code
#: ``# dlaf: disable=lint-unregistered-knob(reason)`` for one-off test
#: hooks so the justification sits next to the read.
NON_KNOB_ENV: Set[str] = set()

_SUPPRESS_RE = re.compile(
    r"#\s*dlaf:\s*disable=([A-Za-z0-9_-]+)\s*(\(([^)]*)\))?")

_ENV_READ_FUNCS = {"get", "setdefault", "pop"}


def _config_knob_names() -> Set[str]:
    """Registered Configuration field names (no jax import needed)."""
    from dlaf_tpu.config import Configuration

    return {f.name for f in dataclasses.fields(Configuration)}


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------

def _attr_chain(node) -> List[str]:
    """['obs', 'counter'] for ``obs.counter``; [] for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("<expr>")
    return list(reversed(parts))


def _contains_name(node, name: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == name:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == name:
            return True
    return False


def _is_env_read(call: ast.Call) -> Optional[str]:
    """The literal env-var name read by this call, if it is one."""
    chain = _attr_chain(call.func)
    literal = None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        literal = call.args[0].value
    if chain[-2:] in (["environ", f] for f in _ENV_READ_FUNCS) \
            or chain[-2:] == ["os", "getenv"]:
        return literal
    return None


def _env_subscript_name(node: ast.Subscript) -> Optional[str]:
    # Load context only: os.environ["DLAF_X"] = v is a WRITE (propagating
    # a setting to a child process), not an unregistered-knob read
    if not isinstance(node.ctx, ast.Load):
        return None
    chain = _attr_chain(node.value)
    if chain[-1:] == ["environ"] and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, str):
        return node.slice.value
    return None


def _decorated_jit(fn: ast.FunctionDef) -> bool:
    return any(_contains_name(d, "jit") for d in fn.decorator_list)


@dataclasses.dataclass
class _Scope:
    """Lexical function-nesting info for every AST node."""

    parents: Dict[int, ast.AST]

    def chain(self, node) -> List[ast.FunctionDef]:
        """Enclosing FunctionDefs, innermost first."""
        out = []
        cur = self.parents.get(id(node))
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(id(cur))
        return out

    def traced_function(self, node) -> Optional[ast.FunctionDef]:
        """The innermost enclosing function whose body is traced: a
        jit-decorated def, or any def nested inside a ``_build_*``
        builder (the program bodies the builders return)."""
        chain = self.chain(node)
        for i, fn in enumerate(chain):
            if _decorated_jit(fn):
                return chain[0]
            if fn.name.startswith("_build_") and i > 0:
                # node sits in a def nested inside the builder
                return chain[0]
        return None


def _parent_map(tree) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


# ---------------------------------------------------------------------------
# Per-file lint
# ---------------------------------------------------------------------------

def _suppressions(src: str) -> Tuple[Dict[int, Set[str]], List[Finding]]:
    """Line -> suppressed rules, plus findings for reason-less ones.

    Scans real COMMENT tokens only (tokenize, not raw lines), so a
    docstring or string literal QUOTING the suppression syntax is
    neither a phantom bare-suppression finding nor a silent suppressor.
    Tokenization errors end the scan early; such files surface as
    ``lint-syntax-error`` from the AST parse."""
    import io
    import tokenize

    by_line: Dict[int, Set[str]] = {}
    bad: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type != tokenize.COMMENT:
                continue
            for m in _SUPPRESS_RE.finditer(tok.string):
                rule, reason = m.group(1), (m.group(3) or "").strip()
                if reason:
                    by_line.setdefault(tok.start[0], set()).add(rule)
                else:
                    bad.append((tok.start[0], rule))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return by_line, bad


def lint_source(src: str, path: str) -> List[Finding]:
    """All lint findings for one file's source. ``path`` must be the
    repo-root-relative posix path — the rules scope on it."""
    path = path.replace(os.sep, "/")
    findings: List[Finding] = []
    suppressed, bare = _suppressions(src)

    def emit(rule: str, node, message: str, detail: str) -> None:
        lineno = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", None) or lineno
        # a multi-line statement is suppressible from any of its lines
        if any(rule in suppressed.get(ln, ()) for ln in range(lineno, end + 1)):
            return
        findings.append(Finding(rule, f"{path}:{lineno}", message,
                                key_detail=f"{path}|{detail}"))

    for lineno, rule in bare:
        node = ast.Constant(value=None)
        node.lineno = lineno
        emit("lint-suppression-reason", node,
             f"suppression of {rule} carries no (reason) — say why or "
             f"remove it", f"bare-suppression|{rule}")

    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        findings.append(Finding("lint-syntax-error", f"{path}:{e.lineno}",
                                f"file does not parse: {e.msg}",
                                key_detail=f"{path}|syntax"))
        return findings

    scope = _Scope(_parent_map(tree))
    knobs = _config_knob_names()
    in_traced_dirs = path.startswith(TRACED_DIRS)
    in_np_dirs = path.startswith(NP_TRACED_DIRS)
    host_sync_applies = (path.startswith("dlaf_tpu/")
                        and not path.startswith(HOST_SYNC_ALLOWED))

    for node in ast.walk(tree):
        # ---- lint-unregistered-knob ----
        env_name = None
        if isinstance(node, ast.Call):
            env_name = _is_env_read(node)
        elif isinstance(node, ast.Subscript):
            env_name = _env_subscript_name(node)
        if env_name and env_name.startswith("DLAF_") \
                and env_name not in NON_KNOB_ENV \
                and env_name[len("DLAF_"):].lower() not in knobs:
            emit("lint-unregistered-knob", node,
                 f"env read of {env_name} which is not a registered "
                 f"Configuration field — unlayered side-channel knob "
                 f"(register it in dlaf_tpu/config.py or suppress with "
                 f"a reason)", f"knob|{env_name}")

        if not isinstance(node, ast.Call):
            continue

        # ---- lint-unguarded-traced-metric ----
        if in_traced_dirs and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("inc", "observe"):
            recv = node.func.value
            is_metric = isinstance(recv, ast.Call) and \
                _attr_chain(recv.func)[-1:] in (["counter"], ["gauge"],
                                                ["histogram"])
            if is_metric:
                fns = scope.chain(node)
                guarded = any(_contains_name(fn, "metrics_active")
                              for fn in fns)
                if not guarded:
                    emit("lint-unguarded-traced-metric", node,
                         "metric mutation in a traced layer without a "
                         "metrics_active() guard — use the trace-time "
                         "pattern (comm.collectives._record)",
                         f"metric|{_attr_chain(recv.func)[-1]}|"
                         f"{fns[0].name if fns else '<module>'}")

        # ---- lint-np-in-traced ----
        if in_np_dirs:
            chain = _attr_chain(node.func)
            if len(chain) >= 2 and chain[0] == "np":
                traced = scope.traced_function(node)
                if traced is not None:
                    params = {a.arg for a in traced.args.args
                              + traced.args.posonlyargs
                              + traced.args.kwonlyargs}
                    used = {sub.id for arg in node.args
                            for sub in ast.walk(arg)
                            if isinstance(sub, ast.Name)}
                    hit = params & used
                    if hit:
                        emit("lint-np-in-traced", node,
                             f"np.{'.'.join(chain[1:])} applied to traced "
                             f"parameter(s) {sorted(hit)} of "
                             f"{traced.name}() — use jnp inside traced "
                             f"code",
                             f"np|{traced.name}|{'.'.join(chain[1:])}")

        # ---- lint-host-sync ----
        if host_sync_applies:
            chain = _attr_chain(node.func)
            sync_kind = None
            if chain[-1:] == ["device_get"]:
                sync_kind = "jax.device_get"
            elif chain[-1:] == ["block_until_ready"]:
                sync_kind = ".block_until_ready()"
            elif chain == ["print"]:
                sync_kind = "print"
            if sync_kind:
                fns = scope.chain(node)
                emit("lint-host-sync", node,
                     f"{sync_kind} outside the allow-listed host-boundary "
                     f"sites — hot-path library code must stay async "
                     f"(allowlist in analysis/lint.py, or suppress with "
                     f"a reason)",
                     f"sync|{sync_kind}|{fns[0].name if fns else '<module>'}")

    return findings


# ---------------------------------------------------------------------------
# Repo walk
# ---------------------------------------------------------------------------

def iter_py_files(root: str, subdirs: Sequence[str] = ("dlaf_tpu",),
                  ) -> Iterable[str]:
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def run(root: str = ".", subdirs: Sequence[str] = ("dlaf_tpu",),
        ) -> List[Finding]:
    """Lint every ``.py`` file under ``root``'s ``subdirs``. An empty
    walk raises: zero files scanned must never report as a clean gate
    (a wrong ``--root`` would otherwise silently disable the linter)."""
    findings: List[Finding] = []
    paths = list(iter_py_files(root, subdirs))
    if not paths:
        raise FileNotFoundError(
            f"no .py files under {root!r} subdirs {tuple(subdirs)} — "
            f"wrong --root? the lint gate refuses to pass vacuously")
    for path in paths:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        rel = os.path.relpath(path, root)
        findings.extend(lint_source(src, rel))
    return findings
