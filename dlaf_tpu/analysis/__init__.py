"""Static-analysis layer: jaxpr graph auditor + repo-convention linter.

The reference DLA-Future leans on compiler-enforced invariants (its
sender/receiver typing makes a mis-ordered collective a type error);
the Python/JAX port lost that compiler, and until this package existed
its hardest guarantees lived in bespoke per-test jaxpr walkers and
reviewer memory. This layer restores them as machine-checked rules
(docs/static_analysis.md):

* :mod:`.depgraph` — the shared jaxpr dependency/traversal vocabulary
  (transitive closures, emission order, collective enumeration,
  scan-body descent) the structural test pins are written in.
* :mod:`.graphcheck` — traces every builder (unrolled/scan x local/dist
  x uplo x knob combos) abstractly on virtual meshes and audits
  semantic invariants: no conditional (rank-varying) collectives, no
  host callbacks in hot paths, no silent f64->f32 demotion on the
  native routes, no dead scan carries / dropped scan outputs, no
  materialized intermediates blowing past a configurable multiple of
  the program's input bytes.
* :mod:`.lint` — an AST convention linter: config knobs must be
  registered ``Configuration`` fields, traced-code metric mutation must
  use the guarded trace-time pattern, no ``np.*`` on traced values in
  the algorithm layers, host syncs (``jax.device_get``/``print``) only
  at allow-listed sites. ``# dlaf: disable=RULE(reason)`` suppresses a
  finding on its line — the reason is mandatory.
* ``python -m dlaf_tpu.analysis`` — the CI gate: runs both, diffs
  against the committed ``.analysis_baseline.json``, exits 1 on any new
  finding. ``--drill`` runs the seeded-bad must-trip programs
  (:mod:`.drills`) that prove the gate can fail.

Import note: this module stays jax-free at import time so the CLI can
force the virtual CPU device count before jax loads (same constraint as
tests/conftest.py).
"""

from .findings import (Finding, diff_baseline, load_baseline,  # noqa: F401
                       write_baseline)

#: Repo-root-relative path of the committed findings baseline.
BASELINE_PATH = ".analysis_baseline.json"
