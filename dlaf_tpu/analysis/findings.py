"""Findings + committed-baseline workflow for the static-analysis layer.

A :class:`Finding` is one rule violation at one site. The gate semantics
mirror the bench/accuracy gates (scripts/bench_gate.py,
scripts/accuracy_gate.py): a committed baseline file grandfathers the
findings that predate a rule, and CI fails on any finding NOT in the
baseline — so the codebase can only get cleaner. The baseline is keyed
on ``rule|site|detail`` (not line numbers), so unrelated edits that move
code around do not churn it; ``site`` carries the line only for the
human report.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``rule``   — stable rule id (``graph-*`` from the jaxpr auditor,
                 ``lint-*`` from the AST linter).
    ``site``   — where: ``path:line`` for lint, the program spec name
                 (e.g. ``cholesky.dist.unrolled.L``) for graph checks.
    ``message``— human-readable description, printed in reports.
    ``key_detail`` — the stable identity tail; defaults to the message.
                 Lint findings override it with a line-number-free form
                 so editing an unrelated part of a file cannot churn
                 the baseline.
    """

    rule: str
    site: str
    message: str
    key_detail: Optional[str] = None

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.key_detail if self.key_detail is not None else self.site}"

    def __str__(self) -> str:
        return f"{self.site}: [{self.rule}] {self.message}"


def load_baseline(path: str) -> List[str]:
    """Read the committed baseline: a JSON document
    ``{"findings": [key, ...]}``. A missing file is an empty baseline."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return []
    if not isinstance(doc, dict) or not isinstance(doc.get("findings"), list):
        raise ValueError(f"{path}: baseline must be {{'findings': [...]}}")
    keys = doc["findings"]
    if not all(isinstance(k, str) for k in keys):
        raise ValueError(f"{path}: baseline keys must be strings")
    return keys


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    doc = {
        "comment": "Grandfathered dlaf_tpu.analysis findings. CI fails on "
                   "any finding not listed here; remove entries as the "
                   "underlying issue is fixed (docs/static_analysis.md).",
        "findings": sorted({f.key for f in findings}),
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def diff_baseline(findings: Sequence[Finding], baseline: Sequence[str],
                  ) -> Tuple[List[Finding], List[str]]:
    """(new findings not grandfathered, stale baseline keys no longer
    observed). New findings fail the gate; stale keys are reported so
    the baseline shrinks as code is fixed."""
    base = set(baseline)
    new = [f for f in findings if f.key not in base]
    seen = {f.key for f in findings}
    stale = sorted(base - seen)
    return new, stale
