"""Reusable jaxpr dependency/traversal library (docs/static_analysis.md).

The repo's hardest invariants — look-ahead overlap, collective
independence from the bulk trailing product, callback-free hot paths —
are properties of the *traced program*, not of any single execution.
Until this module existed, each test file that pinned one of them grew
its own jaxpr walker (producer maps, transitive closures, shard_map body
extraction); this is the shared vocabulary those pins — and the
:mod:`dlaf_tpu.analysis.graphcheck` auditor — are written in.

Everything here operates on traced jaxprs only: :func:`jax.make_jaxpr`
over ``ShapeDtypeStruct`` arguments (abstract eval — no compile, no
execution, the same trick ``scripts/mfu_table.py`` uses for its virtual-
mesh ICI traces), so the whole library runs on any host, accelerator or
not.

Terminology: an *eqn list* is the flat ``jaxpr.eqns`` of one (sub)jaxpr.
Closure/position/dependency queries are *flat* — they see one eqn list
and treat control-flow eqns (scan, cond, pjit, ...) as opaque nodes.
:func:`iter_eqns` is the *recursive* walk that descends into every
sub-jaxpr and reports the control-flow path it took to reach each eqn.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Iterator, Sequence, Tuple, Union

import jax
from jax import core as jax_core

#: Cross-device collective primitives, as spelled in this jax line's
#: jaxprs (``lax.psum`` -> ``psum``; ``bcast``'s mask+psum realization is
#: therefore counted as a psum, which is exactly what the program runs).
COLLECTIVE_PRIMS = frozenset({
    "psum", "all_gather", "all_to_all", "ppermute", "reduce_scatter",
    "psum_scatter", "pmax", "pmin",
})

#: Host-callback / host-transfer primitives that must never appear in a
#: hot-path program: each one stalls the device on a host round trip
#: (the class of bug ``jax.transfer_guard`` catches dynamically; here it
#: is pinned statically on the traced program).
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_callback_call",
})

#: Control-flow primitives whose sub-jaxprs execute *conditionally* — a
#: collective under one of these can run on a subset of ranks only,
#: which on SPMD hardware is the deadlock class (every rank must issue
#: every collective in the same order). ``scan`` is NOT here: its trip
#: count is a trace-time constant, identical on every rank.
CONDITIONAL_PRIMS = frozenset({"cond", "while"})

Predicate = Callable[[jax_core.JaxprEqn], bool]


def _as_predicate(pred: Union[str, Predicate]) -> Predicate:
    """Accept a primitive name as shorthand for an eqn predicate."""
    if isinstance(pred, str):
        name = pred
        return lambda e: e.primitive.name == name
    return pred


# ---------------------------------------------------------------------------
# Tracing entry points
# ---------------------------------------------------------------------------

def trace(fn, *args) -> jax_core.ClosedJaxpr:
    """Trace ``fn`` abstractly (``jax.make_jaxpr``) — args may be real
    arrays or ``jax.ShapeDtypeStruct`` placeholders; nothing compiles or
    executes."""
    return jax.make_jaxpr(fn)(*args)


def _jaxpr_of(obj) -> jax_core.Jaxpr:
    """The plain ``Jaxpr`` behind a ClosedJaxpr / Jaxpr."""
    return getattr(obj, "jaxpr", obj)


def shard_map_body(fn_or_jaxpr, *args) -> list:
    """Eqn list of the single ``shard_map`` body of a traced program.

    Accepts either an already-traced (Closed)Jaxpr, or a callable plus
    its (abstract) arguments. Exactly one shard_map eqn must exist at
    the top level — the shape of every distributed builder in this repo.
    """
    if callable(fn_or_jaxpr):
        fn_or_jaxpr = trace(fn_or_jaxpr, *args)
    jaxpr = _jaxpr_of(fn_or_jaxpr)
    matches = [e for e in jaxpr.eqns if "shard_map" in e.primitive.name]
    if len(matches) != 1:
        raise ValueError(
            f"expected exactly one shard_map eqn, found {len(matches)} "
            f"among {[e.primitive.name for e in jaxpr.eqns]}")
    inner = matches[0].params["jaxpr"]
    return list(_jaxpr_of(inner).eqns)


def scan_eqns(eqns: Sequence) -> list:
    """All ``lax.scan`` eqns among ``eqns`` (flat — no descent)."""
    return [e for e in eqns if e.primitive.name == "scan"]


def scan_body(eqns: Sequence, index: int = 0) -> list:
    """Body eqn list of the ``index``-th scan among ``eqns``.

    The scan builders telescope their k-loop into segments — one scan
    eqn per segment; ``index`` selects which segment's body to inspect
    (the pins use the first).
    """
    scans = scan_eqns(eqns)
    if not scans:
        raise ValueError("no scan in traced program")
    return list(_jaxpr_of(scans[index].params["jaxpr"]).eqns)


# ---------------------------------------------------------------------------
# Flat dependency queries
# ---------------------------------------------------------------------------

def producers(eqns: Sequence) -> dict:
    """Map each output var to the eqn that produces it (within ``eqns``)."""
    out = {}
    for e in eqns:
        for v in e.outvars:
            out[v] = e
    return out


def closure(eqns: Sequence, seed_vars) -> list:
    """Transitive producer closure of ``seed_vars`` within ``eqns``:
    every eqn whose outputs the seeds (transitively) depend on. Literals
    terminate the walk; vars produced outside ``eqns`` (jaxpr inputs,
    outer-scope consts) have no producer here and terminate it too."""
    prods = producers(eqns)
    seen: set = set()
    todo = list(seed_vars)
    out = []
    while todo:
        v = todo.pop()
        if isinstance(v, jax_core.Literal):
            continue
        e = prods.get(v)
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        out.append(e)
        todo.extend(e.invars)
    return out


def depends_on(eqns: Sequence, eqn_or_index, pred: Union[str, Predicate],
               ) -> bool:
    """True iff the eqn (given directly or by flat index) transitively
    depends — through producers within ``eqns`` — on an eqn matching
    ``pred`` (a predicate or a primitive name)."""
    e = eqns[eqn_or_index] if isinstance(eqn_or_index, int) else eqn_or_index
    pred = _as_predicate(pred)
    return any(pred(d) for d in closure(eqns, e.invars))


def positions(eqns: Sequence, pred: Union[str, Predicate]) -> list:
    """Flat emission-order indices of eqns matching ``pred`` (predicate
    or primitive name). Emission order is what XLA's scheduler sees —
    the pins on "collective emitted BEFORE the bulk product" compare
    exactly these indices."""
    pred = _as_predicate(pred)
    return [i for i, e in enumerate(eqns) if pred(e)]


def is_bulk_dot(e, rank: int = 4) -> bool:
    """The bulk trailing product of every distributed builder under test
    is the only ``dot_general`` with a ``rank``-D (tile-pair grid)
    output; panel solves, strips and W/M products are lower-rank. The
    local builders' bulk is the square 2-D trailing dot — pass
    ``rank=2`` and filter by shape at the call site."""
    return (e.primitive.name == "dot_general"
            and len(e.outvars[0].aval.shape) == rank)


# ---------------------------------------------------------------------------
# Recursive walk
# ---------------------------------------------------------------------------

def subjaxprs(eqn) -> Iterator[Tuple[str, jax_core.Jaxpr]]:
    """(label, jaxpr) pairs for every sub-jaxpr of ``eqn``'s params —
    scan/pjit/shard_map bodies, cond branches, while cond/body, custom
    call rules — discovered generically so new primitives keep walking."""
    for key, val in eqn.params.items():
        if isinstance(val, (jax_core.Jaxpr, jax_core.ClosedJaxpr)):
            yield key, _jaxpr_of(val)
        elif isinstance(val, (tuple, list)):
            for i, item in enumerate(val):
                if isinstance(item, (jax_core.Jaxpr, jax_core.ClosedJaxpr)):
                    yield f"{key}[{i}]", _jaxpr_of(item)


def iter_eqns(eqns_or_jaxpr, path: Tuple[Tuple[str, str], ...] = (),
              ) -> Iterator[Tuple[Tuple[Tuple[str, str], ...],
                                  jax_core.JaxprEqn]]:
    """Depth-first walk over every eqn, descending into all sub-jaxprs.

    Yields ``(path, eqn)`` where ``path`` is a tuple of
    ``(primitive_name, param_label)`` frames for each enclosing
    control-flow eqn — e.g. a collective traced inside a cond branch
    inside a shard_map body arrives with path
    ``(("shard_map", "jaxpr"), ("cond", "branches[1]"))``.
    """
    if not isinstance(eqns_or_jaxpr, (list, tuple)):
        eqns_or_jaxpr = _jaxpr_of(eqns_or_jaxpr).eqns
    for e in eqns_or_jaxpr:
        yield path, e
        for label, sub in subjaxprs(e):
            yield from iter_eqns(sub.eqns,
                                 path + ((e.primitive.name, label),))


def path_has_conditional(path) -> bool:
    """True if any frame of an :func:`iter_eqns` path is a conditionally-
    executed control-flow primitive (cond branch / while body)."""
    return any(name in CONDITIONAL_PRIMS for name, _ in path)


# ---------------------------------------------------------------------------
# Collective / callback enumeration
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective eqn of a traced program, with the static facts a
    schedule-uniformity or traffic audit needs."""

    kind: str                 #: primitive name (psum, all_gather, ...)
    axes: Tuple[str, ...]     #: mesh axis names it communicates over
    shape: Tuple[int, ...]    #: operand shape
    dtype: str                #: operand dtype name
    path: Tuple               #: iter_eqns control-flow path
    eqn: jax_core.JaxprEqn = dataclasses.field(compare=False, repr=False)

    @property
    def conditional(self) -> bool:
        return path_has_conditional(self.path)

    @property
    def nbytes(self) -> int:
        import numpy as np

        size = 1
        for d in self.shape:
            size *= int(d)
        return size * np.dtype(self.dtype).itemsize


def _collective_axes(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def is_collective(e) -> bool:
    return e.primitive.name in COLLECTIVE_PRIMS


def collectives(eqns_or_jaxpr, descend: bool = True) -> list:
    """Enumerate collectives as :class:`Collective` records, in emission
    order. ``descend=False`` restricts to the given flat eqn list."""
    walk = (iter_eqns(eqns_or_jaxpr) if descend
            else (((), e) for e in eqns_or_jaxpr))
    out = []
    for path, e in walk:
        if is_collective(e):
            aval = e.invars[0].aval
            out.append(Collective(
                kind=e.primitive.name, axes=_collective_axes(e),
                shape=tuple(aval.shape), dtype=str(aval.dtype),
                path=path, eqn=e))
    return out


def callbacks(eqns_or_jaxpr) -> list:
    """Every host-callback/transfer eqn in the program (recursive walk),
    as (path, eqn) pairs — must be empty for hot-path programs."""
    return [(path, e) for path, e in iter_eqns(eqns_or_jaxpr)
            if e.primitive.name in CALLBACK_PRIMS]


def contains_primitive(eqns_or_jaxpr, names) -> bool:
    """True if any eqn (recursive) has a primitive named in ``names``."""
    if isinstance(names, str):
        names = {names}
    names = set(names)
    return any(e.primitive.name in names for _, e in iter_eqns(eqns_or_jaxpr))


# ---------------------------------------------------------------------------
# Scan carry analysis
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CarrySlot:
    """One carry slot of a scan eqn: whether the body reads it, whether
    it passes through unchanged, and whether the stacked/final outputs
    are consumed by the outer program."""

    index: int          #: carry position (0-based, after num_consts)
    read: bool          #: some body eqn consumes the carry invar
    passthrough: bool   #: body outvar is the same var as the invar
    out_dropped: bool   #: the outer scan outvar for this slot is DropVar

    @property
    def dead(self) -> bool:
        """A slot the body never reads and never rewrites: it does no
        work across iterations — a closed-over constant in disguise (or
        a dropped carry left behind by a refactor)."""
        return not self.read and self.passthrough


def scan_carry_slots(scan_eqn) -> list:
    """Analyze every carry slot of one scan eqn (see :class:`CarrySlot`)."""
    body = _jaxpr_of(scan_eqn.params["jaxpr"])
    num_consts = scan_eqn.params["num_consts"]
    num_carry = scan_eqn.params["num_carry"]
    carry_invars = body.invars[num_consts:num_consts + num_carry]
    carry_outvars = body.outvars[:num_carry]
    consumed = set()
    for e in body.eqns:
        for v in e.invars:
            if not isinstance(v, jax_core.Literal):
                consumed.add(id(v))
    # a carry returned at a *different* position still flows somewhere
    # (check every occurrence — a var can be passthrough at its own slot
    # AND feed a later slot, which is a read)
    out_ids = [id(getattr(v, "val", v)) for v in carry_outvars]
    slots = []
    for i, (iv, ov) in enumerate(zip(carry_invars, carry_outvars)):
        read = id(iv) in consumed or any(
            oid == id(iv) and j != i for j, oid in enumerate(out_ids))
        slots.append(CarrySlot(
            index=i, read=read,
            passthrough=getattr(ov, "val", ov) is iv,
            out_dropped=isinstance(scan_eqn.outvars[i], jax_core.DropVar)))
    return slots


def dropped_outputs(scan_eqn) -> list:
    """Indices of stacked (ys) outputs of ``scan_eqn`` nobody consumes
    (DropVar in the outer eqn): per-iteration work the program computes
    and throws away."""
    num_carry = scan_eqn.params["num_carry"]
    return [i for i, v in enumerate(scan_eqn.outvars[num_carry:])
            if isinstance(v, jax_core.DropVar)]


# ---------------------------------------------------------------------------
# Per-step scope structure (ISSUE 16)
# ---------------------------------------------------------------------------

#: The per-step ``named_scope`` convention every pipelined builder
#: annotates with (``<algo>.step<k>.<phase>``, obs.named_span) and the
#: index-free scan form (``<algo>.scanstep[.<phase>]``, obs.scoped_step).
#: Kept textually identical to obs.critpath's HLO-side patterns — the
#: jaxpr name stack and the compiled op_name metadata carry the same
#: scopes, so the static structure here and the measured timeline there
#: join on the same keys.
STEP_SCOPE_RE = re.compile(
    r"([A-Za-z0-9_]+)\.step(\d+)(?:\.(panel|strip|bulk))?")
SCAN_SCOPE_RE = re.compile(
    r"([A-Za-z0-9_]+)\.scanstep(?:\.(panel|strip|bulk))?")


def step_scope_of(eqn) -> Tuple[str, int, str] | None:
    """``(algo, step, phase)`` of an eqn's innermost step scope, from its
    traced name stack — or ``None`` for unscoped eqns.  Scan-body scopes
    carry no index and report step ``-1``; phase defaults to ``other``
    (the scope names only the step)."""
    stack = str(getattr(eqn.source_info, "name_stack", "") or "")
    hits = list(STEP_SCOPE_RE.finditer(stack))
    if hits:
        h = hits[-1]  # innermost scope wins (comm-lookahead hoisting)
        return (h.group(1), int(h.group(2)), h.group(3) or "other")
    hits = list(SCAN_SCOPE_RE.finditer(stack))
    if hits:
        h = hits[-1]
        return (h.group(1), -1, h.group(2) or "other")
    return None


def step_groups(eqns: Sequence) -> dict:
    """Group a flat eqn list by step scope: ``{(algo, step, phase):
    [eqn, ...]}`` in emission order.  Unscoped eqns are omitted."""
    out: dict = {}
    for e in eqns:
        key = step_scope_of(e)
        if key is not None:
            out.setdefault(key, []).append(e)
    return out


def step_edges(eqns: Sequence) -> set:
    """Inter-group dependency edges of the step structure.

    ``(src, dst)`` is present when some eqn in group ``dst`` transitively
    depends — through producers within ``eqns`` — on an eqn in group
    ``src``.  This is the static step DAG the critpath joiner's
    critical-path model walks with measured walls; tests pin the
    lookahead property on it (panel k+1 must NOT depend on bulk k).
    """
    groups = step_groups(eqns)
    owner = {id(e): key for key, evs in groups.items() for e in evs}
    edges: set = set()
    for key, evs in groups.items():
        seeds = [v for e in evs for v in e.invars]
        for d in closure(eqns, seeds):
            src = owner.get(id(d))
            if src is not None and src != key:
                edges.add((src, key))
    return edges


def step_structure(eqns_or_jaxpr) -> dict:
    """Export the static per-step phase structure of a traced program:
    ``{"groups": {key: n_eqns}, "edges": [...], "algos": {algo:
    {"steps": K, "scan": bool}}}`` with keys rendered as
    ``"<algo>.step<k>.<phase>"`` strings (scan: ``"<algo>.scanstep.
    <phase>"``) — the depgraph-side mirror of obs.critpath's measured
    schedule, JSON-ready for tooling."""
    if hasattr(eqns_or_jaxpr, "eqns"):
        eqns = list(eqns_or_jaxpr.eqns)
    elif hasattr(eqns_or_jaxpr, "jaxpr"):
        eqns = list(eqns_or_jaxpr.jaxpr.eqns)
    else:
        eqns = list(eqns_or_jaxpr)
    groups = step_groups(eqns)
    edges = step_edges(eqns)

    def render(key) -> str:
        algo, step, phase = key
        stem = f"{algo}.scanstep" if step < 0 else f"{algo}.step{step:03d}"
        return f"{stem}.{phase}"

    algos: dict = {}
    for algo, step, _phase in groups:
        a = algos.setdefault(algo, {"steps": 0, "scan": False})
        if step < 0:
            a["scan"] = True
        else:
            a["steps"] = max(a["steps"], step + 1)
    return {
        "groups": {render(k): len(v) for k, v in sorted(groups.items())},
        "edges": sorted((render(a), render(b)) for a, b in edges),
        "algos": algos,
    }
