"""Seeded-bad programs that MUST trip the analysis gate.

Mirrors the bench/accuracy-gate injection drills (scripts/bench_gate.py
``--inject-slowdown``, scripts/accuracy_gate.py ``--inject``): a checker
whose failure mode has never been demonstrated is not a gate. Each drill
builds a program (or source snippet) carrying exactly one violation; CI
runs ``python -m dlaf_tpu.analysis --drill <name>`` and requires exit 1
with the expected rule named in the log (docs/static_analysis.md).

The graph drills trace real shard_map/jit programs on the virtual mesh —
the same trace path the auditor uses on the production builders — so a
drill that stops tripping means the CHECK broke, not the drill.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from . import depgraph, graphcheck, lint
from .findings import Finding


def _x64():
    """The graph drills trace f64 programs like the production builders;
    without x64 the placeholders silently truncate to f32 and the
    precision drill would audit the wrong program."""
    import jax

    jax.config.update("jax_enable_x64", True)


def _mesh22():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    graphcheck._require_devices(4)
    return Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("row", "col"))


def _rank_varying_collective() -> List[Finding]:
    """A psum only rank-row-0 executes (``lax.cond`` on ``axis_index``):
    the SPMD deadlock class graph-conditional-collective exists for."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dlaf_tpu import _compat

    def body(x):
        return lax.cond(lax.axis_index("row") == 0,
                        lambda v: lax.psum(v, "col"),
                        lambda v: v, x)

    fn = _compat.shard_map(body, mesh=_mesh22(), in_specs=P("row", "col"),
                           out_specs=P("row", "col"), check_vma=False)
    sds = jax.ShapeDtypeStruct((8, 8), jnp.float64)
    return graphcheck.audit_jaxpr("drill.rank_varying_collective",
                                  depgraph.trace(fn, sds))


def _host_callback() -> List[Finding]:
    """A ``pure_callback`` spliced into a hot-path program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def fn(x):
        y = jax.pure_callback(
            np.sin, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return x + y

    sds = jax.ShapeDtypeStruct((8, 8), jnp.float64)
    return graphcheck.audit_jaxpr("drill.host_callback",
                                  depgraph.trace(fn, sds))


def _dropped_carry() -> List[Finding]:
    """A scan carrying a slot its body never reads (and stacking an
    output nobody consumes): the dropped-carry refactor residue."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fn(x):
        def body(carry, _):
            a, dropped = carry
            a = a * 1.5
            return (a, dropped), a.sum()

        (a, _), _ys = lax.scan(body, (x, x + 1.0), None, length=4)
        return a

    sds = jax.ShapeDtypeStruct((8, 8), jnp.float64)
    return graphcheck.audit_jaxpr("drill.dropped_carry",
                                  depgraph.trace(fn, sds))


def _hbm_blowup() -> List[Finding]:
    """A broadcast-then-reduce temporary 64x the program's input bytes —
    the materialized-intermediate OOM class."""
    import jax
    import jax.numpy as jnp

    def fn(x):
        big = jnp.broadcast_to(x, (64,) + x.shape) * 2.0
        return big.sum(axis=0)

    sds = jax.ShapeDtypeStruct((16, 16), jnp.float64)
    return graphcheck.audit_jaxpr("drill.hbm_blowup",
                                  depgraph.trace(fn, sds))


def _precision_demotion() -> List[Finding]:
    """An f64 operand silently demoted to f32 for the product."""
    import jax
    import jax.numpy as jnp

    def fn(x):
        y = x.astype(jnp.float32)
        return (y @ y).astype(jnp.float64)

    sds = jax.ShapeDtypeStruct((8, 8), jnp.float64)
    return graphcheck.audit_jaxpr("drill.precision_demotion",
                                  depgraph.trace(fn, sds))


#: Seeded-bad source snippet for the lint drill: one violation per rule,
#: in a path that puts it under the traced-layer scoping
#: (dlaf_tpu/algorithms/). The bare suppression on the last function is
#: itself the violation for lint-suppression-reason. (The suppression
#: scanner reads real COMMENT tokens only, so this string literal's
#: embedded marker is invisible when THIS file is linted.)
LINT_DRILL_PATH = "dlaf_tpu/algorithms/_lint_drill.py"
LINT_DRILL_SOURCE = '''\
import os

import jax
import numpy as np

from dlaf_tpu import obs


def resolved_bad_knob():
    return os.environ.get("DLAF_TOTALLY_UNREGISTERED_KNOB", "0")


def _build_bad(dist, mesh):
    def fn(storage):
        obs.counter("dlaf_bad_steps_total", mode="bad").inc()
        return np.abs(storage)
    return fn


@jax.jit
def _bad_local(a):
    host = jax.device_get(a)
    print("peek:", host[0, 0])
    return a


def suppressed_without_reason():
    return os.environ.get("DLAF_OTHER_KNOB")  # dlaf: disable=lint-unregistered-knob
'''


def _lint_violation() -> List[Finding]:
    return lint.lint_source(LINT_DRILL_SOURCE, LINT_DRILL_PATH)


#: drill name -> (runner, rules the run MUST report)
DRILLS: Dict[str, Tuple[Callable[[], List[Finding]], Tuple[str, ...]]] = {
    "rank_varying_collective": (_rank_varying_collective,
                                ("graph-conditional-collective",)),
    "host_callback": (_host_callback, ("graph-host-callback",)),
    "dropped_carry": (_dropped_carry,
                      ("graph-dead-carry", "graph-dead-output")),
    "hbm_blowup": (_hbm_blowup, ("graph-hbm-blowup",)),
    "precision_demotion": (_precision_demotion,
                           ("graph-precision-demotion",)),
    "lint_violation": (_lint_violation,
                       ("lint-unregistered-knob",
                        "lint-unguarded-traced-metric",
                        "lint-np-in-traced", "lint-host-sync",
                        "lint-suppression-reason")),
}


def run(name: str) -> Tuple[List[Finding], Tuple[str, ...]]:
    """Run one drill; returns (findings, rules that must appear)."""
    if name not in DRILLS:
        raise KeyError(f"unknown drill {name!r}; have {sorted(DRILLS)}")
    runner, expected = DRILLS[name]
    if name != "lint_violation":
        _x64()
    return runner(), expected
