"""Jaxpr graph auditor: semantic invariants over every builder's traced
program (docs/static_analysis.md).

Traces every factorization/solve/eigensolver builder — unrolled and scan
forms, local and distributed, both uplos, the knob combos that change
program structure — abstractly (``jax.make_jaxpr`` over
``ShapeDtypeStruct`` args on a virtual CPU mesh: no compile, no
execution; the same trick as ``scripts/mfu_table.py``) and audits each
program for the invariant classes whose violation is a silent
scale-or-correctness bug:

``graph-conditional-collective``
    A collective under ``cond``/``while`` executes on a data-dependent
    subset of ranks. Since every builder is one SPMD program traced
    once, rank-variance of the collective schedule can ONLY enter
    through conditional execution — on multihost meshes this is the
    deadlock class (arXiv:2112.09017 keeps its collectives
    program-order-uniform for exactly this reason). ``scan`` bodies are
    fine: the trip count is a trace-time constant, equal on all ranks.

``graph-host-callback``
    ``pure_callback``/``io_callback``/``debug_callback``/infeed/outfeed
    inside a hot-path program stalls the device pipeline on a host
    round trip every step.

``graph-precision-demotion``
    A non-weak f64/c128 value converted to f32/bf16/f16/c64 inside a
    program traced on the NATIVE route (mxu/ozaki slicing and the mixed
    f32-seed solver are the gated exceptions — the auditor pins those
    knobs off, so any demotion it sees is silent precision loss).

``graph-dead-carry`` / ``graph-dead-output``
    A scan carry slot the body never reads and passes through unchanged
    (a dropped carry left by a refactor — it costs HBM every iteration
    and hides a value someone meant to use), or stacked scan outputs
    nobody consumes (per-iteration work thrown away).

``graph-hbm-blowup``
    Any eqn materializing an intermediate larger than ``hbm_factor``
    times the whole program's input bytes (broadcast-then-reduce
    temporaries — the class behind the session-4d N=16384 OOM).

Audited under a pinned native configuration with ``DLAF_*`` env scrubbed
(restored after), so the result is deterministic regardless of the
caller's environment.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import os
from typing import Callable, List, Optional, Sequence, Tuple

from . import depgraph
from .findings import Finding

#: Demotion targets: landing one of these from a non-weak f64/c128 value
#: loses mantissa silently.
_NARROW = {"float32", "bfloat16", "float16", "complex64"}
_WIDE = {"float64", "complex128"}

#: Default materialized-intermediate budget, as a multiple of the traced
#: program's total input bytes. The legit builders peak well under 4x
#: (the bulk trailing product and the gathered transposed panels are
#: each <= the local storage); 8x only trips on genuinely materialized
#: broadcast temporaries.
DEFAULT_HBM_FACTOR = 8.0


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One traced program to audit. ``build`` returns ``(fn, args)``
    with args as ShapeDtypeStructs; nothing is compiled."""

    name: str
    build: Callable[[], Tuple[Callable, Tuple]]
    #: no-callback rule applies (all current builders are hot paths)
    hot_path: bool = True
    #: precision-demotion rule applies (traced with the native knobs
    #: pinned, so every demotion is unexpected)
    native_route: bool = True


@contextlib.contextmanager
def pinned_native_config():
    """Scrub ``DLAF_*`` env and pin the knobs that steer trace-time
    routes to their native/serialized choices, so the audited programs
    are deterministic and the precision rule has no gated exceptions in
    scope. On exit the env is restored and the caller's ACTIVE config is
    re-installed (re-layered over the restored env) — a caller that had
    installed a struct config programmatically keeps it."""
    import dlaf_tpu.config as config

    prev = dataclasses.replace(config.get_configuration())
    saved = {k: os.environ.pop(k) for k in list(os.environ)
             if k.startswith("DLAF_")}
    try:
        config.initialize(config.Configuration(
            f64_gemm="native", f64_trsm="native", qr_panel="geqrf",
            cholesky_trailing="loop", cholesky_lookahead="0",
            comm_lookahead="0", dc_level_batch="0", bt_lookahead="0",
            hegst_impl="blocked", dist_step_mode="unrolled",
            # the traced-program matrix must audit DETERMINISTIC routes:
            # an adaptive autotune table steering mid-audit would make
            # the audited programs depend on probe history
            autotune="0",
            # panel_impl pinned to the XLA route so the precision-
            # demotion and route audits keep auditing the native path;
            # the fused route gets its OWN f32 traced-program entries
            # (program_specs *.fpanel variants, built with an explicit
            # panel_fused=True)
            panel_impl="xla", log="off"))
        yield
    finally:
        os.environ.update(saved)
        config.initialize(prev)


def _require_devices(count: int) -> None:
    import jax

    have = len(jax.devices())
    if have < count:
        raise RuntimeError(
            f"graphcheck needs >= {count} devices for its virtual meshes "
            f"but the jax platform has {have}; run via `python -m "
            f"dlaf_tpu.analysis` (which forces an 8-device virtual CPU "
            f"platform) or set XLA_FLAGS=--xla_force_host_platform_"
            f"device_count=8 before the first jax import")


def program_specs(rows: int = 2, cols: int = 2, n: int = 24, nb: int = 4,
                  ) -> List[ProgramSpec]:
    """The audited program matrix. Sizes are tiny (tracing cost only —
    the invariants are size-independent program structure); the grid is
    the 2x2 virtual mesh every structural test pin uses."""
    import jax
    import jax.numpy as jnp

    _require_devices(rows * cols)
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import (GlobalElementSize, GridSize2D,
                                         TileElementSize)
    from dlaf_tpu.matrix.distribution import Distribution
    from dlaf_tpu.matrix.tiling import storage_tile_grid

    grid = Grid(rows, cols)
    dist = Distribution(GlobalElementSize(n, n), TileElementSize(nb, nb),
                        grid_size=GridSize2D(rows, cols))
    str_, stc, _, _ = storage_tile_grid(dist)
    f64 = jnp.float64
    st = jax.ShapeDtypeStruct((str_, stc, nb, nb), f64)
    loc = jax.ShapeDtypeStruct((n, n), f64)
    alpha = jax.ShapeDtypeStruct((), f64)

    specs: List[ProgramSpec] = []

    def add(name, make, **kw):
        specs.append(ProgramSpec(name=name, build=make, **kw))

    # ---- local Cholesky (unrolled trailing forms + scan form) ----
    from dlaf_tpu.algorithms.cholesky import (_build_dist_cholesky,
                                              _build_dist_cholesky_scan,
                                              _cholesky_local,
                                              _cholesky_local_scan)

    for uplo in ("L", "U"):
        for trailing in ("loop", "biggemm"):
            for la in (False, True):
                add(f"cholesky.local.{trailing}.{uplo}.la{int(la)}",
                    lambda uplo=uplo, trailing=trailing, la=la: (
                        lambda x: _cholesky_local.__wrapped__(
                            x, uplo=uplo, nb=nb, trailing=trailing,
                            lookahead=la), (loc,)))
        add(f"cholesky.local_scan.{uplo}.la1",
            lambda uplo=uplo: (
                lambda x: _cholesky_local_scan.__wrapped__(
                    x, uplo=uplo, nb=nb, lookahead=True), (loc,)))

    # ---- distributed Cholesky (unrolled + scan, knob combos) ----
    for uplo in ("L", "U"):
        for la, comm in ((False, False), (True, True)):
            add(f"cholesky.dist.{uplo}.la{int(la)}.comm{int(comm)}",
                lambda uplo=uplo, la=la, comm=comm: (
                    _build_dist_cholesky(dist, grid.mesh, uplo, False,
                                         True, lookahead=la, comm_la=comm),
                    (st,)))
        add(f"cholesky.dist_scan.{uplo}.la1",
            lambda uplo=uplo: (
                _build_dist_cholesky_scan(dist, grid.mesh, uplo,
                                          lookahead=True), (st,)))
    add("cholesky.dist.L.la1.comm1.info",
        lambda: (_build_dist_cholesky(dist, grid.mesh, "L", False, True,
                                      lookahead=True, comm_la=True,
                                      with_info=True), (st,)))

    # ---- fused Pallas panel route (panel_impl="fused"; f32 — the route's
    # supported dtype, so the precision rule sees no wide values to
    # demote). Built with an EXPLICIT panel_fused=True: the pinned
    # native config above keeps the knob itself on "xla", these specs
    # audit the fused programs the TPU auto resolution emits. ----
    f32 = jnp.float32
    st32 = jax.ShapeDtypeStruct((str_, stc, nb, nb), f32)
    loc32 = jax.ShapeDtypeStruct((n, n), f32)
    alpha32 = jax.ShapeDtypeStruct((), f32)
    for uplo in ("L", "U"):
        add(f"cholesky.local.fpanel.{uplo}.la1",
            lambda uplo=uplo: (
                lambda x: _cholesky_local.__wrapped__(
                    x, uplo=uplo, nb=nb, trailing="loop", lookahead=True,
                    panel_fused=True, panel_interpret=True), (loc32,)))
        add(f"cholesky.dist.fpanel.{uplo}.la1.comm1",
            lambda uplo=uplo: (
                _build_dist_cholesky(dist, grid.mesh, uplo, False, True,
                                     lookahead=True, comm_la=True,
                                     panel_fused=True), (st32,)))
    add("cholesky.dist_scan.fpanel.L.la1",
        lambda: (_build_dist_cholesky_scan(dist, grid.mesh, "L",
                                           lookahead=True,
                                           pallas_interpret=True,
                                           panel_fused=True), (st32,)))

    # ---- fused STEP route (step_impl="fused"; f32 — ONE pallas_call
    # per strip-bearing blocked step, docs/pallas_panel.md "Fused step
    # kernel"). Built with an EXPLICIT step_fused=True like the fpanel
    # specs above: the pinned native config keeps the knob itself on
    # "xla", these audit the fused-step programs the TPU auto
    # resolution emits (tests/test_fused_step.py pins the per-step
    # kernel count and the comm-overlap independence on this route). ----
    for uplo in ("L", "U"):
        add(f"cholesky.local.fstep.{uplo}.la1",
            lambda uplo=uplo: (
                lambda x: _cholesky_local.__wrapped__(
                    x, uplo=uplo, nb=nb, trailing="loop", lookahead=True,
                    step_fused=True, panel_interpret=True), (loc32,)))
        add(f"cholesky.dist.fstep.{uplo}.la1.comm1",
            lambda uplo=uplo: (
                _build_dist_cholesky(dist, grid.mesh, uplo, False, True,
                                     lookahead=True, comm_la=True,
                                     step_fused=True), (st32,)))
    add("cholesky.local_scan.fstep.L.la1",
        lambda: (
            lambda x: _cholesky_local_scan.__wrapped__(
                x, uplo="L", nb=nb, lookahead=True, step_fused=True,
                panel_interpret=True), (loc32,)))
    add("cholesky.dist_scan.fstep.L.la1",
        lambda: (_build_dist_cholesky_scan(dist, grid.mesh, "L",
                                           lookahead=True,
                                           pallas_interpret=True,
                                           step_fused=True), (st32,)))

    # ---- autotune-routed programs (ISSUE 15, docs/autotune.md): the
    # re-routed programs the steered entries dispatch — a fast rung
    # (s=5 + the fused ozaki reduction) and the safety-top rung traced
    # with the route context LIVE (the routed knobs are read at trace
    # time). native_route=False: the mxu slicing and the mixed f32 seed
    # are the demotion rule's documented gated exceptions, and these
    # specs deliberately trace them ON. ----
    from dlaf_tpu.autotune.routes import LADDER_F64
    from dlaf_tpu.autotune.routes import applied as _route_applied

    def _under_route(rung: int, make):
        route = LADDER_F64.rungs[rung]

        def build():
            with _route_applied(route):
                fn, args = make()

            def traced(*xs):
                with _route_applied(route):
                    return fn(*xs)

            return traced, args

        return build

    add("cholesky.dist.atroute.rung0.L.la1",
        _under_route(0, lambda: (
            _build_dist_cholesky(dist, grid.mesh, "L", False, True,
                                 use_mxu=True, use_mixed=True,
                                 use_oz_pallas=True, lookahead=True),
            (st,))), native_route=False)
    add("cholesky.dist.atroute.top.L.la1",
        _under_route(len(LADDER_F64.rungs) - 1, lambda: (
            _build_dist_cholesky(dist, grid.mesh, "L", False, True,
                                 use_mxu=True, lookahead=True),
            (st,))), native_route=False)

    # ---- distributed triangular solve / multiply ----
    from dlaf_tpu.algorithms.triangular import (_build_dist_mult,
                                                _build_dist_mult_scan,
                                                _build_dist_solve,
                                                _build_dist_solve_scan)

    for side, uplo, op in (("L", "L", "N"), ("R", "U", "C")):
        add(f"solve.dist.{side}{uplo}{op}",
            lambda side=side, uplo=uplo, op=op: (
                _build_dist_solve(dist, dist, grid.mesh, side, uplo, op,
                                  "N", "float64"), (st, st, alpha)))
        add(f"solve.dist_scan.{side}{uplo}{op}.la1.comm1",
            lambda side=side, uplo=uplo, op=op: (
                _build_dist_solve_scan(dist, dist, grid.mesh, side, uplo,
                                       op, "N", "float64", lookahead=True,
                                       comm_la=True), (st, st, alpha)))
    add("solve.dist.fpanel.LLN",
        lambda: (_build_dist_solve(dist, dist, grid.mesh, "L", "L", "N",
                                   "N", "float32", panel_fused=True,
                                   panel_interpret=True),
                 (st32, st32, alpha32)))
    add("solve.dist_scan.fpanel.LLN.la1",
        lambda: (_build_dist_solve_scan(dist, dist, grid.mesh, "L", "L",
                                        "N", "N", "float32",
                                        lookahead=True, panel_fused=True,
                                        panel_interpret=True),
                 (st32, st32, alpha32)))
    add("mult.dist.LLN",
        lambda: (_build_dist_mult(dist, dist, grid.mesh, "L", "L", "N",
                                  "N", "float64"), (st, st, alpha)))
    add("mult.dist_scan.LLN",
        lambda: (_build_dist_mult_scan(dist, dist, grid.mesh, "L", "L",
                                       "N", "N", "float64"),
                 (st, st, alpha)))

    # ---- distributed HEGST (blocked two-sided update) ----
    from dlaf_tpu.algorithms.gen_to_std import _build_dist_hegst

    for uplo in ("L", "U"):
        for la, comm in ((False, False), (True, True)):
            add(f"hegst.dist.{uplo}.la{int(la)}.comm{int(comm)}",
                lambda uplo=uplo, la=la, comm=comm: (
                    _build_dist_hegst(dist, grid.mesh, uplo, lookahead=la,
                                      comm_la=comm), (st, st)))
    add("hegst.dist.fpanel.L.la1.comm1",
        lambda: (_build_dist_hegst(dist, grid.mesh, "L", lookahead=True,
                                   comm_la=True, panel_fused=True,
                                   panel_interpret=True), (st32, st32)))

    # ---- reduction to band (local + dist, unrolled + scan) ----
    from dlaf_tpu.eigensolver.reduction_to_band import (
        _build_dist_red2band, _build_dist_red2band_scan, _red2band_local,
        _red2band_local_scan)

    add("red2band.local",
        lambda: (lambda x: _red2band_local.__wrapped__(x, nb=nb), (loc,)))
    add("red2band.local_scan",
        lambda: (lambda x: _red2band_local_scan.__wrapped__(x, nb=nb),
                 (loc,)))
    for comm in (False, True):
        add(f"red2band.dist.comm{int(comm)}",
            lambda comm=comm: (
                _build_dist_red2band(dist, grid.mesh, "float64", nb,
                                     comm_la=comm), (st,)))
    add("red2band.dist_scan",
        lambda: (_build_dist_red2band_scan(dist, grid.mesh, "float64", nb),
                 (st,)))

    # ---- back-transforms ----
    from dlaf_tpu.eigensolver.back_transform import (_build_dist_bt_b2t,
                                                     _build_dist_bt_r2b,
                                                     _build_dist_bt_r2b_scan)

    npan = max(-(-n // nb) - 1, 0)
    taus = jax.ShapeDtypeStruct((npan, nb), f64)
    for la in (False, True):
        add(f"bt_r2b.dist.la{int(la)}",
            lambda la=la: (_build_dist_bt_r2b(dist, dist, grid.mesh, nb,
                                              la=la), (st, taus, st)))
    add("bt_r2b.dist_scan.la1",
        lambda: (_build_dist_bt_r2b_scan(dist, dist, grid.mesh, nb,
                                         la=True), (st, taus, st)))
    n_sweeps = max(n - 2, 0)
    n_steps = -(-max(n - 1, 1) // nb)
    add("bt_b2t.dist",
        lambda: (_build_dist_bt_b2t(dist, grid.mesh, b=nb, cplx=False,
                                    n_sweeps=n_sweeps),
                 (jax.ShapeDtypeStruct((n_sweeps, n_steps, nb), f64),
                  jax.ShapeDtypeStruct((n_sweeps, n_steps), f64),
                  jax.ShapeDtypeStruct((n,), f64), st)))

    # ---- serve batched bucket programs (ISSUE 11, docs/serving.md):
    # the vmapped forms the program service compiles, built through the
    # SAME builder the service uses (serve.programs.program_builder) so
    # the audited programs are the served programs; f64 on the pinned
    # native config, with_info on (the serving default). ----
    from dlaf_tpu.serve.programs import (cholesky_spec, eigh_spec,
                                         program_builder, solve_spec)

    serve_specs = [
        cholesky_spec(batch=3, n=n, nb=nb, dtype="float64", uplo="L"),
        cholesky_spec(batch=3, n=n, nb=nb, dtype="float64", uplo="U"),
        solve_spec(batch=3, n=n, nrhs=nb, nb=nb, dtype="float64",
                   side="L", uplo="L", transa="N", diag="N"),
        solve_spec(batch=3, n=n, nrhs=nb, nb=nb, dtype="float64",
                   side="R", uplo="U", transa="C", diag="N"),
        eigh_spec(batch=3, n=n, nb=nb, dtype="float64", uplo="L"),
    ]
    for sspec in serve_specs:
        tag = (f"{sspec.side}{sspec.uplo}{sspec.transa}"
               if sspec.op == "solve" else sspec.uplo)
        add(f"serve.{sspec.op}.batched.{tag}",
            lambda sspec=sspec: program_builder(sspec)[:2])
    return specs


# ---------------------------------------------------------------------------
# Checks over one traced program
# ---------------------------------------------------------------------------

def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    import numpy as np

    return math.prod(int(d) for d in shape) * np.dtype(dtype).itemsize \
        if shape else np.dtype(dtype).itemsize


def _path_str(path) -> str:
    return "/".join(f"{name}.{label}" for name, label in path) or "top"


def audit_jaxpr(name: str, closed_jaxpr, *, hot_path: bool = True,
                native_route: bool = True,
                hbm_factor: float = DEFAULT_HBM_FACTOR) -> List[Finding]:
    """All graph findings for one traced program (see module docstring
    for the rule catalog)."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    findings: List[Finding] = []

    for coll in depgraph.collectives(jaxpr):
        if coll.conditional:
            findings.append(Finding(
                "graph-conditional-collective", name,
                f"{coll.kind} over {coll.axes} executes conditionally "
                f"(path {_path_str(coll.path)}) — rank-varying collective "
                f"schedules deadlock SPMD meshes",
                key_detail=f"{name}|{coll.kind}|{','.join(coll.axes)}"))

    if hot_path:
        for path, e in depgraph.callbacks(jaxpr):
            findings.append(Finding(
                "graph-host-callback", name,
                f"{e.primitive.name} inside hot-path program "
                f"(path {_path_str(path)}) — stalls the device on a host "
                f"round trip",
                key_detail=f"{name}|{e.primitive.name}"))

    if native_route:
        for path, e in depgraph.iter_eqns(jaxpr):
            if e.primitive.name != "convert_element_type":
                continue
            old = e.invars[0].aval
            new = str(e.params.get("new_dtype"))
            if (str(getattr(old, "dtype", "")) in _WIDE
                    and not getattr(old, "weak_type", False)
                    and new in _NARROW):
                findings.append(Finding(
                    "graph-precision-demotion", name,
                    f"{old.dtype}->{new} conversion on the native route "
                    f"(path {_path_str(path)}, shape "
                    f"{tuple(getattr(old, 'shape', ()))}) — silent "
                    f"mantissa loss outside the gated mxu/mixed routes",
                    key_detail=f"{name}|{old.dtype}->{new}"))

    for path, e in depgraph.iter_eqns(jaxpr):
        if e.primitive.name != "scan":
            continue
        for slot in depgraph.scan_carry_slots(e):
            if slot.dead:
                findings.append(Finding(
                    "graph-dead-carry", name,
                    f"scan carry slot {slot.index} (path "
                    f"{_path_str(path)}) is never read and passes "
                    f"through unchanged — a dropped carry",
                    key_detail=f"{name}|carry{slot.index}|{_path_str(path)}"))
        for idx in depgraph.dropped_outputs(e):
            findings.append(Finding(
                "graph-dead-output", name,
                f"scan stacked output {idx} (path {_path_str(path)}) is "
                f"computed every iteration and never consumed",
                key_detail=f"{name}|ys{idx}|{_path_str(path)}"))

    def _hbm_walk(sub_jaxpr, input_bytes, path):
        # inside a shard_map body every aval is PER-SHARD, so the budget
        # denominator must be the body's own (per-shard) input bytes —
        # comparing against the global program inputs would slacken the
        # rule by the mesh size on exactly the distributed builders
        for e in sub_jaxpr.eqns:
            for ov in e.outvars:
                nbytes = _aval_bytes(getattr(ov, "aval", None))
                if nbytes > hbm_factor * input_bytes:
                    findings.append(Finding(
                        "graph-hbm-blowup", name,
                        f"{e.primitive.name} materializes {nbytes} bytes "
                        f"— {nbytes / input_bytes:.1f}x the enclosing "
                        f"program's {input_bytes} input bytes (path "
                        f"{_path_str(path)}, budget {hbm_factor}x)",
                        key_detail=f"{name}|{e.primitive.name}|"
                                   f"{nbytes // input_bytes}x"))
            for label, sub in depgraph.subjaxprs(e):
                sub_bytes = input_bytes
                if "shard_map" in e.primitive.name:
                    sub_bytes = max(sum(_aval_bytes(v.aval)
                                        for v in sub.invars), 1)
                _hbm_walk(sub, sub_bytes,
                          path + ((e.primitive.name, label),))

    _hbm_walk(jaxpr, max(sum(_aval_bytes(v.aval)
                             for v in jaxpr.invars), 1), ())
    return findings


def run(hbm_factor: float = DEFAULT_HBM_FACTOR,
        specs: Optional[Sequence[ProgramSpec]] = None) -> List[Finding]:
    """Trace + audit every spec under the pinned native config. A spec
    that fails to trace is itself a finding (``graph-trace-error``) —
    the auditor must fail loudly, not skip silently."""
    with pinned_native_config():
        if specs is None:
            specs = program_specs()
        findings: List[Finding] = []
        for spec in specs:
            try:
                fn, args = spec.build()
                jaxpr = depgraph.trace(fn, *args)
            except Exception as e:   # noqa: BLE001 — converted to finding
                findings.append(Finding(
                    "graph-trace-error", spec.name,
                    f"builder failed to trace: {type(e).__name__}: {e}",
                    key_detail=f"{spec.name}|{type(e).__name__}"))
                continue
            findings.extend(audit_jaxpr(
                spec.name, jaxpr, hot_path=spec.hot_path,
                native_route=spec.native_route, hbm_factor=hbm_factor))
    return findings
