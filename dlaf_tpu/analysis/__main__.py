"""``python -m dlaf_tpu.analysis`` — the static-analysis CI gate.

Runs the jaxpr graph auditor (:mod:`.graphcheck`) and the AST convention
linter (:mod:`.lint`), diffs the findings against the committed baseline
(``.analysis_baseline.json``), and exits 1 on any finding not in the
baseline — same only-gets-cleaner semantics as the bench/accuracy gates.

``--drill NAME`` runs one seeded-bad must-trip program (:mod:`.drills`)
instead: exit 1 with the expected rule named in the log proves the gate
can fail; exit 3 means the CHECK is broken (it no longer flags its own
drill) — CI requires specifically 1.

Must run with the virtual CPU platform so the 2x2 audit meshes exist;
invoked as a module this file forces it (before the first jax import,
the same constraint tests/conftest.py documents).
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_virtual_devices() -> None:
    """Force >= 8 virtual CPU devices, BEFORE the first jax import.
    No-op when the caller already forced a device count."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    # never probe a (possibly wedged) accelerator tunnel from analysis:
    # static auditing is hermetic by design (same stance as ci/run.sh)
    os.environ["PALLAS_AXON_POOL_IPS"] = ""


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dlaf_tpu.analysis",
        description="jaxpr graph auditor + repo-convention linter "
                    "(docs/static_analysis.md)")
    parser.add_argument("--root", default=".",
                        help="repo root to lint / find the baseline in")
    # mutually exclusive: both at once would skip every checker and
    # report a vacuously clean gate
    only = parser.add_mutually_exclusive_group()
    only.add_argument("--lint-only", action="store_true",
                      help="skip the graph auditor")
    only.add_argument("--graph-only", action="store_true",
                      help="skip the linter")
    parser.add_argument("--baseline", default=None,
                        help="baseline path (default <root>/"
                             ".analysis_baseline.json)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather ALL current findings and exit 0")
    parser.add_argument("--hbm-factor", type=float, default=None,
                        help="materialized-intermediate budget as a "
                             "multiple of program input bytes")
    parser.add_argument("--drill", default=None,
                        help="run one seeded-bad must-trip drill")
    parser.add_argument("--list-drills", action="store_true")
    args = parser.parse_args(argv)

    if "jax" not in sys.modules:
        _force_virtual_devices()

    from . import BASELINE_PATH, diff_baseline, load_baseline, write_baseline
    from . import lint as lint_mod

    if args.list_drills:
        from . import drills as drills_mod

        print("\n".join(sorted(drills_mod.DRILLS)))
        return 0

    if args.drill:
        from . import drills as drills_mod

        try:
            findings, expected = drills_mod.run(args.drill)
        except KeyError as e:
            # a typo'd drill name must exit 2 (usage error), never 1 —
            # rc=1 is the "drill tripped" success contract CI greps for
            parser.error(str(e))
        for f in findings:
            print(f)
        missing = set(expected) - {f.rule for f in findings}
        if missing:
            print(f"DRILL BROKEN: {args.drill} did not trip "
                  f"{sorted(missing)} — the checker lost its teeth",
                  file=sys.stderr)
            return 3
        print(f"drill {args.drill}: tripped "
              f"{sorted(set(expected))} as required")
        return 1

    findings = []
    if not args.lint_only:
        from . import graphcheck as graphcheck_mod

        kw = {}
        if args.hbm_factor is not None:
            kw["hbm_factor"] = args.hbm_factor
        findings.extend(graphcheck_mod.run(**kw))
    if not args.graph_only:
        try:
            findings.extend(lint_mod.run(args.root))
        except FileNotFoundError as e:
            # zero files scanned = misconfiguration, not a clean tree
            parser.error(str(e))

    baseline_path = args.baseline or os.path.join(args.root, BASELINE_PATH)
    if args.write_baseline:
        if args.lint_only or args.graph_only:
            # a partial run would overwrite the shared baseline with only
            # the selected checker's findings, silently erasing the other
            # checker's grandfathered keys
            parser.error("--write-baseline requires a full run (drop "
                         "--lint-only/--graph-only)")
        write_baseline(baseline_path, findings)
        print(f"wrote {len(findings)} finding key(s) to {baseline_path}")
        return 0

    baseline = load_baseline(baseline_path)
    new, stale = diff_baseline(findings, baseline)
    old = len(findings) - len(new)
    print(f"dlaf_tpu.analysis: {len(findings)} finding(s) "
          f"({len(new)} new, {old} baselined), "
          f"{len(stale)} stale baseline key(s)")
    for key in stale:
        print(f"  stale baseline entry (fixed? remove it): {key}")
    for f in new:
        print(f"  NEW {f}")
    if new:
        print(f"FAILED: {len(new)} new finding(s) — fix them or, for a "
              f"deliberate grandfather, rerun with --write-baseline "
              f"(docs/static_analysis.md)", file=sys.stderr)
        return 1
    print("analysis gate: PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
