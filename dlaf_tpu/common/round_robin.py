"""Round-robin resource rotation (reference ``common/round_robin.h:10-35``).

The reference rotates pre-allocated workspaces — look-ahead panel pairs in
the factorizations (``factorization/cholesky/impl.h:187-189``) and the
kernel microbenchmark's work tiles (``miniapp/kernel/work_tiles.h``) — so
that in-flight tasks never share a buffer. Under XLA the look-ahead use
disappears (the compiler owns buffer lifetimes inside a traced step), but
the *measurement* use survives: rotating independent input sets between
timed runs keeps a microbenchmark from re-reading the exact buffers the
previous run just touched. :mod:`dlaf_tpu.miniapp.miniapp_kernel` is the
consumer.
"""

from __future__ import annotations

from typing import Generic, Iterable, Sequence, TypeVar

T = TypeVar("T")

__all__ = ["RoundRobin"]


class RoundRobin(Generic[T]):
    """Cycle through a fixed pool of resources.

    ``next_resource()`` returns pool items in order, wrapping around
    (reference ``RoundRobin::nextResource``, ``common/round_robin.h:24-30``).
    ``current_resource()`` re-reads the last item handed out without
    advancing (reference ``currentResource``).
    """

    def __init__(self, items: Iterable[T]):
        self._items: Sequence[T] = tuple(items)
        if not self._items:
            raise ValueError("RoundRobin needs at least one resource")
        self._index = len(self._items) - 1  # first next_resource() -> items[0]

    def next_resource(self) -> T:
        self._index = (self._index + 1) % len(self._items)
        return self._items[self._index]

    def current_resource(self) -> T:
        return self._items[self._index]

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        """Iterate the pool once in storage order (does not advance the
        rotation); lets callers touch every resource, e.g. to pre-compile."""
        return iter(self._items)
