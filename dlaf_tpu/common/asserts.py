"""Three-level assertion system.

TPU-native counterpart of the reference's ``common/assert.h:105-121``
(``DLAF_ASSERT`` / ``DLAF_ASSERT_MODERATE`` / ``DLAF_ASSERT_HEAVY``): three
severity tiers, each independently switchable, that print the failing
expression with a source location. The reference gates tiers at compile time
via CMake options (``src/CMakeLists.txt:33-46``); here they are gated at import
time by environment variables so test runs can enable the heavy tier:

* ``DLAF_ASSERT_ENABLE``          (default: on)
* ``DLAF_ASSERT_MODERATE_ENABLE`` (default: on  — reference default: debug only)
* ``DLAF_ASSERT_HEAVY_ENABLE``    (default: off)
"""

from __future__ import annotations

import inspect
import os


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "off", "false", "no", "")


ASSERT_ENABLED = _env_flag("DLAF_ASSERT_ENABLE", True)
ASSERT_MODERATE_ENABLED = _env_flag("DLAF_ASSERT_MODERATE_ENABLE", True)
ASSERT_HEAVY_ENABLED = _env_flag("DLAF_ASSERT_HEAVY_ENABLE", False)


class DlafAssertError(AssertionError):
    """Raised on a failed DLAF assertion (reference aborts; we raise)."""


def _fail(level: str, message: str, extras: tuple) -> None:
    frame = inspect.stack()[2]
    loc = f"{frame.filename}:{frame.lineno} in {frame.function}"
    extra = ("\n  " + "\n  ".join(str(e) for e in extras)) if extras else ""
    raise DlafAssertError(f"[{level}] {message}\n  at {loc}{extra}")


def dlaf_assert(cond: bool, message: str = "", *extras) -> None:
    """Tier-1 assertion: cheap invariants, on by default everywhere.

    Mirrors ``DLAF_ASSERT`` (reference ``common/assert.h:105``).
    """
    if ASSERT_ENABLED and not cond:
        _fail("DLAF_ASSERT", message, extras)


def dlaf_assert_moderate(cond: bool, message: str = "", *extras) -> None:
    """Tier-2 assertion: moderate-cost checks (reference ``assert.h:113``)."""
    if ASSERT_MODERATE_ENABLED and not cond:
        _fail("DLAF_ASSERT_MODERATE", message, extras)


def dlaf_assert_heavy(cond: bool, message: str = "", *extras) -> None:
    """Tier-3 assertion: expensive checks (reference ``assert.h:121``)."""
    if ASSERT_HEAVY_ENABLED and not cond:
        _fail("DLAF_ASSERT_HEAVY", message, extras)
