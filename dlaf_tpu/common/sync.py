"""Real device synchronization for timing fences.

``jax.Array.block_until_ready`` is the canonical fence, but on remote-tunnel
PJRT platforms (device proxies) it has been observed returning before the
producing computation actually executes — so enqueue time masquerades as run
time and throughput numbers inflate by an order of magnitude. A device→host
readback of a value that depends on the array is a reliable barrier on every
platform. :func:`hard_fence` does both: ``block_until_ready`` (correct and
sufficient on local backends) plus a one-element readback (forces completion
through proxies). The readback cost is a single-element transfer — noise next
to any timed region worth measuring.

Reference analog: the fenced-timing protocol ``waitLocalTiles()`` +
``MPI_Barrier`` around every benchmark region (miniapp_cholesky.cpp:134-146);
this module is that fence made trustworthy on TPU tunnels.

Note: on a sharded array the readback pulls one element from the first
shard. All shards of one array are defined by the same launched program, so
completion of any output buffer implies the program ran; per-device skew is
bounded by the program itself.
"""

from __future__ import annotations

import numpy as np

__all__ = ["hard_fence"]


def hard_fence(*arrays):
    """Block until every given array's producing computation has really run.

    Accepts jax Arrays (or anything with ``block_until_ready``); numpy
    arrays and ``None`` pass through untouched. Returns the single argument
    (or the tuple) for call-site chaining.
    """
    for x in arrays:
        if x is None:
            continue
        if hasattr(x, "block_until_ready"):
            x.block_until_ready()
            if getattr(x, "size", 0):
                # tiny readback: the only fence proxies cannot lie about.
                # On multi-controller runs the global element (0,..,0) may
                # live on a non-addressable device — read back from a local
                # shard instead (completion of any output buffer implies the
                # launched program ran).
                if getattr(x, "is_fully_addressable", True):
                    np.asarray(x[(0,) * x.ndim])
                else:
                    shard = x.addressable_shards[0].data
                    np.asarray(shard[(0,) * shard.ndim])
    return arrays[0] if len(arrays) == 1 else arrays
