"""Wall-clock timing and phase profiling.

TPU-native counterpart of the reference's ``common::Timer``
(``common/timer.h``) plus the green-field profiling hook SURVEY §5 calls for:
the reference delegates profiling to pika's runtime; here phase timers can
additionally emit XLA/PJRT execution profiles via ``jax.profiler`` when a
trace directory is configured.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


class Timer:
    """Elapsed-seconds timer (reference ``common::Timer``)."""

    def __init__(self):
        self._t0 = time.perf_counter()

    def reset(self) -> None:
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0


class PhaseTimer:
    """Named phase timings for multi-stage algorithms (eigensolver pipeline).

    Use ``with phases.phase("reduction_to_band"): ...``; ``report()`` returns
    {name: seconds}. When ``profile_dir`` is set, each phase is additionally
    wrapped in a ``jax.profiler.TraceAnnotation`` so device timelines carry
    the phase names.
    """

    def __init__(self, profile_dir: Optional[str] = None):
        self.times: dict[str, float] = {}
        self.profile_dir = profile_dir
        self._tracing = False

    @contextlib.contextmanager
    def phase(self, name: str):
        ctx = contextlib.nullcontext()
        if self.profile_dir is not None:
            import jax

            if not self._tracing:
                # perfetto trace alongside the xplane: a gzipped JSON this
                # container can post-process WITHOUT tensorboard
                # (scripts/profile_summary.py aggregates op durations)
                jax.profiler.start_trace(self.profile_dir,
                                         create_perfetto_trace=True)
                self._tracing = True
            ctx = jax.profiler.TraceAnnotation(name)
        t0 = time.perf_counter()
        with ctx:
            yield
        self.times[name] = self.times.get(name, 0.0) + time.perf_counter() - t0

    def stop(self) -> None:
        if self._tracing:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False

    def report(self) -> dict[str, float]:
        return dict(self.times)
