"""Wall-clock timing and phase profiling.

TPU-native counterpart of the reference's ``common::Timer``
(``common/timer.h``). Phase profiling is now a thin veneer over the
:mod:`dlaf_tpu.obs` span tracer: each ``phase(...)`` region is an obs span
(structured JSONL record + duration histogram when ``DLAF_METRICS_PATH``
is set, ``jax.profiler.TraceAnnotation`` names on the profiler timeline
when a trace dir is active) while the familiar ``report()`` {name:
seconds} aggregation is kept for existing callers.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from .. import obs


class Timer:
    """Elapsed-seconds timer (reference ``common::Timer``)."""

    def __init__(self):
        self._t0 = time.perf_counter()

    def reset(self) -> None:
        self._t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0


class PhaseTimer:
    """Named phase timings for multi-stage algorithms (eigensolver pipeline).

    Use ``with phases.phase("stage.reduction_to_band"): ...``; ``report()``
    returns {name: seconds}. Phase names should stay distinct from the
    algorithms' own entry-span names (hence the ``stage.`` prefix in the
    pipeline) — a fenced stage wall-time span sharing a name with an
    unfenced dispatch-time entry span would aggregate two different
    populations under one histogram. Phases are obs spans, so with
    observability configured
    they also land in the JSONL artifact and on profiler timelines. When
    ``profile_dir`` is set (the pre-obs knob), a ``jax.profiler`` trace is
    additionally started for the timer's lifetime — even if the obs layer
    itself is off — preserving the original contract.
    """

    def __init__(self, profile_dir: Optional[str] = None):
        self.times: dict[str, float] = {}
        self.profile_dir = profile_dir
        self._tracing = False

    @contextlib.contextmanager
    def phase(self, name: str, **attrs):
        # keep the span name constant across repeats (one histogram per
        # phase, aggregable durations) and put per-call context — run
        # index and the like — in span attrs instead
        from ..obs._state import STATE

        ann = contextlib.nullcontext()
        if self.profile_dir is not None and STATE.trace_dir \
                and STATE.trace_dir != self.profile_dir:
            # jax.profiler supports one trace per process: the obs layer's
            # DLAF_TRACE_DIR wins and this timer's directory stays empty —
            # say so rather than silently dropping the requested output
            obs.get_logger("timer").warning_once(
                ("profile_dir_superseded", self.profile_dir),
                f"profile_dir={self.profile_dir!r} superseded by "
                f"DLAF_TRACE_DIR={STATE.trace_dir!r}; the trace lands there",
                profile_dir=self.profile_dir, trace_dir=STATE.trace_dir)
        if self.profile_dir is not None and not STATE.trace_dir:
            # pre-obs contract: this timer owns a jax.profiler trace. Only
            # when the obs layer has no trace dir of its own — otherwise
            # the spans below start/annotate exactly one process trace
            # (a second start_trace would fail).
            import jax

            if not self._tracing and obs.start_profiler(self.profile_dir):
                # claimed via the obs layer's single-owner protocol, so a
                # later configure(trace_dir=...) mid-phase (lazy config
                # init inside an algorithm call) can't start_trace again
                # over this live trace
                self._tracing = True
            # the obs span won't annotate (no obs trace dir): keep the
            # profiler timeline labeled ourselves
            ann = jax.profiler.TraceAnnotation(name)
        sp = obs.span(name, **attrs)
        with sp, ann:
            # t0 after span entry: one-time jax.profiler.start_trace cost
            # (possibly hundreds of ms, paid by the first phase) stays out
            # of the reported per-phase seconds, as pre-obs
            t0 = time.perf_counter()
            yield
            self.times[name] = self.times.get(name, 0.0) \
                + time.perf_counter() - t0

    def stop(self) -> None:
        from ..obs._state import STATE

        if self._tracing:
            # routed through the obs layer so its profiler_started flag
            # clears with the trace (we claimed it at start)
            obs.stop_profiler()
            self._tracing = False
        elif self.profile_dir is not None \
                and STATE.trace_dir == self.profile_dir:
            # the obs layer started the profiler on this timer's behalf
            # (profile_dir doubles as the obs trace dir); stopping here
            # keeps the pre-obs contract that stop() lands the trace files
            obs.stop_profiler()

    def report(self) -> dict[str, float]:
        return dict(self.times)
