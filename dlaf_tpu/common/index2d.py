"""Strongly-tagged 2D index/size algebra.

TPU-native counterpart of the reference's ``common/index2d.h`` plus the tag
instantiations from ``matrix/index.h`` and ``communication/index.h``: a small
family of (row, col) value types whose *tags* prevent mixing incompatible
coordinate spaces (global-element vs global-tile vs local-tile vs
tile-element vs process-grid coordinates). The reference enforces this with
C++ template tags (``common/index2d.h:141-238``); here each tag is a distinct
frozen dataclass sharing arithmetic through two mixins.

Also provides RowMajor/ColMajor linearization (``index2d.h:288-410``) and the
``iterate_range2d`` tile-loop helper (``common/range2d.h:15-269``).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Iterator, Type

from ..types import SizeType
from .asserts import dlaf_assert


class Ordering(enum.Enum):
    """Linearization order (reference ``common/index2d.h:24-30``)."""

    RowMajor = "row-major"
    ColMajor = "col-major"


@dataclasses.dataclass(frozen=True, order=False)
class _Coords2D:
    """Common (row, col) payload (reference ``basic_coords``)."""

    row: SizeType
    col: SizeType

    def __iter__(self):
        yield self.row
        yield self.col

    def transposed(self):
        return type(self)(self.col, self.row)

    def __str__(self) -> str:
        return f"({self.row}, {self.col})"


class _SizeMixin:
    def is_valid(self) -> bool:
        return self.row >= 0 and self.col >= 0

    def is_empty(self) -> bool:
        return self.row == 0 or self.col == 0

    def linear_size(self) -> SizeType:
        return self.row * self.col


class _IndexMixin:
    def is_valid(self) -> bool:
        return self.row >= 0 and self.col >= 0

    def is_in(self, size) -> bool:
        """True iff this index addresses an element of ``size``
        (reference ``index2d.h:198-208``; size must be the paired tag)."""
        dlaf_assert(type(size) is self._size_tag,
                    f"is_in: expected {self._size_tag.__name__}, got {type(size).__name__}")
        return 0 <= self.row < size.row and 0 <= self.col < size.col


def _make_pair(index_name: str, size_name: str) -> tuple[Type, Type]:
    size_cls = type(size_name, (_Coords2D, _SizeMixin), {})
    index_cls = type(index_name, (_Coords2D, _IndexMixin), {"_size_tag": size_cls})
    return index_cls, size_cls


# Tag zoo (reference matrix/index.h + communication/index.h)
GlobalElementIndex, GlobalElementSize = _make_pair("GlobalElementIndex", "GlobalElementSize")
GlobalTileIndex, GlobalTileSize = _make_pair("GlobalTileIndex", "GlobalTileSize")
LocalTileIndex, LocalTileSize = _make_pair("LocalTileIndex", "LocalTileSize")
LocalElementIndex, LocalElementSize = _make_pair("LocalElementIndex", "LocalElementSize")
TileElementIndex, TileElementSize = _make_pair("TileElementIndex", "TileElementSize")
# Process-grid coordinates (reference comm::Index2D / comm::Size2D)
RankIndex2D, GridSize2D = _make_pair("RankIndex2D", "GridSize2D")


def compute_linear_index(ordering: Ordering, index, dims) -> SizeType:
    """Linearize ``index`` inside a box of extents ``dims``
    (reference ``index2d.h:288-330``)."""
    dlaf_assert(index.is_in(dims) if hasattr(index, "is_in") else True,
                f"linear index out of bounds: {index} in {dims}")
    if ordering is Ordering.RowMajor:
        return index.row * dims.col + index.col
    return index.col * dims.row + index.row


def compute_coords(ordering: Ordering, linear: SizeType, dims, cls):
    """Inverse of :func:`compute_linear_index` (reference ``index2d.h:340-380``)."""
    if ordering is Ordering.RowMajor:
        return cls(linear // dims.col, linear % dims.col)
    return cls(linear % dims.row, linear // dims.row)


def iterate_range2d(begin_or_end, end=None, *, cls=LocalTileIndex) -> Iterator:
    """Iterate a 2D half-open range in col-major order, yielding ``cls`` indices.

    ``iterate_range2d(end)`` iterates [(0,0), end); ``iterate_range2d(begin,
    end)`` iterates [begin, end). Col-major order matches the reference's
    ``common/range2d.h`` iteration used by all tile loops.
    """
    if end is None:
        b_row, b_col = 0, 0
        e_row, e_col = begin_or_end
    else:
        b_row, b_col = begin_or_end
        e_row, e_col = end
    for col in range(b_col, e_col):
        for row in range(b_row, e_row):
            yield cls(row, col)
