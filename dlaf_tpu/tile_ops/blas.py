"""tpu_blas — BLAS tile operations on (batched) 2D blocks.

TPU-native counterpart of the reference's ``blas/tile.h:139-517`` (tile-level
``gemm/hemm/her2k/herk/trmm/trsm`` dispatched to blaspp on CPU and cuBLAS on
GPU) plus the ``add`` extension (``blas/tile_extensions.h``). Here every op is
a pure jnp function on arrays whose last two axes are the tile; leading axes
are batch dims, so one call expresses the reference's per-tile task fan-out as
a single batched XLA op that tiles onto the MXU (the idiomatic TPU form of
"many small gemms" is one big batched gemm).

Conventions:
* ``op``: 'N' (none), 'T' (transpose), 'C' (conjugate transpose) — the
  reference's ``blas::Op``.
* ``side``: 'L'/'R'; ``uplo``: 'L'/'U'/'G' (general); ``diag``: 'N'/'U' —
  ``blas::{Side,Uplo,Diag}``.
* Triangular inputs are *stored* triangles: the opposite triangle of the
  argument may hold garbage and is never read (LAPACK storage semantics).
* No in-place: ops return new values; XLA aliases buffers when it can.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax


def _op(a, op: str):
    if op == "N":
        return a
    if op == "T":
        return jnp.swapaxes(a, -1, -2)
    if op == "C":
        return jnp.conj(jnp.swapaxes(a, -1, -2))
    raise ValueError(f"bad op {op!r}")


def _mxu_f64(*arrs, dims) -> bool:
    """Trace-time decision: route this f64/complex128 contraction through
    the error-free int8 MXU path (config knob ``f64_gemm``; see
    tile_ops/ozaki.py)? Programs caching this decision register with
    ``config.register_program_cache`` so knob changes re-trace."""
    from ..config import get_configuration, resolved_f64_gemm

    if resolved_f64_gemm() != "mxu":
        return False
    if any(x.dtype not in (jnp.float64, jnp.complex128) for x in arrs):
        return False
    return min(dims) >= get_configuration().f64_gemm_min_dim


def _oz_slices() -> int:
    """Resolved slice count: the configured value, or — for the 0 "auto"
    default — 7 on f64-emulating backends (TPU: the platform's ~47-48-bit
    double-f32 arithmetic already bounds every surrounding op, so the
    49-bit dot loses nothing and drops 8 of 36 gemms) and 8 (f64-grade
    dots) where f64 is native. Keyed on the PROCESS default backend: a
    trace explicitly placed on a non-default backend (jax.default_device)
    inherits the process tier — set the knob explicitly for that case.
    The auto resolution is announced once per (backend, count) on stderr
    so the tier in effect is never silent. See
    Configuration.f64_gemm_slices. An active autotune route
    (docs/autotune.md) overrides the whole resolution — read at trace
    time, so every program cache on the mxu path carries the route in
    its cache key."""
    from ..config import _route_override, get_configuration

    routed = _route_override("f64_gemm_slices")
    if routed is not None:
        return int(routed)
    s = int(get_configuration().f64_gemm_slices)
    if s:
        return s
    import jax

    backend = jax.default_backend()
    s = 7 if backend == "tpu" else 8
    from ..obs import get_logger

    # once per (backend, slices): the accuracy tier in effect (56 vs 49
    # mantissa bits) is visible, not silent (round-2 advisory)
    get_logger("config").warning_once(
        ("f64_gemm_slices", backend, s),
        f"f64_gemm_slices=0 (auto) resolved to {s} for default backend "
        f"{backend!r} (~{7 * s} mantissa bits); traces placed on other "
        "backends inherit this — set the knob explicitly to override",
        knob="f64_gemm_slices", backend=backend, choice=s)
    return s


def mm_mxu(a, b):
    """``a @ b`` FORCED onto the int8 MXU path (tile_ops.ozaki), regardless
    of the ``f64_gemm`` knob — the gemm primitive of algorithm paths that
    are themselves MXU-routed by their own knob (the local "ozaki" cholesky
    sweep's panel application). Complex operands promote like :func:`mm`."""
    from . import ozaki

    if jnp.iscomplexobj(a) or jnp.iscomplexobj(b):
        ac = a.astype(jnp.complex128)
        bc = b.astype(jnp.complex128)
        return ozaki.matmul_c128(ac, bc, slices=_oz_slices())
    return ozaki.matmul_f64(a, b, slices=_oz_slices())


def _mm(a, b):
    """Central matmul of the level-3 ops, with the f64_gemm="mxu" reroute."""
    if _mxu_f64(a, b, dims=(a.shape[-2], a.shape[-1], b.shape[-1])):
        return mm_mxu(a, b)
    return a @ b


def mm(a, b):
    """Public matmul with the ``f64_gemm="mxu"`` reroute — for algorithm code
    whose products don't fit a named BLAS op (whole-panel compositions,
    gathered blocks). Native path is exactly ``a @ b``."""
    return _mm(a, b)


def contract(subscripts: str, x, y):
    """Two-operand einsum with the ``f64_gemm="mxu"`` reroute.

    Native path: ``jnp.einsum(subscripts, x, y, preferred_element_type=...)``
    — bit-identical to the raw einsums the distributed algorithms used. On
    the mxu path the contraction is factored into (transpose → flatten →
    ozaki matmul → unflatten → transpose), which is exactly how XLA lowers
    einsum to dot_general, so the int8 path sees one large product.

    Supported: no repeated labels within an operand, every label of each
    operand present in the other operand and/or the output (no implicit
    broadcasting). Labels shared by both operands and the output batch;
    shared labels absent from the output contract.
    """
    lhs, out = subscripts.split("->")
    s1, s2 = lhs.split(",")
    assert len(set(s1)) == len(s1) and len(set(s2)) == len(s2), subscripts
    batch = [c for c in s1 if c in s2 and c in out]
    contracted = [c for c in s1 if c in s2 and c not in out]
    free1 = [c for c in s1 if c not in s2]
    free2 = [c for c in s2 if c not in s1]
    assert all(c in out for c in free1 + free2), subscripts
    assert set(out) == set(batch + free1 + free2), subscripts

    dims1 = dict(zip(s1, x.shape))
    dims2 = dict(zip(s2, y.shape))
    if _mxu_f64(x, y, dims=(max(int(np.prod([dims1[c] for c in free1], dtype=np.int64)), 1),
                            max(int(np.prod([dims1[c] for c in contracted], dtype=np.int64)), 1),
                            max(int(np.prod([dims2[c] for c in free2], dtype=np.int64)), 1))):
        from . import ozaki

        xt = jnp.transpose(x, [s1.index(c) for c in batch + free1 + contracted])
        yt = jnp.transpose(y, [s2.index(c) for c in batch + contracted + free2])
        bshape = tuple(dims1[c] for c in batch)
        f1 = int(np.prod([dims1[c] for c in free1], dtype=np.int64)) if free1 else 1
        f2 = int(np.prod([dims2[c] for c in free2], dtype=np.int64)) if free2 else 1
        kk = int(np.prod([dims1[c] for c in contracted], dtype=np.int64)) if contracted else 1
        mmfn = (ozaki.matmul_c128 if jnp.iscomplexobj(x) or jnp.iscomplexobj(y)
                else ozaki.matmul_f64)
        xf = xt.reshape(bshape + (f1, kk))
        yf = yt.reshape(bshape + (kk, f2))
        if jnp.iscomplexobj(xf) != jnp.iscomplexobj(yf):
            xf = xf.astype(jnp.complex128)
            yf = yf.astype(jnp.complex128)
        full = mmfn(xf, yf, slices=_oz_slices())
        full = full.reshape(bshape + tuple(dims1[c] for c in free1)
                            + tuple(dims2[c] for c in free2))
        order = batch + free1 + free2
        return jnp.transpose(full, [order.index(c) for c in out])
    return jnp.einsum(subscripts, x, y,
                      preferred_element_type=jnp.result_type(x, y))


def tri_mask(a, uplo: str, *, k: int = 0):
    """Keep the stored triangle of the last-two-dims block."""
    if uplo == "G":
        return a
    if uplo == "L":
        return jnp.tril(a, k=k)
    if uplo == "U":
        return jnp.triu(a, k=-k)
    raise ValueError(f"bad uplo {uplo!r}")


def hermitian_from(a, uplo: str):
    """Full (conjugate-)symmetric block from its stored triangle, e.g. for
    ``hemm``/``hegst`` inputs. Diagonal imaginary parts are dropped for
    complex dtypes (Hermitian diagonal is real by definition)."""
    if uplo == "G":
        return a
    tri = tri_mask(a, uplo, k=-1)
    diag = jnp.real(_diag_of(a)) if jnp.iscomplexobj(a) else _diag_of(a)
    d = _embed_diag(diag, a.shape, a.dtype)
    return tri + jnp.conj(jnp.swapaxes(tri, -1, -2)) + d


def _diag_of(a):
    return jnp.diagonal(a, axis1=-2, axis2=-1)


def _embed_diag(d, shape, dtype):
    n = shape[-1]
    eye = jnp.eye(n, dtype=dtype)
    return d[..., None] * eye


def _tri(a, uplo: str, diag: str):
    """Triangle of ``a`` with optional implicit unit diagonal."""
    t = tri_mask(a, uplo)
    if diag == "U":
        n = a.shape[-1]
        t = t - _embed_diag(_diag_of(t), a.shape, a.dtype) + jnp.eye(n, dtype=a.dtype)
    return t


# ---------------------------------------------------------------------------
# Level-3 ops (reference blas/tile.h:139-517)
# ---------------------------------------------------------------------------

def gemm(a, b, c=None, *, alpha=1.0, beta=0.0, op_a: str = "N", op_b: str = "N"):
    """``c = alpha op_a(a) op_b(b) + beta c`` (reference ``tile::gemm``)."""
    prod = _mm(_op(a, op_a), _op(b, op_b))
    out = alpha * prod
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out.astype(a.dtype)


def hemm(side: str, uplo: str, a, b, c=None, *, alpha=1.0, beta=0.0):
    """``c = alpha A b + beta c`` (side='L') with Hermitian ``A`` stored in
    ``uplo`` (reference ``tile::hemm``)."""
    af = hermitian_from(a, uplo)
    prod = _mm(af, b) if side == "L" else _mm(b, af)
    out = alpha * prod
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out.astype(b.dtype)


def herk(uplo: str, op_a: str, a, c, *, alpha=1.0, beta=1.0):
    """``c = alpha op_a(a) op_a(a)^H + beta c`` on the ``uplo`` triangle
    (reference ``tile::herk``; alpha/beta real).

    The full Hermitian product is formed (one MXU gemm); only the requested
    triangle of ``c`` is updated, the other triangle passes through — matching
    LAPACK update semantics so garbage triangles stay untouched.
    """
    oa = _op(a, op_a)
    if _mxu_f64(oa, dims=(oa.shape[-2], oa.shape[-1])):
        from . import ozaki

        prod = (ozaki.herk_c128(oa, slices=_oz_slices())
                if jnp.iscomplexobj(oa)
                else ozaki.syrk_f64(oa, slices=_oz_slices()))
    else:
        prod = oa @ jnp.conj(jnp.swapaxes(oa, -1, -2))
    upd = alpha * prod + beta * c
    if jnp.iscomplexobj(c):  # herk guarantees a real diagonal
        d = _embed_diag(jnp.real(_diag_of(upd)) - _diag_of(upd), upd.shape, upd.dtype)
        upd = upd + d
    return _merge_triangle(upd, c, uplo)


def her2k(uplo: str, op: str, a, b, c, *, alpha=1.0, beta=1.0):
    """``c = alpha op(a) op(b)^H + conj(alpha) op(b) op(a)^H + beta c`` on the
    ``uplo`` triangle (reference ``tile::her2k``; beta real)."""
    oa, ob = _op(a, op), _op(b, op)
    prod = alpha * _mm(oa, jnp.conj(jnp.swapaxes(ob, -1, -2)))
    prod = prod + jnp.conj(jnp.swapaxes(prod, -1, -2))
    upd = prod + beta * c
    return _merge_triangle(upd, c, uplo)


def _merge_triangle(update, orig, uplo: str):
    if uplo == "G":
        return update
    return tri_mask(update, uplo) + tri_mask(orig, "U" if uplo == "L" else "L", k=-1)


def trmm(side: str, uplo: str, op_a: str, diag: str, a, b, *, alpha=1.0):
    """``b = alpha op_a(A) b`` (side='L') with triangular ``A``
    (reference ``tile::trmm``)."""
    t = _op(_tri(a, uplo, diag), op_a)
    prod = _mm(t, b) if side == "L" else _mm(b, t)
    return (alpha * prod).astype(b.dtype)


#: Triangle sizes above this split recursively instead of lowering to one
#: XLA TriangularSolve. Two reasons (both measured on the v5e tunnel,
#: 2026-07-31 session): (1) memory — XLA's blocked substitution under the
#: f64→f32-pair X64 rewrite keeps O(n/128) prefix-shaped update temps
#: alive simultaneously (observed: f64 n=8192 against an 8192-wide rhs
#: wants ~13 GB of HLO temps and OOMs a 16 GB chip); (2) perf — the
#: recursion turns the bulk of the flops into large gemms, which ride
#: ``_mm``'s f64_gemm="mxu" reroute onto the int8 MXU path, while the
#: native solve is always software-emulated f64.
TRSM_RECURSE_MIN = 2048


def _trsm_native(side, uplo, op_a, diag, a, b):
    return lax.linalg.triangular_solve(
        a, b,
        left_side=(side == "L"),
        lower=(uplo == "L"),
        transpose_a=(op_a in ("T", "C")),
        conjugate_a=(op_a == "C"),
        unit_diagonal=(diag == "U"))


def _trsm_rec(side, uplo, op_a, diag, a, b):
    """Recursive blocked solve: split A 2x2, solve the halves, connect with
    one gemm (the standard blocked substitution the reference hand-tiles at
    ``nb`` granularity — here at halving granularity so the connecting gemm
    is as large as possible for the MXU)."""
    n = a.shape[-1]
    if n <= TRSM_RECURSE_MIN:
        return _trsm_native(side, uplo, op_a, diag, a, b)
    h = max(TRSM_RECURSE_MIN // 2, (n // 2) // 256 * 256)  # MXU-aligned split
    a11, a22 = a[:h, :h], a[h:, h:]
    # off-diagonal block of op(A): for op='N' the stored block on the
    # ``eff_lower`` side; otherwise the transpose of the other one
    eff_lower = (uplo == "L") == (op_a == "N")
    if eff_lower:
        s = a[h:, :h] if op_a == "N" else _op(a[:h, h:], op_a)
    else:
        s = a[:h, h:] if op_a == "N" else _op(a[h:, :h], op_a)
    if side == "L":
        if eff_lower:       # forward: op(A) = [[T11, 0], [S, T22]]
            x1 = _trsm_rec(side, uplo, op_a, diag, a11, b[:h])
            x2 = _trsm_rec(side, uplo, op_a, diag, a22,
                           b[h:] - _mm(s, x1))
        else:               # backward: op(A) = [[T11, S], [0, T22]]
            x2 = _trsm_rec(side, uplo, op_a, diag, a22, b[h:])
            x1 = _trsm_rec(side, uplo, op_a, diag, a11,
                           b[:h] - _mm(s, x2))
        return jnp.concatenate([x1, x2], axis=0)
    if eff_lower:           # X [[T11, 0], [S, T22]] = [B1, B2]
        x2 = _trsm_rec(side, uplo, op_a, diag, a22, b[..., h:])
        x1 = _trsm_rec(side, uplo, op_a, diag, a11,
                       b[..., :h] - _mm(x2, s))
    else:                   # X [[T11, S], [0, T22]] = [B1, B2]
        x1 = _trsm_rec(side, uplo, op_a, diag, a11, b[..., :h])
        x2 = _trsm_rec(side, uplo, op_a, diag, a22,
                       b[..., h:] - _mm(x1, s))
    return jnp.concatenate([x1, x2], axis=-1)


def trsm(side: str, uplo: str, op_a: str, diag: str, a, b, *, alpha=1.0):
    """Solve ``op_a(A) x = alpha b`` (side='L') / ``x op_a(A) = alpha b``
    (side='R') with triangular ``A`` (reference ``tile::trsm``).

    Small/batched triangles lower to XLA ``TriangularSolve`` (blocked
    forward substitution on TPU); 2D triangles above ``TRSM_RECURSE_MIN``
    use the recursive blocked form (see there for why).
    """
    out_dtype = b.dtype
    b = alpha * b
    if a.ndim == 2 and b.ndim == 2 and a.shape[-1] > TRSM_RECURSE_MIN:
        return _trsm_rec(side, uplo, op_a, diag, a, b).astype(out_dtype)
    return _trsm_native(side, uplo, op_a, diag, a, b).astype(out_dtype)


def f64_gemm_uses_mxu(dtype, dim: int) -> bool:
    """Does the ``f64_gemm="mxu"`` knob route this dtype at this block size
    onto the int8/bf16 MXU path? Single owner of the algorithm-level route
    decision (the tile-level ``_mm`` gate checks per-operand shapes
    itself)."""
    from ..config import get_configuration, resolved_f64_gemm

    import numpy as _np

    routed = (resolved_f64_gemm() == "mxu"
              and _np.dtype(dtype) in (_np.dtype(_np.float64),
                                       _np.dtype(_np.complex128))
              and dim >= get_configuration().f64_gemm_min_dim)
    if routed:
        # fault injection can force the ozaki -> plain-dot degradation;
        # the min-dim gate above is route policy and stays uncounted
        from ..health.registry import route_available

        return route_available("ozaki", "ozaki_gemm")
    return routed


def resolve_chunk_width(knob: str, dtype, gate_dim: int, chunk_axis: int,
                        *auto_dims: int) -> int:
    """Shared trace-time resolution for the workspace-bounding chunk knobs
    (``trsm_rhs_chunk``, ``red2band_trail_chunk``), which agree on
    everything but their dims. Returns the chunk width, or 0 for
    unchunked — including whenever the resolved width would not be
    shorter than ``chunk_axis``. Knob semantics: 0 = off; explicit widths
    are clamped to ``f64_gemm_min_dim`` when the mxu route is active at
    ``gate_dim`` (the per-gemm route gate takes min over ALL gemm dims —
    a narrower chunk would flip routes and change numerics); -1 = auto,
    which chunks at ``max(4096, f64_gemm_min_dim)`` only where the
    measured OOMs live — TPU, mxu route, every dim of ``auto_dims``
    >= 8192."""
    from ..config import get_configuration

    cfg = get_configuration()
    cfg_width = getattr(cfg, knob)
    mxu = f64_gemm_uses_mxu(dtype, gate_dim)
    if cfg_width > 0:
        cw = max(cfg_width, cfg.f64_gemm_min_dim) if mxu else cfg_width
    elif cfg_width == 0:
        return 0
    else:
        import jax

        if jax.default_backend() != "tpu" or not mxu \
                or any(d < 8192 for d in auto_dims):
            return 0
        cw = max(4096, cfg.f64_gemm_min_dim)
    return cw if cw < chunk_axis else 0


def trsm_panel_uses_mixed(dtype) -> bool:
    """Will :func:`trsm_panel` route this dtype through the refined-inverse
    mixed path under the current config? For callers that precompute
    ``inv_a`` once and reuse it across several panel solves."""
    from ..config import resolved_f64_trsm

    import numpy as _np

    return (resolved_f64_trsm() == "mixed"
            and _np.dtype(dtype) in (_np.dtype(_np.float64),
                                     _np.dtype(_np.complex128)))


def trsm_panel(side: str, uplo: str, op_a: str, diag: str, a, b, *,
               alpha=1.0, inv_a=None):
    """``trsm`` with ONE (2D) triangular block ``a`` against a possibly
    batched rhs ``b`` — the per-tile panel-solve pattern of the distributed
    algorithms. Under config ``f64_trsm="mixed"`` (f64 / complex128) the solve
    becomes refined-explicit-inverse (tile_ops.mixed, computed once, not per
    batch entry) times matmul (which follows ``f64_gemm``, so "mxu" puts the
    application on the int8 path); otherwise ``a`` broadcasts into the
    native solve. Whole-matrix local solves should call :func:`trsm` — the
    explicit-inverse route is for block-sized panels.

    ``inv_a``: optional precomputed refined inverse of ``a``'s triangle
    (from ``mixed.potrf_inv_refined`` — the fused factor+inverse step),
    consumed only on the mixed path; saves re-deriving the f32 seed solve."""
    from ..config import resolved_f64_trsm

    if (resolved_f64_trsm() == "mixed" and a.ndim == 2
            and a.dtype in (jnp.float64, jnp.complex128)
            and b.dtype == a.dtype):
        from . import mixed as mx

        inv = inv_a if inv_a is not None else \
            mx.tri_inv_refined(_tri(a, uplo, diag), lower=(uplo == "L"))
        ti = _op(inv, op_a)
        prod = _mm(ti, b) if side == "L" else _mm(b, ti)
        return (alpha * prod).astype(b.dtype)
    if b.ndim > a.ndim:
        a = jnp.broadcast_to(a, b.shape[:b.ndim - 2] + a.shape)
    return trsm(side, uplo, op_a, diag, a, b, alpha=alpha)


# ---------------------------------------------------------------------------
# Extensions / small helpers used by algorithms
# ---------------------------------------------------------------------------

def add(a, b, *, alpha=1.0):
    """``b = b + alpha a`` (reference ``tile_extensions.h`` ``tile::add``)."""
    return b + alpha * a


def scal(a, *, alpha):
    return alpha * a


def axpy(x, y, *, alpha=1.0):
    """``y = y + alpha x`` elementwise (reference GPU-internal ``tile::axpy``,
    ``blas/tile.h``; used by reduction-to-band micro-kernels there — here the
    algorithms fuse it into einsums, the op exists for tile-level use)."""
    return y + alpha * x


def gemv(a, x, y=None, *, alpha=1.0, beta=1.0, op_a: str = "N"):
    """``y = alpha op(A) x + beta y`` (reference GPU-internal ``tile::gemv``).
    ``x``/``y`` are vectors on the last axis; leading axes batch."""
    ax = jnp.einsum("...ij,...j->...i", _op(a, op_a), x)
    if y is None:
        return alpha * ax
    return alpha * ax + beta * y


def trmv(uplo: str, op_a: str, diag: str, a, x):
    """``x = op(T) x`` with triangular ``T`` (reference GPU-internal
    ``tile::trmv``; the T-factor accumulation uses it)."""
    t = _tri(a, uplo, diag)
    return jnp.einsum("...ij,...j->...i", _op(t, op_a), x)
