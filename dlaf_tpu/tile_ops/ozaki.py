"""Emulated float64 matmul on the MXU via error-free slicing (Ozaki scheme).

TPU hardware has no native f64 multiply: XLA emulates f64 dots in software at
~1 TFlop/s on a v5e while the MXU runs int8/bf16 contractions two to three
orders of magnitude faster. The Ozaki splitting (Ozaki et al., "Error-free
transformations of matrix multiplication", 2012; int8-tensor-core variants in
recent GPU literature) recovers f64-accurate GEMM from fast low-precision
hardware:

1. normalize each row of ``A`` (column of ``B``) to ``[-1/2, 1/2]`` by its
   max (halving folded back in at recombine, so nothing overflows even at
   ``max ~ DBL_MAX``),
2. peel ``s`` slices of ``q=7`` mantissa bits each: every slice is a small
   integer in ``[-64, 64]`` — exactly representable in int8,
3. contract slice pairs on the MXU with **exact** int32 accumulation
   (``|sum| <= k * 2^12 * s < 2^31`` for any practical ``k``),
4. recombine partial products grouped by total shift ``d = t+u`` (at most
   ``2s-1`` int32->f64 conversions, not ``s^2``), applying the row/col
   scales back.

Cross terms with ``t+u >= s`` fall below the kept mantissa (relative to the
row/column scale) and are dropped, leaving ``s(s+1)/2`` int8 gemms: 36 for the
default ``s=8`` (56 mantissa bits — slightly tighter than f64's 53, so the
result matches a native f64 gemm to its own rounding error on well-scaled
data). The error bound is relative to ``rowmax(A) * colmax(B)``, like the
classical f64 bound ``k * eps * |A||B|``.

This is a *capability the reference cannot express*: its f64 GEMM rides
cuBLAS; the TPU-native framework routes f64 tile contractions through the
int8 systolic array. Used by the Cholesky trailing update (the flops-dominant
stage of BASELINE config #1) behind ``cholesky_trailing = "ozaki"`` and
available as ``tile_ops.ozaki.{matmul_f64,syrk_f64}``.

Scope/caveats (documented, asserted where cheap): finite inputs only (no
inf/nan propagation guarantees); real f64 directly, complex128 via the
3-real-product composition (:func:`matmul_c128`/:func:`herk_c128`);
accumulation exactness needs
``k * 2^12 * min(s, d+1) < 2^31`` per grouped sum — beyond that the group sum
switches to f64. On TPU, XLA's X64 rewrite emulates f64 with f32 pairs, so
*every* f64 op there (this module included) is limited to f32's exponent
range: magnitudes beyond ~1e38 overflow the emulation. That is a platform
property, not an algorithm one — the CPU path handles the full f64 range
(covered by the pathological-scale tests).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

__all__ = ["matmul_f64", "syrk_f64", "matmul_c128", "herk_c128",
           "DEFAULT_SLICES", "SLICE_BITS"]

SLICE_BITS = 7          # q: mantissa bits per slice; int8 holds +-64 exactly
DEFAULT_SLICES = 8      # s: 8 * 7 = 56 bits >= f64's 53-bit mantissa


def _scale(x, axis):
    """Per-row/col max ``M = max|x|`` (zero rows map to 1). The normalized
    block is ``(x / M) * 0.5`` — in ``[-1/2, 1/2]`` — and :func:`_fold_group`/:func:`_apply_scales`
    folds the two implicit factors of 2 back in as an exact constant, so no
    intermediate (like ``2*M``) can overflow even at ``M ~ DBL_MAX``.

    The scale need not be a power of two: slices stay integer-exact either
    way, and the one rounding of the normalize/rescale pair is a ~1-ulp
    relative error — the same order as native f64 gemm rounding. (A
    power-of-two scale would need ``frexp``/``ldexp``, whose 64-bit
    bit-twiddling the TPU X64-emulation pipeline does not implement.)"""
    m = jnp.max(jnp.abs(x), axis=axis, keepdims=True)
    return jnp.where(m > 0, m, 1.0)


def _normalize(x, scale):
    """``(x / scale) * 0.5`` — in ``[-1/2, 1/2]``; the *0.5 is exact."""
    return (x / scale) * 0.5


def _peel_slices(xn, s: int):
    """``s`` int8 slices of the normalized block: ``xn ~= sum_t I_t 2^-q(t+1)``
    with every ``|I_t| <= 2^(q-1)`` (round-to-nearest residual peeling).

    Two hardening rules, both REQUIRED on TPU's 2xf32 f64 emulation
    (root-caused on the v5e 2026-08-02, ``scripts/tpu_ozaki_peel_probe.py``
    + ``tpu_peel_dump.py`` — the source of red2band's 2e-5 eigenvalue
    residual and the dominant term of cholesky's 6.1e-9):

    * The integer is extracted by a NATIVE f32 round — ``r*sc`` is cast
      to f32 first, then rounded — never by the emulated-f64 ``round``.
      The emulated round mis-rounds exact round-to-nearest ties plus an
      epsilon (measured: ``xn*128 = 17.5000005`` rounded to 19, not 18),
      and the one-unit overshoot pushes the next residual*scale to ~192:
      OUTSIDE int8, where the f32->s8 conversion saturates at +-127 and
      every later slice stays pinned at the rail — the decomposition is
      permanently off by ``~2^-q(t+1)``. The f32 cast loses at most
      2^-24-relative of ``r*sc`` (|values| <= ~64), which moves the
      integer choice by at most one unit off a tie — exactly what the
      next slice absorbs (|I| <= 65, well inside int8).
    * The residual subtracts the STORED slice value (int8 cast back
      through f32 — exact for |I| <= 127), so slice and residual cannot
      disagree whatever the rounding path did; any quantization surprise
      flows into the next slice instead of corrupting the sum.

    On platforms with true f64 the f32 round differs from an f64 round
    only by tie-vs-cast-noise unit choices that the residual re-absorbs:
    accuracy is unchanged (property-tested), though slice values may
    differ from a pure-f64 peel."""
    out = []
    r = xn
    for t in range(s):
        sc = float(2.0 ** (SLICE_BITS * (t + 1)))
        # f32 bridge both ways: native f32 round (see above), and small
        # integers cast exactly; f64->s8 directly could also route
        # through s64 ops the TPU emulation pipeline lacks
        it8 = jnp.round((r * sc).astype(jnp.float32)).astype(jnp.int8)
        out.append(it8)
        r = r - it8.astype(jnp.float32).astype(xn.dtype) * (1.0 / sc)
    return out


# int32 accumulation of int8 x int8 products (each |p| <= 2^12) is provably
# exact while k * 2^12 < 2^31, i.e. k < 2^19; deeper contractions are chunked
_K_I32_EXACT = 1 << 19
_K_CHUNK = 1 << 18
# f32 accumulation of the same products is integer-exact while
# k * 2^12 <= 2^24, i.e. k <= 2^12 — the bound of the bf16-dot route
_K_F32_EXACT = 1 << 12


def _slice_dot_impl() -> str:
    """"int8" (s8 x s8 -> s32 dot) or "bf16": cast the slices to bf16 —
    every value is a small integer in [-2^6, 2^6], exactly representable —
    and contract on the MXU's native bf16 path with f32 accumulation,
    which is integer-exact while ``k * 2^12 <= 2^24`` (deeper
    contractions are chunked). Same bits out either way; the knob exists
    because XLA's HLO-level int8 dot has measured far below MXU peak on
    v5e (~1-4.5 TF/s-int8) while bf16 matmul is the hardware's first-class
    path. The "auto" default resolves bf16 on TPU, int8 elsewhere, keyed
    on the PROCESS default backend like blas._oz_slices (config
    ``ozaki_dot``)."""
    from ..config import get_configuration, resolve_platform_auto

    return resolve_platform_auto(
        get_configuration().ozaki_dot, knob="ozaki_dot",
        tpu_choice="bf16", other_choice="int8",
        detail="routes bit-identical ON DEVICE and at performance parity "
               "at the pipeline level — dot_ab, 2026-08-01 v5e session, "
               "BASELINE.md round 4")


def _group_impl() -> str:
    """Per-shift group summation shape (config ``ozaki_group``): "dots"
    (one dot per slice pair + elementwise group sums) or "concat" (one
    dot per group over k-concatenated operands). Trace-time knob like
    :func:`_slice_dot_impl`; bit-identical results (tests/test_ozaki.py
    TestConcatGroupRoute). "auto" resolves concat on TPU — the
    2026-08-01 dot_ab session measured concat at 16.6 vs 19.1 ms/step
    on chained trailing syrks and 112.1 vs 105.1 GF/s on full config
    #1, confirming the HBM-traffic model — and dots elsewhere."""
    from ..config import get_configuration, resolve_platform_auto

    return resolve_platform_auto(
        get_configuration().ozaki_group, knob="ozaki_group",
        tpu_choice="concat", other_choice="dots",
        detail="concat measured +7% on config #1 and -13% ms/step on "
               "trailing chains, 2026-08-01 v5e session; bit-identical "
               "results")


def _accum_impl() -> str:
    """Schedule of the per-shift group accumulation under the concat
    group form (config ``ozaki_accum``): "xla" (straight-line trace; XLA
    owns the schedule and MAY keep several (m, n) int32 group partials
    live at once — measured at ~13 GB of live ~1 GB planes in the
    N=16384 OOM diag) or "scan" (``lax.scan`` over zero-padded uniform
    shift groups: the loop carry forces one partial + the f64
    accumulator live, O(1) in the slice count). Bit-identical results —
    zero int8 pad columns contribute exactly nothing on either dot
    route. "auto" resolves scan on TPU (session-4d A/B: 119.6 vs 112.8
    GF/s on config #1 at N=4096 — the bounded live set is also the
    faster HBM schedule) and xla elsewhere. The "dots" group form
    ignores this knob (its partials are per-pair and XLA fuses them
    well)."""
    from ..config import get_configuration, resolve_platform_auto

    return resolve_platform_auto(
        get_configuration().ozaki_accum, knob="ozaki_accum",
        tpu_choice="scan", other_choice="xla",
        detail="scan schedule measured 119.6 vs 112.8 GF/s on config #1 "
               "at N=4096 with an O(1) live-partials bound — session 4d, "
               "2026-08-02; bit-identical results")


def _group_scales(s):
    """(s,) f64 per-shift-group fold scales ``2^-q(d+2)`` (cf.
    :func:`_fold_group`)."""
    import numpy as np

    return jnp.asarray(
        [2.0 ** (-SLICE_BITS * (d + 2)) for d in range(s)], dtype=np.float64)


def _pad_k(x, k_pad, axis):
    """Zero-pad int8 slice operand ``x`` to ``k_pad`` along ``axis`` —
    exact on both dot routes (0 * anything accumulates to 0)."""
    pad = k_pad - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _dot_bf16(ia, ib):
    """Exact slice contraction over the native bf16 MXU path: bf16
    operands (exact for 7-bit slices), f32 accumulation (exact while
    ``k * 2^12 <= 2^24``), int32 result (each f32 partial is an integer
    below 2^24, so the cast is exact)."""
    k = ia.shape[-1]
    # single chunk for k <= 2^12; int32 chunk sums stay exact up to
    # 2^31 / 2^24 = 128 chunks, i.e. k < 2^19 — callers route deeper
    # contractions to the int8 path
    acc = None
    for s0 in range(0, k, _K_F32_EXACT):
        p = jnp.matmul(ia[..., s0:s0 + _K_F32_EXACT].astype(jnp.bfloat16),
                       ib[..., s0:s0 + _K_F32_EXACT, :].astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        acc = p.astype(jnp.int32) if acc is None else acc + p.astype(jnp.int32)
    return acc


def _dot_i8(ia, ib):
    """Batched exact slice contraction (last axis of ``ia`` with
    second-to-last of ``ib``); route per ``config.ozaki_dot``.

    int8 route: s8 x s8 -> s32. For contraction depth ``k >= 2^19`` a
    single int32 accumulation could wrap (``k * 2^12 >= 2^31`` —
    reachable through ``blas.contract``, which flattens multiple
    contracted dims into one k), so the axis is chunked into exact int32
    partials summed in f64 (the caller's group-sum path is already f64 in
    that regime, since ``s*k*2^12 >= 2^31`` too)."""
    k = ia.shape[-1]
    if _slice_dot_impl() == "bf16" and k < _K_I32_EXACT:
        return _dot_bf16(ia, ib)
    if k < _K_I32_EXACT:
        return jnp.matmul(ia, ib, preferred_element_type=jnp.int32)
    acc = None
    for s0 in range(0, k, _K_CHUNK):
        p = jnp.matmul(ia[..., s0:s0 + _K_CHUNK],
                       ib[..., s0:s0 + _K_CHUNK, :],
                       preferred_element_type=jnp.int32).astype(jnp.float64)
        acc = p if acc is None else acc + p
    return acc


def _fold_group(acc, d, p):
    """Fold one per-shift group into the running f64 accumulator:
    ``acc + P_d 2^-q(d+2)``. The power-of-two constant multiply is exact
    and avoids ldexp (s64 ops). Folding each group as soon as it is
    complete — instead of collecting all ``s`` (m, n) groups and combining
    at the end — keeps at most one group plus the accumulator live, which
    is what lets the unrolled N=16384 factorization fit HBM (the collect-
    then-combine form compiled to a 22.7 GB peak on a 16 GB v5e)."""
    term = p.astype(jnp.float64) * float(2.0 ** (-SLICE_BITS * (d + 2)))
    return term if acc is None else acc + term


def _apply_scales(acc, sa, sb):
    """``((acc * 4) * sa) * sb`` — *4 = the two deferred halvings of
    :func:`_normalize`; the scales multiply in last so nothing overflows
    unless the true result does."""
    return ((acc * 4.0) * sa) * sb


def _use_fused_pallas(k: int) -> bool:
    """Trace-time: route the slice reduction through the fused Pallas kernel
    (config ``ozaki_impl="pallas"``)? Interpret mode keeps it testable off
    TPU; contraction depth is VMEM-bounded. The config check comes first so
    the default jnp path never imports pallas at all."""
    from ..config import get_configuration

    if get_configuration().ozaki_impl != "pallas":
        return False
    from .pallas_ozaki import K_MAX

    return k <= K_MAX


@functools.partial(jnp.vectorize, signature="(m,k),(k,n)->(m,n)",
                   excluded=frozenset({"slices"}))
def _matmul_f64_2d(a, b, *, slices=DEFAULT_SLICES):
    s = int(slices)
    k = a.shape[-1]
    sa = _scale(a, axis=-1)           # (m, 1)
    sb = _scale(b, axis=-2)           # (1, n)
    ia = _peel_slices(_normalize(a, sa), s)
    ib = _peel_slices(_normalize(b, sb), s)
    if _use_fused_pallas(k):
        import jax

        from .pallas_ozaki import fused_slice_product

        hi, lo = fused_slice_product(jnp.stack(ia), jnp.stack(ib),
                                     interpret=jax.default_backend() == "cpu",
                                     dot=_slice_dot_impl())
        acc = hi.astype(jnp.float64) + lo.astype(jnp.float64)
        return _apply_scales(acc, sa, sb)
    # int32 group sums stay exact while (d+1) * k * 2^12 < 2^31
    exact_i32 = (s * k) << (2 * SLICE_BITS - 2) < (1 << 31)
    acc = None
    if _group_impl() == "concat":
        # one dot per shift group over k-concatenated operands: the d+1
        # pair sums ride the MXU accumulator (same integer math as the
        # "dots" form — the concatenated contraction is exactly the sum
        # of the per-pair contractions — so chunking/exactness bounds in
        # _dot_i8/_dot_bf16 apply to (d+1)*k unchanged, and they chunk
        # at depths far above s*k for every supported shape)
        if _accum_impl() == "scan":
            # uniform zero-padded groups scanned with an f64 carry: one
            # int32 partial live instead of (potentially) all s
            k_pad = s * k
            ga = jnp.stack([_pad_k(jnp.concatenate(
                [ia[t] for t in range(d + 1)], axis=-1), k_pad, -1)
                for d in range(s)])
            gb = jnp.stack([_pad_k(jnp.concatenate(
                [ib[d - t] for t in range(d + 1)], axis=-2), k_pad, -2)
                for d in range(s)])

            def body(carry, xs):
                a_d, b_d, scale = xs
                p = _dot_i8(a_d, b_d)
                return carry + p.astype(jnp.float64) * scale, None

            acc0 = jnp.zeros((a.shape[-2], b.shape[-1]), jnp.float64)
            acc, _ = lax.scan(body, acc0, (ga, gb, _group_scales(s)))
            return _apply_scales(acc, sa, sb)
        for d in range(s):
            ga = jnp.concatenate([ia[t] for t in range(d + 1)], axis=-1)
            gb = jnp.concatenate([ib[d - t] for t in range(d + 1)], axis=-2)
            p = _dot_i8(ga, gb)
            acc = _fold_group(acc, d, p)
        return _apply_scales(acc, sa, sb)
    for d in range(s):
        terms = [_dot_i8(ia[t], ib[d - t]) for t in range(d + 1)]
        if exact_i32:
            p = terms[0]
            for t in terms[1:]:
                p = p + t
        else:
            p = terms[0].astype(jnp.float64)
            for t in terms[1:]:
                p = p + t.astype(jnp.float64)
        acc = _fold_group(acc, d, p)
    return _apply_scales(acc, sa, sb)


def matmul_f64(a, b, *, slices: int = DEFAULT_SLICES):
    """``a @ b`` for real float64 inputs through int8 MXU passes.

    Batch dims broadcast like ``jnp.matmul``. ``slices`` trades speed for
    mantissa coverage: gemm count is ``slices*(slices+1)/2``; accuracy is
    ``~2^(-7*slices)`` relative to ``rowmax(a)*colmax(b)`` (8 -> f64-grade,
    6 -> ~f64 with 3 fewer mantissa digits at half the gemms).
    """
    return _matmul_f64_2d(a, b, slices=slices)


@functools.partial(jnp.vectorize, signature="(m,k)->(m,m)",
                   excluded=frozenset({"slices"}))
def _syrk_f64_2d(a, *, slices=DEFAULT_SLICES):
    s = int(slices)
    k = a.shape[-1]
    sa = _scale(a, axis=-1)           # (m, 1)
    ia = _peel_slices(_normalize(a, sa), s)
    if _use_fused_pallas(k):
        import jax

        from .pallas_ozaki import fused_slice_syrk

        # predicated square grid: strictly-upper tiles skip their MXU
        # dots, mirrored here (halves the MXU work vs the general kernel)
        hi, lo = fused_slice_syrk(jnp.stack(ia),
                                  interpret=jax.default_backend() == "cpu",
                                  dot=_slice_dot_impl())
        acc = hi.astype(jnp.float64) + lo.astype(jnp.float64)
        acc = jnp.tril(acc) + jnp.swapaxes(jnp.tril(acc, -1), -1, -2)
        return _apply_scales(acc, sa, jnp.swapaxes(sa, -1, -2))
    exact_i32 = (s * k) << (2 * SLICE_BITS - 2) < (1 << 31)
    cast = (lambda x: x) if exact_i32 else (lambda x: x.astype(jnp.float64))
    acc = None
    if _group_impl() == "concat":
        # one dot for the strict-upper pair half of each shift group
        # (mirrored once), plus the even-shift diagonal pair separately —
        # keeps the syrk MAC halving while the pair sums ride the MXU
        # accumulator; exactness as in _matmul_f64_2d's concat branch
        if _accum_impl() == "scan":
            # scan form of the same math: half-pair concats zero-padded
            # to the widest group, the diagonal pair as a zeroed operand
            # on odd shifts (its dot is then exactly zero — one wasted
            # (m, k) pass per odd shift, ~1/s of a group's MACs)
            halves = [[t for t in range(d // 2 + 1) if t != d - t]
                      for d in range(s)]
            h_pad = max(max((len(h) for h in halves), default=0), 1) * k
            zero = jnp.zeros_like(ia[0])

            def half_cat(idx):
                return _pad_k(jnp.concatenate([ia[t] for t in idx], axis=-1)
                              if idx else zero, h_pad, -1)

            ga = jnp.stack([half_cat(halves[d]) for d in range(s)])
            gb = jnp.stack([half_cat([d - t for t in halves[d]])
                            for d in range(s)])
            gd = jnp.stack([ia[d // 2] if d % 2 == 0 else zero
                            for d in range(s)])

            def body(carry, xs):
                a_d, b_d, d_d, scale = xs
                # cast BEFORE the elementwise pair sum when the group
                # magnitude bound exceeds int32 (same guard as the
                # "dots" branch): g + g.T + diag can wrap in the window
                # where s*k*2^12 >= 2^31 but the half-concat depth is
                # still below _dot_i8's own f64-chunking threshold
                g = cast(_dot_i8(a_d, jnp.swapaxes(b_d, -1, -2)))
                p = g + jnp.swapaxes(g, -1, -2) \
                    + cast(_dot_i8(d_d, jnp.swapaxes(d_d, -1, -2)))
                return carry + p.astype(jnp.float64) * scale, None

            m = a.shape[-2]
            acc, _ = lax.scan(body, jnp.zeros((m, m), jnp.float64),
                              (ga, gb, gd, _group_scales(s)))
            return _apply_scales(acc, sa, jnp.swapaxes(sa, -1, -2))
        for d in range(s):
            half = [t for t in range(d // 2 + 1) if t != d - t]
            p = None
            if half:
                ga = jnp.concatenate([ia[t] for t in half], axis=-1)
                gb = jnp.concatenate([ia[d - t] for t in half], axis=-1)
                # cast before the elementwise pair sum (see the scan
                # body above): int32 g + g.T + diag can wrap where
                # s*k*2^12 >= 2^31 but _dot_i8 still returns int32
                g = cast(_dot_i8(ga, jnp.swapaxes(gb, -1, -2)))
                p = g + jnp.swapaxes(g, -1, -2)
            if d % 2 == 0:
                g = cast(_dot_i8(ia[d // 2], jnp.swapaxes(ia[d // 2], -1, -2)))
                p = g if p is None else p + g
            acc = _fold_group(acc, d, p)
        return _apply_scales(acc, sa, jnp.swapaxes(sa, -1, -2))
    for d in range(s):
        # G_{t,u} with t+u=d: pair (t,u) and (u,t) are mutual transposes —
        # compute the strict-upper half once and mirror (the syrk symmetry
        # saving: ~s^2/4 gemms instead of s^2/2)
        p = None
        for t in range(d // 2 + 1):
            u = d - t
            g = cast(_dot_i8(ia[t], jnp.swapaxes(ia[u], -1, -2)))
            term = g if t == u else g + jnp.swapaxes(g, -1, -2)
            p = term if p is None else p + term
        acc = _fold_group(acc, d, p)
    return _apply_scales(acc, sa, jnp.swapaxes(sa, -1, -2))


def syrk_f64(a, *, slices: int = DEFAULT_SLICES):
    """``a @ a.T`` (symmetric rank-k update) for real float64 ``a`` through
    int8 MXU passes; slices of ``a`` are peeled once and pair symmetry halves
    the gemm count vs :func:`matmul_f64`."""
    return _syrk_f64_2d(a, slices=slices)


# ---------------------------------------------------------------------------
# complex128: composed from real products (3-multiplication Karatsuba form)
# ---------------------------------------------------------------------------

def matmul_c128(a, b, *, slices: int = DEFAULT_SLICES):
    """``a @ b`` for complex128 inputs via four real :func:`matmul_f64`
    products, each on the int8 MXU path.

    The 3-product Karatsuba form (``(ar+ai)(br+bi) - p1 - p2``) is NOT used:
    its operand sums overflow for component magnitudes above ``DBL_MAX/2``
    and its intermediates grow ~2x beyond what a native complex product
    forms — the 4-product form has exactly the native overflow and error
    profile, and ozaki gemms are cheap enough that the extra product is the
    right trade."""
    ar, ai = jnp.real(a), jnp.imag(a)
    br, bi = jnp.real(b), jnp.imag(b)
    re = matmul_f64(ar, br, slices=slices) - matmul_f64(ai, bi, slices=slices)
    im = matmul_f64(ar, bi, slices=slices) + matmul_f64(ai, br, slices=slices)
    return lax.complex(re, im)


def herk_c128(a, *, slices: int = DEFAULT_SLICES):
    """``a @ a^H`` (Hermitian gram block) for complex128 ``a``: two real
    syrks for the real part, one real matmul (plus its transpose, free) for
    the imaginary part — 2 peels + ~1.5x one real product's gemm count."""
    ar, ai = jnp.real(a), jnp.imag(a)
    re = syrk_f64(ar, slices=slices) + syrk_f64(ai, slices=slices)
    m = matmul_f64(ai, jnp.swapaxes(ar, -1, -2), slices=slices)
    return lax.complex(re, m - jnp.swapaxes(m, -1, -2))
