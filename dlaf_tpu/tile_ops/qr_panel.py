"""Panel Householder QR with a TPU-trustworthy precision path.

The framework's panel factorizations (reduction_to_band's reflector
panels — its sole consumer; the QR T-factor algorithm takes already-
computed reflectors and is unaffected) ride XLA's ``geqrf`` primitive by
default off-TPU (LAPACK — f64-grade) and this module's ``householder_qr``
on TPU.

History: built while chasing the session-4d red2band ~1e-5 TPU check
failures, as the prime-suspect replacement for geqrf. The silicon probes
(``scripts/tpu_geqrf_probe.py``) then EXONERATED geqrf — its expansion is
f64-grade on device (backward error ~2e-14 at every red2band panel
shape); the real culprit was the ozaki peel's use of the emulated-f64
``round`` (see ``tile_ops/ozaki.py _peel_slices``). The sweep earned the
TPU default anyway on throughput: red2band 4096/512/band128 scan measured
74.9 GF/s under it vs 49.3 under the geqrf expansion (+52%, equal
7e-14-grade residuals, post-peel-fix, 2026-08-02 v5e) — XLA's expansion
pays per-block dispatch this single fused loop avoids.

``householder_qr`` is the classical column Householder sweep (LAPACK
``geqrf``'s own algorithm — reference tile op ``dlaf/lapack/tile.h``
geqrf wrapper) in plain jnp elementwise / reduction / outer-product ops.
One ``lax.fori_loop`` iteration per column keeps the compile cost O(1) in
the panel width; the per-column work is one masked column reduction + one
rank-1 update of the trailing columns — ``m*k`` elements each, the same
flop count as any Householder QR. A width-``k`` panel costs ``k``
sequential steps; red2band panels are ``k = band`` (128-512) on ``m`` up
to the matrix size.

``panel_qr`` is the drop-in ``geqrf`` replacement used by the algorithm
layer: it dispatches per the ``qr_panel`` config knob ("auto" = the
householder sweep on TPU, the LAPACK-backed primitive elsewhere).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

__all__ = ["householder_qr", "panel_qr", "rebuild_q"]


def _qr_panel_impl() -> str:
    """"geqrf" (XLA primitive) or "householder" (this module); "auto"
    resolves householder on TPU — a pure PERFORMANCE choice: red2band
    panels measured +52% under this sweep vs the geqrf expansion at
    equal (7e-14) accuracy — and geqrf (= LAPACK) elsewhere."""
    from ..config import get_configuration, resolve_platform_auto

    return resolve_platform_auto(
        get_configuration().qr_panel, knob="qr_panel",
        tpu_choice="householder", other_choice="geqrf",
        detail="the jnp householder sweep measured 74.9 GF/s vs 49.3 for "
               "XLA's geqrf expansion on red2band 4096 scan at equal "
               "7e-14-grade residuals — 2026-08-02 v5e")


@functools.partial(jnp.vectorize, signature="(m,k)->(m,k),(p)")
def householder_qr(a):
    """Column Householder QR of a panel ``a``, in ``geqrf``'s output
    convention: R in the upper triangle (diagonal = the real beta
    values), the reflector tails strictly below it, and ``taus`` of
    shape (min(m, k),) with ``H_j = I - tau_j v_j v_j^H``
    (``v_j[j] = 1``). Matches LAPACK ``*larfg``'s sign choice
    (``beta = -sign(Re alpha) * ||x||``), zero-tail columns produce
    ``tau = 0`` exactly as LAPACK does; wide panels (m < k — the ragged
    final panel of a reduction) reduce min(m, k) columns like geqrf.

    Scope note (documented like tile_ops/ozaki.py): no lassq-style
    rescaling against overflow of ``sum |x|^2`` — on TPU the f64
    emulation is range-limited to f32's exponents anyway, and panels here
    are slices of already well-scaled matrices.
    """
    m, k = a.shape
    kk = min(m, k)                      # columns that get a reflector
    dtype = a.dtype
    cplx = jnp.issubdtype(dtype, jnp.complexfloating)
    rows = jnp.arange(m)
    cols = jnp.arange(k)
    taus0 = jnp.zeros((kk,), dtype=dtype)

    def body(j, carry):
        a, taus = carry
        col = lax.dynamic_slice_in_dim(a, j, 1, axis=1)[:, 0]   # (m,)
        alpha = lax.dynamic_slice_in_dim(col, j, 1)[0]
        below = rows > j
        tail = jnp.where(below, col, jnp.zeros_like(col))
        sigma = jnp.sum(jnp.abs(tail) ** 2)                     # real
        alphr = jnp.real(alpha)
        norm2 = jnp.abs(alpha) ** 2 + sigma
        beta_r = -jnp.sign(jnp.where(alphr == 0, jnp.ones_like(alphr),
                                     alphr)) * jnp.sqrt(norm2)
        # tau = 0 (null reflector, column already reduced): zero tail and,
        # for complex, a real diagonal entry
        null = (sigma == 0) & ((jnp.imag(alpha) == 0) if cplx else True)
        beta = beta_r.astype(dtype)
        tau = jnp.where(null, jnp.zeros((), dtype),
                        ((beta - alpha) / beta).astype(dtype))
        denom = alpha - beta
        scale = jnp.where(null, jnp.zeros((), dtype), 1.0 / denom)
        v = jnp.where(below, col * scale, jnp.zeros_like(col))
        v = jnp.where(rows == j, jnp.ones((), dtype), v)        # v_j = 1
        v = jnp.where(rows < j, jnp.zeros((), dtype), v)
        # apply H^H = I - conj(tau) v v^H to the trailing columns (cols >
        # j) — LAPACK zgeqr2 applies the ADJOINT reflector there while
        # storing tau itself for Q = H_1 ... H_k (real: conj is identity).
        # Earlier columns hold stored reflectors; later rows of col j are
        # written as the stored tail below.
        vha = jnp.conj(v) @ a                                    # (k,)
        upd = jnp.conj(tau) * v[:, None] * vha[None, :]
        a = a - jnp.where(cols[None, :] > j, upd, jnp.zeros_like(upd))
        # column j: R above (rows < j untouched), beta on the diagonal
        # (alpha when null), stored tail below
        dcol = jnp.where(rows < j, col,
                         jnp.where(rows == j,
                                   jnp.where(null, alpha, beta),
                                   jnp.where(null, col, col * scale)))
        a = lax.dynamic_update_slice_in_dim(a, dcol[:, None], j, axis=1)
        taus = jnp.where(jnp.arange(kk) == j, tau, taus)
        return a, taus

    a, taus = lax.fori_loop(0, kk, body, (a, taus0))
    return a, taus


def rebuild_q(vfull, taus):
    """Host-side (numpy, true f64) accumulation of the first ``k`` columns
    of ``Q = H_0 H_1 ... H_{k-1}`` from stored reflectors — the
    verification oracle shared by the unit tests and
    ``scripts/tpu_geqrf_probe.py``: any precision loss in ``vfull``/
    ``taus`` shows up as backward error against the input panel."""
    import numpy as np

    v = np.asarray(vfull)
    taus = np.asarray(taus)
    m, k = v.shape
    q = np.eye(m, k, dtype=v.dtype)
    for j in reversed(range(len(taus))):
        w = np.zeros(m, dtype=v.dtype)
        w[j] = 1.0
        w[j + 1:] = v[j + 1:, j]
        q -= taus[j] * np.outer(w, np.conj(w) @ q)
    return q


def panel_qr(a):
    """Drop-in ``geqrf`` replacement for panel factorizations: returns
    ``(vfull, taus)`` with R in ``vfull``'s upper triangle and reflector
    tails below. Dispatches per config ``qr_panel`` (see
    :func:`_qr_panel_impl`); both routes share output convention, so call
    sites are route-agnostic."""
    if _qr_panel_impl() == "householder":
        return householder_qr(a)
    from jax._src.lax.linalg import geqrf

    return geqrf(a)
