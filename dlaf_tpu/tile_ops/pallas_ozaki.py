"""Fused Pallas kernel for the Ozaki slice products (opt-in).

The jnp path of :mod:`.ozaki` materializes every per-shift int32 group
(``s`` arrays of the full output shape) before the f64 combine — for a
3840x3840 trailing update that is ~0.5 GB of intermediate HBM traffic per
product. This kernel keeps the whole reduction in VMEM: for each output
tile it runs all ``s(s+1)/2`` int8 MXU dots, accumulates each shift group
exactly in int32, and folds the groups into a double-f32 accumulator
(Knuth two-sum), writing ONE (hi, lo) pair to HBM.

Accuracy: the int8 dots and int32 group sums are exact (same argument as
ozaki.py); the double-f32 fold carries ~48 mantissa bits vs the jnp path's
full f64 combine (~53) — a few bits under native f64, far inside the
``60 n eps`` algorithm budgets, and documented at the knob
(``Configuration.ozaki_impl``, default "jnp" = full accuracy).

VMEM budget: ``s*(BM + BN)*K`` int8 + ``BM*BN`` int32 + 2 f32 — with the
default 256-blocks and s=8 that is 4 MiB of slices + ~0.75 MiB accumulators
at K=1024 (~4.75 MiB total); the wrapper falls back to the jnp path beyond
``K_MAX``.

:func:`fused_slice_syrk` is the symmetric variant: a square tile grid
whose strictly-upper cells are predicated off (``pl.when`` on the program
ids) so only lower-triangle output tiles run their MXU dots — halving the
MXU work of the general kernel for the Cholesky trailing update; the
caller mirrors the strict lower triangle. (An earlier triangular-grid
form drove the block index maps through scalar-prefetched (i, j) lookup
tables; the v5e tunnel's chipless AOT Mosaic compiler cannot legalize
SMEM loads inside index-map functions — observed 2026-07-31 — so the
predicated square grid, whose index maps are pure program-id arithmetic,
is the portable design. Dead cells still pay their block fetch, not
their dots.)

Status: validated in interpret mode (CPU CI); MXU-hardware timing pending —
this is the designated next perf lever for the trailing update (the int8
dots run at ~4.5 TF/s standalone while the jnp ozaki syrk lands at ~650
GF/s effective; the gap is intermediate traffic this kernel removes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .ozaki import SLICE_BITS

#: Largest contraction depth the fused kernel accepts (VMEM bound).
K_MAX = 1024


def _two_sum(a, b):
    """Knuth two-sum: s + err == a + b exactly (f32)."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _fold_body(s: int, ia_ref, ib_ref, hi_ref, lo_ref, rhs_contract: int,
               dot: str = "int8"):
    """Shared numerical body: per-shift int32 group accumulation, exact
    int32 -> double-f32 split (|p| <= s*k*2^12 < 2^27, so the residual
    after the f32 round fits f32 exactly), and the two-sum fold.
    ``rhs_contract`` picks the rhs contraction axis (0: (K, BN) blocks;
    1: (BN, K) blocks as in the syrk form, contracting K against K).
    ``dot``: "int8" (s8 MXU dot) or "bf16" — cast the slices in VMEM and
    contract on the native bf16 path with f32 accumulation, exact for
    the K <= K_MAX <= 2^12 depths this kernel accepts (same bound
    argument as ozaki._dot_bf16); bit-identical outputs."""
    bm = hi_ref.shape[0]
    bn = hi_ref.shape[1]
    hi = jnp.zeros((bm, bn), jnp.float32)
    lo = jnp.zeros((bm, bn), jnp.float32)
    for d in range(s):
        p = jnp.zeros((bm, bn), jnp.int32)
        for t in range(d + 1):
            if dot == "bf16":
                g = jax.lax.dot_general(
                    ia_ref[t].astype(jnp.bfloat16),
                    ib_ref[d - t].astype(jnp.bfloat16),
                    dimension_numbers=(((1,), (rhs_contract,)), ((), ())),
                    preferred_element_type=jnp.float32).astype(jnp.int32)
            else:
                g = jax.lax.dot_general(
                    ia_ref[t], ib_ref[d - t],
                    dimension_numbers=(((1,), (rhs_contract,)), ((), ())),
                    preferred_element_type=jnp.int32)
            p = p + g
        phi = p.astype(jnp.float32)
        plo = (p - phi.astype(jnp.int32)).astype(jnp.float32)
        scale = float(2.0 ** (-SLICE_BITS * (d + 2)))  # exact pow2 mult
        hi, err = _two_sum(hi, phi * scale)
        lo = lo + (err + plo * scale)
    hi_ref[:] = hi
    lo_ref[:] = lo


def _make_kernel(s: int, dot: str):
    def kernel(ia_ref, ib_ref, hi_ref, lo_ref):
        _fold_body(s, ia_ref, ib_ref, hi_ref, lo_ref, rhs_contract=0,
                   dot=dot)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("block_m", "block_n", "interpret", "dot"))
def fused_slice_product(ia, ib, *, block_m: int = 256, block_n: int = 256,
                        interpret: bool = False, dot: str = "int8"):
    """All-shift Ozaki reduction of stacked int8 slices, fused per tile.

    ``ia``: (s, M, K) int8 slices of the normalized A; ``ib``: (s, K, N) of
    B. Returns ``(hi, lo)`` float32 arrays with
    ``hi + lo ~= sum_{t+u=d<s} 2^(-q(d+2)) IA_t @ IB_u``
    (the caller applies ``*4*sa*sb`` in f64, as :func:`ozaki._apply_scales`).
    M/N are padded to block multiples internally.
    """
    s, m, k = ia.shape
    n = ib.shape[-1]
    assert k <= K_MAX, f"fused kernel contraction depth {k} > {K_MAX}"
    pm = (-m) % block_m
    pn = (-n) % block_n
    if pm:
        ia = jnp.pad(ia, ((0, 0), (0, pm), (0, 0)))
    if pn:
        ib = jnp.pad(ib, ((0, 0), (0, 0), (0, pn)))
    mp, np_ = m + pm, n + pn
    grid = (mp // block_m, np_ // block_n)
    hi, lo = pl.pallas_call(
        _make_kernel(s, dot),
        out_shape=(jax.ShapeDtypeStruct((mp, np_), jnp.float32),
                   jax.ShapeDtypeStruct((mp, np_), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((s, block_m, k), lambda i, j: (0, i, 0)),
            pl.BlockSpec((s, k, block_n), lambda i, j: (0, 0, j)),
        ],
        out_specs=(pl.BlockSpec((block_m, block_n), lambda i, j: (i, j)),
                   pl.BlockSpec((block_m, block_n), lambda i, j: (i, j))),
        interpret=interpret,
    )(ia, ib)
    return hi[:m, :n], lo[:m, :n]


#: Largest tile edge the predicated per-tile-pair kernel accepts: its
#: per-cell VMEM is ~2*s*mb^2 int8 slice blocks + int32/f32 accumulators
#: + two mb^2 f32 outputs — ~1.8 MiB at mb=256 (safe with pipelining),
#: ~14 MiB at mb=512 (over budget with double buffering). Distinct from
#: K_MAX, which budgets the fixed-256-block matmul/syrk kernels' depth.
MASKED_MB_MAX = 256


def _make_masked_kernel(s: int, dot: str):
    def kernel(mode_ref, ia_ref, ib_ref, hi_ref, lo_ref):
        # whole (R, C) mode table in SMEM, indexed by the grid step in the
        # kernel BODY: TPU lowering rejects sub-(8, 128) SMEM blocks (the
        # earlier (1, 1)-block form — r4 session finding), and loads
        # inside the INDEX MAP failed Mosaic AOT legalization (r2 session
        # finding). A program_id-indexed body load is the form the Pallas
        # docs sanction for per-cell predication, but whether it legalizes
        # on the chipless AOT path is UNVERIFIED — no pallas_call compiles
        # through the current tunnel at all (docs/ROUND4.md)
        mode = mode_ref[pl.program_id(0), pl.program_id(1)]

        @pl.when(mode == 0)
        def _():
            hi_ref[...] = jnp.zeros_like(hi_ref)
            lo_ref[...] = jnp.zeros_like(lo_ref)

        @pl.when(mode > 0)
        def _():
            # both operands are row blocks contracting k against k — the
            # syrk rhs layout, so the shared fold applies unchanged
            _fold_body(s, ia_ref, ib_ref, hi_ref, lo_ref, rhs_contract=1,
                       dot=dot)

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret", "dot"))
def masked_slice_product(ia, ib, mode, *, interpret: bool = False,
                         dot: str = "int8"):
    """Per-tile-pair Ozaki slice reduction, PREDICATED on ``mode``: pairs
    with ``mode[r, c] == 0`` skip the MXU work entirely (outputs zero).

    The exact-flop form of the distributed Cholesky trailing update
    (reference hot loop ``factorization/cholesky/impl.h:242-271``): only
    trailing lower-triangle tile pairs run their ``s(s+1)/2`` int8 dots,
    instead of computing the full rectangle and masking (~2x the flops).

    ``ia``: (s, R, bm, k) int8 slices of the row-side tiles; ``ib``:
    (s, C, bn, k) of the column-side tiles (both contract their LAST axis);
    ``mode``: (R, C) int32. Returns ``(hi, lo)`` float32 (R, C, bm, bn)
    with ``hi + lo ~= sum_d 2^(-q(d+2)) IA_t @ IB_u^T``; the caller applies
    ``*4*sa*sb`` in f64 and its element masks, as :func:`ozaki._apply_scales`.
    """
    s, R, bm, k = ia.shape
    C, bn = ib.shape[1], ib.shape[2]
    assert max(bm, bn, k) <= MASKED_MB_MAX, \
        f"masked kernel tile edge {max(bm, bn, k)} > {MASKED_MB_MAX}"
    # None block dims squeeze the R/C axes away, so the kernel sees the
    # same (s, b, k)/(b, b) refs as the matmul/syrk kernels and shares
    # their _fold_body
    hi, lo = pl.pallas_call(
        _make_masked_kernel(s, dot),
        grid=(R, C),
        in_specs=[
            pl.BlockSpec((R, C), lambda r, c: (0, 0),
                         memory_space=pltpu.SMEM),                   # mode
            pl.BlockSpec((s, None, bm, k), lambda r, c: (0, r, 0, 0)),
            pl.BlockSpec((s, None, bn, k), lambda r, c: (0, c, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, None, bm, bn), lambda r, c: (r, c, 0, 0)),
            pl.BlockSpec((None, None, bm, bn), lambda r, c: (r, c, 0, 0))),
        out_shape=(jax.ShapeDtypeStruct((R, C, bm, bn), jnp.float32),
                   jax.ShapeDtypeStruct((R, C, bm, bn), jnp.float32)),
        interpret=interpret,
    )(mode, ia, ib)
    return hi, lo


def _make_syrk_kernel(s: int, dot: str):
    def kernel(ia_ref, ja_ref, hi_ref, lo_ref):
        r = pl.program_id(0)
        c = pl.program_id(1)

        @pl.when(c > r)
        def _():
            # strictly-upper tile: mirrored by the caller, never computed
            hi_ref[...] = jnp.zeros_like(hi_ref)
            lo_ref[...] = jnp.zeros_like(lo_ref)

        @pl.when(c <= r)
        def _():
            # rhs blocks are (BN, K) row blocks of the SAME operand:
            # contract the K axes directly (no transposed copy)
            _fold_body(s, ia_ref, ja_ref, hi_ref, lo_ref, rhs_contract=1,
                       dot=dot)

    return kernel


@functools.partial(jax.jit, static_argnames=("block", "interpret", "dot"))
def fused_slice_syrk(ia, *, block: int = 256, interpret: bool = False,
                     dot: str = "int8"):
    """Symmetric fused reduction: lower-triangle tiles of the gram product
    of the stacked slices ``ia`` (s, M, K) with themselves.

    Returns ``(hi, lo)`` float32 (M, M) pairs whose LOWER triangle (block
    diagonal included, full blocks) is valid; tiles strictly above the
    block diagonal skip their MXU dots (``pl.when`` predication on the
    program ids) — the caller mirrors: ``C = tril(H) + tril(H, -1).T``.
    Halves the MXU work of :func:`fused_slice_product` for syrk-shaped
    uses; see the module docstring for why the grid is a predicated
    square rather than a scalar-prefetched triangle.
    """
    s, m, k = ia.shape
    assert k <= K_MAX, f"fused kernel contraction depth {k} > {K_MAX}"
    pm = (-m) % block
    if pm:
        ia = jnp.pad(ia, ((0, 0), (0, pm), (0, 0)))
    mp = m + pm
    nt = mp // block
    hi, lo = pl.pallas_call(
        _make_syrk_kernel(s, dot),
        out_shape=(jax.ShapeDtypeStruct((mp, mp), jnp.float32),
                   jax.ShapeDtypeStruct((mp, mp), jnp.float32)),
        grid=(nt, nt),
        in_specs=[
            pl.BlockSpec((s, block, k), lambda i, j: (0, i, 0)),
            pl.BlockSpec((s, block, k), lambda i, j: (0, j, 0)),
        ],
        out_specs=(pl.BlockSpec((block, block), lambda i, j: (i, j)),
                   pl.BlockSpec((block, block), lambda i, j: (i, j))),
        interpret=interpret,
    )(ia, ia)
    return hi[:m, :m], lo[:m, :m]
