"""Fused Pallas panel factorization — the ``tpu_lapack`` panel shim.

The blocked factorizations' critical path is the per-step PANEL chain:
potrf on the diagonal tile, then the panel TRSM against it. On the XLA
route both lower to chains of small latency-bound thunks (XLA's generic
blocked Cholesky emits a while loop of tiny solves; the panel trsm is a
separate TriangularSolve op), so every step pays dispatch latency that no
amount of MXU throughput can hide — the 1.9-7.3% MFU signature in
BASELINE.md where neither the compute nor the HBM roofline binds. The
reference dispatches exactly this path to hand-tuned ``cusolver`` tile
kernels; this module is the TPU analog (BASELINE north star "tpu_lapack
shim"): Pallas kernels that factor/solve the whole panel without leaving
VMEM, one ``pallas_call`` per panel step instead of one XLA op (or op
chain) per tile.

Kernels
-------

:func:`fused_potrf`
    Right-looking Cholesky of ONE nb x nb tile, entirely in VMEM: the
    kernel body is statically unrolled over a micro-block ladder (width
    :data:`MICRO`) — within a micro-block, ``rsqrt``-scaled column
    updates (VPU rank-1s on the narrow micro-panel); between
    micro-blocks, ONE MXU ``dot_general`` applies the rank-``MICRO``
    trailing update. Exact right-looking flops, no HBM round trips
    between columns. Failure semantics match ``tile_ops.lapack
    .potrf_info``'s contract: a non-positive pivot turns into
    ``rsqrt(d) = NaN/inf`` which propagates into every later column, so
    the factor's diagonal is non-finite from the first failing column on
    (the info scan reads exactly that prefix).

:func:`fused_panel_solve`
    The panel TRSM applied to the stacked strip of below-diagonal tiles
    with the factored diagonal held in VMEM: the kernel grids over the
    strip's tile axis; grid step 0 builds the triangular inverse of the
    diagonal factor into VMEM scratch (micro-blocked substitution,
    statically unrolled), and every step then applies it as ONE MXU gemm
    — the TPU grid is sequential, so the scratch inverse persists across
    steps and is derived once per ``pallas_call``, not once per tile.

Numerics contract: the fused route is NOT bitwise-equal to the XLA route
(different factorization order within the tile; explicit-inverse solve
application) — parity is pinned at documented ulp-level bounds instead
(tests/test_pallas_panel.py, docs/pallas_panel.md). WITHIN the fused
route all the bitwise knob contracts hold unchanged (``cholesky_lookahead``
/ ``comm_lookahead`` on/off, ``with_info`` on/off): the kernels are pure
deterministic functions and those knobs only reorder emission.

Supported dtypes: float32 / bfloat16 (MXU-native; compute in f32, cast
back). float64/complex stay on the XLA (or mixed) route — on TPU their
panel latency problem is already attacked by ``tile_ops.mixed``'s
f32-seed-plus-Newton path, whose *seed* is exactly the shape this kernel
accelerates next.

Status: validated in interpret mode (CPU CI) like every Pallas kernel in
this repo — the axon tunnel's remote compile helper still rejects all
``pallas_call`` compiles (docs/ROUND4.md), so silicon timing is pending.

Routing (``panel_impl`` knob — "fused" / "xla" / "auto"): single owner
:func:`panel_uses_fused`; the builders call :func:`panel_potrf` /
:func:`panel_solve`, which also maintain the trace-time
``dlaf_panel_kernel_total{impl,op}`` counters. ``auto`` = fused on TPU
for f32/bf16 inputs, xla elsewhere. An EXPLICIT ``panel_impl="fused"``
with an unsupported dtype registers through
``health.registry.report_fallback(site="panel")`` (counted, strict-mode
raise); ``health.inject.disable_pallas`` covers the route like every
pallas kernel.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import obs

#: Micro-block width of the potrf ladder and the in-kernel triangular
#: inverse: 8 = the f32 sublane, so every micro-panel/row op is at least
#: one full VPU sublane wide.
MICRO = 8

#: Largest diagonal-tile edge the fused panel route accepts (route
#: policy, like pallas_ozaki.MASKED_MB_MAX): the potrf ladder and the
#: solve's scratch inverse hold O(nb^2) f32 working values in VMEM —
#: ~0.75 MiB at nb=256 plus the strip tile being solved; 512 would put
#: the solve step's live set past comfortable double-buffering.
PANEL_MB_MAX = 256

_SUPPORTED = (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))


def _pad_size(m: int, interpret: bool) -> int:
    """Padded square edge: micro-block multiple always; full (8, 128)
    lane alignment when headed for the Mosaic compiler (interpret mode
    keeps the pad minimal so tiny-tile tests stay cheap)."""
    s = -(-m // MICRO) * MICRO
    if not interpret:
        s = -(-s // 128) * 128
    return s


def _identity_pad(a, s: int):
    """Embed the (m, m) block top-left in an (s, s) identity-padded
    block: ``chol(blkdiag(A, I)) = blkdiag(chol(A), I)`` and a
    triangular ``blkdiag(T, I)`` inverts blockwise, so the pad region
    never contaminates the sliced-back result."""
    m = a.shape[-1]
    if s == m:
        return a
    pad = jnp.arange(s) >= m
    out = jnp.zeros((s, s), a.dtype).at[:m, :m].set(a)
    return out + jnp.diag(pad.astype(a.dtype))


# ---------------------------------------------------------------------------
# fused_potrf
# ---------------------------------------------------------------------------

def _potrf_ladder(x, s: int):
    """Statically-unrolled right-looking micro-block ladder on the
    f32 lower triangle ``x`` (strictly-upper entries are never read:
    the column mask zeroes them before use, and the caller tril-masks
    the result). ``rsqrt``-scaled columns: a non-positive pivot yields
    NaN/inf that propagates to every later column — the
    ``potrf_info`` failure contract.

    In-kernel updates use ``lax.dynamic_update_slice`` with static
    starts (jnp ``.at`` set/add lowers to a scatter whose empty index
    array Pallas rejects as a captured constant)."""
    upd_at = jax.lax.dynamic_update_slice
    for j0 in range(0, s, MICRO):
        m = s - j0
        p = x[j0:, j0:j0 + MICRO]                      # (m, MICRO) panel
        rows = jax.lax.broadcasted_iota(jnp.int32, (m,), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (MICRO,), 0)
        for jj in range(MICRO):
            d = p[jj, jj]
            col = jnp.where(rows >= jj, p[:, jj] * jax.lax.rsqrt(d), 0.0)
            # rank-1 update of the micro-panel's LATER columns only;
            # the factor row entries of those columns are col[:MICRO]
            later = jnp.where(cols > jj, col[:MICRO], 0.0)
            p = p - col[:, None] * later[None, :]
            p = jnp.where((cols == jj)[None, :], col[:, None], p)
        x = upd_at(x, p, (j0, j0))
        if j0 + MICRO < s:
            l21 = p[MICRO:, :]                          # (m-MICRO, MICRO)
            upd = jax.lax.dot_general(
                l21, l21, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            j1 = j0 + MICRO
            x = upd_at(x, x[j1:, j1:] - upd, (j1, j1))
    return x


def _make_potrf_kernel(uplo: str, s: int):
    def kernel(a_ref, out_ref):
        a = a_ref[...].astype(jnp.float32)
        rows = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        tril = rows >= cols
        if uplo == "L":
            f = _potrf_ladder(jnp.where(tril, a, 0.0), s)
            # factor in the stored triangle, the other passes through
            out = jnp.where(tril, f, a)
        else:
            # U^H U = A from the stored UPPER triangle: run the ladder
            # on A^T's lower triangle, transpose the factor back
            at = jnp.where(tril, a.T, 0.0)
            f = _potrf_ladder(at, s).T
            out = jnp.where(~tril | (rows == cols), f, a)
        out_ref[...] = out.astype(out_ref.dtype)

    return kernel


@functools.partial(jax.jit, static_argnames=("uplo", "interpret"))
def _fused_potrf(a, *, uplo: str, interpret: bool = False):
    m = a.shape[-1]
    s = _pad_size(m, interpret)
    ap = _identity_pad(a, s)
    out = pl.pallas_call(
        _make_potrf_kernel(uplo, s),
        out_shape=jax.ShapeDtypeStruct((s, s), a.dtype),
        interpret=interpret,
    )(ap)
    return out[:m, :m]


def fused_potrf(uplo: str, a, *, interpret: bool = False):
    """Cholesky factor of one SPD block stored in ``uplo``, as ONE fused
    Pallas kernel (micro-blocked right-looking ladder in VMEM). Same
    LAPACK storage semantics as ``tile_ops.lapack.potrf``: the factor
    lands in the ``uplo`` triangle, the opposite triangle of ``a``
    passes through. f32/bf16 only (computed in f32)."""
    assert a.ndim == 2 and a.shape[-1] == a.shape[-2], a.shape
    assert jnp.dtype(a.dtype) in _SUPPORTED, a.dtype
    fn = _fused_potrf
    if not _tracing(a):
        return obs.telemetry.call("pallas_panel.potrf", fn, a, uplo=uplo,
                                  interpret=interpret)
    return fn(a, uplo=uplo, interpret=interpret)


# ---------------------------------------------------------------------------
# fused_panel_solve
# ---------------------------------------------------------------------------

def _micro_inv_lower(d):
    """Inverse of a MICRO x MICRO lower-triangular block by statically
    unrolled forward substitution (all columns at once): row i of X is
    ``(e_i - D[i, :i] X[:i]) / D[i, i]``."""
    w = d.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (w,), 0)
    x = jnp.zeros((w, w), d.dtype)
    for i in range(w):
        e = (cols == i).astype(d.dtype)
        if i:
            e = (e - d[i:i + 1, :i] @ x[:i, :]).reshape(w)
        x = jax.lax.dynamic_update_slice(x, (e / d[i, i])[None], (i, 0))
    return x


def _tri_inv_lower(t, s: int):
    """Inverse of the (s, s) lower triangle ``t``, micro-blocked and
    statically unrolled: each ladder step inverts its MICRO-wide
    diagonal block by substitution and fills the block row below the
    already-inverted prefix with two small gemms
    (``-D^-1 R X_prefix``)."""
    upd_at = jax.lax.dynamic_update_slice
    x = jnp.zeros_like(t)
    for j0 in range(0, s, MICRO):
        dinv = _micro_inv_lower(t[j0:j0 + MICRO, j0:j0 + MICRO])
        if j0:
            r = t[j0:j0 + MICRO, :j0]
            blkrow = -(dinv @ (r @ x[:j0, :j0]))
            x = upd_at(x, blkrow, (j0, 0))
        x = upd_at(x, dinv, (j0, j0))
    return x


def _make_solve_kernel(uplo: str, op: str, diag: str, s: int):
    """Right-side canonical solve kernel: each grid step computes
    ``out = b_block @ op(inv(T))`` with ``T`` the stored (identity-
    padded) triangle. The scratch inverse is built ONCE at grid step 0
    (the TPU grid is sequential, so it persists across steps)."""

    def kernel(a_ref, b_ref, out_ref, inv_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            t = a_ref[...].astype(jnp.float32)
            rows = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
            tri = rows >= cols if uplo == "L" else rows <= cols
            t = jnp.where(tri, t, 0.0)
            if diag == "U":
                ondiag = rows == cols
                t = jnp.where(ondiag, 1.0, t)
            if uplo == "L":
                inv_ref[...] = _tri_inv_lower(t, s)
            else:
                inv_ref[...] = _tri_inv_lower(t.T, s).T

        b = b_ref[...].astype(jnp.float32)
        inv = inv_ref[...]
        # contract b's columns against op(inv): "N" uses inv's rows,
        # "T"/"C" (real dtypes only) its columns
        rhs_dim = 0 if op == "N" else 1
        out = jax.lax.dot_general(
            b, inv, dimension_numbers=(((1,), (rhs_dim,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[...] = out.astype(out_ref.dtype)

    return kernel


@functools.partial(jax.jit, static_argnames=("uplo", "op", "diag",
                                             "interpret"))
def _fused_solve_rows(a, b, *, uplo: str, op: str, diag: str,
                      interpret: bool = False):
    """Canonical right-side solve ``X op(T) = B`` over the rows of the
    2D ``b`` (free axis first): rows are independent, so the kernel
    grids over row blocks of the padded triangle's edge."""
    na = a.shape[-1]
    f = b.shape[0]
    s = _pad_size(na, interpret)
    ap = _identity_pad(a, s)
    rb = s
    fp = -(-max(f, 1) // rb) * rb
    bp = jnp.zeros((fp, s), b.dtype).at[:f, :na].set(b)
    out = pl.pallas_call(
        _make_solve_kernel(uplo, op, diag, s),
        grid=(fp // rb,),
        in_specs=[
            pl.BlockSpec((s, s), lambda i: (0, 0)),
            pl.BlockSpec((rb, s), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rb, s), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((fp, s), b.dtype),
        scratch_shapes=[pltpu.VMEM((s, s), jnp.float32)],
        interpret=interpret,
    )(ap, bp)
    return out[:f, :na]


def fused_panel_solve(side: str, uplo: str, op: str, diag: str, a, b, *,
                      alpha=1.0, interpret: bool = False):
    """Panel TRSM against ONE triangular block ``a``, fused: one
    ``pallas_call`` for the WHOLE (possibly batched) strip ``b``,
    batched over the strip's tile axis via the Pallas grid, with the
    factored diagonal (its in-kernel triangular inverse) held in VMEM
    scratch across grid steps.

    Same call convention as ``tile_ops.blas.trsm_panel`` (solve
    ``op(A) X = alpha B`` for side='L' / ``X op(A) = alpha B`` for 'R';
    ``b`` 2D or a stacked (R, nb, nb) tile batch). Left-side solves are
    mapped to the right-side canonical kernel through the transpose
    identity ``op(A) X = B  <=>  X^T op'(A) = B^T`` (real dtypes: 'C'
    == 'T'); the transposes are cheap XLA relayouts outside the single
    kernel. f32/bf16 only."""
    assert a.ndim == 2 and jnp.dtype(a.dtype) in _SUPPORTED, (a.shape,
                                                              a.dtype)
    out_dtype = b.dtype
    if alpha != 1.0:
        b = (alpha * b).astype(out_dtype)
    flip = {"N": "T", "T": "N", "C": "N"}
    if side == "L":
        bt = jnp.swapaxes(b, -1, -2)
        out = fused_panel_solve("R", uplo, flip[op], diag, a, bt,
                                interpret=interpret)
        return jnp.swapaxes(out, -1, -2)
    shape = b.shape
    b2 = b.reshape(-1, shape[-1])
    kw = dict(uplo=uplo, op="T" if op == "C" else op, diag=diag,
              interpret=interpret)
    if not _tracing(a, b2):
        out = obs.telemetry.call("pallas_panel.solve", _fused_solve_rows,
                                 a, b2, **kw)
    else:
        out = _fused_solve_rows(a, b2, **kw)
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# Fused STEP kernels (step_impl route, docs/pallas_panel.md)
# ---------------------------------------------------------------------------

def _factor_into(a_ref, fac_ref, inv_ref, s: int):
    """Grid-step-0 shared prologue of the fused step kernels: run the
    micro-block potrf ladder on the identity-padded diagonal tile, write
    the factor out with the LAPACK pass-through triangle, and build the
    factor's triangular inverse into VMEM scratch for the strip solve
    (the sequential TPU grid keeps both resident across grid steps)."""
    a = a_ref[...].astype(jnp.float32)
    rows = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
    tril = rows >= cols
    f = _potrf_ladder(jnp.where(tril, a, 0.0), s)
    fac_ref[...] = jnp.where(tril, f, a).astype(fac_ref.dtype)
    inv_ref[...] = _tri_inv_lower(jnp.where(tril, f, 0.0), s)


def _make_factor_solve_kernel(s: int):
    """2-op step kernel (canonical lower/right form): grid step 0
    factors the diagonal tile and derives its inverse into scratch;
    every grid step then applies the inverse to its strip block as ONE
    MXU gemm — potrf + whole-strip solve in a single ``pallas_call``,
    the factor never round-tripping to HBM between the two ops."""

    def kernel(a_ref, b_ref, fac_ref, out_ref, inv_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            _factor_into(a_ref, fac_ref, inv_ref, s)

        b = b_ref[...].astype(jnp.float32)
        out = jax.lax.dot_general(
            b, inv_ref[...], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[...] = out.astype(out_ref.dtype)

    return kernel


def _make_step_kernel(s: int, w: int):
    """3-op step kernel (canonical lower form): the factor+solve
    prologue of :func:`_make_factor_solve_kernel` plus the ADJACENT
    trailing-update slab consumed in the same kernel. Block 0's solved
    strip rows (the rows aligned with the slab's columns) persist in a
    second VMEM scratch square across the sequential grid, and every
    grid step subtracts its ``p_i p_0^H`` outer product from its slab
    block under the trailing lower-triangle mask (``w`` = the slab's
    true column count). The solved strip never leaves VMEM between the
    solve and the slab gemm that consumes it."""

    def kernel(a_ref, b_ref, c_ref, fac_ref, p_ref, nc_ref, inv_ref,
               p0_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            _factor_into(a_ref, fac_ref, inv_ref, s)

        b = b_ref[...].astype(jnp.float32)
        p = jax.lax.dot_general(
            b, inv_ref[...], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p_ref[...] = p.astype(p_ref.dtype)

        @pl.when(i == 0)
        def _():
            p0_ref[...] = p

        upd = jax.lax.dot_general(
            p, p0_ref[...], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # strip row (global) vs slab column mask: strictly-below rows
        # take the full update, the leading block its lower triangle;
        # pad columns (>= w) pass the slab through untouched
        grow = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0) + i * s
        col = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        mask = (grow >= col) & (col < w)
        c = c_ref[...].astype(jnp.float32)
        nc_ref[...] = (c + jnp.where(mask, -upd, 0.0)).astype(nc_ref.dtype)

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_factor_solve_rows(diag, b, *, interpret: bool = False):
    """Canonical lower 2-op step over the rows of the 2D strip ``b``."""
    d = diag.shape[-1]
    f = b.shape[0]
    s = _pad_size(d, interpret)
    ap = _identity_pad(diag, s)
    fp = -(-max(f, 1) // s) * s
    bp = jnp.zeros((fp, s), b.dtype).at[:f, :d].set(b)
    fac, out = pl.pallas_call(
        _make_factor_solve_kernel(s),
        grid=(fp // s,),
        in_specs=[
            pl.BlockSpec((s, s), lambda i: (0, 0)),
            pl.BlockSpec((s, s), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((s, s), lambda i: (0, 0)),
            pl.BlockSpec((s, s), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, s), diag.dtype),
            jax.ShapeDtypeStruct((fp, s), b.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((s, s), jnp.float32)],
        interpret=interpret,
    )(ap, bp)
    return fac[:d, :d], out[:f, :d]


def fused_factor_solve(uplo: str, diag, strip, *, interpret: bool = False):
    """Fused panel CHAIN: potrf of the diagonal tile + the whole panel
    strip solve in ONE ``pallas_call`` (the 2-op step kernel — the
    scan/distributed builders' step form, where the trailing slab is
    separated from the panel chain by collectives or traced-index
    masking and cannot join the kernel).

    uplo='L': ``fac = chol(tril(diag))`` (upper triangle passes
    through) and each strip row block solves ``X fac^H = strip`` — the
    ``("R", "L", "C", "N")`` panel convention. uplo='U' is the mirrored
    sweep (``fac^H X = strip``), mapped onto the canonical lower kernel
    through cheap transposes outside the single kernel. ``strip`` is 2D
    (rows, d) or a stacked (R, d, d) tile batch. f32/bf16 only
    (computed in f32)."""
    assert diag.ndim == 2 and jnp.dtype(diag.dtype) in _SUPPORTED, (
        diag.shape, diag.dtype)
    if uplo == "U":
        st = jnp.swapaxes(strip, -1, -2)
        fac, pan = fused_factor_solve("L", diag.T, st, interpret=interpret)
        return fac.T, jnp.swapaxes(pan, -1, -2)
    shape = strip.shape
    b2 = strip.reshape(-1, shape[-1])
    kw = dict(interpret=interpret)
    if not _tracing(diag, b2):
        fac, out = obs.telemetry.call("pallas_panel.factor_solve",
                                      _fused_factor_solve_rows, diag, b2,
                                      **kw)
    else:
        fac, out = _fused_factor_solve_rows(diag, b2, **kw)
    return fac, out.reshape(shape)


@functools.partial(jax.jit, static_argnames=("w", "interpret"))
def _fused_step_lower(diag, strip, slab, *, w: int,
                      interpret: bool = False):
    """Canonical lower 3-op step: pad, grid over the strip's row blocks,
    slice the three outputs back."""
    d = diag.shape[-1]
    m = strip.shape[0]
    s = _pad_size(d, interpret)
    ap = _identity_pad(diag, s)
    r = -(-max(m, 1) // s)
    mp = r * s
    bp = jnp.zeros((mp, s), strip.dtype).at[:m, :d].set(strip)
    cp = jnp.zeros((mp, s), slab.dtype).at[:m, :w].set(slab)
    fac, pan, nc = pl.pallas_call(
        _make_step_kernel(s, w),
        grid=(r,),
        in_specs=[
            pl.BlockSpec((s, s), lambda i: (0, 0)),
            pl.BlockSpec((s, s), lambda i: (i, 0)),
            pl.BlockSpec((s, s), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((s, s), lambda i: (0, 0)),
            pl.BlockSpec((s, s), lambda i: (i, 0)),
            pl.BlockSpec((s, s), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, s), diag.dtype),
            jax.ShapeDtypeStruct((mp, s), strip.dtype),
            jax.ShapeDtypeStruct((mp, s), slab.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((s, s), jnp.float32),
                        pltpu.VMEM((s, s), jnp.float32)],
        interpret=interpret,
    )(ap, bp, cp)
    return fac[:d, :d], pan[:m, :d], nc[:m, :w]


def fused_step(uplo: str, diag, strip, slab, *, interpret: bool = False):
    """One fused Cholesky STEP — panel potrf + panel strip solve + the
    ADJACENT trailing-update slab — as ONE ``pallas_call``: the factor,
    its triangular inverse, and block 0 of the solved strip all stay
    resident in VMEM between the three ops (the ROADMAP item-4 kernel;
    docs/pallas_panel.md "Fused step kernel").

    uplo='L': ``diag`` (d, d) lower-stored, ``strip`` (m, d) the rows
    below the diagonal, ``slab`` (m, w) the first ``w = min(d, m)``
    trailing columns. Returns ``(fac, panel, new_slab)`` where ``fac``
    is the factored tile (opposite triangle passed through), ``panel``
    the solved strip, and ``new_slab = slab - mask(panel panel[:w]^H)``
    with the trailing lower-triangle mask — exactly the builders'
    lookahead-split column strip, so the SSA carry can consume it
    directly. uplo='U' is the mirrored sweep (``strip`` (d, m), ``slab``
    (w, m)), mapped onto the canonical lower kernel through transposes
    outside the single kernel. f32/bf16 only (computed in f32); the
    NaN-prefix ``potrf_info`` failure contract propagates through the
    solve and slab like the composed ops."""
    assert diag.ndim == 2 and jnp.dtype(diag.dtype) in _SUPPORTED, (
        diag.shape, diag.dtype)
    if uplo == "U":
        fac, pan, ns = fused_step("L", diag.T, strip.T, slab.T,
                                  interpret=interpret)
        return fac.T, pan.T, ns.T
    w = slab.shape[-1]
    kw = dict(w=w, interpret=interpret)
    if not _tracing(diag, strip, slab):
        return obs.telemetry.call("pallas_panel.step", _fused_step_lower,
                                  diag, strip, slab, **kw)
    return _fused_step_lower(diag, strip, slab, **kw)


# ---------------------------------------------------------------------------
# Routing — the panel_impl knob's single owner
# ---------------------------------------------------------------------------

def _tracing(*arrs) -> bool:
    """Are we inside a jax trace? (telemetry.call AOT-compiles on
    concrete args only — inside a builder's jit the kernels inline.)"""
    return any(isinstance(x, jax.core.Tracer) for x in arrs)


def panel_uses_fused(dtype, nb: int, platform=None) -> bool:
    """Will the panel chain route through the fused Pallas kernels under
    the current config? Single owner of the ``panel_impl`` route
    decision (mirrors ``blas.f64_gemm_uses_mxu`` /
    ``trsm_panel_uses_mixed``): callers resolve it ONCE per entry and
    thread it into the builders as a static/cache-key argument.

    * ``"xla"`` — never.
    * ``"auto"`` — fused on TPU for f32/bf16 tiles within
      :data:`PANEL_MB_MAX`; everything else is route POLICY (uncounted).
    * ``"fused"`` (explicit) — fused wherever supported (off-TPU the
      call sites run the kernels in interpret mode); an unsupported
      dtype/block registers through ``health.registry.report_fallback``
      (``dlaf_fallback_total{site="panel"}``, strict-mode raise).

    ``health.inject.disable_pallas`` forces the gate closed; when that
    flips a would-be-True answer the degradation is counted at
    ``site="panel"`` like every pallas route.
    """
    from ..config import get_configuration, resolved_panel_impl
    from ..health.registry import report_fallback, route_available

    impl = resolved_panel_impl()
    if impl != "fused":
        return False
    supported = jnp.dtype(dtype) in _SUPPORTED and nb <= PANEL_MB_MAX
    if not supported:
        if get_configuration().panel_impl == "fused":
            # the user explicitly asked for the fused route: landing on
            # XLA is a degradation, not policy — counted, strict raises
            report_fallback(
                "panel", "unsupported_dtype"
                if jnp.dtype(dtype) not in _SUPPORTED else "block_too_large",
                detail=f"dtype={np.dtype(dtype).name} nb={nb} (fused panel "
                       f"needs f32/bf16, nb<={PANEL_MB_MAX})")
        return False
    return route_available("pallas", "panel")


def step_vmem_bytes(nb: int, dtype, interpret: bool = False) -> int:
    """Modeled VMEM live set of the fused 3-op STEP kernel at block edge
    ``nb``: the resident diagonal tile + factor output (single-buffered
    by their constant index maps), double-buffered strip/slab/panel/
    new-slab grid blocks, and the two f32 scratch squares (triangular
    inverse + leading solved strip block). docs/pallas_panel.md walks
    the arithmetic."""
    s = _pad_size(nb, interpret)
    db = jnp.dtype(dtype).itemsize
    return s * s * (2 * db + 8 * db + 2 * 4)


def step_uses_fused(dtype, nb: int) -> bool:
    """Will the blocked-Cholesky STEP route through the fused step
    kernels under the current config? Single owner of the ``step_impl``
    route decision (mirrors :func:`panel_uses_fused`); callers resolve
    it ONCE per entry and thread it into the builders as a static
    cache-key argument.

    * ``"xla"`` — never (the panel chain stays composed ops; the
      ``panel_impl`` route still decides potrf/solve individually).
    * ``"auto"`` — fused on TPU for f32/bf16 within
      :data:`PANEL_MB_MAX` and the ``step_vmem_limit`` budget;
      everything else is route POLICY (uncounted).
    * ``"fused"`` (explicit) — wherever supported (off-TPU the call
      sites run the kernel in interpret mode); an unsupported
      dtype/block or a VMEM-budget overflow registers through
      ``report_fallback(site="step")`` (counted, strict raises).

    An autotune ROUTE override to "fused" binds only on TPU — the
    ladder rung stays behavior-inert on CPU per the docs/autotune.md
    ladder discipline — while explicit config ``step_impl=fused`` binds
    everywhere (tests/CI use it in interpret mode).
    ``health.inject.disable_route("pallas")`` forces the gate closed;
    when that flips a would-be-True answer the degradation is counted
    at ``site="step"`` like every pallas route.
    """
    from ..config import get_configuration, resolved_step_impl
    from ..health.registry import report_fallback, route_available

    impl = resolved_step_impl()
    if impl != "fused":
        return False
    cfg = get_configuration()
    explicit = cfg.step_impl == "fused"
    if not explicit and jax.default_backend() != "tpu":
        # route-override rung relaxing onto "fused" off-TPU: stay inert
        return False
    supported = jnp.dtype(dtype) in _SUPPORTED and nb <= PANEL_MB_MAX
    need = step_vmem_bytes(nb, dtype)
    if not supported or need > cfg.step_vmem_limit:
        if explicit:
            # the user explicitly asked for the fused step: landing on
            # XLA is a degradation, not policy — counted, strict raises
            if not supported:
                reason = ("unsupported_dtype"
                          if jnp.dtype(dtype) not in _SUPPORTED
                          else "block_too_large")
                detail = (f"dtype={np.dtype(dtype).name} nb={nb} (fused "
                          f"step needs f32/bf16, nb<={PANEL_MB_MAX})")
            else:
                reason = "vmem_budget"
                detail = (f"nb={nb}: fused step kernel models ~{need} B "
                          f"VMEM > step_vmem_limit={cfg.step_vmem_limit}")
            report_fallback("step", reason, detail=detail)
        return False
    return route_available("pallas", "step")


def count_step_kernel(impl: str) -> None:
    """Trace-time step-route accounting (once per emitted strip-bearing
    step in the compiled program): how many blocked-factorization steps
    run their panel chain through a fused step kernel vs the composed
    XLA/op chain — ``dlaf_step_kernel_total{impl}``."""
    if obs.metrics_active():
        obs.counter("dlaf_step_kernel_total", impl=impl).inc()


def count_panel_kernel(impl: str, op: str) -> None:
    """Trace-time panel-kernel accounting (once per emitted kernel in
    the compiled program): how many panel potrf/solve steps route
    through the fused kernels vs the XLA op chain."""
    if obs.metrics_active():
        obs.counter("dlaf_panel_kernel_total", impl=impl, op=op).inc()


def panel_potrf(uplo: str, a, *, fused: bool, interpret: bool = False):
    """Route one diagonal-tile potrf: the fused Pallas kernel or the
    XLA route (``tile_ops.lapack.potrf``), counted either way under
    ``dlaf_panel_kernel_total{impl, op="potrf"}``."""
    if fused:
        count_panel_kernel("fused", "potrf")
        return fused_potrf(uplo, a, interpret=interpret)
    from . import lapack as tl

    count_panel_kernel("xla", "potrf")
    return tl.potrf(uplo, a)


def panel_solve(side: str, uplo: str, op: str, diag: str, a, b, *,
                fused: bool, interpret: bool = False, inv_a=None,
                alpha=1.0):
    """Route one panel strip solve: the fused grid-batched kernel or
    the XLA route (``tile_ops.blas.trsm_panel``, which itself honors
    the ``f64_trsm`` mixed path and consumes ``inv_a``), counted under
    ``dlaf_panel_kernel_total{impl, op="solve"}``."""
    if fused:
        count_panel_kernel("fused", "solve")
        return fused_panel_solve(side, uplo, op, diag, a, b, alpha=alpha,
                                 interpret=interpret)
    from . import blas as tb

    count_panel_kernel("xla", "solve")
    return tb.trsm_panel(side, uplo, op, diag, a, b, alpha=alpha,
                         inv_a=inv_a)
