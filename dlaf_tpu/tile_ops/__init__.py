"""L5 tile kernels — tpu_blas / tpu_lapack (reference ``blas/tile.h``,
``lapack/tile.h`` and the custom-kernel layer):

* :mod:`.blas` — level-3 ops (gemm/hemm/her2k/herk/trmm/trsm), the
  mxu-routable ``mm``/``contract``/``trsm_panel`` entry points.
* :mod:`.lapack` — potrf(+info), hegst, laset/lacpy, lange/lantr, larft,
  laed4, stedc (host), and friends.
* :mod:`.ozaki` — emulated-f64/c128 gemm on the int8 MXU (error-free
  slicing); :mod:`.pallas_ozaki` is its fused-kernel variant.
* :mod:`.mixed` — mixed-precision panel potrf / triangular inverse
  (half-precision seed + Newton).
* :mod:`.pallas_kernels` — predicated trailing-update Pallas kernel.
"""

from . import blas, lapack, mixed, ozaki  # noqa: F401

__all__ = ["blas", "lapack", "mixed", "ozaki"]
