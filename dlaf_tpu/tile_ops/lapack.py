"""tpu_lapack — LAPACK tile operations.

TPU-native counterpart of the reference's ``lapack/tile.h:66-645`` (tile-level
``potrf/hegst/laset/lacpy/lange/lantr/larft/stedc/laed4`` dispatched to
lapackpp on CPU, cuSOLVER + custom CUDA kernels on GPU — the custom-kernel
table in SURVEY.md §2/L5). Device ops are pure jnp functions (XLA has native
Cholesky and TriangularSolve; ``lacpy``/``laset`` are trivial masked ops — the
reference needed hand-written CUDA for those, ``src/lapack/gpu/{lacpy,laset}.cu``);
the symmetric-tridiagonal eigensolver leaf (``stedc``) stays a host kernel
exactly as the reference keeps it on CPU (``eigensolver/impl.h:46-72``).

``larft`` replaces the reference's gemv-loop T-factor accumulation
(``factorization/qr/t_factor_impl.h``) with a closed form: for forward
columnwise reflectors, ``T^{-1} = diag(1/tau) + strict_upper(V^H V)``, so T
comes from ONE gemm (MXU) plus one small triangular solve — the TPU-idiomatic
formulation.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from .blas import _diag_of, _embed_diag, hermitian_from, tri_mask, trsm


def laset(uplo: str, alpha, beta, shape, dtype):
    """Fresh block: off-diagonal ``alpha``, diagonal ``beta``, over the
    ``uplo`` region (reference ``tile::laset``; custom CUDA kernel
    ``src/lapack/gpu/laset.cu``)."""
    m, n = shape[-2], shape[-1]
    a = jnp.full(shape, alpha, dtype=dtype)
    a = a + (beta - alpha) * jnp.eye(m, n, dtype=dtype)
    return tri_mask(a, uplo)


def lacpy(uplo: str, a, b):
    """Copy the ``uplo`` region of ``a`` onto ``b`` (reference ``tile::lacpy``;
    custom CUDA kernel ``src/lapack/gpu/lacpy.cu``)."""
    if uplo == "G":
        return a
    keep = "U" if uplo == "L" else "L"
    return tri_mask(a, uplo) + tri_mask(b, keep, k=-1)


def lange(norm: str, a):
    """General-block norm: 'M' (max abs), '1', 'I', 'F'
    (reference ``tile::lange``)."""
    aa = jnp.abs(a)
    if norm == "M":
        return jnp.max(aa, axis=(-2, -1)) if a.size else jnp.zeros(a.shape[:-2], a.dtype)
    if norm == "1":
        return jnp.max(jnp.sum(aa, axis=-2), axis=-1)
    if norm == "I":
        return jnp.max(jnp.sum(aa, axis=-1), axis=-1)
    if norm == "F":
        return jnp.sqrt(jnp.sum(aa * aa, axis=(-2, -1)))
    raise ValueError(f"bad norm {norm!r}")


def lantr(norm: str, uplo: str, diag: str, a):
    """Triangular-block norm (reference ``tile::lantr``)."""
    t = tri_mask(a, uplo)
    if diag == "U":
        t = t - _embed_diag(_diag_of(t), t.shape, t.dtype) + jnp.eye(a.shape[-1], dtype=a.dtype)
    return lange(norm, t)


def potrf(uplo: str, a):
    """Cholesky factor of an SPD/HPD block stored in ``uplo``
    (reference ``tile::potrf``). The factor lands in the ``uplo`` triangle;
    the opposite triangle of ``a`` passes through unchanged (LAPACK in-place
    semantics). Lowers to XLA's native blocked Cholesky on TPU."""
    af = hermitian_from(a, uplo)
    if uplo == "L":
        f = lax.linalg.cholesky(af)
        return tri_mask(f, "L") + tri_mask(a, "U", k=-1)
    f = jnp.conj(jnp.swapaxes(lax.linalg.cholesky(af), -1, -2))
    return tri_mask(f, "U") + tri_mask(a, "L", k=-1)


def potrf_info(uplo: str, a):
    """``potrf`` plus an info value (reference ``tile::potrfInfo``, which
    surfaces the LAPACK/cusolver info instead of asserting): returns
    ``(factor, info)`` with info = 0 on success, nonzero on a failed
    factorization. Unlike LAPACK, info's value does NOT identify the exact
    failing column: XLA backends mark failures by NaN-ing the factor (CPU
    NaNs all of it, TPU's blocked form NaNs from the failing block on), so
    nonzero info is the 1-based index of the first non-finite diagonal —
    a success/failure signal first, a column locator only as far as the
    backend preserves the prefix."""
    f = potrf(uplo, a)
    diag = _diag_of(tri_mask(f, uplo) if uplo != "G" else f)
    bad = ~jnp.isfinite(diag.real) if jnp.iscomplexobj(diag) else ~jnp.isfinite(diag)
    idx = jnp.argmax(bad, axis=-1)
    info = jnp.where(jnp.any(bad, axis=-1), idx + 1, 0)
    return f, info


def laed4(d, z, rho):
    """Secular-equation roots of the rank-one update
    ``D + rho z z^T`` (reference ``tile::laed4`` -> LAPACK ``dlaed4``, the
    D&C merge's per-eigenvalue kernel). Host-side like the reference (it
    keeps laed4 on the CPU even for the GPU backend); delegates to the
    framework's secular solver (native C++ safeguarded Newton, numpy
    bisection fallback — ``eigensolver/tridiag_solver.py``), which also
    provides the device-fused variant for large merges. Returns the k
    updated eigenvalues (ascending)."""
    from ..eigensolver.tridiag_solver import _secular_roots_host

    d = np.asarray(d, dtype=np.float64)
    anchor, offset = _secular_roots_host(d, np.asarray(z, dtype=np.float64),
                                         float(rho))
    return d[anchor] + offset


def hegst(itype: int, uplo: str, a, b):
    """Tile-level generalized-to-standard transform (reference
    ``tile::hegst`` / custom GPU port ``gpu/cusolver/hegst.h``):

    itype=1: ``A := inv(L) A inv(L)^H`` (uplo='L', B = L) or
             ``A := inv(U^H) A inv(U)`` (uplo='U').

    Composed from two XLA triangular solves on the Hermitianized block —
    no custom kernel needed on TPU.
    """
    if itype != 1:
        raise NotImplementedError("hegst itype=2,3 not used by the pipeline")
    af = hermitian_from(a, uplo)
    if uplo == "L":
        t = trsm("L", "L", "N", "N", b, af)         # inv(L) A
        out = trsm("R", "L", "C", "N", b, t)        # ... inv(L)^H
    else:
        t = trsm("L", "U", "C", "N", b, af)         # inv(U)^H A
        out = trsm("R", "U", "N", "N", b, t)        # ... inv(U)
    return _restore_other_triangle(out, a, uplo)


def _restore_other_triangle(update, orig, uplo: str):
    if uplo == "G":
        return update
    other = "U" if uplo == "L" else "L"
    return tri_mask(update, uplo) + tri_mask(orig, other, k=-1)


def larft(v, tau):
    """T factor of a block of forward, columnwise Householder reflectors
    (reference ``tile::larft`` and the distributed T-factor algorithm
    ``factorization/qr/t_factor_impl.h:42-347``).

    ``v``: (m, k) reflectors (unit lower trapezoidal, implicit ones NOT
    required — v's upper triangle is ignored); ``tau``: (k,).
    Uses ``T^{-1} = diag(1/tau) + strict_upper(V^H V)``; zero taus produce
    zero rows/cols in T (null reflectors), as LAPACK does. A zero-tau
    column's stored sub-diagonal is ignored (treated as the null reflector
    it represents) so the closed form matches LAPACK dlarft even when the
    caller left stale data in that column.
    """
    k = tau.shape[-1]
    vlow = tri_mask(v, "L", k=-1)
    # null reflectors (tau==0) must not route cross terms through the Gram:
    # zero their stored sub-diagonal before forming V^H V
    vlow = jnp.where((tau == 0)[..., None, :], jnp.zeros_like(vlow), vlow)
    vv = vlow + jnp.eye(v.shape[-2], k, dtype=v.dtype)
    s = jnp.conj(jnp.swapaxes(vv, -1, -2)) @ vv            # V^H V, one gemm
    tau_safe = jnp.where(tau == 0, jnp.ones_like(tau), tau)
    tinv = tri_mask(s, "U", k=-1) + _embed_diag(1.0 / tau_safe, s.shape, s.dtype)
    eye = jnp.broadcast_to(jnp.eye(k, dtype=v.dtype), s.shape)
    t = lax.linalg.triangular_solve(tinv, eye, left_side=True, lower=False)
    nz = (tau != 0)
    mask = nz[..., :, None] & nz[..., None, :]
    return jnp.where(mask, t, jnp.zeros_like(t))


# ---------------------------------------------------------------------------
# Host kernels (reference keeps these on CPU too)
# ---------------------------------------------------------------------------

def stedc(d: np.ndarray, e: np.ndarray):
    """Host symmetric-tridiagonal eigensolver used for D&C leaf solves
    (reference ``tile::stedc`` -> LAPACK stedc / cusolver syevd wrapper
    ``src/cusolver/stedc.cu``). Returns (eigenvalues, eigenvectors)."""
    import scipy.linalg as sla

    d = np.asarray(d, dtype=np.float64)
    e = np.asarray(e, dtype=np.float64)
    if d.size == 1:
        return d.copy(), np.ones((1, 1))
    w, v = sla.eigh_tridiagonal(d, e)
    return w, v
