"""Mixed-precision f64/c128 panel factorization for TPU: half-precision seed
plus one Newton step.

On TPU, f64 is compiler-emulated (double-double over f32), which makes the
*latency-bound* panel ops of a blocked factorization disproportionately slow:
a 256x256 ``lax.linalg.cholesky`` costs ~16 ms in f64 but ~1.8 ms in f32 on a
v5e, while the flops it performs are trivial. These helpers recover f64-grade
panel results from f32 factorizations plus one Newton-type correction whose
heavy lifting is a handful of small *gemms* (which ARE fast in emulated f64,
being throughput- not latency-bound):

* :func:`potrf_refined`:  ``L32 = chol(f32(A))``, then
  ``L = L32 + L32 * phi(Linv32 E Linv32^T)`` with ``E = A - L32 L32^T`` in
  f64 and ``phi`` = strict lower + half diagonal. One Newton step leaves a
  residual that grows with the block's conditioning (measured ``~6e-16 *
  kappa`` at n=256), so the fast path is gated on a cheap in-program
  condition estimate (:func:`cond_limit`); blocks over the limit take the
  native branch.
* :func:`tri_inv_refined`: explicit ``L^-1`` from the f32 inverse plus one
  Newton iteration ``X <- X + X(I - L X)`` in f64, so a panel solve
  ``P L^-H`` becomes a *gemm* instead of an emulated-f64 triangular solve.

Robustness: the ``lax.cond`` fallback to the native f64 path triggers when
the f32 seed fails outright (non-finite results: block not positive definite
at f32 precision) OR when the condition estimate exceeds :func:`cond_limit`
— the slow-but-sure branch only executes when taken.

The reference has no analog (its panels run on native-f64 hardware); this is
TPU-specific redesign, used by the ``cholesky_trailing="ozaki"`` fast path
together with :mod:`dlaf_tpu.tile_ops.ozaki`.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["potrf_refined", "potrf_inv_refined", "tri_inv_refined",
           "cond_limit"]


def cond_limit() -> float:
    """Conditioning guard for the fast path, as a limit on the squared
    diagonal ratio ``(max diag(L32) / min diag(L32))^2`` (a cheap in-program
    condition estimate of the block: empirically ``residual ~ 3.5e-14 *
    estimate`` for one Newton step, so the default 100 keeps residuals at
    the ``60 n eps`` budget for tile-sized blocks). Blocks estimated worse
    than this take the native emulated-f64 branch.

    Config field ``mixed_cond_limit`` (env ``DLAF_MIXED_COND_LIMIT``,
    CLI ``--dlaf:mixed-cond-limit``) — a real Configuration field so a
    change invalidates registered program caches (the limit is baked into
    compiled ``lax.cond`` guards at trace time)."""
    from ..config import get_configuration

    return float(get_configuration().mixed_cond_limit)


def _seed_dtype(dtype):
    """Half-precision seed dtype: f32 for f64, c64 for c128."""
    return jnp.complex64 if jnp.dtype(dtype).kind == "c" else jnp.float32


def _phi_lower(m):
    """Strict lower triangle plus half the diagonal — the projector that
    maps the Hermitian correction equation onto lower-triangular space. The
    diagonal of the (Hermitian) correction is real up to rounding; its real
    part is taken so the factor's diagonal stays exactly real."""
    d = jnp.diagonal(m, axis1=-2, axis2=-1)
    d = jnp.real(d) if jnp.iscomplexobj(m) else d
    n = m.shape[-1]
    return jnp.tril(m, -1) + 0.5 * d[..., None] * jnp.eye(n, dtype=m.dtype)


def _herm_from_tril(a):
    """Full Hermitian block from its stored lower triangle (real
    diagonal enforced for complex dtypes)."""
    lo = jnp.tril(a, -1)
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    d = jnp.real(d).astype(a.dtype) if jnp.iscomplexobj(a) else d
    n = a.shape[-1]
    return lo + jnp.conj(jnp.swapaxes(lo, -1, -2)) \
        + d[..., None] * jnp.eye(n, dtype=a.dtype)


def _diag_ratio_sq(tri32):
    """Squared max/min ratio of the (f32) triangular factor's diagonal —
    the conditioning estimate behind :func:`cond_limit`. Non-positive or
    non-finite diagonals map to +inf (forces the native branch)."""
    d = jnp.abs(jnp.diagonal(tri32, axis1=-2, axis2=-1))
    est = (jnp.max(d) / jnp.min(d)) ** 2
    good = jnp.isfinite(est) & (jnp.min(d) > 0)
    return jnp.where(good, est, jnp.inf)


def _chol_inv_seed_recursive(a, base: int):
    """(chol(a), inv(chol(a))) in the seed dtype via TRACE-TIME recursive
    block decomposition: leaves call the native kernels at ``base`` size;
    every upper level composes with gemms only —

        L = [[L11, 0], [A21 L11^-H, chol(A22 - L21 L21^H)]]
        L^-1 = [[L11^-1, 0], [-L22^-1 L21 L11^-1, L22^-1]]

    — so the loop-based XLA triangular solves disappear above the leaves
    and the sequential latency is leaf chols + MXU gemms (config
    ``mixed_seed="recursive"``; the latency attack docs/ROADMAP.md item 4
    proposes)."""
    n = a.shape[-1]
    if n <= base:
        l = lax.linalg.cholesky(a)
        linv = lax.linalg.triangular_solve(
            l, jnp.eye(n, dtype=a.dtype), left_side=True, lower=True)
        return l, linv
    h = n // 2
    l11, i11 = _chol_inv_seed_recursive(a[:h, :h], base)
    l21 = a[h:, :h] @ jnp.conj(i11).T
    s = a[h:, h:] - l21 @ jnp.conj(l21).T
    l22, i22 = _chol_inv_seed_recursive(s, base)
    i21 = -(i22 @ l21) @ i11
    ztop = jnp.zeros((h, n - h), dtype=a.dtype)
    l = jnp.concatenate([jnp.concatenate([l11, ztop], axis=1),
                         jnp.concatenate([l21, l22], axis=1)], axis=0)
    linv = jnp.concatenate([jnp.concatenate([i11, ztop], axis=1),
                            jnp.concatenate([i21, i22], axis=1)], axis=0)
    return l, linv


def _refined_seed(a):
    """Shared seed+Newton factor body: f32/c64 cholesky seed, its seed
    inverse, and the one-Newton-step refined f64 factor. Returns
    ``(refined_l, linv0, l32)`` — the fused and non-fused entry points
    build on the same refinement so they cannot diverge."""
    from ..config import get_configuration

    cfg = get_configuration()
    sd = _seed_dtype(a.dtype)
    if cfg.mixed_seed == "recursive":
        l32, linv32 = _chol_inv_seed_recursive(a.astype(sd),
                                               int(cfg.mixed_seed_base))
    else:
        l32 = lax.linalg.cholesky(a.astype(sd))
        linv32 = lax.linalg.triangular_solve(
            l32, jnp.eye(a.shape[-1], dtype=sd), left_side=True, lower=True)
    l0 = jnp.tril(l32).astype(a.dtype)
    linv0 = jnp.tril(linv32).astype(a.dtype)
    e = a - l0 @ jnp.conj(l0).T
    m = (linv0 @ e) @ jnp.conj(linv0).T
    return l0 + l0 @ _phi_lower(m), linv0, l32


def _potrf_refined_l(a):
    """Lower-Cholesky of an f64/c128 block via half-precision seed + one
    Newton step (Hermitian-correct: conjugate transposes throughout)."""
    refined, _, l32 = _refined_seed(a)

    def native(_):
        return jnp.tril(lax.linalg.cholesky(a))

    ok = (jnp.all(jnp.isfinite(refined))
          & (_diag_ratio_sq(l32) <= cond_limit()))
    return lax.cond(ok, lambda r: r, native, refined)


def potrf_refined(uplo: str, a):
    """f64/complex128 Cholesky factor of the HPD block ``a`` (``uplo``
    triangle read, other triangle of the *result* zeroed). 2D blocks; the
    seed runs at f32/c64 and one Hermitian Newton step recovers full
    precision.

    uplo='L': returns lower ``L`` with ``L L^H`` = the Hermitian matrix
    rebuilt from the stored lower triangle; uplo='U': returns upper ``U``
    with ``U^H U = a`` (``U = conj(L).T`` of the factorization of the
    Hermitian rebuild of ``conj(a).T``'s lower storage).
    """
    if uplo == "L":
        sym = _herm_from_tril(a)
        return _potrf_refined_l(sym)
    sym = _herm_from_tril(jnp.conj(a).T)   # upper storage, transposed problem
    return jnp.conj(_potrf_refined_l(sym)).T


def _potrf_inv_refined_l(a):
    """(L, L^-1) fused: the f32 seed solves are shared, so one panel step
    pays ONE latency-bound f32 cholesky + ONE f32 triangular solve instead
    of two solves (potrf_refined already computes the f32 inverse for its
    Newton step; the separate tri_inv_refined re-solved it)."""
    n = a.shape[-1]
    l, linv0, l32 = _refined_seed(a)
    eye = jnp.eye(n, dtype=a.dtype)
    # Newton inverse of the REFINED factor, seeded by the f32 inverse:
    # seed error is f32-rounding + the l0 -> l drift (~f64-grade), so one
    # step lands at the same residual tri_inv_refined reaches
    x = linv0 + linv0 @ (eye - l @ linv0)

    def native(_):
        ln = jnp.tril(lax.linalg.cholesky(a))
        return ln, lax.linalg.triangular_solve(ln, eye, left_side=True,
                                               lower=True)

    ok = (jnp.all(jnp.isfinite(l)) & jnp.all(jnp.isfinite(x))
          & (_diag_ratio_sq(l32) <= cond_limit()))
    return lax.cond(ok, lambda lx: lx, native, (l, x))


def potrf_inv_refined(uplo: str, a):
    """Fused (factor, explicit inverse) of the HPD block ``a`` — same
    contracts as :func:`potrf_refined` + :func:`tri_inv_refined` of its
    result, sharing the half-precision seed solves. uplo='L': ``(L, L^-1)``
    lower; uplo='U': ``(U, U^-1)`` upper (transposed problem)."""
    if uplo == "L":
        return _potrf_inv_refined_l(_herm_from_tril(a))
    l, linv = _potrf_inv_refined_l(_herm_from_tril(jnp.conj(a).T))
    return jnp.conj(l).T, jnp.conj(linv).T


def tri_inv_refined(l, *, lower: bool = True):
    """Explicit f64 inverse of a triangular block: f32 solve + one Newton
    step ``X <- X + X(I - L X)`` (two small f64 gemms). Non-finite f32 seed
    falls back to the native emulated-f64 triangular solve."""
    n = l.shape[-1]
    sd = _seed_dtype(l.dtype)
    eye32 = jnp.eye(n, dtype=sd)
    l32 = l.astype(sd)
    x32 = lax.linalg.triangular_solve(l32, eye32, left_side=True, lower=lower)
    tri = jnp.tril if lower else jnp.triu
    x0 = tri(x32).astype(l.dtype)
    lt = tri(l)
    refined = x0 + x0 @ (jnp.eye(n, dtype=l.dtype) - lt @ x0)

    def native(_):
        return lax.linalg.triangular_solve(lt, jnp.eye(n, dtype=l.dtype),
                                           left_side=True, lower=lower)

    # Newton on the inverse needs ||I - L X0|| < 1, which fails for badly
    # conditioned blocks long before anything overflows — same guard
    ok = (jnp.all(jnp.isfinite(refined))
          & (_diag_ratio_sq(l32) <= cond_limit()))
    return lax.cond(ok, lambda r: r, native, refined)
