"""Pallas TPU kernels for the hot tile ops.

The reference needed custom CUDA kernels where vendor libraries fell short
(SURVEY §2/L5). On TPU most of those collapse into trivial XLA ops; the one
place a custom kernel genuinely pays is the Cholesky trailing update in SPMD
form: the batched einsum over local tile pairs computes the FULL (rows x
cols) rectangle and then masks, spending ~2x the required MXU flops (only
trailing lower-triangle tile pairs matter). This kernel predicates per tile
pair with ``@pl.when``, so masked-out pairs skip the matmul entirely —
exact-flop trailing updates with the masking fused into the epilogue.

``mode`` per tile pair: 0 = untouched, 1 = full update, 2 = update only the
within-tile lower triangle (diagonal tiles of the uplo='L' sweep), 3 = only
the within-tile upper triangle (diagonal tiles of the uplo='U' sweep; the
caller passes transposed panel tiles so the contraction stays vr @ vc^T).

Supported dtypes: float32 / bfloat16 (MXU-native). float64 and complex fall
back to the einsum path at the call site (TPU f64 is emulated anyway; complex
matmul is not a single MXU op).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _update_kernel(mode_ref, vr_ref, vc_ref, a_ref, out_ref):
    # whole (R, C) mode table in SMEM, indexed by the grid step in the
    # kernel body: TPU lowering rejects sub-(8, 128) SMEM blocks (the
    # earlier (1, 1)-block form), and loads inside the INDEX MAP failed
    # Mosaic AOT legalization (r2 session) — same form as
    # pallas_ozaki._make_masked_kernel; body-load legality on the AOT
    # path is still unverified on silicon (no pallas_call compiles via
    # the current tunnel, docs/ROUND4.md)
    mode = mode_ref[pl.program_id(0), pl.program_id(1)]

    @pl.when(mode == 0)
    def _():
        out_ref[...] = a_ref[...]

    @pl.when(mode > 0)
    def _():
        acc = jax.lax.dot_general(
            vr_ref[0], vc_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        upd = a_ref[0].astype(jnp.float32) - acc
        nb = upd.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
        tri = jnp.where(mode == 3, rows <= cols, rows >= cols)
        keep_full = mode == 1
        sel = jnp.where(keep_full | tri, upd, a_ref[0].astype(jnp.float32))
        out_ref[0] = sel.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_trailing_update(a, vr, vc, mode, *, interpret: bool = False):
    """``a[r,c] -= vr[r] @ vc[c]^T`` where ``mode[r,c]`` directs the update
    (0 skip / 1 full / 2 tile lower triangle / 3 tile upper triangle).
    Shapes: a (R, C, nb, nb),
    vr (R, nb, nb), vc (C, nb, nb), mode (R, C) int32."""
    R, C, nb, _ = a.shape
    return pl.pallas_call(
        _update_kernel,
        grid=(R, C),
        in_specs=[
            pl.BlockSpec((R, C), lambda r, c: (0, 0),
                         memory_space=pltpu.SMEM),                 # mode
            pl.BlockSpec((1, nb, nb), lambda r, c: (r, 0, 0)),     # vr
            pl.BlockSpec((1, nb, nb), lambda r, c: (c, 0, 0)),     # vc
            pl.BlockSpec((1, 1, nb, nb), lambda r, c: (r, c, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, nb, nb), lambda r, c: (r, c, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        interpret=interpret,
    )(mode, vr, vc, a)


def supports_pallas_update(dtype, platform: str) -> bool:
    """Gate: MXU-native real dtypes on real TPU hardware.

    ``DLAF_FORCE_PALLAS_UPDATE=1`` drops the platform requirement so tests can
    exercise the Pallas integration path off-TPU (the call site then runs the
    kernel in interpret mode).

    Fault injection (``health.inject.disable_pallas``) forces the gate
    closed; when that flips a would-be-True answer the pallas -> XLA
    degradation is registered (dlaf_fallback_total{site="pallas_update"},
    strict mode raises) — the platform/dtype gate itself is route policy,
    not degradation, and stays uncounted.
    """
    import os

    dtype_ok = jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                    jnp.dtype(jnp.bfloat16))
    supported = dtype_ok if os.environ.get(
        "DLAF_FORCE_PALLAS_UPDATE"  # dlaf: disable=lint-unregistered-knob(CI/test hook forcing the pallas route on CPU interpret mode; not a user-facing runtime knob)
    ) == "1" \
        else (platform == "tpu" and dtype_ok)
    if supported:
        from ..health.registry import route_available

        return route_available("pallas", "pallas_update")
    return supported
