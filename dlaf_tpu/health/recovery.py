"""Shift-retry recovery driver for the Cholesky factorization.

``robust_cholesky`` is the policy layer above ``cholesky(...,
with_info=True)``: the factorization itself stays a pure in-graph program
(info computed on device, no host sync on the hot path); ONLY when the
caller opts into recovery does the driver fetch the info scalar (the one
deliberate host sync, per attempt) and decide. On a nonzero info it
retries with an exponentially growing diagonal shift ``alpha * I`` — the
standard modified-Cholesky response to an indefinite or barely-SPD matrix
(Nocedal & Wright §3.4 spelling; the reference leaves this policy to the
application, surfacing only ``potrfInfo``). Every attempt is traced as a
span carrying ``attempt``/``shift`` attributes so JSONL artifacts record
the whole recovery history, and exhaustion raises the structured
:class:`~dlaf_tpu.health.errors.FactorizationError`.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..config import register_program_cache
from .errors import CheckError, FactorizationError
from .policy import RETRY_COUNTER, RetryPolicy, attempts  # noqa: F401 — re-export (pinned import site)


@dataclasses.dataclass(frozen=True)
class RecoveryResult:
    """Outcome of a successful :func:`robust_cholesky`.

    ``matrix`` holds the factor; ``attempts`` counts factorization
    attempts performed (1 = no recovery was needed); ``shifts``/``infos``
    record the per-attempt diagonal shift and info value (the final info
    is 0 by construction)."""

    matrix: object
    attempts: int
    shifts: tuple
    infos: tuple


@register_program_cache
@functools.lru_cache(maxsize=64)
def _add_diag_prog(dist):
    """Compiled ``(tile storage, alpha) -> storage + alpha*I`` for one
    layout: a static scatter-add into the global diagonal tiles (edge tile
    truncated to the matrix size). ``alpha`` is a traced scalar, so every
    attempt of a retry loop reuses ONE program."""
    from .info import _diag_tile_coords

    coords = _diag_tile_coords(dist)
    mb = dist.block_size.row

    def run(storage, alpha):
        eye = jnp.eye(mb, dtype=storage.dtype)
        for si, sj, ts in coords:
            e = eye if ts == mb else eye * (jnp.arange(mb) < ts)[:, None]
            storage = storage.at[si, sj].add(alpha.astype(storage.dtype) * e)
        return storage

    return jax.jit(run)


def shift_diagonal(mat, alpha):
    """``mat + alpha * I`` as a new Matrix (same layout/sharding). With
    ``alpha == 0`` this is the fresh-copy idiom — the retry loop's
    attempts all consume copies so the original survives for the next
    shift."""
    return mat.with_storage(
        _add_diag_prog(mat.dist)(mat.storage, jnp.asarray(alpha)))


def check_finite(what: str, mat) -> None:
    """Opt-in finite guard (``DLAF_CHECK``): raise :class:`CheckError`
    naming ``what`` when the matrix carries non-finite elements. Host-
    syncs by design — callers gate it on the config knob."""
    s = mat.storage
    finite = jnp.isfinite(s.real) & jnp.isfinite(s.imag) \
        if jnp.iscomplexobj(s) else jnp.isfinite(s)
    count = int(jnp.sum(~finite))
    if count:
        obs.counter("dlaf_check_failures_total", what=what).inc()
        raise CheckError(what, count)


def checks_enabled() -> bool:
    """Is the opt-in finite guard on (``DLAF_CHECK``)?"""
    from ..config import get_configuration

    return bool(get_configuration().check)


def robust_cholesky(uplo: str, mat, *, max_attempts: int = 4,
                    shift: Optional[float] = None,
                    shift_growth: float = 1e4) -> RecoveryResult:
    """Factorize ``mat`` with in-graph failure detection and bounded
    shift-retry recovery.

    Attempt 0 runs unshifted. On a nonzero info (1-based first failing
    global column), the matrix is re-shifted from the ORIGINAL as
    ``A + alpha*I`` with ``alpha`` starting at ``shift`` (default
    ``sqrt(eps) * max|A|``) and growing by ``shift_growth`` per retry —
    exponential backoff bounded by ``max_attempts`` total attempts. Each
    attempt is traced as a ``robust_cholesky.attempt`` span with
    ``attempt``/``shift``/``info`` attrs; retries count under
    ``dlaf_retry_total{algo="cholesky"}``. Exhaustion raises
    :class:`FactorizationError`; success returns a
    :class:`RecoveryResult`.

    With ``DLAF_CHECK=1`` the input and the returned factor additionally
    pass a finite guard (:func:`check_finite`) — e.g. a NaN planted by
    :func:`dlaf_tpu.health.inject.nan_tile` fails fast here instead of
    surfacing as an unexplained nonzero info.

    The original ``mat`` must stay live across attempts (each retry
    shifts it afresh), so unlike ``cholesky`` there is no ``donate``
    option; every attempt's working copy IS donated internally.
    """
    from ..algorithms.cholesky import cholesky

    if max_attempts < 1:
        raise ValueError(f"max_attempts={max_attempts}: must be >= 1")
    if shift is not None and not shift > 0:
        # 0 would alias the first-attempt sentinel: every retry would
        # repeat the identical unshifted factorization
        raise ValueError(f"shift={shift}: must be > 0 (or None for the "
                         "sqrt(eps)*max|A| default)")
    if not shift_growth > 1:
        raise ValueError(f"shift_growth={shift_growth}: must be > 1")
    if checks_enabled():
        check_finite("cholesky input", mat)
    n = mat.size.row
    alpha = 0.0
    shifts, infos = [], []
    log = obs.get_logger("health")
    # the shared policy engine owns attempt counting, retry accounting
    # (one dlaf_retry_total{algo="cholesky"} per retry — the pinned label
    # spelling), resilience records, and (zero, here) backoff; the shift
    # ladder, spans, and FactorizationError stay this driver's contract
    policy = RetryPolicy(max_attempts=max_attempts, backoff_base_s=0.0)
    for a in attempts("robust_cholesky", policy,
                      retry_labels=({"algo": "cholesky"},)):
        attempt = a.index
        span = obs.span("robust_cholesky.attempt", attempt=attempt,
                        shift=float(alpha), n=n, uplo=uplo,
                        dtype=np.dtype(mat.dtype).name)
        with span:
            work = shift_diagonal(mat, alpha)
            out, info_dev = cholesky(uplo, work, donate=True, with_info=True)
            info = int(info_dev)       # the recovery decision point: the
            span.set_attr("info", info)  # driver's deliberate host sync
        shifts.append(float(alpha))
        infos.append(info)
        if info == 0:
            if checks_enabled():
                check_finite("cholesky factor", out)
            return RecoveryResult(out, attempt + 1, tuple(shifts),
                                  tuple(infos))
        a.fail(reason=f"info={info}")
        if attempt + 1 < max_attempts:
            if alpha == 0.0:
                alpha = shift if shift is not None else _default_shift(mat)
            else:
                alpha *= shift_growth
            log.warning(
                f"cholesky info={info} (first failing global column) at "
                f"attempt {attempt}; retrying with diagonal shift "
                f"{alpha:.3e}", n=n, uplo=uplo, attempt=attempt)
    # exhaustion is an incident: capture the flight ring (the attempts'
    # retry records are already in it) before raising
    from ..obs import flight
    flight.trigger("factorization_exhausted", algo="cholesky",
                   attempts=max_attempts, failing_column=int(infos[-1]))
    raise FactorizationError(failing_column=infos[-1],
                             attempts=max_attempts,
                             shifts=tuple(shifts), infos=tuple(infos))


@dataclasses.dataclass(frozen=True)
class BatchRecoveryResult:
    """Outcome of a successful :func:`robust_cholesky_batched`.

    ``out`` holds the ``(B, n, n)`` factor batch; ``attempts`` is the
    max attempts any lane needed (1 = no recovery anywhere);
    ``lane_attempts`` counts attempts per lane; ``shifts`` records the
    per-attempt shared shift scale (first is 0.0); ``infos`` the
    per-attempt full-batch info vectors (lanes already clean in an
    earlier attempt repeat their 0)."""

    out: object
    attempts: int
    lane_attempts: tuple
    shifts: tuple
    infos: tuple


def robust_cholesky_batched(uplo: str, a, *, nb: Optional[int] = None,
                            max_attempts: int = 4,
                            shift: Optional[float] = None,
                            shift_growth: float = 1e4,
                            service=None) -> BatchRecoveryResult:
    """Batched :func:`robust_cholesky`: factorize the ``(B, n, n)`` batch
    ``a`` through :func:`dlaf_tpu.algorithms.batched.cholesky_batched`
    with per-LANE shift-retry recovery.

    Attempt 0 factors the whole batch unshifted. On nonzero lane infos,
    ONLY the failed lanes are re-shifted from the ORIGINAL batch
    (``A_i + alpha*I``; ``alpha`` defaults to ``sqrt(eps) * max|A|`` over
    the batch and grows by ``shift_growth`` per retry) and re-dispatched
    as ONE batch through the SAME warm bucket program — the still-clean
    slots ride as inert identity pad lanes, so a retry never compiles a
    second program or re-factors a lane that already succeeded. Retries
    count per lane under ``dlaf_retry_total{algo="cholesky_batched",
    lane}``; each attempt is a ``robust_cholesky_batched.attempt`` span
    with ``attempt``/``shift``/``lanes`` attrs. Exhaustion raises
    :class:`FactorizationError` whose ``failing_column`` is the first
    still-failing lane's info and whose ``infos`` carry every still-bad
    lane's final info.

    The original ``a`` must stay live across attempts (each retry
    re-shifts the failed subset from it); every dispatched working batch
    is donated internally.
    """
    from ..algorithms.batched import cholesky_batched, default_nb

    if max_attempts < 1:
        raise ValueError(f"max_attempts={max_attempts}: must be >= 1")
    if shift is not None and not shift > 0:
        raise ValueError(f"shift={shift}: must be > 0 (or None for the "
                         "sqrt(eps)*max|A| default)")
    if not shift_growth > 1:
        raise ValueError(f"shift_growth={shift_growth}: must be > 1")
    a = np.asarray(a)
    if a.ndim != 3 or a.shape[1] != a.shape[2]:
        raise ValueError(f"robust_cholesky_batched: expected a (B, n, n) "
                         f"batch, got shape {a.shape}")
    if checks_enabled():
        count = int(np.sum(~np.isfinite(a)))
        if count:
            obs.counter("dlaf_check_failures_total",
                        what="cholesky_batched input").inc()
            raise CheckError("cholesky_batched input", count)
    b_, n = a.shape[0], a.shape[1]
    nb = nb if nb is not None else default_nb(n)
    eye = np.eye(n, dtype=a.dtype)
    log = obs.get_logger("health")
    alpha = 0.0
    shifts, infos_hist = [], []
    lane_attempts = np.zeros(b_, dtype=int)
    out = None
    failed = np.arange(b_)
    # same engine as the singleton driver; retries count PER LANE under
    # the pinned dlaf_retry_total{algo="cholesky_batched", lane} labels
    # via the per-attempt retry_labels override
    policy = RetryPolicy(max_attempts=max_attempts, backoff_base_s=0.0)
    for att in attempts("robust_cholesky_batched", policy):
        attempt = att.index
        span = obs.span("robust_cholesky_batched.attempt", attempt=attempt,
                        shift=float(alpha), lanes=len(failed), batch=b_,
                        n=n, uplo=uplo, dtype=np.dtype(a.dtype).name)
        with span:
            # donated working batch of the FULL bucket width: failed
            # lanes re-shifted from the original, remaining slots inert
            # identity pad lanes (same program, cache stays warm)
            work = np.broadcast_to(eye, a.shape).copy()
            work[failed] = a[failed] + alpha * eye
            fac, info_dev = cholesky_batched(uplo, work, nb=nb,
                                             with_info=True, donate=True,
                                             service=service)
            info = np.asarray(info_dev)      # the one host sync/attempt
            span.set_attr("failed", int(np.count_nonzero(info[failed])))
        lane_attempts[failed] += 1
        # full-batch info vector for the record: untouched lanes are 0
        full_info = np.zeros(b_, dtype=info.dtype)
        full_info[failed] = info[failed]
        shifts.append(float(alpha))
        infos_hist.append(tuple(int(i) for i in full_info))
        newly_ok = failed[full_info[failed] == 0]
        if out is None:
            out = fac
        elif len(newly_ok):
            out = jnp.asarray(out).at[newly_ok].set(fac[newly_ok])
        failed = failed[full_info[failed] != 0]
        if len(failed) == 0:
            return BatchRecoveryResult(
                out, attempts=int(lane_attempts.max(initial=1)),
                lane_attempts=tuple(int(x) for x in lane_attempts),
                shifts=tuple(shifts), infos=tuple(infos_hist))
        att.fail(reason=f"lanes={len(failed)}",
                 retry_labels=tuple({"algo": "cholesky_batched",
                                     "lane": int(lane)}
                                    for lane in failed))
        if attempt + 1 < max_attempts:
            if alpha == 0.0:
                amax = float(np.abs(a).max(initial=0.0)) or 1.0
                eps = float(np.finfo(np.dtype(a.dtype).type(0).real.dtype
                                     ).eps)
                alpha = shift if shift is not None \
                    else float(np.sqrt(eps)) * amax
            else:
                alpha *= shift_growth
            log.warning(
                f"cholesky_batched: {len(failed)} of {b_} lanes failed at "
                f"attempt {attempt} (infos "
                f"{[int(full_info[i]) for i in failed]}); retrying the "
                f"subset with diagonal shift {alpha:.3e}", n=n, uplo=uplo,
                attempt=attempt, lanes=len(failed))
    bad = [int(full_info[i]) for i in failed]
    from ..obs import flight
    flight.trigger("factorization_exhausted", algo="cholesky_batched",
                   attempts=max_attempts, failing_column=bad[0],
                   lanes=len(bad))
    raise FactorizationError(failing_column=bad[0], attempts=max_attempts,
                             shifts=tuple(shifts), infos=tuple(bad))


def _default_shift(mat) -> float:
    """Initial shift scale: ``sqrt(eps) * max|A|`` — large enough to
    regularize rounding-level indefiniteness in one step, small enough to
    stay a perturbation; subsequent retries grow it exponentially."""
    eps = float(np.finfo(np.dtype(mat.dtype).type(0).real.dtype).eps)
    amax = float(jnp.max(jnp.abs(mat.storage))) if mat.storage.size else 1.0
    if not np.isfinite(amax) or amax == 0.0:
        amax = 1.0
    return float(np.sqrt(eps)) * amax
