"""Deterministic, seedable fault injection (tests and chaos drills).

Every degradation path in the framework must be provable end-to-end
WITHOUT breaking a real component (arXiv:2112.09017's pod-scale lesson:
the untested fallback is the one that corrupts silently). This module is
the injection surface the tests use:

* :func:`nan_tile` — pure: returns a copy of a Matrix with one (seeded or
  chosen) element of one tile poisoned to NaN, the stand-in for silent
  numerical corruption.
* :func:`corrupt_collective` — context manager: poisons the payload of
  ONE collective (the ``nth`` traced call of a ``kind``) via a hook in
  :mod:`dlaf_tpu.comm.collectives`. Corruption happens at TRACE time, so
  compiled-program caches are cleared on entry and exit — a cached clean
  program must not mask the injection, and a cached poisoned program must
  not outlive it.
* :func:`disable_route` (and the :func:`disable_pallas` /
  :func:`disable_ozaki` shorthands) — context manager: makes a route gate
  report "unavailable", driving the pallas->XLA / ozaki->plain-dot
  degradations without touching the real gates' inputs.
* :func:`force_native_failure` — context manager: makes
  ``native.bindings`` fail its build/load (covering the cached-error
  re-raise path and every native->numpy chain).
* :func:`fail_dispatch` — context manager: raises inside the serve
  dispatch path on the ``nth`` (and the following ``count - 1``, or
  every ``every``-th) batch dispatch attempt — the transient/flapping
  dispatch fault the retry-policy and circuit-breaker drills need
  (docs/robustness.md).
* :func:`hang` — context manager: arms a CLOCK-AWARE stall at a policy
  site: the policy engine charges the armed seconds against the
  attempt's per-attempt deadline without sleeping real wall time, so
  deadline handling is provable in milliseconds of test time.
* :func:`preempt` — context manager: kills the eigensolver pipeline
  with :class:`~dlaf_tpu.health.errors.PreemptionError` at a chosen
  stage boundary (AFTER that stage's checkpoint landed), so CI can
  prove kill -> resume -> identical-result end-to-end.

All injection state is process-global and OFF by default; the production
cost of the hooks is one module-attribute check. Every context is
reset-safe: its arming clears on exit, and the contexts that can trip
circuit breakers (:func:`force_native_failure`, :func:`disable_route`,
:func:`fail_dispatch`) also reset the breakers they may have opened so
an injected failure storm never fails fast into unrelated code.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import numpy as np

import jax.numpy as jnp

_LOCK = threading.Lock()

#: Armed collective corruption: {"kind", "nth", "seed", "count"} or None.
_COLLECTIVE: Optional[dict] = None

#: Route names currently forced unavailable (see :func:`disable_route`).
_DISABLED_ROUTES: set = set()

#: Armed dispatch fault: {"nth", "count", "every", "exc", "seen"} or None.
_FAIL_DISPATCH: Optional[dict] = None

#: Armed clock-aware stalls: policy site -> seconds.
_HANGS: dict = {}

#: Armed preemption: the stage name to kill at, or None.
_PREEMPT: Optional[str] = None


def _clear_program_caches() -> None:
    from ..config import _clear_program_caches as clear

    clear()


# ---------------------------------------------------------------------------
# Data corruption
# ---------------------------------------------------------------------------

def nan_tile(mat, tile: Optional[tuple] = None,
             element: Optional[tuple] = None, seed: int = 0):
    """A copy of ``mat`` with one element of one tile set to NaN.

    ``tile``: global tile index (i, j); ``element``: (row, col) within the
    tile. Either may be None — a deterministic choice is drawn from
    ``seed`` over the valid range, so repeated runs inject the same fault.
    """
    from ..matrix.tiling import global_tile_to_storage_index

    dist = mat.dist
    nt_r, nt_c = dist.nr_tiles.row, dist.nr_tiles.col
    if nt_r == 0 or nt_c == 0:
        raise ValueError("nan_tile: matrix has no tiles")
    rng = np.random.default_rng(seed)
    ti, tj = tile if tile is not None else (int(rng.integers(nt_r)),
                                            int(rng.integers(nt_c)))
    mb_r = min(dist.block_size.row, dist.size.row - ti * dist.block_size.row)
    mb_c = min(dist.block_size.col, dist.size.col - tj * dist.block_size.col)
    ei, ej = element if element is not None else (int(rng.integers(mb_r)),
                                                  int(rng.integers(mb_c)))
    si, sj = global_tile_to_storage_index(dist, ti, tj)
    poison = jnp.asarray(np.nan, mat.dtype)
    return mat.with_storage(mat.storage.at[si, sj, ei, ej].set(poison))


def _corrupt_payload(x, seed: int):
    """One NaN (max value for integer payloads) at a seeded position."""
    if x.ndim == 0:
        flat = x[None]
    else:
        flat = x.reshape(-1)
    pos = int(np.random.default_rng(seed).integers(flat.shape[0])) \
        if flat.shape[0] else 0
    bad = jnp.asarray(np.nan, x.dtype) if jnp.issubdtype(x.dtype, jnp.inexact) \
        else jnp.asarray(jnp.iinfo(x.dtype).max, x.dtype)
    flat = flat.at[pos].set(bad)
    return flat.reshape(x.shape) if x.ndim else flat[0]


def _kind_matches(armed: str, kind: str) -> bool:
    """Armed ``"bcast"`` also matches the fused 2D diagonal broadcast
    (``"bcast2d"``, comm.collectives) — the drill targets "a broadcast on
    the step critical path", and the bcast2d fusion must not silently
    move that payload out of the corruption's reach."""
    return armed == kind or (armed == "bcast" and kind == "bcast2d")


def _collective_hook(kind: str, axis: str, x):
    """Installed into ``comm.collectives`` while :func:`corrupt_collective`
    is armed; corrupts the payload of the nth matching traced call."""
    with _LOCK:
        spec = _COLLECTIVE
        if spec is None or not _kind_matches(spec["kind"], kind):
            return x
        hit = spec["count"] == spec["nth"]
        spec["count"] += 1
    return _corrupt_payload(x, spec["seed"]) if hit else x


@contextlib.contextmanager
def corrupt_collective(kind: str = "bcast", nth: int = 0, seed: int = 0):
    """Poison the payload of the ``nth`` traced ``kind`` collective
    (``"bcast"`` — which also matches the fused ``"bcast2d"`` diagonal
    broadcast — | ``"all_reduce"`` | ``"bcast2d"``) while the context is
    active."""
    global _COLLECTIVE
    from ..comm import collectives as cc

    with _LOCK:
        if _COLLECTIVE is not None:
            raise RuntimeError("corrupt_collective is not reentrant")
        _COLLECTIVE = {"kind": kind, "nth": int(nth), "seed": int(seed),
                       "count": 0}
    cc._INJECT_HOOK = _collective_hook
    _clear_program_caches()
    try:
        yield
    finally:
        cc._INJECT_HOOK = None
        with _LOCK:
            _COLLECTIVE = None
        _clear_program_caches()


# ---------------------------------------------------------------------------
# Route availability
# ---------------------------------------------------------------------------

def route_disabled(name: str) -> bool:
    """Has injection forced route ``name`` unavailable? Consulted by the
    route gates (``pallas`` — tile_ops.pallas_kernels; ``ozaki`` —
    tile_ops.blas)."""
    return name in _DISABLED_ROUTES


@contextlib.contextmanager
def disable_route(name: str):
    """Force route ``name`` unavailable while active; the owning gate
    reports the degradation through :mod:`dlaf_tpu.health.registry`.
    Program caches are cleared on entry and exit — route choices are
    trace-time decisions. Degradation-site circuit breakers are reset on
    exit: the injected storm must not leave a breaker failing fast into
    real runs."""
    with _LOCK:
        _DISABLED_ROUTES.add(name)
    _clear_program_caches()
    try:
        yield
    finally:
        with _LOCK:
            _DISABLED_ROUTES.discard(name)
        _clear_program_caches()
        _reset_breakers("fallback.")


def disable_pallas():
    """Force every pallas kernel route off (degrades to the XLA forms)."""
    return disable_route("pallas")


def disable_ozaki():
    """Force the int8/bf16 MXU f64 gemm route off (degrades to the
    native dot)."""
    return disable_route("ozaki")


# ---------------------------------------------------------------------------
# Native library failure
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def force_native_failure():
    """Make ``native.bindings`` build/load fail while active (drives every
    native->numpy chain and the cached-error re-raise path). The bindings
    cache is reset on entry and exit so neither a pre-loaded library nor
    the injected failure leaks across the boundary; degradation-site
    circuit breakers reset both ways for the same reason."""
    from ..native import bindings

    _reset_breakers("fallback.")
    bindings._reset_for_tests(force_failure=True)
    try:
        yield
    finally:
        bindings._reset_for_tests(force_failure=False)
        _reset_breakers("fallback.")


def _reset_breakers(prefix: str) -> None:
    from . import circuit

    circuit.reset(prefix)


# ---------------------------------------------------------------------------
# Dispatch / policy-engine faults (docs/robustness.md chaos drills)
# ---------------------------------------------------------------------------

def maybe_fail_dispatch() -> None:
    """Hook consulted by the serve dispatch path once per batch dispatch
    ATTEMPT (so policy retries hit the fault again): raises the armed
    exception when this attempt falls on a faulted index."""
    with _LOCK:
        spec = _FAIL_DISPATCH
        if spec is None:
            return
        idx = spec["seen"]
        spec["seen"] += 1
        if spec["every"] is not None:
            hit = idx >= spec["nth"] and (idx - spec["nth"]) \
                % spec["every"] == 0
        else:
            hit = spec["nth"] <= idx < spec["nth"] + spec["count"]
    if hit:
        raise spec["exc"](f"injected dispatch fault (attempt {idx})")


@contextlib.contextmanager
def fail_dispatch(nth: int = 0, count: int = 1,
                  every: Optional[int] = None, exc: type = RuntimeError):
    """Raise ``exc`` inside the serve dispatch path, deterministically by
    attempt index: attempts ``nth .. nth+count-1`` fail (or, with
    ``every``, every ``every``-th attempt from ``nth`` on — the flapping
    fault the breaker soak test drives). Not reentrant; serve-site
    breakers are reset on exit so an injected failure storm never leaves
    a bucket failing fast into real traffic."""
    global _FAIL_DISPATCH
    if count < 1:
        raise ValueError(f"fail_dispatch: count={count} must be >= 1")
    if every is not None and every < 1:
        raise ValueError(f"fail_dispatch: every={every} must be >= 1")
    with _LOCK:
        if _FAIL_DISPATCH is not None:
            raise RuntimeError("fail_dispatch is not reentrant")
        _FAIL_DISPATCH = {"nth": int(nth), "count": int(count),
                          "every": None if every is None else int(every),
                          "exc": exc, "seen": 0}
    try:
        yield
    finally:
        with _LOCK:
            _FAIL_DISPATCH = None
        _reset_breakers("serve.")


#: Armed fleet-dispatch fault (separate schedule from _FAIL_DISPATCH: the
#: router and its in-process drill workers share one process, and a
#: single global attempt counter would let worker-queue dispatches
#: consume the router's faulted indices nondeterministically).
_FAIL_FLEET: Optional[dict] = None


def maybe_fail_fleet_dispatch() -> None:
    """Hook consulted by the fleet router once per ticket-dispatch
    ATTEMPT (after worker selection, so the fault is charged to the
    routed worker's breaker): raises the armed exception when this
    attempt falls on a faulted index."""
    with _LOCK:
        spec = _FAIL_FLEET
        if spec is None:
            return
        idx = spec["seen"]
        spec["seen"] += 1
        if spec["every"] is not None:
            hit = idx >= spec["nth"] and (idx - spec["nth"]) \
                % spec["every"] == 0
        else:
            hit = spec["nth"] <= idx < spec["nth"] + spec["count"]
    if hit:
        raise spec["exc"](f"injected fleet dispatch fault (attempt {idx})")


@contextlib.contextmanager
def fail_fleet_dispatch(nth: int = 0, count: int = 1,
                        every: Optional[int] = None,
                        exc: type = RuntimeError):
    """The fleet-layer twin of :func:`fail_dispatch` (docs/fleet.md drill
    catalog): raises ``exc`` inside the router's ticket-dispatch attempt,
    deterministically by FLEET attempt index — a schedule independent of
    the serve-queue one, so a drill's router faults replay exactly even
    while in-process workers dispatch concurrently. Not reentrant;
    ``fleet.`` breakers are reset on exit so an injected storm never
    leaves a worker's breaker failing fast into real routing."""
    global _FAIL_FLEET
    if count < 1:
        raise ValueError(f"fail_fleet_dispatch: count={count} must be >= 1")
    if every is not None and every < 1:
        raise ValueError(f"fail_fleet_dispatch: every={every} must be >= 1")
    with _LOCK:
        if _FAIL_FLEET is not None:
            raise RuntimeError("fail_fleet_dispatch is not reentrant")
        _FAIL_FLEET = {"nth": int(nth), "count": int(count),
                       "every": None if every is None else int(every),
                       "exc": exc, "seen": 0}
    try:
        yield
    finally:
        with _LOCK:
            _FAIL_FLEET = None
        _reset_breakers("fleet.")


def hang_seconds(site: str) -> float:
    """Armed clock-aware stall for ``site`` (0.0 when unarmed) — the
    policy engine adds this to each attempt's measured elapsed time, so a
    deadline trips without real wall clock (see :func:`hang`)."""
    with _LOCK:
        return _HANGS.get(site, 0.0)


@contextlib.contextmanager
def hang(site: str, seconds: float):
    """Arm a clock-aware stall at policy site ``site``: while active,
    every attempt the policy engine runs at that site is charged
    ``seconds`` of extra elapsed time against its per-attempt deadline
    (``RetryPolicy.attempt_deadline_s``) WITHOUT sleeping — the
    deterministic stand-in for a hung dispatch/connect that lets deadline
    handling be proven in milliseconds of test time."""
    if not seconds >= 0:
        raise ValueError(f"hang: seconds={seconds} must be >= 0")
    with _LOCK:
        if site in _HANGS:
            raise RuntimeError(f"hang({site!r}) is not reentrant")
        _HANGS[site] = float(seconds)
    try:
        yield
    finally:
        with _LOCK:
            _HANGS.pop(site, None)


# ---------------------------------------------------------------------------
# Preemption (kill-and-resume drills, docs/robustness.md §5)
# ---------------------------------------------------------------------------

def maybe_preempt(stage: str) -> None:
    """Hook the pipeline calls at each stage BOUNDARY (after the stage's
    checkpoint landed): raises PreemptionError when ``stage`` is armed."""
    with _LOCK:
        armed = _PREEMPT
    if armed is not None and armed == stage:
        from .errors import PreemptionError

        from .. import obs

        obs.emit_event("resilience", site="pipeline", event="preempt",
                       attrs={"stage": stage})
        raise PreemptionError(stage)


@contextlib.contextmanager
def preempt(stage: str):
    """Kill the eigensolver pipeline with
    :class:`~dlaf_tpu.health.errors.PreemptionError` at stage boundary
    ``stage`` (one of red2band | b2t | tridiag | bt_b2t | bt_r2b) —
    AFTER that stage's ``DLAF_RESUME_DIR`` checkpoint was written, so the
    kill lands exactly where a real preemption is recoverable. Not
    reentrant; disarms on exit."""
    global _PREEMPT
    with _LOCK:
        if _PREEMPT is not None:
            raise RuntimeError("preempt is not reentrant")
        _PREEMPT = str(stage)
    try:
        yield
    finally:
        with _LOCK:
            _PREEMPT = None
