"""Deterministic, seedable fault injection (tests and chaos drills).

Every degradation path in the framework must be provable end-to-end
WITHOUT breaking a real component (arXiv:2112.09017's pod-scale lesson:
the untested fallback is the one that corrupts silently). This module is
the injection surface the tests use:

* :func:`nan_tile` — pure: returns a copy of a Matrix with one (seeded or
  chosen) element of one tile poisoned to NaN, the stand-in for silent
  numerical corruption.
* :func:`corrupt_collective` — context manager: poisons the payload of
  ONE collective (the ``nth`` traced call of a ``kind``) via a hook in
  :mod:`dlaf_tpu.comm.collectives`. Corruption happens at TRACE time, so
  compiled-program caches are cleared on entry and exit — a cached clean
  program must not mask the injection, and a cached poisoned program must
  not outlive it.
* :func:`disable_route` (and the :func:`disable_pallas` /
  :func:`disable_ozaki` shorthands) — context manager: makes a route gate
  report "unavailable", driving the pallas->XLA / ozaki->plain-dot
  degradations without touching the real gates' inputs.
* :func:`force_native_failure` — context manager: makes
  ``native.bindings`` fail its build/load (covering the cached-error
  re-raise path and every native->numpy chain).

All injection state is process-global and OFF by default; the production
cost of the hooks is one module-attribute check.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import numpy as np

import jax.numpy as jnp

_LOCK = threading.Lock()

#: Armed collective corruption: {"kind", "nth", "seed", "count"} or None.
_COLLECTIVE: Optional[dict] = None

#: Route names currently forced unavailable (see :func:`disable_route`).
_DISABLED_ROUTES: set = set()


def _clear_program_caches() -> None:
    from ..config import _clear_program_caches as clear

    clear()


# ---------------------------------------------------------------------------
# Data corruption
# ---------------------------------------------------------------------------

def nan_tile(mat, tile: Optional[tuple] = None,
             element: Optional[tuple] = None, seed: int = 0):
    """A copy of ``mat`` with one element of one tile set to NaN.

    ``tile``: global tile index (i, j); ``element``: (row, col) within the
    tile. Either may be None — a deterministic choice is drawn from
    ``seed`` over the valid range, so repeated runs inject the same fault.
    """
    from ..matrix.tiling import global_tile_to_storage_index

    dist = mat.dist
    nt_r, nt_c = dist.nr_tiles.row, dist.nr_tiles.col
    if nt_r == 0 or nt_c == 0:
        raise ValueError("nan_tile: matrix has no tiles")
    rng = np.random.default_rng(seed)
    ti, tj = tile if tile is not None else (int(rng.integers(nt_r)),
                                            int(rng.integers(nt_c)))
    mb_r = min(dist.block_size.row, dist.size.row - ti * dist.block_size.row)
    mb_c = min(dist.block_size.col, dist.size.col - tj * dist.block_size.col)
    ei, ej = element if element is not None else (int(rng.integers(mb_r)),
                                                  int(rng.integers(mb_c)))
    si, sj = global_tile_to_storage_index(dist, ti, tj)
    poison = jnp.asarray(np.nan, mat.dtype)
    return mat.with_storage(mat.storage.at[si, sj, ei, ej].set(poison))


def _corrupt_payload(x, seed: int):
    """One NaN (max value for integer payloads) at a seeded position."""
    if x.ndim == 0:
        flat = x[None]
    else:
        flat = x.reshape(-1)
    pos = int(np.random.default_rng(seed).integers(flat.shape[0])) \
        if flat.shape[0] else 0
    bad = jnp.asarray(np.nan, x.dtype) if jnp.issubdtype(x.dtype, jnp.inexact) \
        else jnp.asarray(jnp.iinfo(x.dtype).max, x.dtype)
    flat = flat.at[pos].set(bad)
    return flat.reshape(x.shape) if x.ndim else flat[0]


def _kind_matches(armed: str, kind: str) -> bool:
    """Armed ``"bcast"`` also matches the fused 2D diagonal broadcast
    (``"bcast2d"``, comm.collectives) — the drill targets "a broadcast on
    the step critical path", and the bcast2d fusion must not silently
    move that payload out of the corruption's reach."""
    return armed == kind or (armed == "bcast" and kind == "bcast2d")


def _collective_hook(kind: str, axis: str, x):
    """Installed into ``comm.collectives`` while :func:`corrupt_collective`
    is armed; corrupts the payload of the nth matching traced call."""
    with _LOCK:
        spec = _COLLECTIVE
        if spec is None or not _kind_matches(spec["kind"], kind):
            return x
        hit = spec["count"] == spec["nth"]
        spec["count"] += 1
    return _corrupt_payload(x, spec["seed"]) if hit else x


@contextlib.contextmanager
def corrupt_collective(kind: str = "bcast", nth: int = 0, seed: int = 0):
    """Poison the payload of the ``nth`` traced ``kind`` collective
    (``"bcast"`` — which also matches the fused ``"bcast2d"`` diagonal
    broadcast — | ``"all_reduce"`` | ``"bcast2d"``) while the context is
    active."""
    global _COLLECTIVE
    from ..comm import collectives as cc

    with _LOCK:
        if _COLLECTIVE is not None:
            raise RuntimeError("corrupt_collective is not reentrant")
        _COLLECTIVE = {"kind": kind, "nth": int(nth), "seed": int(seed),
                       "count": 0}
    cc._INJECT_HOOK = _collective_hook
    _clear_program_caches()
    try:
        yield
    finally:
        cc._INJECT_HOOK = None
        with _LOCK:
            _COLLECTIVE = None
        _clear_program_caches()


# ---------------------------------------------------------------------------
# Route availability
# ---------------------------------------------------------------------------

def route_disabled(name: str) -> bool:
    """Has injection forced route ``name`` unavailable? Consulted by the
    route gates (``pallas`` — tile_ops.pallas_kernels; ``ozaki`` —
    tile_ops.blas)."""
    return name in _DISABLED_ROUTES


@contextlib.contextmanager
def disable_route(name: str):
    """Force route ``name`` unavailable while active; the owning gate
    reports the degradation through :mod:`dlaf_tpu.health.registry`.
    Program caches are cleared on entry and exit — route choices are
    trace-time decisions."""
    with _LOCK:
        _DISABLED_ROUTES.add(name)
    _clear_program_caches()
    try:
        yield
    finally:
        with _LOCK:
            _DISABLED_ROUTES.discard(name)
        _clear_program_caches()


def disable_pallas():
    """Force every pallas kernel route off (degrades to the XLA forms)."""
    return disable_route("pallas")


def disable_ozaki():
    """Force the int8/bf16 MXU f64 gemm route off (degrades to the
    native dot)."""
    return disable_route("ozaki")


# ---------------------------------------------------------------------------
# Native library failure
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def force_native_failure():
    """Make ``native.bindings`` build/load fail while active (drives every
    native->numpy chain and the cached-error re-raise path). The bindings
    cache is reset on entry and exit so neither a pre-loaded library nor
    the injected failure leaks across the boundary."""
    from ..native import bindings

    bindings._reset_for_tests(force_failure=True)
    try:
        yield
    finally:
        bindings._reset_for_tests(force_failure=False)
