"""Preemption-safe pipeline resume (docs/robustness.md §5).

The eigensolver pipeline spans five stages and minutes of multi-chip wall
clock on preemptible hardware; before PR 12 a preemption at minute N lost
all N minutes. This module is the generic driver above
:mod:`dlaf_tpu.matrix.checkpoint`'s stage primitives:

* a :class:`StageCheckpointer` bound to ``DLAF_RESUME_DIR`` (config
  ``resume_dir``) and a run FINGERPRINT (config/grid/dtype/shape — the
  identity of the numerical run);
* ``commit(stage, arrays)`` persists a completed stage atomically
  (payload then manifest; a kill mid-write leaves no torn stage), emits a
  ``resilience`` ``checkpoint`` record, and THEN consults
  :func:`dlaf_tpu.health.inject.maybe_preempt` — so the drill's kill
  lands exactly at the recoverable boundary;
* with ``resume=True``, ``completed(stage)`` is True iff the stage's
  manifest exists AND its fingerprint matches this run's — a manifest
  from a different config/grid/dtype raises
  :class:`~dlaf_tpu.health.errors.ResumeError` naming the mismatched
  keys rather than silently recomputing (or worse, silently loading)
  someone else's numbers. Each skipped stage emits a ``resume`` record —
  the audit trail ``--require-resilience`` checks in the CI
  kill-and-resume drill.

The pipeline (``eigensolver(..., resume=True)``) owns the stage payload
packing; this module owns directories, manifests, fingerprints, and the
records. Resumed stages are pinned bitwise against the uninterrupted run
on the native routes (tests/test_resilience.py): a restored payload is
the exact bytes the uninterrupted run produced, and every downstream
stage recomputes from identical inputs.
"""

from __future__ import annotations

import os
from typing import Optional

from .. import obs
from ..matrix import checkpoint as _ckpt
from .errors import ResumeError
from .inject import maybe_preempt


def fingerprint_mismatch(saved: dict, current: dict) -> list:
    """Keys on which two fingerprints disagree (missing counts)."""
    keys = set(saved) | set(current)
    return sorted(k for k in keys if saved.get(k) != current.get(k))


class StageCheckpointer:
    """One pipeline run's checkpoint driver (module docstring).

    ``directory`` empty disables persistence (commits still consult the
    preemption hook, so ``inject.preempt`` drills work without a resume
    dir); ``resume=True`` with no directory raises :class:`ResumeError`
    — a silent full recompute is not what the caller asked for."""

    def __init__(self, pipeline: str, directory: str, fingerprint: dict,
                 *, resume: bool = False):
        self.pipeline = str(pipeline)
        self.directory = (os.path.join(directory, self.pipeline)
                          if directory else "")
        self.fingerprint = {k: fingerprint[k] for k in sorted(fingerprint)}
        self.resume = bool(resume)
        if self.resume and not self.directory:
            raise ResumeError(
                "", "resume=True but no checkpoint directory is "
                "configured — set DLAF_RESUME_DIR (config resume_dir)")

    def completed(self, stage: str) -> bool:
        """Is ``stage`` resumable: manifest present, version compatible,
        fingerprint matching? Only consulted under ``resume=True`` —
        a fresh run never skips stages, whatever is on disk."""
        if not (self.resume and self.directory):
            return False
        manifest = _ckpt.stage_manifest(self.directory, stage)
        if manifest is None:
            return False
        if manifest.get("version") != _ckpt.STAGE_MANIFEST_VERSION:
            raise ResumeError(
                stage, f"manifest version {manifest.get('version')!r} != "
                f"{_ckpt.STAGE_MANIFEST_VERSION} — written by an "
                "incompatible dlaf_tpu; clear the resume dir")
        bad = fingerprint_mismatch(manifest.get("fingerprint") or {},
                                   self.fingerprint)
        if bad:
            saved = manifest.get("fingerprint") or {}
            raise ResumeError(
                stage, "checkpoint fingerprint mismatch on "
                + ", ".join(f"{k} (saved {saved.get(k)!r}, run "
                            f"{self.fingerprint.get(k)!r})" for k in bad)
                + " — these checkpoints belong to a different run; clear "
                  "the resume dir or fix the configuration")
        return True

    def load(self, stage: str) -> dict:
        """The completed stage's array payload; emits the ``resume``
        resilience record (the skip's audit trail)."""
        arrays, _ = _ckpt.load_stage(self.directory, stage)
        obs.emit_event("resilience", site=f"{self.pipeline}.{stage}",
                       event="resume", attrs={"stage": stage})
        obs.get_logger("health").info(
            f"{self.pipeline}: stage {stage!r} resumed from checkpoint "
            f"({self.directory})", stage=stage)
        return arrays

    def commit(self, stage: str, arrays: Optional[dict] = None,
               extra: Optional[dict] = None) -> None:
        """Mark ``stage`` complete: persist (when a directory is
        configured), record, then hand the preemption hook its window —
        the kill point of the chaos drill is AFTER the write, exactly
        where a real preemption is recoverable."""
        if self.directory and arrays is not None:
            _ckpt.save_stage(self.directory, stage, arrays,
                             self.fingerprint, extra=extra)
            obs.emit_event("resilience", site=f"{self.pipeline}.{stage}",
                           event="checkpoint", attrs={"stage": stage})
        maybe_preempt(stage)


_warned_multiprocess = False


def stage_checkpointer(pipeline: str, fingerprint: dict, *,
                       resume: bool = False) -> StageCheckpointer:
    """The pipeline's checkpointer under the config ``resume_dir`` knob
    (``DLAF_RESUME_DIR``); persistence disabled when the knob is empty
    (and ``resume=True`` then raises — see :class:`StageCheckpointer`).

    Stage checkpoints are SINGLE-CONTROLLER only: a multi-process world
    cannot gather sharded storage from one process, and every rank would
    race ``os.replace`` on the same manifest paths. In a multi-process
    world the knob is ignored with a once-per-process warning (the
    pipeline still runs — losing checkpointing must not kill the job it
    protects), and ``resume=True`` refuses loudly."""
    from ..config import get_configuration

    directory = get_configuration().resume_dir
    if directory:
        import jax

        if jax.process_count() > 1:
            if resume:
                raise ResumeError(
                    "", "DLAF_RESUME_DIR stage checkpoints are "
                    "single-controller only (sharded storage is not "
                    "addressable from one process, and ranks would race "
                    "on the manifest files) — resume on a single "
                    "controller")
            global _warned_multiprocess
            if not _warned_multiprocess:
                _warned_multiprocess = True
                obs.get_logger("health").warning(
                    "DLAF_RESUME_DIR is ignored in a multi-process "
                    "world: stage checkpoints are single-controller "
                    "only")
            directory = ""
    return StageCheckpointer(pipeline, directory, fingerprint,
                             resume=resume)
