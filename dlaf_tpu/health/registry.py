"""Unified graceful-degradation policy (docs/robustness.md).

The codebase grew four independent ad-hoc fallback chains — native secular
solver -> numpy bisection, native band chase -> numpy, pallas kernels ->
XLA, ozaki MXU gemm -> plain dot — each with its own bare ``except`` and
no accounting. This module is the single policy they now share:

* every degradation is counted in ``dlaf_fallback_total{site,reason}``
  (:mod:`dlaf_tpu.obs` — visible in JSONL artifacts and the Prometheus
  exposition) and announced once per (site, reason) through the obs
  logger, so a pod silently running 100x slower on an interpreter
  fallback cannot happen;
* strict mode (``DLAF_STRICT=1`` / ``Configuration.strict``) turns every
  degradation into a structured
  :class:`~dlaf_tpu.health.errors.DegradationError` — the CI/bring-up
  stance where a missing native library must fail the job, not quietly
  degrade it.

Sites register a degradation at the moment they *decide* to fall back
(:func:`report_fallback`), or wrap the whole try/except with
:func:`run_with_fallback`. Route *policy* decisions (e.g. the
``f64_gemm_min_dim`` small-gemm gate) are configuration, not degradation,
and are not reported here.
"""

from __future__ import annotations

from typing import Callable, Optional

from .. import obs
from .errors import DegradationError

#: Counter name shared by every degradation site (labels: site, reason).
FALLBACK_COUNTER = "dlaf_fallback_total"


def strict_mode() -> bool:
    """Is strict mode on (``DLAF_STRICT``)? Strict forbids degradation:
    :func:`report_fallback` raises instead of recording-and-continuing."""
    from ..config import get_configuration

    return bool(get_configuration().strict)


def report_fallback(site: str, reason: str, *,
                    exc: Optional[BaseException] = None,
                    detail: str = "") -> None:
    """Record one degradation at ``site`` (counter + one-shot warning);
    raise :class:`DegradationError` in strict mode.

    ``exc`` is the triggering exception, if any — chained onto the strict
    error and included in the announcement. Call this exactly when the
    fallback decision is made; callers then proceed down their degraded
    path (unless this raises)."""
    obs.counter(FALLBACK_COUNTER, site=site, reason=reason).inc()
    why = detail or (repr(exc) if exc is not None else "")
    obs.get_logger("health").warning_once(
        (site, reason),
        f"degraded path at {site!r} ({reason})"
        + (f": {why}" if why else "")
        + " — counting under dlaf_fallback_total; DLAF_STRICT=1 raises "
          "instead",
        site=site, reason=reason)
    if strict_mode():
        err = DegradationError(site, reason, why)
        if exc is not None:
            raise err from exc
        raise err


def run_with_fallback(site: str, primary: Callable, fallback: Callable, *,
                      reason: str = "native_unavailable",
                      expected: type = Exception,
                      use_breaker: bool = True):
    """Run ``primary()``; on ``expected`` record the degradation and run
    ``fallback()`` — the one-policy spelling of the repo's try/except
    chains (the native band-chase/secular/deflate sites). Strict mode
    raises from inside :func:`report_fallback`, so the fallback never
    executes there.

    A per-site circuit breaker (``fallback.<site>``,
    :mod:`dlaf_tpu.health.circuit`) rides the chain: after
    ``DLAF_CIRCUIT_THRESHOLD`` consecutive primary failures the breaker
    opens and the primary is SKIPPED (degradation counted under reason
    ``circuit_open``) until the cooldown's half-open probe — a
    segfault-looping native library stops being re-tried on every call.
    ``use_breaker=False`` opts a site out. The injection contexts reset
    ``fallback.*`` breakers on exit, so injected storms never leak an
    open breaker into real runs."""
    from . import circuit
    from .errors import CircuitOpenError

    br = circuit.breaker(f"fallback.{site}") if use_breaker else None
    if br is not None:
        try:
            br.allow()
        except CircuitOpenError as e:
            report_fallback(site, "circuit_open", exc=e)
            return fallback()
    try:
        result = primary()
    except expected as e:
        if br is not None:
            br.record_failure()
        report_fallback(site, reason, exc=e)
        return fallback()
    except BaseException:
        # an unexpected error still resolves the breaker's probe slot —
        # a stuck half-open probe would reject every later call
        if br is not None:
            br.record_failure()
        raise
    if br is not None:
        br.record_success()
    return result


def route_available(name: str, site: str, reason: str = "injected_off") -> bool:
    """Injection gate shared by the route deciders (tile_ops.blas ozaki,
    tile_ops.pallas_kernels, the dist cholesky ozaki-pallas gate): call
    ONLY after the route's own policy gates said yes. Returns False —
    registering the degradation at ``site`` — when
    :func:`dlaf_tpu.health.inject.disable_route` forced ``name`` off."""
    from .inject import route_disabled

    if route_disabled(name):
        report_fallback(site, reason)
        return False
    return True
