"""dlaf_tpu.health — failure detection, recovery, injection, degradation.

The robustness layer (docs/robustness.md), four surfaces:

* **Info plumbing** — ``cholesky(..., with_info=True)`` returns
  ``(L, info)`` with info = 1-based first failing global column computed
  in-graph (:mod:`.info`); analogous singular-diagonal detection for the
  triangular solve and HEGST (``matrix_diag_info``).
* **Recovery** — :func:`robust_cholesky` retries a failed factorization
  under an exponentially growing diagonal shift, raising a structured
  :class:`FactorizationError` when exhausted; the ``DLAF_CHECK`` knob
  adds opt-in finite guards on inputs/outputs (:mod:`.recovery`).
* **Fault injection** — :mod:`.inject`: deterministic, seedable faults
  (NaN a tile, corrupt one collective, force the native-library load to
  fail, disable a pallas/ozaki route) so every degradation path is
  testable end-to-end.
* **Degradation registry** — :mod:`.registry`: the four ad-hoc fallback
  chains (secular, band chase, pallas, ozaki) share one policy with
  ``dlaf_fallback_total{site,reason}`` counters and a strict mode
  (``DLAF_STRICT``) that raises instead of degrading.
"""

from __future__ import annotations

from . import circuit, info, inject, policy, registry  # noqa: F401
from .circuit import CIRCUIT_GAUGE, CircuitBreaker, breaker  # noqa: F401
from .errors import (AutotuneExhaustedError, CheckError,  # noqa: F401
                     CircuitOpenError, DeadlineExceededError,
                     DegradationError, FactorizationError, HealthError,
                     OverloadError, PreemptionError, ResumeError)
from .info import matrix_diag_info  # noqa: F401
from .policy import (DEADLINE_COUNTER, RETRY_COUNTER, RetryPolicy,  # noqa: F401
                     with_policy)
from .registry import (FALLBACK_COUNTER, report_fallback, route_available,  # noqa: F401
                       run_with_fallback, strict_mode)

__all__ = [
    "AutotuneExhaustedError",
    "CheckError", "CircuitBreaker", "CircuitOpenError",
    "DeadlineExceededError", "DegradationError", "FactorizationError",
    "HealthError", "OverloadError", "PreemptionError", "ResumeError",
    "CIRCUIT_GAUGE", "DEADLINE_COUNTER", "FALLBACK_COUNTER",
    "RETRY_COUNTER", "BatchRecoveryResult", "RecoveryResult", "RetryPolicy",
    "breaker", "check_finite", "circuit", "inject", "info",
    "matrix_diag_info", "policy", "registry", "report_fallback", "resume",
    "robust_cholesky", "robust_cholesky_batched", "route_available",
    "run_with_fallback", "shift_diagonal", "strict_mode", "with_policy",
]

#: Symbols served lazily from .recovery / .resume (they import the matrix
#: layer; keeping them out of package-import time lets low-level modules —
#: comm, tile_ops — consult .inject/.registry/.policy without an import
#: cycle).
_LAZY = ("robust_cholesky", "robust_cholesky_batched", "RecoveryResult",
         "BatchRecoveryResult",
         "check_finite", "shift_diagonal", "recovery")


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        recovery = importlib.import_module(".recovery", __name__)
        globals()["recovery"] = recovery
        return recovery if name == "recovery" else getattr(recovery, name)
    if name == "resume":
        import importlib

        resume = importlib.import_module(".resume", __name__)
        globals()["resume"] = resume
        return resume
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
