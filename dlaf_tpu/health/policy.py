"""Declarative retry/timeout/backoff policy engine (docs/robustness.md §2).

PR 3 left the repo with three hand-rolled retry loops — ``robust_cholesky``'s
shift ladder, its batched twin, and ``initialize_multihost``'s one-shot
coordinator connect — each owning its own attempt counting, backoff, and
accounting. This module is the single engine they (and the PR-12 serving
dispatch path) now share:

* :class:`RetryPolicy` — the declarative policy: total attempt budget,
  exponential backoff with DETERMINISTIC seeded jitter (same policy + same
  retry index => same delay, so drills and tests replay exactly), a
  per-attempt deadline, and retryable-error classification.
* :func:`with_policy` — run an exception-deciding callable under a policy
  (optionally behind a :class:`~dlaf_tpu.health.circuit.CircuitBreaker`):
  retryable failures re-run with backoff, non-retryable ones raise
  immediately, exhaustion re-raises the last error.
* :func:`attempts` — the outcome-deciding driver beneath ``with_policy``,
  for loops whose "failure" is data (a nonzero Cholesky info), not an
  exception: the caller marks an attempt failed and the engine owns the
  retry counting, records, and backoff while the caller keeps its own
  span/error contracts (``robust_cholesky`` rides this, behavior-pinned).

Accounting, uniform across every site: one ``dlaf_retry_total`` increment
per retry (labels chosen by the site — ``{site}`` by default, the pinned
``{algo[,lane]}`` spelling for the recovery drivers), one
``dlaf_deadline_exceeded_total{site}`` per deadline breach, and one
``resilience`` JSONL record per retry / give-up / deadline decision
(schema: :mod:`dlaf_tpu.obs.sinks`; CI obligation:
``python -m dlaf_tpu.obs.validate --require-resilience``).

Error classification (the docs/robustness.md table): exceptions that name
a caller bug or a structured health *decision* (``ValueError``/
``TypeError``/``AssertionError``/``KeyError``/``IndexError``/
``AttributeError``/``NotImplementedError``/``KeyboardInterrupt``/any
:class:`~dlaf_tpu.health.errors.HealthError`) are never retried — a retry
cannot fix them and would mask them. Everything else (``TimeoutError``,
``ConnectionError``, ``OSError``, runtime/backend errors) defaults to
retryable; sites narrow this with ``RetryPolicy(retryable=predicate)``.

Deadline semantics: the per-attempt deadline is measured around the
attempt with the injected ``clock`` plus any armed
:func:`dlaf_tpu.health.inject.hang` stall (clock-aware — no real wall
time burns in tests). An attempt that *raises* late is classified like
any failure; an attempt that *returns* late raises
:class:`~dlaf_tpu.health.errors.DeadlineExceededError` without retrying —
the engine cannot cancel completed work and re-running it would be waste,
so a late success is surfaced as the contract breach it is.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from .. import obs
from .errors import DeadlineExceededError, HealthError

#: Counter incremented once per retry. Labels are site-chosen:
#: ``{site}`` from :func:`with_policy`, the pinned ``{algo[,lane]}``
#: spelling from the recovery drivers (docs/robustness.md §2).
RETRY_COUNTER = "dlaf_retry_total"

#: Counter incremented once per per-attempt-deadline breach (labels: site).
DEADLINE_COUNTER = "dlaf_deadline_exceeded_total"

#: Exception families a retry can never fix (classification table above).
NON_RETRYABLE = (ValueError, TypeError, AssertionError, KeyError,
                 IndexError, AttributeError, NotImplementedError,
                 HealthError)


def default_retryable(exc: BaseException) -> bool:
    """The default classification: retry anything that is a plain
    ``Exception`` and not in :data:`NON_RETRYABLE`."""
    return isinstance(exc, Exception) and not isinstance(exc, NON_RETRYABLE)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """One site's declarative retry policy (module docstring).

    ``max_attempts`` is the TOTAL attempt budget (1 = no retry).
    ``backoff_base_s`` is the delay before the first retry, growing by
    ``backoff_growth`` per retry and capped at ``backoff_max_s``;
    ``jitter`` spreads each delay by up to +-``jitter`` fraction, drawn
    DETERMINISTICALLY from ``(seed, retry index)`` so a replayed drill
    backs off identically. ``attempt_deadline_s`` bounds each attempt's
    wall clock (None = unbounded). ``retryable`` overrides the default
    error classification (a predicate ``exc -> bool``)."""

    max_attempts: int = 3
    backoff_base_s: float = 0.0
    backoff_growth: float = 2.0
    backoff_max_s: float = 60.0
    jitter: float = 0.1
    seed: int = 0
    attempt_deadline_s: Optional[float] = None
    retryable: Optional[Callable[[BaseException], bool]] = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"RetryPolicy.max_attempts={self.max_attempts}:"
                             " must be >= 1")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("RetryPolicy backoff bounds must be >= 0")
        if not self.backoff_growth >= 1:
            raise ValueError(f"RetryPolicy.backoff_growth="
                             f"{self.backoff_growth}: must be >= 1")
        if not 0 <= self.jitter < 1:
            raise ValueError(f"RetryPolicy.jitter={self.jitter}: must be "
                             "in [0, 1)")
        if self.attempt_deadline_s is not None \
                and not self.attempt_deadline_s > 0:
            raise ValueError(f"RetryPolicy.attempt_deadline_s="
                             f"{self.attempt_deadline_s}: must be > 0 "
                             "(or None for unbounded attempts)")

    def is_retryable(self, exc: BaseException) -> bool:
        pred = self.retryable if self.retryable is not None \
            else default_retryable
        return bool(pred(exc))

    def delay_s(self, retry: int) -> float:
        """Backoff before retry number ``retry`` (0-based): exponential,
        capped, with the deterministic seeded jitter. Pure function of
        ``(policy, retry)`` — replays bit-identically."""
        if self.backoff_base_s <= 0:
            return 0.0
        base = min(self.backoff_base_s * self.backoff_growth ** retry,
                   self.backoff_max_s)
        if self.jitter <= 0:
            return base
        u = float(np.random.default_rng(
            (int(self.seed), int(retry))).random())
        return base * (1.0 + self.jitter * (2.0 * u - 1.0))


class Attempt:
    """One attempt of an :func:`attempts` loop. The caller marks it
    failed (requesting another attempt) via :meth:`fail`; an attempt left
    unmarked ends the loop as a success."""

    def __init__(self, index: int):
        self.index = index
        self.failed = False
        self.reason = ""
        self.exc: Optional[BaseException] = None
        self.retry_labels: Optional[tuple] = None

    def fail(self, reason: str = "", exc: Optional[BaseException] = None,
             retry_labels: Optional[tuple] = None) -> None:
        """Mark this attempt failed. ``retry_labels`` (a tuple of label
        dicts) overrides the loop's per-retry counter labels for THIS
        retry — one ``dlaf_retry_total`` increment per dict (the batched
        recovery driver counts per lane this way)."""
        self.failed = True
        self.reason = str(reason)
        self.exc = exc
        if retry_labels is not None:
            self.retry_labels = tuple(retry_labels)


def _emit(site: str, event: str, **fields) -> None:
    """One resilience JSONL record (no-op with the sink off)."""
    attrs = fields.pop("attrs", None) or {}
    obs.emit_event("resilience", site=site, event=event, attrs=attrs,
                   **fields)


def attempts(site: str, policy: RetryPolicy, *,
             retry_labels: Optional[tuple] = None,
             sleep: Optional[Callable[[float], None]] = None):
    """Outcome-driven retry driver: yields :class:`Attempt` objects until
    the policy is exhausted or an attempt is left unmarked (success).

    The engine owns what every hand-rolled loop used to duplicate: on
    each marked failure with budget remaining it increments
    ``dlaf_retry_total`` once per label dict (``retry_labels``, default
    ``({"site": site},)``; overridable per-attempt via
    :meth:`Attempt.fail`), emits a ``resilience`` retry record, and
    sleeps the policy backoff. Exhaustion emits a ``give_up`` record and
    ends the generator — raising the site's contract error
    (``FactorizationError``, ...) stays the CALLER's job, which is how
    ``robust_cholesky`` keeps its pinned error contract."""
    # sleep defaults LATE (call time, not def time) so tests can
    # monkeypatch time.sleep and the engine picks it up; deadline
    # measurement (the clock-aware part) lives in with_policy
    sleep = time.sleep if sleep is None else sleep
    base_labels = tuple(retry_labels) if retry_labels is not None \
        else ({"site": site},)
    for index in range(policy.max_attempts):
        a = Attempt(index)
        yield a
        if not a.failed:
            return
        if index + 1 < policy.max_attempts:
            for labels in (a.retry_labels or base_labels):
                obs.counter(RETRY_COUNTER, **labels).inc()
            delay = policy.delay_s(index)
            _emit(site, "retry", attempt=index, delay_s=float(delay),
                  attrs={"reason": a.reason} if a.reason else {})
            if delay > 0:
                sleep(delay)
        else:
            _emit(site, "give_up", attempt=index,
                  attrs={"reason": a.reason} if a.reason else {})


def with_policy(site: str, fn: Callable, *args,
                policy: Optional[RetryPolicy] = None,
                breaker=None,
                clock: Optional[Callable[[], float]] = None,
                sleep: Optional[Callable[[float], None]] = None,
                **kwargs):
    """Run ``fn(*args, **kwargs)`` under ``policy`` at ``site``; returns
    ``fn``'s result.

    Retryable failures (``policy.is_retryable``, module classification
    table) re-run with the policy backoff; non-retryable ones raise
    immediately; exhaustion re-raises the last error after a ``give_up``
    record. ``breaker`` (a :class:`~dlaf_tpu.health.circuit.
    CircuitBreaker`) gates every attempt: an open breaker fails the call
    fast with :class:`~dlaf_tpu.health.errors.CircuitOpenError`, and each
    attempt's outcome feeds it — N consecutive attempt failures open it
    even mid-policy, so the next attempt (and the next call) stops
    hammering a down dependency.

    The per-attempt deadline is measured with ``clock`` plus any armed
    :func:`dlaf_tpu.health.inject.hang` stall (clock-aware: deadline
    drills burn no real wall time); see the module docstring for the
    late-success semantics."""
    from . import inject

    clock = time.monotonic if clock is None else clock
    policy = policy if policy is not None else RetryPolicy()
    last: Optional[BaseException] = None
    for a in attempts(site, policy, sleep=sleep):
        if breaker is not None:
            breaker.allow()
        t0 = clock()
        try:
            result = fn(*args, **kwargs)
        except BaseException as e:
            last = e
            if breaker is not None:
                breaker.record_failure()
            if not policy.is_retryable(e):
                raise
            a.fail(reason=type(e).__name__, exc=e)
            continue
        elapsed = clock() - t0 + inject.hang_seconds(site)
        if policy.attempt_deadline_s is not None \
                and elapsed > policy.attempt_deadline_s:
            obs.counter(DEADLINE_COUNTER, site=site).inc()
            _emit(site, "deadline", attempt=a.index,
                  attrs={"elapsed_s": float(elapsed),
                         "deadline_s": float(policy.attempt_deadline_s)})
            if breaker is not None:
                breaker.record_failure()
            raise DeadlineExceededError(site, elapsed,
                                        policy.attempt_deadline_s,
                                        attempt=a.index)
        if breaker is not None:
            breaker.record_success()
        # rolling-window SLO tracking (ISSUE 13): every policy-guarded
        # success feeds the same windowed-percentile machinery the serve
        # queue uses, with op = the policy site — observe_latency no-ops
        # when metrics are off
        obs.observe_latency(site, elapsed)
        # a recovered retry must not leak its failure: the caught
        # exception's traceback references THIS frame (the classic tb
        # reference cycle), so returning with `last` still bound keeps
        # every object in the guarded call chain — a serve Queue, its
        # batch arrays — alive until the next cyclic GC pass (observed:
        # /healthz listing a long-dead queue whose dispatch once
        # retried through an injected fault)
        last = None
        return result
    assert last is not None  # attempts() only exhausts on marked failures
    raise last
