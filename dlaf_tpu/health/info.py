"""In-graph failure detection: info values from factor/operand diagonals.

The reference's ``tile::potrfInfo`` surfaces per-tile LAPACK/cusolver info
as data; the blocked composition of that signal is what this module owns.
XLA backends mark a failed Cholesky by NaN-ing the factor (CPU NaNs the
whole tile, TPU's blocked form NaNs from the failing block on —
``tile_ops/lapack.py:potrf_info``), and NaNs propagate through every
downstream trailing update, so the FIRST non-finite diagonal element of
the *final* factor is the blocked-algorithm info: a 1-based first failing
global column, exact to the backend's NaN-prefix behavior. Computing it
from the final diagonal (instead of collecting per-step tile infos) keeps
the factorization subgraph byte-identical with detection on or off, works
uniformly across the unrolled/scan step forms and the look-ahead carry,
and additionally catches corruption injected *after* the failing potrf
(e.g. a poisoned collective payload — :mod:`dlaf_tpu.health.inject`).

Everything here is pure jnp (jit-safe, no host callbacks, no host sync);
distributed combination happens in the callers — the cholesky builders
merge the per-rank owner-masked vectors with an all-reduce ``max`` over
both mesh axes (disjoint owner masks make max an OR).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def bad_diag_mask(d, *, singular: bool = False):
    """Bool mask of "bad" diagonal entries. Default (``potrf_info``
    semantics): non-finite real part. ``singular=True`` (triangular-solve /
    HEGST detection) additionally flags exact zeros and — for complex —
    non-finite imaginary parts."""
    if jnp.iscomplexobj(d):
        bad = ~jnp.isfinite(d.real)
        if singular:
            bad = bad | ~jnp.isfinite(d.imag) | (d == 0)
    else:
        bad = ~jnp.isfinite(d)
        if singular:
            bad = bad | (d == 0)
    return bad


def first_bad_info(bad):
    """1-based index of the first True along the last axis, 0 if none —
    the LAPACK-shaped info value, as an int32 device scalar."""
    if bad.shape[-1] == 0:
        return jnp.zeros(bad.shape[:-1], jnp.int32)
    idx = jnp.argmax(bad, axis=-1)
    return jnp.where(jnp.any(bad, axis=-1), idx + 1, 0).astype(jnp.int32)


def local_factor_info(a, *, singular: bool = False):
    """Info of a square global factor (local builders): 1-based first bad
    diagonal column, 0 on success."""
    n = a.shape[-1]
    if n == 0:
        return jnp.zeros((), jnp.int32)
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    return first_bad_info(bad_diag_mask(d, singular=singular))


def dist_diag_bad(lt, rr, rc, *, Pr: int, Qc: int, nt: int, mb: int, n: int,
                  singular: bool = False):
    """Per-rank owner-masked bad-column vector for the distributed
    builders (called INSIDE shard_map).

    ``lt``: this rank's local tiles ``(ltr, ltc, mb, mb)``; ``rr``/``rc``:
    this rank's (traced) cycle positions along the row/col axes. Returns a
    length-``n`` int32 vector that is 1 exactly at the global diagonal
    columns whose OWNED diagonal tile has a bad entry, 0 elsewhere —
    owner masks are disjoint across ranks, so an all-reduce ``max`` over
    both axes yields the global bad-column vector.
    """
    if n == 0:
        return jnp.zeros((0,), jnp.int32)
    ltr, ltc = lt.shape[0], lt.shape[1]
    g_rows = jnp.arange(ltr) * Pr + rr                    # global tile rows
    g_cols = jnp.arange(ltc) * Qc + rc
    own = (g_rows[:, None] == g_cols[None, :]) & (g_rows[:, None] < nt)
    d = jnp.diagonal(lt, axis1=-2, axis2=-1)              # (ltr, ltc, mb)
    bad = bad_diag_mask(d, singular=singular)
    contrib = (bad & own[:, :, None]).any(axis=1)         # (ltr, mb)
    pos = g_rows[:, None] * mb + jnp.arange(mb)[None, :]  # global columns
    vec = jnp.zeros((nt * mb,), jnp.int32)
    # invalid slots (padded local rows past nt) scatter out of range: drop
    vec = vec.at[pos.reshape(-1)].max(
        contrib.reshape(-1).astype(jnp.int32), mode="drop")
    return vec[:n]


# ---------------------------------------------------------------------------
# Standalone diag-info program over Matrix tile storage (triangular / HEGST)
# ---------------------------------------------------------------------------

def _diag_tile_coords(dist):
    """Host-side (storage_row, storage_col, extent) of every global
    diagonal tile, in global order (storage layout owned by
    ``matrix.tiling.global_tile_to_storage_index``)."""
    from ..matrix.tiling import global_tile_to_storage_index

    mb = dist.block_size.row
    n = dist.size.row
    coords = []
    for k in range(dist.nr_tiles.row):
        si, sj = global_tile_to_storage_index(dist, k, k)
        coords.append((si, sj, min(mb, n - k * mb)))
    return coords


from ..config import register_program_cache


@register_program_cache
@functools.lru_cache(maxsize=64)
def _diag_info_prog(dist, singular: bool):
    """Compiled ``tile storage -> info`` reduction for one layout. Static
    per-tile indexing; on a sharded storage GSPMD inserts the gathers, so
    one program serves local and distributed matrices."""
    coords = _diag_tile_coords(dist)

    def run(storage):
        if not coords:
            return jnp.zeros((), jnp.int32)
        parts = [jnp.diagonal(storage[si, sj])[:ts]
                 for (si, sj, ts) in coords]
        d = jnp.concatenate(parts)
        return first_bad_info(bad_diag_mask(d, singular=singular))

    return jax.jit(run)


def matrix_diag_info(mat, *, singular: bool = False):
    """1-based first bad global diagonal column of ``mat`` (0 = clean), as
    an int32 device scalar — jit-compiled, no host sync (the caller decides
    when/whether to fetch). ``singular=True`` is the triangular-solve /
    HEGST detection (zero OR non-finite diagonal); the default matches
    ``potrf_info`` (non-finite only)."""
    from .. import obs

    # program telemetry (DLAF_PROGRAM_TELEMETRY): off = passthrough
    return obs.telemetry.call("diag_info", _diag_info_prog(mat.dist, singular),
                              mat.storage)
