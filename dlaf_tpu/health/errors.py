"""Structured error types of the health subsystem (docs/robustness.md).

The reference surfaces factorization failure as *data* (``tile::potrfInfo``
returns the LAPACK/cusolver info instead of asserting); these types are the
host-side face of that contract once the in-graph detection
(:mod:`dlaf_tpu.health.info`) decides a run cannot proceed. All of them
carry their diagnostic payload as attributes — callers branch on fields,
not on message text.
"""

from __future__ import annotations


class HealthError(RuntimeError):
    """Base of every error the health subsystem raises."""


class FactorizationError(HealthError):
    """A factorization stayed indefinite after every recovery attempt
    (:func:`dlaf_tpu.health.recovery.robust_cholesky`).

    Attributes:
        failing_column: 1-based first failing global column reported by the
            LAST attempt (backend NaN semantics bound its precision — see
            ``tile_ops/lapack.py:potrf_info``).
        attempts: number of factorization attempts performed.
        shifts: the diagonal shift ``alpha`` of each attempt (first is 0.0).
        infos: the info value of each attempt (all nonzero, or this would
            not have been raised).
    """

    def __init__(self, failing_column: int, attempts: int,
                 shifts: tuple, infos: tuple = ()):
        self.failing_column = int(failing_column)
        self.attempts = int(attempts)
        self.shifts = tuple(float(s) for s in shifts)
        self.infos = tuple(int(i) for i in infos)
        super().__init__(
            f"factorization failed at global column {self.failing_column} "
            f"after {self.attempts} attempt(s) with diagonal shifts "
            f"{self.shifts}")


class DegradationError(HealthError):
    """Strict mode (``DLAF_STRICT=1``) forbids a registered degradation
    (:func:`dlaf_tpu.health.registry.report_fallback`): the preferred
    implementation is unavailable and falling back silently is not allowed.

    Attributes:
        site: the degradation site (the ``site`` label of
            ``dlaf_fallback_total``).
        reason: why the preferred route was unavailable.
    """

    def __init__(self, site: str, reason: str, detail: str = ""):
        self.site = site
        self.reason = reason
        suffix = f": {detail}" if detail else ""
        super().__init__(
            f"strict mode: degradation at site {site!r} ({reason}){suffix} "
            "— unset DLAF_STRICT to allow the fallback")


class CheckError(HealthError):
    """The opt-in finite guard (``DLAF_CHECK=1``) found non-finite values.

    Attributes:
        what: which operand failed (e.g. ``"cholesky input"``).
        count: number of non-finite elements.
    """

    def __init__(self, what: str, count: int):
        self.what = what
        self.count = int(count)
        super().__init__(
            f"finite guard: {self.count} non-finite element(s) in {what} "
            "(DLAF_CHECK=1)")
