"""Structured error types of the health subsystem (docs/robustness.md).

The reference surfaces factorization failure as *data* (``tile::potrfInfo``
returns the LAPACK/cusolver info instead of asserting); these types are the
host-side face of that contract once the in-graph detection
(:mod:`dlaf_tpu.health.info`) decides a run cannot proceed. All of them
carry their diagnostic payload as attributes — callers branch on fields,
not on message text.
"""

from __future__ import annotations


class HealthError(RuntimeError):
    """Base of every error the health subsystem raises."""


class FactorizationError(HealthError):
    """A factorization stayed indefinite after every recovery attempt
    (:func:`dlaf_tpu.health.recovery.robust_cholesky`).

    Attributes:
        failing_column: 1-based first failing global column reported by the
            LAST attempt (backend NaN semantics bound its precision — see
            ``tile_ops/lapack.py:potrf_info``).
        attempts: number of factorization attempts performed.
        shifts: the diagonal shift ``alpha`` of each attempt (first is 0.0).
        infos: the info value of each attempt (all nonzero, or this would
            not have been raised).
    """

    def __init__(self, failing_column: int, attempts: int,
                 shifts: tuple, infos: tuple = ()):
        self.failing_column = int(failing_column)
        self.attempts = int(attempts)
        self.shifts = tuple(float(s) for s in shifts)
        self.infos = tuple(int(i) for i in infos)
        super().__init__(
            f"factorization failed at global column {self.failing_column} "
            f"after {self.attempts} attempt(s) with diagonal shifts "
            f"{self.shifts}")


class DegradationError(HealthError):
    """Strict mode (``DLAF_STRICT=1``) forbids a registered degradation
    (:func:`dlaf_tpu.health.registry.report_fallback`): the preferred
    implementation is unavailable and falling back silently is not allowed.

    Attributes:
        site: the degradation site (the ``site`` label of
            ``dlaf_fallback_total``).
        reason: why the preferred route was unavailable.
    """

    def __init__(self, site: str, reason: str, detail: str = ""):
        self.site = site
        self.reason = reason
        suffix = f": {detail}" if detail else ""
        super().__init__(
            f"strict mode: degradation at site {site!r} ({reason}){suffix} "
            "— unset DLAF_STRICT to allow the fallback")


class DeadlineExceededError(HealthError):
    """An attempt ran past its :class:`~dlaf_tpu.health.policy.RetryPolicy`
    per-attempt deadline, or a queued serving request expired before its
    batch dispatched (``Request.deadline_s``; docs/robustness.md §2).

    Attributes:
        site: the policy/queue site that enforced the deadline.
        elapsed_s: how long the attempt/wait actually took (including any
            :func:`dlaf_tpu.health.inject.hang` clock-aware stall).
        deadline_s: the budget that was exceeded.
        attempt: 0-based attempt index (0 for queue-expiry).
    """

    def __init__(self, site: str, elapsed_s: float, deadline_s: float,
                 attempt: int = 0):
        self.site = str(site)
        self.elapsed_s = float(elapsed_s)
        self.deadline_s = float(deadline_s)
        self.attempt = int(attempt)
        super().__init__(
            f"deadline exceeded at {self.site!r}: attempt {self.attempt} "
            f"took {self.elapsed_s:.3f}s against a {self.deadline_s:.3f}s "
            "budget")


class CircuitOpenError(HealthError):
    """A circuit breaker (:mod:`dlaf_tpu.health.circuit`) is open: the
    site failed ``threshold`` consecutive times and calls fail fast until
    the cooldown lets a half-open probe through.

    Attributes:
        site: the breaker's site label (``dlaf_circuit_state{site}``).
        retry_in_s: seconds until the next half-open probe is admitted
            (0.0 when a probe is already in flight).
    """

    def __init__(self, site: str, retry_in_s: float = 0.0):
        self.site = str(site)
        self.retry_in_s = float(max(retry_in_s, 0.0))
        super().__init__(
            f"circuit open at {self.site!r}: failing fast (next probe in "
            f"{self.retry_in_s:.3f}s) — see dlaf_circuit_state{{site}}")


class OverloadError(HealthError):
    """The serving queue is at its ``DLAF_SERVE_MAX_DEPTH`` admission
    bound and sheds the submit instead of growing unboundedly
    (docs/serving.md overload protection).

    Attributes:
        depth: pending depth at the rejection.
        max_depth: the configured bound.
        op / bucket_n: the bucket the shed was counted against.
    """

    def __init__(self, depth: int, max_depth: int, op: str = "",
                 bucket_n: int = 0):
        self.depth = int(depth)
        self.max_depth = int(max_depth)
        self.op = str(op)
        self.bucket_n = int(bucket_n)
        super().__init__(
            f"serve queue overloaded: {self.depth} pending >= "
            f"DLAF_SERVE_MAX_DEPTH={self.max_depth}; shedding "
            f"{self.op or '?'}(n<={self.bucket_n}) — submit again after "
            "draining, or raise the bound")


class PreemptionError(HealthError):
    """The pipeline was preempted at a stage boundary
    (:func:`dlaf_tpu.health.inject.preempt` in drills; the real signal in
    production). With ``DLAF_RESUME_DIR`` set, every completed stage's
    checkpoint is already on disk — rerun with ``resume=True``.

    Attributes:
        stage: the stage boundary where the preemption fired.
    """

    def __init__(self, stage: str):
        self.stage = str(stage)
        super().__init__(
            f"preempted at stage boundary {self.stage!r} — completed "
            "stages are checkpointed under DLAF_RESUME_DIR; rerun with "
            "resume=True to continue from here")


class ResumeError(HealthError):
    """``resume=True`` could not use the checkpoints under
    ``DLAF_RESUME_DIR``: no directory configured, an incompatible
    manifest version, or a fingerprint mismatch (the checkpoints belong
    to a different config/grid/dtype run).

    Attributes:
        stage: the stage whose manifest failed (empty for setup errors).
        detail: what specifically mismatched.
    """

    def __init__(self, stage: str, detail: str):
        self.stage = str(stage)
        self.detail = str(detail)
        where = f" at stage {self.stage!r}" if self.stage else ""
        super().__init__(f"cannot resume{where}: {self.detail}")


class AutotuneExhaustedError(HealthError):
    """An accuracy probe breached its analytic budget at the TOP rung of
    an autotune precision ladder (:mod:`dlaf_tpu.autotune`,
    docs/autotune.md): every safer route has already been tried and the
    numbers are still out of budget. Raised under ``DLAF_STRICT``
    (non-strict deployments hold at the top rung, count
    ``dlaf_autotune_exhausted_total``, and dump the flight recorder —
    the validator's ``--require-autotune`` rejects the open state).

    Attributes:
        site: the route-table key label (op.nN.nbN.dtype.platform).
        rung: the (top) rung the ladder is pinned at.
        ladder: the ladder's name (e.g. "f64").
        bound_ratio: the breaching probe's normalized ratio (inf for a
            non-finite estimate).
    """

    def __init__(self, site: str, *, rung: int, ladder: str,
                 bound_ratio: float):
        self.site = str(site)
        self.rung = int(rung)
        self.ladder = str(ladder)
        self.bound_ratio = float(bound_ratio)
        super().__init__(
            f"autotune ladder exhausted at {self.site!r}: probe "
            f"bound_ratio {self.bound_ratio!r} breached the budget at "
            f"the top rung ({self.rung}) of the {self.ladder!r} ladder "
            "— no safer precision route exists (DLAF_STRICT=1 raises; "
            "see docs/autotune.md)")


class DrainedError(HealthError):
    """A queued request was drained undispatched (:meth:`dlaf_tpu.serve.
    queue.Queue.drain` — graceful worker shutdown, docs/fleet.md). The
    request was never started, so resubmitting it elsewhere is always
    safe; the fleet router does exactly that with handed-back tickets.

    Attributes:
        site: the draining queue's site label.
        rid: the drained request's id.
        op / bucket_n: the bucket the request was pending in.
    """

    def __init__(self, site: str, rid: int, op: str = "",
                 bucket_n: int = 0):
        self.site = str(site)
        self.rid = int(rid)
        self.op = str(op)
        self.bucket_n = int(bucket_n)
        super().__init__(
            f"request {self.rid} drained undispatched from {self.site!r} "
            f"({self.op or '?'}(n<={self.bucket_n})) — never started; "
            "safe to resubmit")


class WorkerLostError(HealthError):
    """A fleet worker died (socket EOF or heartbeat timeout) holding this
    unacknowledged ticket, and failover is DISABLED
    (``DLAF_FLEET_FAILOVER=0``) so the router cannot re-dispatch it to a
    sibling (docs/fleet.md). With failover on this error never surfaces —
    the ticket is re-dispatched instead.

    Attributes:
        worker: the dead worker's index.
        seq: the router ticket sequence number.
        reason: how the death was detected ("eof" | "heartbeat_timeout").
    """

    def __init__(self, worker: int, seq: int, reason: str):
        self.worker = int(worker)
        self.seq = int(seq)
        self.reason = str(reason)
        super().__init__(
            f"fleet worker {self.worker} lost ticket {self.seq} "
            f"({self.reason}) and DLAF_FLEET_FAILOVER=0 forbids "
            "re-dispatch — the request did not complete")


class FleetUnavailableError(HealthError):
    """The fleet router has no routable worker: every member is dead,
    draining, or behind an open breaker whose cooldown has not admitted
    a half-open probe yet (docs/fleet.md). Fail-fast by design — queueing
    against a fully-down fleet would hide the outage.

    Attributes:
        workers: total registered workers.
        states: ``{worker: membership state}`` at the rejection.
    """

    def __init__(self, workers: int, states: dict):
        self.workers = int(workers)
        self.states = dict(states)
        super().__init__(
            f"fleet has no routable worker ({self.workers} registered: "
            f"{self.states}) — every member is dead, draining, or "
            "breaker-rejected")


class CheckError(HealthError):
    """The opt-in finite guard (``DLAF_CHECK=1``) found non-finite values.

    Attributes:
        what: which operand failed (e.g. ``"cholesky input"``).
        count: number of non-finite elements.
    """

    def __init__(self, what: str, count: int):
        self.what = what
        self.count = int(count)
        super().__init__(
            f"finite guard: {self.count} non-finite element(s) in {what} "
            "(DLAF_CHECK=1)")
