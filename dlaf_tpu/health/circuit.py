"""Circuit breakers: stop hammering a failing site (docs/robustness.md §3).

A retry policy protects ONE call; a breaker protects the SITE across
calls. Under sustained failure (a wedged accelerator tunnel, a native
library that segfault-loops, a bucket program that OOMs every dispatch)
retrying every submit multiplies the damage — the breaker converts the
N-th consecutive failure into fast, cheap rejections until a cooldown
lets one probe through.

State machine (the classic three states):

    closed ──(threshold consecutive failures)──> open
    open ──(cooldown elapsed; ONE probe admitted)──> half_open
    half_open ──probe success──> closed
    half_open ──probe failure──> open  (cooldown restarts)

``allow()`` raises :class:`~dlaf_tpu.health.errors.CircuitOpenError`
when the breaker rejects; ``record_success``/``record_failure`` feed
outcomes back. Any success fully closes the breaker (consecutive-failure
count resets). Thread-safe: one lock per breaker; in ``half_open``
exactly one in-flight probe is admitted — concurrent callers are
rejected until it resolves, so a recovering dependency is never
thundering-herded.

Every transition sets the ``dlaf_circuit_state{site}`` gauge
(0 = closed, 1 = half_open, 2 = open) and lands as a ``resilience``
JSONL record (events ``circuit_open`` / ``circuit_half_open`` /
``circuit_close``), so an artifact shows exactly when a site tripped and
recovered — and ``--require-resilience`` REJECTS an artifact whose final
snapshot leaves any breaker open (a run that ended in a tripped state
must not pass CI silently).

Defaults come from the config knobs ``DLAF_CIRCUIT_THRESHOLD`` /
``DLAF_CIRCUIT_COOLDOWN_S``; per-breaker overrides (and an injectable
``clock`` for deterministic tests) are constructor arguments. The
process-wide registry (:func:`breaker`) keys breakers by site — the
serving queue uses one per bucket program, ``run_with_fallback`` one per
degradation site.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .. import obs
from .errors import CircuitOpenError

#: Gauge holding each breaker's state (labels: site).
CIRCUIT_GAUGE = "dlaf_circuit_state"

#: Gauge values (also the ``state()`` -> value mapping).
STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}

_EVENTS = {"closed": "circuit_close", "half_open": "circuit_half_open",
           "open": "circuit_open"}


class CircuitBreaker:
    """One site's breaker (module docstring). ``threshold``/``cooldown_s``
    default to the config knobs at construction; ``clock`` is injectable
    so cooldown behavior is deterministic under test."""

    def __init__(self, site: str, *, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..config import get_configuration

        cfg = get_configuration()
        self.site = str(site)
        self.threshold = int(threshold if threshold is not None
                             else cfg.circuit_threshold)
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else cfg.circuit_cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_live = False

    # -- state -----------------------------------------------------------

    def state(self) -> str:
        """"closed" | "half_open" | "open" (point-in-time; an elapsed
        cooldown still reports "open" until a caller's allow() admits
        the probe — transitions happen on calls, not on a timer)."""
        with self._lock:
            return self._state

    def _set(self, state: str) -> None:
        """Transition (lock held): gauge + resilience record. The
        flight-recorder dump a transition TO open owes
        (docs/observability.md trigger catalog) is fired by the caller
        AFTER the lock is released — the dump is file I/O (write +
        fsync + replace), and holding the breaker lock through it would
        stall every dispatch thread and the /healthz scrape at exactly
        the moment of an incident storm."""
        if state == self._state:
            return
        self._state = state
        obs.gauge(CIRCUIT_GAUGE, site=self.site).set(
            float(STATE_VALUES[state]))
        obs.emit_event("resilience", site=self.site, event=_EVENTS[state],
                       attrs={"consecutive": self._consecutive})

    # -- the breaker protocol --------------------------------------------

    def allow(self) -> None:
        """Admit or reject one call. Raises :class:`CircuitOpenError`
        when open (cooldown pending) or when a half-open probe is already
        in flight; admits exactly one probe once the cooldown elapses."""
        with self._lock:
            if self._state == "closed":
                return
            now = self.clock()
            if self._state == "open":
                remaining = self.cooldown_s - (now - self._opened_at)
                if remaining > 0:
                    raise CircuitOpenError(self.site, retry_in_s=remaining)
                self._set("half_open")
                self._probe_live = True
                return          # this caller IS the probe
            # half_open: one probe at a time
            if self._probe_live:
                raise CircuitOpenError(self.site, retry_in_s=0.0)
            self._probe_live = True

    def record_success(self) -> None:
        """A call succeeded: any state fully closes (consecutive count
        resets — the site is healthy again)."""
        with self._lock:
            self._consecutive = 0
            self._probe_live = False
            self._set("closed")

    def record_failure(self) -> None:
        """A call failed: a half-open probe failure re-opens (cooldown
        restarts); the threshold-th consecutive closed-state failure
        opens. An opening trips the flight recorder (reason
        ``breaker_open``) — AFTER the lock is released (see
        :meth:`_set`) and after the transition record landed in the
        ring, so the dump includes the opening itself."""
        opened = False
        with self._lock:
            self._consecutive += 1
            if self._state == "half_open":
                self._probe_live = False
                self._opened_at = self.clock()
                self._set("open")
                opened = True
            elif self._state == "closed" \
                    and self._consecutive >= self.threshold:
                self._opened_at = self.clock()
                self._set("open")
                opened = True
            consecutive = self._consecutive
        if opened:
            from ..obs import flight

            flight.trigger("breaker_open", site=self.site,
                           consecutive=consecutive)

    def reset(self) -> None:
        """Force-close (tests / injection reset-safety)."""
        with self._lock:
            self._consecutive = 0
            self._probe_live = False
            self._set("closed")


# ---------------------------------------------------------------------------
# Process registry
# ---------------------------------------------------------------------------

_BREAKERS: Dict[str, CircuitBreaker] = {}
_REG_LOCK = threading.Lock()


def breaker(site: str, **kwargs) -> CircuitBreaker:
    """The process breaker for ``site``, created on first use. On later
    calls ``threshold``/``cooldown_s`` are ignored (first creation wins;
    use :func:`reset` + recreate to change them), but an explicitly
    passed ``clock`` REBINDS — the active caller drives time, so a
    breaker created under one queue's injected test clock can never
    wedge a later caller's cooldown (its ``now - opened_at`` would
    otherwise never elapse)."""
    with _REG_LOCK:
        br = _BREAKERS.get(site)
        if br is None:
            br = _BREAKERS[site] = CircuitBreaker(site, **kwargs)
        elif "clock" in kwargs:
            br.clock = kwargs["clock"]
        return br


def peek(site: str) -> Optional[str]:
    """``site``'s state without creating a breaker (None = never used)."""
    with _REG_LOCK:
        br = _BREAKERS.get(site)
    return br.state() if br is not None else None


def states() -> dict:
    """``{site: state_name}`` for every registered breaker — the live
    ``/healthz`` endpoint's breaker table (dlaf_tpu/obs/exporter.py),
    sorted by site so the JSON is deterministic."""
    with _REG_LOCK:
        live = sorted(_BREAKERS.items())
    return {site: br.state() for site, br in live}


def reset(prefix: Optional[str] = None) -> int:
    """Close and drop registered breakers (all, or those whose site
    starts with ``prefix``); returns how many were dropped. The
    injection contexts call this on exit so an injected failure storm
    never leaves a breaker open into unrelated code (reset-safety)."""
    with _REG_LOCK:
        sites = [s for s in _BREAKERS
                 if prefix is None or s.startswith(prefix)]
        dropped = [_BREAKERS.pop(s) for s in sites]
    for br in dropped:
        br.reset()          # gauge back to closed before the drop
    return len(dropped)
