"""Request-queue front end over the bucketed program service
(docs/serving.md).

:class:`Queue` accepts singleton requests (one ``(n, n)`` problem each),
buckets them by ``(op, dtype, uplo/side/op/diag, bucket ceiling)``,
identity-pads each problem to the bucket ceiling, dispatches the warm
vmapped bucket program when a batch fills — or when the oldest pending
request exceeds the ``DLAF_SERVE_DEADLINE_MS`` deadline — and unpads the
results back to request shape.

Determinism contract: the queue runs NO background thread. Deadlines are
evaluated against the injected ``clock`` at ``submit``/``poll``/
``flush`` calls, so which requests share a dispatch is a pure function
of the submission sequence and the clock values — testable to the lane.

Padding contract (probed + pinned in tests/test_serve.py):

* **lane padding** (a non-full dispatch): missing lanes are identity
  matrices (zero rhs for the solve). Lanes of the batched programs are
  bitwise independent, so pad lanes are provably inert — real-lane
  results are bitwise identical at every occupancy, and the pad lanes
  themselves factor to the singleton-builder identity result (info 0).
* **shape padding** (``n_req < bucket_n``): the problem is embedded in
  an identity border (``[[A, 0], [0, I]]``; zero rhs rows/cols; the
  eigh border is ``c*I`` with ``c`` strictly above the Gershgorin
  bound of the stored triangle's hermitian expansion — an upper bound
  on the spectral radius, so the pad eigenvalues sort strictly last
  and the real pairs are the leading ``n_req``).
  The padded region stays exactly zero/identity, but the real block is
  ulp-level — NOT bitwise — against the exact-size program (the
  backend's lowering is shape-dependent); the per-request accuracy
  records bound the effect against the analytic budget.

Every request carries a span and, under ``DLAF_ACCURACY``, a
per-request ``accuracy`` record (site ``serve``); every dispatch and
request lands as a ``serve`` JSONL record so the validator's
``--require-serve`` covers the serving path end to end
(docs/observability.md).

Trace correlation (ISSUE 13, docs/observability.md live operations):
``submit`` stamps one ``trace_id`` per request (``Ticket.trace_id``)
and each batch dispatch draws one ``span_id``; the dispatch runs under
a batch-scope ``obs.trace_context`` (member-ID list + span_id) so the
dispatch record, the policy engine's retry/breaker records, and any
program compile it triggers are all joinable from any member ID, while
the per-request records (request, span, accuracy, SLO latency
exemplar) re-enter request scope with the single ID. The dispatch
record's ``stages`` object (compose/program/fetch/unpad walls) plus
the request's ``queue_s`` is the per-request waterfall
``obs.aggregate --trace <id>`` renders. Request completions feed
``obs.observe_latency`` (the rolling-window SLO gauges + breach
counter), the queue registers itself on the live ``/healthz`` endpoint
at construction, and an admission shed trips the flight recorder.

Resilience (PR 12, docs/robustness.md):

* **Admission control** (``DLAF_SERVE_MAX_DEPTH`` / ``DLAF_SERVE_SHED``):
  total pending depth is bounded; at the bound a submit either sheds fast
  with a structured :class:`~dlaf_tpu.health.errors.OverloadError` (shed
  counted per bucket, ``dlaf_serve_shed_total``) or — shed off —
  force-dispatches the fullest bucket as backpressure. Either way depth
  provably never exceeds the bound (queue memory is bounded under
  overload; bench.py's ``overload`` arm certifies shed rate + p99 at 2x
  capacity).
* **Per-request deadlines** (``Request.deadline_s``): at dispatch
  composition, requests whose wait exceeded their deadline are cancelled
  with a :class:`~dlaf_tpu.health.errors.DeadlineExceededError` cause
  (counted ``dlaf_deadline_exceeded_total{site="serve.queue"}`` +
  per-bucket ``expired``) instead of riding a batch whose result nobody
  will read.
* **Retried, breaker-guarded dispatch**: each batch dispatch runs under
  the shared :mod:`dlaf_tpu.health.policy` engine
  (``DLAF_SERVE_RETRY_ATTEMPTS``/``DLAF_SERVE_RETRY_BACKOFF_MS``) behind
  a per-bucket circuit breaker (:mod:`dlaf_tpu.health.circuit`,
  ``dlaf_circuit_state{site}``) — a transient failure retries before any
  ticket is poisoned; sustained failure opens the breaker and fails
  later dispatches fast instead of re-running a broken program.
* :meth:`Queue.stats` snapshots per-bucket depth / in-flight / shed /
  expired counts and breaker states (also exported as gauges and printed
  by ``scripts/profile_summary.py``'s serve section).
"""

from __future__ import annotations

import base64
import dataclasses
import functools
import itertools
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..common.asserts import dlaf_assert
from ..config import (get_configuration, parse_serve_buckets,
                      register_program_cache)
from ..health import circuit as _circuit
from ..health.errors import (DeadlineExceededError, DrainedError,
                             OverloadError)
from ..health.policy import RetryPolicy, with_policy
from .programs import (ProgramService, cholesky_spec, eigh_spec,
                       get_service, solve_spec)

#: ops the queue serves, with their singular per-request result shapes
OPS = ("cholesky", "solve", "eigh")


def resolve_buckets() -> tuple:
    """The configured explicit ceilings (may be empty = pure
    power-of-two policy)."""
    return parse_serve_buckets(get_configuration().serve_buckets)


def bucket_ceiling(n: int, buckets: tuple = None) -> int:
    """Deterministic ceiling for a request dimension: the smallest
    configured bucket >= n, else (no bucket fits / no explicit list)
    the next power of two >= max(n, 8) — every shape is servable, an
    unconfigured one just lands in a colder bucket."""
    n = int(n)
    dlaf_assert(n >= 1, f"bucket_ceiling: n must be >= 1, got {n}")
    if buckets is None:
        buckets = resolve_buckets()
    for b in buckets:
        if b >= n:
            return b
    return 1 << max(int(n) - 1, 7).bit_length()


def rhs_ceiling(free: int) -> int:
    """Ceiling for the solve's rhs FREE-axis width: the next power of
    two >= free. Deliberately NOT the ``serve_buckets`` list — those are
    MATRIX-size ceilings, and rounding a 1-column rhs up to the smallest
    configured matrix bucket would multiply the rhs work/traffic by
    ``bucket/nrhs``; the pow2 policy bounds the padding waste at 2x
    while still sharing programs across nearby widths."""
    free = int(free)
    dlaf_assert(free >= 1, f"rhs_ceiling: free must be >= 1, got {free}")
    return 1 << (free - 1).bit_length()


# ---------------------------------------------------------------------------
# Wire codec (fleet ticket handoff, docs/fleet.md): requests must cross a
# process boundary as JSON — the fleet transport is length-prefixed JSON
# over local sockets, zero new deps — so arrays ride as base64(raw bytes)
# + dtype + shape. Defined HERE (not in dlaf_tpu.fleet) because the
# request owns its serialization and serve must not import fleet.
# ---------------------------------------------------------------------------

def array_to_wire(a) -> dict:
    """One ndarray as a JSON-safe dict (dtype name + shape + base64 of
    the C-contiguous raw bytes — exact, no text round-trip loss)."""
    a = np.ascontiguousarray(np.asarray(a))
    return {"dtype": a.dtype.name, "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def array_from_wire(doc: dict) -> np.ndarray:
    """Inverse of :func:`array_to_wire` (a writable copy — frombuffer
    views are read-only and serve results are caller-owned)."""
    flat = np.frombuffer(base64.b64decode(doc["data"]),
                         dtype=np.dtype(doc["dtype"]))
    return flat.reshape(tuple(int(s) for s in doc["shape"])).copy()


@dataclasses.dataclass
class Request:
    """One serving request: ``op`` in :data:`OPS`, ``a`` the ``(n, n)``
    problem (triangle semantics per op), ``b`` the rhs for the solve
    (``(n, nrhs)`` side='L', ``(nrhs, n)`` side='R'), ``alpha`` the
    solve scale. ``rid`` is stamped by the queue when left None.
    ``deadline_s`` (None = no deadline) bounds the QUEUE WAIT: a request
    still pending ``deadline_s`` seconds after submit is cancelled at
    dispatch composition with a
    :class:`~dlaf_tpu.health.errors.DeadlineExceededError` cause."""

    op: str
    a: Any
    b: Any = None
    uplo: str = "L"
    side: str = "L"
    transa: str = "N"
    diag: str = "N"
    alpha: float = 1.0
    rid: Optional[int] = None
    deadline_s: Optional[float] = None

    def to_wire(self) -> dict:
        """JSON-safe form for the fleet ticket handoff (docs/fleet.md):
        arrays via :func:`array_to_wire`, scalars as-is. Round-trips
        exactly through :meth:`from_wire`."""
        return {"op": self.op, "a": array_to_wire(self.a),
                "b": None if self.b is None else array_to_wire(self.b),
                "uplo": self.uplo, "side": self.side,
                "transa": self.transa, "diag": self.diag,
                "alpha": float(self.alpha), "rid": self.rid,
                "deadline_s": self.deadline_s}

    @classmethod
    def from_wire(cls, doc: dict) -> "Request":
        return cls(op=str(doc["op"]), a=array_from_wire(doc["a"]),
                   b=(None if doc.get("b") is None
                      else array_from_wire(doc["b"])),
                   uplo=str(doc.get("uplo", "L")),
                   side=str(doc.get("side", "L")),
                   transa=str(doc.get("transa", "N")),
                   diag=str(doc.get("diag", "N")),
                   alpha=float(doc.get("alpha", 1.0)),
                   rid=doc.get("rid"),
                   deadline_s=doc.get("deadline_s"))


class Ticket:
    """Handle returned by :meth:`Queue.submit`. ``done`` flips when the
    request's batch dispatched; :meth:`result` returns the unpadded
    per-request output as HOST (numpy) arrays — the dispatch fetches the
    whole batch once, so per-ticket results are zero-cost views — and
    raises RuntimeError while still queued. ``info`` is the per-element
    info value (int) once done."""

    def __init__(self, request: Request, submitted: float,
                 trace_id: Optional[str] = None):
        self.request = request
        self.submitted = submitted
        self.done = False
        self.error: Optional[BaseException] = None
        self.info: Optional[int] = None
        self.queue_s: Optional[float] = None
        self.total_s: Optional[float] = None
        # request-scoped trace correlation (ISSUE 13): one ID per
        # request, stamped by obs.trace_context onto every record the
        # request's causal chain emits — `obs.aggregate --trace <id>`
        # joins them back together. An adopted trace_id (the fleet
        # worker passing through its router ticket's ID) keeps the
        # cross-process chain joinable from either side.
        self.trace_id = trace_id or obs.new_trace_id()
        self._result = None

    def result(self):
        if self.error is not None:
            # the request was not served: expired before dispatch, or the
            # batch it rode in failed to dispatch (compile error, OOM,
            # open breaker, ...) — surface the cause instead of "queued"
            what = ("expired before dispatch"
                    if isinstance(self.error, DeadlineExceededError)
                    else "drained undispatched"
                    if isinstance(self.error, DrainedError)
                    else "batch dispatch failed")
            raise RuntimeError(
                f"request {self.request.rid}: {what} "
                f"({type(self.error).__name__})") from self.error
        if not self.done:
            raise RuntimeError(
                f"request {self.request.rid} is still queued; Queue.flush() "
                "forces dispatch of partial batches")
        return self._result


@dataclasses.dataclass(frozen=True)
class _BucketKey:
    op: str
    n: int            # bucket ceiling
    nrhs: int         # rhs ceiling (0 for non-solve)
    dtype: str
    uplo: str
    side: str
    transa: str
    diag: str


# ---------------------------------------------------------------------------
# Padding / unpadding (host side — shapes are request-sized, tiny)
# ---------------------------------------------------------------------------

def _pad_a(req: Request, bn: int) -> np.ndarray:
    a = np.asarray(req.a)
    n = a.shape[0]
    if n == bn:
        return a
    out = np.zeros((bn, bn), a.dtype)
    out[:n, :n] = a
    if req.op == "eigh":
        # pad eigenvalues must sort strictly AFTER every real one so the
        # leading n pairs are the request's. max|A| alone does NOT bound
        # the spectrum (rho(A) can reach n*max|A| — e.g. the all-ones
        # matrix); use the Gershgorin/inf-norm bound of the hermitian
        # expansion of the STORED triangle (the only data the op reads)
        tri = np.tril(a) if req.uplo == "L" else np.triu(a)
        k = -1 if req.uplo == "L" else 1
        herm = tri + np.conj(np.tril(tri, k) if req.uplo == "L"
                             else np.triu(tri, k)).T
        c = 1.0 + float(np.abs(herm).sum(axis=1).max(initial=0.0))
    else:
        c = 1.0
    out[range(n, bn), range(n, bn)] = c
    return out


def _pad_b(req: Request, bn: int, brhs: int) -> np.ndarray:
    b = np.asarray(req.b)
    shape = (bn, brhs) if req.side == "L" else (brhs, bn)
    if b.shape == shape:
        return b
    out = np.zeros(shape, b.dtype)
    out[:b.shape[0], :b.shape[1]] = b
    return out


def _pad_lane(key: _BucketKey):
    """The inert pad-lane operands for one unfilled batch slot."""
    dt = np.dtype(key.dtype)
    a = np.eye(key.n, dtype=dt)
    if key.op != "solve":
        return (a,)
    shape = (key.n, key.nrhs) if key.side == "L" else (key.nrhs, key.n)
    return a, np.zeros(shape, dt)


def _unpad(req: Request, key: _BucketKey, lane_out):
    """Slice one lane's bucket-shaped outputs back to request shape."""
    n = np.asarray(req.a).shape[0]
    if req.op == "cholesky":
        return lane_out[:n, :n]
    if req.op == "solve":
        rows, cols = np.asarray(req.b).shape
        return lane_out[:rows, :cols]
    w, v = lane_out
    return w[:n], v[:n, :n]


# ---------------------------------------------------------------------------
# Per-dispatch accuracy probes (exact residuals — bucket problems are
# small by regime, so the O(n^3) check is cheap next to the solve)
# ---------------------------------------------------------------------------

@register_program_cache
@functools.lru_cache(maxsize=64)
def _residual_prog(op: str, shapes, dtype: str, uplo: str, side: str,
                   transa: str, diag: str):
    dt = np.dtype(dtype)

    def _fro(x):
        return jnp.sqrt(jnp.sum(jnp.abs(x) ** 2, axis=(-2, -1)))

    def _herm(a):
        if uplo == "L":
            return jnp.tril(a) + jnp.conj(jnp.tril(a, -1)).swapaxes(-1, -2)
        return jnp.triu(a) + jnp.conj(jnp.triu(a, 1)).swapaxes(-1, -2)

    tiny = jnp.asarray(np.finfo(dt.type(0).real.dtype).tiny)
    if op == "cholesky":
        def run(a, fac):
            ah = _herm(a)
            tri = jnp.tril(fac) if uplo == "L" else jnp.triu(fac)
            ll = (tri @ jnp.conj(tri).swapaxes(-1, -2) if uplo == "L"
                  else jnp.conj(tri).swapaxes(-1, -2) @ tri)
            return _fro(ll - ah) / jnp.maximum(_fro(ah), tiny)
    elif op == "solve":
        # vmapped bodies see ONE lane: a (n,n), b/x (n,nrhs), alpha scalar
        def run(a, b, alpha, x):
            tri = jnp.tril(a) if uplo == "L" else jnp.triu(a)
            if diag == "U":
                eye = jnp.eye(tri.shape[-1], dtype=tri.dtype)
                tri = jnp.where(eye.astype(bool), eye, tri)
            if transa != "N":
                tri = tri.swapaxes(-1, -2)
                if transa == "C":
                    tri = jnp.conj(tri)
            lhs = tri @ x if side == "L" else x @ tri
            rhs = alpha * b
            return _fro(lhs - rhs) / jnp.maximum(_fro(rhs), tiny)
    else:   # eigh
        def run(a, w, v):
            ah = _herm(a)
            r = ah @ v - v * w[None, :]
            return _fro(r) / jnp.maximum(_fro(ah), tiny)

    return jax.jit(jax.vmap(run))


#: op -> (accuracy metric label, analytic tolerance factor c) — the c
#: constants the existing estimator family uses for the same metrics
#: (docs/accuracy.md).
_ACCURACY = {"cholesky": ("cholesky_residual", 60.0),
             "solve": ("trsm_residual", 60.0),
             "eigh": ("eigen_residual", 200.0)}

#: serve op -> route-table op key (docs/autotune.md §serving): the
#: serve buckets consult the SAME table entries the offline algorithm
#: entries learn, so committed routes apply to batched traffic.
_AUTOTUNE_OP = {"cholesky": "cholesky", "solve": "trsm",
                "eigh": "eigensolver"}


# ---------------------------------------------------------------------------
# The queue
# ---------------------------------------------------------------------------

class Queue:
    """Bucketing/padding/deadline front end (module docstring).

    ``batch``/``deadline_s``/``buckets`` default to the
    ``DLAF_SERVE_BATCH``/``DLAF_SERVE_DEADLINE_MS``/``DLAF_SERVE_BUCKETS``
    knobs; ``clock`` (default ``time.monotonic``) is injectable so
    deadline behavior is deterministic under test."""

    def __init__(self, service: Optional[ProgramService] = None, *,
                 batch: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 buckets: Optional[tuple] = None,
                 clock: Callable[[], float] = time.monotonic,
                 max_depth: Optional[int] = None,
                 shed: Optional[bool] = None,
                 retry_attempts: Optional[int] = None,
                 retry_backoff_s: Optional[float] = None):
        cfg = get_configuration()
        self.service = service if service is not None else get_service()
        self.batch = int(batch if batch is not None else cfg.serve_batch)
        dlaf_assert(self.batch >= 1, f"Queue: batch must be >= 1, got "
                    f"{self.batch}")
        self.deadline_s = float(cfg.serve_deadline_ms / 1e3
                                if deadline_s is None else deadline_s)
        self.buckets = (tuple(buckets) if buckets is not None
                        else resolve_buckets())
        self.clock = clock
        self.max_depth = int(max_depth if max_depth is not None
                             else cfg.serve_max_depth)
        dlaf_assert(self.max_depth >= 0, f"Queue: max_depth must be >= 0, "
                    f"got {self.max_depth}")
        self.shed = bool(cfg.serve_shed if shed is None else shed)
        self.retry_attempts = int(retry_attempts if retry_attempts
                                  is not None else cfg.serve_retry_attempts)
        dlaf_assert(self.retry_attempts >= 1, "Queue: retry_attempts must "
                    f"be >= 1, got {self.retry_attempts}")
        self.retry_backoff_s = float(
            cfg.serve_retry_backoff_ms / 1e3 if retry_backoff_s is None
            else retry_backoff_s)
        self._pending: dict = {}          # _BucketKey -> [(req, ticket)]
        self._rid = itertools.count()
        # one lock over submit/poll/flush: the service below is already
        # thread-safe, but bucket fill/pop must be atomic too or two
        # request threads filling the same bucket double-pop it
        self._lock = threading.RLock()
        self.dispatches = 0
        self.requests = 0
        self._in_flight = 0               # dispatches currently executing
        self._counts: dict = {}           # _BucketKey -> {shed, expired}
        # expose this queue on the live /healthz endpoint (weakref, no
        # unregister protocol) — LAST, after every field stats() reads
        # exists: a scrape thread may call stats() the instant the queue
        # is visible, and a half-constructed queue answering /healthz
        # with an AttributeError would fabricate a healthz_failure
        # flight dump on a perfectly clean run
        obs.exporter.register_queue(self)

    # -- submission ------------------------------------------------------

    def _key(self, req: Request) -> _BucketKey:
        a = np.asarray(req.a)
        dlaf_assert(req.op in OPS,
                    f"Queue: op must be one of {OPS}, got {req.op!r}")
        dlaf_assert(a.ndim == 2 and a.shape[0] == a.shape[1],
                    f"Queue: request 'a' must be square (n, n), got "
                    f"{a.shape}")
        bn = bucket_ceiling(a.shape[0], self.buckets)
        nrhs = 0
        if req.op == "solve":
            b = np.asarray(req.b)
            dlaf_assert(b.ndim == 2, "Queue: solve request needs a 2D rhs")
            dlaf_assert(b.dtype == a.dtype,
                        f"Queue: rhs dtype {b.dtype} != matrix dtype "
                        f"{a.dtype} (one bucket program serves one dtype)")
            solve_dim, free = ((b.shape[0], b.shape[1]) if req.side == "L"
                               else (b.shape[1], b.shape[0]))
            dlaf_assert(solve_dim == a.shape[0],
                        f"Queue: rhs solve dimension {solve_dim} != "
                        f"n={a.shape[0]}")
            nrhs = rhs_ceiling(free)
        return _BucketKey(op=req.op, n=bn, nrhs=nrhs,
                          dtype=np.dtype(a.dtype).name, uplo=req.uplo,
                          side=req.side, transa=req.transa, diag=req.diag)

    def _bucket_counts(self, key: _BucketKey) -> dict:
        return self._counts.setdefault(
            key, {"shed": 0, "expired": 0, "dispatches": 0, "failures": 0,
                  "drained": 0})

    def _admit(self, key: _BucketKey) -> None:
        """Admission control (lock held): at the ``max_depth`` bound,
        shed this submit with OverloadError, or — shed off — dispatch the
        fullest bucket inline (backpressure) until there is room. Depth
        therefore provably never exceeds ``max_depth``."""
        if not self.max_depth:
            return
        while self.pending() >= self.max_depth:
            if self.shed:
                counts = self._bucket_counts(key)
                counts["shed"] += 1
                if obs.metrics_active():
                    obs.counter("dlaf_serve_shed_total", op=key.op,
                                bucket_n=key.n).inc()
                obs.emit_event("resilience", site="serve.queue",
                               event="shed",
                               attrs={"op": key.op, "bucket_n": key.n,
                                      "depth": self.pending(),
                                      "max_depth": self.max_depth})
                # a shed burst is an incident: dump the flight ring
                # (the shed record above is already in it); the
                # recorder's per-reason cooldown means the FIRST shed
                # of a burst dumps and the next thousand do not
                from ..obs import flight
                flight.trigger("overload_shed", op=key.op,
                               bucket_n=key.n, depth=self.pending(),
                               max_depth=self.max_depth)
                raise OverloadError(self.pending(), self.max_depth,
                                    op=key.op, bucket_n=key.n)
            fullest = max((k for k, v in self._pending.items() if v),
                          key=lambda k: len(self._pending[k]),
                          default=None)
            if fullest is None:
                return          # nothing pending: the bound cannot bind
            try:
                self._dispatch(fullest)
            except Exception:
                # the inline dispatch failed for ANOTHER bucket's batch:
                # its tickets already carry the cause (poisoned by
                # _dispatch) and its lanes were popped either way, so
                # room was made — that failure belongs to those tickets,
                # not to THIS submit, which must still be admitted
                pass

    def submit(self, req: Request,
               trace_id: Optional[str] = None) -> Ticket:
        """Enqueue one request; dispatches its bucket immediately when
        the batch fills, and sweeps OTHER buckets' expired deadlines
        (the no-background-thread discipline: submission is the clock
        edge). At the ``max_depth`` admission bound the submit sheds
        (:class:`~dlaf_tpu.health.errors.OverloadError`, no ticket
        created — a shed request is never stranded) or applies
        backpressure, per the ``shed`` knob. ``trace_id`` (optional)
        makes the ticket adopt an existing trace — the fleet worker
        passes its router ticket's ID through so the whole
        cross-process chain joins on one ID."""
        with self._lock:
            now = self.clock()
            key = self._key(req)          # validate BEFORE admission
            self._admit(key)
            if req.rid is None:
                req.rid = next(self._rid)
            ticket = Ticket(req, now, trace_id)
            lanes = self._pending.setdefault(key, [])
            lanes.append((req, ticket))
            self.requests += 1
            if obs.metrics_active():
                obs.counter("dlaf_serve_requests_total", op=req.op).inc()
                obs.gauge("dlaf_serve_depth", op=key.op,
                          bucket_n=key.n).set(float(len(lanes)))
            if len(lanes) >= self.batch:
                self._dispatch(key)
            self.poll(now)
            return ticket

    def poll(self, now: Optional[float] = None) -> int:
        """Dispatch every bucket whose OLDEST pending request has
        exceeded the deadline; returns the number of dispatches."""
        with self._lock:
            now = self.clock() if now is None else now
            n = 0
            for key in [k for k, lanes in self._pending.items()
                        if lanes and now - lanes[0][1].submitted
                        >= self.deadline_s]:
                self._dispatch(key)
                n += 1
            return n

    def flush(self) -> int:
        """Dispatch every pending bucket regardless of fill or deadline
        (shutdown / end-of-stream); returns the number of dispatches."""
        with self._lock:
            n = 0
            for key in [k for k, lanes in self._pending.items() if lanes]:
                self._dispatch(key)
                n += 1
            return n

    def drain(self) -> list:
        """Cancel every UNDISPATCHED pending request (graceful shutdown:
        stop serving without running partial batches nobody will wait
        for) and return the ``(request, ticket)`` pairs, in submission
        order per bucket. The explicit API the fleet worker's drain path
        uses instead of reaching into ``_pending`` (docs/fleet.md) —
        drained requests were never started, so handing them back to the
        router for resubmission elsewhere is always safe.

        Each drained ticket is poisoned with a structured
        :class:`~dlaf_tpu.health.errors.DrainedError` (``result()``
        names the cause instead of claiming "still queued"), counted
        per bucket (``stats()['drained']``,
        ``dlaf_serve_drained_total{op}``), and emits one ``resilience``
        ``drain`` record under the ticket's trace ID — stats, records,
        and metrics stay in exact agreement (pinned in
        tests/test_serve.py)."""
        with self._lock:
            drained = []
            for key in [k for k, lanes in self._pending.items() if lanes]:
                lanes = self._pending.pop(key)
                counts = self._bucket_counts(key)
                if obs.metrics_active():
                    obs.gauge("dlaf_serve_depth", op=key.op,
                              bucket_n=key.n).set(0.0)
                for req, ticket in lanes:
                    ticket.error = DrainedError("serve.queue", req.rid,
                                                op=key.op, bucket_n=key.n)
                    counts["drained"] += 1
                    if obs.metrics_active():
                        obs.counter("dlaf_serve_drained_total",
                                    op=key.op).inc()
                    with obs.trace_context(trace_id=ticket.trace_id):
                        obs.emit_event(
                            "resilience", site="serve.queue", event="drain",
                            attrs={"rid": req.rid, "op": key.op,
                                   "bucket_n": key.n})
                    drained.append((req, ticket))
            return drained

    def pending(self) -> int:
        return sum(len(v) for v in self._pending.values())

    def stats(self) -> dict:
        """Operational snapshot (docs/serving.md; printed by
        scripts/profile_summary.py's serve section): totals — pending
        depth, in-flight dispatch count (nonzero only when read from
        WITHIN the dispatching thread, e.g. service hooks or probes —
        the single submit/poll/flush lock serializes outside readers
        past the dispatch), requests/dispatches, shed/
        expired totals, the ``max_depth``/``shed`` admission config —
        plus a per-bucket table keyed by the bucket program's site label:
        depth, shed, expired, and the bucket breaker's state ("closed" |
        "half_open" | "open"; None = the bucket never dispatched)."""
        with self._lock:
            buckets = {}
            for key in set(self._pending) | set(self._counts):
                counts = self._counts.get(key) or {}
                site = self._spec(key).site
                buckets[site] = {
                    "depth": len(self._pending.get(key, [])),
                    "shed": counts.get("shed", 0),
                    "expired": counts.get("expired", 0),
                    "dispatches": counts.get("dispatches", 0),
                    "failures": counts.get("failures", 0),
                    "drained": counts.get("drained", 0),
                    "breaker": _circuit.peek(site),
                }
            return {
                "pending": self.pending(),
                "in_flight": self._in_flight,
                "requests": self.requests,
                "dispatches": self.dispatches,
                "shed": sum(b["shed"] for b in buckets.values()),
                "expired": sum(b["expired"] for b in buckets.values()),
                "drained": sum(b["drained"] for b in buckets.values()),
                "max_depth": self.max_depth,
                "shed_policy": "shed" if self.shed else "backpressure",
                "buckets": buckets,
            }

    # -- warmup sugar ----------------------------------------------------

    def _steering(self, key: _BucketKey):
        """The bucket's autotune steering handle (None = loop closed for
        it): per-bucket route consultation against the SAME table the
        algorithm entries learn (docs/autotune.md §serving)."""
        from .. import autotune

        return autotune.steering(_AUTOTUNE_OP[key.op], n=key.n,
                                 nb=_default_nb(key.n), dtype=key.dtype)

    def _spec(self, key: _BucketKey):
        steer = self._steering(key)
        route = steer.route.key() if steer is not None else ()
        if key.op == "cholesky":
            return cholesky_spec(batch=self.batch, n=key.n,
                                 nb=_default_nb(key.n), dtype=key.dtype,
                                 uplo=key.uplo, with_info=True, donate=True,
                                 route=route)
        if key.op == "solve":
            return solve_spec(batch=self.batch, n=key.n, nrhs=key.nrhs,
                              nb=_default_nb(key.n), dtype=key.dtype,
                              side=key.side, uplo=key.uplo,
                              transa=key.transa, diag=key.diag,
                              with_info=True, donate=True, route=route)
        return eigh_spec(batch=self.batch, n=key.n, nb=_default_nb(key.n),
                         dtype=key.dtype, uplo=key.uplo, with_info=True,
                         donate=True, route=route)

    def warmup_specs(self, requests) -> tuple:
        """The exact ProgramSpecs a stream of ``requests`` will dispatch
        through — ``service.warmup(*queue.warmup_specs(sample))`` warms
        precisely the buckets the production stream hits."""
        return tuple({self._spec(self._key(r)): None for r in requests})

    def warmup(self, requests) -> dict:
        return self.service.warmup(*self.warmup_specs(requests))

    # -- dispatch --------------------------------------------------------

    def _dispatch(self, key: _BucketKey) -> None:
        lanes = self._pending.pop(key)
        if obs.metrics_active():
            obs.gauge("dlaf_serve_depth", op=key.op,
                      bucket_n=key.n).set(0.0)
        self._in_flight += 1
        observe = None
        try:
            ran, observe = self._dispatch_lanes(key, lanes)
            if ran:
                self._bucket_counts(key)["dispatches"] += 1
        except Exception as e:
            self._bucket_counts(key)["failures"] += 1
            # a failed dispatch (compile error, OOM, exhausted retries,
            # open breaker, ...) must not strand its tickets as
            # silently-forever-"queued": poison them with the cause —
            # result() re-raises it — and let the exception reach the
            # submitting caller. Tickets already cancelled (expiry) keep
            # their own, more precise cause.
            for _, ticket in lanes:
                if ticket.error is None and not ticket.done:
                    ticket.error = e
            raise
        finally:
            self._in_flight -= 1
        if observe is not None:
            # the autotune feedback runs AFTER the dispatch bookkeeping:
            # the batch completed and its tickets are fulfilled, so a
            # strict-mode AutotuneExhaustedError here must surface to
            # the caller WITHOUT counting a failure or desyncing
            # stats()['dispatches'] from the dispatch records (the
            # /healthz agreement leg) — the dispatch did not fail, the
            # accuracy budget did
            observe()

    def _expire_lanes(self, key: _BucketKey, lanes: list, now: float
                      ) -> list:
        """Cancel requests whose queue wait exceeded their deadline (the
        dispatch-composition cancellation point: an expired request must
        not ride a batch whose answer nobody will read); returns the
        still-live lanes."""
        live = []
        for req, ticket in lanes:
            waited = now - ticket.submitted
            if req.deadline_s is not None and waited > req.deadline_s:
                err = DeadlineExceededError("serve.queue", waited,
                                            req.deadline_s)
                ticket.error = err
                self._bucket_counts(key)["expired"] += 1
                if obs.metrics_active():
                    obs.counter("dlaf_deadline_exceeded_total",
                                site="serve.queue").inc()
                with obs.trace_context(trace_id=ticket.trace_id):
                    obs.emit_event(
                        "resilience", site="serve.queue", event="expired",
                        attrs={"rid": req.rid, "op": key.op,
                               "bucket_n": key.n,
                               "waited_s": float(waited),
                               "deadline_s": float(req.deadline_s)})
            else:
                live.append((req, ticket))
        return live

    def _dispatch_lanes(self, key: _BucketKey, lanes: list):
        """Returns ``(ran, observe)``: whether a program actually ran —
        an all-expired batch does not count as a dispatch anywhere
        (stats, records, metrics all stay consistent) — and the deferred
        autotune-feedback thunk (None when the loop is closed), which
        ``_dispatch`` runs after its own bookkeeping."""
        lanes = self._expire_lanes(key, lanes, self.clock())
        if not lanes:
            return False, None  # everything expired: nothing to run
        reqs = [r for r, _ in lanes]
        tickets = [t for _, t in lanes]
        spec = self._spec(key)
        resident = spec in self.service.specs()
        # batch-scope trace context (ISSUE 13): the dispatch's span_id
        # plus the MEMBER trace-ID list stamp every record emitted below
        # — the dispatch record, the policy engine's retry/breaker
        # records, any program compile the batch triggers — so one
        # request ID finds its whole dispatch by membership
        span_id = obs.new_span_id()
        member_ids = [t.trace_id for t in tickets]
        with obs.trace_context(trace_id=member_ids, span_id=span_id):
            return self._dispatch_traced(key, reqs, tickets, spec,
                                         resident, span_id)

    def _dispatch_traced(self, key: _BucketKey, reqs: list, tickets: list,
                         spec, resident: bool, span_id: str):
        t0 = self.clock()
        # assemble the padded batch (host: request shapes are serve-small)
        a_batch = np.stack(
            [_pad_a(r, key.n) for r in reqs]
            + [_pad_lane(key)[0]] * (self.batch - len(reqs)))
        args = [a_batch]
        if key.op == "solve":
            b_batch = np.stack(
                [_pad_b(r, key.n, key.nrhs) for r in reqs]
                + [_pad_lane(key)[1]] * (self.batch - len(reqs)))
            alpha = np.array([np.dtype(key.dtype).type(r.alpha)
                              for r in reqs]
                             + [np.dtype(key.dtype).type(1.0)]
                             * (self.batch - len(reqs)))
            args += [b_batch, alpha]
        t_compose = self.clock()
        # dispatch + compile run under the shared policy engine behind
        # the bucket's circuit breaker: a transient failure (e.g. an
        # inject.fail_dispatch drill, a flaky tunnel) retries before any
        # ticket is poisoned; consecutive attempt failures open the
        # breaker and later dispatches fail fast (CircuitOpenError)
        breaker = _circuit.breaker(spec.site, clock=self.clock)
        policy = RetryPolicy(max_attempts=self.retry_attempts,
                             backoff_base_s=self.retry_backoff_s)

        def _attempt():
            from ..health import inject

            inject.maybe_fail_dispatch()
            return self.service.run(spec, *args)

        with obs.span("serve.dispatch", op=key.op, bucket_n=key.n,
                      nrhs=key.nrhs, lanes=len(reqs), batch=self.batch,
                      dtype=key.dtype, cache="hit" if resident else "miss"):
            out = with_policy(spec.site, _attempt, policy=policy,
                              breaker=breaker, clock=self.clock)
        t_prog = self.clock()
        dev_outs, infos = _split_outputs(key.op, out)
        # ONE device->host fetch per dispatch, then zero-cost numpy views
        # per ticket: per-lane device slicing would cost a dispatch per
        # request — the exact overhead this layer exists to amortize —
        # and serving results are host-bound by regime. The fetch is also
        # the fence, so the per-request latency records are honest.
        lane_outs = (tuple(np.asarray(o) for o in dev_outs)
                     if isinstance(dev_outs, tuple) else np.asarray(dev_outs))
        t1 = self.clock()
        infos_np = np.asarray(infos) if infos is not None else None
        # unpad every lane BEFORE the dispatch record so the record's
        # stages object covers the whole waterfall the requests ride
        for i, (req, ticket) in enumerate(zip(reqs, tickets)):
            ticket._result = _unpad(req, key, _lane(key.op, lane_outs, i))
            ticket.info = int(infos_np[i]) if infos_np is not None else None
            ticket.queue_s = max(t0 - ticket.submitted, 0.0)
            ticket.total_s = max(t1 - ticket.submitted, 0.0)
            ticket.done = True
        t_unpad = self.clock()
        self.dispatches += 1
        if obs.metrics_active():
            obs.counter("dlaf_serve_dispatch_total", op=key.op).inc()
            obs.histogram("dlaf_serve_dispatch_seconds",
                          op=key.op).observe(t1 - t0)
        obs.emit_event("serve", event="dispatch", op=key.op,
                       bucket_n=key.n, nrhs=key.nrhs, dtype=key.dtype,
                       lanes=len(reqs), batch=self.batch,
                       cache="hit" if resident else "miss",
                       dispatch_s=float(t1 - t0),
                       stages={"compose_s": float(t_compose - t0),
                               "program_s": float(t_prog - t_compose),
                               "fetch_s": float(t1 - t_prog),
                               "unpad_s": float(t_unpad - t1)})
        residuals = self._residuals(key, reqs, args, dev_outs)
        for i, (req, ticket) in enumerate(zip(reqs, tickets)):
            n_req = int(np.asarray(req.a).shape[0])
            attrs = {"rid": req.rid,
                     **({"info": ticket.info}
                        if ticket.info is not None else {})}
            # request-scope trace context: these records carry the ONE
            # member trace ID (overriding the surrounding batch scope)
            # while keeping the dispatch's span_id as the join key
            with obs.trace_context(trace_id=ticket.trace_id,
                                   span_id=span_id):
                obs.emit_event("serve", event="request", op=key.op,
                               n=n_req, bucket_n=key.n, dtype=key.dtype,
                               queue_s=float(ticket.queue_s),
                               total_s=float(ticket.total_s), attrs=attrs)
                # per-request span record (unfenced-wall convention does
                # not apply: total_s ends at the dispatch's host
                # materialization, a real fence) — the request-granular
                # audit trail next to the typed serve record
                obs.emit_event("span", name="serve.request",
                               dur_s=float(ticket.total_s), depth=0,
                               parent=None,
                               attrs={"op": key.op, "n": n_req,
                                      "bucket_n": key.n, **attrs})
                # rolling-window SLO tracking: the histogram records the
                # exemplar trace ID from this request-scoped context
                obs.observe_latency(f"serve.{key.op}", ticket.total_s,
                                    bucket=str(key.n))
                if residuals is not None:
                    metric, c = _ACCURACY[key.op]
                    obs.accuracy.emit(
                        "serve", metric, residuals[i], n=n_req,
                        nb=_default_nb(key.n), c=c,
                        dtype=np.dtype(key.dtype),
                        of=_lane_array(dev_outs),
                        attrs={"op": key.op, "rid": req.rid,
                               "bucket_n": key.n})
        observe = None
        if residuals is not None:
            # close the loop for batched traffic (docs/autotune.md
            # §serving): the dispatch's WORST real-lane residual feeds
            # the bucket's route-table entry — one decision per
            # dispatch, so a breaching batch escalates the bucket's
            # route (the next dispatch compiles the safer program) and
            # a comfortable steady state can relax it. DEFERRED to
            # _dispatch (post-bookkeeping): a strict exhaustion raise
            # is an accuracy incident, never a dispatch failure
            steer = self._steering(key)
            if steer is not None:
                worst = residuals.max() if len(residuals) else 0.0
                if not np.isfinite(residuals).all():
                    worst = float("nan")
                _, c = _ACCURACY[key.op]
                member_ids = [t.trace_id for t in tickets]
                of = _lane_array(dev_outs)

                def observe():
                    # re-enter the batch trace scope the decision
                    # belongs to (the deferral left the context manager)
                    with obs.trace_context(trace_id=member_ids,
                                           span_id=span_id):
                        steer.observe(
                            worst, c=c, of=of,
                            attrs={"source": "serve", "op": key.op,
                                   "bucket_n": key.n,
                                   "lanes": len(reqs)})
        return True, observe

    def _residuals(self, key, reqs, args, lane_outs):
        """Per-real-lane residual vector under DLAF_ACCURACY, else None
        (the hot path computes nothing)."""
        if not obs.accuracy.enabled():
            return None
        shapes = tuple(tuple(np.asarray(a).shape) for a in args)
        prog = _residual_prog(key.op, shapes, key.dtype, key.uplo,
                              key.side, key.transa, key.diag)
        if key.op == "cholesky":
            vals = prog(args[0], lane_outs)
        elif key.op == "solve":
            vals = prog(args[0], args[1], args[2], lane_outs)
        else:
            vals = prog(args[0], lane_outs[0], lane_outs[1])
        return np.asarray(vals)[:len(reqs)]


def _default_nb(n: int) -> int:
    from ..algorithms.batched import default_nb

    return default_nb(n)


def _split_outputs(op: str, out):
    """(lane outputs, info vector or None) from one dispatch result."""
    if op == "eigh":
        if len(out) == 3:
            w, v, info = out
            return (w, v), info
        return out, None
    if isinstance(out, tuple):
        return out[0], out[1]
    return out, None


def _lane(op: str, lane_outs, i: int):
    if op == "eigh":
        return lane_outs[0][i], lane_outs[1][i]
    return lane_outs[i]


def _lane_array(lane_outs):
    """A representative device array for platform/eps attribution."""
    return lane_outs[1] if isinstance(lane_outs, tuple) else lane_outs
