"""dlaf_tpu.serve — batched many-problem serving layer (docs/serving.md).

The production front end for the batch-small-problems regime (ROADMAP
item 1, ISSUE 11): millions of small/medium factorize/solve/EVP requests
served at MXU-bound — not dispatch/compile-bound — throughput. Three
surfaces:

* **Batched entry points** (:mod:`dlaf_tpu.algorithms.batched`,
  re-exported here): ``cholesky_batched`` / ``solve_batched`` /
  ``eigh_batched`` over a leading batch axis — one vmapped, donated
  program per shape bucket, per-element ``info`` vectors.
* **Program service** (:mod:`.programs`): the shape-bucketed AOT cache —
  ``warmup(spec, ...)`` pre-compiles a bucket set, ``pin``/``evict``
  manage residency under the ``DLAF_SERVE_CACHE_BYTES`` LRU budget,
  hit/miss/evict/compile metrics per bucket, persistent-compile-cache
  integration (``DLAF_COMPILATION_CACHE_DIR``) so a restarted server
  warms from disk.
* **Request queue** (:mod:`.queue`): buckets incoming (shape, dtype)
  requests to the nearest ceiling, pads, dispatches the cached program
  when a batch fills or the deadline expires, unpads — each request
  carrying a span, a ``serve`` JSONL record, and (under
  ``DLAF_ACCURACY``) an accuracy record, so the existing validator and
  CI gates cover the serving path end to end (``--require-serve``).
"""

from __future__ import annotations

from ..algorithms.batched import (cholesky_batched, eigh_batched,  # noqa: F401
                                  solve_batched)
from .programs import (ProgramService, ProgramSpec, cholesky_spec,  # noqa: F401
                       eigh_spec, get_service, program_builder, solve_spec,
                       warmup)
from .queue import (OPS, Queue, Request, Ticket, bucket_ceiling,  # noqa: F401
                    rhs_ceiling)

__all__ = [
    "OPS", "ProgramService", "ProgramSpec", "Queue", "Request", "Ticket",
    "bucket_ceiling", "cholesky_batched", "cholesky_spec", "eigh_batched",
    "eigh_spec", "get_service", "program_builder", "rhs_ceiling",
    "solve_batched", "solve_spec", "warmup",
]
