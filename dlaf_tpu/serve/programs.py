"""Shape-bucketed AOT program service (docs/serving.md).

PR 7's keyed AOT cache (:func:`dlaf_tpu.obs.telemetry.call`) grown into
an explicit API: a :class:`ProgramService` holds one AOT-compiled,
donated, vmapped program per :class:`ProgramSpec` bucket key
``(op, batch, n, nrhs, nb, dtype, uplo/side/op/diag, with_info,
donate)`` and serves it warm —

* :meth:`ProgramService.warmup` pre-compiles a bucket set (the server
  bring-up step; with ``DLAF_COMPILATION_CACHE_DIR`` set, compiles land
  in jax's persistent compile cache so a RESTARTED server warms from
  disk instead of from XLA);
* :meth:`ProgramService.pin` / :meth:`ProgramService.evict` manage
  residency under the ``DLAF_SERVE_CACHE_BYTES`` LRU byte budget
  (pinned programs are never evicted; cost = ``memory_analysis()`` peak
  where the backend reports one, an aval-derived estimate otherwise);
* every lookup counts ``dlaf_serve_cache_total{event=hit|miss|warmup|
  evict|pin, op}`` and the live footprint lands on
  ``dlaf_serve_cache_bytes``; compiles route through
  :func:`dlaf_tpu.obs.telemetry.aot_compile` under a PER-BUCKET site
  (``serve.<op>.<bucket>``), so with ``DLAF_PROGRAM_TELEMETRY=1`` each
  bucket gets its own compile-seconds/HBM/retrace series — and
  "``dlaf_retrace_total{site=serve.*}`` stays 1 per site" IS the
  steady-state zero-retrace pin (a value of 2 means an evicted bucket
  recompiled, exactly what the CI evict drill must surface).

The module-level default service (:func:`get_service`) is registered
with the config program caches: a knob change that invalidates traced
decisions drops the compiled programs with it.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Optional

import numpy as np

from .. import obs
from ..config import get_configuration, register_program_cache


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One bucket program's identity — THE cache key (ISSUE 11:
    ``(bucket_n, nb, dtype, uplo/side/op)`` plus the lane count, rhs
    width, info flag, and donation, each of which changes the compiled
    program)."""

    op: str                 # "cholesky" | "solve" | "eigh"
    batch: int              # lanes per dispatch (B)
    n: int                  # bucket matrix dimension (the shape ceiling)
    nb: int                 # block size (bucket-key member; see batched.py)
    dtype: str              # numpy dtype name
    uplo: str = "L"
    side: str = "L"         # solve only
    transa: str = "N"       # solve only: op(A)
    diag: str = "N"         # solve only
    nrhs: int = 0           # solve only: rhs free-axis width
    with_info: bool = True
    donate: bool = False
    #: Active autotune route (``Route.key()`` tuple, docs/autotune.md):
    #: a spec member, so a learned route change is a NEW bucket program
    #: (a visible miss + compile) — never an in-place retrace of the old
    #: one. The serve queue stamps it per bucket from the route table.
    route: tuple = ()

    @property
    def site(self) -> str:
        """Per-bucket telemetry site label (bounded cardinality: one per
        cached program; the route suffix adds at most one label per
        ladder rung)."""
        extra = (f".{self.side}{self.uplo}{self.transa}{self.diag}"
                 f".r{self.nrhs}" if self.op == "solve"
                 else f".{self.uplo}")
        if self.route:
            from ..autotune.routes import Route

            extra += f".rt_{Route(**dict(self.route)).tag()}"
        return (f"serve.{self.op}.b{self.batch}n{self.n}nb{self.nb}"
                f".{self.dtype}{extra}"
                + (".info" if self.with_info else "")
                + (".don" if self.donate else ""))

    def to_wire(self) -> dict:
        """JSON-safe form (fleet warmup handoff, docs/fleet.md): every
        field is already a JSON scalar except ``route``, whose
        key/value pairs survive the list round-trip."""
        doc = dataclasses.asdict(self)
        doc["route"] = [list(pair) for pair in self.route]
        return doc

    @classmethod
    def from_wire(cls, doc: dict) -> "ProgramSpec":
        """Inverse of :meth:`to_wire` — restores the route pairs to the
        tuples the (frozen, hashed) spec is keyed by, so a wire-round-
        tripped spec is ``==`` to the original."""
        doc = dict(doc)
        doc["route"] = tuple(tuple(pair) for pair in doc.get("route", ()))
        return cls(**doc)


def cholesky_spec(*, batch: int, n: int, nb: int, dtype: str,
                  uplo: str = "L", with_info: bool = True,
                  donate: bool = False, route: tuple = ()) -> ProgramSpec:
    return ProgramSpec(op="cholesky", batch=int(batch), n=int(n),
                       nb=int(nb), dtype=str(dtype), uplo=uplo,
                       with_info=bool(with_info), donate=bool(donate),
                       route=tuple(route))


def solve_spec(*, batch: int, n: int, nrhs: int, nb: int, dtype: str,
               side: str = "L", uplo: str = "L", transa: str = "N",
               diag: str = "N", with_info: bool = True,
               donate: bool = False, route: tuple = ()) -> ProgramSpec:
    return ProgramSpec(op="solve", batch=int(batch), n=int(n), nb=int(nb),
                       dtype=str(dtype), uplo=uplo, side=side,
                       transa=transa, diag=diag, nrhs=int(nrhs),
                       with_info=bool(with_info), donate=bool(donate),
                       route=tuple(route))


def eigh_spec(*, batch: int, n: int, nb: int, dtype: str, uplo: str = "L",
              with_info: bool = True, donate: bool = False,
              route: tuple = ()) -> ProgramSpec:
    return ProgramSpec(op="eigh", batch=int(batch), n=int(n), nb=int(nb),
                       dtype=str(dtype), uplo=uplo,
                       with_info=bool(with_info), donate=bool(donate),
                       route=tuple(route))


def program_builder(spec: ProgramSpec):
    """``(batched fn, arg ShapeDtypeStructs, donate_argnums)`` for one
    bucket spec — the UNJITTED vmapped program, shared with the
    graphcheck traced matrix (analysis/graphcheck.py serve specs) so the
    audited programs are the served programs."""
    import functools

    import jax

    from ..algorithms import batched as bt

    dt = np.dtype(spec.dtype)
    b_, n = spec.batch, spec.n
    a_st = jax.ShapeDtypeStruct((b_, n, n), dt)
    if spec.op == "cholesky":
        fn = jax.vmap(functools.partial(bt.cholesky_one, uplo=spec.uplo,
                                        nb=spec.nb,
                                        with_info=spec.with_info))
        return fn, (a_st,), ((0,) if spec.donate else ())
    if spec.op == "solve":
        rhs_shape = ((b_, n, spec.nrhs) if spec.side == "L"
                     else (b_, spec.nrhs, n))
        b_st = jax.ShapeDtypeStruct(rhs_shape, dt)
        al_st = jax.ShapeDtypeStruct((b_,), dt)
        fn = jax.vmap(functools.partial(bt.solve_one, side=spec.side,
                                        uplo=spec.uplo, op=spec.transa,
                                        diag=spec.diag,
                                        with_info=spec.with_info))
        return fn, (a_st, b_st, al_st), ((1,) if spec.donate else ())
    if spec.op == "eigh":
        fn = jax.vmap(functools.partial(bt.eigh_one, uplo=spec.uplo,
                                        with_info=spec.with_info))
        return fn, (a_st,), ((0,) if spec.donate else ())
    raise ValueError(f"unknown serve op {spec.op!r}")


def _estimate_bytes(spec: ProgramSpec, memory: Optional[dict]) -> int:
    """Residency cost of one cached program: the allocator's own peak
    when the backend reports a memory analysis, else the summed
    argument+output aval bytes (a deliberate UNDER-estimate — the budget
    stays a budget, not a precise allocator model)."""
    if memory and math.isfinite(memory.get("peak", float("nan"))):
        return max(int(memory["peak"]), 1)
    _, args, _ = program_builder(spec)
    arg_bytes = sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
                    for a in args)
    return max(2 * arg_bytes, 1)


@dataclasses.dataclass
class _Entry:
    compiled: object
    nbytes: int
    compile_s: float
    pinned: bool = False


class ProgramService:
    """Keyed AOT program cache with warmup/pin/evict under an LRU byte
    budget (see module docstring). Thread-safe: a serving front end
    submits from request threads."""

    def __init__(self, cache_bytes: Optional[int] = None):
        #: insertion order ≈ recency (moved-to-end on hit) — the LRU order
        self._entries: dict = {}
        self._lock = threading.RLock()
        self._cache_bytes = cache_bytes
        self._stats = {"hits": 0, "misses": 0, "warmups": 0, "pins": 0,
                       "evictions": 0, "compiles": 0, "compile_s": 0.0}

    # -- residency -------------------------------------------------------

    def _budget(self) -> int:
        if self._cache_bytes is not None:
            return int(self._cache_bytes)
        return int(get_configuration().serve_cache_bytes)

    def _bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    #: stats key -> metric label (the singular event name on the counter)
    _EVENTS = {"hits": "hit", "misses": "miss", "warmups": "warmup",
               "pins": "pin", "evictions": "evict"}

    def _count(self, event: str, spec: ProgramSpec) -> None:
        self._stats[event] += 1
        if obs.metrics_active():
            obs.counter("dlaf_serve_cache_total", event=self._EVENTS[event],
                        op=spec.op).inc()
            obs.gauge("dlaf_serve_cache_bytes").set(float(self._bytes()))

    def _evict_for_budget(self) -> None:
        budget = self._budget()
        if budget <= 0:
            return
        while self._bytes() > budget:
            victim = next((s for s, e in self._entries.items()
                           if not e.pinned), None)
            if victim is None:
                obs.get_logger("serve").warning_once(
                    ("serve_cache_all_pinned", budget),
                    f"serve program cache exceeds its {budget}-byte "
                    "budget but every program is pinned; nothing evicted",
                    budget=budget, bytes=self._bytes())
                return
            self._evict_locked(victim)

    def _evict_locked(self, spec: ProgramSpec) -> None:
        del self._entries[spec]
        self._count("evictions", spec)

    # -- compile / lookup ------------------------------------------------

    def _compile(self, spec: ProgramSpec) -> _Entry:
        import jax

        from ..autotune.routes import Route, applied

        fn, args, donate = program_builder(spec)
        jitted = jax.jit(fn, donate_argnums=donate)
        # the spec's autotune route must be LIVE while the program
        # traces (the routed knobs are read at trace time) — warmup and
        # miss compiles therefore bake the same route the spec is keyed
        # by, wherever the compile happens (docs/autotune.md)
        route = Route(**dict(spec.route)) if spec.route else None
        with applied(route):
            prog = obs.telemetry.aot_compile(spec.site, jitted, *args)
        self._stats["compiles"] += 1
        self._stats["compile_s"] += prog.compile_s
        return _Entry(compiled=prog.compiled,
                      nbytes=_estimate_bytes(spec, prog.memory),
                      compile_s=prog.compile_s)

    def get(self, spec: ProgramSpec, *, _event: str = "misses"):
        """The compiled executable for ``spec`` — compiling on a miss
        (counted ``miss``; ``warmup``/``pin`` compiles count their own
        events) and refreshing LRU recency on a hit."""
        with self._lock:
            entry = self._entries.get(spec)
            if entry is not None:
                self._entries[spec] = self._entries.pop(spec)   # recency
                self._count("hits", spec)
                return entry.compiled
            entry = self._compile(spec)
            self._entries[spec] = entry
            self._count(_event, spec)
            self._evict_for_budget()
            return entry.compiled

    def run(self, spec: ProgramSpec, *args):
        """Dispatch ``args`` through the bucket program (the batched
        entry points' call path). Donation-capability warnings are
        silenced the way every library dispatch silences them: the
        donated buffer is service-owned."""
        from ..matrix.tiling import quiet_donation

        prog = self.get(spec)
        with quiet_donation():
            return prog(*args)

    # -- explicit residency API -----------------------------------------

    def warmup(self, *specs: ProgramSpec) -> dict:
        """Pre-compile every missing spec (counted ``warmup``, never
        ``miss``); returns ``{spec: compile_seconds}`` (0.0 for already-
        warm entries). The server bring-up step: after warmup, an
        in-bucket request stream is all hits and never retraces."""
        walls = {}
        for spec in specs:
            with self._lock:
                if spec in self._entries:
                    walls[spec] = 0.0
                    continue
                with obs.span("serve.warmup", op=spec.op, site=spec.site):
                    entry = self._compile(spec)
                self._entries[spec] = entry
                self._count("warmups", spec)
                self._evict_for_budget()
                walls[spec] = entry.compile_s
        return walls

    def pin(self, *specs: ProgramSpec) -> None:
        """Exempt ``specs`` from LRU eviction (compiling any that are
        missing, counted ``pin``)."""
        for spec in specs:
            with self._lock:
                entry = self._entries.get(spec)
                if entry is None:
                    entry = self._compile(spec)
                    self._entries[spec] = entry
                entry.pinned = True
                self._count("pins", spec)
                self._evict_for_budget()

    def unpin(self, *specs: ProgramSpec) -> None:
        with self._lock:
            for spec in specs:
                entry = self._entries.get(spec)
                if entry is not None:
                    entry.pinned = False

    def evict(self, spec: ProgramSpec) -> bool:
        """Drop one cached program (pinned or not — an explicit evict is
        an operator decision). Returns False when it was not resident."""
        with self._lock:
            if spec not in self._entries:
                return False
            self._evict_locked(spec)
            return True

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        """Counters + live footprint: ``hits``/``misses``/``warmups``/
        ``pins``/``evictions``/``compiles``/``compile_s`` plus
        ``entries``/``bytes``/``pinned`` and the derived ``hit_rate``
        (hits / (hits + misses); 1.0 when nothing missed — the
        steady-state target after warmup)."""
        with self._lock:
            served = self._stats["hits"] + self._stats["misses"]
            return dict(self._stats, entries=len(self._entries),
                        bytes=self._bytes(),
                        pinned=sum(e.pinned
                                   for e in self._entries.values()),
                        hit_rate=(self._stats["hits"] / served
                                  if served else 1.0))

    def specs(self) -> tuple:
        with self._lock:
            return tuple(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    # config.register_program_cache protocol: knob changes invalidate the
    # traced routes baked into these executables
    cache_clear = clear


_SERVICE: Optional[ProgramService] = None
_SERVICE_LOCK = threading.Lock()


def get_service() -> ProgramService:
    """The process-default program service (what the batched entry
    points and ``serve.Queue`` use unless handed an explicit one)."""
    global _SERVICE
    if _SERVICE is None:
        with _SERVICE_LOCK:
            if _SERVICE is None:
                svc = ProgramService()
                register_program_cache(svc)
                _SERVICE = svc
    return _SERVICE


def warmup(*specs: ProgramSpec) -> dict:
    """``get_service().warmup(*specs)`` — the one-line server bring-up."""
    return get_service().warmup(*specs)


def _reset_for_tests() -> None:
    if _SERVICE is not None:
        _SERVICE.clear()
        _SERVICE._stats.update(hits=0, misses=0, warmups=0, pins=0,
                               evictions=0, compiles=0, compile_s=0.0)
