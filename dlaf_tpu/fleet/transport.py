"""Length-prefixed JSON framing over local sockets (docs/fleet.md).

The fleet tier's only wire format: each message is a 4-byte big-endian
unsigned length followed by that many bytes of UTF-8 JSON — the same
zero-new-deps stdlib discipline as the ``obs/exporter.py`` HTTP
endpoint, chosen over pickle (no cross-process code execution surface)
and over a streaming parser (framing makes partial-read handling
trivial and a torn message impossible: a frame either arrives whole or
the connection is dead). Arrays ride INSIDE the JSON via the serve wire
codec (:func:`dlaf_tpu.serve.queue.array_to_wire`) — this module only
moves bytes.

Failure vocabulary: EOF mid-frame or on a frame boundary raises
:class:`TransportClosed` (the router's fast worker-death signal);
a socket timeout BETWEEN frames raises :class:`TransportIdle` (the
worker loop's "check the drain flag" tick) while a timeout mid-frame
keeps reading — the peer writes frames atomically, so a half-received
frame means bytes are in flight, not lost.
"""

from __future__ import annotations

import json
import socket
import struct

#: Hard per-frame bound. A frame length above this is a protocol error
#: (corrupt stream / wrong peer), not a big request — serve-regime
#: requests are small by definition and even a 4096-lane f64 bucket of
#: n=512 is ~8 GiB short of this.
MAX_FRAME_BYTES = 256 << 20

_LEN = struct.Struct(">I")


class TransportClosed(ConnectionError):
    """The peer closed the connection (EOF) — at a frame boundary or,
    worse, mid-frame. The router treats either as worker death."""


class TransportIdle(TimeoutError):
    """No frame STARTED within the socket timeout. Nothing was consumed;
    the stream is intact — poll your flags and call recv again."""


def _recv_exact(sock: socket.socket, n: int, *, idle_ok: bool) -> bytes:
    """Read exactly ``n`` bytes. ``idle_ok`` governs only the FIRST
    byte: a timeout with zero bytes read raises :class:`TransportIdle`
    (clean idle tick); once any byte arrived, timeouts keep reading —
    abandoning a partial frame would desync the framing forever."""
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(n - got)
        except socket.timeout:
            if idle_ok and got == 0:
                raise TransportIdle("no frame within the socket timeout")
            continue
        if not chunk:
            raise TransportClosed(
                f"peer closed the connection ({got}/{n} bytes of the "
                "current read)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_msg(sock: socket.socket, obj: dict) -> None:
    """Frame and send one JSON message (atomic from the reader's view:
    ``sendall`` of length+payload in one buffer)."""
    payload = json.dumps(obj).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"fleet frame of {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket, *, idle_ok: bool = False) -> dict:
    """Receive one framed JSON message (see module docstring for the
    :class:`TransportClosed` / :class:`TransportIdle` split)."""
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size, idle_ok=idle_ok))
    if length > MAX_FRAME_BYTES:
        raise TransportClosed(
            f"frame length {length} exceeds MAX_FRAME_BYTES="
            f"{MAX_FRAME_BYTES} — corrupt stream or wrong peer")
    return json.loads(_recv_exact(sock, length, idle_ok=False)
                      .decode("utf-8"))
