"""Fleet router: durable-ticket dispatch across serve workers
(docs/fleet.md).

The front tier of ROADMAP item 3: requests enter here, get a durable
router-owned :class:`FleetTicket`, and are sharded bucket-stably across
N :mod:`.worker` replicas over the :mod:`.transport` framing. The
robustness contract, in order of importance:

* **Zero loss.** A ticket belongs to the router until the worker's
  ``result`` ACK arrives. Worker death — socket EOF (SIGKILL) or
  heartbeat timeout (wedged) — re-dispatches every unacknowledged
  ticket to a sibling through the shared
  :mod:`~dlaf_tpu.health.policy` engine; with failover disabled
  (``DLAF_FLEET_FAILOVER=0``) the tickets are poisoned with a
  structured :class:`~dlaf_tpu.health.errors.WorkerLostError` and
  ``ticket_lost`` fleet records that ``--require-fleet`` REJECTS — a
  lost ticket is an open incident, never a silent drop. Semantics are
  therefore AT-LEAST-ONCE: a timed-out-but-alive worker may still
  complete a re-dispatched ticket; the first ACK wins, late ones drop.
* **Breaker-aware routing.** Each worker is gated by a circuit breaker
  at site ``fleet.worker{k}`` (:mod:`dlaf_tpu.health.circuit`):
  dispatch faults and heartbeat timeouts open it, candidate selection
  skips open breakers, and re-admission is exactly the half-open probe
  discipline — one real request probes the recovered worker.
* **Determinism.** No decision happens off a router clock edge
  (``submit``/``poll``/``flush``): reader threads only enqueue messages
  and record last-seen; heartbeat-timeout evaluation runs against the
  injected ``clock`` at ``poll``. With a fake clock and the seeded
  :func:`~dlaf_tpu.health.inject.fail_fleet_dispatch` schedule, a
  failover drill replays exactly.
* **Observability.** Every routing decision lands as a ``fleet`` JSONL
  record (``route``/``redispatch``/``handback``/``worker_up``/
  ``worker_dead``/``heartbeat_timeout``/``draining``/``drained``/
  ``probe``/``ticket_lost``) stamped with the affected ticket's trace
  ID; worker death trips the flight recorder (reason
  ``fleet_worker_down``) with the routing decision already in-ring;
  the router registers on ``/healthz`` and :meth:`Router.healthz`
  aggregates per-worker payloads into one fleet view.

Bucket co-location: tickets route by a stable bucket string (op, bucket
ceiling, rhs ceiling, dtype, flags) CRC-indexed into the sorted
routable-worker list, so same-bucket requests land on the same worker
and fill its batches — failover shifts whole buckets to siblings, whose
warm program caches (shared persistent compile cache) absorb them
without a retrace.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
import zlib
from collections import deque
from typing import Callable, Optional

import numpy as np

from .. import obs
from ..config import get_configuration
from ..health import circuit as _circuit
from ..health.errors import FleetUnavailableError, WorkerLostError
from ..health.policy import RetryPolicy, with_policy
from ..obs import flight
from ..serve.queue import (Request, array_from_wire, bucket_ceiling,
                           rhs_ceiling)
from .membership import Membership
from . import transport

#: The policy-engine site of router ticket dispatch (resilience records,
#: ``dlaf_retry_total{site}``, the :func:`~dlaf_tpu.health.inject.hang`
#: stall target for fleet deadline drills).
DISPATCH_SITE = "fleet.dispatch"


def worker_site(worker: int) -> str:
    """The breaker site of one worker (``dlaf_circuit_state{site}``)."""
    return f"fleet.worker{int(worker)}"


class RemoteError(RuntimeError):
    """A worker processed a request and ACKed a structured failure
    (shed, expired, dispatch exhausted, ...). Terminal: the request WAS
    handled — at-least-once re-dispatch applies only to lost tickets.

    Attributes:
        worker: the worker that failed the request.
        etype: the worker-side exception type name.
        message: the worker-side message.
    """

    def __init__(self, worker: int, etype: str, message: str):
        self.worker = int(worker)
        self.etype = str(etype)
        self.message = str(message)
        super().__init__(f"worker {self.worker}: {self.etype}: "
                         f"{self.message}")


class FleetTicket:
    """Durable router-owned handle for one accepted request: retains the
    wire form for re-dispatch, the trace ID every related record is
    stamped with, and the worker attempt history. ``result()`` mirrors
    :class:`~dlaf_tpu.serve.queue.Ticket`: the unpadded host result, or
    a raise naming the structured cause."""

    def __init__(self, request: Request, seq: int, submitted: float):
        self.request = request
        self.seq = int(seq)
        self.submitted = submitted
        self.wire = request.to_wire()
        self.trace_id = obs.new_trace_id()
        self.bucket = _bucket_of(request)
        self.worker: Optional[int] = None
        self.attempts: list = []        # workers dispatched to, in order
        self.redispatched = 0
        self.done = False
        self.error: Optional[BaseException] = None
        self.info: Optional[int] = None
        self.queue_s: Optional[float] = None
        self.total_s: Optional[float] = None
        self._result = None

    def resolved(self) -> bool:
        return self.done or self.error is not None

    def result(self):
        if self.error is not None:
            raise RuntimeError(
                f"fleet ticket {self.seq}: request failed "
                f"({type(self.error).__name__})") from self.error
        if not self.done:
            raise RuntimeError(
                f"fleet ticket {self.seq} is still in flight; "
                "Router.join()/poll() drive completion")
        return self._result


def _bucket_of(req: Request) -> str:
    """Stable bucket-routing string (module docstring): same fields the
    serve queue buckets by, so co-located tickets batch together."""
    a = np.asarray(req.a)
    n = bucket_ceiling(a.shape[0])
    nrhs = 0
    if req.op == "solve":
        b = np.asarray(req.b)
        free = b.shape[1] if req.side == "L" else b.shape[0]
        nrhs = rhs_ceiling(free)
    return (f"{req.op}.n{n}.r{nrhs}.{a.dtype.name}"
            f".{req.uplo}{req.side}{req.transa}{req.diag}")


class Router:
    """The fleet front tier (module docstring).

    ``heartbeat_s``/``heartbeat_timeout_s``/``failover``/
    ``retry_attempts``/``retry_backoff_s`` default to the
    ``DLAF_FLEET_*`` knobs; ``clock`` is injectable for deterministic
    drills. The router listens on ``host:port`` (port 0 = OS-assigned;
    read :attr:`port`) and workers dial in with a ``hello``."""

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 heartbeat_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 failover: Optional[bool] = None,
                 retry_attempts: Optional[int] = None,
                 retry_backoff_s: Optional[float] = None,
                 host: str = "127.0.0.1", port: int = 0):
        cfg = get_configuration()
        self.clock = clock
        self.heartbeat_s = float(
            cfg.fleet_heartbeat_ms / 1e3 if heartbeat_s is None
            else heartbeat_s)
        timeout_s = float(
            cfg.fleet_heartbeat_timeout_ms / 1e3
            if heartbeat_timeout_s is None else heartbeat_timeout_s)
        self.failover = bool(cfg.fleet_failover if failover is None
                             else failover)
        self.retry_attempts = int(
            cfg.fleet_retry_attempts if retry_attempts is None
            else retry_attempts)
        self.retry_backoff_s = float(
            cfg.fleet_retry_backoff_ms / 1e3 if retry_backoff_s is None
            else retry_backoff_s)
        self.membership = Membership(heartbeat_timeout_s=timeout_s,
                                     clock=clock)
        self._lock = threading.RLock()
        self._seq = itertools.count()
        self._tickets: dict = {}        # seq -> unresolved FleetTicket
        self._assigned: dict = {}       # worker -> set of unacked seqs
        self._socks: dict = {}          # worker -> socket
        self._inbox: deque = deque()    # (worker, msg) from readers
        self._replies: dict = {}        # (worker, kind) -> msg
        self._last_ping = self.clock()
        self._closing = False
        self.redispatches = 0
        self.handbacks = 0
        self.lost = 0
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="fleet-accept").start()
        # visible on the live /healthz endpoint LAST, fully constructed
        obs.exporter.register_fleet(self)

    # -- reader side (record only; decisions happen at clock edges) -------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(sock,),
                             daemon=True, name="fleet-reader").start()

    def _reader(self, sock: socket.socket) -> None:
        worker = None
        try:
            hello = transport.recv_msg(sock)
            if hello.get("kind") != "hello":
                sock.close()
                return
            worker = int(hello["worker"])
            with self._lock:
                self._socks[worker] = sock
                self.membership.add(worker, hello.get("pid"))
            self._emit("worker_up", worker=worker,
                       attrs={"pid": hello.get("pid")})
            while True:
                msg = transport.recv_msg(sock)
                self.membership.beat(worker)
                if msg.get("kind") == "pong":
                    continue
                self._inbox.append((worker, msg))
        except (transport.TransportClosed, OSError, ValueError):
            if worker is not None:
                self._inbox.append((worker, {"kind": "eof"}))

    # -- public queue-like API --------------------------------------------

    def submit(self, req: Request) -> FleetTicket:
        """Accept one request: durable ticket, bucket-stable dispatch.
        Submission is a clock edge (inbox + heartbeats are processed
        first). A dispatch that exhausts every attempt poisons the
        ticket with the cause AND raises it, mirroring
        :meth:`Queue.submit <dlaf_tpu.serve.queue.Queue.submit>`."""
        with self._lock:
            self._process(self.clock())
            seq = next(self._seq)
            if req.rid is None:
                req.rid = seq
            ticket = FleetTicket(req, seq, self.clock())
            self._tickets[seq] = ticket
            try:
                self._dispatch(ticket, "route")
            except Exception as e:
                ticket.error = e
                del self._tickets[seq]
                raise
            return ticket

    def poll(self) -> None:
        """The router clock edge: apply ACKs, evaluate heartbeat
        timeouts against the injected clock, send due pings, re-dispatch
        tickets of newly-dead/suspect workers."""
        with self._lock:
            self._process(self.clock())

    def flush(self) -> None:
        """Force every worker to dispatch its partial batches (end of
        stream / latency flush)."""
        with self._lock:
            self._process(self.clock())
            for worker in self.membership.routable():
                self._send(worker, {"kind": "flush"})

    def join(self, tickets, timeout_s: float = 60.0,
             poll_s: float = 0.005) -> bool:
        """Drive clock edges until every ticket resolves (result or
        error); returns False on wall-clock timeout. The waiting loop
        uses REAL wall time for its budget — the injected clock is a
        protocol clock, not a scheduler."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            if all(t.resolved() for t in tickets):
                return True
            if time.monotonic() >= deadline:
                return False
            self.poll()
            time.sleep(poll_s)

    def drain_fleet(self, timeout_s: float = 30.0) -> None:
        """Gracefully drain every worker (handbacks re-route until no
        routable worker remains) — the router-initiated shutdown."""
        with self._lock:
            self._process(self.clock())
            for worker in self.membership.routable():
                self._send(worker, {"kind": "drain"})
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            self.poll()
            if not self.membership.routable():
                return
            time.sleep(0.01)

    def close(self) -> None:
        # shutdown() before close(): the reader threads sit in a
        # blocking recv holding the open file description, so close()
        # alone never sends FIN — the accept loop and every worker
        # would block forever (and the worker Queues would stay pinned
        # on /healthz). shutdown() wakes the blocked syscalls now.
        self._closing = True
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            for sock in self._socks.values():
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass

    # -- aggregated health ------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "workers": self.membership.states(),
                "unresolved": len(self._tickets),
                "redispatches": self.redispatches,
                "handbacks": self.handbacks,
                "lost": self.lost,
                "failover": self.failover,
                "breakers": {w: _circuit.peek(worker_site(w))
                             for w in self.membership.states()},
            }

    def fleet_view(self) -> dict:
        """The LOCAL fleet section of ``/healthz`` (no worker fan-out —
        the scrape thread must never block on a wedged worker)."""
        return self.stats()

    def healthz(self, timeout_s: float = 5.0) -> dict:
        """One aggregated fleet view: the local stats plus each routable
        worker's own ``/healthz`` payload (fanned out over the protocol;
        a worker that cannot answer within ``timeout_s`` is reported as
        its error string). ``status`` is ``ok`` only when every
        registered worker is up and answered."""
        with self._lock:
            self._process(self.clock())
            targets = self.membership.routable()
            for worker in targets:
                self._replies.pop((worker, "healthz"), None)
                self._send(worker, {"kind": "healthz"})
        payloads = {}
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline and len(payloads) < len(targets):
            self.poll()
            with self._lock:
                for worker in targets:
                    msg = self._replies.pop((worker, "healthz"), None)
                    if msg is not None:
                        payloads[worker] = msg.get("payload")
            time.sleep(0.005)
        states = self.membership.states()
        ok = (states and
              all(m["state"] == "up" for m in states.values()) and
              len(payloads) == len(targets))
        return {"status": "ok" if ok else "degraded",
                "fleet": self.stats(),
                "workers": {w: payloads.get(w, "no healthz reply")
                            for w in targets}}

    def warmup(self, specs, timeout_s: float = 120.0) -> dict:
        """Broadcast ``warmup`` (wire ProgramSpecs) to every routable
        worker and wait for the ACKs; returns
        ``{worker: compile_seconds}`` (missing = no ACK in time)."""
        wire = [s.to_wire() for s in specs]
        with self._lock:
            self._process(self.clock())
            targets = self.membership.routable()
            for worker in targets:
                self._replies.pop((worker, "warmed"), None)
                self._send(worker, {"kind": "warmup", "specs": wire})
        walls = {}
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline and len(walls) < len(targets):
            self.poll()
            with self._lock:
                for worker in targets:
                    msg = self._replies.pop((worker, "warmed"), None)
                    if msg is not None:
                        walls[worker] = float(msg.get("compile_s", 0.0))
            time.sleep(0.005)
        return walls

    # -- clock-edge processing --------------------------------------------

    def _process(self, now: float) -> None:
        while self._inbox:
            worker, msg = self._inbox.popleft()
            kind = msg.get("kind")
            if kind == "result":
                self._apply_result(worker, msg)
            elif kind == "draining":
                self.membership.mark_draining(worker)
                self._emit("draining", worker=worker)
            elif kind == "drained":
                self._apply_drained(worker, msg)
            elif kind == "eof":
                self._on_worker_down(worker, "eof")
            elif kind in ("healthz", "warmed"):
                self._replies[(worker, kind)] = msg
        for worker in self.membership.timed_out(now):
            self._on_heartbeat_timeout(worker)
        if now - self._last_ping >= self.heartbeat_s:
            self._last_ping = now
            for worker in self.membership.routable():
                self._send(worker, {"kind": "ping"})

    def _apply_result(self, worker: int, msg: dict) -> None:
        seq = int(msg["seq"])
        self._assigned.get(worker, set()).discard(seq)
        ticket = self._tickets.pop(seq, None)
        if ticket is None:
            return              # late duplicate of a re-dispatched ticket
        if msg.get("ok"):
            arrays = [array_from_wire(d) for d in msg.get("arrays", [])]
            ticket._result = arrays[0] if len(arrays) == 1 \
                else tuple(arrays)
            ticket.info = msg.get("info")
            ticket.queue_s = msg.get("queue_s")
            ticket.total_s = msg.get("total_s")
            ticket.done = True
            _circuit.breaker(worker_site(worker),
                             clock=self.clock).record_success()
        else:
            err = msg.get("error") or {}
            ticket.error = RemoteError(worker, err.get("type", "Exception"),
                                       err.get("message", ""))

    def _apply_drained(self, worker: int, msg: dict) -> None:
        handback = [int(s) for s in msg.get("handback", [])]
        self.membership.mark_dead(worker, "drained")
        self._emit("drained", worker=worker,
                   attrs={"handback": len(handback)})
        self._emit("worker_dead", worker=worker,
                   attrs={"reason": "drained"})
        self._assigned.pop(worker, None)
        for seq in handback:
            ticket = self._tickets.get(seq)
            if ticket is None or ticket.resolved():
                continue
            self.handbacks += 1
            try:
                self._dispatch(ticket, "handback", previous=worker)
            except Exception as e:
                ticket.error = e
                self._tickets.pop(seq, None)

    def _on_heartbeat_timeout(self, worker: int) -> None:
        """An ``up`` worker went silent past the timeout: force its
        breaker open (re-admission = the half-open probe), re-dispatch
        its unacked tickets, trip the flight recorder. The worker may
        still be alive — at-least-once semantics cover the overlap."""
        self._emit("heartbeat_timeout", worker=worker,
                   attrs={"timeout_s": self.membership.heartbeat_timeout_s})
        br = _circuit.breaker(worker_site(worker), clock=self.clock)
        while br.state() != "open":
            br.record_failure()
        self._reap(worker, "heartbeat_timeout")

    def _on_worker_down(self, worker: int, reason: str) -> None:
        already_dead = self.membership.state(worker) == "dead"
        self.membership.mark_dead(worker, reason)
        with self._lock:
            sock = self._socks.pop(worker, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if not already_dead:
            self._emit("worker_dead", worker=worker,
                       attrs={"reason": reason})
        self._reap(worker, reason)

    def _reap(self, worker: int, reason: str) -> None:
        """Resolve the fate of ``worker``'s unacknowledged tickets:
        re-dispatch (failover) or poison with ``ticket_lost`` records
        the validator rejects. Either way the flight recorder dumps with
        the decision in-ring."""
        seqs = sorted(self._assigned.pop(worker, set()))
        live = [s for s in seqs if s in self._tickets
                and not self._tickets[s].resolved()]
        flight.trigger("fleet_worker_down", worker=worker, cause=reason,
                       unacked=len(live), failover=self.failover)
        for seq in live:
            ticket = self._tickets[seq]
            if self.failover:
                self.redispatches += 1
                ticket.redispatched += 1
                try:
                    self._dispatch(ticket, "redispatch", previous=worker)
                except Exception as e:
                    ticket.error = e
                    self._tickets.pop(seq, None)
            else:
                self.lost += 1
                ticket.error = WorkerLostError(worker, seq, reason)
                self._tickets.pop(seq, None)
                with obs.trace_context(trace_id=ticket.trace_id):
                    self._emit("ticket_lost", worker=worker, seq=seq,
                               attrs={"reason": reason,
                                      "rid": ticket.request.rid})

    # -- dispatch ---------------------------------------------------------

    def _candidates(self, ticket: FleetTicket) -> list:
        """Routable workers in bucket-stable preference order: the CRC
        of the ticket's bucket string indexes the sorted routable list,
        so one bucket's tickets co-locate while distinct buckets spread
        across the fleet."""
        workers = self.membership.routable()
        if not workers:
            return []
        start = zlib.crc32(ticket.bucket.encode()) % len(workers)
        return workers[start:] + workers[:start]

    def _select(self, ticket: FleetTicket):
        """First candidate whose breaker admits the call (an open one is
        skipped; an elapsed-cooldown one admits THIS dispatch as its
        half-open probe). No admissible worker -> structured fail-fast.
        Returns ``(worker, probed)``."""
        for worker in self._candidates(ticket):
            br = _circuit.breaker(worker_site(worker), clock=self.clock)
            was = br.state()
            try:
                br.allow()
            except Exception:
                continue
            return worker, was != "closed"
        raise FleetUnavailableError(
            len(self.membership.states()),
            {w: m["state"] for w, m in self.membership.states().items()})

    def _dispatch(self, ticket: FleetTicket, event: str,
                  previous: Optional[int] = None) -> None:
        """Send one ticket under the retry policy. Worker selection
        happens PER ATTEMPT: a transient fault retries into the same
        (still-admitted) worker; a sustained fault opens that worker's
        breaker mid-policy and the next attempt re-routes to a sibling
        — exactly the failover drill contract (docs/fleet.md)."""
        from ..health import inject

        policy = RetryPolicy(max_attempts=self.retry_attempts,
                             backoff_base_s=self.retry_backoff_s)
        msg = {"kind": "submit", "seq": ticket.seq, "req": ticket.wire,
               "trace_id": ticket.trace_id}

        def _attempt():
            worker, probed = self._select(ticket)
            br = _circuit.breaker(worker_site(worker), clock=self.clock)
            try:
                inject.maybe_fail_fleet_dispatch()
                self._send_raw(worker, msg)
            except Exception:
                br.record_failure()
                raise
            return worker, probed

        worker, probed = with_policy(DISPATCH_SITE, _attempt,
                                     policy=policy, clock=self.clock)
        ticket.worker = worker
        ticket.attempts.append(worker)
        self._assigned.setdefault(worker, set()).add(ticket.seq)
        attrs = {"bucket": ticket.bucket, "rid": ticket.request.rid}
        if previous is not None:
            attrs["from"] = previous
        with obs.trace_context(trace_id=ticket.trace_id):
            self._emit(event, worker=worker, seq=ticket.seq, attrs=attrs)
            if probed:
                self._emit("probe", worker=worker, seq=ticket.seq,
                           attrs={"bucket": ticket.bucket})

    def _send(self, worker: int, msg: dict) -> None:
        """Best-effort control-plane send: a dead socket is routed
        through the EOF path instead of raising into the caller."""
        try:
            self._send_raw(worker, msg)
        except (OSError, KeyError):
            self._inbox.append((worker, {"kind": "eof"}))

    def _send_raw(self, worker: int, msg: dict) -> None:
        with self._lock:
            sock = self._socks.get(worker)
        if sock is None:
            raise ConnectionError(f"fleet worker {worker} has no live "
                                  "connection")
        transport.send_msg(sock, msg)

    # -- records ----------------------------------------------------------

    def _emit(self, event: str, *, worker: int,
              seq: Optional[int] = None, attrs: Optional[dict] = None
              ) -> None:
        payload = {"event": event, "worker": int(worker),
                   "attrs": attrs or {}}
        if seq is not None:
            payload["seq"] = int(seq)
        obs.emit_event("fleet", **payload)
        if obs.metrics_active():
            obs.counter("dlaf_fleet_events_total", event=event).inc()
