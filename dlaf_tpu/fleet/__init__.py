"""dlaf_tpu.fleet — multi-replica serve tier with failover
(docs/fleet.md, ROADMAP item 3).

The jump from "a server" to "a service": a :class:`~.router.Router`
front tier shards bucketed requests across N :class:`~.worker.
FleetWorker` replicas — each one the existing single-process serve
stack (``serve.Queue`` + ``ProgramService``), warm-started from the
persistent compile cache and the committed autotune table — over the
zero-new-deps length-prefixed-JSON transport of :mod:`.transport`.

Robustness contract (the headline, docs/fleet.md):

* every accepted request gets a durable router-owned
  :class:`~.router.FleetTicket`; worker death re-dispatches
  unacknowledged tickets to siblings (at-least-once, never dropped);
* liveness is heartbeat-based with clock-injectable timeouts
  (:mod:`.membership`) so drills replay deterministically;
* routing is breaker-aware per worker (``fleet.worker{k}`` sites,
  half-open probe re-admission);
* SIGTERM drains gracefully (``Queue.drain()`` handback, zero
  re-dispatches), SIGKILL exercises failover;
* every decision lands as a schema-validated ``fleet`` JSONL record
  (``python -m dlaf_tpu.obs.validate --require-fleet``) and worker
  death trips the flight recorder (reason ``fleet_worker_down``).
"""

from __future__ import annotations

from .membership import Membership  # noqa: F401
from .router import (DISPATCH_SITE, FleetTicket, RemoteError,  # noqa: F401
                     Router, worker_site)
from .transport import (MAX_FRAME_BYTES, TransportClosed,  # noqa: F401
                        TransportIdle, recv_msg, send_msg)


def __getattr__(name: str):
    # .worker is exposed lazily so ``python -m dlaf_tpu.fleet.worker``
    # does not import it twice (runpy warns when the -m target is
    # already in sys.modules) — same pattern as ``obs.devtrace``.
    if name in ("FleetWorker", "connect_worker"):
        from . import worker
        return getattr(worker, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "DISPATCH_SITE", "FleetTicket", "FleetWorker", "MAX_FRAME_BYTES",
    "Membership", "RemoteError", "Router", "TransportClosed",
    "TransportIdle", "connect_worker", "recv_msg", "send_msg",
    "worker_site",
]
