"""Fleet membership: per-worker liveness bookkeeping (docs/fleet.md).

One :class:`Membership` per router. Reader threads only RECORD here
(``beat`` on every message received); every liveness DECISION —
heartbeat-timeout evaluation, state transitions the router acts on —
happens at router clock edges (``Router.poll``), against the injected
``clock``, so drills replay deterministically (the same discipline as
``serve.Queue``'s no-background-thread deadlines).

State machine per worker::

    up ──(heartbeat_timeout_s without traffic)──> suspect
    suspect ──(any message arrives)──> up
    up|suspect ──(drain announced)──> draining
    any ──(socket EOF / drain completed)──> dead

``suspect`` stays ROUTABLE: the worker's circuit breaker (forced open at
the timeout) is what actually gates traffic, so re-admission follows the
half-open probe discipline of :mod:`dlaf_tpu.health.circuit` — one real
request probes the recovered worker, a success closes the breaker, a
failure re-opens it. ``dead`` and ``draining`` are never routable.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

#: States a worker can be routed in (see module docstring for why
#: ``suspect`` is included).
ROUTABLE_STATES = ("up", "suspect")


@dataclasses.dataclass
class Member:
    worker: int
    pid: Optional[int]
    state: str              # "up" | "suspect" | "draining" | "dead"
    last_seen: float
    reason: str = ""        # why dead/suspect ("eof", "heartbeat_timeout",
                            # "drained", ...)


class Membership:
    """The router's worker table (module docstring). ``clock`` is the
    router's injected clock; ``heartbeat_timeout_s`` the silence budget
    after which an ``up`` worker turns ``suspect``."""

    def __init__(self, *, heartbeat_timeout_s: float,
                 clock: Callable[[], float]):
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.clock = clock
        self._members: dict = {}        # worker -> Member
        self._lock = threading.Lock()

    # -- recording (reader threads + router) ------------------------------

    def add(self, worker: int, pid: Optional[int] = None) -> None:
        with self._lock:
            self._members[int(worker)] = Member(
                worker=int(worker), pid=pid, state="up",
                last_seen=self.clock())

    def beat(self, worker: int) -> None:
        """Any message from ``worker`` is proof of life: refresh
        ``last_seen`` and lift ``suspect`` back to ``up`` (dead and
        draining are terminal — a late pong does not resurrect)."""
        with self._lock:
            m = self._members.get(int(worker))
            if m is None:
                return
            m.last_seen = self.clock()
            if m.state == "suspect":
                m.state = "up"
                m.reason = ""

    def mark_draining(self, worker: int) -> None:
        with self._lock:
            m = self._members.get(int(worker))
            if m is not None and m.state != "dead":
                m.state = "draining"

    def mark_dead(self, worker: int, reason: str) -> None:
        with self._lock:
            m = self._members.get(int(worker))
            if m is not None and m.state != "dead":
                m.state = "dead"
                m.reason = str(reason)

    # -- decisions (router clock edges only) ------------------------------

    def timed_out(self, now: float) -> list:
        """CLOCK-EDGE evaluation: flip every ``up`` worker silent longer
        than ``heartbeat_timeout_s`` to ``suspect`` and return their
        indices (the router force-opens their breakers and re-dispatches
        their unacknowledged tickets)."""
        flipped = []
        with self._lock:
            for m in self._members.values():
                if m.state == "up" \
                        and now - m.last_seen > self.heartbeat_timeout_s:
                    m.state = "suspect"
                    m.reason = "heartbeat_timeout"
                    flipped.append(m.worker)
        return sorted(flipped)

    # -- introspection ----------------------------------------------------

    def state(self, worker: int) -> Optional[str]:
        with self._lock:
            m = self._members.get(int(worker))
            return m.state if m is not None else None

    def routable(self) -> list:
        """Worker indices traffic may be routed to, sorted (the stable
        order the router's deterministic bucket assignment indexes)."""
        with self._lock:
            return sorted(w for w, m in self._members.items()
                          if m.state in ROUTABLE_STATES)

    def states(self) -> dict:
        """``{worker: {state, pid, last_seen, reason}}`` — the fleet
        section of the aggregated healthz view."""
        with self._lock:
            return {m.worker: {"state": m.state, "pid": m.pid,
                               "last_seen": m.last_seen,
                               "reason": m.reason}
                    for m in sorted(self._members.values(),
                                    key=lambda m: m.worker)}
