"""Fleet worker: one serve replica behind the router (docs/fleet.md).

A :class:`FleetWorker` wraps the existing per-replica stack — one
:class:`~dlaf_tpu.serve.queue.Queue` over one
:class:`~dlaf_tpu.serve.programs.ProgramService`, warm-started from the
jax persistent compile cache (``DLAF_COMPILATION_CACHE_DIR``) and the
committed autotune table (``DLAF_AUTOTUNE_TABLE``) exactly like a
single-process server — and speaks the length-prefixed JSON protocol of
:mod:`.transport` back to the router over one connect-back socket.

The protocol loop is deliberately SINGLE-THREADED: a wedged dispatch
blocks the pong too, so the router's heartbeat timeout observes real
unresponsiveness, not just socket liveness. Deadline-based partial-batch
dispatch still works because every incoming message AND every idle tick
is a queue clock edge (``queue.poll()``), preserving the
no-background-thread determinism of the serve layer.

Message kinds (router -> worker): ``submit`` (one wire request + router
ticket seq + trace id), ``flush``, ``ping``, ``healthz``, ``warmup``
(wire ProgramSpecs), ``drain``. Worker -> router: ``hello``, ``result``
(the ACK — a ticket is only ever router-owned until this arrives),
``pong``, ``healthz``, ``warmed``, ``draining``, ``drained`` (carrying
the handback seq list).

Shutdown contract (docs/fleet.md): SIGTERM (or a router ``drain``)
triggers the GRACEFUL path — stop admission, absorb any submits already
in the socket buffer as unstarted handbacks, let the synchronous
in-flight dispatch finish (it already has, by single-threadedness),
``Queue.drain()`` the undispatched remainder, send results + the
``drained`` handback, exit 0. SIGKILL skips all of that and exercises
the router's failover path instead.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
from typing import Optional

from .. import obs
from ..health.errors import DrainedError
from ..serve.programs import ProgramSpec
from ..serve.queue import Queue, Request, array_to_wire
from . import transport

#: Socket timeout of the protocol loop — the idle-tick cadence at which
#: the worker polls its queue's deadlines and checks the drain flag.
IDLE_TICK_S = 0.05


class FleetWorker:
    """One worker's protocol loop over an already-connected socket
    (module docstring). ``queue`` defaults to a fresh config-driven
    :class:`~dlaf_tpu.serve.queue.Queue`; tests inject one with a fake
    clock / tiny batch."""

    def __init__(self, sock: socket.socket, worker: int,
                 queue: Optional[Queue] = None,
                 idle_tick_s: float = IDLE_TICK_S):
        self.sock = sock
        self.worker = int(worker)
        self.queue = queue if queue is not None else Queue()
        self.idle_tick_s = float(idle_tick_s)
        self._tickets: dict = {}        # router seq -> serve Ticket
        self._draining = False
        self._killed = False

    # -- external control (signal handler / tests) ------------------------

    def request_drain(self) -> None:
        """Arm the graceful-drain path; honored at the next loop tick
        (the SIGTERM handler calls this — nothing async-unsafe here)."""
        self._draining = True

    def kill(self) -> None:
        """SIGKILL stand-in for in-process drill workers: drop the
        connection with no drain, no handback, unacked tickets and all —
        the router must detect the EOF and fail over."""
        self._killed = True
        try:
            self.sock.close()
        except OSError:
            pass

    # -- the loop ---------------------------------------------------------

    def serve(self) -> None:
        """Run the protocol loop until drain completes or the router
        goes away. Sends ``hello`` first (the router learns this
        worker's index and pid from it, never from connection order)."""
        self.sock.settimeout(self.idle_tick_s)
        self._send({"kind": "hello", "worker": self.worker,
                    "pid": os.getpid()})
        try:
            while True:
                if self._draining:
                    self._drain()
                    return
                try:
                    msg = transport.recv_msg(self.sock, idle_ok=True)
                except transport.TransportIdle:
                    # idle tick = queue clock edge: deadline-expired
                    # partial batches dispatch here, results ack here
                    self._poll_safely()
                    self._pump()
                    continue
                self._handle(msg)
                self._pump()
        except (transport.TransportClosed, OSError):
            # the router went away (or this worker was kill()ed) — there
            # is nobody left to report to, so exit the loop cleanly; the
            # docstring's "until ... the router goes away" contract
            return
        finally:
            if not self._killed:
                try:
                    self.sock.close()
                except OSError:
                    pass

    # -- message handling -------------------------------------------------

    def _handle(self, msg: dict) -> None:
        kind = msg.get("kind")
        if kind == "submit":
            self._submit(msg)
        elif kind == "flush":
            try:
                self.queue.flush()
            except Exception:
                pass            # failed tickets are poisoned; _pump acks
        elif kind == "ping":
            self._poll_safely()
            self._send({"kind": "pong", "worker": self.worker})
        elif kind == "healthz":
            self._send({"kind": "healthz", "worker": self.worker,
                        "payload": obs.exporter.healthz_payload()})
        elif kind == "warmup":
            specs = [ProgramSpec.from_wire(d) for d in msg.get("specs", [])]
            walls = self.queue.service.warmup(*specs)
            self._send({"kind": "warmed", "worker": self.worker,
                        "compile_s": float(sum(walls.values()))})
        elif kind == "drain":
            self._draining = True

    def _submit(self, msg: dict) -> None:
        seq = int(msg["seq"])
        req = Request.from_wire(msg["req"])
        # sweep OTHER buckets' deadlines first so a failure there (whose
        # tickets are all mapped) cannot masquerade as this submit's
        self._poll_safely()
        try:
            ticket = self.queue.submit(req, trace_id=msg.get("trace_id"))
            self._tickets[seq] = ticket
        except Exception as e:
            # shed (OverloadError) or this bucket's inline dispatch
            # failed after the worker's own retries: ack the structured
            # cause — the router treats a processed-and-failed request
            # as final (at-least-once applies to LOST tickets only)
            self._send_error(seq, e)

    def _poll_safely(self) -> None:
        try:
            self.queue.poll()
        except Exception:
            pass                # poisoned tickets are acked by _pump

    # -- result pump ------------------------------------------------------

    def _pump(self) -> None:
        """Ack every resolved ticket (result or structured error) back
        to the router; drained tickets are NOT error-acked — the drain
        handback owns them."""
        for seq in [s for s, t in self._tickets.items()
                    if t.done or t.error is not None]:
            ticket = self._tickets[seq]
            if ticket.done:
                out = ticket._result
                arrays = (list(out) if isinstance(out, tuple) else [out])
                self._send({"kind": "result", "seq": seq, "ok": True,
                            "worker": self.worker,
                            "arrays": [array_to_wire(a) for a in arrays],
                            "info": ticket.info,
                            "queue_s": ticket.queue_s,
                            "total_s": ticket.total_s})
            elif isinstance(ticket.error, DrainedError):
                continue
            else:
                self._send_error(seq, ticket.error)
            del self._tickets[seq]

    def _send_error(self, seq: int, exc: BaseException) -> None:
        self._send({"kind": "result", "seq": seq, "ok": False,
                    "worker": self.worker,
                    "error": {"type": type(exc).__name__,
                              "message": str(exc)}})

    def _send(self, msg: dict) -> None:
        transport.send_msg(self.sock, msg)

    # -- graceful drain ---------------------------------------------------

    def _drain(self) -> None:
        """The SIGTERM / router-``drain`` path (module docstring)."""
        self._send({"kind": "draining", "worker": self.worker})
        # absorb submits already in the socket buffer: admission is
        # stopped, so they are unstarted by definition -> handback
        handback = []
        idle = 0
        while idle < 2:
            try:
                msg = transport.recv_msg(self.sock, idle_ok=True)
            except (transport.TransportIdle, transport.TransportClosed,
                    OSError):
                idle += 1
                continue
            if msg.get("kind") == "submit":
                handback.append(int(msg["seq"]))
            elif msg.get("kind") == "ping":
                self._send({"kind": "pong", "worker": self.worker})
        # the synchronous in-flight dispatch (if any) already completed;
        # ack its results, then hand back the undispatched remainder
        self._pump()
        drained = {id(t) for _, t in self.queue.drain()}
        for seq in [s for s, t in self._tickets.items()
                    if id(t) in drained]:
            handback.append(seq)
            del self._tickets[seq]
        self._pump()            # drain() may have raced a done ticket
        self._send({"kind": "drained", "worker": self.worker,
                    "handback": sorted(handback)})
        try:
            self.sock.close()
        except OSError:
            pass


def connect_worker(port: int, worker: int, host: str = "127.0.0.1",
                   queue: Optional[Queue] = None,
                   idle_tick_s: float = IDLE_TICK_S) -> FleetWorker:
    """Dial the router and wrap the connection (shared by the subprocess
    entry point below and the in-process drill workers in tests)."""
    sock = socket.create_connection((host, int(port)))
    return FleetWorker(sock, worker, queue=queue, idle_tick_s=idle_tick_s)


def main(argv=None) -> int:
    """``python -m dlaf_tpu.fleet.worker --connect HOST:PORT --worker K``
    — the real-subprocess worker (CI chaos drill, bench fleet arm).
    Stamps ``obs.set_rank(K)`` BEFORE any sink write so a ``%r``
    metrics-path template lands each worker's records in its own shard,
    and installs the SIGTERM graceful-drain handler."""
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--connect", required=True,
                        help="router address, HOST:PORT")
    parser.add_argument("--worker", required=True, type=int,
                        help="this worker's fleet index (also its obs "
                        "rank for %%r path templates)")
    args = parser.parse_args(argv)
    host, port = args.connect.rsplit(":", 1)
    obs.set_rank(args.worker)
    w = connect_worker(int(port), args.worker, host=host)
    signal.signal(signal.SIGTERM, lambda *_: w.request_drain())
    try:
        w.serve()
    finally:
        obs.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
