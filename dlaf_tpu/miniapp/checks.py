"""Platform-aware precision for the miniapp residual checks.

On TPU, XLA's X64 rewrite emulates every f64 operation with an f32 pair
(double-f32 arithmetic, ~47-49 effective mantissa bits) — there is no
native f64 unit. Residual tolerances of the form ``c * n * eps`` with
``eps = 2^-53`` are therefore unachievable by ANY f64 code path on that
platform, including XLA's own solves (measured 2026-07-31 on a v5e:
recursive-blocked f64 TRSM at n=8192 lands at ~2^-47.5-grade residual
on both the native-emulated and the int8-MXU gemm routes).

:func:`effective_eps` returns the dtype eps the *platform* can honor:
the true f64/f32 eps off-TPU, and the double-f32 effective eps
(:data:`EMULATED_F64_EPS`) for 64-bit dtypes when the computation ran on
an f64-emulating backend. Checks print the label so a relaxed tolerance
is always visible in the output — the point is honest
platform-calibrated verification, not a looser test.
"""

from __future__ import annotations

import numpy as np

#: Effective machine epsilon of XLA's double-f32 f64 emulation. Per-op
#: relative error of float-float add/mul is ~2^-48..2^-49; isolated
#: composed steps (round-2 TRSM probes) measured ~2^-47.5-grade. The
#: round-4 history of this constant: the 2026-08-01 dot_ab session
#: measured a route-independent 6.112e-9 config-#1 residual and this eps
#: was temporarily relaxed to 2^-45 on the theory of "composed emulation
#: error" — but the session-4e root-cause hunt found the true source:
#: the ozaki peel's use of the emulated-f64 ``round``, which mis-rounds
#: tie+epsilon values and saturates subsequent int8 slices
#: (tile_ops/ozaki.py _peel_slices). With the peel fixed, the same
#: pipelines measure 2.7e-15 (cholesky n=4096), 8.0e-15 (n=8192), and
#: 3.2e-14 / 6.9e-14 (red2band n=4096 eigenvalues, geqrf / householder
#: panel routes) ON SILICON — true f64 grade — so eps returns to the
#: per-op figure 2^-47 the probes support.
EMULATED_F64_EPS = 2.0 ** -47


def _real_dtype(dtype) -> np.dtype:
    return np.dtype(np.dtype(dtype).type(0).real.dtype)


def f64_is_emulated(of=None) -> bool:
    """True when f64 runs as double-f32 emulation — judged from the
    platform of the array that actually holds the checked result (``of``:
    the DEVICE array, e.g. ``out.storage`` — not a fetched numpy copy),
    so a result computed under ``jax.default_device`` on a non-default
    backend is judged by ITS platform, not the process default. A host
    numpy ``of`` is judged as native f64 arithmetic (False) — it carries
    no provenance, so pass the device array for device-computed results.
    With ``of=None`` the active jax default backend decides."""
    if of is not None:
        devs = getattr(of, "devices", None)
        if callable(devs):
            try:
                return any(d.platform == "tpu" for d in devs())
            except Exception:
                pass  # fall through to the process default
        else:
            return False  # host numpy array: native f64 arithmetic
    import jax

    return jax.default_backend() == "tpu"


def effective_eps(dtype, of=None):
    """``(eps, label)`` for residual tolerances: the dtype's eps, widened
    to :data:`EMULATED_F64_EPS` for 64-bit dtypes on f64-emulating
    backends. ``of`` (optional jax array) pins the judgment to the devices
    that produced the checked result. ``label`` is "" when nothing was
    widened."""
    rt = _real_dtype(dtype)
    eps = float(np.finfo(rt).eps)
    if rt == np.float64 and f64_is_emulated(of):
        exp = int(np.log2(EMULATED_F64_EPS))
        return EMULATED_F64_EPS, f" [tpu f64=2xf32 emulation, eps=2^{exp}]"
    return eps, ""
