"""Tile-kernel microbenchmark.

TPU-native counterpart of the reference's kernel runner
(``miniapp/kernel/miniapp_laset.cpp`` + ``kernel_runner.h``/``work_tiles.h``):
times one tile op over a batch of work tiles. Supports the ops whose
throughput matters for the algorithm mix: laset, lacpy, gemm, trsm, potrf.

Run:  python -m dlaf_tpu.miniapp.miniapp_kernel --kernel gemm -m 256 --batch 64
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import config
from ..common.round_robin import RoundRobin
from ..common.sync import hard_fence
from ..tile_ops import blas as tb
from ..tile_ops import lapack as tl
from ..types import total_ops, type_letter
from .options import add_miniapp_arguments, parse_miniapp_options, select_devices


def build_parser():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--kernel", choices=["laset", "lacpy", "gemm", "trsm", "potrf"],
                   default="laset")
    p.add_argument("-m", "--tile-size", type=int, default=256)
    p.add_argument("--batch", type=int, default=64)
    add_miniapp_arguments(p)
    return p


def run(argv=None):
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    select_devices(opts)
    m, batch = args.tile_size, args.batch
    dtype = opts.dtype
    rng = np.random.default_rng(0)
    # rotate between independent work-tile sets so consecutive timed runs
    # never re-read the buffers the previous run just touched (reference
    # WorkTiles rotation, miniapp/kernel/work_tiles.h)
    work = RoundRobin([
        (jnp.asarray(rng.standard_normal((batch, m, m)).astype(dtype)),
         jnp.asarray((rng.standard_normal((batch, m, m)) / m
                      + 2 * np.eye(m)).astype(dtype)))
        for _ in range(2)
    ])

    kernels = {
        "laset": (lambda a, spd: tl.laset("G", 1.0, 2.0, (batch, m, m), dtype), 0),
        "lacpy": (lambda a, spd: tl.lacpy("L", a, jnp.zeros_like(a)), 0),
        "gemm": (lambda a, spd: tb.gemm(a, a), batch * 2.0 * m**3 / 2),
        "trsm": (lambda a, spd: tb.trsm("L", "L", "N", "N", spd, a),
                 batch * m**3 / 2 / 2),
        "potrf": (lambda a, spd: tl.potrf("L", spd), batch * m**3 / 6),
    }
    fn, half_flops = kernels[args.kernel]
    from .. import obs

    jfn = jax.jit(fn)
    for a, spd in work:  # compile + device-place every work set before timing
        # telemetry-aware warmup: with DLAF_PROGRAM_TELEMETRY on, the
        # artifact carries this kernel's compile wall + memory analysis
        hard_fence(obs.telemetry.call(f"miniapp_kernel.{args.kernel}",
                                      jfn, a, spd))
    results = []
    flops = total_ops(dtype, half_flops, half_flops)
    for run_i in range(-opts.nwarmups, opts.nruns):
        a, spd = work.next_resource()
        # fenced per-run span, same contract as the other miniapps: the
        # JSONL record derives the honest GFlop/s
        step_span = obs.span("miniapp_kernel.run", flops=flops, run=run_i,
                             warmup=run_i < 0, kernel=args.kernel, m=m,
                             batch=batch, dtype=np.dtype(dtype).name)
        with step_span:
            t0 = time.perf_counter()
            out = obs.telemetry.call(f"miniapp_kernel.{args.kernel}",
                                     jfn, a, spd)
            hard_fence(out)
            t = time.perf_counter() - t0
        gflops = flops / t / 1e9
        if run_i < 0:
            continue
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s {args.kernel} "
              f"{type_letter(dtype)} ({m}, {m}) x{batch} {os.cpu_count()} "
              f"{jax.devices()[0].platform}", flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
    # counters land in the artifact when run() returns, not at exit
    obs.flush()
    return results


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
