"""HEGST (gen_to_std) benchmark driver.

TPU-native counterpart of the reference's ``miniapp/miniapp_gen_to_std.cpp``
(202 LoC): fenced timing, hegst flop model (n^3/2 muls + n^3/2 adds), schema
output line. BASELINE config #3: z, N=8192, nb=256, 2x2 grid.

Run:  python -m dlaf_tpu.miniapp.miniapp_gen_to_std -m 8192 -b 256 \
          --type z --grid-rows 2 --grid-cols 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from . import checks
from .. import config
from ..common.sync import hard_fence
from ..algorithms.cholesky import cholesky
from ..algorithms.gen_to_std import gen_to_std
from ..comm.grid import Grid
from ..common.index2d import GlobalElementSize, TileElementSize
from ..matrix.matrix import Matrix
from ..types import total_ops, type_letter
from .generators import hpd_element_fn
from .options import (CheckIterFreq, add_miniapp_arguments,
                      announce_donation, parse_miniapp_options,
                      select_devices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--matrix-size", type=int, default=4096)
    p.add_argument("-b", "--block-size", type=int, default=256)
    p.add_argument("--uplo", choices=["L", "U"], default="L")
    add_miniapp_arguments(p)
    return p


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    devices = select_devices(opts)

    n, nb = args.matrix_size, args.block_size
    grid = Grid(opts.grid_rows, opts.grid_cols, devices=devices,
                ordering=config.get_configuration().grid_ordering)
    use_grid = None if grid.num_devices == 1 else grid
    size = GlobalElementSize(n, n)
    block = TileElementSize(nb, nb)

    am = Matrix.from_element_fn(hpd_element_fn(n, opts.dtype), size, block,
                                grid=use_grid, dtype=opts.dtype)
    bm = Matrix.from_element_fn(hpd_element_fn(n, opts.dtype), size, block,
                                grid=use_grid, dtype=opts.dtype)
    # B itself is dead once factored (the reference's mat_b holds the
    # factor in place) — donate it and drop the handle: one full-matrix
    # HBM buffer back before the timed runs
    bf = cholesky(args.uplo, bm, donate=True)
    del bm
    hard_fence(bf.storage)

    backend = devices[0].platform
    results = []
    announce_donation()   # timed runs consume their input copies
    for run_i in range(-opts.nwarmups, opts.nruns):
        a_in = am.with_storage(am.storage + 0)
        hard_fence(a_in.storage)
        t0 = time.perf_counter()
        # donate: this run's fresh copy is dead after the call (reference
        # in-place semantics); frees one full matrix at n=16384 single-chip
        out = gen_to_std(args.uplo, a_in, bf, donate=True)
        hard_fence(out.storage)
        t = time.perf_counter() - t0
        gflops = total_ops(opts.dtype, n**3 / 2, n**3 / 2) / t / 1e9
        if run_i < 0:
            continue
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
              f"{type_letter(opts.dtype)}{args.uplo} ({n}, {n}) ({nb}, {nb}) "
              f"({opts.grid_rows}, {opts.grid_cols}) {os.cpu_count()} {backend}",
              flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        if opts.check is CheckIterFreq.ALL or (opts.check is CheckIterFreq.LAST and last):
            check(args.uplo, am, bf, out)
    return results


def check(uplo, am, bf, out) -> None:
    a = am.to_numpy()
    f = bf.to_numpy()
    c = out.to_numpy()
    n = a.shape[0]
    if uplo == "L":
        l = np.tril(f)
        cf = np.tril(c) + np.tril(c, -1).conj().T
        resid = np.linalg.norm(l @ cf @ l.conj().T - _hermfull(a, "L"))
    else:
        u = np.triu(f)
        cf = np.triu(c) + np.triu(c, 1).conj().T
        resid = np.linalg.norm(u.conj().T @ cf @ u - _hermfull(a, "U"))
    resid /= max(np.linalg.norm(a), 1e-30)
    eps, eps_label = checks.effective_eps(a.dtype, of=out.storage)
    tol = 100 * n * eps
    status = "PASSED" if resid < tol else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={tol:.3e}{eps_label}", flush=True)
    if resid >= tol:
        sys.exit(1)


def _hermfull(a, uplo):
    tri = np.tril(a, -1) if uplo == "L" else np.triu(a, 1)
    return tri + tri.conj().T + np.diag(np.real(np.diag(a)))


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
