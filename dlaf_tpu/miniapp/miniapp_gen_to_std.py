"""HEGST (gen_to_std) benchmark driver.

TPU-native counterpart of the reference's ``miniapp/miniapp_gen_to_std.cpp``
(202 LoC): fenced timing, hegst flop model (n^3/2 muls + n^3/2 adds), schema
output line. BASELINE config #3: z, N=8192, nb=256, 2x2 grid.

Run:  python -m dlaf_tpu.miniapp.miniapp_gen_to_std -m 8192 -b 256 \
          --type z --grid-rows 2 --grid-cols 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from .. import config
from ..common.sync import hard_fence
from ..algorithms.cholesky import cholesky
from ..algorithms.gen_to_std import gen_to_std
from ..comm.grid import Grid
from ..common.index2d import GlobalElementSize, TileElementSize
from ..matrix.matrix import Matrix
from ..types import total_ops, type_letter
from .generators import hpd_element_fn
from .options import (CheckIterFreq, add_miniapp_arguments,
                      announce_donation, parse_miniapp_options,
                      select_devices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--matrix-size", type=int, default=4096)
    p.add_argument("-b", "--block-size", type=int, default=256)
    p.add_argument("--uplo", choices=["L", "U"], default="L")
    add_miniapp_arguments(p)
    return p


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    devices = select_devices(opts)

    n, nb = args.matrix_size, args.block_size
    grid = Grid(opts.grid_rows, opts.grid_cols, devices=devices,
                ordering=config.get_configuration().grid_ordering)
    use_grid = None if grid.num_devices == 1 else grid
    size = GlobalElementSize(n, n)
    block = TileElementSize(nb, nb)

    am = Matrix.from_element_fn(hpd_element_fn(n, opts.dtype), size, block,
                                grid=use_grid, dtype=opts.dtype)
    bm = Matrix.from_element_fn(hpd_element_fn(n, opts.dtype), size, block,
                                grid=use_grid, dtype=opts.dtype)
    # B itself is dead once factored (the reference's mat_b holds the
    # factor in place) — donate it and drop the handle: one full-matrix
    # HBM buffer back before the timed runs
    bf = cholesky(args.uplo, bm, donate=True)
    del bm
    hard_fence(bf.storage)

    backend = devices[0].platform
    results = []
    announce_donation()   # timed runs consume their input copies
    for run_i in range(-opts.nwarmups, opts.nruns):
        a_in = am.with_storage(am.storage + 0)
        hard_fence(a_in.storage)
        t0 = time.perf_counter()
        # donate: this run's fresh copy is dead after the call (reference
        # in-place semantics); frees one full matrix at n=16384 single-chip
        out = gen_to_std(args.uplo, a_in, bf, donate=True)
        hard_fence(out.storage)
        t = time.perf_counter() - t0
        gflops = total_ops(opts.dtype, n**3 / 2, n**3 / 2) / t / 1e9
        if run_i < 0:
            continue
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
              f"{type_letter(opts.dtype)}{args.uplo} ({n}, {n}) ({nb}, {nb}) "
              f"({opts.grid_rows}, {opts.grid_cols}) {os.cpu_count()} {backend}",
              flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        checked = opts.check is CheckIterFreq.ALL or \
            (opts.check is CheckIterFreq.LAST and last)
        if not checked:
            from ..obs import accuracy

            if accuracy.enabled():
                # paired perf+accuracy record per timed run
                # (DLAF_ACCURACY, docs/accuracy.md) — probe outside the
                # timed region; checked runs emit via check() instead
                value = accuracy.hegst_residual(args.uplo, am, bf, out)
                accuracy.emit(
                    "miniapp_gen_to_std", "hegst_residual", value, n=n,
                    nb=nb, c=100.0, dtype=opts.dtype, of=out.storage,
                    attrs={"uplo": args.uplo, "run": run_i,
                           "grid": f"{opts.grid_rows}x{opts.grid_cols}"})
        else:
            check(args.uplo, am, bf, out)
    return results


def check(uplo, am, bf, out) -> None:
    """Residual |L C L^H - A|_F / |A|_F <= c*n*eps (uplo U: the
    |U^H C U - A|_F form) via the shared device estimator
    (:func:`dlaf_tpu.obs.accuracy.hegst_residual`; the old path gathered
    all three matrices to the host for two O(n^3) numpy gemms). Stdout
    keeps the historical ``check:`` line contract."""
    from ..obs import accuracy as acc

    n = am.size.row
    resid = acc.hegst_residual(uplo, am, bf, out)
    res = acc.emit(
        "miniapp_gen_to_std", "hegst_residual", resid, n=n,
        nb=am.block_size.row, c=100.0, dtype=am.dtype, of=out.storage,
        attrs={"uplo": uplo, "check": True})
    status = "PASSED" if res.passed else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={res.tol:.3e}{res.eps_label}", flush=True)
    if not res.passed:
        sys.exit(1)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
