"""Analytic matrix generators for miniapps and tests.

Mirrors the reference test-support style (``util_generic_lapack.h``
``getCholeskySetters``, ``util_matrix.h`` ``set_random_hermitian_*``):
closed-form element functions, cheap to evaluate at any (i, j), deterministic,
with well-conditioned factorizations — so benchmark inputs at N=65536 never
require an O(n^3) host-side setup.
"""

from __future__ import annotations

import numpy as np

from ..types import is_complex


def hpd_element_fn(n: int, dtype):
    """Hermitian positive-definite element function.

    ``a(i,j) = 1/(1+|i-j|) + n·[i==j]`` (+ a small skew-Hermitian imaginary
    part for complex types): strictly diagonally dominant, hence HPD, with
    condition number O(n) — comparable to the reference's analytic setters.
    """
    def fn(i, j):
        base = 1.0 / (1.0 + np.abs(i - j)) + n * (i == j)
        if is_complex(dtype):
            im = np.sign(j - i) / (1.0 + np.abs(i - j)) / 2.0
            return base + 1j * im
        return base
    return fn


def random_hermitian(n: int, dtype, seed: int = 0, diag_boost: float | None = None):
    """Dense random Hermitian (optionally PD-shifted) host matrix; O(n^2)."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if is_complex(dtype):
        x = x + 1j * rng.standard_normal((n, n))
    a = (x + x.conj().T) / 2
    if diag_boost:
        a = a + diag_boost * np.eye(n)
    return a.astype(dtype)
