"""Cholesky benchmark driver.

TPU-native counterpart of the reference's ``miniapp/miniapp_cholesky.cpp``:
same fenced-timing protocol (device-sync before and after the factorization —
the analog of ``waitLocalTiles()`` + ``MPI_Barrier``, ``:134-146``), same flop
model (``total_ops(n^3/6, n^3/6)``, ``:149-154``), and the same schema for the
per-run output line (``:157-164``):

    [i] <t>s <gflops>GFlop/s <type><uplo> (m,m) (mb,mb) (gr,gc) <threads> <backend>

Run:  python -m dlaf_tpu.miniapp.miniapp_cholesky -m 4096 -b 256
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from .. import config
from ..common.sync import hard_fence
from ..algorithms.cholesky import cholesky
from ..comm.grid import Grid
from ..common.index2d import GlobalElementSize, TileElementSize
from ..matrix.matrix import Matrix
from ..types import total_ops, type_letter
from .generators import hpd_element_fn
from .options import (CheckIterFreq, add_miniapp_arguments,
                      announce_donation, parse_miniapp_options,
                      select_devices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--matrix-size", type=int, default=4096,
                   help="matrix size (reference default 4096)")
    p.add_argument("-b", "--block-size", type=int, default=256,
                   help="tile size (reference default 256)")
    p.add_argument("--uplo", choices=["L", "U"], default="L")
    add_miniapp_arguments(p)
    return p


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    devices = select_devices(opts)

    n, nb = args.matrix_size, args.block_size
    grid = Grid(opts.grid_rows, opts.grid_cols, devices=devices,
                ordering=config.get_configuration().grid_ordering)
    use_grid = None if grid.num_devices == 1 else grid

    size = GlobalElementSize(n, n)
    block = TileElementSize(nb, nb)
    ref = Matrix.from_element_fn(hpd_element_fn(n, opts.dtype), size, block,
                                 grid=use_grid, dtype=opts.dtype)
    backend = devices[0].platform
    threads = os.cpu_count()
    results = []
    from ..common.timer import PhaseTimer

    ptimer = PhaseTimer(config.get_configuration().profile_dir or None)
    try:
        return _timed_runs(args, opts, ref, ptimer, backend, threads, results)
    finally:
        ptimer.stop()


def _timed_runs(args, opts, ref, ptimer, backend, threads, results):
    from .. import obs
    from ..obs import accuracy

    n, nb = args.matrix_size, args.block_size
    flops = total_ops(opts.dtype, n**3 / 6, n**3 / 6)
    announce_donation()   # timed runs consume their input copies
    # --dlaf:check (the DLAF_CHECK knob): drive the robustness path by
    # hand — in-graph info detection, shift-retry recovery, finite guards
    # on input/factor — and report info/attempts per run (docs/
    # robustness.md). Off by default: the guard host-syncs by design, and
    # the robust driver cannot donate the run's input (the original must
    # survive for re-shifted retries), so peak HBM is ~one full-matrix
    # buffer higher than the plain donated path — near the single-chip
    # ceiling (N=16384) run WITHOUT the flag.
    robust = config.get_configuration().check
    if robust:
        from ..health import robust_cholesky
    for run_i in range(-opts.nwarmups, opts.nruns):
        mat = ref.with_storage(ref.storage + 0)   # fresh copy per run (:127-128)
        hard_fence(mat.storage)                   # start fence (:134-136)
        t0 = time.perf_counter()
        # per-step span: fenced device wall per timed run, with the
        # reference flop model attached so the JSONL record derives
        # GFlop/s — the per-step artifact the CI smoke gate validates
        step_span = obs.span("miniapp_cholesky.run", flops=flops,
                             run=run_i, warmup=run_i < 0, n=n, nb=nb,
                             uplo=args.uplo,
                             dtype=np.dtype(opts.dtype).name,
                             grid=f"{opts.grid_rows}x{opts.grid_cols}",
                             backend=backend)
        rec = None
        with step_span, ptimer.phase("cholesky.factor", run=run_i):
            if robust:
                rec = robust_cholesky(args.uplo, mat)
                out = rec.matrix
            else:
                # donate: the reference's cholesky overwrites mat_a in
                # place (factorization/cholesky.h:36); this run's fresh
                # copy is dead after the call, and the freed buffer is
                # what lets N=16384 fit the single chip
                out = cholesky(args.uplo, mat, donate=True)
            hard_fence(out.storage)               # end fence (:142-144)
        t = time.perf_counter() - t0
        gflops = flops / t / 1e9
        if run_i < 0:
            continue
        line = (f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
                f"{type_letter(opts.dtype)}{args.uplo} ({n}, {n}) ({nb}, {nb}) "
                f"({opts.grid_rows}, {opts.grid_cols}) {threads} {backend}")
        if rec is not None:
            line += (f" info={rec.infos[-1]} attempts={rec.attempts}"
                     f" shifts={list(rec.shifts)}")
        print(line, flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        checked = opts.check is CheckIterFreq.ALL or \
            (opts.check is CheckIterFreq.LAST and last)
        if accuracy.enabled() and not checked:
            # accuracy telemetry (DLAF_ACCURACY, docs/accuracy.md): one
            # in-graph residual probe per timed run, OUTSIDE the timed
            # region — the paired perf+accuracy record the accuracy gate
            # consumes. O(n^2) device work; never touches the factor.
            # Checked runs skip this: check_cholesky runs the identical
            # probe and emits the record itself.
            value = accuracy.cholesky_residual(args.uplo, ref, out)
            res = accuracy.emit(
                "miniapp_cholesky", "cholesky_residual", value, n=n, nb=nb,
                c=60.0, dtype=opts.dtype, of=out.storage,
                attrs={"uplo": args.uplo, "run": run_i,
                       "grid": f"{opts.grid_rows}x{opts.grid_cols}"})
            # donated-entry autotune feed (docs/autotune.md): the timed
            # run donated its input, so the entry could not probe — this
            # probe against the kept reference closes the loop instead
            from .. import autotune

            autotune.ingest_result("cholesky", res, n=n, nb=nb,
                                   dtype=opts.dtype,
                                   attrs={"entry": "miniapp_cholesky",
                                          "run": run_i})
        if checked:
            check_cholesky(args.uplo, ref, out)
    # land the counters (collective bytes, tile ops, span histograms) in
    # the artifact now — not at interpreter exit — so library callers and
    # the CI gate read a complete file as soon as run() returns
    obs.flush()
    return results


def check_cholesky(uplo: str, ref: Matrix, out: Matrix) -> None:
    """Residual check |A - L L^H|_F / |A|_F <= c*n*eps (reference
    ``:379-417``) via the shared device estimator
    (:func:`dlaf_tpu.obs.accuracy.cholesky_residual`) — a stochastic
    O(n^2) probe under DLAF_ACCURACY in {0, 1}, the exact Frobenius
    residual under "full"; no full-matrix host fetch either way (the old
    host numpy recompute gathered both matrices and paid an O(n^3)
    gemm). Stdout keeps the historical ``check:`` line contract."""
    from ..obs import accuracy

    n = ref.size.row
    resid = accuracy.cholesky_residual(uplo, ref, out)
    res = accuracy.emit(
        "miniapp_cholesky", "cholesky_residual", resid, n=n,
        nb=ref.block_size.row, c=60.0, dtype=ref.dtype, of=out.storage,
        attrs={"uplo": uplo, "check": True})
    from .. import autotune

    # donated-entry autotune feed (docs/autotune.md): checked runs
    # compute this residual anyway — ingest it so miniapp streams steer
    # even though the timed factorization donated its input
    autotune.ingest_result("cholesky", res, n=n, nb=ref.block_size.row,
                           dtype=ref.dtype,
                           attrs={"entry": "miniapp_cholesky",
                                  "check": True})
    status = "PASSED" if res.passed else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={res.tol:.3e}{res.eps_label}", flush=True)
    if not res.passed:
        sys.exit(1)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
