"""Back-transformation (band -> tridiag stage) benchmark driver.

TPU-native counterpart of the reference's
``miniapp/miniapp_bt_band_to_tridiag.cpp`` (195 LoC): times the application
of the bulge-chasing Householder vectors to an eigenvector matrix
(``bt_band_to_tridiag``), with the chase itself as untimed setup. Flop
model: ~n^2/b reflectors of length b applied to m columns at 4bm real ops
each -> muls = adds = 2 n^2 m.

Run:  python -m dlaf_tpu.miniapp.miniapp_bt_band_to_tridiag -m 4096 -b 128
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from .. import config
from ..common.sync import hard_fence
from ..common.index2d import TileElementSize
from ..comm.grid import Grid
from ..eigensolver.back_transform import bt_band_to_tridiag
from ..eigensolver.band_to_tridiag import band_to_tridiag
from ..matrix.matrix import Matrix
from ..types import total_ops, type_letter
from .miniapp_band_to_tridiag import make_band
from .options import CheckIterFreq, add_miniapp_arguments, parse_miniapp_options, select_devices


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--matrix-size", type=int, default=4096,
                   help="rows of the band matrix / eigenvector matrix")
    p.add_argument("-n", "--evec-cols", type=int, default=0,
                   help="eigenvector columns (default: matrix size)")
    p.add_argument("-b", "--band-size", type=int, default=128)
    add_miniapp_arguments(p)
    return p


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    devices = select_devices(opts)
    n, b = args.matrix_size, args.band_size
    m = args.evec_cols or n

    band = make_band(n, b, opts.dtype)
    tri = band_to_tridiag(band, b)          # untimed setup (own miniapp)
    rng = np.random.default_rng(1)
    e0 = rng.standard_normal((n, m)).astype(opts.dtype)

    grid = None
    if opts.grid_rows * opts.grid_cols > 1:
        grid = Grid(opts.grid_rows, opts.grid_cols, devices=devices)
    em = Matrix.from_global(e0, TileElementSize(b, b), grid=grid)

    backend = devices[0].platform
    results = []
    for run_i in range(-opts.nwarmups, opts.nruns):
        e_in = em.with_storage(em.storage + 0)
        hard_fence(e_in.storage)
        t0 = time.perf_counter()
        out = bt_band_to_tridiag(tri, e_in)
        hard_fence(out.storage)
        t = time.perf_counter() - t0
        gflops = total_ops(opts.dtype, 2.0 * n * n * m, 2.0 * n * n * m) / t / 1e9
        if run_i < 0:
            continue
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
              f"{type_letter(opts.dtype)} ({n}, {m}) band={b} "
              f"({opts.grid_rows}, {opts.grid_cols}) {os.cpu_count()} {backend}",
              flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        if opts.check is CheckIterFreq.ALL or (opts.check is CheckIterFreq.LAST and last):
            check(tri, e0, out)
    return results


def check(tri, e0, out) -> None:
    """|Q E - out| with the dense Q materialized by applying the reflectors
    to the identity, then one reference gemm (host-computed by
    construction; recorded through the shared accuracy emitter)."""
    from ..obs import accuracy

    n = tri.d.shape[0]
    qmat = np.asarray(bt_band_to_tridiag(tri, np.eye(n, dtype=out.dtype)))
    qe = qmat @ np.asarray(e0, dtype=out.dtype)
    got = out.to_numpy()
    resid = np.linalg.norm(got - qe) / max(np.linalg.norm(qe), 1e-30)
    rec = accuracy.emit("miniapp_bt_band_to_tridiag", "bt_residual", resid,
                        n=n, nb=out.block_size.row, c=100.0,
                        dtype=out.dtype, of=out.storage,
                        attrs={"check": True})
    status = "PASSED" if rec.passed else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={rec.tol:.3e}{rec.eps_label}", flush=True)
    if not rec.passed:
        sys.exit(1)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
