"""Generalized eigensolver benchmark driver.

TPU-native counterpart of the reference's
``miniapp/miniapp_gen_eigensolver.cpp`` (190 LoC). The pipeline (cholesky ->
gen_to_std -> eigensolver -> triangular back-substitution) and the timing
protocol are shared with :mod:`.miniapp_eigensolver`; this standalone entry
point mirrors the reference's separate executable and BASELINE config #5
(gen_eigensolver d N=32768 nb=512 8x8).

Run:  python -m dlaf_tpu.miniapp.miniapp_gen_eigensolver -m 4096 -b 256
"""

from __future__ import annotations

from .miniapp_eigensolver import run as _run_eigensolver


def run(argv=None) -> list[dict]:
    import sys

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if "--generalized" not in argv:
        argv.append("--generalized")
    return _run_eigensolver(argv)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
