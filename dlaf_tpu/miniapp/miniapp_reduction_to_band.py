"""Reduction-to-band benchmark driver.

TPU-native counterpart of the reference's
``miniapp/miniapp_reduction_to_band.cpp`` (204 LoC). Flop model: the
two-sided blocked Householder reduction costs ~4/3 n^3 (muls+adds evenly
split). BASELINE config #4: d, N=16384, nb=512, 4x4 grid.

Run:  python -m dlaf_tpu.miniapp.miniapp_reduction_to_band -m 16384 -b 512 \
          --grid-rows 4 --grid-cols 4
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from .. import config
from ..common.sync import hard_fence
from ..comm.grid import Grid
from ..common.index2d import GlobalElementSize, TileElementSize
from ..eigensolver.reduction_to_band import reduction_to_band
from ..matrix.matrix import Matrix
from ..types import total_ops, type_letter
from .options import (CheckIterFreq, add_miniapp_arguments,
                      announce_donation, parse_miniapp_options,
                      select_devices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--matrix-size", type=int, default=4096)
    p.add_argument("-b", "--block-size", type=int, default=256,
                   help="tile size (reference --block-size)")
    p.add_argument("--band-size", type=int, default=-1,
                   help="bandwidth; negative = block-size (reference "
                        "--band-size; must divide block-size; unlike the "
                        "reference this also works distributed). NOTE: the "
                        "step loop unrolls ceil(n/band)-1 panels at trace "
                        "time — very small bands inflate compile time")
    add_miniapp_arguments(p)
    return p


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    devices = select_devices(opts)

    n, nb = args.matrix_size, args.block_size
    band = nb if args.band_size < 0 else args.band_size
    grid = Grid(opts.grid_rows, opts.grid_cols, devices=devices,
                ordering=config.get_configuration().grid_ordering)
    use_grid = None if grid.num_devices == 1 else grid

    def fn(i, j):  # Hermitian analytic setter
        return np.cos(0.001 * (i * 31 + j * 17)) + np.cos(0.001 * (j * 31 + i * 17))

    ref = Matrix.from_element_fn(fn, GlobalElementSize(n, n),
                                 TileElementSize(nb, nb), grid=use_grid,
                                 dtype=opts.dtype)
    backend = devices[0].platform
    results = []
    announce_donation()   # timed runs consume their input copies
    for run_i in range(-opts.nwarmups, opts.nruns):
        mat = ref.with_storage(ref.storage + 0)
        hard_fence(mat.storage)
        t0 = time.perf_counter()
        # donate: this run's fresh copy is dead after the call (the
        # reference overwrites mat_a with V/R in place); frees one
        # full-matrix HBM buffer — needed headroom at n=16384 single-chip
        red = reduction_to_band(mat, band_size=band, donate=True)
        hard_fence(red.matrix.storage)
        t = time.perf_counter() - t0
        gflops = total_ops(opts.dtype, 2 * n**3 / 3, 2 * n**3 / 3) / t / 1e9
        if run_i < 0:
            continue
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
              f"{type_letter(opts.dtype)}L ({n}, {n}) ({nb}, {nb}) "
              f"({opts.grid_rows}, {opts.grid_cols}) {os.cpu_count()} {backend}",
              flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        if opts.check is CheckIterFreq.ALL or (opts.check is CheckIterFreq.LAST and last):
            check(ref, red, n, band)
    return results


def check(ref, red, n, band) -> None:
    """Eigenvalues of the band matrix must match the input's (an
    eigenvalue-set comparison — host-computed by construction; recorded
    through the shared accuracy emitter, docs/accuracy.md)."""
    from ..obs import accuracy

    a = ref.to_numpy()
    full = red.matrix.to_numpy()
    bd = np.zeros_like(a)
    for r in range(band + 1):
        d = np.diagonal(full, -r)
        bd += np.diag(d, -r)
        if r:
            bd += np.diag(d.conj(), r)
    w1 = np.linalg.eigvalsh(bd)
    w2 = np.linalg.eigvalsh(a)
    resid = np.abs(w1 - w2).max() / max(np.abs(w2).max(), 1e-30)
    rec = accuracy.emit("miniapp_reduction_to_band", "eigenvalue_drift",
                        resid, n=n, nb=ref.block_size.row, c=100.0,
                        dtype=a.dtype, of=red.matrix.storage,
                        attrs={"band": band, "check": True})
    status = "PASSED" if rec.passed else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={rec.tol:.3e}{rec.eps_label}", flush=True)
    if not rec.passed:
        sys.exit(1)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
