"""Shared miniapp option scaffolding.

TPU-native counterpart of the reference's
``miniapp/include/dlaf/miniapp/options.h:38-338`` (``MiniappOptions``: grid
rows/cols, nruns, nwarmups, check-result mode, backend, element type) and the
string->template dispatch of ``dispatch.h:1-75`` (here: string -> dtype/
backend values). Every miniapp parses these plus its own size options and the
``--dlaf:*`` runtime options (forwarded to :mod:`dlaf_tpu.config`).
"""

from __future__ import annotations

import argparse
import dataclasses
import enum

import numpy as np

from ..types import ELEMENT_TYPES


class CheckIterFreq(enum.Enum):
    """``--check-result`` mode (reference ``options.h`` CheckIterFreq)."""

    NONE = "none"
    LAST = "last"
    ALL = "all"


@dataclasses.dataclass
class MiniappOptions:
    grid_rows: int = 1
    grid_cols: int = 1
    nruns: int = 1
    nwarmups: int = 1
    check: CheckIterFreq = CheckIterFreq.NONE
    dtype: type = np.float64
    backend: str = "default"  # 'default' | 'mc' | 'tpu'


def add_miniapp_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--grid-rows", type=int, default=1,
                        help="process grid rows (reference --grid-rows)")
    parser.add_argument("--grid-cols", type=int, default=1,
                        help="process grid cols (reference --grid-cols)")
    parser.add_argument("--nruns", type=int, default=1, help="timed runs")
    parser.add_argument("--nwarmups", type=int, default=1, help="warmup runs")
    parser.add_argument("--check-result", choices=[c.value for c in CheckIterFreq],
                        default="none", help="verify the result")
    parser.add_argument("--type", choices=list(ELEMENT_TYPES), default="d",
                        help="element type s/d/c/z (reference --type)")
    parser.add_argument("--backend", choices=["default", "mc", "tpu"],
                        default="default",
                        help="'mc' forces the XLA-CPU backend, 'tpu' a TPU device")


def announce_donation() -> None:
    """Print the donation marker line. Miniapps whose timed runs donate
    their per-run input copies (the reference's in-place semantics) call
    this once before the run loop; ``scripts/summarize_session.py`` keys
    the history log's ``donate`` provenance flag on this marker, so
    harvested sessions record the flag only when the measured program
    actually aliased its input (round-4 advisory: donated and undonated
    timings must stay distinguishable)."""
    print("[meta] donate=1", flush=True)


def parse_miniapp_options(args: argparse.Namespace) -> MiniappOptions:
    return MiniappOptions(
        grid_rows=args.grid_rows, grid_cols=args.grid_cols,
        nruns=args.nruns, nwarmups=args.nwarmups,
        check=CheckIterFreq(args.check_result),
        dtype=ELEMENT_TYPES[args.type], backend=args.backend)


def select_devices(opts: MiniappOptions):
    """Device list for the requested backend; uses the virtual-device trick
    when the host must emulate a grid (tests / CPU runs)."""
    import os

    import jax

    # An accelerator plugin's register() may force-set jax_platforms at
    # interpreter start, silently overriding the JAX_PLATFORMS env var; the
    # config-level update wins (as long as no backend is initialized yet), so
    # re-assert the user's env choice here.
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and opts.backend == "default":
        # only the 'default' backend defers to the env; an explicit
        # --backend mc/tpu wins over an inherited JAX_PLATFORMS
        jax.config.update("jax_platforms", env_platforms)
    if opts.backend == "mc":
        jax.config.update("jax_platforms", "cpu")
    elif opts.backend == "tpu" and env_platforms:
        # defeat a leaked JAX_PLATFORMS=cpu: None = automatic discovery,
        # which prefers the registered accelerator plugin (whatever its
        # platform name) over CPU
        jax.config.update("jax_platforms", None)
    devs = jax.devices()
    if opts.backend == "tpu" and devs[0].platform == "cpu":
        raise SystemExit("--backend tpu requested but only CPU devices are "
                         "visible")
    need = opts.grid_rows * opts.grid_cols
    if len(devs) < need:
        raise SystemExit(
            f"grid {opts.grid_rows}x{opts.grid_cols} needs {need} devices but "
            f"only {len(devs)} are visible; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={need} with "
            f"JAX_PLATFORMS=cpu to emulate, or shrink the grid")
    return devs
