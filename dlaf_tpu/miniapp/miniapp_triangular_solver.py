"""Triangular-solve benchmark driver.

TPU-native counterpart of the reference's
``miniapp/miniapp_triangular_solver.cpp`` (285 LoC): fenced timing, TRSM flop
model (side-dependent m*m*n adds + muls), schema-stable output line.

Run:  python -m dlaf_tpu.miniapp.miniapp_triangular_solver -m 8192 -n 512 \
          -b 256 --grid-rows 2 --grid-cols 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from .. import config
from ..common.sync import hard_fence
from ..algorithms.triangular import triangular_solve
from ..comm.grid import Grid
from ..common.index2d import GlobalElementSize, TileElementSize
from ..matrix.matrix import Matrix
from ..types import total_ops, type_letter
from .options import (CheckIterFreq, add_miniapp_arguments,
                      announce_donation, parse_miniapp_options,
                      select_devices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--m", type=int, default=4096, help="rows of B")
    p.add_argument("-n", "--n", type=int, default=512, help="cols of B")
    p.add_argument("-b", "--block-size", type=int, default=256)
    p.add_argument("--side", choices=["L", "R"], default="L")
    p.add_argument("--uplo", choices=["L", "U"], default="L")
    p.add_argument("--op", choices=["N", "T", "C"], default="N")
    p.add_argument("--diag", choices=["N", "U"], default="N")
    add_miniapp_arguments(p)
    return p


def trsm_flops(dtype, side, m, n):
    """m^2 n (side L) / m n^2 (side R) muls + same adds (reference
    ``miniapp_triangular_solver.cpp`` flop model)."""
    mul = m * m * n / 2 if side == "L" else m * n * n / 2
    return total_ops(dtype, mul, mul)


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    devices = select_devices(opts)

    m, n, nb = args.m, args.n, args.block_size
    adim = m if args.side == "L" else n
    grid = Grid(opts.grid_rows, opts.grid_cols, devices=devices,
                ordering=config.get_configuration().grid_ordering)
    use_grid = None if grid.num_devices == 1 else grid

    def a_fn(i, j):  # well-conditioned triangular analytic setter
        return (1.0 / (1.0 + np.abs(i - j))) + 2.0 * adim * (i == j)

    def b_fn(i, j):
        return np.cos(0.001 * (i + 1)) + np.sin(0.002 * (j + 1))

    am = Matrix.from_element_fn(a_fn, GlobalElementSize(adim, adim),
                                TileElementSize(nb, nb), grid=use_grid,
                                dtype=opts.dtype)
    bm = Matrix.from_element_fn(b_fn, GlobalElementSize(m, n),
                                TileElementSize(nb, nb), grid=use_grid,
                                dtype=opts.dtype)
    backend = devices[0].platform
    results = []
    announce_donation()   # timed runs consume their input copies
    for run_i in range(-opts.nwarmups, opts.nruns):
        b_in = bm.with_storage(bm.storage + 0)
        hard_fence(b_in.storage)
        t0 = time.perf_counter()
        # donate_b: the reference solves in place into mat_b; this run's
        # fresh copy is dead after the call
        out = triangular_solve(args.side, args.uplo, args.op, args.diag, 1.0,
                               am, b_in, donate_b=True)
        hard_fence(out.storage)
        t = time.perf_counter() - t0
        gflops = trsm_flops(opts.dtype, args.side, m, n) / t / 1e9
        if run_i < 0:
            continue
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
              f"{type_letter(opts.dtype)}{args.side}{args.uplo}{args.op}{args.diag} "
              f"({m}, {n}) ({nb}, {nb}) ({opts.grid_rows}, {opts.grid_cols}) "
              f"{os.cpu_count()} {backend}", flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        checked = opts.check is CheckIterFreq.ALL or \
            (opts.check is CheckIterFreq.LAST and last)
        if not checked:
            from ..obs import accuracy

            if accuracy.enabled():
                # paired perf+accuracy record per timed run
                # (DLAF_ACCURACY, docs/accuracy.md) — probe outside the
                # timed region; checked runs emit via check() instead
                value = accuracy.trsm_residual(
                    args.side, args.uplo, args.op, args.diag, 1.0, am, bm,
                    out)
                accuracy.emit(
                    "miniapp_triangular_solver", "trsm_residual", value,
                    n=max(m, n), nb=nb, c=60.0, dtype=opts.dtype,
                    of=out.storage,
                    attrs={"side": args.side, "uplo": args.uplo,
                           "op": args.op, "diag": args.diag, "run": run_i,
                           "grid": f"{opts.grid_rows}x{opts.grid_cols}"})
        else:
            check(args, am, bm, out)
    return results


def check(args, am: Matrix, bm: Matrix, out: Matrix) -> None:
    """Residual |op(T) X - B|_F / |B|_F <= c*max(m,n)*eps via the shared
    device estimator (:func:`dlaf_tpu.obs.accuracy.trsm_residual`; the
    old path gathered A/B/X to the host for an O(m^2 n) numpy recompute).
    Stdout keeps the historical ``check:`` line contract."""
    from ..obs import accuracy as acc

    resid = acc.trsm_residual(args.side, args.uplo, args.op, args.diag,
                              1.0, am, bm, out)
    res = acc.emit(
        "miniapp_triangular_solver", "trsm_residual", resid,
        n=max(args.m, args.n), nb=args.block_size, c=60.0,
        dtype=am.dtype, of=out.storage,
        attrs={"side": args.side, "uplo": args.uplo, "op": args.op,
               "diag": args.diag, "check": True})
    status = "PASSED" if res.passed else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={res.tol:.3e}{res.eps_label}", flush=True)
    if not res.passed:
        sys.exit(1)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
