"""Band-to-tridiagonal benchmark driver.

TPU-native counterpart of the reference's
``miniapp/miniapp_band_to_tridiag.cpp`` (195 LoC): times the host bulge-chase
stage (native C++ or numpy impl per ``--dlaf:band_to_tridiag_impl``). Flop
model: ~6 n^2 b real ops for the chase (muls=adds=3 n^2 b).

Run:  python -m dlaf_tpu.miniapp.miniapp_band_to_tridiag -m 4096 -b 128
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from .. import config
from ..eigensolver.band_to_tridiag import band_to_tridiag
from ..types import total_ops, type_letter
from .options import CheckIterFreq, add_miniapp_arguments, parse_miniapp_options


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--matrix-size", type=int, default=4096)
    p.add_argument("-b", "--band-size", type=int, default=128)
    add_miniapp_arguments(p)
    return p


def make_band(n, b, dtype, seed=0):
    rng = np.random.default_rng(seed)
    band = rng.standard_normal((b + 1, n))
    if np.dtype(dtype).kind == "c":
        band = band + 1j * rng.standard_normal((b + 1, n))
        band[0] = np.real(band[0])
    for r in range(1, b + 1):
        band[r, n - r:] = 0
    return band.astype(dtype)


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    n, b = args.matrix_size, args.band_size
    band = make_band(n, b, opts.dtype)
    results = []
    for run_i in range(-opts.nwarmups, opts.nruns):
        t0 = time.perf_counter()
        res = band_to_tridiag(band, b)
        t = time.perf_counter() - t0
        gflops = total_ops(opts.dtype, 3.0 * n * n * b, 3.0 * n * n * b) / t / 1e9
        if run_i < 0:
            continue
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
              f"{type_letter(opts.dtype)} ({n}, {n}) band={b} "
              f"({opts.grid_rows}, {opts.grid_cols}) {os.cpu_count()} host",
              flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        if opts.check is CheckIterFreq.ALL or (opts.check is CheckIterFreq.LAST and last):
            check(band, b, res, n)
    return results


def check(band, b, res, n) -> None:
    import scipy.linalg as sla

    from ..obs import accuracy

    a = np.zeros((n, n), dtype=band.dtype)
    for r in range(b + 1):
        d = band[r, : n - r]
        a += np.diag(d, -r)
        if r:
            a += np.diag(d.conj(), r)
    w_ref = np.linalg.eigvalsh(a)
    w_tri = sla.eigvalsh_tridiagonal(res.d, res.e)
    resid = np.abs(w_ref - w_tri).max() / max(np.abs(w_ref).max(), 1e-30)
    # host-computed by construction (the check compares eigenvalue sets,
    # not a matrix residual) — still recorded through the shared
    # accuracy emitter so the artifact carries this family's quality too
    rec = accuracy.emit("miniapp_band_to_tridiag", "eigenvalue_drift",
                        resid, n=n, nb=b, c=100.0, dtype=np.float64,
                        of=res.d, attrs={"check": True})
    status = "PASSED" if rec.passed else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={rec.tol:.3e}{rec.eps_label}", flush=True)
    if not rec.passed:
        sys.exit(1)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
