"""Eigensolver benchmark drivers (standard + generalized).

TPU-native counterpart of the reference's ``miniapp/miniapp_eigensolver.cpp``
(177 LoC) and ``miniapp_gen_eigensolver.cpp`` (190 LoC). Flop model: the
canonical full Hermitian eigensolver cost ~(4/3 + 4/3 + 2) n^3 -> reported as
the reference does via time + derived GFLOPS with the 4n^3/3 reduction term
dominant; we report 10n^3/3 total (reduction + tridiag D&C + two back
transforms), muls = adds. BASELINE config #5: gen_eigensolver d N=32768
nb=512 8x8. Grid options > 1x1 run the distributed pipeline (beyond the
reference, whose eigensolver is local-only at this snapshot).

Run:  python -m dlaf_tpu.miniapp.miniapp_eigensolver -m 4096 -b 256
      python -m dlaf_tpu.miniapp.miniapp_eigensolver -m 4096 -b 256 --generalized
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

from .. import config
from ..common.sync import hard_fence
from ..common.index2d import GlobalElementSize, TileElementSize
from ..eigensolver.eigensolver import eigensolver, gen_eigensolver
from ..matrix.matrix import Matrix
from ..types import total_ops, type_letter
from .generators import hpd_element_fn
from .options import (CheckIterFreq, add_miniapp_arguments,
                      announce_donation, parse_miniapp_options,
                      select_devices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--matrix-size", type=int, default=1024)
    p.add_argument("-b", "--block-size", type=int, default=256)
    p.add_argument("--uplo", choices=["L", "U"], default="L")
    p.add_argument("--generalized", action="store_true",
                   help="solve A x = lambda B x (miniapp_gen_eigensolver)")
    p.add_argument("--band-size", type=int, default=-1,
                   help="reduction bandwidth; negative = block-size "
                        "(must divide block-size; works local and "
                        "distributed)")
    add_miniapp_arguments(p)
    return p


def run(argv=None) -> list[dict]:
    args, extra = build_parser().parse_known_args(argv)
    config.initialize(argv=extra)
    opts = parse_miniapp_options(args)
    devices = select_devices(opts)

    n, nb = args.matrix_size, args.block_size
    band = None if args.band_size < 0 else args.band_size
    size = GlobalElementSize(n, n)
    block = TileElementSize(nb, nb)

    def herm_fn(i, j):
        return np.cos(0.001 * (i * 31 + j * 17)) + np.cos(0.001 * (j * 31 + i * 17))

    grid = None
    if opts.grid_rows * opts.grid_cols > 1:
        from ..comm.grid import Grid

        grid = Grid(opts.grid_rows, opts.grid_cols, devices=devices)
    am = Matrix.from_element_fn(herm_fn, size, block, grid=grid, dtype=opts.dtype)
    bm = Matrix.from_element_fn(hpd_element_fn(n, opts.dtype), size, block,
                                grid=grid, dtype=opts.dtype) if args.generalized else None

    backend = devices[0].platform
    results = []
    from .. import obs
    from ..common.timer import PhaseTimer

    # phase instrumentation is opt-in (profile_dir set): its per-stage device
    # fences change the headline timing methodology, so the default protocol
    # stays a single end fence like the reference's
    profiling = bool(config.get_configuration().profile_dir)
    announce_donation()   # timed runs consume their input copies
    for run_i in range(-opts.nwarmups, opts.nruns):
        ptimer = PhaseTimer(config.get_configuration().profile_dir or None)
        phases = ptimer if profiling else None
        a_in = am.with_storage(am.storage + 0)
        hard_fence(a_in.storage)
        t0 = time.perf_counter()
        flops = total_ops(opts.dtype, 5 * n**3 / 3, 5 * n**3 / 3)
        step_span = obs.span(
            "miniapp_eigensolver.run", flops=flops, run=run_i,
            warmup=run_i < 0, n=n, nb=nb, uplo=args.uplo,
            generalized=bool(args.generalized),
            dtype=np.dtype(opts.dtype).name,
            grid=f"{opts.grid_rows}x{opts.grid_cols}", backend=backend)
        step_span.__enter__()
        try:
            # donate: this run's fresh copy of A is dead after the call
            # (reference in-place pipeline); B is reused across runs and
            # is never consumed
            if args.generalized:
                res = gen_eigensolver(args.uplo, a_in, bm, phases=phases,
                                      band_size=band, donate=True)
            else:
                res = eigensolver(args.uplo, a_in, phases=phases,
                                  band_size=band, donate=True)
            hard_fence(res.eigenvectors.storage)
        finally:
            step_span.__exit__(None, None, None)
            ptimer.stop()
        t = time.perf_counter() - t0
        gflops = flops / t / 1e9
        if run_i < 0:
            continue
        name = "gen_evp" if args.generalized else "evp"
        print(f"[{run_i}] {t:.6f}s {gflops:.2f}GFlop/s "
              f"{type_letter(opts.dtype)}{args.uplo} {name} ({n}, {n}) "
              f"({nb}, {nb}) ({opts.grid_rows}, {opts.grid_cols}) "
              f"{os.cpu_count()} {backend}", flush=True)
        if profiling:
            phase_str = " ".join(f"{k}={v:.4f}s" for k, v in ptimer.report().items())
            print(f"[{run_i}] phases: {phase_str}", flush=True)
        results.append({"run": run_i, "time_s": t, "gflops": gflops})
        last = run_i == opts.nruns - 1
        checked = opts.check is CheckIterFreq.ALL or \
            (opts.check is CheckIterFreq.LAST and last)
        if checked:
            check(args, am, bm, res, opts=opts)
        else:
            from ..obs import accuracy

            if accuracy.enabled():
                # paired perf+accuracy records per timed run
                # (DLAF_ACCURACY, docs/accuracy.md): eigenpair residual +
                # orthogonality probes, outside the timed region; checked
                # runs emit through check() instead
                _emit_eigen_records(args, opts, am, bm, res, run_i)
    obs.flush()   # complete the JSONL artifact before returning
    return results


#: Analytic tolerance factors (tol = c * n * eps_eff): the eigenpair
#: residual keeps the historical check's c=200; the orthogonality defect
#: |Z^H Z - I|_F of a backward-stable Hermitian eigensolver is bounded by
#: the same-grade c*n*eps.
EIGEN_BUDGETS = {"eigen_residual": 200.0, "eigenpair_max": 200.0,
                 "orthogonality": 200.0}


def _emit_eigen_records(args, opts, am, bm, res, run_i, check=False):
    from ..obs import accuracy as acc

    n = am.size.row
    vals = acc.eigen_residuals(args.uplo, am, res.eigenvalues,
                               res.eigenvectors,
                               b=bm if args.generalized else None)
    out = {}
    for metric, value in vals.items():
        out[metric] = acc.emit(
            "miniapp_eigensolver", metric, value, n=n, nb=args.block_size,
            c=EIGEN_BUDGETS[metric], dtype=opts.dtype,
            of=res.eigenvectors.storage,
            attrs={"uplo": args.uplo, "generalized": bool(args.generalized),
                   "run": run_i, "check": check,
                   "grid": f"{opts.grid_rows}x{opts.grid_cols}"})
    return vals, out


def check(args, am, bm, res, opts=None) -> None:
    """Eigenpair residual |A Z - [B] Z diag(lam)|_F / |A|_F <= c*n*eps
    via the shared device estimator
    (:func:`dlaf_tpu.obs.accuracy.eigen_residuals`; the old path gathered
    A/B/Z to the host for O(n^3) numpy gemms), plus orthogonality records
    in the artifact. Stdout keeps the historical ``check:`` line
    contract, keyed on the eigenpair residual like before."""
    if opts is None:
        opts = parse_miniapp_options(args)
    vals, out = _emit_eigen_records(args, opts=opts, am=am, bm=bm, res=res,
                                    run_i=-1, check=True)
    resid = vals["eigen_residual"]
    res_r = out["eigen_residual"]
    status = "PASSED" if res_r.passed else "FAILED"
    print(f"check: {status} residual={resid:.3e} tol={res_r.tol:.3e}{res_r.eps_label}", flush=True)
    if not res_r.passed:
        sys.exit(1)


def main(argv=None) -> int:
    """Console-script entry: run() returns per-run results for
    library callers; exit status must not carry that list."""
    run(argv)
    return 0


if __name__ == "__main__":
    main()
