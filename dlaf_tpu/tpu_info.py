"""TPU/PJRT device introspection.

TPU-native counterpart of the reference's ``gpu/`` API shim tree (~770 LoC of
CUDA/HIP spelling unification, error-check macros, and handle plumbing —
SURVEY §2/L1): on TPU the PJRT client owns devices, streams, allocators and
error handling, so the shim reduces to an introspection surface used by
miniapps and diagnostics.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .types import Backend, Device


@dataclasses.dataclass
class DeviceInfo:
    index: int
    platform: str          # 'tpu' | 'cpu' | ...
    kind: str              # e.g. 'TPU v5 lite'
    memory_bytes: Optional[int]


def devices(backend: Optional[Backend] = None) -> list[DeviceInfo]:
    """Visible devices, optionally filtered by backend."""
    import jax

    out = []
    for d in jax.devices():
        if backend is Backend.MC and d.platform != "cpu":
            continue
        if backend is Backend.TPU and d.platform == "cpu":
            continue
        mem = None
        try:
            stats = d.memory_stats()
            if stats:
                mem = stats.get("bytes_limit")
        except Exception:
            pass
        out.append(DeviceInfo(index=d.id, platform=d.platform,
                              kind=getattr(d, "device_kind", d.platform),
                              memory_bytes=mem))
    return out


def default_device() -> Device:
    import jax

    return Device.CPU if jax.devices()[0].platform == "cpu" else Device.TPU


def cpu_subprocess_env(n_virtual_devices: Optional[int] = None) -> dict:
    """Environment for a subprocess that must come up on the pure-CPU
    platform (accelerator plugin registration disabled), optionally with an
    n-device virtual CPU platform.

    Needed because a TPU plugin's register() force-sets ``jax_platforms`` at
    interpreter start, overriding the ``JAX_PLATFORMS`` env var; gating the
    plugin out of the child entirely is the only env-only way to force CPU.
    """
    import os
    import re

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize gates plugin on this
    env["JAX_PLATFORMS"] = "cpu"
    if n_virtual_devices is not None:
        flag = f"--xla_force_host_platform_device_count={n_virtual_devices}"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                           flag, flags)
        else:
            flags = (flags + " " + flag).strip()
        env["XLA_FLAGS"] = flags
    return env


def memory_in_use(device_index: int = 0) -> Optional[int]:
    """Live HBM bytes on a device (PJRT allocator stats), if reported."""
    import jax

    try:
        stats = jax.devices()[device_index].memory_stats()
        return stats.get("bytes_in_use") if stats else None
    except Exception:
        return None
