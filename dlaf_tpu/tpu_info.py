"""TPU/PJRT device introspection.

TPU-native counterpart of the reference's ``gpu/`` API shim tree (~770 LoC of
CUDA/HIP spelling unification, error-check macros, and handle plumbing —
SURVEY §2/L1): on TPU the PJRT client owns devices, streams, allocators and
error handling, so the shim reduces to an introspection surface used by
miniapps and diagnostics.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .types import Backend, Device


@dataclasses.dataclass
class DeviceInfo:
    index: int
    platform: str          # 'tpu' | 'cpu' | ...
    kind: str              # e.g. 'TPU v5 lite'
    memory_bytes: Optional[int]


def devices(backend: Optional[Backend] = None) -> list[DeviceInfo]:
    """Visible devices, optionally filtered by backend."""
    import jax

    out = []
    for d in jax.devices():
        if backend is Backend.MC and d.platform != "cpu":
            continue
        if backend is Backend.TPU and d.platform == "cpu":
            continue
        mem = None
        try:
            stats = d.memory_stats()
            if stats:
                mem = stats.get("bytes_limit")
        except Exception:
            pass
        out.append(DeviceInfo(index=d.id, platform=d.platform,
                              kind=getattr(d, "device_kind", d.platform),
                              memory_bytes=mem))
    return out


def default_device() -> Device:
    import jax

    return Device.CPU if jax.devices()[0].platform == "cpu" else Device.TPU


def memory_in_use(device_index: int = 0) -> Optional[int]:
    """Live HBM bytes on a device (PJRT allocator stats), if reported."""
    import jax

    try:
        stats = jax.devices()[device_index].memory_stats()
        return stats.get("bytes_in_use") if stats else None
    except Exception:
        return None
