"""Divide-and-conquer symmetric tridiagonal eigensolver.

TPU-native counterpart of the reference's ``eigensolver/tridiag_solver``
(``api.h:18-26``, ``impl.h``, ``merge.h``): Cuppen's method — split at tile
boundaries (``impl.h:66-80``), ``stedc`` leaf solves (``impl.h:84-90``),
bottom-up merges (``merge.h:790-887``) with rank-one tear, deflation
(zero-component + Givens rotation on near-equal poles, ``merge.h:443-508``),
per-root secular-equation solves (the reference uses LAPACK ``laed4`` on CPU,
``merge.h:590-629``), Gu-Eisenstat z-refinement, and eigenvector assembly by
GEMM (``merge.h`` via ``GeneralSub``).

Division of labor mirrors the reference's host/device split: O(n^2) control
work (deflation, secular roots via vectorized shifted bisection, z
refinement) runs on the host in float64; the O(n^3) eigenvector assembly runs
as device matmuls. Roots are stored as (anchor pole, offset) pairs so the
pole differences ``d_j - lambda_i`` that feed the eigenvector formula never
suffer cancellation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import obs
from ..config import register_program_cache
from ..tile_ops import blas as tb
from ..tile_ops.lapack import stedc

_EPS = np.finfo(np.float64).eps

#: Merges below this size run unsharded even when a mesh is given (the
#: collective overhead of a sharded gemm only pays off for big merges).
_SHARD_MERGE_MIN_N = 512

# Above this deflated-problem size the secular solve and the O(k^2)
# z-refinement run on the device (HBM-bound batched math). Below it the host
# path wins — but only when the native C++ Newton solver (secular.cpp,
# O(iters*k) per root, ~50ms at k=2000) actually loaded; with the numpy
# bisection fallback (~4s at k=2000) the device takes over much earlier.
# The configured default lives in config.Configuration.secular_device_min_k.
_DEVICE_SECULAR_MIN_K_NO_NATIVE = 1024


def _device_secular_min_k() -> int:
    from ..config import get_configuration

    cfg = get_configuration()
    s = cfg.secular_device_min_k
    auto = s == 0
    if auto:
        import jax

        # measured round 4 (BASELINE.md): the CPU backend's device route
        # loses to the native host solver at every size, so auto disables
        # it there; on TPU the device side is MXU-backed batched math
        s = 4096 if jax.default_backend() == "tpu" else (1 << 62)
    have_native = False
    if cfg.secular_impl == "native":
        try:
            from ..native import bindings

            bindings.get_lib()
            have_native = True
        except Exception:
            pass
    if not have_native:
        # the numpy bisection fallback is ~100x the native Newton solver,
        # so the device takes over much earlier — this overrides the auto
        # host-always resolution on CPU too
        s = min(s, _DEVICE_SECULAR_MIN_K_NO_NATIVE)
    if auto:
        import jax

        backend = jax.default_backend()
        from ..obs import get_logger

        label = "host-always" if s >= (1 << 62) else str(s)
        # once per (backend, threshold) — auto decisions must not be
        # silent (round-2 advisory pattern)
        get_logger("config").warning_once(
            ("secular_device_min_k", backend, s),
            f"secular_device_min_k=0 (auto) resolved to {label} for "
            f"default backend {backend!r}"
            f"{'' if have_native else ' (no native secular solver)'}"
            " — set the knob explicitly to override",
            knob="secular_device_min_k", backend=backend, choice=label)
    return s


def _secular_roots(ds: np.ndarray, zs: np.ndarray, rho: float):
    """All k roots of ``1 + rho * sum z_j^2/(d_j - lam) = 0``.

    ``ds`` ascending, ``zs`` nonzero, ``rho > 0``. Returns (anchor_idx,
    offset): ``lambda_i = ds[anchor_idx[i]] + offset[i]`` with the anchor
    chosen as the nearest pole (LAPACK laed4's shifted representation).
    Vectorized bisection: ~90 iterations of an (k x k) evaluation — monotone,
    unconditionally convergent, and embarrassingly parallel.
    """
    k = ds.shape[0]
    zsq = zs * zs
    # interval ends: (d_i, d_{i+1}), last interval (d_k, d_k + rho*sum z^2)
    upper = np.empty(k)
    upper[:-1] = ds[1:]
    upper[-1] = ds[-1] + rho * zsq.sum()
    gaps = upper - ds

    # choose anchors by the secular value at the midpoint: f(mid) > 0 means
    # the root lies in the left half (anchor at d_i), else right (d_{i+1})
    mid = ds + gaps / 2
    fmid = 1.0 + rho * (zsq[None, :] / (ds[None, :] - mid[:, None])).sum(1)
    anchor = np.where(fmid >= 0, np.arange(k), np.minimum(np.arange(k) + 1, k - 1))
    anchor[-1] = k - 1
    danchor = ds[anchor]
    # bisect offset mu in (lo, hi) relative to the anchor
    lo = np.where(anchor == np.arange(k), 0.0, ds - upper)   # left- vs right-anchored
    hi = np.where(anchor == np.arange(k), gaps, 0.0)
    lo = lo.copy()
    hi = hi.copy()
    # pole differences relative to anchors: delta[i, j] = d_j - d_anchor_i
    delta = ds[None, :] - danchor[:, None]
    for _ in range(90):
        mu = 0.5 * (lo + hi)
        f = 1.0 + rho * (zsq[None, :] / (delta - mu[:, None])).sum(1)
        take_left = f >= 0
        hi = np.where(take_left, mu, hi)
        lo = np.where(take_left, lo, mu)
    mu = 0.5 * (lo + hi)
    return anchor, mu


def _secular_roots_host(ds, zs, rho):
    """Host secular solve: native C++ safeguarded Newton (``native/
    secular.cpp``, the laed4 analog — the reference calls LAPACK laed4 here,
    ``merge.h:590-629``) with transparent fallback to the numpy bisection."""
    from ..config import get_configuration

    if get_configuration().secular_impl == "native":
        # unified degradation policy (health.registry): counted under
        # dlaf_fallback_total{site="secular"}, announced once, raises in
        # strict mode — the ~100x bisection slowdown is never silent
        from ..health.registry import run_with_fallback

        def _native():
            from ..native import bindings

            return bindings.secular_roots(ds, zs, rho)

        return run_with_fallback("secular", _native,
                                 lambda: _secular_roots(ds, zs, rho))
    return _secular_roots(ds, zs, rho)


def _secular_vcols_device(ds, zs, rho, live):
    """Device twin of :func:`_secular_roots` + the Gu-Eisenstat refinement +
    eigenvector-coefficient assembly: returns ``(lam_live, vcols)``. The pole
    differences ``m[i, j] = d_j - lambda_i`` are formed internally in the
    shifted (cancellation-free) representation. All f64; one fused HBM-bound
    program instead of ~90 numpy sweeps.

    ``live`` marks real entries: the caller pads (ds, zs) to a shape bucket
    (padded poles strictly above the root bound, z = 0) so the jit cache is
    keyed by bucket instead of by the data-dependent deflated size k.
    Padded z contribute nothing to the secular function; anchoring a live
    root to a padded pole is still exact (the shifted representation needs
    an ordered reference point, not a pole); only the log-product
    z-refinement must exclude padded ROWS, via ``live``.
    """
    k = ds.shape[0]
    zsq = zs * zs
    upper = jnp.concatenate([ds[1:], (ds[-1] + rho * zsq.sum())[None]])
    gaps = upper - ds
    mid = ds + gaps / 2
    fmid = 1.0 + rho * (zsq[None, :] / (ds[None, :] - mid[:, None])).sum(1)
    idx = jnp.arange(k)
    anchor = jnp.where(fmid >= 0, idx, jnp.minimum(idx + 1, k - 1))
    anchor = anchor.at[-1].set(k - 1)
    danchor = ds[anchor]
    lo = jnp.where(anchor == idx, 0.0, ds - upper)
    hi = jnp.where(anchor == idx, gaps, 0.0)
    delta = ds[None, :] - danchor[:, None]

    def body(_, lohi):
        lo, hi = lohi
        mu = 0.5 * (lo + hi)
        f = 1.0 + rho * (zsq[None, :] / (delta - mu[:, None])).sum(1)
        take_left = f >= 0
        return jnp.where(take_left, lo, mu), jnp.where(take_left, mu, hi)

    # 300 halvings (matching the native solver's iteration cap): roots next
    # to near-deflated poles sit ~1e-28*gap from the anchor and need >90
    # halvings before the offset mu carries any relative accuracy
    lo, hi = lax.fori_loop(0, 300, body, (lo, hi))
    mu = 0.5 * (lo + hi)
    lam_live = danchor + mu
    m = delta - mu[:, None]
    logm = jnp.where(live[:, None], jnp.log(jnp.abs(m)), 0.0)
    dd = ds[None, :] - ds[:, None]
    dd = dd.at[idx, idx].set(1.0)
    logdd = jnp.log(jnp.abs(dd))
    logdd = logdd.at[idx, idx].set(0.0)
    logdd = jnp.where(live[:, None], logdd, 0.0)
    log_zhat2 = logm.sum(0) - logdd.sum(0)
    zhat = jnp.sign(zs) * jnp.exp(0.5 * log_zhat2)
    vcols = zhat[None, :] / m
    vcols = vcols / jnp.linalg.norm(vcols, axis=1, keepdims=True)
    return lam_live, vcols


@functools.lru_cache(maxsize=None)
def _secular_vcols_jit(mesh):
    """Compiled device secular solve. With a mesh, the (kb, kb) bisection
    and refinement run ROW-sharded over all mesh devices (each root's
    bisection is independent; only the log-product column reductions
    cross shards) and the coefficient matrix comes out row-sharded — the
    last (n, n)-class single-device workspace of the sharded merge path."""
    if mesh is None:
        return jax.jit(_secular_vcols_device)
    from jax.sharding import NamedSharding, PartitionSpec

    from ..comm.grid import COL_AXIS, ROW_AXIS

    rows = PartitionSpec((ROW_AXIS, COL_AXIS))
    return jax.jit(_secular_vcols_device,
                   out_shardings=(NamedSharding(mesh, rows),
                                  NamedSharding(mesh, PartitionSpec(
                                      (ROW_AXIS, COL_AXIS), None))))


def _deflation_scan(ds, zs, live, tol):
    """Near-equal-pole deflation scan (reference ``merge.h:443-508``):
    rotate the z weight of pole pairs closer than ``tol`` onto the earlier
    live pole, deflating the later one. Mutates ``zs``/``live`` in place;
    returns the Givens rotations as arrays ``(i, j, c, s)`` in application
    order. Native C++ single pass (``native/deflate.cpp``) with a
    transparent numpy/Python fallback — the scan is sequential (each
    rotation feeds the running anchor's weight into later decisions), so
    the interpreter loop is the fallback, not the product path."""
    from ..config import get_configuration

    if get_configuration().secular_impl == "native":
        try:
            from ..native import bindings

            return bindings.deflate_scan(ds, zs, live, tol)
        except Exception as e:
            from ..health.registry import report_fallback

            report_fallback("deflate", "native_unavailable", exc=e)
    gi, gj, gc, gs = [], [], [], []
    prev = -1
    for j in range(ds.shape[0]):
        if not live[j]:
            continue
        if prev >= 0 and ds[j] - ds[prev] <= tol:
            r = np.hypot(zs[prev], zs[j])
            if r == 0:
                prev = j
                continue
            gi.append(prev)
            gj.append(j)
            gc.append(zs[prev] / r)
            gs.append(zs[j] / r)
            # rotating makes the two poles share d ~ equal; eigenvalue at
            # ds[j] deflates exactly
            zs[prev], zs[j] = r, 0.0
            live[j] = False
        else:
            prev = j
    return (np.asarray(gi, np.int64), np.asarray(gj, np.int64),
            np.asarray(gc, np.float64), np.asarray(gs, np.float64))


def _assemble_qc_impl(vcols, live_b, rows_live, rows_d, cols_d, giv,
                      inv_order, fin, *, n: int):
    """Device-side assembly of the merge's eigenvector-coefficient matrix
    ``qc`` (n, n) from O(n)-sized host control data + the (kb, kb) secular
    output — the TPU analog of the reference's device merge workspaces
    (``merge.h:45-118``, ``kernels.cu``). The host never holds an (n, n)
    array: scatters place the live coefficient columns and the deflated
    unit columns, a ``lax.scan`` undoes the Givens rotations (identity
    padding makes the rotation count a static bucket), and gathers undo the
    pole sort and apply the final eigenvalue ordering.

    Under a column sharding (see :func:`_assemble_qc_jit`) every step here
    is shard-local: the scatters and the Givens row rotations touch each
    column independently, the ``inv_order`` row gather is per-column, and
    only the final ``fin`` column permutation crosses shards."""
    kb = vcols.shape[0]
    w = max(n, kb)
    vm = jnp.where(live_b[:, None] & live_b[None, :], vcols, 0.0)
    u = jnp.zeros((n, w), vcols.dtype)
    # live columns: root i's coefficients scattered to the live poles' rows
    u = u.at[rows_live, :kb].add(vm.T, mode="drop")
    # deflated columns: unit vectors (pad rows point past n -> dropped)
    u = u.at[rows_d, cols_d].add(1.0, mode="drop")

    def rot(uu, p):
        i = p[0].astype(jnp.int32)
        j = p[1].astype(jnp.int32)
        c, s = p[2], p[3]
        ri, rj = uu[i], uu[j]
        uu = uu.at[i].set(c * ri - s * rj)
        uu = uu.at[j].set(s * ri + c * rj)
        return uu, None

    u, _ = lax.scan(rot, u, giv)
    # undo the pole sort (rows), apply the final eigenvalue order (cols) —
    # the reference's permutation-kernel call sites inside the merge
    from ..algorithms.permutations import permute_array

    return permute_array("Col", fin, permute_array("Row", inv_order, u))


def _qc_col_sharding(mesh):
    """THE layout contract of an assembled qc under a mesh: columns sharded
    over all mesh devices, rows replicated — chosen so every internal
    assembly step (scatters, Givens row rotations, row gather) is
    shard-local and only the final column permutation crosses shards."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..comm.grid import COL_AXIS, ROW_AXIS

    return NamedSharding(mesh, PartitionSpec(None, (ROW_AXIS, COL_AXIS)))


def _q_2d_sharding(mesh):
    """Layout of a merge's Q output: 2D block-sharded over the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..comm.grid import COL_AXIS, ROW_AXIS

    return NamedSharding(mesh, PartitionSpec(ROW_AXIS, COL_AXIS))


@functools.lru_cache(maxsize=None)
def _assemble_qc_jit(n: int, mesh):
    """Compiled qc assembly; with a mesh, the (n, n) workspace and result
    follow :func:`_qc_col_sharding`, so no device ever materializes the
    full qc."""
    fn = functools.partial(_assemble_qc_impl, n=n)
    if mesh is None:
        return jax.jit(fn)
    return jax.jit(fn, out_shardings=_qc_col_sharding(mesh))


@functools.lru_cache(maxsize=None)
def _eye_perm_jit(n: int, dtype_name: str, mesh):
    """Decoupled-merge qc: a column-permuted identity, laid out per
    :func:`_qc_col_sharding` under a mesh."""
    def fn(fin):
        return jnp.eye(n, dtype=jnp.dtype(dtype_name))[:, fin]

    if mesh is None:
        return jax.jit(fn)
    return jax.jit(fn, out_shardings=_qc_col_sharding(mesh))


@register_program_cache
@functools.lru_cache(maxsize=None)
def _apply_qc_jit(mesh):
    """Compiled merge gemms ``blkdiag(q1, q2) @ qc`` (jit specializes per
    shape; the slice point is q1's static row count). With a mesh, the
    OUTPUT (the next level's Q) is 2D-sharded (:func:`_q_2d_sharding`) and
    XLA inserts the SUMMA-style collectives. Together with the
    column-sharded qc assembly (:func:`_assemble_qc_jit`) this removes the
    one-device HBM ceiling on the (n, n) merge arrays; the remaining
    single-device term is the deflated secular workspace (kb x kb, bounded
    by the deflation count) — the sharded-Q extension the reference,
    local-only here, does not have.

    The gemms ride ``tb.mm`` so ``f64_gemm="mxu"`` reroutes the D&C
    stage's dominant flops onto the int8/bf16 MXU path like every other
    algorithm's trailing products (raw jnp.matmul kept them on the
    ~342 GF/s emulated-f64 tier regardless of the knob)."""
    def fn(q1, q2, qc):
        # FRESH closure per builder call: jax.jit keyed on a module-level
        # function would survive this lru cache's config-change clearing
        # (jit's trace cache keys on the underlying callable), resurrecting
        # a program traced under the previous f64_gemm route
        return _apply_qc_fn(q1, q2, qc)

    if mesh is None:
        return jax.jit(fn)
    return jax.jit(fn, out_shardings=_q_2d_sharding(mesh))


def _apply_qc_fn(q1, q2, qc):
    """The one merge-apply kernel (shared by the per-merge and the
    vmapped level-batched programs, so the two walks can never drift
    apart and break the bitwise contract)."""
    n1 = q1.shape[0]
    top = tb.mm(q1, qc[:n1, :])
    bot = tb.mm(q2, qc[n1:, :])
    return jnp.concatenate([top, bot], axis=0)


@register_program_cache
@functools.lru_cache(maxsize=None)
def _secular_vcols_batched_jit():
    """vmapped device secular solve for one level batch of same-bucket
    merges (``dc_level_batch=1``): every lane is an independent merge's
    deflated problem, padded to the group's max bucket, so a whole tree
    level's secular work lands in ONE device dispatch instead of one per
    merge. Sharded merges never batch (they keep the per-merge
    :func:`_secular_vcols_jit` with its mesh shardings)."""
    vm = jax.vmap(_secular_vcols_device)

    def fn(*args):
        # trace-time retrace counter (DLAF_PROGRAM_TELEMETRY): each
        # re-bucketing of the level batch retraces this program — the
        # documented compile-cost tail of dc_level_batch, now measurable
        obs.telemetry.count_retrace("tridiag.secular_batched")
        return vm(*args)

    return jax.jit(fn)


@register_program_cache
@functools.lru_cache(maxsize=None)
def _assemble_qc_batched_jit(n: int):
    """vmapped qc assembly over a level group of same-(n, kb, gb) merges."""
    return jax.jit(jax.vmap(functools.partial(_assemble_qc_impl, n=n)))


@register_program_cache
@functools.lru_cache(maxsize=None)
def _apply_qc_batched_jit():
    """vmapped merge gemms over a level group: the batched dot_general is
    the MXU-earning form of many small Q·C products (arXiv:2112.09017).
    Same kernel as the per-merge program (:func:`_apply_qc_fn`; the vmap
    wrapper is a fresh callable per builder call, so jit retraces after a
    config-change cache clear)."""
    vm = jax.vmap(_apply_qc_fn)

    def fn(q1, q2, qc):
        obs.telemetry.count_retrace("tridiag.apply_qc_batched")
        return vm(q1, q2, qc)

    return jax.jit(fn)


def _count_merges(mode: str, n: int = 1) -> None:
    """Per-level merge-dispatch accounting (docs/eigensolver_perf.md):
    ``dlaf_dc_merges_total{mode=batched|serialized}`` counts how many
    merges ran through the level-batched vmapped dispatch vs one-at-a-time
    programs."""
    from .. import obs

    if n and obs.metrics_active():
        obs.counter("dlaf_dc_merges_total", mode=mode).inc(n)


#: Per-level deflation accounting (DLAF_ACCURACY, docs/accuracy.md):
#: while a list is installed here, :func:`_merge_ctl_pre` appends one
#: ``(merge size n, deflated count)`` pair per merge — the heavily
#: data-dependent quantity arXiv:2112.09017's D&C throughput hinges on.
#: Scoped per tree level by :func:`_tridiag_dc` (the only writer of this
#: global; the solver is not re-entrant) and emitted as
#: ``accuracy`` records ``site=tridiag_solver,
#: metric=dc_deflation_fraction`` with the level in the attrs.
_DEFLATION_SINK: Optional[list] = None


def _log_deflation(n: int, deflated: int) -> None:
    if _DEFLATION_SINK is not None:
        _DEFLATION_SINK.append((n, deflated))


@dataclasses.dataclass
class _MergeCtl:
    """Host control state of one Cuppen merge, split in two phases so the
    level-batched driver can interleave the host control scans with the
    device dispatches: :func:`_merge_ctl_pre` (sort + deflation + host
    secular solve / device-secular prep), then — once ``lam_live`` exists
    — :func:`_merge_ctl_fin` (final eigenvalue order + the pole-sort
    undo). All fields are O(n) host arrays or scalars; the O(n^2)
    workspaces stay on device."""

    n1: int
    n2: int
    neg: bool
    decoupled: bool = False
    rho_n: float = 0.0
    order: np.ndarray = None
    ds: np.ndarray = None           # sorted (negated) poles, full n
    k: int = 0
    kb: int = 0                     # secular bucket (>= k, power of two)
    idx_live: np.ndarray = None
    idx_defl: np.ndarray = None
    gi: np.ndarray = None           # deflation Givens rotations
    gj: np.ndarray = None
    gc: np.ndarray = None
    gs: np.ndarray = None
    dsk: np.ndarray = None          # live poles/weights (secular inputs)
    zsk: np.ndarray = None
    dev_secular: bool = False       # secular solve deferred to the device
    vcols: np.ndarray = None        # host secular output (k, k)
    lam_live: np.ndarray = None     # host-mode roots (ready after pre)
    lam: np.ndarray = None          # final ascending eigenvalues
    fin: np.ndarray = None
    inv_order: np.ndarray = None

    @property
    def n(self) -> int:
        return self.n1 + self.n2


def _merge_ctl_pre(lam1, lam2, z, rho_signed, use_device: bool,
                   dev_min_k: int) -> _MergeCtl:
    """Phase 1 of a merge's host control work (reference
    ``merge.h:443-629``): rank-one tear normalization, pole sort,
    deflation scan, and either the host secular solve + Gu-Eisenstat
    refinement (small k) or the device-secular prep (large k — the solve
    itself is dispatched by the caller, per merge or level-batched)."""
    n1, n2 = lam1.shape[0], lam2.shape[0]
    d = np.concatenate([lam1, lam2])
    # rho < 0: rho*z z^T is negative semidefinite, so solve the negated
    # problem -T = diag(-d) + |rho| z z^T (same eigenvectors, negated
    # eigenvalues) — the LAPACK dlaed normalization
    neg = rho_signed < 0
    rho = abs(rho_signed)
    if neg:
        d = -d
    ctl = _MergeCtl(n1=n1, n2=n2, neg=neg)
    znorm2 = float(z @ z)
    if rho * znorm2 <= 1e-300:  # fully decoupled
        lam = -d if neg else d
        fin = np.argsort(lam, kind="stable")
        ctl.decoupled = True
        ctl.lam = lam[fin]
        ctl.fin = fin
        _log_deflation(ctl.n, ctl.n)    # every pole is an eigenvalue
        return ctl
    zn = z / np.sqrt(znorm2)
    ctl.rho_n = rho_n = rho * znorm2
    # sort poles
    order = np.argsort(d, kind="stable")
    ds, zs = d[order].copy(), zn[order].copy()
    ctl.order, ctl.ds = order, ds

    # -- deflation (reference merge.h:443-508) ------------------------------
    dmax = np.abs(ds).max(initial=0.0)
    tol = 8 * _EPS * max(dmax, 1.0)
    # dropping z_j perturbs the matrix by ~rho_n*|z_j|; deflate when that
    # is below eps * ||T|| (LAPACK dlaed2 criterion)
    live = rho_n * np.abs(zs) > 8 * _EPS * max(dmax, rho_n)
    ctl.gi, ctl.gj, ctl.gc, ctl.gs = _deflation_scan(ds, zs, live, tol)
    ctl.idx_live = np.nonzero(live)[0]
    ctl.idx_defl = np.nonzero(~live)[0]
    k = ctl.k = ctl.idx_live.shape[0]
    ctl.kb = 1 << max(0, (k - 1).bit_length())
    _log_deflation(ctl.n, ctl.n - k)
    if k == 0:
        return ctl
    ctl.dsk = dsk = ds[ctl.idx_live]
    ctl.zsk = zsk = zs[ctl.idx_live]
    if use_device and k >= dev_min_k and jax.config.jax_enable_x64:
        ctl.dev_secular = True
        return ctl
    anchor, mu = _secular_roots_host(dsk, zsk, rho_n)
    ctl.lam_live = dsk[anchor] + mu
    # accurate pole-root differences: m[i, j] = d_j - lambda_i
    m = (dsk[None, :] - dsk[anchor][:, None]) - mu[:, None]
    # Gu-Eisenstat z refinement (reference laed4/dlaed3 step)
    logm = np.log(np.abs(m))
    dd = dsk[None, :] - dsk[:, None]
    np.fill_diagonal(dd, 1.0)
    logdd = np.log(np.abs(dd))
    np.fill_diagonal(logdd, 0.0)
    log_zhat2 = logm.sum(0) - logdd.sum(0)
    zhat = np.sign(zsk) * np.exp(0.5 * log_zhat2)
    # eigenvector coefficients: v_i[j] = zhat_j / (d_j - lambda_i)
    vcols = (zhat[None, :] / m)
    vcols /= np.linalg.norm(vcols, axis=1, keepdims=True)
    ctl.vcols = vcols
    return ctl


def _secular_bucket(ctl: _MergeCtl, kb: int):
    """Padded ``(ds_b, zs_b, live_kb)`` device-secular inputs at bucket
    ``kb >= ctl.k``: padded poles sit strictly above the root bound with
    z = 0, so they contribute nothing to the secular function (the
    level-batched driver re-buckets to the group's max kb; the padding
    policy is the same one the per-merge path has always used)."""
    dsk, zsk, k = ctl.dsk, ctl.zsk, ctl.k
    if kb > k:
        span = ctl.rho_n * float((zsk * zsk).sum()) + 1.0
        # scale-aware step: at |d| ~ 1e17 an absolute +1.0 would
        # round away, colliding a padded pole with a live one
        step = max(1.0, 16 * np.spacing(abs(dsk[-1]) + span))
        ds_b = np.concatenate(
            [dsk, dsk[-1] + span + step * np.arange(1.0, kb - k + 1)])
        zs_b = np.concatenate([zsk, np.zeros(kb - k)])
    else:
        ds_b, zs_b = dsk, zsk
    live_kb = np.zeros(kb, dtype=bool)
    live_kb[:k] = True
    return ds_b, zs_b, live_kb


def _merge_ctl_fin(ctl: _MergeCtl, lam_live) -> _MergeCtl:
    """Phase 2 of the host control work: final ascending eigenvalue order
    and the pole-sort undo, from the (host- or device-) solved roots."""
    n, k = ctl.n, ctl.k
    lam = np.empty(n)
    if k == 0:
        lam[:] = ctl.ds
    else:
        lam[:k] = lam_live
        lam[k:] = ctl.ds[ctl.idx_defl]
    if ctl.neg:
        lam = -lam
    fin = np.argsort(lam, kind="stable")
    ctl.lam = lam[fin]
    ctl.fin = fin
    inv_order = np.empty(n, dtype=np.int64)
    inv_order[ctl.order] = np.arange(n)
    ctl.inv_order = inv_order
    return ctl


def _givens_padded(ctl: _MergeCtl, gb: int) -> np.ndarray:
    """(gb, 4) Givens-undo array in application (reverse) order, padded
    with identity rotations (exact no-ops) to the bucket ``gb``."""
    g = ctl.gi.shape[0]
    giv = np.zeros((gb, 4))
    giv[:, 2] = 1.0                     # identity-rotation padding
    # reverse order: the undo applies rotations last-to-first
    giv[:g, 0] = ctl.gi[::-1]
    giv[:g, 1] = ctl.gj[::-1]
    giv[:g, 2] = ctl.gc[::-1]
    giv[:g, 3] = ctl.gs[::-1]
    return giv


def _givens_bucket(ctl: _MergeCtl) -> int:
    """Power-of-two bucket of this merge's deflation-rotation count."""
    g = ctl.gi.shape[0]
    return (1 << max(0, (g - 1).bit_length())) if g else 0


def _assembly_arrays(ctl: _MergeCtl, kb: int):
    """O(n)-sized qc-assembly control arrays at secular bucket ``kb``
    (shapes bucketed so the jit cache is keyed by (n, kb, givens bucket),
    not by data-dependent counts). The Givens-undo array is NOT built
    here — callers pad it once at their target bucket
    (:func:`_givens_padded`; the level-batched driver pads to the group
    max, the per-merge path to :func:`_givens_bucket`)."""
    n, k = ctl.n, ctl.k
    live_b = np.zeros(kb, dtype=bool)
    live_b[:k] = True
    rows_live = np.full(kb, n, dtype=np.int64)
    rows_live[:k] = ctl.idx_live
    nd = n - k
    rows_d = np.full(n, n, dtype=np.int64)
    rows_d[:nd] = ctl.idx_defl
    cols_d = np.full(n, n, dtype=np.int64)
    cols_d[:nd] = k + np.arange(nd)
    return live_b, rows_live, rows_d, cols_d


def _vcols_padded(ctl: _MergeCtl, kb: int) -> np.ndarray:
    """Host secular output zero-padded to bucket ``kb``."""
    vpad = np.zeros((kb, kb), dtype=np.float64)
    if ctl.k:
        vpad[:ctl.k, :ctl.k] = ctl.vcols
    return vpad


def _merge_apply(ctl: _MergeCtl, q1, q2, vcols_dev, use_device: bool,
                 mesh=None):
    """Device (or numpy-twin) tail of one merge: qc assembly + the
    blkdiag(q1, q2) @ qc gemms. Device gemms keep Q device-resident
    across the whole merge tree; only O(n) vectors cross to the host.
    Under a mesh the gemms run sharded (SUMMA via GSPMD)."""
    n1, n = ctl.n1, ctl.n
    dtype = q1.dtype

    def apply_qc(lam, qc_dev=None, qc_host=None):
        if use_device:
            return lam, _apply_qc_jit(mesh)(
                jnp.asarray(q1), jnp.asarray(q2), qc_dev)
        return lam, np.vstack([q1 @ qc_host[:n1, :], q2 @ qc_host[n1:, :]])

    if ctl.decoupled:
        if use_device:
            qc = _eye_perm_jit(n, np.dtype(dtype).name, mesh)(
                jnp.asarray(ctl.fin))
            return apply_qc(ctl.lam, qc_dev=qc)
        return apply_qc(ctl.lam, qc_host=np.eye(n, dtype=dtype)[:, ctl.fin])

    if use_device:
        if vcols_dev is None:
            vcols_dev = jnp.asarray(_vcols_padded(ctl, ctl.kb))
        live_b, rows_live, rows_d, cols_d = _assembly_arrays(ctl, ctl.kb)
        giv = _givens_padded(ctl, _givens_bucket(ctl))
        qc = _assemble_qc_jit(n, mesh)(
            vcols_dev, jnp.asarray(live_b), jnp.asarray(rows_live),
            jnp.asarray(rows_d), jnp.asarray(cols_d), jnp.asarray(giv),
            jnp.asarray(ctl.inv_order), jnp.asarray(ctl.fin))
        return apply_qc(ctl.lam, qc_dev=qc)

    # host assembly (use_device=False twin, kept as the numpy reference)
    k = ctl.k
    u_sorted = np.zeros((n, n), dtype=dtype)
    if k == 0:
        u_sorted[:] = np.eye(n, dtype=dtype)
    else:
        u_live = np.zeros((n, k), dtype=dtype)
        u_live[ctl.idx_live, :] = ctl.vcols.T.astype(dtype)
        u_sorted[:, :k] = u_live
        for t, j in enumerate(ctl.idx_defl):
            u_sorted[j, k + t] = 1.0
    # undo the Givens rotations (rows, reverse order)
    for i, j, c, s in zip(ctl.gi[::-1], ctl.gj[::-1], ctl.gc[::-1],
                          ctl.gs[::-1]):
        ri = u_sorted[i].copy()
        rj = u_sorted[j].copy()
        u_sorted[i] = c * ri - s * rj
        u_sorted[j] = s * ri + c * rj
    qc = u_sorted[ctl.inv_order][:, ctl.fin]
    return apply_qc(ctl.lam, qc_host=qc)


def _merge(lam1, q1, lam2, q2, rho_signed, use_device: bool, mesh=None):
    """One Cuppen merge (reference ``merge.h:790-887``), serialized.

    Division of labor (device path): O(n) control work (sort, deflation
    scan, liveness) on host; the secular solve on host (small k) or device
    (large k, bucketed); and ALL O(n^2) workspace assembly on device
    (:func:`_assemble_qc_impl`) — host memory stays O(n + k^2_small) per
    merge, against the round-1 review's O(n^2) host ``u_sorted``/``qc``.
    With ``mesh``, the merge gemms and their Q outputs are 2D-sharded."""
    # rank-one coupling: z from the edge rows of the subproblem eigenvectors
    z = np.concatenate([np.asarray(q1[-1, :]), np.asarray(q2[0, :])])
    ctl = _merge_ctl_pre(lam1, lam2, z, rho_signed, use_device,
                         _device_secular_min_k())
    _count_merges("serialized")
    vcols_dev = None
    if ctl.decoupled:
        return _merge_apply(ctl, q1, q2, None, use_device, mesh)
    if ctl.dev_secular:
        ds_b, zs_b, live_kb = _secular_bucket(ctl, ctl.kb)
        lam_j, vcols_dev = _secular_vcols_jit(mesh)(
            jnp.asarray(ds_b), jnp.asarray(zs_b), jnp.float64(ctl.rho_n),
            jnp.asarray(live_kb))
        # only the O(kb) eigenvalues cross to the host; the (kb, kb)
        # coefficient matrix stays device-resident (row-sharded over
        # the mesh when one is given)
        lam_live = np.asarray(lam_j)[:ctl.k]
    else:
        lam_live = ctl.lam_live
    _merge_ctl_fin(ctl, lam_live)
    return _merge_apply(ctl, q1, q2, vcols_dev, use_device, mesh)


# ---------------------------------------------------------------------------
# Level-batched merge tree (dc_level_batch=1, docs/eigensolver_perf.md)
# ---------------------------------------------------------------------------

class _TreeNode:
    """One node of the D&C split tree (host bookkeeping only)."""

    __slots__ = ("off", "n", "rho", "left", "right", "height")

    def __init__(self, off, n, rho=None, left=None, right=None, height=0):
        self.off, self.n, self.rho = off, n, rho
        self.left, self.right, self.height = left, right, height


def _merge_schedule(d, e, nb: int):
    """Host twin of the recursive splitting (same split rule, same
    pre-order d adjustments — leaf subproblems are bitwise the
    recursion's): returns ``(d_adj, leaves, levels, root)`` with
    ``levels[h]`` = all merge nodes at height ``h`` above the leaves.
    Merges within one level have disjoint index ranges and both children
    at strictly lower heights, so a whole level can run as one batch."""
    d_adj = d.copy()
    leaves: list = []
    levels: dict = {}

    def build(off, n):
        if n <= max(nb, 2):
            node = _TreeNode(off, n)
            leaves.append(node)
            return node
        # split at a tile boundary near the middle (reference impl.h:66-80
        # splits at every tile boundary; binary recursion reaches the same
        # leaves)
        m = (n // 2 // nb) * nb
        if m == 0 or m == n:
            m = n // 2
        rho = e[off + m - 1]
        d_adj[off + m - 1] -= rho
        d_adj[off + m] -= rho
        left = build(off, m)
        right = build(off + m, n - m)
        node = _TreeNode(off, n, rho, left, right,
                         1 + max(left.height, right.height))
        levels.setdefault(node.height, []).append(node)
        return node

    root = build(0, d.shape[0])
    return d_adj, leaves, levels, root


def _run_group(group, res, zmap, dev_min_k: int):
    """One same-(n1, n2) level group: host control scan for every merge
    (the scan overlaps the previously dispatched device programs — jax
    dispatch is async, so the device grinds group g's assembly gemms
    while the host runs group g+1's deflation/secular work), ONE vmapped
    secular dispatch for the device-secular members (padded to the
    group's max bucket), then ONE vmapped assembly + apply dispatch."""
    ctls = [
        _merge_ctl_pre(res[node.left][0], res[node.right][0], zmap[node],
                       node.rho, True, dev_min_k)
        for node in group
    ]
    # batched device secular at the group's shared max bucket
    dev = [(i, c) for i, c in enumerate(ctls)
           if not c.decoupled and c.dev_secular]
    vdev = {}
    if dev:
        kb_g = max(c.kb for _, c in dev)
        buckets = [_secular_bucket(c, kb_g) for _, c in dev]
        lam_j, vcols_j = _secular_vcols_batched_jit()(
            jnp.asarray(np.stack([b[0] for b in buckets])),
            jnp.asarray(np.stack([b[1] for b in buckets])),
            jnp.asarray(np.array([c.rho_n for _, c in dev])),
            jnp.asarray(np.stack([b[2] for b in buckets])))
        lam_h = np.asarray(lam_j)           # one sync for the whole group
        for lane, (i, c) in enumerate(dev):
            c.kb = kb_g                     # re-bucketed to the group max
            vdev[i] = vcols_j[lane]
            _merge_ctl_fin(c, lam_h[lane][:c.k])
    for c in ctls:
        if not c.decoupled and not c.dev_secular:
            _merge_ctl_fin(c, c.lam_live)
    # decoupled merges have no assembly to batch: per-merge dispatch
    asm = [(i, c) for i, c in enumerate(ctls) if not c.decoupled]
    for i, c in enumerate(ctls):
        if c.decoupled:
            node = group[i]
            res[node] = _merge_apply(c, res[node.left][1],
                                     res[node.right][1], None, True, None)
    _count_merges("batched", len(asm))
    _count_merges("serialized", len(ctls) - len(asm))
    if not asm:
        return
    n = group[0].n
    kb_g = max(c.kb for _, c in asm)
    arrs = [_assembly_arrays(c, kb_g) for _, c in asm]
    gb_g = max(_givens_bucket(c) for _, c in asm)
    vcols_stack = jnp.stack(
        [vdev[i] if i in vdev else jnp.asarray(_vcols_padded(c, kb_g))
         for i, c in asm])
    qc = _assemble_qc_batched_jit(n)(
        vcols_stack,
        jnp.asarray(np.stack([a[0] for a in arrs])),
        jnp.asarray(np.stack([a[1] for a in arrs])),
        jnp.asarray(np.stack([a[2] for a in arrs])),
        jnp.asarray(np.stack([a[3] for a in arrs])),
        jnp.asarray(np.stack([_givens_padded(c, gb_g) for _, c in asm])),
        jnp.asarray(np.stack([c.inv_order for _, c in asm])),
        jnp.asarray(np.stack([c.fin for _, c in asm])))
    qout = _apply_qc_batched_jit()(
        jnp.stack([res[group[i].left][1] for i, _ in asm]),
        jnp.stack([res[group[i].right][1] for i, _ in asm]),
        qc)
    for lane, (i, c) in enumerate(asm):
        res[group[i]] = (c.lam, qout[lane])


def _run_level(merges, res, use_device: bool, mesh, level_batch: bool):
    """Execute one tree level. Sharded merges (mesh given, n >=
    _SHARD_MERGE_MIN_N) and sub-2-member groups stay on the serialized
    per-merge path; everything else batches by (n1, n2) shape."""
    serial, groups = [], {}
    for node in merges:
        eff_mesh = mesh if (mesh is not None
                            and node.n >= _SHARD_MERGE_MIN_N) else None
        if not level_batch or not use_device or eff_mesh is not None:
            serial.append((node, eff_mesh))
        else:
            groups.setdefault((node.left.n, node.right.n), []).append(node)
    # singleton groups run serialized: a one-lane vmapped program would
    # only duplicate the per-merge jit cache entries
    for key in [key for key, g in groups.items() if len(g) < 2]:
        serial.extend((node, None) for node in groups.pop(key))
    for node, eff_mesh in serial:
        res[node] = _merge(res[node.left][0], res[node.left][1],
                           res[node.right][0], res[node.right][1],
                           node.rho, use_device, mesh=eff_mesh)
    if groups:
        batch_nodes = [node for g in groups.values() for node in g]
        # ONE host sync pulls every batched merge's rank-one coupling rows
        # (vs two device round trips per merge on the serialized walk)
        edges = jax.device_get(
            [(res[node.left][1][-1, :], res[node.right][1][0, :])
             for node in batch_nodes])
        zmap = {node: np.concatenate([e1, e2])
                for node, (e1, e2) in zip(batch_nodes, edges)}
        dev_min_k = _device_secular_min_k()
        for group in groups.values():
            _run_group(group, res, zmap, dev_min_k)
    # children are dead once the level completes: free their Q storage
    for node in merges:
        del res[node.left], res[node.right]


def _tridiag_dc(d, e, nb: int, use_device: bool, mesh, level_batch: bool):
    """Iterative bottom-up merge-tree driver (level order). With
    ``level_batch`` (and ``use_device``) same-shape merges of one level
    run as single vmapped dispatches; otherwise each merge runs the
    serialized :func:`_merge` — same per-merge math in either walk (the
    merges of a level are independent, so order cannot change results).

    Under ``DLAF_ACCURACY`` != "0" each level additionally emits one
    ``accuracy`` record with its deflation fraction (deflated poles /
    merged poles — the data-dependent work reduction every D&C
    throughput number implicitly depends on; docs/accuracy.md)."""
    global _DEFLATION_SINK
    from ..obs import accuracy

    collect = accuracy.enabled()
    n_total = d.shape[0]
    d_adj, leaves, levels, root = _merge_schedule(d, e, nb)
    res = {}
    for leaf in leaves:
        lam, q = stedc(d_adj[leaf.off: leaf.off + leaf.n],
                       e[leaf.off: leaf.off + leaf.n - 1])
        res[leaf] = (lam, jnp.asarray(q) if use_device else q)
    for h in sorted(levels):
        if collect:
            _DEFLATION_SINK = sink = []
        try:
            _run_level(levels[h], res, use_device, mesh, level_batch)
        finally:
            _DEFLATION_SINK = None
        if collect and sink:
            merged = sum(m for m, _ in sink)
            deflated = sum(k for _, k in sink)
            accuracy.emit(
                "tridiag_solver", "dc_deflation_fraction",
                deflated / merged if merged else 0.0, n=n_total, nb=nb,
                c=None, dtype=np.float64,
                attrs={"level": h, "merges": len(sink),
                       "merged_poles": merged, "deflated_poles": deflated})
    return res[root]


def tridiag_solver(d: np.ndarray, e: np.ndarray, nb: int,
                   use_device: bool = True, mesh=None):
    """Eigendecomposition of the real symmetric tridiagonal (d, e): returns
    ``(eigenvalues, eigenvectors)`` ascending (reference
    ``eigensolver::tridiagSolver``).

    With ``use_device=True`` the eigenvector matrix is a DEVICE-RESIDENT
    (immutable) ``jax.Array`` — Q never round-trips to the host across the
    merge tree; use ``np.asarray`` for a host copy. ``use_device=False``
    returns plain numpy arrays.

    ``mesh`` (the grid's 2D ``jax.sharding.Mesh`` with ('row', 'col')
    axes, i.e. ``grid.mesh``): shard the merge gemms, the qc workspaces,
    and the eigenvector matrix over the mesh — beyond the local-only
    reference, and the scaling path for eigenvector matrices past one
    device's HBM (the returned Q is 2D-sharded; the single-device
    remainder is the deflated secular workspace, bounded by deflation).

    Under ``dc_level_batch=1`` (auto: TPU) all same-shape merges of one
    tree level run as single vmapped device dispatches — the secular
    solves, qc assemblies, and Q·C gemms of a level become one batched
    program each instead of one dispatch per merge, and the host control
    scans overlap the in-flight device work (docs/eigensolver_perf.md).
    Sharded merges (past ``_SHARD_MERGE_MIN_N`` under a mesh) always run
    per merge."""
    if mesh is not None:
        from ..comm.grid import COL_AXIS, ROW_AXIS
        from ..common.asserts import dlaf_assert

        dlaf_assert(use_device,
                    "tridiag_solver: mesh requires use_device=True (the "
                    "numpy twin has no sharded form)")
        dlaf_assert(tuple(mesh.axis_names) == (ROW_AXIS, COL_AXIS),
                    f"tridiag_solver: mesh axes {mesh.axis_names} must be "
                    f"({ROW_AXIS!r}, {COL_AXIS!r}) — pass grid.mesh")
    d = np.asarray(d, dtype=np.float64)
    e = np.asarray(e, dtype=np.float64)
    n = d.shape[0]
    if n == 0:
        return d, (jnp.zeros((0, 0)) if use_device else np.zeros((0, 0)))
    from .. import obs
    from ..config import resolved_dc_level_batch
    from ..types import total_ops

    level_batch = resolved_dc_level_batch()
    # merge-gemm flop model: sum over levels of 2^l * (n/2^l)^3 muls+adds
    # -> (4/3) n^3 (deflation only reduces it; docs/eigensolver_perf.md)
    span = obs.entry_span("tridiag_solver", lambda: dict(
        flops=total_ops(np.dtype(np.float64), 2 * n**3 / 3, 2 * n**3 / 3),
        n=n, nb=nb, dc_level_batch=int(level_batch),
        use_device=int(use_device), sharded=int(mesh is not None)))
    with span:
        return _tridiag_dc(d, e, nb, use_device, mesh, level_batch)
