"""Back-transformations: tridiag -> band -> full eigenvectors.

TPU-native counterpart of the reference's two back-transformation stages:

* ``bt_band_to_tridiag`` (``impl.h:1-938``): apply the bulge-chasing
  Householder vectors to the eigenvector matrix. The reference re-tiles the
  HH storage into cache-friendly b x b groups; here the uniform
  (n_sweeps, n_steps, b) layout produced by the chase makes one sweep = ONE
  batched segment update, and the whole stage is a ``lax.scan`` over sweeps
  (reverse order) — static shapes, device-resident, no host round trips.

* ``bt_reduction_to_band`` (``impl.h:82-373``): apply the panel reflector
  blocks in reverse order, C <- (I - V T V^H) C per panel — two gemms + one
  small T solve per panel, trace-time unrolled.

Both consume the storage contracts of :mod:`.band_to_tridiag` and
:mod:`.reduction_to_band` directly, and both have local AND distributed
variants matching the reference (``bt_reduction_to_band/api.h:18-23``,
``bt_band_to_tridiag/api.h:21-22``):

* distributed ``bt_reduction_to_band``: per panel (reverse order) the V
  column is gathered along the mesh exactly like the forward reduction,
  T is formed redundantly, W2 = (VT)^H C is a partial einsum psum-reduced
  over the row axis, and C -= V W2 is a local update — the reference's
  trmmPanel/gemmUpdateW2/gemmTrailingMatrix trio as three einsums.
* distributed ``bt_band_to_tridiag``: the chase reflectors mix ROWS only
  and every eigenvector column is independent, so the natural TPU layout
  change is one ``all_to_all`` along the row axis converting the
  block-cyclic row sharding into a column split (each device gets ALL rows
  for 1/P of its column group's columns), the whole sweep scan runs
  locally with zero further communication, and a second ``all_to_all``
  restores the block-cyclic layout. The reference instead pipelines per-
  tile sends of HH groups (``impl.h:1-938``); on ICI the two transposes
  are cheaper than n_sweeps round trips.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from .. import obs
from ..tile_ops import blas as tb
from ..config import register_program_cache
from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..common.asserts import dlaf_assert
from ..matrix.matrix import Matrix
from ..matrix import memory
from ..matrix.panel import (DistContext, gather_sub_panel,
                            gather_sub_panel_dyn, pad_sub_panel_to_tiles,
                            tiles_of_rolled, uniform_slot_start)
from ..matrix.tiling import (_axis_perm_inv, global_to_tiles, storage_tile_grid,
                             tiles_to_global)
from ..tile_ops.lapack import larft
from ..types import ceil_div, telescope_windows
from .band_to_tridiag import TridiagResult
from .reduction_to_band import BandReduction


@register_program_cache
@functools.partial(jax.jit, static_argnames=("b", "n", "group"))
def _bt_b2t_blocked(v_all, tau_all, e, *, b: int, n: int, group: int):
    """E <- Q E via blocked compact-WY groups — the MXU form of the
    reference's cache-friendly b x b HH re-tiling (``bt_band_to_tridiag/
    impl.h``: larft + trmm/gemm per group, vs. our sweep-at-a-time scan's
    rank-1 row updates).

    ``group`` (= G <= b) consecutive sweeps' reflectors at one chase step
    level form a (b+G-1) x G staircase V (column j = sweep s0+j's reflector
    at row offset j; v[0]=1 heads land on the staircase diagonal). Validity
    of the reordering: reflector (s, t) overlaps (s+k, t-1) for k >= 1
    (1..k shared rows) so a lower level containing HIGHER sweeps must be
    applied first — and (s, t-1) is row-disjoint from (s+k, t), so applying
    whole levels ascending preserves the required "sweep s+1 fully before
    sweep s" order. Cross-level pairs separated by >= 2 steps are disjoint
    whenever G <= b+1 (enforced). Each level is then T = larft(V) and two
    tall gemms instead of G separate rank-1 updates.
    """
    dlaf_assert(group <= b + 1, "bt_b2t blocked: group must be <= band+1")
    n_sweeps, n_steps, _ = v_all.shape
    m = e.shape[1]
    G = group
    nblk = ceil_div(n_sweeps, G)
    S = nblk * G
    v_all = jnp.pad(v_all, ((0, S - n_sweeps), (0, 0), (0, 0)))
    tau_all = jnp.pad(tau_all, ((0, S - n_sweeps), (0, 0)))
    L = b + G - 1
    rows = S + n_steps * b + b
    e_pad = jnp.pad(e, ((0, rows - n), (0, 0)))

    # iteration sequence in application order: sweep blocks descending,
    # step levels ascending within a block
    v_seq = v_all.reshape(nblk, G, n_steps, b)[::-1].transpose(0, 2, 1, 3) \
        .reshape(nblk * n_steps, G, b)
    tau_seq = tau_all.reshape(nblk, G, n_steps)[::-1].transpose(0, 2, 1) \
        .reshape(nblk * n_steps, G)
    blk_idx = jnp.repeat(jnp.arange(nblk - 1, -1, -1), n_steps)
    t_idx = jnp.tile(jnp.arange(n_steps), nblk)
    base_seq = blk_idx * G + 1 + t_idx * b
    col_off = jnp.arange(G)

    def body(e_pad, xs):
        vcols, taus, base = xs
        stair = jax.vmap(
            lambda vj, j: lax.dynamic_update_slice(
                jnp.zeros((L,), vcols.dtype), vj, (j,)))(vcols, col_off).T
        t_mat = larft(stair, jnp.conj(taus))
        seg = lax.dynamic_slice(e_pad, (base, 0), (L, m))
        w = t_mat @ tb.mm(jnp.conj(stair).T, seg)
        seg = seg - tb.mm(stair, w)
        return lax.dynamic_update_slice(e_pad, seg, (base, 0)), None

    e_pad, _ = lax.scan(body, e_pad, (v_seq, tau_seq, base_seq))
    return e_pad[:n]


@register_program_cache
@functools.partial(jax.jit, static_argnames=("b", "n"))
def _bt_b2t_scan(v_all, tau_all, e, *, b: int, n: int):
    """E <- Q E with Q = prod over reflectors H^H in reverse sweep order."""
    n_sweeps, n_steps, _ = v_all.shape
    m = e.shape[1]
    seg_len = n_steps * b
    pad = seg_len + 1
    e_pad = jnp.pad(e, ((0, pad), (0, 0)))

    def body(e_pad, xs):
        s, v_s, tau_s = xs
        start = s + 1
        seg = lax.dynamic_slice(e_pad, (start, 0), (seg_len, m))
        seg = seg.reshape(n_steps, b, m)
        w = tb.contract("tb,tbm->tm", jnp.conj(v_s), seg)
        seg = seg - jnp.conj(tau_s)[:, None, None] * v_s[..., None] * w[:, None, :]
        e_pad = lax.dynamic_update_slice(e_pad, seg.reshape(seg_len, m), (start, 0))
        return e_pad, None

    xs = (jnp.arange(n_sweeps - 1, -1, -1),
          v_all[::-1], tau_all[::-1])
    e_pad, _ = lax.scan(body, e_pad, xs)
    return e_pad[:n]


def _bt_b2t_params():
    """(impl, group) from config: how to apply the chase reflectors."""
    from ..config import get_configuration

    cfg = get_configuration()
    dlaf_assert(cfg.bt_b2t_impl in ("blocked", "sweeps"),
                f"bt_b2t_impl must be 'blocked' or 'sweeps', got {cfg.bt_b2t_impl!r}")
    return cfg.bt_b2t_impl, cfg.bt_b2t_group


def _effective_group(b: int, n_sweeps: int, group: int) -> int:
    """Effective compact-WY group size: 0 means auto — band size on MXU
    hardware (big-gemm shaped), min(band, 64) on CPU hosts where the extra
    (band+G)/band flops outweigh gemm width (measured: G=64 fastest at
    band=256 on one core). Values are clamped to [1, min(band+1, n_sweeps)]
    (the disjointness bound of the level reordering; see _bt_b2t_blocked)."""
    if group <= 0:
        from ..tpu_info import default_device
        from ..types import Device

        try:
            on_cpu = default_device() == Device.CPU
        except Exception:
            on_cpu = True
        group = min(b, 64) if on_cpu else b
    return max(1, min(group, b + 1, n_sweeps))


def _apply_chase_reflectors(v_all, tau_all, e, *, b: int, n: int,
                            impl: str, group: int):
    if impl == "blocked":
        g = _effective_group(b, int(v_all.shape[0]), group)
        return _bt_b2t_blocked(v_all, tau_all, e, b=b, n=n, group=g)
    return _bt_b2t_scan(v_all, tau_all, e, b=b, n=n)


def _build_dist_bt_b2t(dist, mesh, *, b: int, cplx: bool, n_sweeps: int,
                       impl: str = "blocked", group: int = 0):
    """Distributed chase back-transform: two layout transposes around the
    purely local sweep scan (see module docstring)."""
    n = dist.size.row
    nb = dist.block_size.row
    Pr = dist.grid_size.row
    Sr, _, ltr, ltc = storage_tile_grid(dist)
    ntr = dist.nr_tiles.row
    chunk = ceil_div(ltc, Pr) if ltc else 0
    ltc_pad = chunk * Pr

    # static permutations: a2a slot (p*ltr + l) <-> global row tile g
    # (global->slot map shared with tiling's storage order)
    row_order = [0] * Sr
    slots = _axis_perm_inv(ntr, Pr, dist.source_rank.row, ltr)
    for g, slot in enumerate(slots):
        row_order[g] = slot
    used = set(slots)
    pads = [s for s in range(Sr) if s not in used]
    for i, s in enumerate(pads):
        row_order[ntr + i] = s
    inv_order = [0] * Sr
    for pos, slot in enumerate(row_order):
        inv_order[slot] = pos
    row_order = jnp.array(row_order, dtype=jnp.int32)
    inv_order = jnp.array(inv_order, dtype=jnp.int32)

    def run(v_all, tau_all, phase, lt):
        x = jnp.pad(lt, ((0, 0), (0, ltc_pad - ltc), (0, 0), (0, 0)))
        # block-cyclic rows -> full rows x 1/P of my column group's columns
        x = cc.all_to_all(x, ROW_AXIS, split_axis=1, concat_axis=0)
        x = x[row_order]                              # global row-tile order
        e = x.transpose(0, 2, 1, 3).reshape(Sr * nb, chunk * nb)[:n]
        if cplx:
            e = e * phase[:, None]
        if n_sweeps:
            e = _apply_chase_reflectors(v_all, tau_all, e, b=b, n=n,
                                        impl=impl, group=group)
        e = jnp.pad(e, ((0, Sr * nb - n), (0, 0)))
        x = e.reshape(Sr, nb, chunk, nb).transpose(0, 2, 1, 3)
        x = x[inv_order]
        x = cc.all_to_all(x, ROW_AXIS, split_axis=0, concat_axis=1)
        return x[:, :ltc]

    return shard_map(run, mesh=mesh,
                     in_specs=(P(), P(), P(), P(ROW_AXIS, COL_AXIS)),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


@register_program_cache
@functools.lru_cache(maxsize=32)
def _dist_bt_b2t_cached(dist, mesh, b, cplx, n_sweeps, impl, group):
    return jax.jit(_build_dist_bt_b2t(dist, mesh, b=b, cplx=cplx,
                                      n_sweeps=n_sweeps, impl=impl,
                                      group=group))


def _bt_b2t_local_array(tri: TridiagResult, e) -> jax.Array:
    n = tri.d.shape[0]
    cplx = np.issubdtype(tri.v.dtype, np.complexfloating)
    e = memory.as_device(e)
    if cplx:
        e = e.astype(tri.v.dtype) * memory.as_device(tri.phase)[:, None]
    if tri.v.shape[0] == 0:
        return e
    impl, group = _bt_b2t_params()
    return _apply_chase_reflectors(memory.as_device(tri.v),
                                   memory.as_device(tri.tau),
                                   e, b=tri.band, n=n, impl=impl, group=group)


def _bt_b2t_entry_span(tri: TridiagResult, m: int, impl: str, group: int,
                       grid: str):
    """Entry span: chase back-transform flop model n^2*m muls + n^2*m
    adds (one rank-1 segment update per reflector;
    docs/eigensolver_perf.md)."""
    from .. import obs
    from ..types import total_ops

    n = tri.d.shape[0]
    dt = np.dtype(tri.v.dtype)
    return obs.entry_span("bt_band_to_tridiag", lambda: dict(
        flops=total_ops(dt, n**2 * m, n**2 * m), n=n, m=m, band=tri.band,
        dtype=dt.name, impl=impl, group=group, grid=grid))


def bt_band_to_tridiag(tri: TridiagResult, evecs):
    """Eigenvectors of the BAND matrix from eigenvectors of the tridiagonal:
    apply the complex phases (see band_to_tridiag), then the chase reflectors
    in reverse sweep order.

    ``evecs`` may be an array (local; returns an array) or a
    :class:`~dlaf_tpu.matrix.matrix.Matrix` (local or distributed; returns a
    Matrix — reference distributed overload ``bt_band_to_tridiag/api.h:21-22``).
    """
    impl_l, group_l = _bt_b2t_params()
    # span attr carries the RESOLVED group (same meaning as the
    # distributed span below, where it keys the compiled-program cache)
    group_l = _effective_group(tri.band, int(tri.v.shape[0]), group_l) \
        if impl_l == "blocked" else 0
    if not isinstance(evecs, Matrix):
        m = evecs.shape[1] if getattr(evecs, "ndim", 2) > 1 else 1
        with _bt_b2t_entry_span(tri, m, impl_l, group_l, "1x1"):
            return _bt_b2t_local_array(tri, evecs)
    if evecs.grid is None or evecs.grid.num_devices == 1:
        with _bt_b2t_entry_span(tri, evecs.size.col, impl_l, group_l, "1x1"):
            out = _bt_b2t_local_array(tri,
                                      tiles_to_global(evecs.storage,
                                                      evecs.dist))
        return Matrix(evecs.dist, global_to_tiles(out, evecs.dist), evecs.grid)
    dlaf_assert(evecs.size.row == tri.d.shape[0],
                "bt_band_to_tridiag: eigenvector rows != n")
    dlaf_assert(evecs.block_size.row == evecs.block_size.col,
                "bt_band_to_tridiag: square blocks only (distributed)")
    cplx = bool(np.issubdtype(tri.v.dtype, np.complexfloating))
    storage = evecs.storage
    if cplx and not np.issubdtype(storage.dtype, np.complexfloating):
        storage = storage.astype(tri.v.dtype)
    # normalized cache key = the resolved (impl_l, group_l) from entry:
    # group is pre-clamped and irrelevant for "sweeps", so equivalent
    # configurations share one compiled program — and the span attrs
    # above carry exactly the values that key the cache
    fn = _dist_bt_b2t_cached(evecs.dist, evecs.grid.mesh, tri.band, cplx,
                             int(tri.v.shape[0]), impl_l, group_l)
    with _bt_b2t_entry_span(
            tri, evecs.size.col, impl_l, group_l,
            f"{evecs.dist.grid_size.row}x{evecs.dist.grid_size.col}"):
        out = fn(memory.as_device(tri.v), memory.as_device(tri.tau),
                 memory.as_device(tri.phase), storage)
    return Matrix(evecs.dist, out, evecs.grid)


@register_program_cache
@functools.partial(jax.jit, static_argnames=("nb", "la", "route"))
def _bt_r2b_local(a_v, taus, e, *, nb: int, la: bool = False,
                  route: tuple = ()):
    """C <- (I - V T V^H) C per reflector block, reverse order.

    ``la`` (``bt_lookahead=1``, docs/eigensolver_perf.md): the next
    block's tril/larft T-factor chain reads only the CONSTANT (a_v, taus)
    storage — never the updated ``e`` — so it is emitted BEFORE the
    current block's bulk trmm+gemm application, freeing XLA's scheduler
    to hide the latency-bound chain under the MXU bulk (the PR-2
    look-ahead treatment; same ops either way, bitwise identical)."""
    n = a_v.shape[0]
    nt = ceil_div(n, nb) if n else 0
    ks = [k for k in range(nt - 2, -1, -1) if n - (k + 1) * nb > 0]

    def chain(k):
        k1 = (k + 1) * nb
        m_p = n - k1
        vf = a_v[k1:, k * nb: k * nb + nb]
        v = jnp.tril(vf, -1) + jnp.eye(m_p, nb, dtype=a_v.dtype)
        return k1, v, larft(v, taus[k])

    if la:
        pend = chain(ks[0]) if ks else None
        for i in range(len(ks)):
            k1, v, t = pend
            # emit block i+1's T chain ahead of block i's bulk application
            pend = chain(ks[i + 1]) if i + 1 < len(ks) else None
            w = t @ tb.mm(jnp.conj(v).T, e[k1:])
            e = e.at[k1:].add(-tb.mm(v, w))
        return e
    for k in ks:
        k1, v, t = chain(k)
        w = t @ tb.mm(jnp.conj(v).T, e[k1:])
        e = e.at[k1:].add(-tb.mm(v, w))
    return e


def _build_dist_bt_r2b(dist_a, dist_c, mesh, band, la: bool = False):
    """Distributed reflector-block back-transform C <- (I - V T V^H) C,
    panels in reverse order (reference ``bt_reduction_to_band/impl.h:82-373``:
    trmmPanel W=VT, gemmUpdateW2 W2=W^H C, gemmTrailingMatrix C-=V W2).

    ``band`` <= block size (must divide it): panel p is the width-band slice
    of V at element columns [p*band, (p+1)*band), acting on C rows >=
    (p+1)*band — static sub-tile offsets, element-level masks, same scheme
    as the generalized forward reduction (beyond-reference: the reference's
    distributed back-transform exists only for band == block size).

    ``la`` (``bt_lookahead=1``): panel p+1's whole chain — the V
    sub-panel gather (one COL bcast + one ROW all_gather), larft, and the
    C-side masks — reads only the CONSTANT (lt_a, taus), so it is emitted
    BEFORE panel p's bulk W2/update contractions; XLA's async collective
    start/done pairs can then run the ICI transfer and the latency-bound
    T factor while the MXU grinds the bulk (the PR-4 comm look-ahead
    treatment, docs/comm_overlap.md). Hoisted chains count under
    ``dlaf_comm_overlapped_total{algo="bt_r2b_dist"}``. Bitwise identical
    either way — a pure emission reorder."""
    nt = dist_a.nr_tiles.row
    nb = dist_a.block_size.row
    n = dist_a.size.row
    b = band
    npan = ceil_div(n, b) - 1 if n else 0

    def run(lt_a, taus, lt_c):
        ctx_a = DistContext(dist_a)
        ctx_c = DistContext(dist_c)
        arange_nb = jnp.arange(nb)

        def chain(p):
            """Panel p's hoistable prefix (constant-storage reads only);
            None when this step is a no-op on every rank (trace-time)."""
            bdy = (p + 1) * b
            # -- gather the full V sub-panel (element rows >= bdy) -------
            got = gather_sub_panel(ctx_a, lt_a, pb=p * b, b=b, n=n)
            if got is None:
                return None
            vfull, _, tr0, ro, _, _ = got  # A-side masks unused: the
            # C-side masks below are recomputed from ctx_c
            m_p = (nt - tr0) * nb - ro
            v = jnp.tril(vfull, -1) + jnp.eye(m_p, b, dtype=vfull.dtype)
            t = larft(v, taus[p])
            vt = pad_sub_panel_to_tiles(ctx_a, v, tr0=tr0, ro=ro)
            luc = ctx_c.row_start(tr0)
            nrows_c = ctx_c.ltr - luc
            if nrows_c <= 0:
                return None
            g_rows_c = ctx_c.g_rows(luc, nrows_c)
            g_erows_c = g_rows_c[:, None] * nb + arange_nb[None, :]
            rv_c_e = (g_erows_c >= bdy) & (g_erows_c < n)
            sel = jnp.clip(g_rows_c - tr0, 0, nt - tr0 - 1)
            v_my = jnp.where(rv_c_e[:, :, None], vt[sel],
                             jnp.zeros((nrows_c, nb, b),
                                       dtype=vfull.dtype))
            return luc, t, v_my

        def update(ch, lt_c):
            """Panel p's bulk: W2 = T (V^H C) psum'd over 'row', then
            C -= V W2 — the only reads of the updated C."""
            luc, t, v_my = ch
            cpart = lt_c[luc:]
            w2 = tb.contract("rab,rcad->cbd", jnp.conj(v_my), cpart)
            w2 = cc.all_reduce(w2, ROW_AXIS)     # (ltc_c, b, nb_c) = V^H C
            w2 = tb.contract("xb,cbd->cxd", t, w2)
            upd = tb.contract("rab,cbd->rcad", v_my, w2)
            return lt_c.at[luc:].add(-upd)

        # uniform per-step phase scopes (`bt_r2b.step<p>.<phase>`,
        # docs/observability.md critical-path attribution): panel = the
        # reflector gather + larft chain, bulk = the W2/apply update. The
        # reverse sweep keeps the GLOBAL panel index p in the name; under
        # lookahead panel p's chain is emitted (and scoped) ahead of the
        # pending panel's bulk — the overlap the critpath report must see.
        ps = range(npan - 1, -1, -1)
        if la:
            pend = pend_p = None
            for p in ps:
                with obs.named_span(f"bt_r2b.step{p:03d}.panel"):
                    ch = chain(p)  # emitted ahead of pend's bulk update
                if ch is None:
                    continue
                if pend is not None:
                    # this chain's collectives overlap the pending bulk
                    cc.record_overlapped("bt_r2b_dist", ROW_AXIS, 1)
                    cc.record_overlapped("bt_r2b_dist", COL_AXIS, 1)
                    with obs.named_span(f"bt_r2b.step{pend_p:03d}.bulk"):
                        lt_c = update(pend, lt_c)
                pend, pend_p = ch, p
            if pend is not None:
                with obs.named_span(f"bt_r2b.step{pend_p:03d}.bulk"):
                    lt_c = update(pend, lt_c)
            return lt_c
        for p in ps:
            with obs.named_span(f"bt_r2b.step{p:03d}.panel"):
                ch = chain(p)
            if ch is None:
                continue
            with obs.named_span(f"bt_r2b.step{p:03d}.bulk"):
                lt_c = update(ch, lt_c)
        return lt_c

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(), P(ROW_AXIS, COL_AXIS)),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


def _build_dist_bt_r2b_scan(dist_a, dist_c, mesh, band, la: bool = False):
    """``lax.scan`` form of the distributed back-transform
    (``dist_step_mode="scan"``): one compiled reflector-block step looped
    ``ceil(n/b) - 1`` times in reverse — config #5's back-transform has
    the same per-panel unrolled-compile exposure as the forward reduction
    (docs/DESIGN.md). Uses the shared traced-``p`` rolled sub-panel
    gather. TELESCOPED like the forward reduction, mirrored for the
    reverse sweep: panel ``p`` only touches C rows at element >= (p+1)*b,
    so early segments (large ``p``) work on a small bottom window of the
    row-slot axis that grows as the sweep ascends; the W2 psum and the C
    update run over the window's slots under traced element masks.

    The body already emits its panel gather (COL bcast + ROW all_gather)
    and larft AHEAD of the bulk contractions, reading only the constant
    (sub_a, taus) — overlap by construction, like the PR-4 scan bodies;
    ``la`` (``bt_lookahead=1``) labels the structure and books the
    per-body overlap counters (trace-time: once per telescope segment,
    not per executed step — the PR-4 scan caveat)."""
    nt = dist_a.nr_tiles.row
    nb = dist_a.block_size.row
    n = dist_a.size.row
    Pr, Qc = dist_a.grid_size.row, dist_a.grid_size.col
    b = band
    npan = ceil_div(n, b) - 1 if n else 0

    def run(lt_a, taus, lt_c):
        ctx_a = DistContext(dist_a)
        ctx_c = DistContext(dist_c)
        arange_nb = jnp.arange(nb)

        def make_step(lu_off, lc_off, ltr_w):
            base = lu_off * Pr
            sub_a = lt_a[lu_off:, lc_off:]

            def step(sub_c, i):
                p = npan - 1 - i
                if la:
                    # the gather below reads only constant storage and is
                    # emitted ahead of this body's bulk contractions
                    cc.record_overlapped("bt_r2b_dist_scan", ROW_AXIS, 1)
                    cc.record_overlapped("bt_r2b_dist_scan", COL_AXIS, 1)
                pan, bdy, _, _, _, _, _ = gather_sub_panel_dyn(
                    ctx_a, sub_a, p=p, b=b, n=n,
                    row_off=lu_off, col_off=lc_off)
                m_w = (nt - base) * nb
                v = jnp.tril(pan, -1) + jnp.eye(m_w, b, dtype=pan.dtype)
                t = larft(v, taus[p])
                vt = tiles_of_rolled(ctx_a, v, bdy, base * nb)

                g_rows_c = ctx_c.g_rows(lu_off, ltr_w)
                g_erows_c = g_rows_c[:, None] * nb + arange_nb[None, :]
                rv_c_e = (g_erows_c >= bdy) & (g_erows_c < n)
                sel = jnp.clip(g_rows_c - base, 0, nt - base - 1)
                v_my = jnp.where(rv_c_e[:, :, None], vt[sel],
                                 jnp.zeros((ltr_w, nb, b), dtype=pan.dtype))
                w2 = tb.contract("rab,rcad->cbd", jnp.conj(v_my), sub_c)
                w2 = cc.all_reduce(w2, ROW_AXIS)
                w2 = tb.contract("xb,cbd->cxd", t, w2)
                upd = tb.contract("rab,cbd->rcad", v_my, w2)
                return sub_c - upd, None

            return step

        if npan <= 0:
            return lt_c
        # telescoped segments (reverse sweep: segment [i0, i0+len) covers
        # p = npan-1-i0 down to p_lo = npan-i0-len; its window covers
        # every row tile >= (p_lo*b)//nb)
        def window(pos, seg_len):
            p_lo = npan - pos - seg_len
            t_min = (p_lo * b) // nb
            return (uniform_slot_start(t_min, Pr),
                    uniform_slot_start(t_min, Qc))

        for (lu_off, lc_off), i0, seg_len in telescope_windows(npan, window):
            sub_c = lt_c[lu_off:]
            # index-free scope: one traced body per telescope segment —
            # critpath reconstructs per-step timing by occurrence order
            sub_c, _ = jax.lax.scan(
                obs.scoped_step(
                    "bt_r2b.scanstep",
                    make_step(lu_off, lc_off, ctx_c.ltr - lu_off)), sub_c,
                jnp.arange(i0, i0 + seg_len))
            lt_c = lt_c.at[lu_off:].set(sub_c)
        return lt_c

    return shard_map(run, mesh=mesh,
                     in_specs=(P(ROW_AXIS, COL_AXIS), P(), P(ROW_AXIS, COL_AXIS)),
                     out_specs=P(ROW_AXIS, COL_AXIS), check_vma=False)


@register_program_cache
@functools.lru_cache(maxsize=32)
def _dist_bt_r2b_cached(dist_a, dist_c, mesh, band, scan=False, la=False,
                        route=()):
    # ``route``: the eigensolver's active autotune route as a pure
    # cache-key member (docs/autotune.md) — the bulk trmm/gemm
    # application reads _oz_slices at trace time on the mxu path
    build = _build_dist_bt_r2b_scan if scan else _build_dist_bt_r2b
    return jax.jit(build(dist_a, dist_c, mesh, band, la=la))


def _bt_r2b_entry_span(red: BandReduction, n: int, m: int, la: bool,
                       grid: str):
    """Entry span (docs/observability.md): block-reflector application
    flop model n^2*m muls + n^2*m adds (docs/eigensolver_perf.md)."""
    from .. import obs
    from ..types import total_ops

    dt = np.dtype(red.matrix.dtype)
    return obs.entry_span("bt_reduction_to_band", lambda: dict(
        flops=total_ops(dt, n**2 * m, n**2 * m), n=n, m=m,
        band=red.band, dtype=dt.name, bt_lookahead=int(la), grid=grid))


def bt_reduction_to_band(red: BandReduction, evecs, *, route: tuple = ()):
    """Eigenvectors of the ORIGINAL matrix from eigenvectors of the band
    matrix: apply the panel reflector blocks in reverse order.

    Local when ``red.matrix`` is local (``evecs`` array -> array); distributed
    when both ``red.matrix`` and ``evecs`` live on a grid (Matrix -> Matrix,
    reference ``bt_reduction_to_band/api.h:18-23`` distributed overload).

    Under ``bt_lookahead=1`` (auto: TPU) reflector block k+1's T-factor
    chain — and, distributed, its panel gather collectives — is emitted
    ahead of block k's bulk application (docs/eigensolver_perf.md);
    results are bitwise identical either way.
    """
    from ..config import resolved_bt_lookahead

    la = resolved_bt_lookahead()
    a = red.matrix
    if isinstance(evecs, Matrix) and a.grid is not None and a.grid.num_devices > 1:
        dlaf_assert(evecs.grid is not None
                    and evecs.grid.size == a.grid.size,
                    "bt_reduction_to_band: V and C must share the grid")
        dlaf_assert(evecs.block_size.row == a.block_size.row,
                    "bt_reduction_to_band: C row block != V block")
        dlaf_assert(evecs.size.row == a.size.row,
                    "bt_reduction_to_band: C rows != n")
        dlaf_assert(a.block_size.row % red.band == 0,
                    "bt_reduction_to_band: band must divide the block size")
        storage = evecs.storage
        if storage.dtype != a.dtype:
            storage = storage.astype(a.dtype)
        from ..config import resolve_step_mode

        # the builders trace ceil(n/band) - 1 reflector-block steps
        fn = _dist_bt_r2b_cached(a.dist, evecs.dist, a.grid.mesh, red.band,
                                 scan=resolve_step_mode(max(
                                     -(-a.size.row // red.band) - 1, 1))
                                 == "scan", la=la, route=route)
        with _bt_r2b_entry_span(
                red, a.size.row, evecs.size.col, la,
                f"{a.dist.grid_size.row}x{a.dist.grid_size.col}"):
            from .. import obs

            # program telemetry (DLAF_PROGRAM_TELEMETRY): off = passthrough
            out = obs.telemetry.call("bt_reduction_to_band.dist", fn,
                                     a.storage, memory.as_device(red.taus),
                                     storage)
        return Matrix(evecs.dist, out, evecs.grid)
    a_v = tiles_to_global(a.storage, a.dist)
    arr = evecs
    ret_matrix = isinstance(evecs, Matrix)
    if ret_matrix:
        arr = tiles_to_global(evecs.storage, evecs.dist)
    e = memory.as_device(arr).astype(a_v.dtype)
    with _bt_r2b_entry_span(red, a.size.row,
                            e.shape[1] if e.ndim > 1 else 1, la, "1x1"):
        from .. import obs

        out = obs.telemetry.call("bt_reduction_to_band.local",
                                 _bt_r2b_local, a_v,
                                 memory.as_device(red.taus), e, nb=red.band,
                                 la=la, route=route)
    if ret_matrix:
        return Matrix(evecs.dist, global_to_tiles(out, evecs.dist), evecs.grid)
    return out
