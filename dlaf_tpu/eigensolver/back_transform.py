"""Back-transformations: tridiag -> band -> full eigenvectors.

TPU-native counterpart of the reference's two back-transformation stages:

* ``bt_band_to_tridiag`` (``impl.h:1-938``): apply the bulge-chasing
  Householder vectors to the eigenvector matrix. The reference re-tiles the
  HH storage into cache-friendly b x b groups; here the uniform
  (n_sweeps, n_steps, b) layout produced by the chase makes one sweep = ONE
  batched segment update, and the whole stage is a ``lax.scan`` over sweeps
  (reverse order) — static shapes, device-resident, no host round trips.

* ``bt_reduction_to_band`` (``impl.h:82-373``): apply the panel reflector
  blocks in reverse order, C <- (I - V T V^H) C per panel — two gemms + one
  small T solve per panel, trace-time unrolled.

Both consume the storage contracts of :mod:`.band_to_tridiag` and
:mod:`.reduction_to_band` directly.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..tile_ops.lapack import larft
from ..types import ceil_div
from .band_to_tridiag import TridiagResult
from .reduction_to_band import BandReduction


@functools.partial(jax.jit, static_argnames=("b", "n"))
def _bt_b2t_scan(v_all, tau_all, e, *, b: int, n: int):
    """E <- Q E with Q = prod over reflectors H^H in reverse sweep order."""
    n_sweeps, n_steps, _ = v_all.shape
    m = e.shape[1]
    seg_len = n_steps * b
    pad = seg_len + 1
    e_pad = jnp.pad(e, ((0, pad), (0, 0)))

    def body(e_pad, xs):
        s, v_s, tau_s = xs
        start = s + 1
        seg = lax.dynamic_slice(e_pad, (start, 0), (seg_len, m))
        seg = seg.reshape(n_steps, b, m)
        w = jnp.einsum("tb,tbm->tm", jnp.conj(v_s), seg)
        seg = seg - jnp.conj(tau_s)[:, None, None] * v_s[..., None] * w[:, None, :]
        e_pad = lax.dynamic_update_slice(e_pad, seg.reshape(seg_len, m), (start, 0))
        return e_pad, None

    xs = (jnp.arange(n_sweeps - 1, -1, -1),
          v_all[::-1], tau_all[::-1])
    e_pad, _ = lax.scan(body, e_pad, xs)
    return e_pad[:n]


def bt_band_to_tridiag(tri: TridiagResult, evecs) -> jax.Array:
    """Eigenvectors of the BAND matrix from eigenvectors of the tridiagonal:
    apply the complex phases (see band_to_tridiag), then the chase reflectors
    in reverse sweep order."""
    n = tri.d.shape[0]
    cplx = np.issubdtype(tri.v.dtype, np.complexfloating)
    e = jnp.asarray(evecs)
    if cplx:
        e = e.astype(tri.v.dtype) * jnp.asarray(tri.phase)[:, None]
    if tri.v.shape[0] == 0:
        return e
    return _bt_b2t_scan(jnp.asarray(tri.v), jnp.asarray(tri.tau), e,
                        b=tri.band, n=n)


@functools.partial(jax.jit, static_argnames=("nb",))
def _bt_r2b_local(a_v, taus, e, *, nb: int):
    n = a_v.shape[0]
    nt = ceil_div(n, nb) if n else 0
    for k in range(nt - 2, -1, -1):
        k1 = (k + 1) * nb
        m_p = n - k1
        if m_p <= 0:
            continue
        vf = a_v[k1:, k * nb: k * nb + nb]
        v = jnp.tril(vf, -1) + jnp.eye(m_p, nb, dtype=a_v.dtype)
        t = larft(v, taus[k])
        w = t @ (jnp.conj(v).T @ e[k1:])
        e = e.at[k1:].add(-v @ w)
    return e


def bt_reduction_to_band(red: BandReduction, evecs) -> jax.Array:
    """Eigenvectors of the ORIGINAL matrix from eigenvectors of the band
    matrix: apply the panel reflector blocks in reverse order (local;
    the reference's distributed variant lands with the distributed
    eigensolver driver)."""
    from ..matrix.tiling import tiles_to_global

    a_v = tiles_to_global(red.matrix.storage, red.matrix.dist)
    e = jnp.asarray(evecs, dtype=a_v.dtype)
    return _bt_r2b_local(a_v, jnp.asarray(red.taus), e, nb=red.band)
