"""Eigensolver pipeline — public API (reference ``eigensolver.h:13-19``
umbrella: reductionToBand, bandToTridiag, tridiagSolver,
backTransformation*, eigensolver, genEigensolver)."""

from .back_transform import bt_band_to_tridiag, bt_reduction_to_band
from .band_to_tridiag import band_to_tridiag
from .eigensolver import EigensolverResult, eigensolver, gen_eigensolver
from .reduction_to_band import extract_band, reduction_to_band
from .tridiag_solver import tridiag_solver

__all__ = [
    "EigensolverResult",
    "band_to_tridiag",
    "bt_band_to_tridiag",
    "bt_reduction_to_band",
    "eigensolver",
    "extract_band",
    "gen_eigensolver",
    "reduction_to_band",
    "tridiag_solver",
]
