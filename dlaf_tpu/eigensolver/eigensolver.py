"""Standard and generalized Hermitian eigensolver drivers.

TPU-native counterpart of the reference's ``eigensolver/eigensolver``
(``api.h:28-31``, ``impl.h:33-78``) and ``gen_eigensolver``
(``api.h:17-21``, ``impl.h:24-35``) — LOCAL only, matching the reference at
this snapshot (its distributed eigensolver does not exist either; SURVEY §2).

Pipeline (reference ``impl.h:33-78``):
  reduction_to_band  ->  band_to_tridiag (host chase)  ->  D&C tridiag solve
  ->  bt_band_to_tridiag  ->  bt_reduction_to_band

Generalized problem ``A x = lambda B x`` (``gen_eigensolver/impl.h:24-35``):
  cholesky(B)  ->  gen_to_std  ->  eigensolver  ->  triangular back-
  substitution of the eigenvectors.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from typing import Optional

from ..common.sync import hard_fence
from ..algorithms.cholesky import cholesky
from ..algorithms.gen_to_std import gen_to_std
from ..algorithms.triangular import triangular_solve
from ..common.asserts import dlaf_assert
from ..common.timer import PhaseTimer
from ..matrix import ops as mops
from ..matrix.matrix import Matrix
from .back_transform import bt_band_to_tridiag, bt_reduction_to_band
from .band_to_tridiag import band_to_tridiag
from .reduction_to_band import extract_band, reduction_to_band
from .tridiag_solver import tridiag_solver


@dataclasses.dataclass
class EigensolverResult:
    """Reference ``EigensolverResult{eigenvalues, eigenvectors}``
    (``api.h:21-24``)."""

    eigenvalues: np.ndarray   # (n,) real, ascending
    eigenvectors: Matrix      # columns are eigenvectors


def eigensolver(uplo: str, a: Matrix,
                phases: Optional[PhaseTimer] = None,
                band_size: int | None = None, *,
                donate: bool = False,
                resume: bool = False) -> EigensolverResult:
    """Eigendecomposition of Hermitian ``a`` stored in ``uplo``
    (reference ``eigensolver::eigensolver``, ``api.h:28-31``).

    The reference is LOCAL-only at this snapshot; here the same pipeline also
    runs distributed (beyond-parity): distributed reduction_to_band, host
    band/tridiag/D&C stages (the reference keeps these on CPU too), then the
    two distributed back-transformations.

    ``phases`` (optional :class:`PhaseTimer`) collects per-stage wall times —
    the per-algorithm phase instrumentation SURVEY §5 calls for.

    ``donate=True`` permits consuming ``a``'s device storage at the first
    stage (the reference pipeline overwrites mat_a throughout); ``a`` must
    not be used afterwards (with ``resume=True`` skipping the first stage,
    ``a`` is simply left untouched).

    **Preemption-safe resume** (docs/robustness.md §5): with
    ``DLAF_RESUME_DIR`` (config ``resume_dir``) set, the pipeline writes an
    atomic versioned stage checkpoint after each of red2band / b2t /
    tridiag / bt_b2t / bt_r2b; ``resume=True`` then skips every stage whose
    manifest matches this run's config/grid/dtype fingerprint and restores
    its payload bitwise, so a preempted multi-minute run continues from the
    last completed boundary and produces the SAME eigenpairs as the
    uninterrupted run (bitwise per stage on the native routes — pinned by
    tests/test_resilience.py and the ci/run.sh kill-and-resume drill).
    A fingerprint mismatch raises :class:`dlaf_tpu.health.errors.
    ResumeError` naming the offending keys; ``resume=True`` without a
    resume dir raises too — a silent full recompute is not a resume.
    """
    dlaf_assert(a.size.row == a.size.col, "eigensolver: square only")
    n = a.size.row
    nb = a.block_size.row
    if n == 0:
        return EigensolverResult(np.zeros(0), a)
    pt = phases if phases is not None else PhaseTimer()
    # per-phase device fences only when timing was requested — they would
    # otherwise serialize stage compile/dispatch against device execution
    fence = (hard_fence if phases is not None
             else (lambda x: None))
    distributed = a.grid is not None and a.grid.num_devices > 1
    from .. import obs
    from ..types import total_ops

    from ..config import resolved_bt_lookahead, resolved_dc_level_batch

    # canonical full-EVP flop model (miniapp_eigensolver): 5n^3/3
    # muls+adds; the five stage spans below nest under this one. The
    # pipeline-throughput knobs (docs/eigensolver_perf.md) ride along so
    # one span record says which trailing-stage formulation ran.
    # accuracy-steered precision route (docs/autotune.md): one steering
    # handle for the whole pipeline — the route is applied (and threaded
    # as a cache-key member) around the route-sensitive device stages
    # (reduction_to_band, bt_reduction_to_band); the host chase and the
    # D&C tridiag programs keep the config route (their caches are not
    # route-keyed — documented scope, docs/autotune.md §threading)
    from .. import autotune

    steer = autotune.steering_for_matrix("eigensolver", a)
    route = steer.route.key() if steer is not None else ()
    pipeline_span = obs.entry_span("eigensolver", lambda: dict(
        flops=total_ops(np.dtype(a.dtype), 5 * n**3 / 3, 5 * n**3 / 3),
        n=n, nb=nb, uplo=uplo, dtype=np.dtype(a.dtype).name,
        dc_level_batch=int(resolved_dc_level_batch()),
        bt_lookahead=int(resolved_bt_lookahead()),
        **({"autotune_route": dict(route)} if route else {}),
        grid=f"{a.dist.grid_size.row}x{a.dist.grid_size.col}"))
    with pipeline_span:
        result = _eigensolver_pipeline(uplo, a, pt, fence, distributed,
                                       band_size, donate, n, nb, resume,
                                       steer=steer, route=route)
    if steer is not None and not donate and steer.probe_due:
        # close the loop: the pipeline's cheap Hutchinson eigenpair
        # residual (PR 8's estimator — no new device code) feeds the
        # route table; donated inputs have nothing left to probe against
        est = obs.accuracy.eigen_residuals(
            uplo, a, result.eigenvalues, result.eigenvectors)
        steer.observe(est["eigen_residual"], c=200.0,
                      of=result.eigenvectors.storage,
                      attrs={"entry": "eigensolver", "uplo": uplo})
    return result


def _stage_fingerprint(uplo, a, band_size, n, nb) -> dict:
    """The run identity a stage checkpoint is valid for: shape/layout/
    dtype/grid plus the platform (route autos resolve per backend, and a
    checkpoint must never cross them) plus a content hash of the INPUT —
    two same-shaped runs over different matrices must never trade
    checkpoints (resume would silently return the other run's
    eigenpairs)."""
    import jax

    fp = dict(pipeline="eigensolver", n=int(n), nb=int(nb), uplo=uplo,
              dtype=np.dtype(a.dtype).name,
              band_size=int(band_size) if band_size else 0,
              grid=f"{a.dist.grid_size.row}x{a.dist.grid_size.col}",
              backend=jax.default_backend())
    from ..config import get_configuration

    if get_configuration().resume_dir and jax.process_count() == 1:
        # one host gather of the input, only when checkpointing is
        # armed. Hash the stored triangle only: the other triangle is
        # contractually unread and may hold run-varying garbage.
        import hashlib

        g = np.asarray(a.to_numpy())
        tri = np.tril(g) if uplo == "L" else np.triu(g)
        fp["input_sha"] = hashlib.sha256(
            np.ascontiguousarray(tri).tobytes()).hexdigest()[:16]
    return fp


def _pack_red(red) -> dict:
    from ..matrix.checkpoint import matrix_arrays

    return {**matrix_arrays(red.matrix, "matrix"),
            "taus": np.asarray(red.taus),
            "band": np.asarray(red.band, dtype=np.int64)}


def _load_red(arrays, grid):
    import jax.numpy as jnp

    from ..matrix.checkpoint import matrix_from_arrays
    from .reduction_to_band import BandReduction

    return BandReduction(matrix=matrix_from_arrays(arrays, "matrix", grid),
                         taus=jnp.asarray(arrays["taus"]),
                         band=int(arrays["band"]))


def _pack_tri(tri) -> dict:
    return {"d": np.asarray(tri.d), "e": np.asarray(tri.e),
            "v": np.asarray(tri.v), "tau": np.asarray(tri.tau),
            "phase": np.asarray(tri.phase),
            "band": np.asarray(tri.band, dtype=np.int64)}


def _load_tri(arrays):
    from .band_to_tridiag import TridiagResult

    return TridiagResult(d=arrays["d"], e=arrays["e"], v=arrays["v"],
                         tau=arrays["tau"], phase=arrays["phase"],
                         band=int(arrays["band"]))


def _eigensolver_pipeline(uplo, a, pt, fence, distributed, band_size,
                          donate, n, nb, resume, steer=None, route=()):
    from .. import autotune
    from ..health import resume as hresume
    from ..matrix.checkpoint import matrix_arrays, matrix_from_arrays

    _route = steer.route if steer is not None else None

    ck = hresume.stage_checkpointer(
        "eigensolver", _stage_fingerprint(uplo, a, band_size, n, nb),
        resume=resume)
    with pt.phase("stage.reduction_to_band"):
        if ck.completed("red2band"):
            red = _load_red(ck.load("red2band"), a.grid)
        else:
            # ``donate`` consumes a's storage at the hermitianize; ah
            # itself is always a fresh copy owned by this driver — donate
            # it to the reduction (one full matrix off peak HBM either
            # way)
            ah = mops.hermitianize(a, uplo, donate=donate)
            # route context + cache-key threading (docs/autotune.md):
            # the trailing gemms read the routed slice count at trace
            # time, so the route must be live for the trace AND a
            # member of the builder's cache key
            with autotune.applied(_route):
                red = reduction_to_band(ah, band_size=band_size,
                                        donate=True, route=route)
            ck.commit("red2band", _pack_red(red))
        fence(red.matrix.storage)
    with pt.phase("stage.band_to_tridiag"):
        if ck.completed("b2t"):
            tri = _load_tri(ck.load("b2t"))
        else:
            band = extract_band(red)
            tri = band_to_tridiag(band, red.band)
            ck.commit("b2t", _pack_tri(tri))
    with pt.phase("stage.tridiag_solver"):
        if ck.completed("tridiag"):
            arrs = ck.load("tridiag")
            lam, z = arrs["lam"], arrs["z"]
        else:
            # distributed: the merge-tree gemms, qc workspaces, and Q run
            # sharded over the grid's mesh (beyond the local-only
            # reference) — the (n, n) merge arrays never have to fit one
            # device's HBM (remaining single-device term: the deflated
            # secular workspace)
            lam, z = tridiag_solver(tri.d, tri.e, nb,
                                    mesh=a.grid.mesh if distributed
                                    else None)
            ck.commit("tridiag", {"lam": np.asarray(lam),
                                  "z": np.asarray(z)})
        fence(z)
    with pt.phase("stage.bt_band_to_tridiag"):
        if ck.completed("bt_b2t"):
            arrs = ck.load("bt_b2t")
            zb = (matrix_from_arrays(arrs, "zb", a.grid) if distributed
                  else arrs["zb"])
        elif distributed:
            # z is a device-resident jax.Array (tridiag_solver keeps Q on
            # device across the merge tree); from_global re-tiles it ON
            # DEVICE — no host materialization between stages (round-1
            # review weak item 4)
            zb = bt_band_to_tridiag(
                tri, Matrix.from_global(z, a.block_size, grid=a.grid,
                                        source_rank=a.dist.source_rank))
            fence(zb.storage)
            ck.commit("bt_b2t", matrix_arrays(zb, "zb"))
        else:
            zb = bt_band_to_tridiag(tri, z)
            fence(zb)
            ck.commit("bt_b2t", {"zb": np.asarray(zb)})
    with pt.phase("stage.bt_reduction_to_band"):
        if ck.completed("bt_r2b"):
            vecs = matrix_from_arrays(ck.load("bt_r2b"), "vecs", a.grid)
        else:
            with autotune.applied(_route):
                out = bt_reduction_to_band(red, zb, route=route)
            if distributed:
                vecs = out
                fence(vecs.storage)
            else:
                vecs = Matrix.from_global(out, a.block_size, grid=a.grid,
                                          source_rank=a.dist.source_rank)
            ck.commit("bt_r2b", matrix_arrays(vecs, "vecs"))
    return EigensolverResult(lam, vecs)


def gen_eigensolver(uplo: str, a: Matrix, b: Matrix,
                    phases: Optional[PhaseTimer] = None,
                    band_size: int | None = None, *,
                    donate: bool = False) -> EigensolverResult:
    """Generalized problem ``A x = lambda B x`` with Hermitian ``a`` and
    HPD ``b`` (reference ``eigensolver::genEigensolver``, ``api.h:17-21``;
    LOCAL-only in the reference — here every stage also runs distributed).

    ``donate=True`` permits consuming ``a``'s storage; ``b`` is never
    consumed (its factor is formed from an undonated read)."""
    dlaf_assert(a.size == b.size, "gen_eigensolver: A/B size mismatch")
    pt = phases if phases is not None else PhaseTimer()
    fence = (hard_fence if phases is not None
             else (lambda x: None))
    from .. import obs

    pipeline_span = obs.entry_span("gen_eigensolver", lambda: dict(
        n=a.size.row, nb=a.block_size.row, uplo=uplo,
        dtype=np.dtype(a.dtype).name,
        grid=f"{a.dist.grid_size.row}x{a.dist.grid_size.col}"))
    with pipeline_span:
        return _gen_eigensolver_pipeline(uplo, a, b, pt, phases, fence,
                                         band_size, donate)


def _gen_eigensolver_pipeline(uplo, a, b, pt, phases, fence, band_size,
                              donate):
    with pt.phase("stage.cholesky"):
        bf = cholesky(uplo, b)
        fence(bf.storage)
    with pt.phase("stage.gen_to_std"):
        astd = gen_to_std(uplo, a, bf, donate=donate)
        fence(astd.storage)
    # astd is owned by this driver — always donated into the pipeline
    res = eigensolver(uplo, astd, phases=phases, band_size=band_size,
                      donate=True)
    # back-substitute eigenvectors (reference gen_eigensolver/impl.h:24-35):
    # uplo=L: B = L L^H, standard vec y -> x = L^-H y
    # uplo=U: B = U^H U,                x = U^-1 y
    with pt.phase("stage.back_substitution"):
        # res.eigenvectors is owned by this driver — donated into the solve
        if uplo == "L":
            vecs = triangular_solve("L", "L", "C", "N", 1.0, bf,
                                    res.eigenvectors, donate_b=True)
        else:
            vecs = triangular_solve("L", "U", "N", "N", 1.0, bf,
                                    res.eigenvectors, donate_b=True)
        fence(vecs.storage)
    return EigensolverResult(res.eigenvalues, vecs)
