"""Band -> tridiagonal reduction by bulge chasing (host stage).

TPU-native counterpart of the reference's ``eigensolver/band_to_tridiag``
(``api.h:39-46``, ``mc.h:91-380``): like the reference — which runs this
stage CPU-only even for its GPU backend, with pipelined ``SweepWorker``s —
the inherently sequential fine-grained chase runs on the host, against a
compact band storage with bulge headroom (``ld = 2b+1``; the reference's
``BandBlock`` uses ``ld = 2b-1``).

Sweep ``s`` eliminates column ``s`` below the first subdiagonal with a
length-``b`` Householder reflector, then chases the resulting bulge down the
band in contiguous length-``b`` chunks. Crucially, the chase segments of one
sweep are DISJOINT row ranges ``[s+1+t*b, s+1+(t+1)*b)`` — so a whole sweep's
reflectors commute and the back-transform (:mod:`.bt_band_to_tridiag`) can
apply them as ONE batched device op per sweep. Reflectors are therefore
returned in a dense uniform layout:

    V[s, t, :]   — reflector of sweep s, chase step t (v[0] = 1, zero-padded)
    TAU[s, t]    — its tau (0 => identity)

A C++ twin of this loop (``native/band_to_tridiag.cpp``) provides the fast
path; this numpy implementation is the reference/fallback (selected via
``Configuration.band_to_tridiag_impl``).

Complex matrices: the chase produces a Hermitian tridiagonal with complex
off-diagonals; it is phase-normalized to a REAL symmetric tridiagonal (the
LAPACK ``hbtrd`` convention), returning the unit phases so the back-transform
can restore them (``T_complex = Phi T_real Phi^H``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..types import ceil_div


@dataclasses.dataclass
class TridiagResult:
    """Reference ``TridiagResult{mat_trid, mat_v}`` analog (``api.h:19``)."""

    d: np.ndarray        # (n,) real diagonal
    e: np.ndarray        # (n-1,) real off-diagonal
    v: np.ndarray        # (n_sweeps, n_steps, b) reflectors
    tau: np.ndarray      # (n_sweeps, n_steps)
    phase: np.ndarray    # (n,) unit phases (ones for real dtypes)
    band: int


def _larfg(x):
    """Householder generator: (v, tau, beta) with ``(I - tau v v^H) x =
    beta e1``, ``v[0] = 1``, ``beta`` real (LAPACK larfg convention)."""
    x = np.asarray(x)
    m = x.shape[0]
    alpha = x[0]
    xnorm = np.linalg.norm(x[1:]) if m > 1 else 0.0
    if xnorm == 0.0 and np.imag(alpha) == 0.0:
        return np.zeros_like(x), x.dtype.type(0), np.real(alpha)
    r = np.hypot(np.abs(alpha), xnorm)
    beta = -np.copysign(r, np.real(alpha)) if np.real(alpha) != 0 else -r
    # LAPACK larfg gives H^H x = beta e1 for tau = (beta-alpha)/beta; we use
    # the H x = beta e1 convention, i.e. the conjugate tau.
    tau = np.conj((beta - alpha) / beta)
    v = x / (alpha - beta)
    v[0] = 1.0
    return v, x.dtype.type(tau), beta


def _apply_two_sided(s_mat, v, tau):
    """S <- H S H^H with H = I - tau v v^H, S Hermitian (dense window)."""
    u = s_mat @ v
    vhu = np.vdot(v, u)                      # real (S Hermitian)
    w = np.conj(tau) * u - (np.abs(tau) ** 2 * vhu / 2.0) * v
    return s_mat - np.outer(w, v.conj()) - np.outer(v, w.conj())


def band_to_tridiag_numpy(band: np.ndarray, b: int) -> TridiagResult:
    """Numpy bulge chase. ``band``: (b+1, n) lower 'sb' layout
    (``band[r, j] = A[j+r, j]``)."""
    n = band.shape[1]
    dtype = band.dtype
    cplx = np.issubdtype(dtype, np.complexfloating)
    # working storage with bulge headroom
    wb = np.zeros((2 * b + 1, n), dtype=dtype)
    wb[: b + 1] = band

    def get_win(j0, m):
        """Dense Hermitian window A[j0:j0+m, j0:j0+m] from band storage."""
        w = np.zeros((m, m), dtype=dtype)
        for r in range(min(m, 2 * b + 1)):
            dlen = m - r
            w[np.arange(r, m), np.arange(dlen)] = wb[r, j0: j0 + dlen]
        w = w + np.tril(w, -1).conj().T
        if cplx:
            np.fill_diagonal(w, np.real(np.diag(w)))
        return w

    def put_win(j0, w):
        m = w.shape[0]
        for r in range(min(m, 2 * b + 1)):
            dlen = m - r
            wb[r, j0: j0 + dlen] = w[np.arange(r, m), np.arange(dlen)]

    def _block_rows(i0, j0, mr, mc):
        """Banded-storage row indices of the dense block A[i0:i0+mr,
        j0:j0+mc]: column j0+c starts at storage row i0-(j0+c), so the
        block is an anti-diagonal window — one fancy-index gather/scatter
        instead of a per-column Python loop (it is the reference twin
        every bitwise test runs against; the loops were O(n*b) interpreter
        iterations on the pipeline's host critical path)."""
        return (i0 - j0 - np.arange(mc))[None, :] + np.arange(mr)[:, None]

    def get_block(i0, j0, mr, mc):
        """Dense A[i0:i0+mr, j0:j0+mc] (strictly below-diag block)."""
        return wb[_block_rows(i0, j0, mr, mc),
                  j0 + np.arange(mc)[None, :]]

    def put_block(i0, j0, w):
        mr, mc = w.shape
        wb[_block_rows(i0, j0, mr, mc), j0 + np.arange(mc)[None, :]] = w

    n_sweeps = max(n - 2, 0)
    n_steps = ceil_div(max(n - 1, 1), b) if n > 1 else 0
    v_out = np.zeros((n_sweeps, n_steps, b), dtype=dtype)
    tau_out = np.zeros((n_sweeps, n_steps), dtype=dtype)

    for s in range(n_sweeps):
        l = min(b, n - 1 - s)
        if l < 1:
            continue
        x = wb[1: 1 + l, s].copy()
        v, tau, beta = _larfg(x)
        wb[1, s] = beta
        if l > 1:
            wb[2: 1 + l, s] = 0.0
        v_out[s, 0, :l] = v
        tau_out[s, 0] = tau
        j0, t = s + 1, 0
        while True:
            if tau != 0:
                sw = get_win(j0, l)
                sw = _apply_two_sided(sw, v, tau)
                put_win(j0, sw)
            l2 = min(b, n - (j0 + l))
            if l2 == 0:
                break
            bblk = get_block(j0 + l, j0, l2, l)
            if tau != 0:
                bblk = bblk - np.conj(tau) * np.outer(bblk @ v, v.conj())
            xcol = bblk[:, 0].copy()
            v2, tau2, beta2 = _larfg(xcol)
            bblk[:, 0] = 0.0
            bblk[0, 0] = beta2
            if tau2 != 0 and l > 1:
                rest = bblk[:, 1:]
                bblk[:, 1:] = rest - tau2 * np.outer(v2, v2.conj() @ rest)
            put_block(j0 + l, j0, bblk)
            t += 1
            v_out[s, t, :l2] = v2
            tau_out[s, t] = tau2
            j0, l, v, tau = j0 + l, l2, v2, tau2

    d = np.real(wb[0]).copy()
    e_raw = wb[1, : n - 1].copy()
    phase = np.ones(n, dtype=dtype)
    if cplx:
        for j in range(n - 1):
            mag = np.abs(e_raw[j])
            ph = e_raw[j] / mag if mag > 0 else 1.0
            # T = Phi T_real Phi^H with Phi[j+1] = Phi[j] * ph
            phase[j + 1] = phase[j] * ph
            e_raw[j] = mag
        e = np.real(e_raw)
    else:
        e = np.real(e_raw)
    return TridiagResult(d=d, e=e, v=v_out, tau=tau_out, phase=phase, band=b)


def band_to_tridiag(band: np.ndarray, b: int, impl: str | None = None) -> TridiagResult:
    """Dispatch between the native C++ chase and the numpy fallback
    (reference: the ``Backend::MC``-only specialization, ``api.h:39-46``)."""
    from ..config import get_configuration

    impl = impl or get_configuration().band_to_tridiag_impl
    if impl == "native":
        # unified degradation policy: dlaf_fallback_total counter +
        # one-shot announce; DLAF_STRICT=1 raises instead of degrading
        from ..health.registry import run_with_fallback

        def _native():
            from ..native import bindings

            return bindings.band_to_tridiag(band, b)

        return run_with_fallback("band_to_tridiag", _native,
                                 lambda: band_to_tridiag_numpy(band, b))
    return band_to_tridiag_numpy(band, b)
