"""Reduction of a Hermitian matrix to band form (bandwidth = block size by
default; any band_size dividing the block size is supported, distributed
included).

TPU-native counterpart of the reference's ``eigensolver/reduction_to_band``
(``api.h:18-22``, ``impl.h``; band = blockSize) plus the QR T-factor
(``factorization/qr/t_factor_impl.h:42-347``). The reference computes panel
reflectors column-by-column with dot/scal/gemv/ger micro-kernels on the CPU
(even for its GPU backend, ``impl.h:543-589``) and distributes the panel work
with per-column all-reduces. The TPU-native design replaces all of that with
dense MXU primitives:

* panel reflectors: ONE ``panel_qr`` (tile_ops/qr_panel.py: XLA geqrf or
  the jnp householder sweep, per config) on the whole panel — no
  per-column host round-trip;
* T factor: closed-form ``larft`` (one gemm + small triangular solve);
* trailing two-sided update: W = A (V T); M = V^H W; X = W - 1/2 V (T^H M);
  A <- A - X V^H - V X^H — three big gemms (the reference's hemmComputeX /
  gemmComputeW2 / gemmUpdateX / her2kUpdateTrailingMatrix fused into batched
  einsums).
* distributed: the panel is all-gathered along the row axis (nb columns —
  cheap), factored redundantly on every rank, and the update runs as local
  einsums + psum partial sums over the mesh axes.

The trailing matrix is kept FULL Hermitian during the sweep (both triangles
updated); on return the matrix holds the band (diagonal blocks + upper-
triangular subdiagonal R blocks) with the Householder vectors V stored below
the band (LAPACK-style), plus the tau coefficients — exactly what the
band->tridiag stage and back-transform consume.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..tile_ops.qr_panel import panel_qr  # geqrf-convention; route per config

from .. import obs
from ..config import register_program_cache
from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..common.asserts import dlaf_assert
from ..matrix.matrix import Matrix
from ..matrix.panel import (DistContext, gather_col_panel_ordered,
                            gather_sub_panel, gather_sub_panel_dyn,
                            pad_sub_panel_to_tiles, tiles_of_rolled,
                            uniform_slot_start)
from ..matrix.tiling import (storage_tile_grid, global_to_tiles_donated,
                             to_global, quiet_donation, donate_argnums_kw)
from ..tile_ops import blas as tb
from ..tile_ops.lapack import larft
from ..types import ceil_div, telescope_segments, telescope_windows


@dataclasses.dataclass
class BandReduction:
    """Result: band+V matrix, taus (ceil(n/band)-1, band) zero-padded, and
    the bandwidth ``band`` (= block size unless band_size was given)."""

    matrix: Matrix
    taus: jax.Array  # (ceil(n/band)-1, band), zero-padded
    band: int


# ---------------------------------------------------------------------------
# Local
# ---------------------------------------------------------------------------

def _trail_chunk(m: int, nb: int, dtype) -> int:
    """Trace-time: row-chunk width for the local trailing update, 0 =
    unchunked (config ``red2band_trail_chunk``; see the knob docstring).
    The trailing gemms' A-rows are independent — W = A(VT) row i reads
    only A[i, :], and the rank-2 update writes row i from X[i]/V[i] — so
    the chunked gemms are bitwise-identical to the unchunked ones (the
    emulated-f64 decomposition's scales are per-LHS-row and the
    contraction axes are untouched); whole-step results match to ~1 ulp
    (XLA re-fuses the small interleaved panel matmuls — v@t, the x
    correction — reassociating their reductions across program
    variants). Chunking only bounds the live mxu-route workspaces
    (operand slice planes, per-group product partials) to one chunk of
    rows."""
    # auto chunks only where the measured compile-OOM lives — TPU,
    # mxu-routed emulated dtypes, large trailing block (session 4f:
    # red2band n=16384/band=128 asked 19.28 GB of 15.75 at compile)
    return tb.resolve_chunk_width("red2band_trail_chunk", dtype,
                                  min(m, nb), m, m)


def _map_row_chunks(fn, cw: int, *arrs):
    """``lax.map`` of ``fn`` over row chunks (axis 0, width ``cw``) of
    ``arrs``, concatenating the outputs back along rows. A ragged final
    chunk is handled by clamping its start to ``m - cw`` instead of
    zero-padding (the pad would copy the full m x m operand — the exact
    buffer this lever exists to bound), so its leading rows overlap the
    previous chunk; ``fn`` must be row-local (output row i depends only
    on row i of each input — true of the trailing gemms), making the
    overlap a bitwise-identical recompute whose duplicate rows are
    dropped on reassembly."""
    from jax import lax

    m = arrs[0].shape[0]
    # the clamp-start scheme needs at least one full chunk inside the
    # operand; resolve_chunk_width enforces cw < chunk_axis at the caller,
    # but only indirectly (different module) — fail loudly here instead of
    # via a dynamic_slice size error (round-4 advisory)
    assert 0 < cw < m, f"_map_row_chunks: need 0 < cw < m, got cw={cw} m={m}"
    nc = -(-m // cw)   # cw < m, so nc >= 2
    starts = jnp.minimum(jnp.arange(nc, dtype=jnp.int32) * cw, m - cw)

    def body(i):
        zero = jnp.zeros((), i.dtype)
        return fn(*(lax.dynamic_slice(x, (i,) + (zero,) * (x.ndim - 1),
                                      (cw,) + x.shape[1:]) for x in arrs))

    out = lax.map(body, starts)
    tail = m - (nc - 1) * cw          # static: rows only the last chunk has
    head = out[:-1].reshape(((nc - 1) * cw,) + out.shape[2:])
    return jnp.concatenate([head, out[-1, cw - tail:]], axis=0)


@register_program_cache
@functools.partial(jax.jit, static_argnames=("nb", "route"),
                   donate_argnums=0)
def _red2band_local(a, *, nb: int, route: tuple = ()):
    """Panels of width ``nb`` = the target bandwidth (any 1 <= nb <= n; the
    reference's local variant likewise supports band_size < block size,
    ``reduction_to_band.h:78-87`` with ``mb % band_size == 0``)."""
    n = a.shape[0]
    nt = ceil_div(n, nb) if n else 0
    taus_out = jnp.zeros((max(nt - 1, 0), nb), dtype=a.dtype)
    for k in range(nt - 1):
        k0, k1 = k * nb, (k + 1) * nb
        m_p = n - k1
        panel = a[k1:, k0:k1]
        vfull, taus = panel_qr(panel)
        a = a.at[k1:, k0:k1].set(vfull)          # R in upper part, V below
        ntau = taus.shape[0]
        taus_out = taus_out.at[k, :ntau].set(taus)
        v = jnp.tril(vfull, -1) + jnp.eye(m_p, nb, dtype=a.dtype)
        if ntau < nb:
            taus = jnp.pad(taus, (0, nb - ntau))
        t = larft(v, taus)
        trail = a[k1:, k1:]                       # full Hermitian
        vt = v @ t
        cw = _trail_chunk(m_p, nb, a.dtype)
        if cw:
            w = _map_row_chunks(lambda tr: tb.mm(tr, vt), cw, trail)
        else:
            w = tb.mm(trail, vt)                  # A V T
        m = tb.mm(v.conj().T, w)                  # V^H W  (pw x pw)
        x = w - 0.5 * v @ (t.conj().T @ m)
        vh, xh = v.conj().T, x.conj().T
        if cw:
            new_trail = _map_row_chunks(
                lambda tr, xr, vr: tr - tb.mm(xr, vh) - tb.mm(vr, xh),
                cw, trail, x, v)
        else:
            new_trail = trail - tb.mm(x, vh) - tb.mm(v, xh)
        a = a.at[k1:, k1:].set(new_trail)
    return a, taus_out


@register_program_cache
@functools.partial(jax.jit, static_argnames=("nb", "route"),
                   donate_argnums=0)
def _red2band_local_scan(a, *, nb: int, route: tuple = ()):
    """``lax.scan`` form of the local reduction (``dist_step_mode="scan"``):
    one compiled panel step — the local unrolled trace costs ~19 s/panel
    on the hardware AOT toolchain and config #4's single-chip form is 127
    panels (docs/DESIGN.md). Uniform scheme: the full-height masked panel
    column is top-aligned with a traced roll (zero rows below a
    Householder panel leave its reflectors unchanged), and the two-sided
    update is full-size under traced masks (~2-3x flops)."""
    n = a.shape[0]
    if n == 0:
        return a, jnp.zeros((0, nb), dtype=a.dtype)
    nt = ceil_div(n, nb)
    npan = nt - 1
    npad = nt * nb - n
    if npad:
        a = jnp.pad(a, ((0, npad), (0, npad)))

    def make_step(m, off):
        """Step body on the trailing submatrix a[off*nb:, off*nb:] (size
        m) — completed reflector columns live outside it and the
        two-sided update only touches rows/cols past the (absolute)
        elimination boundary, so the telescoped segments are exact."""
        rows = jnp.arange(m)
        cw = _trail_chunk(m, nb, a.dtype)

        def step(carry, k):
            acc, taus_out = carry
            k0 = (k - off) * nb            # panel column inside the slice
            bdy = k0 + nb
            below = rows >= bdy            # (m,)
            raw = jax.lax.dynamic_slice(acc, (0, k0), (m, nb))
            pan = jnp.roll(jnp.where(below[:, None], raw, 0), -bdy, axis=0)
            # pan has m >= 2*nb rows whenever a step runs, so panel_qr
            # returns exactly nb taus; dead columns masked below
            vfull, taus = panel_qr(pan)
            col_live = jnp.arange(nb) < (n - (k + 1) * nb)
            taus = jnp.where(col_live, taus, jnp.zeros_like(taus))
            taus_out = taus_out.at[k].set(taus)
            vtop = jnp.tril(vfull, -1) + jnp.eye(m, nb, dtype=acc.dtype)
            t = larft(vtop, taus)
            v = jnp.where(below[:, None], jnp.roll(vtop, bdy, axis=0), 0)
            vr = jnp.roll(vfull, bdy, axis=0)
            newcol = jnp.where(below[:, None], vr, raw)
            acc = jax.lax.dynamic_update_slice(acc, newcol, (0, k0))
            vt = v @ t
            if cw:
                # mask fused into the chunk body: the full m x m masked
                # trail temp is exactly the buffer this lever exists to
                # avoid materializing
                w = _map_row_chunks(
                    lambda ar, br: tb.mm(
                        jnp.where(br[:, None] & below[None, :], ar, 0), vt),
                    cw, acc, below)
            else:
                trail = jnp.where(below[:, None] & below[None, :], acc, 0)
                w = tb.mm(trail, vt)
            mm = tb.mm(v.conj().T, w)
            x = w - 0.5 * v @ (t.conj().T @ mm)
            vh, xh = v.conj().T, x.conj().T
            if cw:
                acc = _map_row_chunks(
                    lambda ar, xr, vr: ar - tb.mm(xr, vh) - tb.mm(vr, xh),
                    cw, acc, x, v)
            else:
                acc = acc - tb.mm(x, vh) - tb.mm(v, xh)
            return (acc, taus_out), None

        return step

    taus0 = jnp.zeros((npan, nb), dtype=a.dtype)   # npan >= 0 given n > 0
    if npan == 0:
        return a[:n, :n], taus0
    # telescoped segments over the panel count (see cholesky's
    # _telescope_segments): each segment scans the shrinking trailing
    # submatrix, cutting the full-size masked-work premium toward ~1.7x
    taus = taus0
    p_start = 0
    for seg_len in telescope_segments(npan):
        off = p_start
        m_seg = (nt - off) * nb
        sub = a[off * nb:, off * nb:]
        (sub, taus), _ = jax.lax.scan(
            make_step(m_seg, off), (sub, taus),
            jnp.arange(p_start, p_start + seg_len))
        a = a.at[off * nb:, off * nb:].set(sub)
        p_start += seg_len
    return a[:n, :n], taus


# ---------------------------------------------------------------------------
# Distributed
# ---------------------------------------------------------------------------

def _build_dist_red2band(dist, mesh, dtype, band, comm_la=False):
    """Distributed reduction with bandwidth ``band`` <= block size (``band``
    must divide it, so every sub-panel boundary offset is trace-time static).

    Beyond-reference: the reference's distributed variant requires
    band == block size (``miniapp_reduction_to_band.cpp:60``). Here panel p
    covers element columns [p*b, (p+1)*b) — a static width-b slice of one
    tile column — and the elimination boundary (p+1)*b cuts through tiles at
    a static in-tile offset, so tile-level validity masks simply become
    element-level masks; everything else (redundant panel factorization,
    W/M psums, X all_gather) is unchanged from the band == nb scheme.

    ``comm_la`` (``comm_lookahead=1``, docs/comm_overlap.md) pipelines the
    PANEL GATHER across the bulk rank-2 product: once X is formed, the
    next panel's element columns take their rank-2 strip eagerly (the
    exact dots the bulk product would compute for that tile-column slot),
    panel p+1 is gathered (column broadcast + tile-row all_gather),
    QR-factored and written back — all emitted BEFORE panel p's bulk
    ``X V^H + V X^H`` contraction, which then excludes the already-
    applied strip columns. W/M/X themselves stay on the critical path:
    W reads the whole trailing matrix, so no deferral is possible there
    (the same boundary the reference's hemmComputeX chain has). Results
    are bitwise-identical with the knob on or off (same dots, same
    per-cell application order).
    """
    nt = dist.nr_tiles.row
    nb = dist.block_size.row
    n = dist.size.row
    b = band
    npan = ceil_div(n, b) - 1 if n else 0

    def factor_panel(lt, taus_out, p):
        """Gather + redundant QR + T factor + write-back of panel ``p``;
        returns ``(lt, taus_out, (v, t))`` or ``(lt, taus_out, None)``
        when no rank has sub-panel rows."""
        ctx = DistContext(dist)
        bdy = (p + 1) * b              # first eliminated element row
        tc = (p * b) // nb             # tile column holding the panel
        co = (p * b) % nb              # its in-tile column offset

        got = gather_sub_panel(ctx, lt, pb=p * b, b=b, n=n)
        if got is None:
            return lt, taus_out, None
        pan, lu, tr0, ro, row_val_e, g_rows = got
        m_p = (nt - tr0) * nb - ro
        vfull, taus = panel_qr(pan)
        ntau = taus.shape[0]
        if ntau < b:
            taus = jnp.pad(taus, (0, b - ntau))
        # null out reflectors beyond the real row count (zero-padded rows
        # produce tau=0 from panel_qr already; this is belt-and-braces)
        col_live = jnp.arange(b) < (n - bdy)
        taus = jnp.where(col_live, taus, jnp.zeros_like(taus))
        taus_out = taus_out.at[p].set(taus)
        v = jnp.tril(vfull, -1) + jnp.eye(m_p, b, dtype=pan.dtype)
        t = larft(v, taus)

        # -- write the factored panel back (owner column, my rows) --------
        vtiles = pad_sub_panel_to_tiles(ctx, vfull, tr0=tr0, ro=ro)
        sel = jnp.clip(g_rows - tr0, 0, nt - tr0 - 1)
        my_new = vtiles[sel]
        keep = (ctx.rank_c == ctx.owner_c(tc)) & row_val_e
        col_block = lt[lu:, ctx.kc(tc)]
        col_block = col_block.at[:, :, co:co + b].set(
            jnp.where(keep[:, :, None], my_new, col_block[:, :, co:co + b]))
        lt = lt.at[lu:, ctx.kc(tc)].set(col_block)
        return lt, taus_out, (v, t)

    def trailing_ops(lt, p, v, t, strip_next):
        """Panel p's two-sided update UP TO the bulk rank-2 product:
        W/M/X (their psums + the X all_gather are panel p's own latency
        chain) and — when ``strip_next`` — the eager rank-2 strip of the
        NEXT panel's element columns, so the next panel's gather reads
        final values before the bulk is emitted. Returns ``(lt, ops)``;
        ops is None on the no-trailing early-outs."""
        ctx = DistContext(dist)
        from ..common.index2d import GlobalElementIndex
        from ..matrix.views import SubMatrixView

        bdy = (p + 1) * b
        body = SubMatrixView(ctx.dist, GlobalElementIndex(bdy, p * b))
        tr0, ro = body.begin_tile.row, body.origin_in_tile.row
        lu = ctx.row_start(tr0)
        nrows = ctx.ltr - lu
        luc = ctx.col_start(tr0)
        ncols = ctx.ltc - luc
        if ncols == 0 or nrows == 0:
            return lt, None
        arange_nb = jnp.arange(nb)
        g_rows = ctx.g_rows(lu, nrows)
        g_erows = g_rows[:, None] * nb + arange_nb[None, :]
        row_val_e = (g_erows >= bdy) & (g_erows < n)
        sel = jnp.clip(g_rows - tr0, 0, nt - tr0 - 1)
        g_cols = ctx.g_cols(luc, ncols)
        g_ecols = g_cols[:, None] * nb + arange_nb[None, :]
        col_val_e = (g_ecols >= bdy) & (g_ecols < n)       # (ncols, nb)
        selc = jnp.clip(g_cols - tr0, 0, nt - tr0 - 1)

        def tiles_of(mat):
            return pad_sub_panel_to_tiles(ctx, mat, tr0=tr0, ro=ro)

        v_tiles = tiles_of(v)
        vt_tiles = tiles_of(v @ t)
        vtl = jnp.where(col_val_e[:, :, None], vt_tiles[selc],
                        jnp.zeros((ncols, nb, b), dtype=v.dtype))
        atr = lt[lu:, luc:]
        atr = jnp.where((row_val_e[:, None, :, None]
                         & col_val_e[None, :, None, :]), atr,
                        jnp.zeros_like(atr))
        # W partial over my local cols -> psum along 'col' (replicates W
        # rows across each grid row)
        w_loc = tb.contract("rcab,cbd->rad", atr, vtl)
        w_loc = cc.all_reduce(w_loc, COL_AXIS)           # (nrows, nb, b)
        # M = V^H W partial over my rows -> psum along 'row'
        vr = jnp.where(row_val_e[:, :, None], v_tiles[sel],
                       jnp.zeros((nrows, nb, b), dtype=v.dtype))
        m_mat = tb.contract("rab,rad->bd", jnp.conj(vr), w_loc)
        m_mat = cc.all_reduce(m_mat, ROW_AXIS)           # replicated
        x_loc = w_loc - 0.5 * jnp.einsum("rab,bd->rad", vr,
                                         t.conj().T @ m_mat,
                                         preferred_element_type=lt.dtype)
        # full X (ordered) for column-side updates
        xfull = gather_col_panel_ordered(ctx, x_loc, tr0, lu)  # (nt-tr0,..)
        xc = jnp.where(col_val_e[:, :, None], xfull[selc],
                       jnp.zeros((ncols, nb, b), dtype=v.dtype))
        vc = jnp.where(col_val_e[:, :, None], v_tiles[selc],
                       jnp.zeros((ncols, nb, b), dtype=v.dtype))
        xr = jnp.where(row_val_e[:, :, None], x_loc, jnp.zeros_like(x_loc))
        stripped = False
        if strip_next:
            # -- eager strip of the next panel's element columns
            # [bdy, bdy+b): the SAME dots the bulk computes for that
            # tile-column slot (one narrow contraction — bitwise-equal
            # cells), applied before the gather so panel p+1 reads final
            # values; the bulk below masks these columns out
            tc1 = bdy // nb
            co1 = bdy % nb
            idx1 = ctx.kc(tc1) - luc
            own1 = ctx.rank_c == ctx.owner_c(tc1)
            strip_upd = tb.contract("rad,bd->rab", xr, jnp.conj(vc[idx1])) \
                + tb.contract("rad,bd->rab", vr, jnp.conj(xc[idx1]))
            smask = (arange_nb >= co1) & (arange_nb < co1 + b)
            cur = lt[lu:, luc + idx1]
            lt = lt.at[lu:, luc + idx1].set(
                cur - jnp.where(smask[None, None, :] & own1, strip_upd, 0))
            stripped = True
        return lt, (lu, luc, xr, vr, xc, vc, g_ecols, bdy, stripped)

    def apply_bulk(lt, ops):
        """The bulk rank-2 product ``A -= X V^H + V X^H`` over the
        trailing tile grid — emitted AFTER the next panel's collectives
        under ``comm_la``; excludes the eagerly-stripped columns."""
        lu, luc, xr, vr, xc, vc, g_ecols, bdy, stripped = ops
        upd = (tb.contract("rad,cbd->rcab", xr, jnp.conj(vc))
               + tb.contract("rad,cbd->rcab", vr, jnp.conj(xc)))
        if not stripped:
            return lt.at[lu:, luc:].add(-upd)
        notstrip = ~((g_ecols >= bdy) & (g_ecols < bdy + b))   # (ncols, nb)
        return lt.at[lu:, luc:].add(
            -jnp.where(notstrip[None, :, None, :], upd, 0))

    def prog(lt):
        # uniform per-step phase scopes (`red2band.step<p>.<phase>`,
        # docs/observability.md critical-path attribution): panel =
        # factor_panel's gather+QR chain, strip = the W/M/X chain and the
        # eager next-column strip, bulk = the rank-2 trailing product.
        # The comm_la-hoisted factor_panel(p+1) is scoped as step p+1's
        # panel even though it executes inside step p's window.
        taus_out = jnp.zeros((max(npan, 0), b), dtype=lt.dtype)
        if not comm_la:
            for p in range(npan):
                with obs.named_span(f"red2band.step{p:03d}.panel"):
                    lt, taus_out, pq = factor_panel(lt, taus_out, p)
                if pq is None:
                    continue
                with obs.named_span(f"red2band.step{p:03d}.strip"):
                    lt, ops = trailing_ops(lt, p, *pq, strip_next=False)
                if ops is not None:
                    with obs.named_span(f"red2band.step{p:03d}.bulk"):
                        lt = apply_bulk(lt, ops)
            return lt, taus_out
        pq = None
        for p in range(npan):
            if pq is None:
                with obs.named_span(f"red2band.step{p:03d}.panel"):
                    lt, taus_out, pq = factor_panel(lt, taus_out, p)
            if pq is None:
                continue
            strip_next = p + 1 < npan
            with obs.named_span(f"red2band.step{p:03d}.strip"):
                lt, ops = trailing_ops(lt, p, *pq, strip_next=strip_next)
            pq = None
            if ops is None:
                continue
            if strip_next:
                # panel p+1's gather (column broadcast + tile-row
                # all_gather), QR and write-back — emitted BEFORE panel
                # p's bulk rank-2 product
                with obs.named_span(f"red2band.step{p + 1:03d}.panel"):
                    lt, taus_out, pq = factor_panel(lt, taus_out, p + 1)
                if pq is not None:
                    cc.record_overlapped("red2band_dist", ROW_AXIS, 1)
                    cc.record_overlapped("red2band_dist", COL_AXIS, 1)
            with obs.named_span(f"red2band.step{p:03d}.bulk"):
                lt = apply_bulk(lt, ops)
        return lt, taus_out

    def run(lt):
        out, taus = prog(lt)
        return out, taus

    return shard_map(run, mesh=mesh, in_specs=P(ROW_AXIS, COL_AXIS),
                     out_specs=(P(ROW_AXIS, COL_AXIS), P()), check_vma=False)


def _build_dist_red2band_scan(dist, mesh, dtype, band):
    """``lax.scan`` form of the distributed reduction (config
    ``dist_step_mode="scan"``): one compiled panel step looped
    ``ceil(n/b) - 1`` times — by far the framework's worst unrolled
    compile case (config #4 is 127 panels at ~19 s/step on the hardware
    AOT toolchain, docs/DESIGN.md).

    Uniform-shape scheme: the panel's tile column and in-tile offset are
    traced; the window-height masked column is gathered in static global
    order, top-aligned with a traced ``jnp.roll`` (zero rows below a
    Householder panel do not perturb its reflectors, so ``panel_qr`` of the
    rolled (nt_w*nb, b) column equals the shrunken panel's factorization
    zero-padded), and the two-sided update runs over the window's slots
    under traced element masks. TELESCOPED like the scan Cholesky: panel
    ``p`` only touches rows/cols at element index > p*b, so each segment
    works on the trailing window ``lt[lu_off:, lc_off:]`` (slot offsets
    of tile ``(p0*b)//nb``) — the masked uniform work tracks the live
    trailing block instead of paying the full grid every step."""
    nt = dist.nr_tiles.row
    nb = dist.block_size.row
    n = dist.size.row
    Pr, Qc = dist.grid_size.row, dist.grid_size.col
    b = band
    npan = ceil_div(n, b) - 1 if n else 0

    def make_step(lu_off, lc_off, ltr_w, ltc_w):
        """Step body over the window ``full[lu_off:, lc_off:]``; ``base``
        = ``lu_off*P`` is the window's first global tile row, and all
        panel-tile indexing is window-relative (``g - base``)."""
        base = lu_off * Pr

        def step(carry, p):
            lt, taus_out = carry
            ctx = DistContext(dist)
            arange_nb = jnp.arange(nb)

            # -- window-height masked panel column, top-aligned ----------
            pan, bdy, tc, co, row_val_e, g_rows, raw = gather_sub_panel_dyn(
                ctx, lt, p=p, b=b, n=n, row_off=lu_off, col_off=lc_off)
            kc = ctx.kc(tc) - lc_off
            vfull, taus = panel_qr(pan)
            ntau = taus.shape[0]
            if ntau < b:
                taus = jnp.pad(taus, (0, b - ntau))
            col_live = jnp.arange(b) < (n - bdy)
            taus = jnp.where(col_live, taus, jnp.zeros_like(taus))
            taus_out = taus_out.at[p].set(taus)
            m_w = (nt - base) * nb
            v = jnp.tril(vfull, -1) + jnp.eye(m_w, b, dtype=pan.dtype)

            def tiles_of(mat):
                return tiles_of_rolled(ctx, mat, bdy, base * nb)

            # -- write the factored panel back (owner column, my rows) ---
            vtiles = tiles_of(vfull)
            my_new = vtiles[g_rows - base]
            keep = (ctx.rank_c == ctx.owner_c(tc)) & row_val_e
            new = jnp.where(keep[:, :, None], my_new, raw)
            lt = jax.lax.dynamic_update_slice(lt, new[:, None],
                                              (0, kc, 0, co))

            # -- trailing two-sided update over the window's slots -------
            g_cols = ctx.g_cols(lc_off, ltc_w)
            g_ecols = g_cols[:, None] * nb + arange_nb[None, :]
            col_val_e = (g_ecols >= bdy) & (g_ecols < n)
            # col tiles below the window's first row tile are fully above
            # the boundary (masked); clip keeps their indices in range
            selc = jnp.clip(g_cols - base, 0, nt - base - 1)
            t = larft(v, taus)
            v_tiles = tiles_of(v)
            vt_tiles = tiles_of(v @ t)
            vtl = jnp.where(col_val_e[:, :, None], vt_tiles[selc],
                            jnp.zeros((ltc_w, nb, b), dtype=pan.dtype))
            atr = jnp.where((row_val_e[:, None, :, None]
                             & col_val_e[None, :, None, :]), lt,
                            jnp.zeros_like(lt))
            w_loc = tb.contract("rcab,cbd->rad", atr, vtl)
            w_loc = cc.all_reduce(w_loc, COL_AXIS)
            vr = jnp.where(row_val_e[:, :, None], v_tiles[g_rows - base],
                           jnp.zeros((ltr_w, nb, b), dtype=pan.dtype))
            m_mat = tb.contract("rab,rad->bd", jnp.conj(vr), w_loc)
            m_mat = cc.all_reduce(m_mat, ROW_AXIS)
            x_loc = w_loc - 0.5 * jnp.einsum("rab,bd->rad", vr,
                                             t.conj().T @ m_mat,
                                             preferred_element_type=lt.dtype)
            xfull = gather_col_panel_ordered(ctx, x_loc, base, lu_off)
            xc = jnp.where(col_val_e[:, :, None], xfull[selc],
                           jnp.zeros((ltc_w, nb, b), dtype=pan.dtype))
            vc = jnp.where(col_val_e[:, :, None], v_tiles[selc],
                           jnp.zeros((ltc_w, nb, b), dtype=pan.dtype))
            xr = jnp.where(row_val_e[:, :, None], x_loc,
                           jnp.zeros_like(x_loc))
            upd = (tb.contract("rad,cbd->rcab", xr, jnp.conj(vc))
                   + tb.contract("rad,cbd->rcab", vr, jnp.conj(xc)))
            return (lt - upd, taus_out), None

        return step

    def run(lt):
        taus0 = jnp.zeros((max(npan, 0), b), dtype=lt.dtype)
        if npan <= 0:
            return lt, taus0
        _, _, ltr, ltc = storage_tile_grid(dist)

        # telescoped segments over the panel count (slot bounds via
        # uniform_slot_start, the declared single owner)
        def window(pos, _seg_len):
            t_min = (pos * b) // nb
            return (uniform_slot_start(t_min, Pr),
                    uniform_slot_start(t_min, Qc))

        taus = taus0
        for (lu_off, lc_off), p0, seg_len in telescope_windows(npan, window):
            sub = lt[lu_off:, lc_off:]
            # index-free scope: one traced body per telescope segment —
            # critpath reconstructs per-step timing by occurrence order
            (sub, taus), _ = jax.lax.scan(
                obs.scoped_step(
                    "red2band.scanstep",
                    make_step(lu_off, lc_off, ltr - lu_off, ltc - lc_off)),
                (sub, taus), jnp.arange(p0, p0 + seg_len))
            lt = lt.at[lu_off:, lc_off:].set(sub)
        return lt, taus

    return shard_map(run, mesh=mesh, in_specs=P(ROW_AXIS, COL_AXIS),
                     out_specs=(P(ROW_AXIS, COL_AXIS), P()), check_vma=False)


@register_program_cache
@functools.lru_cache(maxsize=32)
def _dist_red2band_cached(dist, mesh, dtype, band, scan=False, donate=False,
                          comm_la=False, route=()):
    # ``route``: the eigensolver's active autotune route as a pure
    # cache-key member (docs/autotune.md) — the trailing gemms read
    # _oz_slices at trace time on the mxu path
    if scan:
        # the scan body's W reads the whole trailing matrix every
        # iteration, so the panel gather cannot be hoisted across the
        # previous bulk there (documented exception, docs/comm_overlap.md)
        built = _build_dist_red2band_scan(dist, mesh, dtype, band)
    else:
        built = _build_dist_red2band(dist, mesh, dtype, band,
                                     comm_la=comm_la)
    return jax.jit(built, **donate_argnums_kw(donate, 0))


# ---------------------------------------------------------------------------
# Public API (reference eigensolver/reduction_to_band.h)
# ---------------------------------------------------------------------------

def reduction_to_band(a: Matrix, band_size: int | None = None, *,
                      donate: bool = False,
                      route: tuple = ()) -> BandReduction:
    """Reduce Hermitian ``a`` (FULL storage — both triangles) to band form.

    ``band_size`` (default: block size) sets the bandwidth; it must divide
    the block size (reference ``reduction_to_band.h:84``). Both the local
    AND the distributed variant accept ``band_size < block size`` — the
    distributed case goes beyond the reference, whose distributed variant
    requires band == block size (``miniapp_reduction_to_band.cpp:60``).
    Smaller bands shift work from the host bulge-chasing stage (O(n^2 b))
    into this stage's device gemms — the standard two-stage tradeoff knob.

    ``donate=True`` donates ``a``'s device storage to the reduction (the
    reference's in-place semantics — its ``mat_a`` holds V/R on return);
    ``a`` must not be used afterwards. One full-matrix HBM buffer off the
    peak live set; internal stage hand-offs are always donated.
    """
    dlaf_assert(a.size.row == a.size.col, "reduction_to_band: square only")
    dlaf_assert(a.block_size.row == a.block_size.col, "square blocks only")
    nb = a.block_size.row
    band = nb if band_size is None else band_size
    dlaf_assert(band >= 1, f"reduction_to_band: band_size must be >= 1, got {band}")
    dlaf_assert(nb % band == 0,
                f"reduction_to_band: block size {nb} not divisible by band_size {band}"
                " (reference reduction_to_band.h:84)")
    from ..config import resolve_step_mode

    # the traced step count is the PANEL count: the builders run
    # ceil(n/band) - 1 panel steps (the last panel has no trailing block)
    steps = max(-(-a.size.row // band) - 1, 1)
    from ..types import total_ops

    n = a.size.row
    # reference flop model (miniapp_reduction_to_band): 2n^3/3 muls+adds
    entry_span = obs.entry_span("reduction_to_band", lambda: dict(
        flops=total_ops(np.dtype(a.dtype), 2 * n**3 / 3, 2 * n**3 / 3),
        n=n, nb=nb, band=band, dtype=np.dtype(a.dtype).name,
        grid=f"{a.dist.grid_size.row}x{a.dist.grid_size.col}"))
    if a.grid is None or a.grid.num_devices == 1:
        with entry_span, quiet_donation():
            g = to_global(a.storage, a.dist, donate)
            # program telemetry (DLAF_PROGRAM_TELEMETRY): off = passthrough
            if resolve_step_mode(steps) == "scan":
                out, taus = obs.telemetry.call(
                    "reduction_to_band.local_scan", _red2band_local_scan,
                    g, nb=band, route=route)
            else:
                out, taus = obs.telemetry.call(
                    "reduction_to_band.local", _red2band_local, g, nb=band,
                    route=route)
            return BandReduction(
                a.with_storage(global_to_tiles_donated(out, a.dist)),
                taus, band)
    from ..config import resolved_comm_lookahead

    scan_mode = resolve_step_mode(steps) == "scan"
    fn = _dist_red2band_cached(a.dist, a.grid.mesh, np.dtype(a.dtype).name,
                               band,
                               scan=scan_mode,
                               donate=donate,
                               # the unrolled builder pipelines the panel
                               # gather across the bulk rank-2 product
                               # (docs/comm_overlap.md); no compute-carry
                               # prerequisite here — the knob acts alone
                               comm_la=not scan_mode
                               and resolved_comm_lookahead(), route=route)
    with entry_span, quiet_donation():
        storage, taus = obs.telemetry.call("reduction_to_band.dist", fn,
                                           a.storage)
    return BandReduction(a.with_storage(storage), taus, band)


@register_program_cache
@functools.lru_cache(maxsize=32)
def _band_extract_cached(dist, b: int):
    """Device program gathering ONLY the band diagonals from tile storage.

    The reference copies the band tile by tile into compact storage
    (``band_to_tridiag/mc.h:91-270`` ``BandBlock::copyDiag/copyOffDiag``)
    instead of materializing the full matrix; this is the TPU analog — the
    band lives in the diagonal tiles plus the first sub-diagonal tiles, so
    one small gather program produces the (b+1, n) 'sb' panel and the
    host transfer is O(n*b), not O(n^2)."""
    from ..matrix.tiling import global_tile_to_storage_index

    nt = dist.nr_tiles.row
    nb = dist.block_size.row
    n = dist.size.row
    di = np.array([global_tile_to_storage_index(dist, i, i)
                   for i in range(nt)], dtype=np.int32)
    si = np.array([global_tile_to_storage_index(dist, i + 1, i)
                   for i in range(nt - 1)], dtype=np.int32).reshape(-1, 2)
    rr = np.arange(b + 1)[:, None] + np.arange(nb)[None, :]   # row = c + r
    cc = np.broadcast_to(np.arange(nb), (b + 1, nb))
    in_diag = rr < nb       # else the entry lives in the sub-diagonal tile
    rd = np.where(in_diag, rr, 0)
    rs = np.where(in_diag, 0, rr - nb)

    def fn(storage):
        diag = storage[di[:, 0], di[:, 1]]                    # (nt, nb, nb)
        if nt > 1:
            sub = storage[si[:, 0], si[:, 1]]                 # (nt-1, nb, nb)
            sub = jnp.concatenate([sub, jnp.zeros_like(sub[:1])], axis=0)
        else:
            sub = jnp.zeros_like(diag)
        fd = diag[:, rd, cc]                                  # (nt, b+1, nb)
        fs = sub[:, rs, cc]
        tiles = jnp.where(jnp.asarray(in_diag)[None], fd, fs)
        return jnp.moveaxis(tiles, 0, 1).reshape(b + 1, nt * nb)[:, :n]

    return jax.jit(fn)


def extract_band(red: BandReduction) -> np.ndarray:
    """Host-side compact band storage from the reduced matrix:
    ``band[r, j] = A[j+r, j]`` for r = 0..band (lower band, LAPACK 'sb'
    layout, shape (band+1, n)). Only band diagonals are read — the V
    reflectors stored below the band are not part of the band matrix.

    The gather runs on device (:func:`_band_extract_cached`), so only the
    O(n*band) band panel crosses to the host — never the O(n^2) matrix
    (round-1 review item; reference ``band_to_tridiag/mc.h:91-270``)."""
    n = red.matrix.size.row
    b = red.band
    if n == 0:
        return np.zeros((b + 1, 0), dtype=red.matrix.dtype)
    fn = _band_extract_cached(red.matrix.dist, b)
    return np.asarray(fn(red.matrix.storage))
