"""Reduction of a Hermitian matrix to band form (bandwidth = block size).

TPU-native counterpart of the reference's ``eigensolver/reduction_to_band``
(``api.h:18-22``, ``impl.h``; band = blockSize) plus the QR T-factor
(``factorization/qr/t_factor_impl.h:42-347``). The reference computes panel
reflectors column-by-column with dot/scal/gemv/ger micro-kernels on the CPU
(even for its GPU backend, ``impl.h:543-589``) and distributes the panel work
with per-column all-reduces. The TPU-native design replaces all of that with
dense MXU primitives:

* panel reflectors: ONE ``geqrf`` (XLA's blocked Householder QR) on the whole
  panel — no column loop, no host round-trip;
* T factor: closed-form ``larft`` (one gemm + small triangular solve);
* trailing two-sided update: W = A (V T); M = V^H W; X = W - 1/2 V (T^H M);
  A <- A - X V^H - V X^H — three big gemms (the reference's hemmComputeX /
  gemmComputeW2 / gemmUpdateX / her2kUpdateTrailingMatrix fused into batched
  einsums).
* distributed: the panel is all-gathered along the row axis (nb columns —
  cheap), factored redundantly on every rank, and the update runs as local
  einsums + psum partial sums over the mesh axes.

The trailing matrix is kept FULL Hermitian during the sweep (both triangles
updated); on return the matrix holds the band (diagonal blocks + upper-
triangular subdiagonal R blocks) with the Householder vectors V stored below
the band (LAPACK-style), plus the tau coefficients — exactly what the
band->tridiag stage and back-transform consume.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from jax._src.lax.linalg import geqrf  # public in newer jax; stable primitive

from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..common.asserts import dlaf_assert
from ..matrix.matrix import Matrix
from ..matrix.panel import DistContext, gather_col_panel_ordered
from ..matrix.tiling import global_to_tiles, tiles_to_global
from ..tile_ops import blas as tb
from ..tile_ops.lapack import larft
from ..types import ceil_div


@dataclasses.dataclass
class BandReduction:
    """Result: band+V matrix, taus (nt-1, nb), and the bandwidth."""

    matrix: Matrix
    taus: jax.Array  # (nt-1, nb), zero-padded
    band: int


# ---------------------------------------------------------------------------
# Local
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("nb",))
def _red2band_local(a, *, nb: int):
    """Panels of width ``nb`` = the target bandwidth (any 1 <= nb <= n; the
    reference's local variant likewise supports band_size < block size,
    ``reduction_to_band.h:78-87`` with ``mb % band_size == 0``)."""
    n = a.shape[0]
    nt = ceil_div(n, nb) if n else 0
    taus_out = jnp.zeros((max(nt - 1, 0), nb), dtype=a.dtype)
    for k in range(nt - 1):
        k0, k1 = k * nb, (k + 1) * nb
        m_p = n - k1
        panel = a[k1:, k0:k1]
        vfull, taus = geqrf(panel)
        a = a.at[k1:, k0:k1].set(vfull)          # R in upper part, V below
        ntau = taus.shape[0]
        taus_out = taus_out.at[k, :ntau].set(taus)
        v = jnp.tril(vfull, -1) + jnp.eye(m_p, nb, dtype=a.dtype)
        if ntau < nb:
            taus = jnp.pad(taus, (0, nb - ntau))
        t = larft(v, taus)
        trail = a[k1:, k1:]                       # full Hermitian
        w = trail @ (v @ t)                       # A V T
        m = v.conj().T @ w                        # V^H W  (pw x pw)
        x = w - 0.5 * v @ (t.conj().T @ m)
        a = a.at[k1:, k1:].set(trail - x @ v.conj().T - v @ x.conj().T)
    return a, taus_out


# ---------------------------------------------------------------------------
# Distributed
# ---------------------------------------------------------------------------

def _build_dist_red2band(dist, mesh, dtype):
    nt = dist.nr_tiles.row
    nb = dist.block_size.row
    n = dist.size.row

    def full_col_panel(ctx, tiles, k1):
        """All panel tiles (global tile rows k1..nt-1, ordered) on every rank
        (shared helper; ``tiles``: my local row tiles of the panel column,
        already col-broadcast, slots lu.. covering rows >= k1)."""
        return gather_col_panel_ordered(ctx, tiles, k1, ctx.ltr - tiles.shape[0])

    def step(lt, taus_out, k):
        ctx = DistContext(dist)
        k1 = k + 1
        lu = ctx.row_start(k1)
        nrows = ctx.ltr - lu
        g_rows = ctx.g_rows(lu, nrows)
        row_valid = (g_rows >= k1) & (g_rows < nt)

        # -- gather the full panel, factor redundantly ----------------------
        mine = lt[lu:, ctx.kc(k)]
        mine = jnp.where(row_valid[:, None, None], mine, jnp.zeros_like(mine))
        mine = cc.bcast(mine, COL_AXIS, ctx.owner_c(k))
        ptiles = full_col_panel(ctx, mine, k1)          # (nt-k1, nb, nb)
        m_p = (nt - k1) * nb
        pan = ptiles.reshape(m_p, nb)
        vfull, taus = geqrf(pan)
        ntau = taus.shape[0]
        if ntau < nb:
            taus = jnp.pad(taus, (0, nb - ntau))
        # null out reflectors beyond the real row count (zero-padded rows
        # produce tau=0 from geqrf already; this is belt-and-braces)
        real_rows = n - k1 * nb
        col_live = jnp.arange(nb) < real_rows
        taus = jnp.where(col_live, taus, jnp.zeros_like(taus))
        taus_out = taus_out.at[k].set(taus)
        v = jnp.tril(vfull, -1) + jnp.eye(m_p, nb, dtype=pan.dtype)
        t = larft(v, taus)

        # -- write the factored panel back (owner column, my rows) ----------
        vtiles = vfull.reshape(nt - k1, nb, nb)
        sel = jnp.clip(g_rows - k1, 0, nt - k1 - 1)
        my_new = vtiles[sel]
        keep = ((ctx.rank_c == ctx.owner_c(k)) & row_valid)[:, None, None]
        lt = lt.at[lu:, ctx.kc(k)].set(jnp.where(keep, my_new, lt[lu:, ctx.kc(k)]))

        # -- trailing update ------------------------------------------------
        luc = ctx.col_start(k1)
        ncols = ctx.ltc - luc
        if ncols == 0 or nrows == 0:
            return lt, taus_out
        g_cols = ctx.g_cols(luc, ncols)
        col_valid = (g_cols >= k1) & (g_cols < nt)
        vt = (v @ t).reshape(nt - k1, nb, nb)
        vtl = jnp.where(col_valid[:, None, None],
                        vt[jnp.clip(g_cols - k1, 0, nt - k1 - 1)],
                        jnp.zeros((ncols, nb, nb), dtype=pan.dtype))
        atr = lt[lu:, luc:]
        atr = jnp.where((row_valid[:, None] & col_valid[None, :])[:, :, None, None],
                        atr, jnp.zeros_like(atr))
        # W partial over my local cols -> psum along 'col' (replicates W rows
        # across each grid row)
        w_loc = jnp.einsum("rcab,cbd->rad", atr, vtl,
                           preferred_element_type=atr.dtype)
        w_loc = cc.all_reduce(w_loc, COL_AXIS)           # (nrows, nb, pw)
        # M = V^H W partial over my rows -> psum along 'row'
        vr = jnp.where(row_valid[:, None, None],
                       v.reshape(nt - k1, nb, nb)[jnp.clip(g_rows - k1, 0, nt - k1 - 1)],
                       jnp.zeros((nrows, nb, nb), dtype=pan.dtype))
        m_mat = jnp.einsum("rab,rad->bd", jnp.conj(vr), w_loc,
                           preferred_element_type=atr.dtype)
        m_mat = cc.all_reduce(m_mat, ROW_AXIS)           # replicated everywhere
        x_loc = w_loc - 0.5 * jnp.einsum("rab,bd->rad", vr,
                                         t.conj().T @ m_mat,
                                         preferred_element_type=atr.dtype)
        # full X (ordered) for column-side updates
        xfull = cc.all_gather(x_loc, ROW_AXIS).reshape(ctx.P * nrows, nb, nb)
        order = []
        for g in range(k1, nt):
            p = (dist.source_rank.row + g) % ctx.P
            order.append(p * nrows + (g // ctx.P - lu))
        xfull = xfull[jnp.array(order, dtype=jnp.int32)]  # (nt-k1, nb, nb)
        xc = jnp.where(col_valid[:, None, None],
                       xfull[jnp.clip(g_cols - k1, 0, nt - k1 - 1)],
                       jnp.zeros((ncols, nb, nb), dtype=pan.dtype))
        vc = jnp.where(col_valid[:, None, None],
                       v.reshape(nt - k1, nb, nb)[jnp.clip(g_cols - k1, 0, nt - k1 - 1)],
                       jnp.zeros((ncols, nb, nb), dtype=pan.dtype))
        xr = jnp.where(row_valid[:, None, None], x_loc,
                       jnp.zeros_like(x_loc))
        upd = (jnp.einsum("rad,cbd->rcab", xr, jnp.conj(vc),
                          preferred_element_type=atr.dtype)
               + jnp.einsum("rad,cbd->rcab", vr, jnp.conj(xc),
                            preferred_element_type=atr.dtype))
        pair = (row_valid[:, None] & col_valid[None, :])[:, :, None, None]
        upd = jnp.where(pair, upd, jnp.zeros_like(upd))
        lt = lt.at[lu:, luc:].add(-upd)
        return lt, taus_out

    def prog(lt):
        taus_out = jnp.zeros((max(nt - 1, 0), nb), dtype=lt.dtype)
        for k in range(nt - 1):
            lt, taus_out = step(lt, taus_out, k)
        return lt, taus_out

    def run(lt):
        out, taus = prog(lt)
        return out, taus

    return shard_map(run, mesh=mesh, in_specs=P(ROW_AXIS, COL_AXIS),
                     out_specs=(P(ROW_AXIS, COL_AXIS), P()), check_vma=False)


@functools.lru_cache(maxsize=32)
def _dist_red2band_cached(dist, mesh, dtype):
    return jax.jit(_build_dist_red2band(dist, mesh, dtype))


# ---------------------------------------------------------------------------
# Public API (reference eigensolver/reduction_to_band.h)
# ---------------------------------------------------------------------------

def reduction_to_band(a: Matrix, band_size: int | None = None) -> BandReduction:
    """Reduce Hermitian ``a`` (FULL storage — both triangles) to band form.

    ``band_size`` (default: block size) sets the bandwidth; like the
    reference (``reduction_to_band.h:78-87``) the local variant accepts any
    ``band_size`` dividing the block size, while the distributed variant
    supports only ``band_size == block size`` (the reference raises the same
    restriction, ``miniapp_reduction_to_band.cpp:60``). Smaller bands shift
    work from the host bulge-chasing stage (O(n^2 b)) into this stage's
    device gemms — the standard two-stage tradeoff knob.
    """
    dlaf_assert(a.size.row == a.size.col, "reduction_to_band: square only")
    dlaf_assert(a.block_size.row == a.block_size.col, "square blocks only")
    nb = a.block_size.row
    band = nb if band_size is None else band_size
    dlaf_assert(band >= 1, f"reduction_to_band: band_size must be >= 1, got {band}")
    dlaf_assert(nb % band == 0,
                f"reduction_to_band: block size {nb} not divisible by band_size {band}"
                " (reference reduction_to_band.h:84)")
    if a.grid is None or a.grid.num_devices == 1:
        g = tiles_to_global(a.storage, a.dist)
        out, taus = _red2band_local(g, nb=band)
        return BandReduction(a.with_storage(global_to_tiles(out, a.dist)),
                             taus, band)
    dlaf_assert(band == nb,
                "reduction_to_band: distributed variant supports only "
                "band_size == block size (same restriction as the reference)")
    fn = _dist_red2band_cached(a.dist, a.grid.mesh, np.dtype(a.dtype).name)
    storage, taus = fn(a.storage)
    return BandReduction(a.with_storage(storage), taus, nb)


def extract_band(red: BandReduction) -> np.ndarray:
    """Host-side compact band storage from the reduced matrix:
    ``band[r, j] = A[j+r, j]`` for r = 0..band (lower band, LAPACK 'sb'
    layout, shape (band+1, n)). Only band diagonals are read — the V
    reflectors stored below the band are not part of the band matrix."""
    a = red.matrix.to_numpy()
    n = a.shape[0]
    b = red.band
    band = np.zeros((b + 1, n), dtype=a.dtype)
    for r in range(b + 1):
        band[r, : n - r] = np.diagonal(a, -r)
    return band
