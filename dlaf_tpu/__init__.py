"""dlaf_tpu — TPU-native distributed dense linear algebra.

A brand-new framework with the capabilities of DLA-Future (ETH-CSCS), rebuilt
idiomatically for TPUs: JAX/XLA compute, a 2D ``jax.sharding.Mesh`` with ICI
collectives in place of the MPI communicator grid, block-cyclic tile storage
in HBM, and host-C++ components for the inherently sequential stages. See
``SURVEY.md`` at the repo root for the layer-by-layer mapping to the reference.

Layer map (reference → here):
  L1 foundations      → :mod:`dlaf_tpu.types`, :mod:`dlaf_tpu.common`
  L2 runtime glue     → :mod:`dlaf_tpu.config` (+ XLA program order)
  L3 matrix model     → :mod:`dlaf_tpu.matrix`
  L4 communication    → :mod:`dlaf_tpu.comm`
  L5 tile kernels     → :mod:`dlaf_tpu.tile_ops`
  L6 algorithms       → :mod:`dlaf_tpu.algorithms`, :mod:`dlaf_tpu.eigensolver`
  L7 miniapps         → :mod:`dlaf_tpu.miniapp`
"""

from . import obs  # noqa: F401  (observability layer; docs/observability.md)
from .config import Configuration, finalize, get_configuration, initialize
from .types import Backend, Device, SizeType, total_ops

__version__ = "0.1.0"
