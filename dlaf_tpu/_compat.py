"""Version shims for the JAX surface this framework sits on.

The algorithms were written against the modern ``jax.shard_map`` export
(whose replication check is spelled ``check_vma``); older installations —
including the jax 0.4.x line this container ships — only have
``jax.experimental.shard_map.shard_map`` with the same semantics under the
``check_rep`` spelling. Every ``shard_map`` consumer in the tree imports
from here so the version probe happens exactly once.
"""

from __future__ import annotations

import functools
import inspect

try:  # modern export (jax >= 0.6)
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - exercised on the 0.4.x container
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
_HAS_CHECK_VMA = "check_vma" in _PARAMS
_HAS_CHECK_REP = "check_rep" in _PARAMS


def axis_size(axis):
    """``lax.axis_size`` where available; the classic ``psum(1, axis)``
    constant-fold on JAX versions predating the explicit primitive."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


@functools.wraps(_shard_map)
def shard_map(f, *args, **kwargs):
    """``jax.shard_map`` with the ``check_vma`` spelling accepted on every
    JAX version (mapped to ``check_rep`` where that is the installed
    name; dropped if the installed API has neither)."""
    if "check_vma" in kwargs and not _HAS_CHECK_VMA:
        check = kwargs.pop("check_vma")
        if _HAS_CHECK_REP:
            kwargs["check_rep"] = check
    return _shard_map(f, *args, **kwargs)
