"""Layered runtime configuration: defaults < user struct < env < CLI.

TPU-native counterpart of the reference's ``configuration`` struct
(``init.h:28-34``) and its layering logic (``src/init.cpp:117-177``): every
field has a built-in default, can be overridden by a user-supplied
``Configuration``, then by a ``DLAF_<NAME>`` environment variable, then by a
``--dlaf:<name>=<value>`` command-line option. ``dlaf:print-config`` mirrors
``--dlaf:print-config`` (``src/init.cpp:190-194``).

The reference's fields are CUDA-stream/umpire-pool counts; the TPU runtime has
no user-managed streams or pools (PJRT owns both), so the fields here are the
knobs this framework actually honors.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence


@dataclasses.dataclass
class Configuration:
    """Runtime knobs (analog of reference ``init.h:28-34``)."""

    #: Print the final configuration at initialize() (``--dlaf:print-config``).
    print_config: bool = False
    #: Rank ordering when building a grid from a flat device list
    #: ("row-major" | "col-major"), reference CommunicatorGrid ctor option.
    grid_ordering: str = "row-major"
    #: Implementation of the band->tridiag bulge chasing stage:
    #: "native" (C++ via ctypes) with automatic fallback to "numpy".
    band_to_tridiag_impl: str = "native"
    #: Worker threads for the native chase's pipelined sweeps (the
    #: reference's SweepWorker pipeline, band_to_tridiag/mc.h:362-380):
    #: 0 = auto (CPU count), 1 = sequential. Any count gives bitwise
    #: identical results (pipelined windows are disjoint).
    chase_threads: int = 0
    #: Host secular-equation solver in the D&C merge: "native" (C++
    #: safeguarded Newton, the laed4 analog) with fallback to "numpy"
    #: (vectorized bisection).
    secular_impl: str = "native"
    #: Deflated-merge size above which the D&C secular solve + z-refinement
    #: run on the device (see eigensolver/tridiag_solver.py; the threshold
    #: drops automatically when the native host solver failed to build).
    #: The reference's look-ahead/round-robin workspace knobs
    #: (``factorization/cholesky/impl.h:187-189``) have no analog here:
    #: XLA sees the whole step DAG at compile time and owns the overlap.
    secular_device_min_k: int = 4096
    #: Local Cholesky trailing-update strategy: "loop" (exact-flop per-column
    #: herk/gemm, the reference's task shape), "biggemm" (ONE masked full
    #: trailing gemm per step — 2x flops on the strict triangle but a single
    #: large MXU op), "invgemm" (biggemm + panel formed by gemm against the
    #: explicit inverse of the diagonal factor instead of a triangular
    #: solve), or "xla" (delegate the whole local factorization to XLA's
    #: fused native cholesky). Benchmarked per hardware; see bench.py.
    cholesky_trailing: str = "loop"
    #: bt_band_to_tridiag reflector application: "blocked" (compact-WY
    #: staircase groups -> larft + two gemms per step level, the MXU form of
    #: the reference's b x b HH re-tiling) or "sweeps" (one batched rank-1
    #: segment update per sweep).
    bt_b2t_impl: str = "blocked"
    #: Sweeps per compact-WY group for bt_b2t_impl="blocked"; 0 = auto
    #: (band size on MXU hardware, min(band, 64) on CPU). Clamped to
    #: [1, min(band+1, n_sweeps)] — band+1 is the disjointness bound of the
    #: blocked level reordering.
    bt_b2t_group: int = 0
    #: Enable float64/complex128 support (sets jax_enable_x64).
    enable_x64: bool = True
    #: When non-empty, miniapps emit XLA/PJRT execution profiles
    #: (jax.profiler traces with named phases) into this directory
    #: (the green-field tracing hook SURVEY §5 calls for).
    profile_dir: str = ""

    def _fields(self):
        return {f.name: f for f in dataclasses.fields(self)}


def _parse(value: str, typ):
    if typ is bool:
        return value.strip().lower() in ("1", "true", "yes", "on")
    return typ(value)


def update_configuration(
    user: Optional[Configuration] = None,
    argv: Optional[Sequence[str]] = None,
) -> Configuration:
    """Resolve the effective configuration.

    Precedence (highest wins), mirroring ``src/init.cpp:117-156``:
    CLI ``--dlaf:<name>=<v>`` > env ``DLAF_<NAME>`` > ``user`` struct > default.
    """
    cfg = dataclasses.replace(user) if user is not None else Configuration()
    fields = cfg._fields()
    for name, f in fields.items():
        env = os.environ.get("DLAF_" + name.upper())
        if env is not None:
            setattr(cfg, name, _parse(env, f.type if isinstance(f.type, type) else type(f.default)))
    if argv:
        for arg in argv:
            if not arg.startswith("--dlaf:"):
                continue
            body = arg[len("--dlaf:"):]
            if "=" in body:
                key, val = body.split("=", 1)
            else:
                key, val = body, "true"
            key = key.replace("-", "_")
            if key in fields:
                f = fields[key]
                setattr(cfg, key, _parse(val, f.type if isinstance(f.type, type) else type(f.default)))
    return cfg


_active: Optional[Configuration] = None


def initialize(user: Optional[Configuration] = None,
               argv: Optional[Sequence[str]] = None) -> Configuration:
    """Bring up the runtime (analog of ``dlaf::initialize``, ``init.h:60-75``).

    Resolves configuration and applies process-wide JAX settings (x64). Safe
    to call more than once; later calls re-resolve configuration.
    """
    global _active
    cfg = update_configuration(user, argv)
    if cfg.enable_x64:
        import jax

        jax.config.update("jax_enable_x64", True)
    if cfg.print_config:
        print(cfg)
    _active = cfg
    return cfg


def get_configuration() -> Configuration:
    """Active configuration, initializing with defaults on first use."""
    global _active
    if _active is None:
        _active = initialize()
    return _active


def finalize() -> None:
    """Tear down (analog of ``dlaf::finalize``); PJRT owns real resources."""
    global _active
    _active = None
