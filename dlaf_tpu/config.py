"""Layered runtime configuration: defaults < user struct < env < CLI.

TPU-native counterpart of the reference's ``configuration`` struct
(``init.h:28-34``) and its layering logic (``src/init.cpp:117-177``): every
field has a built-in default, can be overridden by a user-supplied
``Configuration``, then by a ``DLAF_<NAME>`` environment variable, then by a
``--dlaf:<name>=<value>`` command-line option. ``dlaf:print-config`` mirrors
``--dlaf:print-config`` (``src/init.cpp:190-194``).

The reference's fields are CUDA-stream/umpire-pool counts; the TPU runtime has
no user-managed streams or pools (PJRT owns both), so the fields here are the
knobs this framework actually honors.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence


@dataclasses.dataclass
class Configuration:
    """Runtime knobs (analog of reference ``init.h:28-34``)."""

    #: Print the final configuration at initialize() (``--dlaf:print-config``).
    print_config: bool = False
    #: Rank ordering when building a grid from a flat device list
    #: ("row-major" | "col-major"), reference CommunicatorGrid ctor option.
    grid_ordering: str = "row-major"
    #: Implementation of the band->tridiag bulge chasing stage:
    #: "native" (C++ via ctypes) with automatic fallback to "numpy".
    band_to_tridiag_impl: str = "native"
    #: Worker threads for the native chase's pipelined sweeps (the
    #: reference's SweepWorker pipeline, band_to_tridiag/mc.h:362-380):
    #: 0 = auto (CPU count), 1 = sequential. Any count gives bitwise
    #: identical results (pipelined windows are disjoint).
    chase_threads: int = 0
    #: Host secular-equation solver in the D&C merge: "native" (C++
    #: safeguarded Newton, the laed4 analog) with fallback to "numpy"
    #: (vectorized bisection).
    secular_impl: str = "native"
    #: Deflated-merge size above which the D&C secular solve + z-refinement
    #: run on the device (see eigensolver/tridiag_solver.py; the threshold
    #: drops automatically when the native host solver failed to build).
    #: 0 = auto (default): 4096 on TPU (device = MXU-backed batched math),
    #: device-disabled on CPU — the round-4 sweep (BASELINE.md: n=16384 at
    #: thr 2048/4096/8192/host-only -> 218/135/81/66 s, identical
    #: residuals) shows the CPU backend's "device" route loses to the
    #: native host solver at every size. The reference's
    #: look-ahead/round-robin workspace knobs
    #: (``factorization/cholesky/impl.h:187-189``) have no analog here:
    #: XLA sees the whole step DAG at compile time and owns the overlap.
    secular_device_min_k: int = 0
    #: Local Cholesky trailing-update strategy: "loop" (exact-flop per-column
    #: herk/gemm, the reference's task shape), "biggemm" (ONE masked full
    #: trailing gemm per step — 2x flops on the strict triangle but a single
    #: large MXU op), "invgemm" (biggemm + panel formed by gemm against the
    #: explicit inverse of the diagonal factor instead of a triangular
    #: solve), or "xla" (delegate the whole local factorization to XLA's
    #: fused native cholesky), or "scan" (lax.scan'd uniform step: one
    #: compiled step body looped nt times — O(1) compile time and carry
    #: buffer reuse at ~3x the exact trailing flops; the compile/HBM
    #: escape hatch at large tile counts, algorithms/cholesky.py). Also
    #: "ozaki" (error-free int8-slice trailing on the MXU) and "auto"
    #: (default): ozaki on TPU — the measured winner every silicon
    #: session (112.8/351.0 GF/s at N=4096/8192 vs 42-47 for the other
    #: forms, 2026-08-01) — and loop elsewhere.
    #: Benchmarked per hardware; see bench.py.
    cholesky_trailing: str = "auto"
    #: Look-ahead (software-pipelined) step formulation for the blocked
    #: Cholesky (and the analogous panel-chain splits in the triangular
    #: scan solve and blocked HEGST): "0" = the plain right-looking step
    #: order, "1" = split every trailing update into "next panel column
    #: first" + "rest of trailing" so panel k+1's potrf/trsm chain
    #: consumes the carried next-column values directly and the bulk
    #: herk/gemm of step k runs concurrently with it (the reference's
    #: high-priority first-column herk + round-robin panel workspaces,
    #: ``factorization/cholesky/impl.h:147-156,187-189``, expressed as
    #: program structure for XLA's scheduler: unrolled forms carry the
    #: next column between steps, scan forms defer the bulk update one
    #: iteration so it overlaps the next latency-bound panel chain).
    #: "auto" (default): 1 on TPU — per-step critical-path latency, not
    #: flops, dominates blocked factorizations there (N=4096 at 133 GF/s
    #: vs N=16384 at 514 is the latency-bound-panel signature) — and 0
    #: elsewhere. Results are bitwise-identical either way on the native
    #: routes (same tile ops, same per-cell application order; enforced
    #: by tests/test_cholesky.py lookahead A/Bs). See docs/lookahead.md.
    cholesky_lookahead: str = "auto"
    #: Communication look-ahead for the distributed builders
    #: (docs/comm_overlap.md): "1" extends the ``cholesky_lookahead``
    #: pipeline across the COLLECTIVES — step k+1's panel broadcast /
    #: all-gather (and the fused diag ``bcast2d``) are emitted BEFORE
    #: step k's bulk trailing product, so XLA's async collective
    #: start/done pairs can run the ICI transfer while the MXU grinds
    #: the bulk gemms (the reference hides the same transfer behind the
    #: trailing update via sender pipelines, ``broadcast_panel.h`` +
    #: ``impl.h:147-156``; arXiv:2112.09017 measures this overlap as the
    #: difference between latency-bound and MXU-bound distributed
    #: factorizations on TPU pods). "0" keeps the plain per-step
    #: emission order. "auto" (default): 1 on TPU, 0 elsewhere. In the
    #: unrolled builders the hoist rides the PR-2 SSA carry, so it only
    #: takes effect when ``cholesky_lookahead`` also resolves 1 (the
    #: scan builders' deferred-bulk bodies already emit their
    #: collectives ahead of the deferred product — there the knob labels
    #: the structure rather than changing it); the distributed
    #: reduction_to_band builder pipelines its panel all-gather under
    #: this knob alone. Results are bitwise-identical either way on the
    #: native routes (same collectives, same payloads, same per-cell
    #: application order; pinned by the comm A/Bs in tests/).
    comm_lookahead: str = "auto"
    #: Level-batched divide-and-conquer merge execution in the tridiagonal
    #: eigensolver (eigensolver/tridiag_solver.py, docs/eigensolver_perf.md):
    #: every merge within one D&C tree level is independent, and "1" runs
    #: all same-shape merges of a level as ONE vmapped device dispatch
    #: (secular solve, qc assembly, Q·C apply) with small merges padded to
    #: the group's max deflated-size bucket — the batch-many-small-problems
    #: idiom arXiv:2112.09017 credits TPU MXU utilization to — while the
    #: host control scan of the next group overlaps the dispatched device
    #: work. "0" walks the tree one merge at a time (the recursive
    #: reference order, ``merge.h:790-887``). "auto" (default): 1 on TPU
    #: (the serialized walk is dispatch-bound there: every small merge
    #: pays a full host->device round trip), 0 elsewhere. Results match
    #: the serialized walk bitwise on the host-secular route; the
    #: device-secular route re-buckets to the group's max k, whose padded
    #: zero terms may reassociate at <= 1 ulp (docs/eigensolver_perf.md
    #: exception table). Counted per level in
    #: ``dlaf_dc_merges_total{mode=batched|serialized}``.
    dc_level_batch: str = "auto"
    #: Look-ahead for the reflector-block back-transform
    #: (bt_reduction_to_band, local + distributed): "1" emits reflector
    #: block k+1's larft/T-factor chain — and, distributed, its panel
    #: gather collectives — BEFORE block k's bulk trmm+gemm application,
    #: so the latency-bound T factor and the ICI transfer hide under the
    #: MXU bulk exactly like ``cholesky_lookahead``/``comm_lookahead`` do
    #: for the factorizations (docs/lookahead.md, docs/comm_overlap.md).
    #: "0" keeps the plain per-block emission order. "auto" (default): 1
    #: on TPU, 0 elsewhere. Bitwise identical either way (the T chain
    #: reads only the constant reflector storage — a pure emission
    #: reorder); hoisted collectives count under
    #: ``dlaf_comm_overlapped_total{algo="bt_r2b_dist"}``. The scan-form
    #: distributed builder already emits its panel gather ahead of the
    #: bulk by construction; there the knob only labels the structure.
    bt_lookahead: str = "auto"
    #: bt_band_to_tridiag reflector application: "blocked" (compact-WY
    #: staircase groups -> larft + two gemms per step level, the MXU form of
    #: the reference's b x b HH re-tiling) or "sweeps" (one batched rank-1
    #: segment update per sweep).
    bt_b2t_impl: str = "blocked"
    #: Sweeps per compact-WY group for bt_b2t_impl="blocked"; 0 = auto
    #: (band size on MXU hardware, min(band, 64) on CPU). Clamped to
    #: [1, min(band+1, n_sweeps)] — band+1 is the disjointness bound of the
    #: blocked level reordering.
    bt_b2t_group: int = 0
    #: Real-f64 level-3 contraction backend for the tile ops (gemm / herk /
    #: her2k / hemm / trmm): "native" (XLA's dot — on TPU, compiler-emulated
    #: double-double arithmetic) or "mxu" (error-free int8 slicing with exact
    #: int32 accumulation, tile_ops/ozaki.py), or "auto" (default): mxu on
    #: TPU, native elsewhere. The TPU resolution is measurement-backed
    #: (2026-08-01 v5e session): the mxu route ran 281-351 GF/s where the
    #: native emulation ran 47-49 (cholesky N=4096/8192), its int8 slice
    #: planes are 4x smaller than the emulation's f32 workspaces (the
    #: native route OOMed red2band n=16384 at 32 GB asked of 15.75), and
    #: scan-form algorithms pair pathologically with the native dot (XLA
    #: sinks the emulation's constant planes into the loop: red2band scan
    #: measured 1.86 GF/s native vs 48.9 unrolled). Triangular *solves*
    #: are unaffected (they are latency-, not throughput-bound; see
    #: ``f64_trsm`` for that side).
    f64_gemm: str = "auto"
    #: Smallest dimension for which f64_gemm="mxu" actually reroutes a
    #: contraction; below it the slicing overhead outweighs the MXU win and
    #: the native path is kept.
    f64_gemm_min_dim: int = 128
    #: int8 slices per operand on the MXU f64 path (tile_ops/ozaki.py):
    #: 8 (56 mantissa bits, 36 gemms per product) down to e.g. 7 (49 bits,
    #: 28 gemms). 0 = auto: 7 on backends whose f64 is the double-f32
    #: emulation (TPU — its ~47-48-bit arithmetic already bounds every
    #: combine/panel op, so the 49-bit dot sacrifices nothing and saves
    #: ~22% of the MXU work; measured 103.9 vs 95.5 GF/s on config #1,
    #: 2026-07-31 v5e session), 8 where f64 is native (f64-grade dots).
    f64_gemm_slices: int = 0
    #: Slice contraction route of the ozaki paths (jnp AND the fused
    #: pallas kernels): "int8" (s8 x s8 ->
    #: s32 dot), "bf16" (slices cast to bf16 — exact for 7-bit integers —
    #: contracted on the MXU's native bf16 path with f32 accumulation,
    #: integer-exact while k*2^12 <= 2^24, chunked beyond; bit-identical
    #: results), or "auto" (default): bf16 on TPU, int8 elsewhere. The
    #: 2026-08-01 dot_ab session settled the routes on silicon:
    #: bit-identical on device (0/65536 mismatches at k up to 4096) and
    #: at performance parity at the pipeline level (within 1% on full
    #: config #1 under either group form — the jnp path is HBM-bound, so
    #: the raw s8-dot lowering deficit never binds); bf16 stays the TPU
    #: default as the hardware's first-class MXU path.
    ozaki_dot: str = "auto"
    #: Shape of the jnp path's per-shift group sums: "dots" (one MXU dot
    #: per slice pair, group summed elementwise in HBM — the original,
    #: hardware-proven form) or "concat" (ONE dot per shift group over
    #: k-concatenated slice operands: the d+1 pair sums ride the MXU
    #: accumulator instead of materializing d+1 (m, n) int32 buffers).
    #: Bit-identical integer math either way (tests/test_ozaki.py); the
    #: r4 session data pins the jnp path ~100x under the raw MXU dot
    #: ceiling, i.e. HBM-bound on exactly this traffic, so "concat"
    #: trades more int8 operand reads (cheap, 1 B/elt) for fewer int32
    #: intermediates (4 B/elt). The 2026-08-01 dot_ab session confirmed
    #: the traffic model on silicon: trailing-syrk chains 16.6 vs
    #: 19.1 ms/step and full config #1 at 112.1/111.7 GF/s (int8/bf16)
    #: vs 105.1/104.5 for "dots", identical residuals — so "auto"
    #: (default) resolves concat on TPU and keeps dots elsewhere (the
    #: traffic argument is TPU-HBM-specific; off-TPU stays on the
    #: long-proven form until measured). Syrk's
    #: even-shift groups keep their diagonal pair as a second dot to
    #: preserve the transpose-mirroring MAC saving.
    ozaki_group: str = "auto"
    #: Schedule of the concat group form's per-shift accumulation: "xla"
    #: (straight-line trace — XLA owns the schedule and may keep several
    #: (m, n) int32 group partials live at once; the suspected config-#1
    #: N=16384 OOM, where ~13 live partials of the whole trailing block
    #: would exceed HBM on their own) or "scan" (lax.scan over
    #: zero-padded uniform shift groups — the carry forces one partial +
    #: the f64 accumulator live, O(1) in the slice count; zero int8 pad
    #: columns contribute exactly nothing on either dot route, so the
    #: results are bit-identical — tests/test_ozaki.py
    #: TestScanAccumRoute). "auto" (default): scan on TPU, xla
    #: elsewhere. The 2026-08-02 session-4d A/B: at N=4096 (fits both
    #: ways) the scan schedule measured 119.6 GF/s vs the 112.8
    #: xla-schedule best (+6% — fewer live int32 partials = less HBM
    #: traffic), identical residual; the 4d OOM diag confirmed the
    #: straight-line schedule keeps ~13 GB of ~1 GB trailing-block
    #: planes live at N=16384 (13.95G program ask vs 15.75G HBM; scan
    #: still OOMs there via other buffers, but is never worse). Off-TPU
    #: stays on the straight-line trace (XLA CPU schedules it fine).
    ozaki_accum: str = "auto"
    #: Ozaki slice-reduction implementation: "jnp" (per-shift int32 groups +
    #: full-f64 combine — f64-grade dots at f64_gemm_slices >= 8) or
    #: "pallas" (fused per-tile kernel, double-f32 fold: ~48 mantissa bits,
    #: no intermediate HBM traffic; see tile_ops/pallas_ozaki.py).
    #: EXPERIMENTAL: interpret-mode validated only — the 2026-08-01 hardware
    #: session found the axon tunnel's remote compile helper rejects every
    #: pallas_call with an infrastructure error (HTTP 500, tpu_compile_helper
    #: exit 1; not a Mosaic legalization failure), so the fused kernels have
    #: never executed on silicon (docs/ROUND4.md).
    ozaki_impl: str = "jnp"
    #: Panel factorization kernels for the blocked algorithms' per-step
    #: potrf + panel-TRSM chain (tile_ops/pallas_panel.py,
    #: docs/pallas_panel.md): "xla" (the generic route — XLA's blocked
    #: Cholesky thunk chain for the diagonal tile, a separate
    #: TriangularSolve per panel strip), "fused" (single-``pallas_call``
    #: VMEM-resident kernels: micro-blocked right-looking potrf ladder +
    #: grid-batched strip solve with the factor's inverse in scratch —
    #: one kernel dispatch per panel step instead of a latency-bound
    #: thunk chain per tile), or "auto" (default): fused on TPU for
    #: f32/bf16 inputs, xla elsewhere (f64/c128 keep their own mixed/
    #: ozaki panel treatment — see ``f64_trsm``). An explicit "fused"
    #: with an unsupported dtype registers the degradation at
    #: ``dlaf_fallback_total{site="panel"}`` (DLAF_STRICT raises);
    #: off-TPU the fused kernels run in interpret mode (CI/parity).
    #: Results are ulp-close, not bitwise, across the two impls; all
    #: knob contracts (lookahead/comm_lookahead/with_info) stay bitwise
    #: WITHIN each impl (tests/test_pallas_panel.py).
    panel_impl: str = "auto"
    #: Fused STEP kernel route for the blocked Cholesky builders
    #: (tile_ops/pallas_panel.py ``fused_step``/``fused_factor_solve``,
    #: docs/pallas_panel.md "Fused step kernel"): "xla" (the panel chain
    #: stays composed ops — ``panel_impl`` decides potrf/solve
    #: individually), "fused" (ONE ``pallas_call`` per blocked step:
    #: potrf ladder + whole-strip solve — and, on the local unrolled
    #: builders, the adjacent trailing-update slab — with the factor,
    #: its triangular inverse, and the solved leading strip block all
    #: VMEM-resident between the ops; removes the per-step
    #: kernel-launch + HBM round-trip that the MFU table pins as the
    #: panel-bound floor, ROADMAP item 4), or "auto" (default): fused
    #: on TPU for f32/bf16 within the ``step_vmem_limit`` budget, xla
    #: elsewhere. Explicit "fused" with an unsupported dtype/block or a
    #: VMEM-budget overflow registers at
    #: ``dlaf_fallback_total{site="step"}`` (DLAF_STRICT raises);
    #: off-TPU explicit "fused" runs in interpret mode (CI/parity).
    #: Results are ulp-close, not bitwise, vs the composed chain; all
    #: knob contracts (lookahead/comm_lookahead/with_info) stay bitwise
    #: WITHIN the fused-step route (tests/test_fused_step.py).
    step_impl: str = "auto"
    #: VMEM budget (bytes) for the fused step kernel's modeled live set
    #: (``pallas_panel.step_vmem_bytes``): block sizes whose kernel
    #: would exceed it degrade to the composed-op step route (counted
    #: under explicit "fused", silent policy under "auto"). The default
    #: caps the kernel at 10 MiB, leaving ~6 MiB of a v5e core's
    #: ~16 MiB VMEM for the compiler's own buffers; the autotune ladder
    #: and ``health.inject`` drills exercise the degrade path.
    step_vmem_limit: int = 10 * 2 ** 20
    #: Panel-level factor/solve ops (real f64): "native" (XLA — latency-bound
    #: under TPU f64 emulation), "mixed" (f32 seed + Newton refinement,
    #: tile_ops/mixed.py: refined explicit inverse + matmul for per-tile
    #: panel solves via tile_ops.blas.trsm_panel, and the distributed
    #: cholesky's per-step panel potrf/trsm; the matmul application follows
    #: f64_gemm, so with "mxu" it runs on the int8 path), or "auto"
    #: (default): mixed on TPU (panel-chain probes, 2026-08-01 v5e
    #: session: +0.6 ms/step over pure gemm vs +15.7 ms for native-f64
    #: panels), native elsewhere. Whole-matrix local solves stay native
    #: either way.
    f64_trsm: str = "auto"
    #: Per-k step formulation for the distributed algorithms (triangular
    #: solve/multiply, reduction_to_band + its back-transform, gen_to_std
    #: via its solves) AND the local reduction_to_band: "unrolled" (per-k
    #: steps traced out — exact shapes, compile time linear in the step
    #: count), "scan" (lax.scan'd uniform masked step — O(1) compile,
    #: ~2-3x masked-shape work; the compile-latency escape hatch at large
    #: tile counts, docs/DESIGN.md), or "auto" (default): pick per (step
    #: count, platform) from the measured compile constants via
    #: :func:`resolve_step_mode`. Cholesky selects its scan form via
    #: cholesky_trailing="scan".
    dist_step_mode: str = "auto"
    #: HEGST (gen_to_std) formulation: "blocked" (per-k two-sided update —
    #: hegst diag, panel trsm/hemm, her2k trailing, deferred trailing
    #: solve — ~n^3 flops, the reference's flop discipline,
    #: ``eigensolver/gen_to_std/impl.h:200-740``), "twosolve" (two
    #: whole-matrix triangular solves: ~2x the flops as two dense
    #: MXU-shaped sweeps with no panel round-trips; also the
    #: scan-compatible compile-latency hatch — both blocked forms are
    #: unrolled-only, so when dist_step_mode resolves to "scan" HEGST
    #: routes through "twosolve" regardless), or "auto" (default):
    #: twosolve on TPU, blocked elsewhere. Session-4d silicon (d/8192/
    #: 256, the config-#3-family dtype this tunnel can run): twosolve
    #: 385.3 GF/s at 5.2e-11 residual vs blocked 298.4 at 2.2e-9 — the
    #: dense sweeps beat the latency-bound panel round-trips on wall
    #: clock (same reference flop model for both labels) AND on
    #: accuracy; off-TPU the ~n^3 blocked discipline wins as before.
    hegst_impl: str = "auto"
    #: Broadcast realization in comm.collectives.bcast: "psum"
    #: (mask-then-all-reduce — ~2V(p-1)/p per link, the bandwidth shape
    #: for panel payloads) or "tree" (binomial ppermute doubling —
    #: ceil(log2 p) hop latencies, the candidate for small diagonal-tile
    #: payloads). First multi-chip ICI access must A/B these.
    bcast_impl: str = "psum"
    #: Panel Householder-QR factorization route (reduction_to_band's
    #: reflector panels — the sole geqrf consumer; the QR T-factor
    #: algorithm takes precomputed reflectors): "geqrf" (the XLA
    #: primitive — LAPACK on CPU, an XLA-internal expansion on TPU),
    #: "householder" (tile_ops/qr_panel.py: the same column-Householder
    #: algorithm in plain jnp ops), or "auto" (default): householder on
    #: TPU, geqrf elsewhere. History: built as the accuracy suspect for
    #: the session-4d red2band ~1e-5 check failures; the silicon probes
    #: EXONERATED geqrf (backward error ~2e-14 at every panel shape —
    #: the real culprit was the ozaki peel's emulated round,
    #: tile_ops/ozaki.py _peel_slices). The TPU auto choice stands on
    #: PERFORMANCE: red2band 4096/512/band128 scan measured 74.9 GF/s
    #: under householder vs 49.3 under the geqrf expansion (+52%, equal
    #: 7e-14-grade residuals, post-peel-fix, 2026-08-02 v5e) — the
    #: fori_loop sweep beats XLA's expansion on this hardware; off-TPU
    #: geqrf is LAPACK and stays.
    qr_panel: str = "auto"
    #: Column-chunk width for LARGE local triangular solves (elements of
    #: the rhs free axis; rhs columns — rows for side='R' — are
    #: mathematically independent, so the solve maps bitwise-identically
    #: over free-axis chunks). 0 disables; -1 (default) = auto: on TPU,
    #: chunk at 4096 when both solve dimensions are >= 8192 and the mxu
    #: route is active — the whole-matrix emulated-f64 solves (HEGST
    #: twosolve, eigensolver back-substitution) otherwise materialize
    #: their int8/bf16 operand slices, int32 partials, and f64 products
    #: at the FULL rhs width simultaneously, the measured single-chip
    #: OOM at n=16384 (session 4g: HEGST d/16384 RESOURCE_EXHAUSTED with
    #: donation already applied). lax.map over chunks bounds that live
    #: set to one chunk's worth; off-TPU the native solves have no such
    #: workspaces and chunking only costs fusion.
    trsm_rhs_chunk: int = -1
    #: Row-chunk width for the LOCAL reduction-to-band trailing update
    #: (rows of the trailing block; W = A(VT) and the rank-2 update
    #: A -= XV^H + VX^H are row-independent in A, so both map over row
    #: chunks with the chunked gemms bitwise-identical; whole-step
    #: results match to ~1 ulp — XLA re-fuses the small interleaved
    #: panel matmuls, reassociating their reductions). 0 disables; -1
    #: (default) =
    #: auto: on TPU, chunk at 4096 when the trailing dimension is
    #: >= 8192 and the mxu route is active — the trailing gemms
    #: otherwise materialize the emulated-f64 operand slice planes and
    #: per-group product partials at the FULL trailing size (the
    #: measured 19.28 GB compile ask of red2band n=16384/band=128 on
    #: the 15.75 GB chip, session 4f). Chunk widths are clamped so the
    #: per-gemm route gate (f64_gemm_min_dim over ALL gemm dims) cannot
    #: flip; off-TPU the native gemms have no slice workspaces and
    #: chunking only costs fusion.
    red2band_trail_chunk: int = -1
    #: Conditioning guard for the "mixed" fast path, as a limit on the
    #: squared diagonal ratio of the f32 seed factor (empirically
    #: residual ~ 3.5e-14 * estimate for one Newton step; blocks estimated
    #: worse take the native branch inside the compiled program).
    mixed_cond_limit: float = 100.0
    #: Half-precision seed kernel for the mixed panel path: "xla" (native
    #: loop-based cholesky + triangular solve) or "recursive" (trace-time
    #: recursive block decomposition producing factor AND inverse from
    #: gemms + small leaf kernels — trades program size for the XLA loop
    #: dispatch latency that dominates panel steps; tile_ops/mixed.py).
    mixed_seed: str = "xla"
    #: Leaf size of the recursive seed (power of two recommended).
    mixed_seed_base: int = 64
    #: Enable float64/complex128 support (sets jax_enable_x64).
    enable_x64: bool = True
    #: When non-empty, miniapps emit XLA/PJRT execution profiles
    #: (jax.profiler traces with named phases) into this directory
    #: (the green-field tracing hook SURVEY §5 calls for).
    profile_dir: str = ""
    #: Structured-log level for the dlaf_tpu.obs logger ("debug" | "info" |
    #: "warning" | "error" | "off"): the one-shot auto-knob resolution
    #: notices and all other library diagnostics route through it, so CI
    #: and pytest output can silence them with DLAF_LOG=off.
    log: str = "info"
    #: When non-empty, the observability layer (dlaf_tpu.obs) appends
    #: span records, metrics snapshots (collective byte counters, tile-op
    #: counts, span-duration histograms), and log events to this JSON-lines
    #: file; schema-checked by ``python -m dlaf_tpu.obs.validate``. Empty
    #: (default) keeps every instrumented call site a zero-allocation
    #: no-op.
    metrics_path: str = ""
    #: When non-empty, host spans start one jax.profiler trace into this
    #: directory (TraceAnnotation phase names on the profiler timeline;
    #: named_scope phase names in compiled-program op metadata). The
    #: pre-obs ``profile_dir`` knob keeps working; this is the obs-layer
    #: spelling, and the two may point at the same directory.
    trace_dir: str = ""
    #: When non-empty, compiled XLA programs persist here across processes
    #: (jax persistent compilation cache). The unrolled factorizations cost
    #: minutes to compile and seconds to run — a disk cache turns every
    #: re-run (benchmark sweeps included) into a cache hit. Empty turns the
    #: cache off (including un-setting it on a later initialize()).
    compilation_cache_dir: str = ""
    #: Only compiles at least this long (seconds) are persisted.
    compilation_cache_min_secs: float = 5.0
    #: Opt-in finite guard (``DLAF_CHECK`` / ``--dlaf:check``): robustness
    #: drivers (health.robust_cholesky; miniapp_cholesky wires the CLI
    #: flag) validate inputs and outputs for non-finite values, raising a
    #: structured health.CheckError instead of letting a NaN propagate
    #: silently. Off by default — the guard host-syncs by design.
    check: bool = False
    #: Strict degradation mode (``DLAF_STRICT``): a registered fallback
    #: (native secular/band-chase -> numpy, pallas -> XLA, ozaki -> plain
    #: dot; health.registry) RAISES health.DegradationError instead of
    #: silently taking the degraded path. The CI/bring-up stance where a
    #: missing native library must fail the job, not slow it 100x.
    strict: bool = False
    #: Accuracy telemetry (``DLAF_ACCURACY``, docs/accuracy.md): "1" arms
    #: the in-graph numerical-quality probes (dlaf_tpu.obs.accuracy) —
    #: miniapps and bench arms compute a stochastic Hutchinson residual
    #: estimate per timed run (O(n^2) device work, no full-matrix host
    #: fetch) and the D&C eigensolver records its per-level deflation
    #: fraction, each landing as an ``accuracy`` JSONL record (site,
    #: metric, value, bound_ratio = value/(c*n*eps_eff) with the
    #: platform-honest eps of miniapp/checks.effective_eps) plus a
    #: ``dlaf_accuracy_ratio{site,metric}`` gauge. "full" upgrades the
    #: probes to the exact tile-wise Frobenius residual (O(n^3) device
    #: work, still no host round trip). "0" (default) emits nothing and
    #: is a bitwise passthrough: factor outputs are identical with the
    #: knob on or off (the probes are separate programs over the outputs;
    #: pinned by tests/test_accuracy.py). ``--check-result`` always
    #: verifies regardless of the knob — the knob only picks the
    #: estimator mode ("0" checks with the "1" probe).
    accuracy: str = "0"
    #: Accuracy-steered precision autotuning (``DLAF_AUTOTUNE``, ISSUE 15,
    #: docs/autotune.md): "1" closes the loop on the accuracy signal —
    #: the precision routes that dominate TPU f64-emulation cost
    #: (``f64_gemm_slices`` / ``f64_trsm`` / ``panel_impl`` /
    #: ``ozaki_impl``) are chosen per (op, n-bucket, nb, dtype, platform)
    #: from a route table fed by PR 8's cheap Hutchinson probe after each
    #: factorization: escalate one ladder rung immediately on a
    #: ``bound_ratio`` breach, relax one rung after
    #: ``autotune_relax_after`` consecutive comfortable probes
    #: (dlaf_tpu.autotune; decisions are pure functions of
    #: (table, probe), so drills replay exactly). "0" (the bitwise
    #: passthrough: ladders start at the platform-default route, and off
    #: nothing is probed or overridden). "auto" (default): 1 on TPU —
    #: exactly where the emulation routes bind — and 0 elsewhere. Probe
    #: cost: one O(n^2 k) device estimate per non-donated entry call;
    #: donated inputs skip the probe (nothing to compare against).
    autotune: str = "auto"
    #: Route-table persistence path (``DLAF_AUTOTUNE_TABLE``,
    #: docs/autotune.md): when non-empty, the autotuner warm-starts from
    #: this schema-validated JSON table (malformed/stale/version-mismatch
    #: refuses loudly, naming the field) and re-serializes it ATOMICALLY
    #: after every decision, so learned routes survive restarts — the
    #: committed ``.autotune_table.json`` is the repo's warm-start
    #: convention (copy it aside before pointing a mutating run at it,
    #: like ``.bench_history.jsonl``). Empty (default): in-memory only.
    autotune_table: str = ""
    #: Relax-comfort threshold (``DLAF_AUTOTUNE_MARGIN``): a probe with
    #: ``bound_ratio <= margin`` counts toward relaxing one rung; ratios
    #: in (margin, 1] hold the route (and reset the comfortable streak —
    #: the documented hysteresis band, docs/autotune.md).
    autotune_margin: float = 0.25
    #: Consecutive comfortable probes required before the route relaxes
    #: one rung toward the fast end (``DLAF_AUTOTUNE_RELAX_AFTER``) —
    #: escalation on a breach is always immediate.
    autotune_relax_after: int = 3
    #: Probe cadence (``DLAF_AUTOTUNE_PROBE_EVERY``): the algorithm
    #: entries run the Hutchinson probe on every K-th call per table
    #: entry (the first call always probes). The probe is O(n^2 k)
    #: against the factorization's O(n^3) — negligible at production
    #: sizes, measurable at toy ones — so latency-sensitive deployments
    #: amortize it here. Un-probed calls still apply the learned route;
    #: the serve queue's per-dispatch residuals (already gated on
    #: ``DLAF_ACCURACY``) ignore this cadence.
    autotune_probe_every: int = 1
    #: Per-site relax budget per process run (``DLAF_AUTOTUNE_BUDGET``):
    #: at most this many relax route changes per table entry, bounding
    #: route churn (each change is a new compiled program). Escalations
    #: are NEVER budget-limited — safety moves always run. 0 = unbounded.
    autotune_budget: int = 16
    #: Bucket ceilings of the serving layer (``DLAF_SERVE_BUCKETS``,
    #: docs/serving.md): a comma-separated ascending list of matrix sizes
    #: (e.g. "32,64,128") that :class:`dlaf_tpu.serve.Queue` rounds
    #: incoming request shapes up to — one compiled (and ideally warmed)
    #: batched program per ceiling. Empty (default) = power-of-two
    #: ceilings chosen per request (next power of two >= n, min 8); a
    #: request larger than the largest explicit ceiling also falls back
    #: to the next power of two, so no shape is ever rejected (it just
    #: pays a cold compile — the cache-miss signal the serve metrics
    #: surface).
    serve_buckets: str = ""
    #: Lanes per batched serve dispatch (``DLAF_SERVE_BATCH``): the
    #: bucket's vmapped program factors this many problems per dispatch;
    #: the queue dispatches early on deadline expiry with the missing
    #: lanes identity-padded (provably inert — docs/serving.md padding
    #: contract). 16 is the smallest batch for which the measured
    #: dispatch-overhead amortization clears the ISSUE-11 3x
    #: requests/s bar with margin on every platform.
    serve_batch: int = 16
    #: Queue deadline in milliseconds (``DLAF_SERVE_DEADLINE_MS``): a
    #: bucket with pending requests older than this dispatches at the
    #: next ``submit``/``poll`` even if not full. The queue never runs a
    #: background thread — expiry is evaluated against the injected
    #: clock at those calls, so dispatch composition is deterministic
    #: and testable (docs/serving.md deadline semantics).
    serve_deadline_ms: float = 50.0
    #: Admission bound of the serving queue (``DLAF_SERVE_MAX_DEPTH``,
    #: docs/serving.md overload protection): the maximum TOTAL number of
    #: pending (undispatched) requests across every bucket. At the bound
    #: the queue either sheds (``serve_shed``) or force-dispatches the
    #: fullest bucket — either way pending depth provably never exceeds
    #: this knob, so queue memory is bounded under overload. 0 (default)
    #: = unbounded (the pre-PR-12 behavior).
    serve_max_depth: int = 0
    #: Overload response at the ``serve_max_depth`` bound
    #: (``DLAF_SERVE_SHED``): True (default) fails the submit fast with a
    #: structured :class:`dlaf_tpu.health.errors.OverloadError` (shed
    #: counted per bucket under ``dlaf_serve_shed_total``); False applies
    #: backpressure instead — the fullest bucket is dispatched inline to
    #: make room, trading submit latency for zero sheds.
    serve_shed: bool = True
    #: Dispatch retry budget of the serving queue
    #: (``DLAF_SERVE_RETRY_ATTEMPTS``): each batch dispatch runs under a
    #: health.policy RetryPolicy with this many total attempts, so a
    #: transiently failing dispatch (the PR-12 motivation: it used to
    #: poison its tickets with no retry) re-runs before the tickets are
    #: poisoned. 1 = no retry.
    serve_retry_attempts: int = 3
    #: Base backoff between serve dispatch retry attempts, milliseconds
    #: (``DLAF_SERVE_RETRY_BACKOFF_MS``; exponential growth + the policy
    #: engine's deterministic seeded jitter). 0 (default) retries
    #: immediately — dispatch failures are dominated by deterministic
    #: causes (compile error, OOM) where waiting buys nothing; set it
    #: when fronting genuinely transient infrastructure.
    serve_retry_backoff_ms: float = 0.0
    #: Circuit-breaker opening threshold (``DLAF_CIRCUIT_THRESHOLD``,
    #: docs/robustness.md): consecutive failures at one site before the
    #: breaker opens (closed -> open) and calls fail fast with
    #: health.CircuitOpenError instead of re-running a failing dispatch/
    #: primary.
    circuit_threshold: int = 3
    #: Circuit-breaker cooldown, seconds (``DLAF_CIRCUIT_COOLDOWN_S``):
    #: how long an open breaker rejects calls before letting ONE half-open
    #: probe through (success closes it, failure re-opens).
    circuit_cooldown_s: float = 30.0
    #: Fleet size (``DLAF_FLEET_WORKERS``, docs/fleet.md): how many
    #: serve worker replicas the launch helpers / CI drills / bench
    #: fleet arm spawn behind one router. The router itself accepts any
    #: number of ``hello`` connections — this knob sizes the launchers,
    #: not the protocol.
    fleet_workers: int = 3
    #: Router heartbeat interval, milliseconds
    #: (``DLAF_FLEET_HEARTBEAT_MS``): how often the router pings each
    #: routable worker at its clock edges (docs/fleet.md liveness).
    fleet_heartbeat_ms: float = 1000.0
    #: Heartbeat silence budget, milliseconds
    #: (``DLAF_FLEET_HEARTBEAT_TIMEOUT_MS``): an ``up`` worker with no
    #: traffic for this long turns ``suspect`` at the next router clock
    #: edge — its breaker is forced open, its unacknowledged tickets
    #: re-dispatch to siblings, and re-admission follows the half-open
    #: probe discipline. Evaluated against the router's injectable
    #: clock, so timeout drills replay deterministically.
    fleet_heartbeat_timeout_ms: float = 5000.0
    #: Failover switch (``DLAF_FLEET_FAILOVER``): True (default)
    #: re-dispatches a dead worker's unacknowledged tickets to siblings
    #: (at-least-once, zero loss); False poisons them with structured
    #: WorkerLostError + ``ticket_lost`` fleet records — which
    #: ``--require-fleet`` REJECTS, so disabling failover is visible in
    #: CI, never silent (the must-trip drill leg).
    fleet_failover: bool = True
    #: Router ticket-dispatch retry budget
    #: (``DLAF_FLEET_RETRY_ATTEMPTS``): total attempts per dispatch
    #: under the shared policy engine, with worker re-selection each
    #: attempt. Must exceed ``circuit_threshold`` for a sustained
    #: per-worker fault to open that worker's breaker mid-policy and
    #: re-route the remaining attempts to a sibling (docs/fleet.md).
    fleet_retry_attempts: int = 5
    #: Base backoff between router dispatch retries, milliseconds
    #: (``DLAF_FLEET_RETRY_BACKOFF_MS``; exponential + deterministic
    #: seeded jitter). 0 (default) retries immediately — a fleet
    #: re-route targets a DIFFERENT worker, so waiting buys nothing.
    fleet_retry_backoff_ms: float = 0.0
    #: Stage-checkpoint directory for preemption-safe pipeline resume
    #: (``DLAF_RESUME_DIR``, docs/robustness.md §5): when non-empty, the
    #: eigensolver pipeline writes an atomic versioned checkpoint after
    #: each stage (red2band, b2t, tridiag, bt_b2t, bt_r2b) and
    #: ``eigensolver(..., resume=True)`` skips stages whose checkpoint
    #: manifest matches the run's config/grid/dtype fingerprint — a
    #: preempted multi-minute pipeline restarts from the last completed
    #: stage instead of from scratch, bitwise-identically per stage on
    #: the native routes. Empty (default) = no checkpointing.
    resume_dir: str = ""
    #: LRU byte budget of the serve program cache
    #: (``DLAF_SERVE_CACHE_BYTES``): compiled bucket programs are
    #: retained up to this many bytes (per-program cost =
    #: ``memory_analysis()`` peak where the backend reports one, an
    #: aval-derived estimate otherwise), evicting
    #: least-recently-dispatched unpinned programs first;
    #: ``serve.ProgramService.pin`` exempts a program from eviction.
    #: 0 (default) = unbounded.
    serve_cache_bytes: int = 0
    #: Live metrics/health endpoint port (``DLAF_METRICS_PORT``, ISSUE 13,
    #: docs/observability.md live operations): when > 0, dlaf_tpu.obs
    #: starts a stdlib-http daemon thread on 127.0.0.1 serving ``GET
    #: /metrics`` (Prometheus text exposition of the LIVE registry, with
    #: exemplar trace IDs on latency histogram buckets) and ``GET
    #: /healthz`` (JSON: serve-queue depth/shed/breaker states, worst
    #: live accuracy bound_ratio, rank/pid/uptime). Arming the port also
    #: turns the metrics registry on even without DLAF_METRICS_PATH
    #: (scrape-only deployments). 0 (default): zero threads, zero
    #: sockets.
    metrics_port: int = 0
    #: Rolling SLO latency objective, milliseconds (``DLAF_SLO_P99_MS``):
    #: every latency recorded through obs.observe_latency (the serve
    #: queue per request; health.policy.with_policy per successful call)
    #: that exceeds this objective increments the
    #: ``dlaf_slo_breach_total{op}`` burn counter. 0 (default) = no
    #: objective, nothing counted. The windowed
    #: ``dlaf_serve_latency_window{op,bucket,q}`` percentile gauges are
    #: maintained regardless.
    slo_p99_ms: float = 0.0
    #: Rolling SLO window length, seconds (``DLAF_SLO_WINDOW_S``): the
    #: span of the sliding-window quantile estimator behind the
    #: ``dlaf_serve_latency_window`` gauges — a ring of fixed-size epoch
    #: buckets (bounded memory, deterministic under an injected clock;
    #: dlaf_tpu.obs.metrics.SlidingWindow).
    slo_window_s: float = 60.0
    #: SLO breach-burst flight trigger threshold (``DLAF_SLO_BURST``,
    #: ISSUE 14): when at least this many ``dlaf_slo_breach_total``
    #: breaches land inside ONE rolling SLO window (``slo_window_s``,
    #: per op), the flight recorder dumps its ring with reason
    #: ``slo_breach_burst`` — once per recorder cooldown, so a sustained
    #: latency storm leaves ONE incident artifact holding the moments
    #: before the burst instead of a thousand re-dumps. Needs
    #: ``DLAF_FLIGHT_RECORDER`` armed (and ``DLAF_SLO_P99_MS`` set —
    #: no objective, no breaches). 0 disables the trigger.
    slo_burst: int = 5
    #: Flight-recorder ring depth (``DLAF_FLIGHT_RECORDER``): keep the
    #: last N JSONL records in memory (all types, pre-serialization) and
    #: dump them atomically to ``<metrics_path>.flight.jsonl`` on
    #: incident triggers — breaker open, overload shed, recovery
    #: exhaustion, accuracy budget breach, /healthz failure
    #: (dlaf_tpu.obs.flight; validated by ``python -m dlaf_tpu.obs.
    #: validate --require-flight``). Requires DLAF_METRICS_PATH (the ring
    #: captures the sink's record stream). 0 (default) = off; a clean
    #: run must produce NO flight artifact.
    flight_recorder: int = 0
    #: Program telemetry (``DLAF_PROGRAM_TELEMETRY``): the algorithm entry
    #: points and the library's cached-program sites record per-site
    #: compile walls (``dlaf_compile_seconds{site}``), trace counts
    #: (``dlaf_retrace_total{site}`` — first trace = 1, more = retraces),
    #: and ``compiled.memory_analysis()`` HBM gauges
    #: (``dlaf_hbm_bytes{what=args|output|temp|peak,site}``), each compile
    #: also landing as a ``program`` record in the ``metrics_path``
    #: artifact (dlaf_tpu.obs.telemetry; docs/observability.md). Off
    #: (default): every instrumented site is a passthrough to the same
    #: jitted callable — bitwise no-op, one attribute read of cost.
    program_telemetry: bool = False

    def _fields(self):
        return {f.name: f for f in dataclasses.fields(self)}


def _parse(value: str, typ):
    if typ is bool:
        return value.strip().lower() in ("1", "true", "yes", "on")
    return typ(value)


def update_configuration(
    user: Optional[Configuration] = None,
    argv: Optional[Sequence[str]] = None,
) -> Configuration:
    """Resolve the effective configuration.

    Precedence (highest wins), mirroring ``src/init.cpp:117-156``:
    CLI ``--dlaf:<name>=<v>`` > env ``DLAF_<NAME>`` > ``user`` struct > default.
    """
    cfg = dataclasses.replace(user) if user is not None else Configuration()
    fields = cfg._fields()
    for name, f in fields.items():
        env = os.environ.get("DLAF_" + name.upper())
        if env is not None:
            setattr(cfg, name, _parse(env, f.type if isinstance(f.type, type) else type(f.default)))
    if argv:
        for arg in argv:
            if not arg.startswith("--dlaf:"):
                continue
            body = arg[len("--dlaf:"):]
            if "=" in body:
                key, val = body.split("=", 1)
            else:
                key, val = body, "true"
            key = key.replace("-", "_")
            if key in fields:
                f = fields[key]
                setattr(cfg, key, _parse(val, f.type if isinstance(f.type, type) else type(f.default)))
    return cfg


#: Allowed values for enum-like knobs, checked at initialize() — a typo'd
#: value must fail loudly, not silently take the other branch (the literal
#: string comparisons at the use sites would otherwise just pick "native").
_VALID_CHOICES = {
    "grid_ordering": ("row-major", "col-major"),
    "band_to_tridiag_impl": ("native", "numpy"),
    "secular_impl": ("native", "numpy"),
    "bt_b2t_impl": ("blocked", "sweeps"),
    "cholesky_lookahead": ("0", "1", "auto"),
    "comm_lookahead": ("0", "1", "auto"),
    "dc_level_batch": ("0", "1", "auto"),
    "bt_lookahead": ("0", "1", "auto"),
    "f64_gemm": ("native", "mxu", "auto"),
    "f64_trsm": ("native", "mixed", "auto"),
    "panel_impl": ("fused", "xla", "auto"),
    "step_impl": ("fused", "xla", "auto"),
    "ozaki_impl": ("jnp", "pallas"),
    "ozaki_dot": ("int8", "bf16", "auto"),
    "ozaki_group": ("dots", "concat", "auto"),
    "ozaki_accum": ("xla", "scan", "auto"),
    "qr_panel": ("geqrf", "householder", "auto"),
    "mixed_seed": ("xla", "recursive"),
    "dist_step_mode": ("unrolled", "scan", "auto"),
    "hegst_impl": ("blocked", "twosolve", "auto"),
    "bcast_impl": ("psum", "tree"),
    "log": ("debug", "info", "warning", "error", "off"),
    "accuracy": ("0", "1", "full"),
    "autotune": ("0", "1", "auto"),
}


def _validate(cfg: Configuration) -> None:
    for name, allowed in _VALID_CHOICES.items():
        v = getattr(cfg, name)
        if v not in allowed:
            raise ValueError(f"configuration {name}={v!r}: must be one of {allowed}")
    if cfg.trsm_rhs_chunk < -1:
        raise ValueError(f"trsm_rhs_chunk={cfg.trsm_rhs_chunk}: must be -1 "
                         "(auto), 0 (off), or a positive chunk width")
    if cfg.red2band_trail_chunk < -1:
        raise ValueError(f"red2band_trail_chunk={cfg.red2band_trail_chunk}: "
                         "must be -1 (auto), 0 (off), or a positive chunk "
                         "width")
    if not 0 <= cfg.f64_gemm_slices <= 9:
        raise ValueError(f"f64_gemm_slices={cfg.f64_gemm_slices}: must be in "
                         "[1, 9], or 0 for the platform-adaptive default")
    if cfg.step_vmem_limit < 1:
        raise ValueError(f"step_vmem_limit={cfg.step_vmem_limit}: must be "
                         ">= 1 byte (the fused step kernel's VMEM budget)")
    if cfg.mixed_seed_base < 1:
        raise ValueError(f"mixed_seed_base={cfg.mixed_seed_base}: must be >= 1"
                         " (the recursive seed's leaf size)")
    if cfg.serve_batch < 1:
        raise ValueError(f"serve_batch={cfg.serve_batch}: must be >= 1 "
                         "(lanes per batched serve dispatch)")
    if not cfg.serve_deadline_ms >= 0:
        raise ValueError(f"serve_deadline_ms={cfg.serve_deadline_ms}: must "
                         "be >= 0 (0 = dispatch at the first poll)")
    if cfg.serve_cache_bytes < 0:
        raise ValueError(f"serve_cache_bytes={cfg.serve_cache_bytes}: must "
                         "be >= 0 (0 = unbounded)")
    if cfg.serve_max_depth < 0:
        raise ValueError(f"serve_max_depth={cfg.serve_max_depth}: must be "
                         ">= 0 (0 = unbounded pending depth)")
    if cfg.serve_retry_attempts < 1:
        raise ValueError(f"serve_retry_attempts={cfg.serve_retry_attempts}: "
                         "must be >= 1 (1 = no dispatch retry)")
    if not cfg.serve_retry_backoff_ms >= 0:
        raise ValueError(f"serve_retry_backoff_ms="
                         f"{cfg.serve_retry_backoff_ms}: must be >= 0")
    if cfg.fleet_workers < 1:
        raise ValueError(f"fleet_workers={cfg.fleet_workers}: must be "
                         ">= 1 (replicas behind the fleet router)")
    if not cfg.fleet_heartbeat_ms > 0:
        raise ValueError(f"fleet_heartbeat_ms={cfg.fleet_heartbeat_ms}: "
                         "must be > 0 (the router ping cadence)")
    if not cfg.fleet_heartbeat_timeout_ms >= cfg.fleet_heartbeat_ms:
        raise ValueError(
            f"fleet_heartbeat_timeout_ms={cfg.fleet_heartbeat_timeout_ms}:"
            f" must be >= fleet_heartbeat_ms={cfg.fleet_heartbeat_ms} "
            "(a timeout shorter than one ping interval declares every "
            "healthy worker suspect)")
    if cfg.fleet_retry_attempts < 1:
        raise ValueError(f"fleet_retry_attempts={cfg.fleet_retry_attempts}:"
                         " must be >= 1 (1 = no dispatch retry)")
    if not cfg.fleet_retry_backoff_ms >= 0:
        raise ValueError(f"fleet_retry_backoff_ms="
                         f"{cfg.fleet_retry_backoff_ms}: must be >= 0")
    if not 0 <= cfg.metrics_port <= 65535:
        raise ValueError(f"metrics_port={cfg.metrics_port}: must be in "
                         "[0, 65535] (0 = live exporter off)")
    if not cfg.slo_p99_ms >= 0:
        raise ValueError(f"slo_p99_ms={cfg.slo_p99_ms}: must be >= 0 "
                         "(0 = no latency objective)")
    if not cfg.slo_window_s > 0:
        raise ValueError(f"slo_window_s={cfg.slo_window_s}: must be > 0 "
                         "(the rolling quantile window length)")
    if cfg.slo_burst < 0:
        raise ValueError(f"slo_burst={cfg.slo_burst}: must be >= 0 "
                         "(0 = breach-burst flight trigger off)")
    if cfg.flight_recorder < 0:
        raise ValueError(f"flight_recorder={cfg.flight_recorder}: must be "
                         ">= 0 (0 = flight recorder off; N = ring depth)")
    if cfg.circuit_threshold < 1:
        raise ValueError(f"circuit_threshold={cfg.circuit_threshold}: must "
                         "be >= 1 (consecutive failures before opening)")
    if not cfg.circuit_cooldown_s >= 0:
        raise ValueError(f"circuit_cooldown_s={cfg.circuit_cooldown_s}: "
                         "must be >= 0 (open -> half-open probe delay)")
    if not 0 < cfg.autotune_margin <= 1:
        raise ValueError(f"autotune_margin={cfg.autotune_margin}: must be "
                         "in (0, 1] (the relax-comfort bound_ratio "
                         "threshold; 1 would erase the hysteresis band)")
    if cfg.autotune_relax_after < 1:
        raise ValueError(f"autotune_relax_after={cfg.autotune_relax_after}:"
                         " must be >= 1 (consecutive comfortable probes "
                         "before a relax)")
    if cfg.autotune_probe_every < 1:
        raise ValueError(f"autotune_probe_every="
                         f"{cfg.autotune_probe_every}: must be >= 1 "
                         "(probe every K-th entry call per site)")
    if cfg.autotune_budget < 0:
        raise ValueError(f"autotune_budget={cfg.autotune_budget}: must be "
                         ">= 0 (0 = unbounded per-site relax budget)")
    parse_serve_buckets(cfg.serve_buckets)   # raises on a malformed list
    # cholesky_trailing is validated against VALID_TRAILING at the use site
    # (algorithms/cholesky.py) to keep the list next to the implementations


def parse_serve_buckets(value: str) -> tuple:
    """``serve_buckets`` parsed to an ascending tuple of positive ints
    (empty tuple = the power-of-two auto policy). A malformed list must
    fail loudly at initialize(), not silently misroute every request to
    the auto buckets."""
    if not str(value).strip():
        return ()
    try:
        buckets = tuple(int(tok) for tok in str(value).split(","))
    except ValueError:
        raise ValueError(f"serve_buckets={value!r}: must be a "
                         "comma-separated list of positive ints")
    if any(b < 1 for b in buckets) or list(buckets) != sorted(set(buckets)):
        raise ValueError(f"serve_buckets={value!r}: ceilings must be "
                         "positive, strictly ascending, and unique")
    return buckets


_active: Optional[Configuration] = None

#: Compiled-program caches (jitted fns / lru-cached program builders) whose
#: traces bake in configuration decisions. Registered via
#: :func:`register_program_cache`; cleared when initialize() lands a config
#: that differs from the active one, so knob changes can never hit a stale
#: trace. (The reference has no analog: its knobs steer a dynamic runtime;
#: ours steer trace-time decisions that persist in compiled programs.)
_PROGRAM_CACHES: list = []


def register_program_cache(fn):
    """Register a cache-bearing callable (``.cache_clear()`` from
    functools.lru_cache or ``.clear_cache()`` from jax.jit) for invalidation
    on configuration changes. Usable as a decorator; returns ``fn``."""
    _PROGRAM_CACHES.append(fn)
    return fn


def _clear_program_caches() -> None:
    for fn in _PROGRAM_CACHES:
        clear = getattr(fn, "cache_clear", None) or getattr(fn, "clear_cache", None)
        if clear is not None:
            clear()


def initialize(user: Optional[Configuration] = None,
               argv: Optional[Sequence[str]] = None) -> Configuration:
    """Bring up the runtime (analog of ``dlaf::initialize``, ``init.h:60-75``).

    Resolves configuration and applies process-wide JAX settings (x64). Safe
    to call more than once; later calls re-resolve configuration and drop
    compiled-program caches if anything changed.
    """
    global _active
    cfg = update_configuration(user, argv)
    _validate(cfg)
    if _active is not None and cfg != _active:
        _clear_program_caches()
    if cfg.enable_x64:
        import jax

        jax.config.update("jax_enable_x64", True)
    prev_cache = _active.compilation_cache_dir if _active is not None else ""
    if cfg.compilation_cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          cfg.compilation_cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(cfg.compilation_cache_min_secs))
    elif prev_cache:
        # OUR previously-set dir is being cleared; never touched when the
        # knob was never used, so a cache configured through JAX's own
        # JAX_COMPILATION_CACHE_DIR mechanism stays intact
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    # bring the observability layer in line with the resolved knobs
    # (DLAF_LOG / DLAF_METRICS_PATH / DLAF_TRACE_DIR; the legacy
    # profile_dir knob doubles as a trace dir so pre-obs profiling
    # configurations keep annotating)
    from . import obs

    obs.configure(log_level=cfg.log, metrics_path=cfg.metrics_path,
                  trace_dir=cfg.trace_dir or cfg.profile_dir,
                  program_telemetry=cfg.program_telemetry,
                  metrics_port=cfg.metrics_port,
                  flight_recorder=cfg.flight_recorder)
    if cfg.print_config:
        print(cfg)
    _active = cfg
    return cfg


def get_configuration() -> Configuration:
    """Active configuration, initializing with defaults on first use."""
    global _active
    if _active is None:
        _active = initialize()
    return _active


def resolve_platform_auto(value: str, *, knob: str, tpu_choice: str,
                          other_choice: str, detail: str) -> str:
    """Shared resolve-and-announce for the platform-keyed "auto" knobs
    (ozaki_dot, ozaki_group, ozaki_accum, qr_panel, f64_gemm, f64_trsm,
    cholesky_trailing — grep for callers rather than trusting this list):
    pick per the PROCESS
    default jax backend — a trace explicitly placed on a non-default
    backend inherits the process choice; set the knob explicitly for
    that case — and print one stderr announcement per (knob, backend,
    choice) so the decision is never silent."""
    if value != "auto":
        return value
    import jax

    backend = jax.default_backend()
    choice = tpu_choice if backend == "tpu" else other_choice
    from .obs import get_logger

    # once per (knob, backend, choice) — the route in effect is visible,
    # not silent (round-2 advisory), via the obs layer's shared one-shot
    # registry (reset/forget hooks live there for tests)
    get_logger("config").warning_once(
        (knob, backend, choice),
        f"{knob}=auto resolved to {choice!r} for default backend "
        f"{backend!r} ({detail}) — set the knob explicitly to override",
        knob=knob, backend=backend, choice=choice)
    return choice


def resolved_f64_gemm() -> str:
    """``f64_gemm`` with "auto" resolved: mxu on TPU, native elsewhere
    (see the knob docstring for the measurement basis)."""
    return resolve_platform_auto(
        get_configuration().f64_gemm, knob="f64_gemm", tpu_choice="mxu",
        other_choice="native",
        detail="int8-slice MXU gemms measured 281-351 GF/s vs 47-49 for "
               "the native f64 emulation, with 4x smaller workspaces — "
               "2026-08-01 v5e session")


def _route_override(field: str):
    """The active autotune route's override for ``field`` (None =
    inherit the ordinary resolution) — docs/autotune.md. Consulted by
    the knob resolvers whose decisions the autotuner steers; every
    program cache on such a path carries the route in its cache key
    (dlaf_tpu.autotune.routes module docstring)."""
    from .autotune.routes import override

    return override(field)


def resolved_f64_trsm() -> str:
    """``f64_trsm`` with "auto" resolved: mixed on TPU, native elsewhere
    (see the knob docstring for the measurement basis). An active
    autotune route (docs/autotune.md) overrides the resolution."""
    routed = _route_override("f64_trsm")
    if routed is not None:
        return routed
    return resolve_platform_auto(
        get_configuration().f64_trsm, knob="f64_trsm",
        tpu_choice="mixed", other_choice="native",
        detail="f32-seed Newton-refined panel solves measured +0.6 ms/step "
               "vs +15.7 for native-f64 panels — 2026-08-01 v5e session")


def resolved_panel_impl() -> str:
    """``panel_impl`` with "auto" resolved: fused on TPU, xla elsewhere
    (platform leg only — the dtype/block-size leg lives in
    ``tile_ops.pallas_panel.panel_uses_fused``, the route's single
    owner). An active autotune route (docs/autotune.md) overrides the
    resolution."""
    routed = _route_override("panel_impl")
    if routed is not None:
        return routed
    return resolve_platform_auto(
        get_configuration().panel_impl, knob="panel_impl",
        tpu_choice="fused", other_choice="xla",
        detail="the per-step potrf+trsm chain is latency-bound on TPU "
               "(MFU table: 1.9-7.3% with neither roofline binding); the "
               "fused Pallas panel kernels collapse it to one dispatch "
               "per step (docs/pallas_panel.md)")


def resolved_step_impl() -> str:
    """``step_impl`` with "auto" resolved: fused on TPU, xla elsewhere
    (platform leg only — the dtype/block/VMEM-budget legs live in
    ``tile_ops.pallas_panel.step_uses_fused``, the route's single
    owner). An active autotune route (docs/autotune.md) overrides the
    resolution."""
    routed = _route_override("step_impl")
    if routed is not None:
        return routed
    return resolve_platform_auto(
        get_configuration().step_impl, knob="step_impl",
        tpu_choice="fused", other_choice="xla",
        detail="the remaining panel-bound floor is the kernel-launch + "
               "HBM round-trip between panel factorization and trailing "
               "update at every blocked step (ROADMAP item 4); the fused "
               "step kernel removes the boundary (docs/pallas_panel.md)")


def resolved_cholesky_lookahead() -> bool:
    """``cholesky_lookahead`` with "auto" resolved (True = pipelined):
    1 on TPU, 0 elsewhere (see the knob docstring for the basis)."""
    return resolve_platform_auto(
        get_configuration().cholesky_lookahead, knob="cholesky_lookahead",
        tpu_choice="1", other_choice="0",
        detail="panel-chain latency dominates blocked factorizations on "
               "TPU (config #1: 133 GF/s at N=4096 vs 514 at N=16384); "
               "the pipelined step order exposes panel k+1 to XLA while "
               "the bulk trailing update of step k is in flight") == "1"


def resolved_comm_lookahead() -> bool:
    """``comm_lookahead`` with "auto" resolved (True = collectives
    hoisted): 1 on TPU, 0 elsewhere (see the knob docstring and
    docs/comm_overlap.md)."""
    return resolve_platform_auto(
        get_configuration().comm_lookahead, knob="comm_lookahead",
        tpu_choice="1", other_choice="0",
        detail="ICI transfer time adds serially to the step chain unless "
               "the next panel's collectives are emitted before the bulk "
               "trailing product (arXiv:2112.09017's overlapped SUMMA "
               "updates); off-TPU the thunk executor runs collectives "
               "serially anyway") == "1"


def resolved_dc_level_batch() -> bool:
    """``dc_level_batch`` with "auto" resolved (True = level-batched D&C
    merges): 1 on TPU, 0 elsewhere (see the knob docstring and
    docs/eigensolver_perf.md)."""
    return resolve_platform_auto(
        get_configuration().dc_level_batch, knob="dc_level_batch",
        tpu_choice="1", other_choice="0",
        detail="the serialized merge walk pays one host->device dispatch "
               "round trip per small merge; batching a level's merges "
               "into one vmapped program is the arXiv:2112.09017 idiom "
               "that earns MXU utilization on many small problems") == "1"


def resolved_bt_lookahead() -> bool:
    """``bt_lookahead`` with "auto" resolved (True = pipelined reflector
    blocks): 1 on TPU, 0 elsewhere (see the knob docstring and
    docs/eigensolver_perf.md)."""
    return resolve_platform_auto(
        get_configuration().bt_lookahead, knob="bt_lookahead",
        tpu_choice="1", other_choice="0",
        detail="the reflector-block T-factor chain (and its panel gather "
               "collectives, distributed) is latency-bound and reads only "
               "constant reflector storage; emitting block k+1's chain "
               "before block k's bulk application lets it hide under the "
               "MXU bulk") == "1"


#: Step counts at which ``dist_step_mode="auto"`` switches to the scan
#: formulation, per platform. The TPU point now rests on the MEASURED
#: silicon ladder (scripts/tpu_nsweep.py, 2026-08-01 session, telescoped
#: scan, nb=256): run premium 1.149x at nt=16 (N=4096) and 1.248x at
#: nt=32 (N=8192) — the premium GROWS with nt (more telescope windows =
#: more slot padding), so lowering the threshold buys nothing, while the
#: compile side still cliffs: the hardware AOT toolchain compiles
#: unrolled per-step programs at ~19 s/step (vs ~2.3 s total for scan),
#: i.e. 10+ cold minutes at nt=32 against a 0.13 s/run premium — a
#: ~4600-run break-even no real session reaches. 32 therefore stays: a
#: COLD cache argues for scan well below it, a warm cache amortizes
#: unrolled compiles away above it. The CPU toolchain's ~0.35 s/step
#: constant moves the breakpoint to ~128.
STEP_MODE_AUTO_SCAN_AT = {"tpu": 32, "cpu": 128}


def resolve_step_mode(steps: int, platform: Optional[str] = None) -> str:
    """Effective step formulation for an algorithm with ``steps`` traced
    per-k steps: the configured ``dist_step_mode``, with ``"auto"``
    resolved per (step count, platform) from the measured compile
    constants (:data:`STEP_MODE_AUTO_SCAN_AT`). ``platform`` defaults to
    the jax default backend."""
    mode = get_configuration().dist_step_mode
    if mode != "auto":
        return mode
    if platform is None:
        import jax

        platform = jax.default_backend()
    return "scan" if steps >= STEP_MODE_AUTO_SCAN_AT.get(platform, 128) \
        else "unrolled"


def finalize() -> None:
    """Tear down (analog of ``dlaf::finalize``); PJRT owns real resources."""
    global _active
    _active = None
