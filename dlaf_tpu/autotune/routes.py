"""Precision routes and their escalation ladders (docs/autotune.md).

A :class:`Route` is one point in the precision/speed trade the autotuner
steers: a set of OVERRIDES over the resolved config knobs that dominate
TPU f64-emulation cost — the Ozaki slice count (``f64_gemm_slices``),
the panel-solve refinement route (``f64_trsm``), the panel factorization
kernels (``panel_impl``), and the Ozaki slice-reduction implementation
(``ozaki_impl``). A field left ``None`` inherits the ordinary config
resolution, so the EMPTY route is exactly the platform default — and the
``DLAF_AUTOTUNE=0`` bitwise-passthrough contract falls out of the same
property (tests/test_autotune.py).

A *ladder* is an ordered tuple of routes from fastest/least-conservative
(rung 0) to safest/most-conservative (top rung), with a ``start`` rung
per ladder. Ladder discipline (docs/autotune.md):

* every rung's overrides only BIND where the underlying route is active
  (the slice count is only read on the mxu gemm path; ``ozaki_impl=
  "pallas"`` only applies inside the mxu route; ``f64_trsm="native"``
  and ``panel_impl="xla"`` coincide with the off-TPU defaults) — so on
  CPU every rung of both ladders is behavior-inert and the decision
  machinery can be drilled without perturbing numerics, while on TPU the
  rungs move real silicon routes;
* the ``start`` rung matches the platform default route, so a fresh
  table changes nothing until probes justify a move.

The ACTIVE route is carried in a contextvar (:func:`applied`) that the
knob-resolution single owners consult (``tile_ops.blas._oz_slices`` /
``trsm_panel_uses_mixed``, ``tile_ops.pallas_panel.panel_uses_fused``,
the cholesky entry's ``ozaki_impl`` gate). Because those reads happen at
trace time, every program cache on a route-sensitive path carries
``Route.key()`` as a static cache-key component — a route change is a
CACHE KEY change, dispatched through a different compiled program, never
an in-place retrace (the PR 7/11 keyed-cache discipline; the zero-
steady-state-retrace pin in tests/test_autotune.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional, Tuple

import numpy as np

#: Fields a route may override, in serialization order. Each is the name
#: of the config knob it shadows.
ROUTE_FIELDS = ("f64_gemm_slices", "f64_trsm", "panel_impl", "ozaki_impl",
                "step_impl")


@dataclasses.dataclass(frozen=True)
class Route:
    """One precision route: overrides over the resolved config knobs
    (None = inherit the ordinary resolution)."""

    f64_gemm_slices: Optional[int] = None
    f64_trsm: Optional[str] = None        # "mixed" | "native"
    panel_impl: Optional[str] = None      # "fused" | "xla"
    ozaki_impl: Optional[str] = None      # "jnp" | "pallas"
    step_impl: Optional[str] = None       # "fused" | "xla"

    def key(self) -> tuple:
        """Hashable cache-key component for the program caches: a route
        change MUST change every affected program's cache key (module
        docstring). The empty route keys as ``()`` so route-free callers
        keep their existing cache identities."""
        items = tuple((f, getattr(self, f)) for f in ROUTE_FIELDS
                      if getattr(self, f) is not None)
        return items

    def tag(self) -> str:
        """Compact human/metric label, e.g. ``s5.ozpallas`` (``default``
        for the empty route) — bounded cardinality: one per ladder rung."""
        parts = []
        if self.f64_gemm_slices is not None:
            parts.append(f"s{self.f64_gemm_slices}")
        if self.f64_trsm is not None:
            parts.append(f"trsm_{self.f64_trsm}")
        if self.panel_impl is not None:
            parts.append(f"panel_{self.panel_impl}")
        if self.ozaki_impl is not None:
            parts.append(f"oz{self.ozaki_impl}")
        if self.step_impl is not None:
            parts.append(f"step_{self.step_impl}")
        return ".".join(parts) or "default"

    def as_dict(self) -> dict:
        """The non-None overrides (JSONL ``autotune`` record payload)."""
        return {f: getattr(self, f) for f in ROUTE_FIELDS
                if getattr(self, f) is not None}


@dataclasses.dataclass(frozen=True)
class Ladder:
    """An escalation ladder: rungs fast -> safe, plus the start rung
    (the platform-default route) and a stable identity string that the
    persisted table refuses to warm-start across (a rung learned against
    one ladder must not index into a different one)."""

    name: str
    rungs: Tuple[Route, ...]
    start: int

    def __post_init__(self):
        assert 0 <= self.start < len(self.rungs), \
            f"ladder {self.name}: start {self.start} outside rungs"

    @property
    def ident(self) -> str:
        """Version-stable identity: name + rung count + every rung tag.
        Any ladder edit changes it, which makes previously persisted
        entries for it STALE (table.load refuses loudly)."""
        return f"{self.name}:{len(self.rungs)}:" + \
            ",".join(r.tag() for r in self.rungs)


#: f64/complex128 ladder: the Ozaki slice count s=5..8 (arXiv:2604.04599's
#: per-shape gemm-route selection), with the fused Pallas slice kernels
#: (``ozaki_impl="pallas"``, ~48-bit double-f32 fold — the fastest, least
#: conservative reduction) as the bottom rung and the native-f64 panel
#: solves (``f64_trsm="native"``) as the safety top. Rung 3 (s=7, the
#: TPU auto default) is the start. Every override only binds inside the
#: mxu gemm route, so the whole ladder is inert where f64_gemm resolves
#: "native" (CPU) — see the module docstring's ladder discipline. Rung 0
#: additionally arms ``step_impl="fused"``: dormant today (the fused
#: step kernel is f32/bf16-only, and a route override never counts a
#: fallback — :func:`~dlaf_tpu.tile_ops.pallas_panel.step_uses_fused`),
#: it pre-registers the fastest step route on the fastest rung for when
#: the emulated-f64 panel chain learns to ride it.
LADDER_F64 = Ladder(
    name="f64",
    rungs=(
        Route(f64_gemm_slices=5, ozaki_impl="pallas", step_impl="fused"),
        Route(f64_gemm_slices=5),
        Route(f64_gemm_slices=6),
        Route(f64_gemm_slices=7),
        Route(f64_gemm_slices=8),
        Route(f64_gemm_slices=8, f64_trsm="native"),
    ),
    start=3,
)

#: f32/bf16 ladder: the fused step kernel (one pallas_call per blocked
#: step — the fastest, least conservative rung) above the platform
#: default (start; on TPU the auto knobs already resolve both fusions
#: on), degrading first to the composed per-op chain with only the
#: panel kernels fused (``step_impl="xla"``) and finally to the generic
#: XLA chain (docs/pallas_panel.md documents the impls as ulp-distinct
#: at equal analytic budget; the generic route is the reference arbiter
#: when a probe breaches). The ``step_impl="fused"`` override binds only
#: on TPU (:func:`dlaf_tpu.tile_ops.pallas_panel.step_uses_fused`), so
#: the ladder stays behavior-inert on CPU.
LADDER_F32 = Ladder(
    name="f32",
    rungs=(
        Route(step_impl="fused"),
        Route(),
        Route(step_impl="xla"),
        Route(step_impl="xla", panel_impl="xla"),
    ),
    start=1,
)

_LADDERS = {
    np.dtype(np.float64): LADDER_F64,
    np.dtype(np.complex128): LADDER_F64,
    np.dtype(np.float32): LADDER_F32,
    # bf16 shares the f32 panel treatment (pallas_panel._SUPPORTED)
}


def ladder_for(dtype) -> Optional[Ladder]:
    """The ladder tuning this dtype's routes, or None (dtype untuned —
    the autotuner leaves it entirely alone)."""
    try:
        dt = np.dtype(dtype)
    except TypeError:
        return None
    if dt == np.dtype(np.float32):
        return _LADDERS[dt]
    if str(dt) == "bfloat16":
        return LADDER_F32
    return _LADDERS.get(dt)


# ---------------------------------------------------------------------------
# Active-route context
# ---------------------------------------------------------------------------

_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "dlaf_autotune_route", default=None)


def active() -> Optional[Route]:
    """The route applied by the innermost :func:`applied` context (None =
    no override, ordinary knob resolution)."""
    return _ACTIVE.get()


def override(field: str):
    """The active route's override for ``field`` (None = inherit) — the
    one consult the knob-resolution single owners make."""
    route = _ACTIVE.get()
    return None if route is None else getattr(route, field)


@contextlib.contextmanager
def applied(route: Optional[Route]):
    """Apply ``route``'s overrides for the duration (None = no-op).
    Entries hold this open across their builder-cache lookup AND the
    first call, because the overrides are read at trace time — and every
    such cache keys on ``Route.key()``, so a stale trace cannot be
    reused under a different route (module docstring)."""
    if route is None:
        yield
        return
    token = _ACTIVE.set(route)
    try:
        yield
    finally:
        _ACTIVE.reset(token)
