"""dlaf_tpu.autotune — accuracy-steered precision route selection
(ISSUE 15, docs/autotune.md).

The closed loop over PR 8's numerical-quality signal: the static
precision knobs that dominate TPU f64-emulation cost
(``f64_gemm_slices`` / ``f64_trsm`` / ``panel_impl`` / ``ozaki_impl``)
become an adaptive policy layer chosen per ``(op, n-bucket, nb, dtype,
platform)`` from the MEASURED ``bound_ratio`` trajectory — the LP-GEMM /
TVM-generator observation (arXiv:2604.04599, arXiv:2310.20347) that the
gemm route should be selected per layout/shape, not globally.

Three parts, behind the layered ``DLAF_AUTOTUNE`` knob ("0"/"1"/"auto";
auto = 1 on TPU):

* :mod:`.routes` — :class:`Route` overrides + the escalation ladders +
  the active-route context the knob-resolution single owners consult;
* :mod:`.table` — the :class:`RouteTable` keyed by site, the PURE
  decision core :func:`~dlaf_tpu.autotune.table.decide` (escalate on
  breach, relax after K comfortable probes, documented hysteresis), and
  schema-validated atomic JSON persistence (``DLAF_AUTOTUNE_TABLE``,
  warm-start like the bench/accuracy histories);
* :mod:`.controller` — the per-entry :func:`steering` handle (route out,
  probe in), the ``autotune`` record/metric emission, and the
  escalation-exhaustion incident path (flight recorder +
  ``DLAF_STRICT``).

Cost contract: with the knob off, every entry pays one config read and
no probe; the factor outputs are bitwise identical knob on/off at the
start rung (the ladders' start routes ARE the platform defaults —
tests/test_autotune.py pins the passthrough).
"""

from __future__ import annotations

from .controller import (Steering, applied, enabled, get_table,
                         ingest_result, observe_ratio,
                         route_metric_values, steering,
                         steering_for_matrix)
from .routes import (LADDER_F32, LADDER_F64, Ladder, Route, active,
                     ladder_for, override)
from .table import (HISTORY_CAP, REASONS, TABLE_VERSION, Decision, Entry,
                    RouteTable, SiteKey, bucket_n, decide, site_key)

__all__ = [
    "Route", "Ladder", "LADDER_F64", "LADDER_F32", "ladder_for",
    "active", "override", "applied",
    "RouteTable", "SiteKey", "Entry", "Decision", "decide", "site_key",
    "bucket_n", "REASONS", "TABLE_VERSION", "HISTORY_CAP",
    "enabled", "steering", "steering_for_matrix", "Steering",
    "observe_ratio", "ingest_result", "get_table", "route_metric_values",
]


def _reset_for_tests() -> None:
    from . import controller

    controller._reset_for_tests()
