"""The route table: per-site route state + the pure decision core
(docs/autotune.md).

One :class:`Entry` per site key ``(op, n_bucket, nb, dtype, platform)``
holds the current ladder rung, the consecutive-comfortable-probe count
(the relax hysteresis), the route-change budget accounting, and a short
probe history (observability, not decision state). Decisions are made by
:func:`decide` — a PURE function of ``(entry state, probe, policy)`` with
no clocks, randomness, or global reads — so an injected probe sequence
replays the exact same decision trail every time (the drill determinism
contract; pinned by tests/test_autotune.py).

Decision semantics (hysteresis, docs/autotune.md):

* ``bound_ratio > 1`` (or a non-finite probe — worse): **escalate** one
  rung IMMEDIATELY (never throttled by the budget: escalation is the
  "never silently wrong" half of the contract). At the top rung there is
  nowhere safer to go: the decision is **exhausted** (the controller
  raises under ``DLAF_STRICT`` and trips the flight recorder).
* ``bound_ratio <= margin`` (``DLAF_AUTOTUNE_MARGIN``): one comfortable
  probe. After ``DLAF_AUTOTUNE_RELAX_AFTER`` CONSECUTIVE comfortable
  probes, **relax** one rung (fastest rung = floor; the relax consumes
  one unit of the per-site ``DLAF_AUTOTUNE_BUDGET`` — exhausted budget
  holds instead, bounding route churn per process).
* anything between: **hold**, and the comfortable streak resets — a
  probe near the budget edge must restart the relax clock.

Persistence (:meth:`RouteTable.save` / :func:`load_table`): a
schema-versioned JSON document written ATOMICALLY (temp file +
``os.replace``, the checkpoint/flight discipline) so a killed process
never leaves a torn table; ``load`` refuses loudly — naming the field —
on malformed entries, a version mismatch, or entries stale against the
current ladder definitions (the warm-start contract: a table is either
trustworthy or rejected, never silently partially applied).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
from typing import Dict, Optional

from .routes import Ladder, Route, ladder_for

#: Persisted-table schema version; bumped on any incompatible change.
TABLE_VERSION = 1

#: Probe history kept per entry (observability/debugging only — never
#: decision state, which is exactly (rung, holds, changes)).
HISTORY_CAP = 8

#: Decision vocabulary (mirrored by the ``autotune`` record schema in
#: obs/sinks.py, the single schema owner).
REASONS = ("escalate", "relax", "hold", "exhausted")


def bucket_n(n: int) -> int:
    """The table's n-bucket: next power of two >= max(n, 8) — the serve
    layer's auto bucket policy, so offline-learned routes and serving
    buckets share entries (docs/autotune.md §table)."""
    return 1 << max(int(n) - 1, 7).bit_length()


@dataclasses.dataclass(frozen=True)
class SiteKey:
    """One tuned site: the route-table key (ISSUE 15 tentpole (a))."""

    op: str
    n_bucket: int
    nb: int
    dtype: str
    platform: str

    @property
    def label(self) -> str:
        return (f"{self.op}.n{self.n_bucket}.nb{self.nb}."
                f"{self.dtype}.{self.platform}")


def site_key(op: str, *, n: int, nb: int, dtype, platform: str) -> SiteKey:
    import numpy as np

    return SiteKey(op=str(op), n_bucket=bucket_n(n), nb=int(nb),
                   dtype=np.dtype(dtype).name, platform=str(platform))


@dataclasses.dataclass
class Entry:
    """Mutable per-site state (decision state + audit history)."""

    rung: int
    holds: int = 0
    changes: int = 0            # relaxes consumed against the budget
    escalations: int = 0
    history: list = dataclasses.field(default_factory=list)
    calls: int = 0              # probe-cadence counter (never persisted)


@dataclasses.dataclass(frozen=True)
class Decision:
    """One decision: the reason, the rung transition, and the probe that
    drove it (``probe`` is +inf for a non-finite estimate)."""

    reason: str
    rung_old: int
    rung_new: int
    probe: float
    nonfinite: bool = False


def decide(rung: int, holds: int, changes: int, ratio: float, *,
           ladder_len: int, margin: float, relax_after: int,
           budget: int):
    """THE decision core — a pure function of (state, probe, policy);
    returns ``(reason, rung_new, holds_new, changes_new)``. See the
    module docstring for the semantics; every branch is pinned by
    tests/test_autotune.py's injected-probe sequences."""
    nonfinite = not math.isfinite(ratio)
    if nonfinite or ratio > 1.0:
        # breach: escalate immediately (budget never throttles safety)
        if rung + 1 < ladder_len:
            return "escalate", rung + 1, 0, changes
        return "exhausted", rung, 0, changes
    if ratio <= margin:
        holds += 1
        if holds >= relax_after and rung > 0 \
                and (budget == 0 or changes < budget):
            return "relax", rung - 1, 0, changes + 1
        return "hold", rung, holds, changes
    # inside the budget but not comfortable: hold, streak resets
    return "hold", rung, 0, changes


class RouteTable:
    """Thread-safe site -> :class:`Entry` map over the ladder catalog
    (module docstring). ``path`` (optional) arms persistence: every
    applied decision re-serializes the table atomically."""

    def __init__(self, path: str = ""):
        self.path = str(path or "")
        self._entries: Dict[SiteKey, Entry] = {}
        self._lock = threading.RLock()

    # -- route lookup ----------------------------------------------------

    def entry(self, key: SiteKey, ladder: Ladder) -> Entry:
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = Entry(rung=ladder.start)
                self._entries[key] = ent
            return ent

    def route_for(self, key: SiteKey, ladder: Ladder) -> Route:
        with self._lock:
            return ladder.rungs[self.entry(key, ladder).rung]

    def rung_of(self, key: SiteKey) -> Optional[int]:
        with self._lock:
            ent = self._entries.get(key)
            return None if ent is None else ent.rung

    def tick(self, key: SiteKey, ladder: Ladder, every: int) -> bool:
        """Count one entry call against the site; True when the probe
        cadence (``DLAF_AUTOTUNE_PROBE_EVERY``) says this call should
        probe — the FIRST call always does. Call counts are in-memory
        only (persisting per call would turn every entry into a table
        write; decisions persist, ticks do not)."""
        with self._lock:
            ent = self.entry(key, ladder)
            due = ent.calls % max(int(every), 1) == 0
            ent.calls += 1
            return due

    # -- decisions -------------------------------------------------------

    def observe(self, key: SiteKey, ladder: Ladder, ratio: float, *,
                margin: float, relax_after: int, budget: int) -> Decision:
        """Feed one probe ``bound_ratio``; applies :func:`decide` to the
        site's entry and persists (when armed). Returns the decision."""
        nonfinite = not math.isfinite(float(ratio))
        with self._lock:
            ent = self.entry(key, ladder)
            reason, rung_new, holds_new, changes_new = decide(
                ent.rung, ent.holds, ent.changes, float(ratio),
                ladder_len=len(ladder.rungs), margin=margin,
                relax_after=relax_after, budget=budget)
            decision = Decision(reason=reason, rung_old=ent.rung,
                                rung_new=rung_new,
                                probe=(float("inf") if nonfinite
                                       else float(ratio)),
                                nonfinite=nonfinite)
            ent.rung = rung_new
            ent.holds = holds_new
            ent.changes = changes_new
            if reason == "escalate":
                ent.escalations += 1
            ent.history.append(None if nonfinite else float(ratio))
            del ent.history[:-HISTORY_CAP]
            if self.path:
                self._save_locked(self.path)
        return decision

    # -- persistence -----------------------------------------------------

    def to_json(self) -> dict:
        with self._lock:
            entries = []
            for key in sorted(self._entries, key=lambda k: k.label):
                ent = self._entries[key]
                ladder = ladder_for(key.dtype)
                entries.append({
                    "op": key.op, "n_bucket": key.n_bucket, "nb": key.nb,
                    "dtype": key.dtype, "platform": key.platform,
                    "ladder": ladder.ident if ladder is not None else "",
                    "rung": ent.rung, "holds": ent.holds,
                    "changes": ent.changes,
                    "escalations": ent.escalations,
                    "history": list(ent.history),
                })
            return {"version": TABLE_VERSION, "entries": entries}

    def save(self, path: Optional[str] = None) -> str:
        with self._lock:
            return self._save_locked(path or self.path)

    def _save_locked(self, path: str) -> str:
        if not path:
            raise ValueError("RouteTable.save: no path configured "
                             "(DLAF_AUTOTUNE_TABLE)")
        doc = self.to_json()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        # atomic-replace discipline (matrix/checkpoint.py, obs/flight.py):
        # the table either exists complete or keeps its previous content
        os.replace(tmp, path)
        return path

    def load_dict(self, doc: dict, *, where: str = "<table>") -> None:
        """Warm-start from a parsed table document; refuses LOUDLY —
        naming the failing field — on malformed/stale/version-mismatched
        content (module docstring)."""
        if not isinstance(doc, dict):
            raise ValueError(f"{where}: autotune table must be a JSON "
                             "object")
        version = doc.get("version")
        if version != TABLE_VERSION:
            raise ValueError(
                f"{where}: field 'version' is {version!r}, this build "
                f"reads version {TABLE_VERSION} — refusing a cross-"
                "version warm start (re-learn or migrate the table)")
        entries = doc.get("entries")
        if not isinstance(entries, list):
            raise ValueError(f"{where}: field 'entries' must be a list, "
                             f"got {type(entries).__name__}")
        parsed: Dict[SiteKey, Entry] = {}
        for i, ent in enumerate(entries):
            w = f"{where}: entries[{i}]"
            if not isinstance(ent, dict):
                raise ValueError(f"{w}: must be an object")
            for field in ("op", "dtype", "platform", "ladder"):
                if not isinstance(ent.get(field), str) or not ent.get(field):
                    raise ValueError(f"{w}: field {field!r} missing or "
                                     "not a non-empty string")
            for field in ("n_bucket", "nb", "rung", "holds", "changes",
                          "escalations"):
                v = ent.get(field)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    raise ValueError(f"{w}: field {field!r} must be a "
                                     f"non-negative int, got {v!r}")
            hist = ent.get("history", [])
            if not isinstance(hist, list) or any(
                    h is not None and not isinstance(h, (int, float))
                    for h in hist):
                raise ValueError(f"{w}: field 'history' must be a list "
                                 "of numbers/nulls")
            ladder = ladder_for(ent["dtype"])
            if ladder is None:
                raise ValueError(f"{w}: field 'dtype' ({ent['dtype']!r}) "
                                 "has no ladder in this build — stale "
                                 "entry, refusing the warm start")
            if ent["ladder"] != ladder.ident:
                raise ValueError(
                    f"{w}: field 'ladder' ({ent['ladder']!r}) does not "
                    f"match this build's {ladder.ident!r} — the rung "
                    "indexes a different ladder; refusing the stale "
                    "warm start")
            if ent["rung"] >= len(ladder.rungs):
                raise ValueError(
                    f"{w}: field 'rung' ({ent['rung']}) outside the "
                    f"{len(ladder.rungs)}-rung {ladder.name} ladder")
            key = SiteKey(op=ent["op"], n_bucket=ent["n_bucket"],
                          nb=ent["nb"], dtype=ent["dtype"],
                          platform=ent["platform"])
            parsed[key] = Entry(
                rung=ent["rung"], holds=ent["holds"],
                changes=ent["changes"], escalations=ent["escalations"],
                history=[None if h is None else float(h) for h in hist])
        with self._lock:
            self._entries = parsed

    def load(self, path: Optional[str] = None) -> None:
        path = path or self.path
        doc = None
        for attempt in range(2):
            try:
                with open(path) as f:
                    doc = json.load(f)
                break
            except ValueError as e:
                # writers replace the table atomically (tmp + fsync +
                # os.replace), but a reader that opened the OLD inode
                # right as it was unlinked can still see a short read on
                # some filesystems. One immediate re-open lands on the
                # NEW complete inode; only a second failure means the
                # file is genuinely corrupt — refuse the warm start then.
                if attempt:
                    raise ValueError(f"{path}: unparsable autotune "
                                     f"table ({e})")
        self.load_dict(doc, where=path)

    # -- introspection ---------------------------------------------------

    def snapshot(self) -> dict:
        """Label -> entry summary (profile_summary's decision-trail
        section and /healthz-adjacent probes)."""
        with self._lock:
            return {k.label: dataclasses.asdict(e)
                    for k, e in self._entries.items()}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
