"""The closed-loop controller: probe -> table -> route (docs/autotune.md).

Wiring (ISSUE 15 tentpole (b)): an algorithm entry asks
:func:`steering` for its site's current route BEFORE building/dispatching
(the route rides the entry's program-cache keys and the
:mod:`dlaf_tpu.autotune.routes` context), runs the factorization, then —
when the input survived (not donated) — feeds PR 8's cheap Hutchinson
probe of the result back through :meth:`Steering.observe`. No new device
code: the probe IS :mod:`dlaf_tpu.obs.accuracy`'s estimator family, and
the ``bound_ratio`` normalization IS :func:`dlaf_tpu.obs.accuracy.emit`'s
(computed with ``record=False`` — the probe lands in the ``autotune``
decision record, while ordinary ``accuracy`` records remain the
``DLAF_ACCURACY`` knob's business).

Every decision (including holds) lands as one ``autotune`` JSONL record
(site, op, rungs, old/new route, probe, reason — obs/sinks.py owns the
schema) plus ``dlaf_autotune_route{op,knob}`` gauges and the
``dlaf_autotune_decisions_total{op,reason}`` /
``dlaf_autotune_escalations_total{op}`` counters. Escalation exhaustion
(a breach at the ladder top) additionally trips the flight recorder
(reason ``autotune_exhausted``) and raises
:class:`~dlaf_tpu.health.errors.AutotuneExhaustedError` under
``DLAF_STRICT`` — at the top of the ladder there is no safer route, so
strict deployments must fail loudly rather than keep serving numbers the
probes say are out of budget.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from . import routes as _routes
from . import table as _table
from .routes import Ladder, Route, applied, ladder_for
from .table import Decision, RouteTable, SiteKey, site_key

__all__ = ["enabled", "steering", "steering_for_matrix", "Steering",
           "observe_ratio", "ingest_result", "applied", "get_table",
           "route_metric_values"]


def enabled() -> bool:
    """The layered ``DLAF_AUTOTUNE`` knob, "auto" resolved per platform:
    1 on TPU (the "fast by default, never silently wrong" production
    default), 0 elsewhere."""
    from ..config import get_configuration, resolve_platform_auto

    return resolve_platform_auto(
        get_configuration().autotune, knob="autotune", tpu_choice="1",
        other_choice="0",
        detail="accuracy-steered precision routes pay off exactly where "
               "the f64-emulation knobs bind (the mxu/mixed/pallas "
               "routes); elsewhere the ladder is behavior-inert and the "
               "probe devices-work buys nothing") == "1"


# ---------------------------------------------------------------------------
# Process table
# ---------------------------------------------------------------------------

_TABLE: Optional[RouteTable] = None
_TABLE_PATH: Optional[str] = None
_TABLE_LOCK = threading.Lock()


def get_table() -> RouteTable:
    """The process route table, re-bound (and warm-started) whenever the
    ``DLAF_AUTOTUNE_TABLE`` knob changes. A configured path that exists
    loads eagerly — and a malformed/stale/mismatched table raises HERE,
    at first use, naming the field (never a silent cold start over a
    table the operator committed)."""
    global _TABLE, _TABLE_PATH
    from ..config import get_configuration

    path = str(get_configuration().autotune_table or "")
    with _TABLE_LOCK:
        if _TABLE is None or path != _TABLE_PATH:
            tab = RouteTable(path)
            if path:
                import os

                if os.path.exists(path):
                    tab.load(path)
            _TABLE = tab
            _TABLE_PATH = path
        return _TABLE


def _reset_for_tests() -> None:
    global _TABLE, _TABLE_PATH
    with _TABLE_LOCK:
        _TABLE = None
        _TABLE_PATH = None


# ---------------------------------------------------------------------------
# Steering handle
# ---------------------------------------------------------------------------

#: Gauge value encodings for the non-numeric route knobs
#: (``dlaf_autotune_route{op,knob}``): higher = more conservative.
_KNOB_VALUES = {
    "f64_trsm": {"mixed": 0.0, "native": 1.0},
    "panel_impl": {"fused": 0.0, "xla": 1.0},
    "ozaki_impl": {"pallas": 0.0, "jnp": 1.0},
    "step_impl": {"fused": 0.0, "xla": 1.0},
}


def route_metric_values(route: Route) -> dict:
    """knob -> numeric gauge value for a route's overrides (plus nothing
    for inherited fields — the gauge only reports what the autotuner is
    actually pinning)."""
    out = {}
    for knob, value in route.as_dict().items():
        if knob == "f64_gemm_slices":
            out[knob] = float(value)
        else:
            out[knob] = _KNOB_VALUES[knob][value]
    return out


@dataclasses.dataclass
class Steering:
    """One entry's steering handle: the key, ladder, and the route in
    effect for this call (:func:`steering`)."""

    key: SiteKey
    ladder: Ladder
    route: Route
    site: str
    #: the ACTUAL problem dimension (not the bucket ceiling): the probe's
    #: analytic tolerance must match what the DLAF_ACCURACY records use
    #: for the same result — normalizing with the power-of-two bucket
    #: would loosen the breach budget by up to 2x mid-bucket
    n: int = 0
    #: probe-cadence verdict (``DLAF_AUTOTUNE_PROBE_EVERY``): entries
    #: skip the residual probe when False (the route still applies)
    probe_due: bool = True

    def applied(self):
        """Context manager applying :attr:`route` (sugar over
        :func:`dlaf_tpu.autotune.routes.applied`)."""
        return _routes.applied(self.route)

    def observe(self, value, *, c: float, of=None,
                attrs: Optional[dict] = None) -> Decision:
        """Feed one raw probe estimate (the accuracy estimator's
        residual) back into the table; normalization to ``bound_ratio``
        rides :func:`dlaf_tpu.obs.accuracy.emit` with ``record=False``
        (module docstring). Returns the decision (emitting the
        ``autotune`` record + metrics; strict-raising on exhaustion)."""
        from ..obs import accuracy

        res = accuracy.emit(self.site, "autotune_probe", value,
                            n=self.n or self.key.n_bucket,
                            nb=self.key.nb,
                            dtype=self.key.dtype, c=c, of=of,
                            record=False)
        ratio = res.bound_ratio if res.finite and res.bound_ratio \
            is not None else float("inf")
        return observe_ratio(self.key, self.ladder, ratio,
                             probe_value=(res.value if res.finite
                                          else None),
                             attrs=attrs)


def steering(op: str, *, n: int, nb: int, dtype,
             platform: Optional[str] = None,
             tick: bool = False) -> Optional[Steering]:
    """The steering handle for one entry call, or None when the loop is
    closed for it: knob off, an untuned dtype (no ladder), or an empty
    problem. ``platform`` defaults to the process backend. ``tick=True``
    counts the call against the site's probe cadence
    (``DLAF_AUTOTUNE_PROBE_EVERY``) and sets :attr:`Steering.probe_due`
    accordingly — the algorithm entries tick; identity-only consults
    (the serve queue's spec labels) do not."""
    if int(n) < 1 or not enabled():
        return None
    ladder = ladder_for(dtype)
    if ladder is None:
        return None
    if platform is None:
        import jax

        platform = jax.default_backend()
    key = site_key(op, n=n, nb=nb, dtype=dtype, platform=platform)
    table = get_table()
    route = table.route_for(key, ladder)
    due = True
    if tick:
        from ..config import get_configuration

        due = table.tick(key, ladder,
                         get_configuration().autotune_probe_every)
    return Steering(key=key, ladder=ladder, route=route, site=key.label,
                    n=int(n), probe_due=due)


def steering_for_matrix(op: str, mat) -> Optional[Steering]:
    """:func:`steering` for a :class:`~dlaf_tpu.matrix.matrix.Matrix`
    entry argument — platform judged from the matrix's own mesh when
    distributed (the entry-span convention), else the process backend."""
    if mat.size.is_empty():
        return None
    if mat.grid is not None and mat.grid.num_devices > 1:
        platform = next(iter(mat.grid.mesh.devices.flat)).platform
    else:
        platform = None
    return steering(op, n=mat.size.row, nb=mat.block_size.row,
                    dtype=mat.dtype, platform=platform, tick=True)


def ingest_result(op: str, result, *, n: int, nb: int, dtype,
                  platform: Optional[str] = None,
                  attrs: Optional[dict] = None) -> Optional[Decision]:
    """Feed an ALREADY-computed residual into the table: the donated-
    entry path. Timed miniapp runs donate their input (the N=16384
    HBM story), so the entry itself has nothing left to probe — but the
    miniapp's ``--check-result`` / ``DLAF_ACCURACY`` probes compute the
    same residual against the kept reference copy; this ingests their
    :class:`~dlaf_tpu.obs.accuracy.AccuracyResult` when the loop is
    armed. Informational results (no budget -> no ``bound_ratio``) and
    untuned dtypes are ignored. Returns the decision, or None."""
    if not enabled():
        return None
    ladder = ladder_for(dtype)
    if ladder is None:
        return None
    if result.tol is None:
        return None
    if platform is None:
        import jax

        platform = jax.default_backend()
    key = site_key(op, n=n, nb=nb, dtype=dtype, platform=platform)
    ratio = result.bound_ratio if result.finite \
        and result.bound_ratio is not None else float("inf")
    return observe_ratio(key, ladder, ratio,
                         probe_value=(result.value if result.finite
                                      else None),
                         attrs=dict(attrs or {}, source="ingest"))


def observe_ratio(key: SiteKey, ladder: Ladder, ratio: float, *,
                  probe_value: Optional[float] = None,
                  attrs: Optional[dict] = None) -> Decision:
    """Feed one normalized ``bound_ratio`` probe for ``key`` into the
    table and publish the decision (record + gauges + counters + flight/
    strict handling). The serve queue calls this directly with its
    per-bucket residual ratios; entries go through
    :meth:`Steering.observe`."""
    from .. import obs
    from ..config import get_configuration

    cfg = get_configuration()
    table = get_table()
    decision = table.observe(
        key, ladder, ratio, margin=float(cfg.autotune_margin),
        relax_after=int(cfg.autotune_relax_after),
        budget=int(cfg.autotune_budget))
    # both routes derived from THE decision's rungs (not a separate
    # pre-observe table read): under concurrent feeds a second lock
    # round-trip could pair one decision's rung_old with another's route
    route_old = ladder.rungs[decision.rung_old]
    route_new = ladder.rungs[decision.rung_new]
    rec = {"site": key.label, "op": key.op, "n_bucket": key.n_bucket,
           "nb": key.nb, "dtype": key.dtype, "platform": key.platform,
           "reason": decision.reason, "rung_old": decision.rung_old,
           "rung_new": decision.rung_new,
           "route_old": route_old.as_dict(),
           "route_new": route_new.as_dict(),
           "probe": None if decision.nonfinite else float(decision.probe),
           "attrs": dict(attrs or {})}
    if decision.nonfinite:
        rec["nonfinite"] = True
    if probe_value is not None:
        rec["attrs"].setdefault("value", float(probe_value))
    obs.emit_event("autotune", **rec)
    if obs.metrics_active():
        obs.gauge("dlaf_autotune_route", op=key.op, knob="rung").set(
            float(decision.rung_new))
        for knob, val in route_metric_values(route_new).items():
            obs.gauge("dlaf_autotune_route", op=key.op, knob=knob).set(val)
        obs.counter("dlaf_autotune_decisions_total", op=key.op,
                    reason=decision.reason).inc()
        if decision.reason == "escalate":
            obs.counter("dlaf_autotune_escalations_total", op=key.op).inc()
    if decision.reason == "exhausted":
        from ..health.registry import strict_mode
        from ..obs import flight

        if obs.metrics_active():
            obs.counter("dlaf_autotune_exhausted_total", op=key.op).inc()
        # the open-state incident: the ladder top could not hold the
        # budget — dump the ring (the exhausted record above is in it)
        flight.trigger("autotune_exhausted", site=key.label,
                       rung=decision.rung_new,
                       ladder=ladder.name,
                       bound_ratio=(None if decision.nonfinite
                                    else float(decision.probe)))
        obs.get_logger("autotune").warning_once(
            ("autotune_exhausted", key.label),
            f"autotune ladder exhausted at {key.label}: probe "
            f"bound_ratio {decision.probe!r} breached the budget at the "
            f"TOP rung ({decision.rung_new}) of the {ladder.name} "
            "ladder — no safer route exists; DLAF_STRICT=1 raises",
            site=key.label, rung=decision.rung_new)
        if strict_mode():
            from ..health.errors import AutotuneExhaustedError

            raise AutotuneExhaustedError(
                key.label, rung=decision.rung_new,
                ladder=ladder.name,
                bound_ratio=(float("inf") if decision.nonfinite
                             else float(decision.probe)))
    return decision
