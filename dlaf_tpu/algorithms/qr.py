"""QR T-factor — the standalone public API (local + distributed).

TPU-native counterpart of the reference's ``factorization/qr``
(``t_factor_impl.h:42-347``; public ``api.h:52,81``): given a panel ``V`` of
``k`` forward columnwise Householder reflectors and their ``taus``, compute
the compact-WY ``T`` factor with ``(I - V T V^H)`` the accumulated product
of the reflectors.

The reference accumulates T with per-tile ``gemv``s and a final ``trmv``
series, all-reducing partial sums over the *column communicator* in the
distributed overload. The TPU-native form uses the closed form
``T^{-1} = diag(1/tau) + strict_upper(V^H V)`` (see ``tile_ops.lapack.
larft``): the only distributed quantity is the small ``k x k`` Gram matrix
``V^H V``, accumulated as rank-local partial products and ``psum``-reduced
along the mesh 'row' axis — the exact analog of the reference's
column-communicator all-reduce — after which every rank finishes the tiny
triangular solve redundantly (replicated T, like the reference's result on
every rank of the column).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..comm import collectives as cc
from ..comm.grid import COL_AXIS, ROW_AXIS
from ..common.asserts import dlaf_assert
from ..config import register_program_cache
from ..matrix.matrix import Matrix
from ..matrix.tiling import storage_tile_grid
from ..tile_ops import blas as tb
from ..tile_ops import lapack as tl


def _t_from_gram(gram, tau):
    """Finish T from the psum'd Gram matrix (small, every rank redundant):
    ``T^{-1} = diag(1/tau) + strict_upper(V^H V)``, zero taus giving zero
    rows/cols (null reflectors, LAPACK semantics)."""
    from jax import lax

    k = tau.shape[-1]
    tau_safe = jnp.where(tau == 0, jnp.ones_like(tau), tau)
    tinv = tb.tri_mask(gram, "U", k=-1) + (1.0 / tau_safe)[..., :, None] \
        * jnp.eye(k, dtype=gram.dtype)
    t = lax.linalg.triangular_solve(tinv, jnp.eye(k, dtype=gram.dtype),
                                    left_side=True, lower=False)
    nz = tau != 0
    return jnp.where(nz[..., :, None] & nz[..., None, :], t,
                     jnp.zeros_like(t))


@register_program_cache
@functools.lru_cache(maxsize=32)
def _dist_t_factor_cached(dist, mesh, dtype_name):
    nt = dist.nr_tiles.row
    mb = dist.block_size.row
    m, k = dist.size.row, dist.size.col
    Pr = dist.grid_size.row
    sr = dist.source_rank.row
    _, _, ltr, _ = storage_tile_grid(dist)

    def prog(lt, taus):
        # rank-local partial Gram over my row tiles of the (single-tile-
        # column) panel; invalid row slots masked out
        rr = (cc.this_rank(ROW_AXIS) - sr) % Pr
        g_rows = jnp.arange(ltr) * Pr + rr
        elem_rows = g_rows[:, None] * mb + jnp.arange(mb)[None, :]
        valid = (elem_rows < m)
        tiles = lt[:, 0]
        # unit-lower-trapezoidal V with implicit ones: global element row r,
        # column c -> keep strictly-lower, inject 1 at r == c
        col = jnp.arange(k)[None, None, :]
        er = elem_rows[:, :, None]
        vv = jnp.where((er > col) & valid[:, :, None], tiles[..., :k], 0)
        vv = vv + jnp.where(er == col, 1.0, 0.0).astype(tiles.dtype)
        part = tb.contract("rab,rad->bd", jnp.conj(vv), vv)
        gram = cc.all_reduce(part, ROW_AXIS)   # the col-communicator allreduce
        # only the grid column owning the panel's single tile column summed
        # real data; everyone else receives its gram (replicated result,
        # like the reference's T on every rank)
        gram = cc.bcast(gram, COL_AXIS, dist.source_rank.col)
        return _t_from_gram(gram, taus)

    fn = shard_map(prog, mesh=mesh, in_specs=(P(ROW_AXIS, COL_AXIS), P()),
                   out_specs=P(), check_vma=False)
    return jax.jit(fn)


def t_factor(v, taus):
    """T factor of the reflector panel ``v`` (reference
    ``factorization::qr::computeTFactor`` local + distributed overloads).

    ``v``: a Matrix whose single block column holds the reflectors (unit
    lower trapezoidal, ones implicit — the stored upper triangle is
    ignored), or a plain (m, k) array; ``taus``: (k,) scaling factors.
    Returns the replicated (k, k) ``T`` as a jax array.
    """
    if not isinstance(v, Matrix):
        arr = jnp.asarray(v)
        return tl.larft(arr, jnp.asarray(taus))
    dlaf_assert(v.dist.nr_tiles.col == 1,
                "t_factor: the reflector panel must be one block column")
    if v.grid is None or v.grid.num_devices == 1:
        from ..matrix.tiling import tiles_to_global

        return tl.larft(tiles_to_global(v.storage, v.dist),
                        jnp.asarray(taus))
    fn = _dist_t_factor_cached(v.dist, v.grid.mesh, np.dtype(v.dtype).name)
    return fn(v.storage, jnp.asarray(taus))
